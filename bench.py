"""Benchmark suite: single-chip throughput across the model families.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "configs"}.
The headline metric stays the 1B-class Q40 Llama decode throughput
(comparable across rounds); "configs" carries the wider sweep the reference
reports across its target configs (BASELINE.json): a Qwen3 shape, a
Qwen3-MoE shape, a 32k long-context model, prefill legs, and a bf16-vs-f32
perplexity accuracy proxy.

Models are synthetic (random weights, real compute/memory profile) — no real
checkpoints exist in this environment (zero egress). Files are built once
into .bench_cache/.

Baseline: the reference's best in-repo prediction throughput, 26.4 tok/s —
8 workers, PP=4, 8B-class Q40 model
(/root/reference/docs/PP_PARAMETER_EXPERIMENT_RESULTS_20260303.md). Its
best single-digit-node TP numbers are far lower (0.44-0.83 tok/s on the
RPi cluster reports). vs_baseline = headline / 26.4.

Measurement notes:
* host->device dispatch through this environment's driver tunnel costs
  ~70 ms per round trip regardless of work size; decode amortizes it with
  64-step on-device chunks and prefill with one big padded chunk, so the
  steady-state numbers below reflect device compute, not tunnel latency;
* decode tok/s = median over measured decode chunks (chunk wall / tokens);
* prefill tok/s = prompt tokens / synced prefill wall time. The prefill
  pipeline double-buffers chunk dispatches (input prep on a worker thread,
  one bare ready-wait as the only sync); each leg reports
  `prefill_dispatch_overlap_pct` — the share of the prefill wall spent
  inside dispatches, i.e. how completely compute hid behind them.
"""

import json
import os
import statistics
import sys
import time

# DLT_BENCH_CACHE lets tools (scripts/ab_bench.py ref mode) point worktree
# copies of this file at one shared model cache
CACHE_DIR = os.environ.get("DLT_BENCH_CACHE") or os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".bench_cache"
)
BASELINE_TOK_S = 26.4  # reference PP=4 best (see module docstring)

# persistent XLA compile cache: first compiles of the big prefill graphs
# cost 30 s - many minutes through the tunnel; cache them across bench runs
os.environ.setdefault(
    "DLT_COMPILE_CACHE",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"),
)


def build_model(name: str, **kw) -> str:
    os.makedirs(CACHE_DIR, exist_ok=True)
    path = os.path.join(CACHE_DIR, f"{name}.m")
    if os.path.exists(path):
        return path
    from distributed_llama_tpu.testing import tiny_header, write_tiny_model

    h = tiny_header(**kw)
    t0 = time.time()
    write_tiny_model(path + ".tmp", h, seed=1234, scale=0.02)
    os.rename(path + ".tmp", path)
    print(f"# built {name} in {time.time() - t0:.1f}s", file=sys.stderr)
    return path


def ensure_model() -> str:
    """The headline 1B-class Llama (kept stable across rounds)."""
    return build_model(
        "llama1b_q40_v1",
        dim=2048, hidden_dim=8192, n_layers=16, n_heads=32, n_kv_heads=8,
        vocab_size=32768, seq_len=2048,
    )


def ensure_qwen3() -> str:
    """The qwen3-class small dense model (bench leg + profiling target)."""
    from distributed_llama_tpu.formats.mfile import ArchType, RopeType

    return build_model(
        "qwen3s_q40_v1",
        arch=ArchType.QWEN3, rope_type=RopeType.FALCON,
        dim=1024, hidden_dim=3072, n_layers=16, n_heads=16,
        n_kv_heads=8, head_dim=128, vocab_size=32768, seq_len=2048,
    )


def ensure_moe() -> str:
    """The qwen3-moe-class model (bench leg + profiling target)."""
    from distributed_llama_tpu.formats.mfile import ArchType, RopeType

    return build_model(
        "qwen3moe_q40_v1",
        arch=ArchType.QWEN3_MOE, rope_type=RopeType.FALCON,
        dim=1024, hidden_dim=3072, n_layers=12, n_heads=16,
        n_kv_heads=8, head_dim=128, n_experts=32, n_active_experts=4,
        moe_hidden_dim=512, vocab_size=32768, seq_len=2048,
    )


def measure(path: str, prefill_tokens: int, decode_tokens: int, max_seq=0, **ekw):
    """(decode_tok_s, prefill_tok_s, ttft_ms, marginal_prefill,
    wall_long, ttft_cold_ms, overlap_pct, prof, eng) where wall_long is
    (long_n, wall_ms) or None, overlap_pct is the measured run's
    prefill dispatch-vs-compute overlap (engine.last_prefill_timing), and
    prof is the device profile (runtime/profiling.py bench_profile: the
    HBM ledger plus dlt_mfu / dlt_bw_utilization from the leg's own cost
    table — the same join /metrics serves live).

    prefill_tok_s is the naive prompt/wall rate — at a 512-token prompt it
    is dominated by the ~70-90 ms tunnel dispatch of this environment, NOT
    compute (one chunk = one dispatch). marginal_prefill differences two
    prompt lengths so the fixed dispatch cancels: the steady-state rate a
    long prompt actually sees (and what non-tunnel deployments get).
    wall_long is the RAW wall of the long prompt arm — the direct lower
    bound the marginal metric must reconcile with (long_n tokens took
    wall_ms, no differencing, no modeling); both numbers are emitted so the
    bound is checkable.
    """
    from distributed_llama_tpu.runtime.engine import InferenceEngine

    # prefix cache pinned OFF: measure() re-runs the same prompt, so an
    # ambient DLT_PREFIX_CACHE_MB would turn the measured prefill into a
    # cache splice and silently invalidate prefill/TTFT numbers; the cache's
    # own leg (leg_prefix_cache) owns the on-vs-off comparison
    ekw.setdefault("prefix_cache_mb", 0)
    eng = InferenceEngine(
        path, compute_dtype="bfloat16", max_chunk=prefill_tokens,
        max_seq_len=max_seq, **ekw,
    )
    prompt = [(i % 1000) + 1 for i in range(prefill_tokens)]
    # decode budget = steps - (len(prompt) - 1); the -1 makes the budget
    # exactly `decode_tokens`, so the chunk ladder stays power-of-two — an
    # off-by-one budget of 129 decays into 64+64+1 (or 128+1) chunks whose
    # 1-token tail is pure dispatch latency and poisons a 2-element median
    # (observed: a healthy 1.55 ms/token config reporting 19 tok/s)
    steps = prefill_tokens + decode_tokens - 1
    # COLD TTFT first: the first streaming request on a fresh engine,
    # compile (or persistent-cache load) included — what a real deployment's
    # first user sees (VERDICT r4 #6). Runs before any warmup on purpose.
    sink0 = lambda t: None  # noqa: E731
    res_cold = eng.generate(prompt, prefill_tokens + 16, sampler=None, on_token=sink0)
    ttft_cold_ms = res_cold.ttft_us / 1e3
    eng.reset()
    eng.generate(prompt, steps, sampler=None)  # warmup: compiles
    eng.reset()
    res = eng.generate(prompt, steps, sampler=None)
    per_tok_us = statistics.median(s.eval_us / s.n_tokens for s in res.pred_steps)
    decode_tok_s = 1e6 / per_tok_us
    prefill_tok_s = res.eval_tok_per_s
    # dispatch-vs-compute overlap of the measured run's prefill: the share
    # of the prefill wall spent inside (async) chunk dispatches — ~100%
    # means the final sync found the device already done (fully hidden)
    overlap_pct = (eng.last_prefill_timing or {}).get("overlap_pct")

    # TTFT as a streaming client sees it: on_token enables the engine's
    # first-chunk ramp (chunk of 8), which non-streaming runs skip to keep
    # full decode chunks. Run twice: first compiles the ramp chunk shape.
    sink = lambda t: None  # noqa: E731
    for _ in range(2):
        eng.reset()
        res_stream = eng.generate(prompt, prefill_tokens + 16, sampler=None, on_token=sink)
    ttft_ms = res_stream.ttft_us / 1e3

    # marginal prefill rate: difference long vs short prompt walls. The
    # long arm is at least prefill+1024 tokens so the differenced compute
    # clears the tunnel's few-ms dispatch jitter even for short prompts
    # (3x a 256-token prompt left only ~2 ms of differenced signal — the
    # round-3 qwen3 leg's null marginal)
    long_n = min(
        max(3 * prefill_tokens, prefill_tokens + 1024), eng.cfg.seq_len - 64
    )
    marginal = None
    wall_long_ms = None
    if long_n > prefill_tokens:
        def prefill_wall(n, reps=5):
            walls = []
            for _ in range(reps):
                eng.reset()
                t0 = time.perf_counter()
                eng.prefill([(i % 1000) + 1 for i in range(n)])
                walls.append(time.perf_counter() - t0)
            walls.sort()
            if len(walls) == 1:  # compile-warmup call
                return walls[0], 0.0
            # jitter bound from the two BEST reps: min-max spread counts a
            # single worst-case stall against the whole measurement and
            # nulls healthy windows
            return walls[0], walls[1] - walls[0]
        prefill_wall(long_n, reps=1)  # compile the extra chunk shapes
        t_long, spread_long = prefill_wall(long_n)
        t_short, spread_short = prefill_wall(prefill_tokens)
        wall_long_ms = (long_n, t_long * 1e3)
        # the difference must clear the observed run-to-run jitter or the
        # quotient is noise (observed: a 2.4k tok/s config reporting 4M
        # through the tunnel's ~10-30 ms dispatch variance); the floor is
        # jitter-RELATIVE so fast direct-attached hardware, where the
        # measurement is clean and small, still reports. 5 reps (min) keep
        # the spreads tight enough that healthy windows rarely null out.
        if t_long - t_short > max(0.002, spread_long + spread_short):
            marginal = (long_n - prefill_tokens) / (t_long - t_short)
    # per-leg device profile: a PARTIAL cost table over exactly the decode
    # programs this leg ran (a handful of AOT compiles, deduped by
    # DLT_COMPILE_CACHE) joined with the leg's own chunk walls — the BENCH
    # json records the same dlt_mfu / dlt_bw_utilization /
    # dlt_hbm_bytes numbers /metrics would serve live
    try:
        from distributed_llama_tpu.runtime.profiling import bench_profile

        prof = bench_profile(eng, final_pos=prefill_tokens + decode_tokens)
    except Exception as e:
        prof = {"error": repr(e)}
    return (
        decode_tok_s, prefill_tok_s, ttft_ms, marginal, wall_long_ms,
        ttft_cold_ms, overlap_pct, prof, eng,
    )


def leg_8b():
    """The north-star class made a measured number: a Llama-3.1-8B-shaped
    synthetic Q40 model (dim 4096, 32L, 32/8 heads, ffn 14336, vocab 128256)
    on ONE chip. Weight reads per decoded token: 7.50e9 weights (32 layers x
    218M + wcls 525M) ~= 7.5 GB int8 + 0.47 GB f16 scales ~= 7.97 GB; the
    roofline % is reported against ~819 GB/s HBM."""
    path = build_model(
        "llama8b_q40_v1",
        dim=4096, hidden_dim=14336, n_layers=32, n_heads=32, n_kv_heads=8,
        head_dim=128, vocab_size=128256, seq_len=2048,
    )
    # the 8B prefill graph's first remote compile has been observed anywhere
    # from ~60 s to >600 s depending on the tunnel's day — don't let the
    # stall watchdog's default hard timeout kill an otherwise-healthy leg
    prev = os.environ.get("DLT_STALL_TIMEOUT_MS")
    os.environ.setdefault("DLT_STALL_TIMEOUT_MS", "1800000")
    try:
        decode, prefill, ttft, marginal, wall_long, ttft_cold, overlap, prof, eng = measure(
            path, 512, 128
        )
    finally:
        if prev is None:
            os.environ.pop("DLT_STALL_TIMEOUT_MS", None)
        else:
            os.environ["DLT_STALL_TIMEOUT_MS"] = prev
    from distributed_llama_tpu.runtime.profiling import peak_hbm_bytes_s

    # bytes per decoded token, from the leg's own warm-ladder COST TABLE
    # (XLA's bytes-accessed census of the exact decode program measured —
    # runtime/profiling.py; the /debug/costs numbers): the roofline line is
    # derived from the same table /metrics serves, not hand arithmetic.
    # The hand-derived weight-read model (all layer weights + wcls,
    # nibble-packed int4 + f16 per-32-block scales: 0.5 + 2/32
    # bytes/weight) stays as the fallback when the cost build failed.
    bytes_tok = prof.get("decode_bytes_per_token_modeled")
    roofline_source = "cost_table"
    if not bytes_tok:
        n_w = 32 * (4096 * (4096 + 1024 + 1024 + 4096) + 3 * 4096 * 14336) + 4096 * 128256
        bytes_tok = n_w * (0.5 + 2 / 32)
        roofline_source = "hand_model"
    gbs = bytes_tok * decode / 1e9
    del eng
    return {
        "config": "llama-8B-class q40 1chip",
        "decode_tok_s": round(decode, 2),
        "ttft_cold_ms": round(ttft_cold, 1),
        "prefill_tok_s": round(prefill, 1),
        "prefill_tok_s_marginal": marginal and round(marginal, 1),
        "prefill_long_n": wall_long and wall_long[0],
        "prefill_wall_long_ms": wall_long and round(wall_long[1], 1),
        "prefill_dispatch_overlap_pct": overlap,
        "ttft_ms": round(ttft, 1),
        "decode_bytes_per_token": round(bytes_tok, 0),
        "roofline_source": roofline_source,
        "decode_eff_gb_s": round(gbs, 1),
        "hbm_roofline_pct": round(100 * gbs / (peak_hbm_bytes_s() / 1e9), 1),
        "profile": prof,
    }


def leg_longcontext():
    """32k-context model: decode cost must track the position bucket, not the
    allocated cache (flash attention + kv_len bucketing). The int8-KV twin
    at the 30k plateau is the quantized arm's depth number: deep buckets are
    where decode turns KV-read-bound, so halved storage width is where the
    plateau should lift on HBM-bound hardware (through this environment's
    dispatch tunnel the twin documents parity instead — the bytes story is
    the kv-quant leg's census-modeled ratio)."""
    path = build_model(
        "llama_32k_q40_v1",
        dim=1024, hidden_dim=4096, n_layers=8, n_heads=16, n_kv_heads=8,
        vocab_size=32768, seq_len=32768,
    )
    from distributed_llama_tpu.runtime.engine import InferenceEngine

    def decode_at(eng, pos: int) -> float:
        """TIMING-ONLY leg: only the last 512 cache positions are prefilled,
        so decode at 30k attends mostly zero K/V rows — the read volume (and
        thus the timing) is identical to a fully-written cache, but the
        generated tokens are numerically meaningless. Numerics at depth are
        covered by the parity/perplexity legs. 768 decode tokens = three
        256-chunks, so the median is a steady-state chunk (a single chunk's
        wall carries its un-overlapped dispatch+fetch round trips)."""
        eng.reset()
        prompt = [(i % 999) + 1 for i in range(512)]
        # place the prompt so decode runs at `pos`
        eng.prefill(prompt, pos_start=pos - 512)
        res = eng.generate([1], pos + 768, sampler=None, pos_start=pos)
        per = statistics.median(s.eval_us / s.n_tokens for s in res.pred_steps)
        return 1e6 / per

    # dim-1024 model: dispatch-overhead-bound below 256-token chunks (see
    # extra_legs)
    eng = InferenceEngine(
        path, compute_dtype="bfloat16", max_chunk=512, decode_chunk_size=256,
        prefix_cache_mb=0,  # repeated-prompt timing legs must not splice
    )
    early = decode_at(eng, 1024)   # bucket 1024
    warm2 = decode_at(eng, 1024)
    early = max(early, warm2)
    late = decode_at(eng, 30000)   # bucket 32768
    late = max(late, decode_at(eng, 30000))
    out = {
        "config": "llama-small-32kctx q40 1chip",
        "decode_tok_s_at_1k": round(early, 1),
        "decode_tok_s_at_30k": round(late, 1),
    }
    del eng
    try:
        eng8 = InferenceEngine(
            path, compute_dtype="bfloat16", cache_dtype="int8",
            max_chunk=512, decode_chunk_size=256, prefix_cache_mb=0,
        )
        late8 = max(decode_at(eng8, 30000), decode_at(eng8, 30000))
        out["decode_tok_s_at_30k_int8"] = round(late8, 1)
        del eng8
    except Exception as e:
        out["int8_arm_error"] = repr(e)
    return out


def leg_kv_quant():
    """Quantized-KV A/B (int8 payload + f32 scale sidecars vs bf16) on the
    qwen3-class model (head_dim 128) under the PAGED layout — the serving
    shape. Four numbers per arm: decode tok/s, census-modeled total decode
    bytes/token and the effective GB/s they imply, and the per-position KV
    read width from DIFFERENCING the cost table's decode census across two
    kv buckets (the weight reads cancel exactly, leaving pure KV traffic).
    The bf16/int8 width ratio is the leg's honest headline on CPU rounds —
    tok/s twins there measure the dispatch tunnel, not HBM; at head_dim 128
    the stored-width model predicts (2*128)/(1*128 + 4) ≈ 1.94x. Quality
    rides along as the ppl-proxy twin: mean next-token logprob of the int8
    arm vs the bf16-KV arm, same bf16 compute both sides."""
    import jax
    import jax.numpy as jnp

    from distributed_llama_tpu.runtime.engine import InferenceEngine
    from distributed_llama_tpu.runtime.profiling import build_cost_table

    path = ensure_qwen3()
    out = {"config": "kv-quant int8-vs-bf16 paged qwen3"}
    slopes = {}
    for cd, tag in ((None, "bf16"), ("int8", "int8")):
        eng = InferenceEngine(
            path, compute_dtype="bfloat16", cache_dtype=cd, max_chunk=256,
            decode_chunk_size=256, prefix_cache_mb=0, kv_layout="paged",
        )
        prompt = [(i % 1000) + 1 for i in range(256)]
        # three 256-chunks: median = steady state. CPU-only rounds shrink
        # the window (DLT_BENCH_KVQ_DECODE) — their tok/s rows measure the
        # dispatch tunnel anyway; the modeled rows are window-independent
        decode = int(os.environ.get("DLT_BENCH_KVQ_DECODE") or 768)
        steps = 256 + decode - 1
        eng.generate(prompt, steps, sampler=None)  # compile pass
        eng.reset()
        res = eng.generate(prompt, steps, sampler=None)
        per = statistics.median(s.eval_us / s.n_tokens for s in res.pred_steps)
        tok_s = 1e6 / per
        out[f"decode_tok_s_{tag}"] = round(tok_s, 2)
        try:
            n = eng.decode_chunk_size
            table = build_cost_table(
                eng, plan=[("decode", n, 1024), ("decode", n, 2048)]
            )
            e1 = table.entries.get(("decode", n, 1024))
            e2 = table.entries.get(("decode", n, 2048))
            if e1 is not None and e2 is not None:
                slope = (e2.bytes_accessed - e1.bytes_accessed) / (2048 - 1024) / n
                slopes[tag] = slope
                out[f"kv_read_bytes_per_pos_{tag}"] = round(slope, 2)
                out[f"decode_bytes_per_token_{tag}"] = round(e1.bytes_per_token, 1)
                out[f"decode_eff_gb_s_{tag}"] = round(
                    e1.bytes_per_token * tok_s / 1e9, 3
                )
        except Exception as e:
            out[f"profile_error_{tag}"] = repr(e)
        del eng
    if slopes.get("int8"):
        out["kv_bytes_per_pos_ratio_modeled"] = round(
            slopes["bf16"] / slopes["int8"], 3
        )

    # quality proxy: the ppl leg's exact recipe, varying ONLY the KV
    # storage dtype (compute stays bf16). Bounded, not zero: quantize-on-
    # write rounds each written vector to 8 bits before attention reads it.
    from distributed_llama_tpu.formats.mfile import MFileReader
    from distributed_llama_tpu.models import (
        config_from_header, forward, init_kv_cache, load_params,
    )
    from distributed_llama_tpu.ops import build_rope_tables

    toks = [(i * 37 % 1000) + 1 for i in range(256)]
    lps = {}
    for cd, tag in (("bfloat16", "bf16"), ("int8", "int8")):
        reader = MFileReader(path)
        cfg = config_from_header(
            reader.header, compute_dtype="bfloat16", cache_dtype=cd
        )
        params = load_params(reader, cfg)
        rope = build_rope_tables(reader.header)
        cache = init_kv_cache(cfg, batch=1)
        logits, _ = forward(
            cfg, params, rope, cache, jnp.asarray([toks], jnp.int32),
            jnp.int32(0), logits_mode="all",
        )
        lp = jnp.take_along_axis(
            jax.nn.log_softmax(logits[0, :-1].astype(jnp.float32)),
            jnp.asarray(toks[1:], jnp.int32)[:, None], axis=-1,
        )
        lps[tag] = float(jnp.mean(lp))
    out["mean_logprob_bf16kv"] = round(lps["bf16"], 4)
    out["mean_logprob_int8kv"] = round(lps["int8"], 4)
    out["logprob_abs_delta_int8"] = round(abs(lps["bf16"] - lps["int8"]), 4)
    return out


def leg_batched_serving():
    """Aggregate decode throughput with 4 concurrent independent sequences
    on the 1B (per-row positions, one batched chunk program). The
    reference's only concurrency is gateway replica-DP — one model copy per
    request stream; this is the axis batched serving beats it on: one model
    instance, one chip, 4 streams."""
    from distributed_llama_tpu.runtime.engine import InferenceEngine

    path = ensure_model()
    b = 4
    eng = InferenceEngine(
        path, compute_dtype="bfloat16", batch=b, max_chunk=256,
        decode_chunk_size=64, prefix_cache_mb=0,
    )
    prompts = [
        [(i * (r + 3) % 1000) + 1 for i in range(128 + 17 * r)] for r in range(b)
    ]
    budget = 192
    eng.generate_batch(prompts, budget, sampler=None)  # warmup: compiles
    eng.reset()
    t0 = time.perf_counter()
    out = eng.generate_batch(prompts, budget, sampler=None)
    wall = time.perf_counter() - t0
    n = sum(len(o) for o in out)
    del eng  # release weights + 4-row cache before the solo arm's engine
    # solo single-stream rate in the same window for the speedup claim.
    # Both walls span prefill + decode end to end (generated tokens / total
    # request wall — the rate a CLIENT sees), so the gain compares like with
    # like; neither number is a pure decode rate.
    solo = InferenceEngine(
        path, compute_dtype="bfloat16", max_chunk=256, prefix_cache_mb=0
    )
    solo.generate(prompts[0], len(prompts[0]) + budget - 1, sampler=None)
    solo.reset()
    t0 = time.perf_counter()
    res = solo.generate(prompts[0], len(prompts[0]) + budget - 1, sampler=None)
    solo_wall = time.perf_counter() - t0
    solo_rate = res.n_pred_tokens / solo_wall
    del solo
    return {
        "config": f"llama-1B q40 1chip batched-serving b={b}",
        "aggregate_tok_s_e2e": round(n / wall, 1),
        "per_stream_tok_s_e2e": round(n / wall / b, 1),
        "solo_stream_tok_s_e2e": round(solo_rate, 1),
        "throughput_gain_vs_serial": round((n / wall) / solo_rate, 2),
    }


def leg_serving_interleave():
    """Decode-stream latency under a concurrently-prefilling long prompt —
    the Batcher's interleaved-admission path (Sarathi-style chunked-prefill
    piggyback). A live decode stream runs alone for a latency baseline, then
    a 1.5k-token prompt is staged with `begin_admit` and its prefill
    advances in bounded 256-token chunks BETWEEN the stream's decode chunks
    (exactly the server loop's schedule). Reported: per-step p95 decode
    latency solo vs interleaved (the acceptance bar is <=2x), and the
    newcomer's prefill wall under interleaving."""
    from distributed_llama_tpu.runtime.batch_session import BatchSession
    from distributed_llama_tpu.runtime.engine import InferenceEngine

    path = ensure_model()
    chunk = 64
    budget = 256
    eng = InferenceEngine(
        path, compute_dtype="bfloat16", batch=2, max_chunk=budget,
        decode_chunk_size=chunk, prefix_cache_mb=0,
    )
    long_prompt = [(i % 1000) + 1 for i in range(1536)]
    short = [(i % 997) + 1 for i in range(128)]

    def run(n_solo_chunks):
        """One full cycle at the same positions/kv buckets: solo decode
        chunk walls, then interleaved walls + the newcomer's prefill wall."""
        session = BatchSession(eng)  # resets the engine/cache
        session.admit(0, short)
        solo = []
        for _ in range(n_solo_chunks):
            t0 = time.perf_counter()
            session.step(chunk)
            solo.append((time.perf_counter() - t0) * 1e3)
        inter = []
        t_admit = time.perf_counter()
        session.begin_admit(1, long_prompt)
        remaining = len(long_prompt) - 1
        prefill_wall_ms = None
        while remaining:
            remaining = session.prefill_pending(1, budget)
            if remaining == 0:
                prefill_wall_ms = (time.perf_counter() - t_admit) * 1e3
            t0 = time.perf_counter()
            session.step(chunk)
            inter.append((time.perf_counter() - t0) * 1e3)
        session.release(0)
        session.release(1)
        return solo, inter, prefill_wall_ms

    run(2)  # warmup: compiles the decode chunks + the admission ladder
    solo, inter, prefill_wall_ms = run(6)

    def p95(xs):
        xs = sorted(xs)
        return xs[min(len(xs) - 1, int(len(xs) * 0.95))]

    solo_step_p95 = p95(solo) / chunk
    inter_step_p95 = p95(inter) / chunk
    # the interleave wall includes the boundary prefill dispatch: per-step
    # latency a co-batched stream actually observes during admission
    return {
        "config": "llama-1B q40 1chip interleaved-prefill b=2",
        "decode_step_p95_ms_solo": round(solo_step_p95, 3),
        "decode_step_p95_ms_while_prefill": round(inter_step_p95, 3),
        "decode_p95_inflation_x": round(inter_step_p95 / solo_step_p95, 2),
        "prefill_1535_wall_ms_interleaved": prefill_wall_ms
        and round(prefill_wall_ms, 1),
        "interleaved_prefill_chunks": len(inter),
    }


def leg_prefix_cache():
    """Shared-system-prompt serving (the radix prefix cache's target
    workload): N requests share a common 512-token prefix with distinct
    64-token tails. Arm A serves them with the prefix cache ON (first
    request publishes, the rest splice cached KV and resume prefill at the
    bucket boundary); arm B is the same traffic with DLT_PREFIX_CACHE_MB=0
    semantics (prefix_cache_mb=0). Reported: median TTFT of the follow-up
    requests per arm, the cold first-request TTFT, and prefix_hit_tokens —
    the bucket-aligned prefill compute the hits skipped."""
    import statistics as _st

    from distributed_llama_tpu.runtime.engine import InferenceEngine

    path = ensure_model()
    prefix = [(i % 1000) + 1 for i in range(512)]

    def run(mb):
        eng = InferenceEngine(
            path, compute_dtype="bfloat16", max_chunk=256,
            decode_chunk_size=64, prefix_cache_mb=mb,
        )
        # compile warm-through on UNRELATED traffic so arm timings measure
        # serving, not XLA; its published entry never matches the workload
        warm = [((i * 13) % 900) + 50 for i in range(576)]
        for _ in range(2):
            eng.reset()
            eng.generate(warm, len(warm) + 16, sampler=None, on_token=lambda t: None)
        # hit accounting from HERE: the warm phase's second rep splices its
        # own published warm prompt, which must not count toward the
        # workload's reported savings
        base_hits = eng.stats.counters_snapshot().get("prefix_hit_tokens", 0)
        ttfts = []
        for r in range(4):
            tail = [((i * 7 + r * 131) % 1000) + 1 for i in range(64)]
            eng.reset()
            res = eng.generate(
                prefix + tail, 576 + 32, sampler=None, on_token=lambda t: None
            )
            ttfts.append(res.ttft_us / 1e3)
        hit_tokens = (
            eng.stats.counters_snapshot().get("prefix_hit_tokens", 0) - base_hits
        )
        del eng
        # ttfts[0] is the cold publish request; 1..3 are the steady state
        return ttfts[0], _st.median(ttfts[1:]), hit_tokens

    ttft_cold_on, ttft_hit, hit_tokens = run(512)
    ttft_cold_off, ttft_off, _ = run(0)
    return {
        "config": "llama-1B q40 1chip shared-512-prefix x4",
        "ttft_ms_first_cold": round(ttft_cold_on, 1),
        "ttft_ms_hit_median": round(ttft_hit, 1),
        "ttft_ms_off_median": round(ttft_off, 1),
        "ttft_hit_speedup_x": round(ttft_off / max(ttft_hit, 1e-9), 2),
        "prefix_hit_tokens": hit_tokens,
    }


def leg_paged_batch():
    """Paged KV cache (runtime/paged_kv.py) vs contiguous at a FIXED
    modeled KV HBM budget — the budget the contiguous batch-4 arm's full
    seq_len slabs cost (per the hbm_ledger, the same accounting /metrics
    exports). The paged arms keep that byte budget (kv_pool_mb) and scale
    the row count instead: rows decoding realistic stream lengths (a few
    hundred tokens, not seq_len) fit many-to-one in the same pool, so the
    same HBM serves 4x-8x the concurrent streams. Reported per arm:
    aggregate + per-stream decode rate, the modeled KV bytes, and (paged)
    pool occupancy + copy-on-write counters. A second sub-leg drives the
    shared-512-prefix shape: under paging a prefix-cache hit pins pages
    (zero-copy) — prefix_hit_tokens ticks while the splice-copy program
    series stay empty."""
    from distributed_llama_tpu.runtime.engine import InferenceEngine
    from distributed_llama_tpu.runtime.profiling import hbm_ledger

    path = ensure_model()

    def run_arm(layout, b, prompt_len, budget, pool_mb=None):
        eng = InferenceEngine(
            path, compute_dtype="bfloat16", batch=b, max_chunk=256,
            decode_chunk_size=64, prefix_cache_mb=0, kv_layout=layout,
            kv_pool_mb=pool_mb,
        )
        prompts = [
            [(i * (r + 3) % 1000) + 1 for i in range(prompt_len)]
            for r in range(b)
        ]
        eng.generate_batch(prompts, budget, sampler=None)  # compiles
        eng.reset()
        t0 = time.perf_counter()
        outs = eng.generate_batch(prompts, budget, sampler=None)
        wall = time.perf_counter() - t0
        n = sum(len(o) for o in outs)
        kv_bytes = hbm_ledger(eng)["components"]["kv_cache"]
        arm = {
            "layout": layout,
            "batch": b,
            "stream_tokens": prompt_len + budget,
            "kv_hbm_modeled_mb": round(kv_bytes / 1e6, 1),
            "aggregate_tok_s_e2e": round(n / wall, 1),
            "per_stream_tok_s_e2e": round(n / wall / b, 2),
        }
        if eng.paged:
            c = eng.stats.counters_snapshot()
            arm["kv_pool"] = eng.page_pool.snapshot()
            arm["kv_cow_pages"] = c.get("kv_cow_pages", 0)
            arm["kv_cow_copies"] = c.get("kv_cow_copies", 0)
        eng.close()
        del eng
        return arm, kv_bytes

    # the budget-setting baseline: contiguous batch 4, full-slab KV
    contig, kv_budget_bytes = run_arm("contiguous", 4, 128, 192)
    pool_mb = max(1, int(kv_budget_bytes // (1024 * 1024)))
    # paged twin at the SAME shape: the per-stream-rate-within-10% check
    paged4, _ = run_arm("paged", 4, 128, 192, pool_mb=pool_mb)
    # scale arms at the SAME KV budget. paged24 is the APPLES-TO-APPLES
    # row-scale claim: identical 320-token streams, 6x the rows (24 rows x
    # 20 pages = 480 of the budget's 512). paged32 is a second data point
    # at shorter streams (its stream_tokens field says so) — same budget
    # serving even more rows when streams are shorter, which is the actual
    # serving-mix story.
    paged24, _ = run_arm("paged", 24, 128, 192, pool_mb=pool_mb)
    paged32, _ = run_arm("paged", 32, 64, 128, pool_mb=pool_mb)

    # shared-512-prefix sub-leg: zero-copy sharing on the paged arm
    eng = InferenceEngine(
        path, compute_dtype="bfloat16", batch=4, max_chunk=256,
        decode_chunk_size=64, prefix_cache_mb=pool_mb, kv_layout="paged",
        kv_pool_mb=pool_mb,
    )
    shared = [(i % 1000) + 1 for i in range(512)]
    prompts = [shared + [(r + 1) * 7 % 997 + 1 for _ in range(16)] for r in range(4)]
    eng.generate_batch(prompts, 64, sampler=None)  # cold: publishes prefix
    eng.reset()
    eng.generate_batch(prompts, 64, sampler=None)  # hit: pages pinned
    c = eng.stats.counters_snapshot()
    prefix_sub = {
        "prefix_hit_tokens": c.get("prefix_hit_tokens", 0),
        "kv_pages_shared": c.get("kv_pages_shared", 0),
        # actual dispatch COUNTS of the splice/extract copy programs (must
        # stay 0 under paging — sharing is host-side refcounting)
        "splice_copy_dispatches": sum(
            s.count
            for k, s in eng.stats.series.items()
            if k.startswith(("prefix_copy", "prefix_extract"))
        ),
    }
    eng.close()
    del eng

    return {
        "config": "llama-1B q40 1chip paged-kv batch scale",
        "kv_budget_mb": pool_mb,
        "arms": [contig, paged4, paged24, paged32],
        # equal-stream-length comparison (both arms run 320-token streams)
        "rows_vs_contiguous_at_same_budget": round(
            paged24["batch"] / contig["batch"], 1
        ),
        "per_stream_rate_vs_contiguous_b4": round(
            paged4["per_stream_tok_s_e2e"]
            / max(contig["per_stream_tok_s_e2e"], 1e-9),
            3,
        ),
        "shared_prefix_zero_copy": prefix_sub,
    }


def leg_speculative():
    """Speculative decoding (ngram/k=4, runtime/speculative.py) vs plain
    chunked decode on the 1B, greedy. Two arms: a REPETITIVE prompt (the
    prompt-lookup draft source's target traffic — templated/quoting
    workloads; high acceptance, each verify dispatch lands up to k+1
    tokens) and a RANDOM prompt (no n-gram recurs — every round is a
    failed host-side lookup plus the ordinary fallback chunk; the
    acceptance bar is <= 1.1x slowdown vs speculation off). Reported:
    decode tok/s and p95 per-token step latency per arm and mode, plus the
    measured acceptance rates."""
    from distributed_llama_tpu.runtime.engine import InferenceEngine

    path = ensure_model()
    pattern = [((i * 37) % 911) + 1 for i in range(48)]
    rep_prompt = (pattern * 12)[:512]
    # i*613 mod 997 is a permutation: 512 distinct tokens, no n-gram recurs
    rand_prompt = [(i * 613) % 997 + 1 for i in range(512)]
    decode_tokens = 256

    def run(mode, prompt):
        eng = InferenceEngine(
            path, compute_dtype="bfloat16", max_chunk=256,
            decode_chunk_size=64, prefix_cache_mb=0, speculative=mode,
            draft_k=4,
        )
        steps = len(prompt) + decode_tokens - 1
        eng.generate(prompt, steps, sampler=None)  # warmup: compiles
        eng.reset()
        res = eng.generate(prompt, steps, sampler=None)
        per_tok = sorted(s.eval_us / s.n_tokens for s in res.pred_steps)
        p95 = per_tok[min(len(per_tok) - 1, int(len(per_tok) * 0.95))] / 1000
        rate = res.n_pred_tokens * 1e6 / max(res.decode_us, 1)
        acc = (eng.last_spec_timing or {}).get("acceptance_rate")
        del eng
        return rate, p95, acc

    rep_on, rep_p95_on, rep_acc = run("ngram", rep_prompt)
    rep_off, rep_p95_off, _ = run("off", rep_prompt)
    rand_on, rand_p95_on, rand_acc = run("ngram", rand_prompt)
    rand_off, rand_p95_off, _ = run("off", rand_prompt)
    return {
        "config": "llama-1B q40 1chip speculative ngram/k4",
        "decode_tok_s_repetitive_on": round(rep_on, 2),
        "decode_tok_s_repetitive_off": round(rep_off, 2),
        "speedup_repetitive_x": round(rep_on / max(rep_off, 1e-9), 2),
        "p95_step_ms_repetitive_on": round(rep_p95_on, 3),
        "p95_step_ms_repetitive_off": round(rep_p95_off, 3),
        "spec_acceptance_rate_repetitive": rep_acc,
        "decode_tok_s_random_on": round(rand_on, 2),
        "decode_tok_s_random_off": round(rand_off, 2),
        "slowdown_random_x": round(rand_off / max(rand_on, 1e-9), 2),
        "p95_step_ms_random_on": round(rand_p95_on, 3),
        "p95_step_ms_random_off": round(rand_p95_off, 3),
        "spec_acceptance_rate_random": rand_acc,
    }


def leg_grammar():
    """Grammar-constrained structured decoding (PR 20, runtime/grammar.py).
    Three arms on a routing-class model with a byte-piece tokenizer:

    * MASK OVERHEAD — a grammar-CAPABLE engine threads the [S, V] mask
      table + per-row state into every decode program even for free rows
      (that's what keeps the warm ladder shared), so the honest cost of
      the subsystem is free-row decode on a masked engine vs an unmasked
      twin. Acceptance bar: <= 5% tok/s overhead.
    * SCHEMA VALIDITY — >= 20 constrained generations against a JSON
      schema, every output validated by the compiled grammar's own byte
      DFA (fullmatch). Acceptance bar: 100% valid.
    * SPECULATIVE COMPOSITION — ngram drafts on a repetitive prompt with
      and without the grammar: the draft source is grammar-blind, so the
      constrained acceptance rate collapses toward the schema's forced
      path; the delta is reported (informational — the invariant that no
      illegal token survives is test-pinned, not benched)."""
    from distributed_llama_tpu.runtime.engine import InferenceEngine
    from distributed_llama_tpu.runtime.grammar import (
        GrammarCompiler,
        GrammarSession,
        schema_to_regex,
    )
    from distributed_llama_tpu.testing import byte_vocab_tokenizer
    from distributed_llama_tpu.tokenizer import Tokenizer

    model = build_model(
        "llama_grammar_q40_v1",
        dim=512, hidden_dim=1536, n_layers=8, n_heads=8, n_kv_heads=4,
        vocab_size=4096, seq_len=2048,
    )
    tok = Tokenizer(byte_vocab_tokenizer(pad_to=4096))
    schema = {"type": "object", "properties": {"ok": {"type": "boolean"}}}
    prompt = [((i * 37) % 911) + 1 for i in range(128)]
    decode_tokens = 128

    def mk(grammar, spec="off"):
        return InferenceEngine(
            model, compute_dtype="bfloat16", max_chunk=128,
            decode_chunk_size=32, prefix_cache_mb=0, grammar=grammar,
            speculative=spec, draft_k=4,
        )

    def timed_free(eng):
        steps = len(prompt) + decode_tokens - 1
        eng.generate(prompt, steps, sampler=None)  # warmup: compiles
        eng.reset()
        res = eng.generate(prompt, steps, sampler=None)
        return res.n_pred_tokens * 1e6 / max(res.decode_us, 1)

    off = timed_free(mk(grammar=None))
    eng = mk(grammar=True)
    on = timed_free(eng)
    overhead_pct = 100.0 * (off - on) / max(off, 1e-9)

    # validity sweep: every constrained generation must fullmatch
    comp = GrammarCompiler(tok, vocab_size=4096)
    g = comp.compile("json_schema", schema_to_regex(schema))
    n_gens, n_valid = 20, 0
    con_rate = None
    for i in range(n_gens):
        eng.reset()
        sess = GrammarSession(eng.grammar, g)
        p = [((j * 613 + i * 97) % 911) + 1 for j in range(32)]
        res = eng.generate(p, len(p) + 32, sampler=None, grammar=sess)
        sess.close()
        out = b"".join(
            tok.vocab[t] for t in res.tokens[len(p):]
            if t not in g.eos_ids and t != tok.bos_id
        )
        n_valid += bool(g.fullmatch(out))
        if con_rate is None:
            con_rate = res.n_pred_tokens * 1e6 / max(res.decode_us, 1)
    del eng

    # speculative composition: grammar-blind ngram drafts vs the schema
    spec_eng = mk(grammar=True, spec="ngram")
    rep = (prompt * 4)[:256]
    spec_eng.generate(rep, len(rep) + 64, sampler=None)  # warmup
    spec_eng.reset()
    spec_eng.generate(rep, len(rep) + 64, sampler=None)
    def acc_rate(timing):
        # drafted == 0 IS the collapse (legal_prefix pre-truncated every
        # grammar-illegal proposal): report 0.0, not an absent metric
        t = timing or {}
        return round(t.get("accepted_tokens", 0) / t["draft_tokens"], 4) \
            if t.get("draft_tokens") else 0.0

    acc_free = acc_rate(spec_eng.last_spec_timing)
    spec_eng.reset()
    sess = GrammarSession(spec_eng.grammar, g)
    spec_eng.generate(rep, len(rep) + 64, sampler=None, grammar=sess)
    sess.close()
    acc_con = acc_rate(spec_eng.last_spec_timing)
    del spec_eng
    return {
        "config": "llama-routing-class q40 1chip grammar-constrained",
        "decode_tok_s_unmasked": round(off, 2),
        "decode_tok_s_masked_free": round(on, 2),
        "masked_overhead_pct": round(overhead_pct, 2),
        "constrained_decode_tok_s": round(con_rate or 0.0, 2),
        "n_constrained_gens": n_gens,
        "schema_valid_rate": round(n_valid / n_gens, 4),
        "spec_acceptance_rate_free": acc_free,
        "spec_acceptance_rate_constrained": acc_con,
        "spec_acceptance_collapse": round(acc_free - acc_con, 4),
    }


def leg_tracing_overhead():
    """Tracing-overhead leg (runtime/tracing.py): greedy decode on the 1B
    with a fully-sampled request trace attached to the engine (the
    DLT_TRACE_SAMPLE=1 serving configuration — every chunk emits a span
    through a pre-bound emitter) vs tracing compiled out (engine.trace is
    None — every emission site short-circuits on the guard). The span emit
    is one host-side tuple append per CHUNK, so the acceptance bar is a
    <=2% decode-throughput delta; both arms and the delta land in the
    BENCH json so a regression is visible round to round."""
    from distributed_llama_tpu.runtime.engine import InferenceEngine
    from distributed_llama_tpu.runtime.tracing import Tracer

    path = ensure_model()
    prompt = [(i % 1000) + 1 for i in range(256)]
    decode_tokens = 512
    tracer = Tracer(capacity=1 << 15)

    def run(traced: bool):
        eng = InferenceEngine(
            path, compute_dtype="bfloat16", max_chunk=256,
            decode_chunk_size=64, prefix_cache_mb=0, speculative="off",
        )
        steps = len(prompt) + decode_tokens - 1
        eng.generate(prompt, steps, sampler=None)  # warmup: compiles
        eng.reset()
        if traced:
            # force the sampled bit: the leg must measure full emission
            # even if the host environment carries DLT_TRACE_SAMPLE!=1
            eng.trace = tracer.start(sampled=True)
        res = eng.generate(prompt, steps, sampler=None)
        n_events = len(tracer.for_trace(eng.trace.id)) if traced else 0
        eng.trace = None
        per_tok = sorted(s.eval_us / s.n_tokens for s in res.pred_steps)
        p95 = per_tok[min(len(per_tok) - 1, int(len(per_tok) * 0.95))] / 1000
        rate = res.n_pred_tokens * 1e6 / max(res.decode_us, 1)
        del eng
        return rate, p95, n_events

    rate_on, p95_on, n_events = run(True)
    assert n_events > 0, "traced arm emitted no spans — the leg measured nothing"
    rate_off, p95_off, _ = run(False)
    overhead_pct = 100.0 * (rate_off - rate_on) / max(rate_off, 1e-9)
    return {
        "config": "llama-1B q40 1chip tracing-overhead",
        "decode_tok_s_traced": round(rate_on, 2),
        "decode_tok_s_untraced": round(rate_off, 2),
        "throughput_overhead_pct": round(overhead_pct, 2),
        "overhead_bar_pct": 2.0,
        "p95_step_ms_traced": round(p95_on, 3),
        "p95_step_ms_untraced": round(p95_off, 3),
        "trace_events_emitted": n_events,
    }


def leg_profiling_overhead():
    """Profiling-overhead leg (runtime/profiling.py): greedy decode on the
    1B while a scraper thread hammers the device-performance layer — the
    HBM ledger + reconcile + roofline/SLO join (`metrics_view`, i.e. what a
    tight Prometheus loop costs) every ~25 ms, with the leg's cost table
    prebuilt — vs the same decode unobserved. The scrape path is host-side
    metadata only (no device dispatch, no d2h), so the acceptance bar is
    the same <=2% decode-throughput delta tracing holds; both arms and the
    delta land in the BENCH json."""
    import threading

    from distributed_llama_tpu.runtime.engine import InferenceEngine
    from distributed_llama_tpu.runtime.profiling import bench_profile, metrics_view

    path = ensure_model()
    prompt = [(i % 1000) + 1 for i in range(256)]
    decode_tokens = 512

    def run(scraped: bool):
        eng = InferenceEngine(
            path, compute_dtype="bfloat16", max_chunk=256,
            decode_chunk_size=64, prefix_cache_mb=0, speculative="off",
        )
        steps = len(prompt) + decode_tokens - 1
        eng.generate(prompt, steps, sampler=None)  # warmup: compiles
        bench_profile(eng, final_pos=steps)  # cost table outside the timed arm
        eng.reset()
        stop = threading.Event()
        n_scrapes = [0]

        def scraper():
            while not stop.is_set():
                metrics_view(eng)
                n_scrapes[0] += 1
                stop.wait(0.025)

        th = None
        if scraped:
            th = threading.Thread(target=scraper, daemon=True)
            th.start()
        res = eng.generate(prompt, steps, sampler=None)
        if th is not None:
            stop.set()
            th.join(timeout=2)
        per_tok = sorted(s.eval_us / s.n_tokens for s in res.pred_steps)
        p95 = per_tok[min(len(per_tok) - 1, int(len(per_tok) * 0.95))] / 1000
        rate = res.n_pred_tokens * 1e6 / max(res.decode_us, 1)
        del eng
        return rate, p95, n_scrapes[0]

    rate_on, p95_on, n_scrapes = run(True)
    assert n_scrapes > 0, "scraped arm never scraped — the leg measured nothing"
    rate_off, p95_off, _ = run(False)
    overhead_pct = 100.0 * (rate_off - rate_on) / max(rate_off, 1e-9)
    return {
        "config": "llama-1B q40 1chip profiling-overhead",
        "decode_tok_s_scraped": round(rate_on, 2),
        "decode_tok_s_unscraped": round(rate_off, 2),
        "throughput_overhead_pct": round(overhead_pct, 2),
        "overhead_bar_pct": 2.0,
        "p95_step_ms_scraped": round(p95_on, 3),
        "p95_step_ms_unscraped": round(p95_off, 3),
        "metrics_scrapes": n_scrapes,
    }


def leg_fleet_overhead():
    """Fleet-observability-overhead leg (server/fleet.py + the batch
    timeline): batched decode (4 rows, BatchSession — the Batcher's
    execution path) on the 1B while (a) a scraper thread plays the
    gateway's fleet scrape against this replica every ~50 ms (40x the
    production 2 s cadence) — rendering the full /metrics body (StepStats
    + profiling gauges + goodput) AND parsing it back through the
    federation parser, i.e. both halves of the scrape — and (b) a
    pre-bound batch_step timeline event lands per chunk
    (the DLT_BATCH_TIMELINE=1 serving configuration); vs both off. Every
    emission/scrape is host-side, so the acceptance bar is the same <=2%
    decode-throughput delta the tracing/profiling legs hold."""
    import threading

    from distributed_llama_tpu.runtime.batch_session import BatchSession
    from distributed_llama_tpu.runtime.engine import InferenceEngine
    from distributed_llama_tpu.runtime.telemetry import (
        GoodputAggregator, GoodputLedger,
    )
    from distributed_llama_tpu.runtime.tracing import (
        Tracer, render_step_stats,
    )
    from distributed_llama_tpu.server.fleet import parse_prom_text

    path = ensure_model()
    b = 4
    chunk = 64
    n_chunks = 8
    prompts = [
        [(i * (r + 3) % 1000) + 1 for i in range(96 + 13 * r)] for r in range(b)
    ]

    def run(observed: bool):
        eng = InferenceEngine(
            path, compute_dtype="bfloat16", batch=b, max_chunk=256,
            decode_chunk_size=chunk, prefix_cache_mb=0, speculative="off",
        )
        goodput = GoodputAggregator()
        tracer = Tracer(capacity=1 << 15)
        em = tracer.bind_global(
            "batch_step",
            ("decoding", "prefilling", "free", "spec",
             "pool_pages_used", "queue_depth"),
        )
        from distributed_llama_tpu.runtime.tracing import now_us

        def cycle(record):
            """One admit -> decode-chunks -> release cycle; returns the
            measured chunk walls when `record`."""
            session = BatchSession(eng)
            for r in range(b):
                session.admit(r, prompts[r])
            walls = []
            for _ in range(n_chunks):
                t0 = time.perf_counter()
                session.step(chunk)
                dur = time.perf_counter() - t0
                if observed:
                    em(now_us(), int(dur * 1e6), b, 0, 0, 0, 0, 0)
                if record:
                    walls.append(dur)
            if observed:
                goodput.record(GoodputLedger(
                    generated_tokens=b * chunk * n_chunks, outcome="ok",
                ))
            for r in range(b):
                session.release(r)
            return walls

        cycle(record=False)  # warmup: compiles the batch ladder
        stop = threading.Event()
        n_scrapes = [0]

        def scraper():
            while not stop.is_set():
                body = render_step_stats(
                    eng.stats,
                    extra_gauges={
                        "goodput_tokens_per_s": goodput.goodput_tokens_per_s()
                    },
                    extra_counter_series={
                        "wasted_tokens": goodput.wasted_series()
                    },
                )
                parse_prom_text(body)  # the gateway-side half of the scrape
                n_scrapes[0] += 1
                stop.wait(0.05)

        th = None
        if observed:
            th = threading.Thread(target=scraper, daemon=True)
            th.start()
        walls = cycle(record=True)
        if th is not None:
            stop.set()
            th.join(timeout=2)
        per_tok = sorted(w * 1e3 / chunk for w in walls)
        p95 = per_tok[min(len(per_tok) - 1, int(len(per_tok) * 0.95))]
        rate = b * chunk * len(walls) / sum(walls)
        n_events = len(tracer.for_names(("batch_step",)))
        del eng
        return rate, p95, n_scrapes[0], n_events

    rate_on, p95_on, n_scrapes, n_events = run(True)
    assert n_events > 0, "observed arm emitted no timeline steps"
    assert n_scrapes > 0, "observed arm never scraped"
    rate_off, p95_off, _, _ = run(False)
    overhead_pct = 100.0 * (rate_off - rate_on) / max(rate_off, 1e-9)
    return {
        "config": "llama-1B q40 1chip fleet-overhead b=4",
        "decode_tok_s_observed": round(rate_on, 2),
        "decode_tok_s_unobserved": round(rate_off, 2),
        "throughput_overhead_pct": round(overhead_pct, 2),
        "overhead_bar_pct": 2.0,
        "p95_step_ms_observed": round(p95_on, 3),
        "p95_step_ms_unobserved": round(p95_off, 3),
        "fleet_scrapes": n_scrapes,
        "timeline_steps": n_events,
    }


def leg_routing():
    """Cache-aware routing twin (server/router.py): FOUR live replicas
    behind a gateway, shared-512-prefix traffic (6 requests, distinct
    tails), cache-aware vs least-inflight — the ISSUE-10 serving-tier leg.
    Cache-aware lands every follow-up on the replica whose radix cache
    holds the prefix (ONE cold prefill fleet-wide -> 5 hits);
    least-inflight round-robins the prefix across the fleet (2,2,1,1 ->
    2 hits), so the expected hit-token gain is 2.5x. Reported per arm:
    median follow-up TTFT at the CLIENT (first SSE byte through the
    gateway) and fleet-wide prefix_hit_tokens_per_s (summed replica
    counters over the traffic window). Each arm uses a disjoint prefix so
    the second arm can't ride the first arm's cache entries."""
    import http.client as _hc
    import json as _json
    import socket as _socket
    import statistics as _st
    import threading
    import urllib.request

    from distributed_llama_tpu.cli import build_arg_parser
    from distributed_llama_tpu.server import api as api_mod
    from distributed_llama_tpu.server import gateway as gw_mod
    from distributed_llama_tpu.server.gateway import (
        Backend, Balancer, GatewayConfig,
    )
    from distributed_llama_tpu.testing import write_tiny_tokenizer

    model = build_model(
        "llama_routing_q40_v1",
        dim=512, hidden_dim=1536, n_layers=8, n_heads=8, n_kv_heads=4,
        vocab_size=4096, seq_len=2048,
    )
    tok_path = os.path.join(CACHE_DIR, "routing_tok_v1.t")
    if not os.path.exists(tok_path):
        write_tiny_tokenizer(
            tok_path, pad_to=4096,
            chat_template="{% for m in messages %}<|im_start|>...{% endfor %}",
        )

    def free_port():
        s = _socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    # four replicas (cost tables off: eight AOT ladders would dominate the
    # leg's wall for zero routing signal)
    os.environ["DLT_COST_TABLE"] = "0"
    servers, ports = [], []
    try:
        for i in range(4):
            p = build_arg_parser()
            p.add_argument("--port", type=int, default=0)
            port = free_port()
            args = p.parse_args(
                [
                    "inference", "--model", model, "--tokenizer", tok_path,
                    "--steps", "0", "--temperature", "0.0",
                    "--port", str(port),
                ]
            )
            httpd = api_mod.serve(args)
            threading.Thread(target=httpd.serve_forever, daemon=True).start()
            servers.append(httpd)
            ports.append(port)

        def fleet_hit_tokens():
            total = 0
            for port in ports:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/health", timeout=30
                ) as r:
                    total += _json.loads(r.read())["counters"].get(
                        "prefix_hit_tokens", 0
                    )
            return total

        def ttft_request(gw_port, system, user):
            """Client-observed TTFT: POST a streaming chat through the
            gateway, clock the first SSE byte (headers go out with the
            first token chunk on this server)."""
            conn = _hc.HTTPConnection("127.0.0.1", gw_port, timeout=600)
            body = _json.dumps(
                {
                    "messages": [
                        {"role": "system", "content": system},
                        {"role": "user", "content": user},
                    ],
                    "max_tokens": 16,
                    "stream": True,
                }
            )
            t0 = time.perf_counter()
            conn.request(
                "POST", "/v1/chat/completions", body=body,
                headers={"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            first = resp.read(1)
            ttft_ms = (time.perf_counter() - t0) * 1e3
            assert first, "empty response through the gateway"
            resp.read()
            conn.close()
            return ttft_ms

        def run_arm(policy, prefix_char):
            cfg = GatewayConfig(
                backends=[Backend("127.0.0.1", port) for port in ports],
                probe_interval_s=0,
                # no scraper: the twin isolates the AFFINITY half of the
                # policy (deterministic serial traffic; signal scoring has
                # its own unit coverage), and replica hit counters are read
                # directly off /health
                fleet_scrape_s=0,
                router_policy=policy,
            )
            bal = Balancer(cfg)
            gw_port = free_port()
            stop = threading.Event()
            threading.Thread(
                target=gw_mod.run, args=(gw_port, bal, stop), daemon=True
            ).start()
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                try:
                    _socket.create_connection(
                        ("127.0.0.1", gw_port), timeout=0.2
                    ).close()
                    break
                except OSError:
                    time.sleep(0.02)
            shared = prefix_char * 512  # ~512 leading tokens (byte vocab)
            try:
                hits0 = fleet_hit_tokens()
                t_arm0 = time.perf_counter()
                ttfts = [
                    ttft_request(gw_port, shared, f"question number {i}")
                    for i in range(6)
                ]
                arm_wall_s = time.perf_counter() - t_arm0
                hit_tokens = fleet_hit_tokens() - hits0
            finally:
                stop.set()
            return {
                "ttft_ms_cold": round(ttfts[0], 1),
                "ttft_ms_hit_median": round(_st.median(ttfts[1:]), 1),
                "prefix_hit_tokens": hit_tokens,
                "prefix_hit_tokens_per_s": round(hit_tokens / arm_wall_s, 1),
            }

        # warm the compile ladder through replica 0 on unrelated traffic
        # (in-process jit caches are shared by shape, so one replica's
        # warmup covers the fleet; the prefix is disjoint from both arms)
        ttft_request(ports[0], "W" * 520, "warm")
        li = run_arm("least_inflight", "L")
        ca = run_arm("cache_aware", "C")
    finally:
        os.environ.pop("DLT_COST_TABLE", None)
        for s in servers:
            s.shutdown()
    ratio = ca["prefix_hit_tokens"] / max(li["prefix_hit_tokens"], 1)
    return {
        "config": "llama-routing q40 4-replica shared-512-prefix x6",
        "ttft_ms_cold_cache_aware": ca["ttft_ms_cold"],
        "ttft_ms_hit_median_cache_aware": ca["ttft_ms_hit_median"],
        "ttft_ms_hit_median_least_inflight": li["ttft_ms_hit_median"],
        "prefix_hit_tokens_cache_aware": ca["prefix_hit_tokens"],
        "prefix_hit_tokens_least_inflight": li["prefix_hit_tokens"],
        "prefix_hit_tokens_per_s_cache_aware": ca["prefix_hit_tokens_per_s"],
        "prefix_hit_tokens_per_s_least_inflight": li["prefix_hit_tokens_per_s"],
        "hit_tokens_gain_x": round(ratio, 2),
        "gain_bar_x": 2.0,
    }


def leg_kv_movement():
    """KV movement leg (runtime/kv_transport.py): the ISSUE-13 disagg
    transfer bar. One prefill worker + one decode worker peered DIRECTLY
    at it (same-process registry), both on the paged server default. Two
    arms over identical fresh-prefix traffic: the DEVICE transport (KV
    handed over as device arrays, zero host serialization) vs the HTTP
    binary codec forced by DLT_KV_TRANSPORT=http — median per-request
    kv_transfer_us from the goodput ledger, bar: device cuts the transfer
    wall >= 3x. Plus the content-addressed re-send proof: a grown prefix
    ships only its missing pages (disagg_pages_skipped > 0)."""
    import json as _json
    import socket as _socket
    import statistics as _st
    import threading
    import urllib.request

    from distributed_llama_tpu.cli import build_arg_parser
    from distributed_llama_tpu.server import api as api_mod
    from distributed_llama_tpu.server.disagg import DisaggClient
    from distributed_llama_tpu.testing import write_tiny_tokenizer

    model = build_model(
        "llama_routing_q40_v1",
        dim=512, hidden_dim=1536, n_layers=8, n_heads=8, n_kv_heads=4,
        vocab_size=4096, seq_len=2048,
    )
    tok_path = os.path.join(CACHE_DIR, "routing_tok_v1.t")
    if not os.path.exists(tok_path):
        write_tiny_tokenizer(
            tok_path, pad_to=4096,
            chat_template="{% for m in messages %}<|im_start|>...{% endfor %}",
        )

    def free_port():
        s = _socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    os.environ["DLT_COST_TABLE"] = "0"
    servers = []
    try:
        def start(extra):
            p = build_arg_parser()
            p.add_argument("--port", type=int, default=0)
            port = free_port()
            args = p.parse_args(
                [
                    "inference", "--model", model, "--tokenizer", tok_path,
                    "--steps", "0", "--temperature", "0.0",
                    "--port", str(port),
                ] + extra
            )
            httpd = api_mod.serve(args)
            threading.Thread(target=httpd.serve_forever, daemon=True).start()
            servers.append(httpd)
            return port, httpd

        pf_port, _pf = start(["--role", "prefill"])
        dec_port, dec = start(
            ["--role", "decode", "--prefill-peer", f"127.0.0.1:{pf_port}"]
        )
        state = dec.RequestHandlerClass.state

        def ask(system, user):
            req = urllib.request.Request(
                f"http://127.0.0.1:{dec_port}/v1/chat/completions",
                data=_json.dumps(
                    {
                        "messages": [
                            {"role": "system", "content": system},
                            {"role": "user", "content": user},
                        ],
                        "max_tokens": 8,
                    }
                ).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=600) as r:
                return _json.loads(r.read())

        def run_arm(transport, tag, n=4):
            state.disagg = DisaggClient(
                state, [("127.0.0.1", pf_port)], transport=transport
            )
            walls = []
            tokens = 0
            for i in range(n):
                # distinct 512-char prefixes: every request is a real
                # transfer, never a local hit
                r = ask(f"{tag}{i}" + "x" * 508, f"question {i}")
                g = r["usage"]["goodput"]
                assert g["kv_transfer_path"] == transport, g
                walls.append(g["kv_transfer_us"])
                tokens += g["prompt_tokens"] - g["prefix_hit_tokens"]
            return {
                "kv_transfer_us_median": int(_st.median(walls)),
                "remote_prefill_tokens": tokens,
            }

        # warm both ladders through one throwaway request per arm
        run_arm("device", "W")
        run_arm("http", "V", n=1)
        dev = run_arm("device", "D")
        http = run_arm("http", "H")

        # content-addressed re-send: base prefix, then the grown twin —
        # only the missing pages ship
        state.disagg = DisaggClient(
            state, [("127.0.0.1", pf_port)], transport="device"
        )
        base = "G" + "g" * 255  # ~256-token base prefix
        ask(base, "first")
        c0 = state.engine.stats.counters_snapshot()
        ask(base + "h" * 512, "second")
        c1 = state.engine.stats.counters_snapshot()
        skipped = c1.get("disagg_pages_skipped", 0) - c0.get(
            "disagg_pages_skipped", 0
        )
        bytes_dev = c1.get("kv_transfer_bytes_device", 0)
        bytes_http = c1.get("kv_transfer_bytes_http", 0)
    finally:
        os.environ.pop("DLT_COST_TABLE", None)
        for s in servers:
            s.shutdown()
    gain = http["kv_transfer_us_median"] / max(dev["kv_transfer_us_median"], 1)
    return {
        "config": "kv-movement q40 prefill->decode disagg, device vs http",
        "kv_transfer_us_device_median": dev["kv_transfer_us_median"],
        "kv_transfer_us_http_median": http["kv_transfer_us_median"],
        "device_gain_x": round(gain, 2),
        "gain_bar_x": 3.0,
        "pages_skipped_resend": skipped,
        "kv_transfer_bytes_device_total": bytes_dev,
        "kv_transfer_bytes_http_total": bytes_http,
    }


def leg_kv_integrity():
    """Data-plane integrity leg (ISSUE 16, runtime/kv_transport.py +
    server/chaos.py): the same prefill->decode disagg pair as the KV
    movement leg, but the decode worker reaches the prefill worker through
    a ChaosProxy flipping one bit in ~10% of responses (seeded). Two arms
    over identical fresh-prefix traffic on the forced-HTTP wire: no-fault
    vs corrupted. Every corrupted transfer must be REJECTED by the
    checksum gate and degrade to local prefill — zero failed requests —
    and goodput must hold >= 90% of the no-fault arm (the corruption tax
    is a re-prefill, never a retry storm or a poisoned cache)."""
    import json as _json
    import socket as _socket
    import threading
    import time as _time
    import urllib.request

    from distributed_llama_tpu.cli import build_arg_parser
    from distributed_llama_tpu.server import api as api_mod
    from distributed_llama_tpu.server.chaos import (
        BITFLIP, ChaosProxy, Fault, FaultPlan,
    )
    from distributed_llama_tpu.server.disagg import DisaggClient
    from distributed_llama_tpu.testing import write_tiny_tokenizer

    model = build_model(
        "llama_routing_q40_v1",
        dim=512, hidden_dim=1536, n_layers=8, n_heads=8, n_kv_heads=4,
        vocab_size=4096, seq_len=2048,
    )
    tok_path = os.path.join(CACHE_DIR, "routing_tok_v1.t")
    if not os.path.exists(tok_path):
        write_tiny_tokenizer(
            tok_path, pad_to=4096,
            chat_template="{% for m in messages %}<|im_start|>...{% endfor %}",
        )

    def free_port():
        s = _socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    os.environ["DLT_COST_TABLE"] = "0"
    servers = []
    proxy = None
    try:
        def start(extra):
            p = build_arg_parser()
            p.add_argument("--port", type=int, default=0)
            port = free_port()
            args = p.parse_args(
                [
                    "inference", "--model", model, "--tokenizer", tok_path,
                    "--steps", "0", "--temperature", "0.0",
                    "--port", str(port),
                ] + extra
            )
            httpd = api_mod.serve(args)
            threading.Thread(target=httpd.serve_forever, daemon=True).start()
            servers.append(httpd)
            return port, httpd

        pf_port, _pf = start(["--role", "prefill"])
        dec_port, dec = start(
            ["--role", "decode", "--prefill-peer", f"127.0.0.1:{pf_port}"]
        )
        state = dec.RequestHandlerClass.state
        proxy = ChaosProxy(
            "127.0.0.1", pf_port,
            FaultPlan(random_mix=[(0.10, Fault(BITFLIP))], seed=16),
        ).start()

        def ask(system, user):
            req = urllib.request.Request(
                f"http://127.0.0.1:{dec_port}/v1/chat/completions",
                data=_json.dumps(
                    {
                        "messages": [
                            {"role": "system", "content": system},
                            {"role": "user", "content": user},
                        ],
                        "max_tokens": 8,
                    }
                ).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=600) as r:
                return _json.loads(r.read())

        def run_arm(peer_port, tag, n=12):
            # generous strike budget: this arm measures the per-transfer
            # corruption tax, not the quarantine cutoff (that proof lives
            # in tests/test_kv_integrity.py)
            state.disagg = DisaggClient(
                state, [("127.0.0.1", peer_port)], transport="http",
                integrity_strikes=10_000,
            )
            c0 = state.engine.stats.counters_snapshot()
            delivered = 0
            failures = 0
            t0 = _time.perf_counter()
            for i in range(n):
                try:
                    r = ask(f"{tag}{i}" + "x" * 508, f"question {i}")
                    delivered += r["usage"]["completion_tokens"]
                except Exception:
                    failures += 1
            wall = _time.perf_counter() - t0
            c1 = state.engine.stats.counters_snapshot()
            return {
                "goodput_tokens_per_s": delivered / max(wall, 1e-9),
                "failures": failures,
                "rejected": c1.get("kv_integrity_rejected", 0)
                - c0.get("kv_integrity_rejected", 0),
                "verified": c1.get("kv_integrity_verified", 0)
                - c0.get("kv_integrity_verified", 0),
            }

        run_arm(pf_port, "W", n=2)  # warm the ladders off the clock
        base = run_arm(pf_port, "B")
        chaos = run_arm(proxy.port, "C")
    finally:
        os.environ.pop("DLT_COST_TABLE", None)
        if proxy is not None:
            proxy.stop()
        for s in servers:
            s.shutdown()
    assert base["failures"] == 0 and chaos["failures"] == 0, (base, chaos)
    assert chaos["rejected"] > 0, chaos  # the 10% mix must actually bite
    retention = 100.0 * chaos["goodput_tokens_per_s"] / max(
        base["goodput_tokens_per_s"], 1e-9
    )
    return {
        "config": "kv-integrity http disagg, 10% bitflipped transfers",
        "goodput_tokens_per_s_nofault": round(
            base["goodput_tokens_per_s"], 1
        ),
        "goodput_tokens_per_s_corrupted": round(
            chaos["goodput_tokens_per_s"], 1
        ),
        "corruption_goodput_retention_pct": round(retention, 1),
        "retention_bar_pct": 90.0,
        "transfers_rejected": chaos["rejected"],
        "transfers_verified": base["verified"] + chaos["verified"],
        "failed_requests": base["failures"] + chaos["failures"],
    }


def leg_kv_tiering():
    """Tiered KV store leg (ISSUE 19, runtime/kv_tiering.py): a shared-
    prefix working set ~3x the HBM prefix budget over identical traffic,
    three arms: (A) all-in-HBM (budget holds everything — the ceiling),
    (B) 1/3 budget with the host tier on (eviction demotes, a repeat hit
    promotes through the warmed insert ladder), (C) 1/3 budget with
    tiering off (eviction deletes — today's cold-prefill fallback). Bar:
    arm B's hit-TTFT holds >= 80% of arm A's (retention = A/B), while
    arm C pays full re-prefill. Engine-level (the server twin of this is
    tests/test_kv_tiering.py): fetch + deferred apply before generate is
    exactly the serialized completion path's sequence."""
    import statistics as _st

    from distributed_llama_tpu.runtime.engine import InferenceEngine
    from distributed_llama_tpu.runtime.kv_tiering import TieredKvStore

    path = build_model(
        "llama_tier_q40_v1",
        dim=512, hidden_dim=1536, n_layers=8, n_heads=8, n_kv_heads=4,
        vocab_size=4096, seq_len=2048,
    )
    n_set = 9
    prompts = [
        [((i * 31 + s * 257) % 1000) + 1 for i in range(576)]
        for s in range(n_set)
    ]

    def run(mb, host_mb, disk_mb, disk_dir):
        eng = InferenceEngine(
            path, compute_dtype="bfloat16", max_chunk=256,
            decode_chunk_size=64, prefix_cache_mb=mb,
        )
        store = None
        if host_mb or disk_mb:
            store = TieredKvStore(
                eng, host_mb=host_mb, disk_mb=disk_mb, disk_dir=disk_dir,
                peers=[],
            )
            eng.kv_tier = store
            eng.prefix_cache.tier = store
        # compile warm-through on unrelated traffic (off the clock)
        warm = [((i * 13) % 900) + 50 for i in range(576)]
        for _ in range(2):
            eng.reset()
            eng.generate(warm, 592, sampler=None, on_token=lambda t: None)

        def serve(ids):
            # the serialized completion path's sequence: tier fetch on
            # the handler thread, deferred insert applied on the engine
            # thread (here: the same thread), then the unmodified
            # admission path
            if store is not None:
                pending = store.fetch(ids).get("pending_kv")
                if pending is not None:
                    pending.apply(None)
            eng.reset()
            return eng.generate(
                ids, len(ids) + 16, sampler=None, on_token=lambda t: None
            )

        for ids in prompts:  # pass 1: populate (and demote, arms B/C)
            serve(ids)
        if store is not None:
            # settle: the demotion drain is async by design; the bench
            # measures promotion, not a race with the drain thread
            deadline = time.time() + 10.0
            while not store._demote_q.empty() and time.time() < deadline:
                time.sleep(0.05)
        c0 = eng.stats.counters_snapshot()
        ttfts = []
        for ids in prompts:  # pass 2: the measured hit pass
            ttfts.append(serve(ids).ttft_us / 1e3)
        c1 = eng.stats.counters_snapshot()
        delta = {
            k: c1.get(k, 0) - c0.get(k, 0)
            for k in (
                "kv_tier_hits_host", "kv_tier_hits_disk",
                "kv_tier_local_hits", "kv_tier_misses",
                "kv_tier_promotions", "kv_tier_promoted_tokens",
                "kv_tier_demoted_host", "kv_tier_demoted_disk",
                "prefix_hit_tokens",
            )
        }
        entry_bytes = max(
            (e.nbytes for e in eng.prefix_cache._entries.values()),
            default=0,
        )
        if store is not None:
            store.close()
        del eng
        return _st.median(ttfts), delta, entry_bytes

    import tempfile as _tf

    with _tf.TemporaryDirectory(prefix="dlt_tier_bench_") as disk_dir:
        # arm A: everything fits — measures the warm-splice ceiling and
        # sizes the 1/3 budget for the constrained arms
        hbm_ttft, hbm_c, entry_bytes = run(512, 0, 0, disk_dir)
        ws_bytes = entry_bytes * n_set
        small_mb = max(1, int(ws_bytes / 3 / (1024 * 1024)))
        tier_ttft, tier_c, _ = run(small_mb, 256, 256, disk_dir)
        cold_ttft, cold_c, _ = run(small_mb, 0, 0, disk_dir)

    tier_hits = tier_c["kv_tier_hits_host"] + tier_c["kv_tier_hits_disk"]
    lookups = (
        tier_hits + tier_c["kv_tier_local_hits"] + tier_c["kv_tier_misses"]
    )
    assert tier_c["kv_tier_demoted_host"] > 0, tier_c  # eviction must demote
    assert tier_c["kv_tier_promotions"] > 0, tier_c    # and hits must promote
    retention = 100.0 * hbm_ttft / max(tier_ttft, 1e-9)
    return {
        "config": f"kv-tiering shared-prefix x{n_set}, budget 1/3 working set",
        "hit_ttft_ms_hbm": round(hbm_ttft, 1),
        "hit_ttft_ms_tiered": round(tier_ttft, 1),
        "hit_ttft_ms_cold_fallback": round(cold_ttft, 1),
        "tier_ttft_retention_pct": round(retention, 1),
        "retention_bar_pct": 80.0,
        "tier_hit_rate_pct": round(100.0 * tier_hits / max(lookups, 1), 1),
        "tier_promoted_tokens": tier_c["kv_tier_promoted_tokens"],
        "tier_demotions": tier_c["kv_tier_demoted_host"]
        + tier_c["kv_tier_demoted_disk"],
        "working_set_mb": round(ws_bytes / (1024 * 1024), 1),
        "hbm_budget_mb_constrained": small_mb,
    }


def leg_loadtwin():
    """Fleet-control-plane leg (server/loadtwin.py + server/scheduler.py):
    the ISSUE-12 mixed-class SLO twin. One seeded bursty mixed-class trace
    (interactive chat bursts + shared-prefix RAG fan-out + agentic tool
    loops with pauses + long batch jobs + client abandonment) replayed
    against two identical 3-replica stub fleets behind REAL gateways —
    SLO classes ON vs stripped-to-standard (the no-class baseline). The
    bars: interactive-class TTFT p95 holds the 300 ms SLO with classes
    on, and fleet goodput over a common measurement horizon stays >= 90%
    of the baseline (preempted batch work is deferred-and-retried, not
    lost). Engine-free (stub service times), so this leg measures the
    CONTROL PLANE — scheduling, routing, retry dynamics — not matmuls."""
    from distributed_llama_tpu.server.loadtwin import (
        LoadTwin, StubReplicaConfig, make_mixed_trace,
    )

    SLO_MS = 300.0
    HORIZON_S = 4.5
    cfg = StubReplicaConfig(batch_slots=2, token_ms=3.0, slo_ttft_ms=SLO_MS)
    trace = make_mixed_trace(seed=11, scale=1.5, duration_s=2.0)
    reports = {}
    decisions = {}
    for enabled in (True, False):
        tw = LoadTwin(
            n_replicas=3, replica_cfg=cfg, classes_enabled=enabled,
            fleet_scrape_s=0.1,
        )
        try:
            reports[enabled] = tw.report(tw.run(trace), horizon_s=HORIZON_S)
            if enabled:
                decisions = {
                    k: v
                    for r in tw.replicas
                    for k, v in r.state.scheduler.decisions_snapshot().items()
                    if ":" in k and not k.endswith(":admit")
                }
        finally:
            tw.close()
    cls, noc = reports[True], reports[False]
    assert cls["failures"] == 0 and noc["failures"] == 0, (cls, noc)
    retention = 100.0 * cls["goodput_tokens_per_s"] / max(
        noc["goodput_tokens_per_s"], 1e-9
    )
    return {
        "config": "load-twin 3-replica mixed-class slo",
        "interactive_ttft_p95_ms": cls["classes"]["interactive"]["ttft_p95_ms"],
        "interactive_ttft_p95_ms_noclass": (
            noc["classes"]["interactive"]["ttft_p95_ms"]
        ),
        "interactive_ttft_p50_ms": cls["classes"]["interactive"]["ttft_p50_ms"],
        "slo_ttft_ms_target": SLO_MS,
        "fleet_goodput_tokens_per_s": cls["goodput_tokens_per_s"],
        "fleet_goodput_tokens_per_s_noclass": noc["goodput_tokens_per_s"],
        "goodput_retention_pct": round(retention, 1),
        "retention_bar_pct": 90.0,
        "makespan_s": cls["makespan_s"],
        "makespan_s_noclass": noc["makespan_s"],
        "delivered_tokens": cls["delivered_tokens"],
        "scheduler_decisions": decisions,
        "fleet_prefix_hit_tokens": cls["fleet_prefix_hit_tokens"],
    }


def leg_gateway_chaos():
    """Gateway failure-domain leg (ISSUE 15, server/peering.py +
    server/recovery.py): TWO active-active peered gateways over a
    6-replica stub fleet replaying the seeded mixed trace, with gateway 0
    hard-killed mid-run and warm-restarted (crash-only recovery from the
    fleet) — vs the same trace on a fault-free twin. The bars: fleet
    goodput over a common horizon holds >= 90% of no-fault (clients fail
    over between gateway addresses; zero failed requests), and a warm-
    restarted gateway's first post-restart window recovers >= 80% of the
    pre-kill prefix-hit rate (locality re-learned from the fleet's
    /debug/hot_prefixes) while the cold baseline re-learns from scratch.
    Engine-free: this leg measures the control plane's failure domain."""
    import threading as _threading

    from distributed_llama_tpu.server.loadtwin import (
        LoadTwin, StubReplicaConfig, TwinRequest, make_mixed_trace,
    )
    from distributed_llama_tpu.server.router import (
        messages_prefix_text, prefix_chain, rendezvous_owner,
    )

    HORIZON_S = 6.0
    cfg = StubReplicaConfig(batch_slots=4, token_ms=2.0)
    trace = make_mixed_trace(seed=23, duration_s=2.0)

    def run_arm(chaos: bool):
        tw = LoadTwin(
            n_replicas=6, replica_cfg=cfg, fleet_scrape_s=0.1,
            n_gateways=2, peer_sync_s=0.1, retry_attempts=3,
        )
        try:
            timers = []
            if chaos:
                timers = [
                    _threading.Timer(0.8, tw.kill_gateway, args=(0,)),
                    _threading.Timer(1.6, tw.restart_gateway, args=(0,)),
                ]
                for t in timers:
                    t.daemon = True
                    t.start()
            results = tw.run(trace)
            for t in timers:
                t.join(timeout=10)
            rep = tw.report(results, horizon_s=HORIZON_S)
            rep["gateway_failovers"] = sum(
                r.gateway_failovers for r in results if r is not None
            )
            return rep
        finally:
            tw.close()

    base = run_arm(chaos=False)
    chaos = run_arm(chaos=True)
    assert base["failures"] == 0 and chaos["failures"] == 0, (base, chaos)
    retention = 100.0 * chaos["goodput_tokens_per_s"] / max(
        base["goodput_tokens_per_s"], 1e-9
    )

    # the restart prefix-recovery arm: learned homes that differ from the
    # rendezvous defaults (drain history), then kill + warm restart vs
    # kill + cold restart, hits counted over identical request windows
    SCRAPE_S = 0.25
    tw = LoadTwin(
        n_replicas=4,
        replica_cfg=StubReplicaConfig(batch_slots=8, token_ms=1.0),
        fleet_scrape_s=SCRAPE_S, quarantine_strikes=0,
    )
    apps = [f"benchapp{i} " * 24 for i in range(6)]

    def send_round(tag, per_app=3):
        for a, system in enumerate(apps):
            for j in range(per_app):
                res = tw._client(TwinRequest(
                    at_s=0.0, system=system, user=f"{tag} q{a}.{j}",
                    max_tokens=2,
                ))
                assert res.outcome == "ok", res

    try:
        keys = tw.replica_keys()
        for system in apps:
            chain = prefix_chain(messages_prefix_text(
                [{"role": "system", "content": system},
                 {"role": "user", "content": "x"}]
            ))
            owner = rendezvous_owner(chain[0], keys)
            tw.balancer.set_draining(owner, True)
            assert tw._client(TwinRequest(
                at_s=0.0, system=system, user="x", max_tokens=2,
            )).outcome == "ok"
            tw.balancer.set_draining(owner, False)
        send_round("warmup")
        h0 = tw.fleet_prefix_hit_tokens()
        send_round("prekill")
        pre_hits = tw.fleet_prefix_hit_tokens() - h0
        tw.kill_gateway(0)
        gw = tw.restart_gateway(0, recover=True)
        recovered_keys = gw.balancer.recovery["locality_keys"]
        recovery_wall_ms = gw.balancer.recovery["wall_ms"]
        h1 = tw.fleet_prefix_hit_tokens()
        send_round("postwarm")
        warm_hits = tw.fleet_prefix_hit_tokens() - h1
        tw.kill_gateway(0)
        tw.restart_gateway(0, recover=False)
        h2 = tw.fleet_prefix_hit_tokens()
        send_round("postcold")
        cold_hits = tw.fleet_prefix_hit_tokens() - h2
    finally:
        tw.close()

    return {
        "config": "gateway-chaos 2-gw active-active kill/restart + warm recovery",
        "fleet_goodput_tokens_per_s_nofault": base["goodput_tokens_per_s"],
        "fleet_goodput_tokens_per_s_chaos": chaos["goodput_tokens_per_s"],
        "failover_goodput_retention_pct": round(retention, 1),
        "retention_bar_pct": 90.0,
        "gateway_failovers": chaos["gateway_failovers"],
        "restart_prefix_recovery_attainment": round(
            warm_hits / max(pre_hits, 1), 3
        ),
        "restart_prefix_recovery_attainment_cold": round(
            cold_hits / max(pre_hits, 1), 3
        ),
        "recovery_bar_attainment": 0.8,
        "recovered_locality_keys": recovered_keys,
        "recovery_wall_ms": recovery_wall_ms,
    }


def leg_perplexity_proxy(path: str):
    """Accuracy proxy: mean next-token logprob delta of the bf16 production
    path vs the f32 reference path on a fixed prompt."""
    import jax.numpy as jnp
    import numpy as np

    from distributed_llama_tpu.formats.mfile import MFileReader
    from distributed_llama_tpu.models import (
        config_from_header, forward, init_kv_cache, load_params,
    )
    from distributed_llama_tpu.ops import build_rope_tables

    import jax

    toks = [(i * 37 % 1000) + 1 for i in range(256)]
    out = {}
    for dt in ("bfloat16", "float32"):
        reader = MFileReader(path)
        cfg = config_from_header(reader.header, compute_dtype=dt)
        params = load_params(reader, cfg)
        rope = build_rope_tables(reader.header)
        cache = init_kv_cache(cfg, batch=1)
        logits, _ = forward(
            cfg, params, rope, cache, jnp.asarray([toks], jnp.int32),
            jnp.int32(0), logits_mode="all",
        )
        lp = jnp.take_along_axis(
            jax.nn.log_softmax(logits[0, :-1]),
            jnp.asarray(toks[1:], jnp.int32)[:, None], axis=-1,
        )
        out[dt] = float(jnp.mean(lp))
    return {
        "config": "ppl-proxy llama-small",
        "mean_logprob_bf16": round(out["bfloat16"], 4),
        "mean_logprob_f32": round(out["float32"], 4),
        "abs_delta": round(abs(out["bfloat16"] - out["float32"]), 4),
    }


def main():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import jax

    configs = []

    # headline: 1B Llama
    model_path = ensure_model()
    t0 = time.time()
    # 896 decode tokens = SEVEN 128-chunks, so the median samples among
    # FIVE steady-state chunks (lookahead fully hides the ~100 ms tunnel
    # round trip behind 157 ms of chunk compute). The r5 384-token budget
    # had exactly ONE steady chunk between the two edge chunks: in a
    # degraded window the edges win a 3-element median and the leg
    # collapses (the 847-vs-730 PERF/BENCH discrepancy — VERDICT r5 weak
    # #1). With >=5 steady chunks the median is a steady chunk in any
    # window ordering.
    decode, prefill, ttft, marginal, wall_long, ttft_cold, overlap, prof, eng = measure(
        model_path, 512, 896, decode_chunk_size=128
    )
    print(
        f"# llama1b: decode {decode:.1f} tok/s, prefill {prefill:.1f} tok/s "
        f"(marginal {marginal and round(marginal, 1)}), "
        f"ttft {ttft:.1f} ms ({time.time()-t0:.0f}s incl compile) on {jax.devices()[0]}",
        file=sys.stderr,
    )
    headline = decode
    configs.append(
        {
            "config": "llama-1B q40 1chip",
            "decode_tok_s": round(decode, 2),
            "prefill_tok_s": round(prefill, 1),
            "prefill_tok_s_marginal": marginal and round(marginal, 1),
            "prefill_long_n": wall_long and wall_long[0],
            "prefill_wall_long_ms": wall_long and round(wall_long[1], 1),
            "prefill_dispatch_overlap_pct": overlap,
            "ttft_ms": round(ttft, 1),
            "ttft_cold_ms": round(ttft_cold, 1),
            "profile": prof,
        }
    )
    del eng

    # the small models are dispatch-overhead-bound below ~256-token chunks
    # (compute/chunk must clear the ~100 ms tunnel round trip for the
    # lookahead to hide it; r5 A/B at qwen3: chunk 256 = 1.14x chunk 128),
    # and their budgets are 3 chunks so the median samples a steady-state
    # chunk. The 1B/8B are compute-bound earlier. MoE prefills a 1024-token prompt: its
    # 512-token chunk computes in ~11 ms (profile_prefill --model moe), so
    # short prompts measure only the ~100 ms per-chunk dispatch.
    extra_legs = [
        ("qwen3-class q40 1chip",
         lambda: measure(ensure_qwen3(), 256, 768, decode_chunk_size=256)),
        ("qwen3-moe-class q40 1chip",
         lambda: measure(ensure_moe(), 1024, 768, decode_chunk_size=256)),
    ]
    for name, fn in extra_legs:
        try:
            d, p, t, m, wl, tc, ov, pr, _ = fn()
            configs.append(
                {
                    "config": name,
                    "decode_tok_s": round(d, 2),
                    "prefill_tok_s": round(p, 1),
                    "prefill_tok_s_marginal": m and round(m, 1),
                    "prefill_long_n": wl and wl[0],
                    "prefill_wall_long_ms": wl and round(wl[1], 1),
                    "prefill_dispatch_overlap_pct": ov,
                    "ttft_ms": round(t, 1),
                    "ttft_cold_ms": round(tc, 1),
                    "profile": pr,
                }
            )
            print(f"# {name}: decode {d:.1f}, prefill {p:.1f}", file=sys.stderr)
        except Exception as e:
            print(f"# {name} leg failed: {e!r}", file=sys.stderr)

    try:
        lc = leg_longcontext()
        configs.append(lc)
        print(f"# longctx: {lc}", file=sys.stderr)
    except Exception as e:
        print(f"# longcontext leg failed: {e!r}", file=sys.stderr)

    try:
        kvq = leg_kv_quant()
        configs.append(kvq)
        print(f"# kv-quant: {kvq}", file=sys.stderr)
    except Exception as e:
        print(f"# kv-quant leg failed: {e!r}", file=sys.stderr)

    try:
        bs = leg_batched_serving()
        configs.append(bs)
        print(f"# batched-serving: {bs}", file=sys.stderr)
    except Exception as e:
        print(f"# batched-serving leg failed: {e!r}", file=sys.stderr)

    try:
        il = leg_serving_interleave()
        configs.append(il)
        print(f"# interleaved-prefill: {il}", file=sys.stderr)
    except Exception as e:
        print(f"# interleaved-prefill leg failed: {e!r}", file=sys.stderr)

    try:
        pfx = leg_prefix_cache()
        configs.append(pfx)
        print(f"# shared-prefix: {pfx}", file=sys.stderr)
    except Exception as e:
        print(f"# shared-prefix leg failed: {e!r}", file=sys.stderr)

    try:
        pb = leg_paged_batch()
        configs.append(pb)
        print(f"# paged-batch: {pb}", file=sys.stderr)
    except Exception as e:
        print(f"# paged-batch leg failed: {e!r}", file=sys.stderr)

    try:
        sp = leg_speculative()
        configs.append(sp)
        print(f"# speculative: {sp}", file=sys.stderr)
    except Exception as e:
        print(f"# speculative leg failed: {e!r}", file=sys.stderr)

    try:
        gr = leg_grammar()
        configs.append(gr)
        print(f"# grammar: {gr}", file=sys.stderr)
    except Exception as e:
        print(f"# grammar leg failed: {e!r}", file=sys.stderr)

    try:
        tro = leg_tracing_overhead()
        configs.append(tro)
        print(f"# tracing-overhead: {tro}", file=sys.stderr)
    except Exception as e:
        print(f"# tracing-overhead leg failed: {e!r}", file=sys.stderr)

    try:
        po = leg_profiling_overhead()
        configs.append(po)
        print(f"# profiling-overhead: {po}", file=sys.stderr)
    except Exception as e:
        print(f"# profiling-overhead leg failed: {e!r}", file=sys.stderr)

    try:
        fo = leg_fleet_overhead()
        configs.append(fo)
        print(f"# fleet-overhead: {fo}", file=sys.stderr)
    except Exception as e:
        print(f"# fleet-overhead leg failed: {e!r}", file=sys.stderr)

    try:
        rt = leg_routing()
        configs.append(rt)
        print(f"# routing: {rt}", file=sys.stderr)
    except Exception as e:
        print(f"# routing leg failed: {e!r}", file=sys.stderr)

    try:
        kvm = leg_kv_movement()
        configs.append(kvm)
        print(f"# kv-movement: {kvm}", file=sys.stderr)
    except Exception as e:
        print(f"# kv-movement leg failed: {e!r}", file=sys.stderr)

    try:
        kvi = leg_kv_integrity()
        configs.append(kvi)
        print(f"# kv-integrity: {kvi}", file=sys.stderr)
    except Exception as e:
        print(f"# kv-integrity leg failed: {e!r}", file=sys.stderr)

    try:
        kvt = leg_kv_tiering()
        configs.append(kvt)
        print(f"# kv-tiering: {kvt}", file=sys.stderr)
    except Exception as e:
        print(f"# kv-tiering leg failed: {e!r}", file=sys.stderr)

    try:
        lt = leg_loadtwin()
        configs.append(lt)
        print(f"# load-twin: {lt}", file=sys.stderr)
    except Exception as e:
        print(f"# load-twin leg failed: {e!r}", file=sys.stderr)

    try:
        gc_leg = leg_gateway_chaos()
        configs.append(gc_leg)
        print(f"# gateway-chaos: {gc_leg}", file=sys.stderr)
    except Exception as e:
        print(f"# gateway-chaos leg failed: {e!r}", file=sys.stderr)

    try:
        l8 = leg_8b()
        configs.append(l8)
        print(f"# 8B-class: {l8}", file=sys.stderr)
    except Exception as e:
        print(f"# 8B leg failed: {e!r}", file=sys.stderr)

    try:
        pp = leg_perplexity_proxy(
            os.path.join(CACHE_DIR, "llama_32k_q40_v1.m")
            if os.path.exists(os.path.join(CACHE_DIR, "llama_32k_q40_v1.m"))
            else model_path
        )
        configs.append(pp)
        print(f"# ppl proxy: {pp}", file=sys.stderr)
    except Exception as e:
        print(f"# perplexity leg failed: {e!r}", file=sys.stderr)

    print(
        json.dumps(
            {
                "metric": "llama1b_q40_decode_tok_s_1chip",
                "value": round(headline, 2),
                "unit": "tokens/s",
                "vs_baseline": round(headline / BASELINE_TOK_S, 3),
                "configs": configs,
            }
        )
    )


if __name__ == "__main__":
    main()
