"""Rotary position embeddings.

Two pairing conventions, matching the reference exactly:

* **Llama style** (reference: ropeLlama_F32, src/nn/nn-cpu-ops.cpp:843-866):
  rotates *interleaved* pairs ``(x[2j], x[2j+1])`` within each head. The
  reference converter permutes HF q/k weights so this layout is correct
  (reference: converter/convert-hf.py:13-16) — since we read the same `.m`
  files, we must use the same convention.
* **Falcon/NeoX style** (reference: ropeFalcon_F32, src/nn/nn-cpu-ops.cpp:868-885,
  used by Qwen3): rotates *half-split* pairs ``(x[j], x[j+headDim/2])``.

Frequencies are ``theta^(-2j/headDim)`` for pair index j in both styles
(reference: fullfillRopeLlamaCache / fullfillRopeFalconCache,
src/nn/nn-core.cpp:345-377), optionally passed through the Llama-3.1
wavelength-dependent scaling (reference: scaleFrequencyLlama3,
src/nn/nn-core.cpp:328-342).

Tables are precomputed on the host in f64->f32 numpy (the reference
precomputes a [seqLen, dim] cache at graph-build time); on device the apply
functions are pure gathers + elementwise, fusing into the q/k matmuls.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import numpy as np
import jax
import jax.numpy as jnp

from ..formats.mfile import ModelHeader, RopeType


@jax.tree_util.register_pytree_node_class
@dataclass(frozen=True)
class RopeTables:
    """cos/sin lookup tables, shape [seq_len, head_dim // 2] (f32)."""

    cos: jnp.ndarray
    sin: jnp.ndarray

    def tree_flatten(self):
        return (self.cos, self.sin), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


def _scale_frequency_llama3(
    freq: float,
    scaling_factor: float,
    low_freq_factor: float,
    high_freq_factor: float,
    orig_max_seq_len: int,
) -> float:
    wave_len = 2.0 * math.pi / freq
    high_freq_wavelen = orig_max_seq_len / high_freq_factor
    if wave_len < high_freq_wavelen:
        return freq
    low_freq_wavelen = orig_max_seq_len / low_freq_factor
    if wave_len > low_freq_wavelen:
        return freq / scaling_factor
    smooth = (orig_max_seq_len / wave_len - low_freq_factor) / (high_freq_factor - low_freq_factor)
    return (1 - smooth) * freq / scaling_factor + smooth * freq


def build_rope_tables(h: ModelHeader) -> RopeTables:
    """Precompute per-position cos/sin for all pair indices of one head."""
    half = h.head_dim // 2
    freqs = np.empty(half, dtype=np.float64)  # dlt: allow(float64) — host-side precompute; cast to f32 before device
    # scaling is gated on the factor alone, matching the reference
    # (applyScaling = ropeScalingFactor != 1.0f, src/nn/nn-core.cpp:346) — a
    # LLAMA3_1-typed header without scaling keys must not apply scaling
    apply_scaling = h.rope_scaling_factor != 1.0
    for j in range(half):
        f = 1.0 / (h.rope_theta ** (2.0 * j / h.head_dim))
        if apply_scaling:
            f = _scale_frequency_llama3(
                f,
                h.rope_scaling_factor,
                h.rope_scaling_low_freq_factor,
                h.rope_scaling_high_freq_factor,
                h.rope_scaling_orig_max_seq_len,
            )
        freqs[j] = f
    pos = np.arange(h.seq_len, dtype=np.float64)[:, None]  # dlt: allow(float64) — host-side; angles cast to f32 below
    angles = (pos * freqs[None, :]).astype(np.float32)
    return RopeTables(cos=jnp.asarray(np.cos(angles)), sin=jnp.asarray(np.sin(angles)))


def apply_rope_llama(
    x: jnp.ndarray, tables: RopeTables, positions: jnp.ndarray
) -> jnp.ndarray:
    """Interleaved-pair rotation.

    x: [..., seq, n_heads, head_dim]; positions: [..., seq] int32.
    """
    cos = tables.cos[positions][..., None, :]  # [..., seq, 1, half]
    sin = tables.sin[positions][..., None, :]
    x0 = x[..., 0::2]
    x1 = x[..., 1::2]
    r0 = x0 * cos - x1 * sin
    r1 = x0 * sin + x1 * cos
    # re-interleave: stack along a new last axis then flatten
    out = jnp.stack([r0, r1], axis=-1).reshape(x.shape)
    return out.astype(x.dtype)


def apply_rope_falcon(
    x: jnp.ndarray, tables: RopeTables, positions: jnp.ndarray
) -> jnp.ndarray:
    """Half-split rotation (NeoX convention, used by Qwen3)."""
    cos = tables.cos[positions][..., None, :]
    sin = tables.sin[positions][..., None, :]
    half = x.shape[-1] // 2
    x0 = x[..., :half]
    x1 = x[..., half:]
    r0 = x0 * cos - x1 * sin
    r1 = x0 * sin + x1 * cos
    return jnp.concatenate([r0, r1], axis=-1).astype(x.dtype)


def apply_rope(
    x: jnp.ndarray, tables: RopeTables, positions: jnp.ndarray, rope_type: int
) -> jnp.ndarray:
    if rope_type in (RopeType.LLAMA, RopeType.LLAMA3_1):
        return apply_rope_llama(x, tables, positions)
    if rope_type == RopeType.FALCON:
        return apply_rope_falcon(x, tables, positions)
    raise ValueError(f"unsupported rope type {rope_type}")
