"""MoE router and ragged expert dispatch (Qwen3-MoE style).

Router semantics match the reference graph exactly (reference:
src/llm.cpp:440-514 + moeGateForward_F32_F32,
src/nn/nn-cpu-ops.cpp:1462-1492):

    probs  = softmax(x @ gate.T)            # full softmax over all experts
    topk   = top-k of probs
    weight = probs[topk] / sum(probs[topk])  # normTopk=1 renormalization

The reference then runs each active expert's SwiGLU through matmul kernels
that index a stacked weight tensor by expert id
(reference: src/nn/nn-cpu-ops.cpp:1166-1192). The TPU-native equivalent here
is a *sort-based ragged dispatch*: flatten the (token, slot) pairs, sort them
by expert id, and run the three FFN matmuls as `lax.ragged_dot` grouped
matmuls against the stacked expert weights resident in HBM. Memory is
O(rows * ff) activations and the weights are never gathered per token —
exact (no capacity factor, no dropped tokens), static shapes, MXU-tiled.
Single-token decode keeps the per-token gather formulation
(models/transformer.py) — reading only the k active experts' weights is
bandwidth-optimal there.

Expert parallelism: `moe_ffn_ragged(..., ep_axis=...)` runs under shard_map
with the expert axis of the stacked weights sharded over the mesh's `ep`
axis. Each shard sorts the GLOBAL row list, folds the rows belonging to
other shards into two zero-weight boundary groups (a padded [1+E_local+1]
group vector against a zero-padded weight stack — those rows produce exact
zeros), and the shards' partial outputs combine with one psum. This replaces
the reference's TP-within-expert-only layout (every node holds a slice of
every expert) with true expert placement; there is no reference analogue.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .quant import QuantTensor, dequantize_t, quantize_q80_activations, slice_layer

# A/B knob for the layer-fold formulation (measured NEUTRAL at bench scale,
# kept for stacks where the dynamic-slice transient grows with E*ff). Read
# ONCE at import: the value is baked into traced functions by the jit cache
# anyway, so a module-level constant makes the process-start-only contract
# structural instead of conventional (ADVICE r4).
import os as _os

MOE_LAYER_FOLD = _os.environ.get("DLT_MOE_LAYER_FOLD", "1") != "0"


def moe_router(
    x: jnp.ndarray, gate: jnp.ndarray, n_active: int, norm_topk: bool = True
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Select experts for each token.

    x: [..., dim]; gate: [n_experts, dim] f32.
    Returns (indices [..., n_active] int32, weights [..., n_active] f32).
    """
    logits = jnp.einsum(
        "...d,ed->...e",
        x.astype(jnp.float32),
        gate.astype(jnp.float32),
        precision=jax.lax.Precision.HIGHEST,
    )
    probs = jax.nn.softmax(logits, axis=-1)
    top_p, top_i = jax.lax.top_k(probs, n_active)
    if norm_topk:
        top_p = top_p / jnp.sum(top_p, axis=-1, keepdims=True)
    return top_i.astype(jnp.int32), top_p


def expert_stack_matrix(w, dtype) -> jnp.ndarray:
    """[E, in, out] dense matrix from a stacked expert weight — QuantTensor
    in the T layout (via quant.dequantize_t) or dense [E, out, in]. The
    contracting (`in`) axis lands in the middle, the shape `lax.ragged_dot`
    wants for its rhs."""
    if isinstance(w, QuantTensor):
        return dequantize_t(w, dtype)
    return jnp.swapaxes(w, -1, -2).astype(dtype)


def _padded_rows_bound(rows: int, n_groups: int, block_r: int) -> int:
    """Tight static bound on the expert-grouped padded row count, i.e. the
    grouped kernel's grid extent. Each NONEMPTY group wastes at most
    block_r - 1 pad rows (it rounds up to a block_r multiple); a zero-count
    group pads to ZERO rows, and at most min(n_groups, rows) groups can be
    nonempty. The old bound (rows + n_groups * block_r) carried a full
    block per group regardless — at decode shapes (rows ≈ b·k, many
    experts) the clip in the block→group map spilled up to n_groups
    all-zero row blocks onto the last group, each running a whole-expert
    matmul grid step for nothing (ADVICE r5 #4). Rounded up to a block_r
    multiple so the grid's floor division still covers every real block."""
    bound = rows + min(n_groups, rows) * (block_r - 1)
    return -(-bound // block_r) * block_r


def _grouped_layout(group_sizes: jnp.ndarray, rows: int, n_groups: int, block_r: int):
    """Row layout for the grouped Pallas kernel: each group padded to a
    block_r multiple so every row block belongs to exactly one expert.

    Returns (padded_idx [rows] — where sorted row r lands in the padded
    buffer, block_expert [n_blocks] — which group each row block computes,
    R_pad — the tight static bound on the padded row count, see
    `_padded_rows_bound`). Pad rows are zeros; their outputs are
    garbage-free (0 @ w = 0) and are never gathered back.
    """
    R_pad = _padded_rows_bound(rows, n_groups, block_r)
    starts = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(group_sizes.astype(jnp.int32))[:-1]]
    )
    padded_sizes = ((group_sizes + block_r - 1) // block_r) * block_r
    padded_starts = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(padded_sizes.astype(jnp.int32))[:-1]]
    )
    r = jnp.arange(rows, dtype=jnp.int32)
    g_of_r = jnp.searchsorted(starts, r, side="right").astype(jnp.int32) - 1
    padded_idx = padded_starts[g_of_r] + (r - starts[g_of_r])
    blocks = jnp.arange(R_pad // block_r, dtype=jnp.int32) * block_r
    block_expert = jnp.clip(
        jnp.searchsorted(padded_starts, blocks, side="right").astype(jnp.int32) - 1,
        0,
        n_groups - 1,
    )
    return padded_idx, block_expert, R_pad


def _grouped_layout_direct(g_flat: jnp.ndarray, n_groups: int, block_r: int):
    """Sort-free grouped layout: for each ORIGINAL row r (group id
    g_flat[r]), its destination in the expert-grouped padded buffer, plus
    each row block's group id.

    Replaces argsort + per-row searchsorted (the round-5 glue profile: one
    stable argsort over rows costs ~0.6 ms on TPU, paid per layer per
    chunk). Group ids are small ints, so a one-hot cumsum gives each row's
    stable rank within its group directly — O(rows * n_groups) VPU work
    instead of a sort network. Returns (dest [rows] int32, block_expert
    [R_pad // block_r] int32, R_pad)."""
    rows = g_flat.shape[0]
    R_pad = _padded_rows_bound(rows, n_groups, block_r)
    oh = (g_flat[:, None] == jnp.arange(n_groups, dtype=g_flat.dtype)).astype(
        jnp.int32
    )  # [rows, n_groups]
    within = jnp.sum(jnp.cumsum(oh, axis=0) * oh, axis=1) - 1  # stable rank
    counts = jnp.sum(oh, axis=0)
    padded_sizes = ((counts + block_r - 1) // block_r) * block_r
    padded_starts = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(padded_sizes.astype(jnp.int32))[:-1]]
    )
    dest = padded_starts[g_flat] + within
    blocks = jnp.arange(R_pad // block_r, dtype=jnp.int32) * block_r
    block_expert = jnp.clip(
        jnp.searchsorted(padded_starts, blocks, side="right").astype(jnp.int32) - 1,
        0,
        n_groups - 1,
    )
    return dest, block_expert, R_pad


def _grouped_quant_eligible(w1, w3, w2, dtype, q80: bool, pallas) -> bool:
    """The grouped Pallas kernel serves the production path: bf16 compute,
    Q40 expert stacks, Pallas on, tile-aligned shapes. The f32/q80 parity
    paths keep the exact dequant+ragged_dot formulation."""
    import jax.numpy as jnp

    from .quant import _use_pallas

    if pallas is None:
        pallas = _use_pallas()
    interpret = pallas == "interpret"
    if not (pallas or interpret) or q80 or dtype != jnp.bfloat16:
        return False
    from .pallas_q40 import q40_stacked_aligned

    for w in (w1, w3, w2):
        if not isinstance(w, QuantTensor):
            return False
        # same alignment contract as every other stacked kernel: the
        # flattened [E*nb, out] scale plane needs lane-aligned out AND
        # nb % 8 (Mosaic's sublane rule — invisible to interpret mode)
        if not q40_stacked_aligned(w.in_features, w.out_features):
            return False
    return True


def moe_ffn_ragged(
    y: jnp.ndarray,  # [b, t, dim] normed activations
    idx: jnp.ndarray,  # [b, t, k] int32 expert ids (GLOBAL, from moe_router)
    wts: jnp.ndarray,  # [b, t, k] f32 combine weights
    w1,
    w3,
    w2,  # stacked expert weights (QuantTensor T layout or dense [E?,out,in]);
    # with `layer` given, the FULL all-layers stacks ([L, E, ...])
    act_fn,  # hidden activation (silu/gelu)
    dtype,  # MXU operand dtype
    q80: bool = False,  # reference-parity Q80 activation round-trip
    ep_axis: str | None = None,  # shard_map axis name when experts are sharded
    pallas=None,  # None=auto | False | True | "interpret" (ops/quant.py)
    layer=None,  # scalar int32: weights are all-layers stacks and this
    # layer's experts are selected INSIDE the grouped kernel (flat group
    # index = layer * n_groups + e). The dynamic-slice alternative
    # materializes every expert's weights per layer per chunk (~50 MB a
    # layer at the bench MoE shape) — measured NEUTRAL there (3 interleaved
    # A/B reps, DLT_MOE_LAYER_FOLD knob; XLA overlaps the copy), but the
    # copy grows with E*ff (GB-scale at 30B-A3B) while the fold stays free
) -> jnp.ndarray:
    """Exact top-k expert SwiGLU via sort + grouped (ragged) matmuls.

    Math identical to the per-token gather formulation
    (models/transformer.py _moe_ffn): for every (token, slot) row,
    h = act(y@w1[e]) * (y@w3[e]); out = sum_k wts * (h@w2[e]) — but executed
    as three `lax.ragged_dot`s over expert-sorted rows, so the expert weights
    stream from HBM once per chunk instead of being gathered per token.
    """
    b, t, dim = y.shape
    k = idx.shape[-1]
    n_tok = b * t
    rows = n_tok * k

    e_flat = idx.reshape(rows)

    use_grouped = _grouped_quant_eligible(w1, w3, w2, dtype, q80, pallas)
    stacked = layer is not None
    if stacked and use_grouped:
        fold_off = not MOE_LAYER_FOLD
        # EP pads zero experts around the stack; padding the FULL all-layers
        # stack would copy every layer's experts (the very transient the
        # fold avoids) — slice this layer first until the pad moves to load
        # time. DLT_MOE_LAYER_FOLD=0 is the A/B knob (process-start-only,
        # read at trace time): forces the dynamic-slice formulation.
        if fold_off or ep_axis is not None:
            w1, w3, w2 = (slice_layer(w, layer) for w in (w1, w3, w2))
            stacked = False
    if not use_grouped:
        # the materialized/ragged_dot path works per layer — slice here
        # (these parity paths are not the production bandwidth path)
        w1, w3, w2 = (slice_layer(w, layer) for w in (w1, w3, w2))
        stacked = False
    e_axis = 1 if stacked else 0
    n_local = w1.q.shape[e_axis] if isinstance(w1, QuantTensor) else w1.shape[e_axis]

    if use_grouped:
        # production path: the grouped Pallas kernel streams the packed
        # expert stacks directly (ops/pallas_q40.py q40_matmul_pallas_grouped)
        # — no dequantized [E, dim, ff] transient exists at ANY expert count.
        # Layout is SORT-FREE (_grouped_layout_direct): one stable argsort
        # over the rows cost ~0.6 ms per layer per chunk on TPU — more than
        # the expert matmuls after 4-bit packing — and group ids are small
        # ints, so a one-hot cumsum replaces the sort entirely. Every
        # gather/scatter runs in ORIGINAL row order (dest map), so the
        # combine is a plain reshape + k-sum instead of a scatter-add.
        from .pallas_q40 import q40_matmul_pallas_grouped

        interpret = pallas == "interpret"
        w1q, w3q, w2q = w1, w3, w2
        if ep_axis is None:
            g_flat = e_flat
            n_groups = n_local
        else:
            # this shard owns experts [e0, e0 + n_local); other shards' rows
            # map to two zero-weight boundary groups (0 and n_local+1) so
            # they contribute exact zeros, then the shards' partials psum.
            # The boundary groups index zero experts padded onto both ends
            # of the stack's expert axis.
            e0 = jax.lax.axis_index(ep_axis) * n_local
            g_flat = jnp.where(
                e_flat < e0,
                0,
                jnp.where(e_flat >= e0 + n_local, n_local + 1, e_flat - e0 + 1),
            ).astype(jnp.int32)
            n_groups = n_local + 2

            def padq2(w, ax=e_axis):
                def z(a):
                    shp = list(a.shape)
                    shp[ax] = 1
                    return jnp.zeros(shp, a.dtype)

                return QuantTensor(
                    q=jnp.concatenate([z(w.q), w.q, z(w.q)], axis=ax),
                    d=jnp.concatenate([z(w.d), w.d, z(w.d)], axis=ax),
                )
            w1q, w3q, w2q = padq2(w1), padq2(w3), padq2(w2)

        # block_r trades tail-padding waste (small blocks) against expert
        # weight re-reads across row blocks (large groups split into many
        # blocks re-stream the same expert): target ~rows/n_groups, clamped
        avg = max(1, rows // max(n_groups, 1))
        block_r = 8
        while block_r * 2 <= min(avg, 64):
            block_r *= 2
        dest, block_expert, R_pad = _grouped_layout_direct(g_flat, n_groups, block_r)
        xrep = jnp.repeat(y.reshape(n_tok, dim), k, axis=0)  # row r = token r//k
        xp = jnp.zeros((R_pad, dim), y.dtype).at[dest].set(xrep.astype(y.dtype))
        if stacked:
            # fold the layer into the FLAT group index: the kernel DMAs this
            # layer's expert tiles straight out of the all-layers stack
            block_expert = block_expert + layer * n_groups

        def gdot(x_, w_):
            return q40_matmul_pallas_grouped(
                x_, w_.q, w_.d, block_expert, block_r, dtype=dtype,
                interpret=interpret,
            )

        h = (act_fn(gdot(xp, w1q)) * gdot(xp, w3q)).astype(y.dtype)
        per_row = gdot(h, w2q)[dest].reshape(n_tok, k, dim)  # original order
        out = jnp.sum(per_row * wts.reshape(n_tok, k, 1).astype(jnp.float32), axis=1)
    else:
        # parity paths (f32 / q80 / unquantized): the sort-based
        # expert-grouped formulation feeding `lax.ragged_dot`
        order = jnp.argsort(e_flat, stable=True)  # row -> (token r//k, slot)
        tok = order // k
        xs = y.reshape(n_tok, dim)[tok]  # [rows, dim] expert-sorted inputs
        w1m = expert_stack_matrix(w1, dtype)  # [E_local, dim, ff]
        w3m = expert_stack_matrix(w3, dtype)
        w2m = expert_stack_matrix(w2, dtype)  # [E_local, ff, dim]
        if ep_axis is None:
            group_sizes = jnp.bincount(e_flat, length=n_local).astype(jnp.int32)
        else:
            ep = jax.lax.psum(1, ep_axis)
            n_experts = n_local * ep
            counts = jnp.bincount(e_flat, length=n_experts)
            e0 = jax.lax.axis_index(ep_axis) * n_local
            ar = jnp.arange(n_experts)
            before = jnp.sum(jnp.where(ar < e0, counts, 0))
            after = jnp.sum(jnp.where(ar >= e0 + n_local, counts, 0))
            local = jax.lax.dynamic_slice(counts, (e0,), (n_local,))
            group_sizes = jnp.concatenate(
                [before[None], local, after[None]]
            ).astype(jnp.int32)

            def pad(w):
                z = jnp.zeros((1,) + w.shape[1:], w.dtype)
                return jnp.concatenate([z, w, z], axis=0)

            w1m, w3m, w2m = pad(w1m), pad(w3m), pad(w2m)

        precision = jax.lax.Precision.HIGHEST if dtype == jnp.float32 else None

        def rdot(x_, w_):
            return jax.lax.ragged_dot(
                x_.astype(dtype), w_, group_sizes,
                precision=precision, preferred_element_type=jnp.float32,
            )

        xq = quantize_q80_activations(xs) if q80 else xs
        h = (act_fn(rdot(xq, w1m)) * rdot(xq, w3m)).astype(y.dtype)
        hq = quantize_q80_activations(h) if q80 else h
        out_rows = rdot(hq, w2m)  # [rows, dim] f32
        w_flat = wts.reshape(rows)[order].astype(jnp.float32)
        out = jnp.zeros((n_tok, dim), jnp.float32).at[tok].add(
            out_rows * w_flat[:, None]
        )
    if ep_axis is not None:
        out = jax.lax.psum(out, ep_axis)
    return out.reshape(b, t, dim).astype(y.dtype)
