"""MoE router (Qwen3-MoE style).

Semantics match the reference graph exactly (reference: src/llm.cpp:440-514 +
moeGateForward_F32_F32, src/nn/nn-cpu-ops.cpp:1462-1492):

    probs  = softmax(x @ gate.T)            # full softmax over all experts
    topk   = top-k of probs
    weight = probs[topk] / sum(probs[topk])  # normTopk=1 renormalization

The reference then runs each active expert's SwiGLU through matmul kernels
that index a stacked weight tensor by expert id
(reference: src/nn/nn-cpu-ops.cpp:1166-1192). On TPU the equivalent is a
gather-free einsum over one-hot combine weights (small models / tiny batch)
or a sort-based ragged dispatch; models/transformer.py uses the dense
einsum formulation, which XLA turns into gathered matmuls.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def moe_router(
    x: jnp.ndarray, gate: jnp.ndarray, n_active: int, norm_topk: bool = True
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Select experts for each token.

    x: [..., dim]; gate: [n_experts, dim] f32.
    Returns (indices [..., n_active] int32, weights [..., n_active] f32).
    """
    logits = jnp.einsum(
        "...d,ed->...e",
        x.astype(jnp.float32),
        gate.astype(jnp.float32),
        precision=jax.lax.Precision.HIGHEST,
    )
    probs = jax.nn.softmax(logits, axis=-1)
    top_p, top_i = jax.lax.top_k(probs, n_active)
    if norm_topk:
        top_p = top_p / jnp.sum(top_p, axis=-1, keepdims=True)
    return top_i.astype(jnp.int32), top_p
