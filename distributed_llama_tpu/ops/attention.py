"""Grouped-query attention against a full KV cache.

Replaces the reference's per-head scalar loop (reference: multiheadAtt_F32,
src/nn/nn-cpu-ops.cpp:753-788): score = q.k/sqrt(headDim) over positions
0..pos, softmax, weighted V sum, with GQA via kvMul = nHeads/nKvHeads.

TPU-first differences from the reference:
* whole-cache batched einsum instead of per-position dot products — the
  score/softmax/value chain is three fused XLA ops that tile onto the MXU;
* causal masking with a static-shape cache (positions > pos are masked with
  -inf rather than loop-bounded), keeping shapes static under jit;
* f32 softmax accumulation regardless of compute dtype.

Long-context path: for sequence-parallel execution the cache's seq axis is
sharded over the mesh's `sp` axis and this same function runs under
shard_map with a psum-based online-softmax combine (parallel/sequence.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


NEG_INF = float(jnp.finfo(jnp.float32).min)


def gqa_attention(
    q: jnp.ndarray,
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    positions: jnp.ndarray,
    scale: float | None = None,
) -> jnp.ndarray:
    """Causal GQA attention over the (padded) cache.

    q: [batch, q_len, n_heads, head_dim]
    k_cache, v_cache: [batch, cache_len, n_kv_heads, head_dim]
    positions: [batch, q_len] int32 absolute position of each query token;
        cache slot t is visible to a query at position p iff t <= p.
    Returns [batch, q_len, n_heads, head_dim] in q.dtype.
    """
    b, q_len, n_heads, head_dim = q.shape
    cache_len = k_cache.shape[1]
    n_kv_heads = k_cache.shape[2]
    kv_mul = n_heads // n_kv_heads
    if scale is None:
        scale = 1.0 / (head_dim ** 0.5)

    qg = q.reshape(b, q_len, n_kv_heads, kv_mul, head_dim)
    # scores: [b, n_kv_heads, kv_mul, q_len, cache_len]
    scores = jnp.einsum(
        "bqhgd,bthd->bhgqt",
        qg,
        k_cache,
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    scores = scores.astype(jnp.float32) * scale

    t_idx = jnp.arange(cache_len, dtype=jnp.int32)
    mask = t_idx[None, None, :] <= positions[:, :, None]  # [b, q_len, cache_len]
    scores = jnp.where(mask[:, None, None, :, :], scores, NEG_INF)

    probs = jax.nn.softmax(scores, axis=-1)
    # probs stay f32 into the weighted-V sum (f32 accumulation even over a
    # bf16 cache), matching the reference's f32 attention path
    out = jnp.einsum(
        "bhgqt,bthd->bqhgd",
        probs,
        v_cache,
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    return out.reshape(b, q_len, n_heads, head_dim).astype(q.dtype)
