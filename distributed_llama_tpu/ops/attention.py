"""Grouped-query attention against a full KV cache.

Replaces the reference's per-head scalar loop (reference: multiheadAtt_F32,
src/nn/nn-cpu-ops.cpp:753-788): score = q.k/sqrt(headDim) over positions
0..pos, softmax, weighted V sum, with GQA via kvMul = nHeads/nKvHeads.

TPU-first differences from the reference:
* whole-cache batched einsum instead of per-position dot products — the
  score/softmax/value chain is three fused XLA ops that tile onto the MXU;
* causal masking with a static-shape cache (positions > pos are masked with
  -inf rather than loop-bounded), keeping shapes static under jit;
* f32 softmax accumulation regardless of compute dtype.

Long-context path: for sequence-parallel execution the cache's seq axis is
sharded over the mesh's `sp` axis and this same function runs under
shard_map with a psum-based online-softmax combine (parallel/sequence.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


NEG_INF = float(jnp.finfo(jnp.float32).min)


def gqa_attention_sp(
    q: jnp.ndarray,
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    positions: jnp.ndarray,
    shard_offset: jnp.ndarray,
    axis_name: str = "sp",
    scale: float | None = None,
) -> jnp.ndarray:
    """Sequence-parallel GQA attention (long-context path).

    Runs under shard_map with the cache's seq axis sharded over `axis_name`:
    each shard computes unnormalized attention over its local cache slice
    with online-softmax statistics (local max m, exp-sum s, weighted-V o),
    then the shards combine exactly via

        M = pmax(m);  out = psum(o * e^(m-M)) / psum(s * e^(m-M))

    — three tiny collectives of [b, heads, t(, head_dim)] partials per layer
    instead of moving any KV. This is the all-to-all-free alternative to ring
    attention; it has no reference analogue (the reference caps context
    instead — SURVEY.md §5 "Long-context: absent").

    q: [b, t, n_heads, head_dim]; k/v_cache: [b, local_seq, n_kv, head_dim];
    positions: [b, t] GLOBAL positions; shard_offset: scalar — global index
    of this shard's cache row 0.
    """
    b, t, n_heads, head_dim = q.shape
    local_seq = k_cache.shape[1]
    n_kv_heads = k_cache.shape[2]
    kv_mul = n_heads // n_kv_heads
    if scale is None:
        scale = 1.0 / (head_dim ** 0.5)

    qg = q.reshape(b, t, n_kv_heads, kv_mul, head_dim)
    scores = jnp.einsum(
        "bqhgd,bthd->bhgqt",
        qg,
        k_cache,
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    ).astype(jnp.float32) * scale

    t_global = shard_offset + jnp.arange(local_seq, dtype=jnp.int32)
    mask = t_global[None, None, :] <= positions[:, :, None]  # [b, t, local_seq]
    scores = jnp.where(mask[:, None, None, :, :], scores, NEG_INF)

    m = jnp.max(scores, axis=-1)  # [b, h, g, t]
    # a shard whose slice is entirely masked contributes nothing: clamp m so
    # exp() stays finite, and its s/o terms are exactly 0
    m_safe = jnp.maximum(m, NEG_INF / 2)
    e = jnp.exp(scores - m_safe[..., None])
    e = jnp.where(mask[:, None, None, :, :], e, 0.0)
    s = jnp.sum(e, axis=-1)  # [b, h, g, t]
    o = jnp.einsum(
        "bhgqt,bthd->bhgqd",
        e,
        v_cache.astype(jnp.float32),
        precision=jax.lax.Precision.HIGHEST,
    )  # [b, h, g, t, d]

    m_max = jax.lax.pmax(m_safe, axis_name)
    corr = jnp.exp(m_safe - m_max)
    o_sum = jax.lax.psum(o * corr[..., None], axis_name)
    s_sum = jax.lax.psum(s * corr, axis_name)
    out = o_sum / jnp.maximum(s_sum, 1e-30)[..., None]
    # [b, h, g, t, d] -> [b, t, h*g, d]
    out = jnp.transpose(out, (0, 3, 1, 2, 4)).reshape(b, t, n_heads, head_dim)
    return out.astype(q.dtype)


def scatter_cache_update_sp(
    cache: jnp.ndarray,  # [b, local_seq, n_kv, head_dim] — this shard's
    # slice; with `layer` given, the full [L, b, local_seq, n_kv, head_dim]
    # stack (the in-place carried-cache threading, models/transformer.py)
    new: jnp.ndarray,  # [b, t, n_kv, head_dim]
    positions: jnp.ndarray,  # [b, t] GLOBAL positions of the new rows
    shard_offset: jnp.ndarray,
    layer=None,  # scalar int32 layer index into the stacked cache
) -> jnp.ndarray:
    """Write new KV rows into a seq-sharded cache slice.

    A token chunk may straddle shard boundaries, so this is a scatter keyed
    on the shard-local row index, with out-of-range rows dropped — each
    shard writes exactly the rows that land in its range and touches nothing
    else. (A round-2 one-hot formulation paid O(local_seq*t) mask work per
    layer per step — on a 16k shard that dwarfed the row writes themselves.)
    """
    seq_axis = 1 if layer is None else 2
    b, local_seq = new.shape[0], cache.shape[seq_axis]
    t = positions.shape[1]
    local_pos = positions - shard_offset  # [b, t]; negative/too-big = foreign
    # remap EVERY foreign row to local_seq + its own column index: negative
    # indices would WRAP (Python semantics) before mode="drop" applies, and
    # the remapped indices must stay pairwise distinct (and distinct from
    # all in-range rows) to honor unique_indices — colliding dropped
    # indices would be formally undefined scatter behavior
    oob = (local_pos < 0) | (local_pos >= local_seq)
    col = jnp.arange(t, dtype=local_pos.dtype)[None, :]
    local_pos = jnp.where(oob, local_seq + col, local_pos)
    b_idx = jnp.arange(b, dtype=jnp.int32)[:, None]
    if layer is None:
        return cache.at[b_idx, local_pos].set(
            new.astype(cache.dtype), mode="drop", unique_indices=True
        )
    return cache.at[layer, b_idx, local_pos].set(
        new.astype(cache.dtype), mode="drop", unique_indices=True
    )


def flash_attention_sp(
    q: jnp.ndarray,  # [b, t, n_heads, head_dim]
    k_local: jnp.ndarray,  # [b, local_kv, n_kv, head_dim] — shard's (bounded) view
    v_local: jnp.ndarray,
    pos_start: jnp.ndarray,  # scalar int32: absolute position of q[:, 0]
    shard_offset: jnp.ndarray,  # scalar int32: global position of local row 0
    axis_name: str = "sp",
    interpret: bool = False,
) -> jnp.ndarray:
    """Sequence-parallel blocked (flash) attention: the shard-local kernel
    emits unnormalized online-softmax partials (o, m, l) over its cache
    slice — fully-masked shards contribute exact zeros — and the shards
    combine with the same three tiny collectives as gqa_attention_sp:

        M = pmax(m);  out = psum(o * e^(m-M)) / psum(l * e^(m-M))

    This is the long-context prefill path under sp: no O(t*S) score tensor
    on any shard, and no KV movement."""
    from .pallas_attention import flash_attention_partial

    o, m, l = flash_attention_partial(
        q, k_local, v_local, pos_start, shard_offset, interpret=interpret
    )
    m_max = jax.lax.pmax(m, axis_name)
    corr = jnp.exp(m - m_max)
    o_sum = jax.lax.psum(o * corr[..., None], axis_name)
    l_sum = jax.lax.psum(l * corr, axis_name)
    out = o_sum / jnp.maximum(l_sum, 1e-30)[..., None]
    return out.astype(q.dtype)


def gqa_attention(
    q: jnp.ndarray,
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    positions: jnp.ndarray,
    scale: float | None = None,
) -> jnp.ndarray:
    """Causal GQA attention over the (padded) cache.

    q: [batch, q_len, n_heads, head_dim]
    k_cache, v_cache: [batch, cache_len, n_kv_heads, head_dim]
    positions: [batch, q_len] int32 absolute position of each query token;
        cache slot t is visible to a query at position p iff t <= p.
    Returns [batch, q_len, n_heads, head_dim] in q.dtype.
    """
    b, q_len, n_heads, head_dim = q.shape
    cache_len = k_cache.shape[1]
    n_kv_heads = k_cache.shape[2]
    kv_mul = n_heads // n_kv_heads
    if scale is None:
        scale = 1.0 / (head_dim ** 0.5)

    qg = q.reshape(b, q_len, n_kv_heads, kv_mul, head_dim)
    # scores: [b, n_kv_heads, kv_mul, q_len, cache_len]
    scores = jnp.einsum(
        "bqhgd,bthd->bhgqt",
        qg,
        k_cache,
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    scores = scores.astype(jnp.float32) * scale

    t_idx = jnp.arange(cache_len, dtype=jnp.int32)
    mask = t_idx[None, None, :] <= positions[:, :, None]  # [b, q_len, cache_len]
    scores = jnp.where(mask[:, None, None, :, :], scores, NEG_INF)

    probs = jax.nn.softmax(scores, axis=-1)
    # probs stay f32 into the weighted-V sum (f32 accumulation even over a
    # bf16 cache), matching the reference's f32 attention path
    out = jnp.einsum(
        "bhgqt,bthd->bqhgd",
        probs,
        v_cache,
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    return out.reshape(b, q_len, n_heads, head_dim).astype(q.dtype)
