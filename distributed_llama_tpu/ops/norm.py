"""RMS normalization.

The reference splits this into two ops — OP_INV_RMS computing
``1/sqrt(mean(x^2) + eps)`` and OP_RMS_NORM applying ``w * (invRms * x)``
(reference: src/nn/nn-cpu-ops.cpp:114-175) — because its executor has no
fusion. Under XLA the two fuse automatically, so this is a single function.

The reduction is always done in f32 regardless of the compute dtype: on TPU
the bf16->f32 upcast is free inside the fused kernel and it keeps parity with
the reference's f32 accumulation.
"""

from __future__ import annotations

import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float) -> jnp.ndarray:
    """``w * x / rms(x)`` along the last axis.

    x: [..., dim]; weight: [dim] (or any shape broadcastable to x after the
    normalization — qwen3's per-head q/k norms pass [head_dim]).
    """
    xf = x.astype(jnp.float32)
    inv_rms = jnp.reciprocal(jnp.sqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps))
    return (weight.astype(jnp.float32) * (xf * inv_rms)).astype(x.dtype)
