"""Symmetric per-(token, head) int8 quantization for the KV cache.

The source engine keeps every resident tensor block-quantized (Q40 weights,
Q80 activations on the wire) because distributed inference is bandwidth
bound; the KV cache is the last bf16-resident tensor on our decode hot
path. This module owns the one quantization scheme both KV layouts use:

* granularity: ONE f32 scale per (token, kv-head) vector of `head_dim`
  elements — i.e. per row of the innermost axis. Per-page/per-block scales
  would be cheaper (one scalar per page) but break under partial-page
  writes: a page is written one token at a time across many decode steps,
  and tokens quantized under an older (smaller) running max would silently
  dequantize wrong once a later token grows the block scale. Per-token
  scales make every write self-contained — exactly the property the
  OOB-drop scatter semantics (runtime/paged_kv.py) rely on. Overhead:
  4 bytes per head_dim int8 bytes (~3% at head_dim=128).
* mapping: symmetric absmax -> [-127, 127]; the scale is clamped away from
  zero so an all-zero vector (freshly allocated pages, parked rows) round
  trips to exact zeros instead of NaN.
* idempotence: re-quantizing a dequantized vector reproduces the same int8
  payload (absmax maps back to +-127 exactly), so requant-on-insert along
  the KV transport path (bf16 wire segments scattered into an int8 pool)
  is lossless after the first quantization.
"""

from __future__ import annotations

import jax.numpy as jnp

#: floor for the per-vector scale: keeps 0-vectors exact and the dequant
#: multiply finite. f32 min normal is ~1.2e-38; 1e-30 is far above denormal
#: territory while being unreachably small for real bf16 activations.
KV_SCALE_FLOOR = 1e-30


def quantize_kv(x: jnp.ndarray):
    """float[..., head_dim] -> (int8[..., head_dim], f32 scale[...]).

    The scale is absmax/127 over the trailing axis, floored at
    KV_SCALE_FLOOR. Round-to-nearest-even (jnp.round) in f32.
    """
    xf = x.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1) / 127.0, KV_SCALE_FLOOR)
    q = jnp.clip(jnp.round(xf / scale[..., None]), -127.0, 127.0)
    return q.astype(jnp.int8), scale


def dequantize_kv(q: jnp.ndarray, scale: jnp.ndarray, dtype=jnp.float32):
    """int8[..., head_dim] + f32 scale[...] -> dtype[..., head_dim]."""
    return (q.astype(jnp.float32) * scale[..., None]).astype(dtype)
