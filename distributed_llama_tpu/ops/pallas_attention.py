"""Blocked (flash) causal GQA attention over the KV cache, in Pallas.

The XLA path (ops/attention.py gqa_attention) materializes the full
[q_len, cache_len] score matrix — O(t*S) activation memory, prohibitive for
long-context prefill (t=512 against a 32k cache is a 2 GB f32 score tensor
per layer at 32 query heads). This kernel never materializes scores: it
tiles the cache into KV blocks and keeps running online-softmax statistics
(row max m, exp-sum l, weighted-V accumulator) in VMEM scratch, the
standard flash decomposition. Fully-masked KV blocks (block start beyond
the last query's position) skip their compute.

The reference has no analogue — it caps context instead (SURVEY.md §5
"Long-context: absent"); this is the framework's beyond-reference axis.

Layout: one grid row per (batch, kv_head); the kv_mul query heads of a KV
head fold into the score-matrix row axis, so GQA costs nothing extra:

    q   [b*kv, t, g, hd]   block [1, BT, g, hd] -> rows BT*g
    k/v [b*kv, S,  hd]     block [1, BS, hd]
    out = softmax(q k^T / sqrt(hd) + causal) v, accumulated over S/BS steps

Grid (b*kv, t/BT, S/BS), KV innermost; the causal structure comes from the
absolute positions: query row r (token index ti*BT + r//g) at position
pos_start + token_index sees cache slot s iff s <= position.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = float(jnp.finfo(jnp.float32).min)

DEFAULT_BLOCK_T = 128
DEFAULT_BLOCK_S = 256


def _kernel(ps_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *, scale, g, n_s):
    si = pl.program_id(2)
    ti = pl.program_id(1)
    pos_start = ps_ref[0]

    _, bt, _, hd = q_ref.shape
    bs = k_ref.shape[1]
    rows = bt * g

    @pl.when(si == 0)
    def _():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # this KV block is visible to this q block iff its first slot is <= the
    # last query's position
    last_pos = pos_start + ti * bt + (bt - 1)
    block_visible = si * bs <= last_pos

    @pl.when(block_visible)
    def _():
        q = q_ref[0].reshape(rows, hd)
        k = k_ref[0]  # [bs, hd]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [rows, bs]

        row_pos = pos_start + ti * bt + jax.lax.broadcasted_iota(
            jnp.int32, (rows, bs), 0
        ) // g
        col_pos = si * bs + jax.lax.broadcasted_iota(jnp.int32, (rows, bs), 1)
        s = jnp.where(col_pos <= row_pos, s, NEG_INF)

        m_prev = m_ref[...][:, :1]  # [rows, 1]
        m_cur = jnp.maximum(jnp.max(s, axis=1, keepdims=True), m_prev)
        # clamp so a fully-masked ROW (padded tail) stays finite
        m_safe = jnp.maximum(m_cur, NEG_INF / 2)
        corr = jnp.exp(m_prev - m_safe)
        p = jnp.exp(s - m_safe)
        p = jnp.where(col_pos <= row_pos, p, 0.0)
        l_ref[...] = l_ref[...] * corr + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [rows, hd]
        acc_ref[...] = acc_ref[...] * corr + pv
        m_ref[...] = jnp.broadcast_to(m_safe, m_ref.shape)

    @pl.when(si == n_s - 1)
    def _():
        l = jnp.maximum(l_ref[...][:, :1], 1e-30)
        o_ref[0] = (acc_ref[...] / l).reshape(bt, g, hd).astype(o_ref.dtype)


def flash_attention_aligned(q, k_cache, t: int) -> bool:
    """Kernel preconditions: prefill-sized q block, lane-aligned cache
    length, uniform head grouping."""
    b, _, n_heads, head_dim = q.shape
    cache_len = k_cache.shape[1]
    n_kv = k_cache.shape[2]
    return (
        t >= 8
        and n_heads % n_kv == 0
        and head_dim % 8 == 0
        and cache_len % 128 == 0
    )


@partial(jax.jit, static_argnames=("scale", "block_t", "block_s", "interpret"))
def flash_attention(
    q: jnp.ndarray,  # [b, t, n_heads, head_dim]
    k_cache: jnp.ndarray,  # [b, S, n_kv, head_dim]
    v_cache: jnp.ndarray,
    pos_start: jnp.ndarray,  # scalar int32: absolute position of q[:, 0]
    scale: float | None = None,
    block_t: int = DEFAULT_BLOCK_T,
    block_s: int = DEFAULT_BLOCK_S,
    interpret: bool = False,
) -> jnp.ndarray:
    """Blocked causal GQA attention; same contract as gqa_attention with
    positions = pos_start + arange(t). Returns [b, t, n_heads, head_dim]."""
    b, t, n_heads, hd = q.shape
    S = k_cache.shape[1]
    n_kv = k_cache.shape[2]
    g = n_heads // n_kv
    if scale is None:
        scale = 1.0 / (hd ** 0.5)

    bt = min(block_t, t)
    while t % bt:
        bt //= 2
    bs = min(block_s, S)
    while S % bs:
        bs //= 2
    n_s = S // bs

    # [b, t, kv, g, hd] -> [b*kv, t, g, hd]; cache [b, S, kv, hd] -> [b*kv, S, hd]
    cdt = k_cache.dtype if k_cache.dtype == jnp.bfloat16 else q.dtype
    q4 = (
        q.reshape(b, t, n_kv, g, hd)
        .transpose(0, 2, 1, 3, 4)
        .reshape(b * n_kv, t, g, hd)
        .astype(cdt)
    )
    k3 = k_cache.transpose(0, 2, 1, 3).reshape(b * n_kv, S, hd)
    v3 = v_cache.transpose(0, 2, 1, 3).reshape(b * n_kv, S, hd)

    grid = (b * n_kv, t // bt, n_s)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bt, g, hd), lambda bk, ti, si, ps: (bk, ti, 0, 0)),
            pl.BlockSpec((1, bs, hd), lambda bk, ti, si, ps: (bk, si, 0)),
            pl.BlockSpec((1, bs, hd), lambda bk, ti, si, ps: (bk, si, 0)),
        ],
        out_specs=pl.BlockSpec((1, bt, g, hd), lambda bk, ti, si, ps: (bk, ti, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((bt * g, 128), jnp.float32),  # running row max
            pltpu.VMEM((bt * g, 128), jnp.float32),  # running exp-sum
            pltpu.VMEM((bt * g, hd), jnp.float32),  # weighted-V accumulator
        ],
    )
    out = pl.pallas_call(
        partial(_kernel, scale=scale, g=g, n_s=n_s),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b * n_kv, t, g, hd), q.dtype),
        interpret=interpret,
    )(jnp.asarray(pos_start, jnp.int32).reshape(1), q4, k3, v3)
    # [b*kv, t, g, hd] -> [b, t, kv*g, hd]
    return (
        out.reshape(b, n_kv, t, g, hd)
        .transpose(0, 2, 1, 3, 4)
        .reshape(b, t, n_heads, hd)
        .astype(q.dtype)
    )
