"""Blocked (flash) causal GQA attention over the KV cache, in Pallas.

The XLA path (ops/attention.py gqa_attention) materializes the full
[q_len, cache_len] score matrix — O(t*S) activation memory, prohibitive for
long-context prefill (t=512 against a 32k cache is a 2 GB f32 score tensor
per layer at 32 query heads). This kernel never materializes scores: it
tiles the cache into KV blocks and keeps running online-softmax statistics
(row max m, exp-sum l, weighted-V accumulator) in VMEM scratch, the
standard flash decomposition. Fully-masked KV blocks (block start beyond
the last query's position) skip their compute.

The reference has no analogue — it caps context instead (SURVEY.md §5
"Long-context: absent"); this is the framework's beyond-reference axis.

Layout: one grid row per (batch, kv_head); the kv_mul query heads of a KV
head fold into the score-matrix row axis, so GQA costs nothing extra:

    q   [b*kv, t, g, hd]   block [1, BT, g, hd] -> rows BT*g
    k/v [b*kv, S,  hd]     block [1, BS, hd]
    out = softmax(q k^T / sqrt(hd) + causal) v, accumulated over S/BS steps

Grid (b*kv, t/BT, S/BS), KV innermost; the causal structure comes from the
absolute positions: query row r (token index ti*BT + r//g) at position
pos_start + token_index sees cache slot s iff s <= position.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = float(jnp.finfo(jnp.float32).min)

# round-3 sweep at t=512 over a 1024-row cache (16-layer chain, differenced):
# bs=256 -> 3.49 ms, bs=512 -> 1.72, bs=1024/bt=512 -> 1.23 — big KV blocks
# amortize the per-block mask/exp/correction VPU work; both chain down for
# smaller t/caches
DEFAULT_BLOCK_T = 512
DEFAULT_BLOCK_S = 1024


def _attend_block(ps_ref, q_ref, k_ref, v_ref, m_ref, l_ref, acc_ref, *, scale, g):
    """One KV block's online-softmax update (shared by the normalizing and
    the partial-stats kernels). ps_ref carries [pos_start, col_offset]:
    col_offset is the GLOBAL position of the cache's local row 0 — nonzero
    when the cache operand is one shard of a sequence-parallel cache."""
    si = pl.program_id(2)
    ti = pl.program_id(1)
    pos_start = ps_ref[0]
    col_offset = ps_ref[1]

    _, bt, _, hd = q_ref.shape
    bs = k_ref.shape[1]
    rows = bt * g

    @pl.when(si == 0)
    def _():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # this KV block is visible to this q block iff its first slot's global
    # position is <= the last query's position
    last_pos = pos_start + ti * bt + (bt - 1)
    block_visible = col_offset + si * bs <= last_pos

    @pl.when(block_visible)
    def _():
        q = q_ref[0].reshape(rows, hd)
        k = k_ref[0]  # [bs, hd]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [rows, bs]

        row_pos = pos_start + ti * bt + jax.lax.broadcasted_iota(
            jnp.int32, (rows, bs), 0
        ) // g
        col_pos = col_offset + si * bs + jax.lax.broadcasted_iota(
            jnp.int32, (rows, bs), 1
        )
        s = jnp.where(col_pos <= row_pos, s, NEG_INF)

        m_prev = m_ref[...][:, :1]  # [rows, 1]
        m_cur = jnp.maximum(jnp.max(s, axis=1, keepdims=True), m_prev)
        # clamp so a fully-masked ROW (padded tail) stays finite
        m_safe = jnp.maximum(m_cur, NEG_INF / 2)
        corr = jnp.exp(m_prev - m_safe)
        p = jnp.exp(s - m_safe)
        p = jnp.where(col_pos <= row_pos, p, 0.0)
        l_ref[...] = l_ref[...] * corr + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [rows, hd]
        acc_ref[...] = acc_ref[...] * corr + pv
        m_ref[...] = jnp.broadcast_to(m_safe, m_ref.shape)


def _kernel(ps_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *, scale, g, n_s):
    _attend_block(ps_ref, q_ref, k_ref, v_ref, m_ref, l_ref, acc_ref, scale=scale, g=g)
    si = pl.program_id(2)
    _, bt, _, hd = q_ref.shape

    @pl.when(si == n_s - 1)
    def _():
        l = jnp.maximum(l_ref[...][:, :1], 1e-30)
        o_ref[0] = (acc_ref[...] / l).reshape(bt, g, hd).astype(o_ref.dtype)


def _kernel_partial(
    ps_ref, q_ref, k_ref, v_ref, o_ref, m_out_ref, l_out_ref, m_ref, l_ref, acc_ref,
    *, scale, g, n_s,
):
    """Like _kernel but emits the UNNORMALIZED accumulator plus the row
    stats (m, l) — the shard-local triple of the sequence-parallel
    online-softmax combine (ops/attention.py flash_attention_sp)."""
    _attend_block(ps_ref, q_ref, k_ref, v_ref, m_ref, l_ref, acc_ref, scale=scale, g=g)
    si = pl.program_id(2)
    _, bt, _, hd = q_ref.shape

    @pl.when(si == n_s - 1)
    def _():
        o_ref[0] = acc_ref[...].reshape(bt, g, hd)
        m_out_ref[0] = m_ref[...][:, :1].reshape(bt, g)
        l_out_ref[0] = l_ref[...][:, :1].reshape(bt, g)


def flash_attention_aligned(q, k_cache, t: int) -> bool:
    """Kernel preconditions: prefill-sized q block, lane-aligned cache
    length, uniform head grouping."""
    b, _, n_heads, head_dim = q.shape
    cache_len = k_cache.shape[1]
    n_kv = k_cache.shape[2]
    return (
        t >= 8
        and n_heads % n_kv == 0
        and head_dim % 8 == 0
        and cache_len % 128 == 0
    )


def _flash_operands(q, k_cache, v_cache, block_t, block_s):
    """Shared shape plumbing: fold kv heads into the batch grid axis and pick
    block sizes. Returns (q4, k3, v3, dims)."""
    b, t, n_heads, hd = q.shape
    S = k_cache.shape[1]
    n_kv = k_cache.shape[2]
    g = n_heads // n_kv

    bt = min(block_t, t)
    while t % bt:
        bt //= 2
    bs = min(block_s, S)
    while S % bs:
        bs //= 2
    n_s = S // bs

    # [b, t, kv, g, hd] -> [b*kv, t, g, hd]; cache [b, S, kv, hd] -> [b*kv, S, hd]
    cdt = k_cache.dtype if k_cache.dtype == jnp.bfloat16 else q.dtype
    q4 = (
        q.reshape(b, t, n_kv, g, hd)
        .transpose(0, 2, 1, 3, 4)
        .reshape(b * n_kv, t, g, hd)
        .astype(cdt)
    )
    k3 = k_cache.transpose(0, 2, 1, 3).reshape(b * n_kv, S, hd)
    v3 = v_cache.transpose(0, 2, 1, 3).reshape(b * n_kv, S, hd)
    return q4, k3, v3, (b, t, n_heads, hd, n_kv, g, bt, bs, n_s)


def _flash_grid_spec(dims, n_extra_outs=0):
    b, t, n_heads, hd, n_kv, g, bt, bs, n_s = dims
    out_specs = [pl.BlockSpec((1, bt, g, hd), lambda bk, ti, si, ps: (bk, ti, 0, 0))]
    out_specs += [
        pl.BlockSpec((1, bt, g), lambda bk, ti, si, ps: (bk, ti, 0))
    ] * n_extra_outs
    return pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b * n_kv, t // bt, n_s),
        in_specs=[
            pl.BlockSpec((1, bt, g, hd), lambda bk, ti, si, ps: (bk, ti, 0, 0)),
            pl.BlockSpec((1, bs, hd), lambda bk, ti, si, ps: (bk, si, 0)),
            pl.BlockSpec((1, bs, hd), lambda bk, ti, si, ps: (bk, si, 0)),
        ],
        out_specs=out_specs if n_extra_outs else out_specs[0],
        scratch_shapes=[
            pltpu.VMEM((bt * g, 128), jnp.float32),  # running row max
            pltpu.VMEM((bt * g, 128), jnp.float32),  # running exp-sum
            pltpu.VMEM((bt * g, hd), jnp.float32),  # weighted-V accumulator
        ],
    )


@partial(jax.jit, static_argnames=("scale", "block_t", "block_s", "interpret"))
def flash_attention(
    q: jnp.ndarray,  # [b, t, n_heads, head_dim]
    k_cache: jnp.ndarray,  # [b, S, n_kv, head_dim]
    v_cache: jnp.ndarray,
    pos_start: jnp.ndarray,  # scalar int32: absolute position of q[:, 0]
    scale: float | None = None,
    block_t: int = DEFAULT_BLOCK_T,
    block_s: int = DEFAULT_BLOCK_S,
    interpret: bool = False,
) -> jnp.ndarray:
    """Blocked causal GQA attention; same contract as gqa_attention with
    positions = pos_start + arange(t). Returns [b, t, n_heads, head_dim]."""
    b, t, n_heads, hd = q.shape
    if scale is None:
        scale = 1.0 / (hd ** 0.5)
    q4, k3, v3, dims = _flash_operands(q, k_cache, v_cache, block_t, block_s)
    _, _, _, _, n_kv, g, bt, bs, n_s = dims
    ps = jnp.stack([jnp.asarray(pos_start, jnp.int32), jnp.int32(0)])
    out = pl.pallas_call(
        partial(_kernel, scale=scale, g=g, n_s=n_s),
        grid_spec=_flash_grid_spec(dims),
        out_shape=jax.ShapeDtypeStruct((b * n_kv, t, g, hd), q.dtype),
        interpret=interpret,
    )(ps, q4, k3, v3)
    # [b*kv, t, g, hd] -> [b, t, kv*g, hd]
    return (
        out.reshape(b, n_kv, t, g, hd)
        .transpose(0, 2, 1, 3, 4)
        .reshape(b, t, n_heads, hd)
        .astype(q.dtype)
    )


# -- fused page-table-aware int8 decode attention ---------------------------
#
# The paged decode arm used to materialize an HLO gather of the row's
# kv_len/ps pages into a [b, n_read*ps, h, d] bf16 view every step — the
# single biggest HBM stream on the decode hot path. This kernel reads the
# pool DIRECTLY: the row's int32 page table rides the scalar-prefetch
# operand, the KV block index map resolves (row, kv-step) -> physical page
# on the scalar core, and the int8 payload dequantizes against its f32
# per-(token, head) scale in VMEM (the same place pallas_q40.py unpacks
# weight nibbles) — so HBM sees int8 + scale bytes only, and the jaxpr
# carries NO page-view gather (profiling.assert_gather_free pins this).
#
# Hardware note: one KV block is one page — (ps, hd) int8 tiles with
# ps=16 under-fill the int8 sublane tile (32); fine in interpret mode
# (CI) and correct on hardware, with a packing follow-up recorded in
# PERF.md before hardware rounds chase peak.


def _paged_kernel(
    m_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref, m_sref, l_sref, acc_ref,
    *, scale, g, ps, n_read, n_kv,
):
    """One page's online-softmax update. m_ref (scalar prefetch) carries
    [layer, pos_base[b], page_table[b*n_read]]; pos_base is each row's
    FIRST query position (per-row — batch decode's unequal rows share the
    program). Clamped-page garbage is causally masked for live rows and
    discarded host-side for parked rows, the XLA paged arm's semantics."""
    si = pl.program_id(2)
    ti = pl.program_id(1)
    bk = pl.program_id(0)

    _, bt, _, hd = q_ref.shape
    rows = bt * g
    pos_base = m_ref[1 + bk // n_kv]

    @pl.when(si == 0)
    def _():
        m_sref[...] = jnp.full_like(m_sref, NEG_INF)
        l_sref[...] = jnp.zeros_like(l_sref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # page si holds positions [si*ps, (si+1)*ps): visible iff its first
    # position is <= the row's last query position
    last_pos = pos_base + ti * bt + (bt - 1)

    @pl.when(si * ps <= last_pos)
    def _():
        q = q_ref[0].reshape(rows, hd).astype(jnp.float32)
        # in-VMEM dequant: int8 payload x f32 per-(token, head) scale
        k = k_ref[0, 0, :, 0, :].astype(jnp.float32) * ks_ref[0, 0, :, 0][:, None]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [rows, ps]

        row_pos = pos_base + ti * bt + jax.lax.broadcasted_iota(
            jnp.int32, (rows, ps), 0
        ) // g
        col_pos = si * ps + jax.lax.broadcasted_iota(jnp.int32, (rows, ps), 1)
        s = jnp.where(col_pos <= row_pos, s, NEG_INF)

        m_prev = m_sref[...][:, :1]
        m_cur = jnp.maximum(jnp.max(s, axis=1, keepdims=True), m_prev)
        m_safe = jnp.maximum(m_cur, NEG_INF / 2)
        corr = jnp.exp(m_prev - m_safe)
        p = jnp.exp(s - m_safe)
        p = jnp.where(col_pos <= row_pos, p, 0.0)
        l_sref[...] = l_sref[...] * corr + jnp.sum(p, axis=1, keepdims=True)
        v = v_ref[0, 0, :, 0, :].astype(jnp.float32) * vs_ref[0, 0, :, 0][:, None]
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        acc_ref[...] = acc_ref[...] * corr + pv
        m_sref[...] = jnp.broadcast_to(m_safe, m_sref.shape)

    @pl.when(si == n_read - 1)
    def _():
        l = jnp.maximum(l_sref[...][:, :1], 1e-30)
        o_ref[0] = (acc_ref[...] / l).reshape(bt, g, hd).astype(o_ref.dtype)


@partial(jax.jit, static_argnames=("n_read", "page_size", "scale", "interpret"))
def paged_flash_attention(
    q: jnp.ndarray,  # [b, t, n_heads, head_dim]
    k_pool: jnp.ndarray,  # [L, n_pages, ps, n_kv, head_dim] int8
    v_pool: jnp.ndarray,
    k_scale: jnp.ndarray,  # [L, n_pages, ps, n_kv] f32
    v_scale: jnp.ndarray,
    layer_idx: jnp.ndarray,  # traced scalar int32 — one program for all layers
    pos_base: jnp.ndarray,  # [b] int32: each row's first query position
    page_table: jnp.ndarray,  # [b, >=n_read] int32 (-1 = unmapped)
    n_read: int,  # static page count per row (kv_len / page_size bucket)
    page_size: int,
    scale: float | None = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Fused page-table-aware int8 GQA decode attention over the pool.

    Reads the first `n_read` table entries per row THROUGH the scalar
    prefetch operand — no materialized page gather, no dequantized KV view;
    per-row positions make solo decode, batch decode, and the speculative
    verify block all one kernel shape family. Returns [b, t, h, hd] in
    q.dtype."""
    b, t, n_heads, hd = q.shape
    n_kv = k_pool.shape[3]
    ps = page_size
    g = n_heads // n_kv
    if scale is None:
        scale = 1.0 / (hd ** 0.5)
    bt = t  # decode-sized q blocks: one t block per grid row

    q4 = (
        q.reshape(b, t, n_kv, g, hd)
        .transpose(0, 2, 1, 3, 4)
        .reshape(b * n_kv, t, g, hd)
    )
    meta = jnp.concatenate(
        [
            jnp.asarray(layer_idx, jnp.int32).reshape(1),
            jnp.asarray(pos_base, jnp.int32).reshape(b),
            jnp.maximum(
                jax.lax.slice_in_dim(page_table, 0, n_read, axis=1), 0
            ).astype(jnp.int32).reshape(b * n_read),
        ]
    )

    def kv_map(bk, ti, si, m):
        return (m[0], m[1 + b + (bk // n_kv) * n_read + si], 0, bk % n_kv, 0)

    def scale_map(bk, ti, si, m):
        return (m[0], m[1 + b + (bk // n_kv) * n_read + si], 0, bk % n_kv)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b * n_kv, t // bt, n_read),
        in_specs=[
            pl.BlockSpec((1, bt, g, hd), lambda bk, ti, si, m: (bk, ti, 0, 0)),
            pl.BlockSpec((1, 1, ps, 1, hd), kv_map),
            pl.BlockSpec((1, 1, ps, 1, hd), kv_map),
            pl.BlockSpec((1, 1, ps, 1), scale_map),
            pl.BlockSpec((1, 1, ps, 1), scale_map),
        ],
        out_specs=pl.BlockSpec(
            (1, bt, g, hd), lambda bk, ti, si, m: (bk, ti, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((bt * g, 128), jnp.float32),  # running row max
            pltpu.VMEM((bt * g, 128), jnp.float32),  # running exp-sum
            pltpu.VMEM((bt * g, hd), jnp.float32),  # weighted-V accumulator
        ],
    )
    out = pl.pallas_call(
        partial(
            _paged_kernel, scale=scale, g=g, ps=ps, n_read=n_read, n_kv=n_kv
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b * n_kv, t, g, hd), q.dtype),
        interpret=interpret,
    )(meta, q4, k_pool, v_pool, k_scale, v_scale)
    return (
        out.reshape(b, n_kv, t, g, hd)
        .transpose(0, 2, 1, 3, 4)
        .reshape(b, t, n_heads, hd)
    )


@partial(jax.jit, static_argnames=("scale", "block_t", "block_s", "interpret"))
def flash_attention_partial(
    q: jnp.ndarray,  # [b, t, n_heads, head_dim]
    k_cache: jnp.ndarray,  # [b, S_local, n_kv, head_dim] — ONE shard's slice
    v_cache: jnp.ndarray,
    pos_start: jnp.ndarray,  # scalar int32: absolute position of q[:, 0]
    col_offset: jnp.ndarray,  # scalar int32: global position of cache row 0
    scale: float | None = None,
    block_t: int = DEFAULT_BLOCK_T,
    block_s: int = DEFAULT_BLOCK_S,
    interpret: bool = False,
):
    """Shard-local flash attention returning the UNNORMALIZED online-softmax
    triple (o [b,t,h,hd] f32, m [b,t,h] f32, l [b,t,h] f32) over this shard's
    cache rows; exact cross-shard combine happens in
    ops/attention.flash_attention_sp. A fully-masked shard returns
    (0, NEG_INF/2, 0) rows, contributing nothing to the combine."""
    b, t, n_heads, hd = q.shape
    if scale is None:
        scale = 1.0 / (hd ** 0.5)
    q4, k3, v3, dims = _flash_operands(q, k_cache, v_cache, block_t, block_s)
    _, _, _, _, n_kv, g, bt, bs, n_s = dims
    ps = jnp.stack(
        [jnp.asarray(pos_start, jnp.int32), jnp.asarray(col_offset, jnp.int32)]
    )
    o, m, l = pl.pallas_call(
        partial(_kernel_partial, scale=scale, g=g, n_s=n_s),
        grid_spec=_flash_grid_spec(dims, n_extra_outs=2),
        out_shape=[
            jax.ShapeDtypeStruct((b * n_kv, t, g, hd), jnp.float32),
            jax.ShapeDtypeStruct((b * n_kv, t, g), jnp.float32),
            jax.ShapeDtypeStruct((b * n_kv, t, g), jnp.float32),
        ],
        interpret=interpret,
    )(ps, q4, k3, v3)

    def unfold(x):  # [b*kv, t, g, ...] -> [b, t, kv*g, ...]
        lead = (b, n_kv, t, g) + x.shape[3:]
        perm = (0, 2, 1, 3) + tuple(range(4, x.ndim + 1))
        return x.reshape(lead).transpose(perm).reshape((b, t, n_heads) + x.shape[3:])

    return unfold(o), unfold(m), unfold(l)
