"""Fused Q40 dequant-matmul Pallas TPU kernels — true 4-bit residency.

TPU-native replacement for the reference's hot loop, `matmul_Q80_Q40_F32`
(reference: src/nn/nn-cpu-ops.cpp:231-449, NEON/AVX-512/AVX2 paths): instead
of SIMD nibble tricks over CPU cache lines, the weight streams from HBM
NIBBLE-PACKED (0.5 bytes/weight — the packed T layout, ops/quant.py; the
reference's own 4.5 bits/weight Q40 trait, nn-quants.hpp:64-72) and unpacks
in VMEM with two i32 mask ops + a pltpu.bitcast to int8 (~0.4 VPU
ops/weight). HBM traffic is half the round-4 int8 layout's and 4-8x less
than the dequant-materialize XLA fallback pays.

The unpack (the FEATURE-SPLIT codec, ops/quant.py docstring): a packed
block arrives as [TILE_KNB*4, TILE_N] int32; `w & 0x0F0F0F0F` yields the
bytes of features 0..15 of each 32-block (+8, unsigned), `(w >> 4) & ...`
features 16..31, and pltpu.bitcast reinterprets each masked word as 4 int8
sublanes (probed natural little-endian order) — no per-element VPU work.
  * decode (row counts <= 8): the int8 results feed the MXU directly via
    two block-diagonal dots (one per nibble plane); the +8 offset folds
    into a per-block correction 8*sum(x8_block) computed in the prologue.
    Bit-exact vs the reference's Q80xQ40 integer dot.
  * prefill (large row counts): the planes concat to [TILE_KNB, 32, TILE_N]
    and dequantize to bf16 ((u - 8) * scale) — the per-element convert
    amortizes over the activation rows, MXU work dominates.
Probes and tile sweeps: scripts/probe_int4*.py (also documents the dead
ends: native s4 arrays cannot cross jit boundaries on this platform, int8
bitwise ops and bitwidth-changing jax.lax.bitcasts don't legalize in
Mosaic, and plane-extraction unpacks are VPU-bound).

Tiling:
  grid = (out/TILE_N, nb/TILE_KNB), k innermost (output tile revisited,
  f32 accumulation in place);
  packed block [TILE_KNB*4, TILE_N] int32 — full 8-sublane i32 vregs (a
  3D [TILE_KNB, 4, TILE_N] block leaves half of every vreg empty and
  measures ~2x slower);
  dt block [TILE_KNB, TILE_N] broadcasts over the unpacked sublane axis.

Scale plane: the .m file's per-block scales are f16; the T layout carries
them verbatim (2 bytes/block — half the round-2 f32 plane's HBM traffic and
footprint, and bit-exact). Mosaic cannot load float16 on this platform
(remote-compile 500 at every tile shape — scripts/probe_f16_scales.py), so
the wrappers bitcast the plane to int16 and the kernels convert bits -> f32
on the VPU (`_scale_f32`): shifts + masks + one bitcast, subnormal-aware,
measured exact. Scales are 1/32nd of the elements, so the conversion cost is
noise next to the dequant work it replaces. f32 planes (hand-built test
tensors) still work everywhere.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# renamed upstream from TPUCompilerParams; alias locally (don't mutate jax)
_CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams

from ..formats.quants import Q_BLOCK

LANE = 128


def _i8_compiler_params():
    """Experiment knob (DLT_I8_DIMSEM=1): declare the i8 kernels' grid as
    (parallel out, arbitrary k). PROCESS-START-ONLY: the env var is read at
    trace time, so flipping it mid-process is ignored by the jit cache —
    A/B it with one subprocess per arm (as scripts did). Measured NEUTRAL
    on the 1B full decode step (3 interleaved subprocess reps: 1.819-1.831
    plain vs 1.823-1.830 dimsem ms); kept off by default."""
    import os

    if os.environ.get("DLT_I8_DIMSEM"):
        return {
            "compiler_params": _CompilerParams(
                dimension_semantics=(pltpu.PARALLEL, pltpu.ARBITRARY)
            )
        }
    return {}


DEFAULT_TILE_N = 256
DEFAULT_TILE_KNB = 64  # 64 blocks = 2048 input features per k step


def q40_matmul_aligned(x, w) -> bool:
    """Kernel supports: an unstacked (2D packed) weight with lane-aligned
    out_features and a matching x. (Unaligned weights fall back to the XLA
    dequant path; expert stacks never reach quant_matmul — they go through
    models.transformer._expert_matmul.)"""
    return (
        w.q.ndim == 2
        and w.out_features % LANE == 0
        and x.shape[-1] == w.in_features
    )


def q40_stacked_aligned(in_features: int, out_features: int) -> bool:
    """THE alignment contract of the stacked (scalar-prefetch) kernels, for
    every gate that selects them: lane-aligned out_features AND nb % 8 == 0.
    The stacked kernels flatten [N, nb, ...] -> [N*nb, ...], so the scale
    block's leading tile can no longer be 'equal to the whole array dim' and
    must be 8-sublane divisible — REAL Mosaic lowering enforces this;
    interpret mode does NOT, so only this predicate protects real TPUs."""
    return (
        out_features % LANE == 0
        and (in_features // Q_BLOCK) % 8 == 0
    )


def _scale_f32(dt: jnp.ndarray) -> jnp.ndarray:
    """Per-block scale block -> f32, inside a kernel.

    int16 = raw f16 bits (the 2-byte scale plane; see module docstring):
    manual f16->f32 with integer ops + bitcast. Normal/zero/subnormal are
    exact; inf/NaN don't occur in scale planes. f32 passes through."""
    if dt.dtype != jnp.int16:
        return dt.astype(jnp.float32)
    h = dt.astype(jnp.int32) & 0xFFFF
    sign = jnp.left_shift(jnp.bitwise_and(h, 0x8000), 16)
    exp = jnp.bitwise_and(jnp.right_shift(h, 10), 0x1F)
    mant = jnp.bitwise_and(h, 0x3FF)
    normal = jax.lax.bitcast_convert_type(
        sign | jnp.left_shift(exp + 112, 23) | jnp.left_shift(mant, 13),
        jnp.float32,
    )
    signf = jnp.where(sign != 0, -1.0, 1.0).astype(jnp.float32)
    subnormal = mant.astype(jnp.float32) * jnp.float32(2.0**-24) * signf
    return jnp.where(exp == 0, subnormal, normal)


def _dt_operand(dt: jnp.ndarray) -> jnp.ndarray:
    """Scale plane -> what the kernel can load: f16 bitcasts to int16 at the
    pallas_call boundary (an XLA no-op); f32 passes through. Interpret mode
    takes the same bitcast path, so CPU tests exercise `_scale_f32`."""
    if dt.dtype == jnp.float16:
        return jax.lax.bitcast_convert_type(dt, jnp.int16)
    return dt


HGRP = Q_BLOCK // 2  # features per nibble plane (ops/quant.py codec)
NIBBLE_MASK = 0x0F0F0F0F


def _fs_lo_hi(w32: jnp.ndarray):
    """Packed block [knb*4, tn] int32 -> (lo, hi) int8 [knb*16, tn]: the
    unsigned (+8) values of features 0..15 / 16..31 of each 32-block. Two
    i32 vector ops + a shift, then pltpu.bitcast reinterprets each masked
    word's 4 bytes as 4 int8 sublanes (probed little-endian — the codec
    packs to match, so this is layout-free)."""
    m = jnp.int32(NIBBLE_MASK)
    lo = pltpu.bitcast(jnp.bitwise_and(w32, m), jnp.int8)
    hi = pltpu.bitcast(
        jnp.bitwise_and(jax.lax.shift_right_logical(w32, jnp.int32(4)), m), jnp.int8
    )
    return lo, hi


def _dequant_dot_accum(k, x_ref, qp_ref, dt_ref, out_ref):
    """Shared body of the bf16-dequant (prefill / multi-row) kernels:
    unpack + dequantize this k-step's packed weight tile, matmul against the
    x tile, accumulate into out over the k grid axis. Single owner of the
    dequant rounding choice — the unstacked, stacked, and grouped kernels
    differ only in how their BlockSpec index_maps pick the tile (plain /
    scalar-prefetched layer / per-row-block expert), never in the math."""
    knb, tn = dt_ref.shape
    lo, hi = _fs_lo_hi(qp_ref[...])
    u = jnp.concatenate(
        [lo.reshape(knb, HGRP, tn), hi.reshape(knb, HGRP, tn)], axis=1
    )  # [knb, 32, tn] unsigned (+8) values, natural feature order
    dtf = _scale_f32(dt_ref[...])
    if x_ref.dtype == jnp.bfloat16:
        # dequant in bf16: (u - 8) is exact in bf16 (small integers); the
        # scale multiply rounds once, same class as the pre-pack kernels
        w = (u.astype(jnp.bfloat16) - jnp.bfloat16(8)) * dtf[:, None, :].astype(
            jnp.bfloat16
        )
    else:
        # f32 multiply keeps full f16-scale precision, then cast once
        w = ((u.astype(jnp.float32) - 8.0) * dtf[:, None, :]).astype(x_ref.dtype)
    w = w.reshape(knb * Q_BLOCK, tn)
    acc = jnp.dot(x_ref[...], w, preferred_element_type=jnp.float32)

    @pl.when(k == 0)
    def _():
        out_ref[...] = acc

    @pl.when(k != 0)
    def _():
        out_ref[...] += acc


def _bf16_tile_cap(b: int, tile_n: int, tile_knb: int, nb: int):
    """Shrink the bf16-dequant kernels' tiles so scoped VMEM stays under the
    ~16 MB stack limit at large row counts (batched prefill pushes
    b = batch x chunk rows; a real 4x256-row run OOMed at the w2 shape).
    Budget model: x block (double-buffered bf16) + dequant temp + int8
    weight block (double-buffered) + out/acc f32. The budget model
    under-counts Mosaic's internal temporaries by ~4 MB (a 1024-row w2
    config modeling 12 MB measured 16.24 MB scoped), so the cap is 10 MB.
    k-depth shrinks first (less valuable than lane width)."""

    def need(tn, knb):
        # x (bf16, dbl-buffered) + dequant w (2B) + unpack temps (lo/hi/cat
        # int8 ~ 2x the unpacked bytes) + packed i32 block (dbl-buffered,
        # 0.5B/weight) + out/acc f32
        return (
            2 * b * knb * Q_BLOCK * 2
            + knb * Q_BLOCK * tn * 2
            + 2 * knb * Q_BLOCK * tn
            + 2 * knb * HGRP * tn
            + 2 * b * tn * 4
        )

    cap = 10 * 1024 * 1024
    while need(tile_n, tile_knb) > cap and tile_knb >= 16:
        nxt = tile_knb // 2
        if nb % nxt:
            break  # a non-divisor would DROP k blocks from the grid —
            # silently wrong results, not a perf choice; shrink lanes instead
        tile_knb = nxt
    while need(tile_n, tile_knb) > cap and tile_n > 128:
        tile_n //= 2
    # Mosaic sublane rule: a multi-k-step scale block needs tile_knb % 8 == 0
    # (only whole-dim blocks are exempt). Do NOT reset to nb here — that
    # would discard the cap just computed (e.g. nb=24 halves to 12, then a
    # reset back to 24 re-OOMs). 12 -> 8 SHRINKS the footprint (budget still
    # holds); ragged nb falls back to one whole-dim k step with tile_n
    # shrunk to fit.
    if tile_knb != nb and tile_knb % 8:
        if nb % 8 == 0:
            tile_knb = 8
        else:
            tile_knb = nb  # ragged nb: whole-dim k step is always legal
            while need(tile_n, tile_knb) > cap and tile_n > 128:
                tile_n //= 2
    return tile_n, tile_knb


def _kernel(x_ref, qt_ref, dt_ref, out_ref):
    _dequant_dot_accum(pl.program_id(1), x_ref, qt_ref, dt_ref, out_ref)


def _kernel_stacked(l_ref, x_ref, qt_ref, dt_ref, out_ref):
    # identical math to _kernel — the layer offset was folded into the block
    # index by the scalar-prefetch index_map (the stacked array arrives
    # flattened to 3D so the blocks match the unstacked kernel exactly)
    _dequant_dot_accum(pl.program_id(1), x_ref, qt_ref, dt_ref, out_ref)


@partial(jax.jit, static_argnames=("dtype", "interpret"))
def q40_matmul_pallas_stacked(
    x: jnp.ndarray,  # [..., in_features]
    qt: jnp.ndarray,  # [L, nb*4, out] int32 packed — all layers, in HBM
    dt: jnp.ndarray,  # [L, nb, out]
    layer: jnp.ndarray,  # scalar int32 — which layer's weight to use
    dtype=jnp.bfloat16,
    interpret: bool = False,
) -> jnp.ndarray:
    """x @ w[layer] for a stacked packed Q40 weight, without materializing
    the layer's slice.

    The layer index rides in as a scalar-prefetch argument and offsets the
    BlockSpec index_maps, so the kernel DMAs only layer `layer`'s tiles out
    of the full stacked array. This is what lets the transformer `lax.scan`
    over layers (one compiled body) while keeping weight traffic at ~0.5
    bytes/weight: scanning over sliced weights instead would force XLA to
    materialize a full copy of every layer's weights each step, because a
    dynamic-slice cannot fuse into an opaque pallas_call (the copies dominated
    the round-1 decode profile).
    """
    L, rows4, out = qt.shape
    nb = rows4 // 4
    in_features = nb * Q_BLOCK
    lead = x.shape[:-1]
    b = 1
    for s in lead:
        b *= s
    x2 = x.reshape(b, in_features).astype(dtype)
    dt = _dt_operand(dt)

    tile_n = min(DEFAULT_TILE_N, out)
    while out % tile_n:
        tile_n //= 2
    tile_knb = min(DEFAULT_TILE_KNB, nb)
    while nb % tile_knb:
        tile_knb //= 2
    tile_n, tile_knb = _bf16_tile_cap(b, tile_n, tile_knb, nb)
    # callers gate on q40_stacked_aligned (nb % 8 == 0), which guarantees the
    # chain above never lands below 8 — the sublane rule Mosaic enforces on
    # real TPUs for blocks that don't span the whole (flattened) leading dim

    # flatten the layer axis into the block-row axis (a free bitcast — the
    # memory is contiguous) so the kernel sees the same 2D blocks as the
    # unstacked kernel; the layer offset folds into the block index
    k_steps = nb // tile_knb
    qt2 = qt.reshape(L * rows4, out)
    dt3 = dt.reshape(L * nb, out)

    grid = (out // tile_n, k_steps)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[
            pl.BlockSpec((b, tile_knb * Q_BLOCK), lambda j, k, l: (0, k)),
            pl.BlockSpec(
                (tile_knb * 4, tile_n), lambda j, k, l: (l[0] * k_steps + k, j)
            ),
            pl.BlockSpec((tile_knb, tile_n), lambda j, k, l: (l[0] * k_steps + k, j)),
        ],
        out_specs=pl.BlockSpec((b, tile_n), lambda j, k, l: (0, j)),
    )
    out2 = pl.pallas_call(
        _kernel_stacked,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, out), jnp.float32),
        interpret=interpret,
    )(jnp.asarray(layer, jnp.int32).reshape(1), x2, qt2, dt3)
    return out2.reshape(*lead, out)


def _kernel_i8(x8_ref, xs_ref, mask_ref, qt_ref, dt_ref, out_ref):
    """int8xint8 MXU path (decode-sized activation rows): the weight's int8
    values hit the MXU directly — no per-element VPU dequant, the structural
    bottleneck of the bf16 kernel at square shapes (measured 17x there).

    Per-block partial dots come from ONE 2D int8 matmul: the lhs stacks, for
    every activation row r, the block-diagonal expansion of that row (lhs
    row r*knb + b = row r masked to block b's 32 columns), so product row
    r*knb + b is exactly x8[r]_block_b . q_block_b. The per-block scales
    (activation q80 scale x weight Q40 scale) then combine on the VPU at
    O(R*knb*tn) — 1/32nd of the dequant's element count. Activation
    numerics are the reference's default `--buffer-float-type q80`
    (src/llm.cpp:221-255). R is small (<= 8, gated in quant_matmul) — the
    lhs expansion is R*knb rows; larger batches amortize dequant over rows
    and use the bf16 kernel instead.
    """
    k = pl.program_id(1)
    knb, tn = dt_ref.shape
    R = x8_ref.shape[0]
    x8 = x8_ref[...]  # [R, knb*32] int8
    # select, not multiply: muli on i8 vectors doesn't legalize in Mosaic.
    # Multi-row stays strictly 2D: per-row broadcast-select then concat on
    # the sublane axis — 3D int8 broadcasts/reshapes ([R,1,knb*32] etc.)
    # fail Mosaic's shape-cast lowering on this platform (found by
    # scripts/compile_check_tpu.py; interpret mode accepted them).
    mask = mask_ref[...]  # [knb, knb*32]
    if R == 1:
        blockdiag = jnp.where(
            mask != 0, jnp.broadcast_to(x8, mask.shape), jnp.int8(0)
        )  # [knb, knb*32]
    else:
        blockdiag = jnp.concatenate(
            [
                jnp.where(
                    mask != 0,
                    jnp.broadcast_to(x8[r : r + 1], mask.shape),
                    jnp.int8(0),
                )
                for r in range(R)
            ],
            axis=0,
        )  # [R*knb, knb*32]
    qt2 = qt_ref[...].reshape(knb * Q_BLOCK, tn)
    partials = jax.lax.dot_general(
        blockdiag, qt2, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # [R*knb, tn]; row r*knb+b = row r's block-b integer dot
    dtf = _scale_f32(dt_ref[...])  # [knb, tn]
    # per-row scale combine, unrolled over the (small, static) R; row r's
    # activation scales sit at xs column r*128 (see _quantize_rows_q80)
    rows = []
    for r in range(R):
        pr = partials[r * knb : (r + 1) * knb]  # [knb, tn]
        scale = xs_ref[...][:, r * 128 : r * 128 + 1] * dtf  # [knb, tn]
        rows.append(jnp.sum(pr.astype(jnp.float32) * scale, axis=0)[None, :])
    acc = rows[0] if R == 1 else jnp.concatenate(rows, axis=0)  # [R, tn]

    @pl.when(k == 0)
    def _():
        out_ref[...] = acc

    @pl.when(k != 0)
    def _():
        out_ref[...] += acc


def _kernel_stacked_i8(l_ref, x8_ref, xs_ref, mask_ref, qt_ref, dt_ref, out_ref):
    # identical math to _kernel_i8; the layer offset was folded into the
    # weight block index by the scalar-prefetch index_map
    _kernel_i8(x8_ref, xs_ref, mask_ref, qt_ref, dt_ref, out_ref)


def _quantize_rows_q80(x2: jnp.ndarray, nb: int):
    """[R, in] f32-able rows -> (x8 [R, in] int8, xs [nb, R*128] f32).
    Per-32-block symmetric int8 with the Q80 codec's numerics (same contract
    as ops/quant.py quantize_q80_activations and the reference's
    quantizeF32toQ80): int8 values are computed against the unrounded f32
    scale, dequantization uses the f16-ROUNDED scale stored in the block.
    Row r's per-block scales live at xs columns [r*128, (r+1)*128) — a
    lane-aligned layout the kernel slices per row."""
    R = x2.shape[0]
    xb = x2.reshape(R, nb, Q_BLOCK).astype(jnp.float32)
    amax = jnp.max(jnp.abs(xb), axis=-1, keepdims=True)
    scale = amax / 127.0
    inv = jnp.where(scale > 0, 1.0 / scale, 0.0)
    x8 = jnp.clip(jnp.round(xb * inv), -127, 127).astype(jnp.int8)
    scale16 = scale.astype(jnp.float16).astype(jnp.float32)  # [R, nb, 1]
    if R == 1:
        # hot decode path: a [nb, 1] -> [nb, 128] broadcast. The general
        # formulation below goes through a 3D transpose that XLA lowers to a
        # relayout copy costing ~16 us PER MATMUL CALL on v5e — 3x the whole
        # kernel at the square decode shapes (caught by a 410 -> 177 tok/s
        # regression in the round-3 bench; scripts/kernel_lab.py reproduces)
        xs = jnp.broadcast_to(scale16.reshape(nb, 1), (nb, 128))
    else:
        xs = jnp.broadcast_to(
            jnp.transpose(scale16, (1, 0, 2)), (nb, R, 128)
        ).reshape(nb, R * 128)
    return x8.reshape(R, nb * Q_BLOCK), xs


# backwards-compatible single-row name (scripts/sweeps import it)
def _quantize_row_q80(x2: jnp.ndarray, nb: int):
    return _quantize_rows_q80(x2, nb)


def _blockdiag_mask(tile_knb: int) -> jnp.ndarray:
    """[tile_knb, tile_knb*32] int8: row b is 1 on block b's columns."""
    import numpy as np

    m = np.zeros((tile_knb, tile_knb * Q_BLOCK), np.int8)
    for b in range(tile_knb):
        m[b, b * Q_BLOCK : (b + 1) * Q_BLOCK] = 1
    return jnp.asarray(m)


def _i8_tiles(nb: int, out: int, rows: int = 1) -> tuple[int, int]:
    """Tile shapes for the int8 kernel, from the round-3 measured sweeps on
    v5e with the f16 scale plane at both the 1B and 8B model shapes
    (scripts/sweep_i8_tiles.py; µs per decode matmul, best of the grid):
      qkvo-like  (out<4096, nb<256):  tn=512  knb=64  (2048->2048:  7.3 µs)
      deep-k w2  (nb>=256, out<4096): tn=2048 knb=16  (8192->2048: 24.8 µs,
                 719 GB/s — wide lanes beat deep k-tiles for w2 shapes)
      ffn-wide   (4096<=out<16384):   nb>=128: tn=2048 knb=16
                 (4096->14336: 82 µs, 14336->4096: 86 µs); smaller
                 contractions: tn=512 knb=32 (2048->8192: 25.6 µs)
      vocab-wide (out>=16384): nb>=128: tn=2048 knb=128 (4096->128256:
                 799 µs, 698 GB/s); nb<128: tn=1024 knb=64 — the round-4
                 fused-shape sweep found deeper k-tiles best for SMALL
                 contractions at huge out (w13-fused 2048->16384:
                 57 -> 50 µs; 1B wcls 2048->32768: 98 µs, tied-best)
    """
    if out >= 16384:
        tile_n = 2048 if nb >= 128 else 1024
        tile_knb = 128 if nb >= 128 else 64
    elif out >= 4096:
        tile_n = 2048 if nb >= 128 else 512
        tile_knb = 16 if nb >= 128 else 32
    elif nb >= 256:
        tile_n = 2048
        tile_knb = 16
    else:
        # qkvo-class small shapes: the round-3 healthy-window re-sweep found
        # wide lanes + shallower k decisively better with the i16 scale
        # plane (2048->3072: 10.6 -> 7.6 us; 2048->2048: 10.1 -> 5.2 us)
        tile_n = 1024
        tile_knb = 32
    tile_n = min(tile_n, out)
    while out % tile_n:
        tile_n //= 2
    tile_knb = min(tile_knb, nb)
    while nb % tile_knb:
        tile_knb //= 2
    # VMEM cap: the int8 weight block (tile_knb*32*tile_n bytes) is
    # double-buffered; >4 MB blocks failed remote compile in the sweep.
    # Multi-row calls also materialize the [rows*knb, knb*32] block-diagonal
    # lhs in VMEM — cap it too.
    while tile_n * tile_knb * Q_BLOCK > 4 * 1024 * 1024 and tile_knb > 8:
        tile_knb //= 2
    while rows * tile_knb * tile_knb * Q_BLOCK > 4 * 1024 * 1024 and tile_knb > 8:
        tile_knb //= 2
    # Mosaic's sublane rule for the multi-k-step case: a [tile_knb, tile_n]
    # scale block must have tile_knb % 8 == 0 UNLESS it spans the whole
    # leading dim. The divisor chain can land below 8 for ragged nb (e.g.
    # nb=68 -> 4); fall back to one whole-dim k step — always legal, and
    # ragged-nb weights are small enough for a single block. Interpret mode
    # doesn't enforce this; only this guard protects real TPUs.
    if tile_knb != nb and tile_knb % 8:
        tile_knb = nb
    return tile_n, tile_knb


def _halfmask(tile_knb: int) -> jnp.ndarray:
    """[tile_knb, tile_knb*16] int8: row b is 1 on block b's 16 columns —
    the blockdiag mask for one nibble plane's feature group."""
    import numpy as np

    m = np.zeros((tile_knb, tile_knb * HGRP), np.int8)
    for b in range(tile_knb):
        m[b, b * HGRP : (b + 1) * HGRP] = 1
    return jnp.asarray(m)


def _quantize_rows_q80_split(x2: jnp.ndarray, nb: int):
    """[R, in] rows -> (x8a, x8b [R, nb*16] int8, xs, bs [nb, R*128] f32).

    Same Q80 numerics as `_quantize_rows_q80`; additionally splits each
    32-block's int8 values into the two nibble-plane feature groups the
    packed kernels dot separately (a/b = features 0..15 / 16..31), and
    computes the per-block sums `bs` that fold the codec's +8 offset out of
    the integer partials (partial - 8*bs == the exact signed dot). Layouts
    mirror xs (row r's scalars at columns [r*128, (r+1)*128))."""
    R = x2.shape[0]
    xb = x2.reshape(R, nb, Q_BLOCK).astype(jnp.float32)
    amax = jnp.max(jnp.abs(xb), axis=-1, keepdims=True)
    scale = amax / 127.0
    inv = jnp.where(scale > 0, 1.0 / scale, 0.0)
    x8 = jnp.clip(jnp.round(xb * inv), -127, 127).astype(jnp.int8)  # [R, nb, 32]
    scale16 = scale.astype(jnp.float16).astype(jnp.float32)  # [R, nb, 1]
    bsum = jnp.sum(x8.astype(jnp.int32), axis=-1).astype(jnp.float32)  # [R, nb]
    if R == 1:
        # hot decode path: plain [nb, 1] -> [nb, 128] broadcasts (the 3D
        # transpose in the general branch costs a ~16 us relayout per call)
        xs = jnp.broadcast_to(scale16.reshape(nb, 1), (nb, 128))
        bs = jnp.broadcast_to(bsum.reshape(nb, 1), (nb, 128))
    else:
        xs = jnp.broadcast_to(
            jnp.transpose(scale16, (1, 0, 2)), (nb, R, 128)
        ).reshape(nb, R * 128)
        bs = jnp.broadcast_to(
            jnp.transpose(bsum, (1, 0))[:, :, None], (nb, R, 128)
        ).reshape(nb, R * 128)
    x8a = x8[:, :, :HGRP].reshape(R, nb * HGRP)
    x8b = x8[:, :, HGRP:].reshape(R, nb * HGRP)
    return x8a, x8b, xs, bs


def _lane_tile(out: int, target: int) -> int:
    """Largest multiple-of-128 divisor of `out` that is <= target. The old
    halving chain collapsed non-power-of-two outs to tiny tiles (the 8B's
    128256 vocab fell from a 2048 target to 256 lanes; 128256 = 167 * 768,
    so 768 is the honest answer)."""
    tn = min(target, out)
    tn -= tn % LANE
    while tn >= LANE:
        if out % tn == 0:
            return tn
        tn -= LANE
    return out


def _fs_tiles(nb: int, out: int, rows: int = 1) -> tuple[int, int]:
    """Tile shapes for the packed (feature-split) int8 decode kernels, from
    the round-5 on-chip sweeps (scripts/probe_int4c.py at 1B shapes plus an
    8B-shape sweep; us per decode matmul, 2D [nb*4, out] storage):
      big-out   (out >= 4096):  tn=2048; knb=64 at nb>=128 (8B wqkv 19.5 us
                725 GB/s, w13 76.8 us 860 GB/s), knb=32 at smaller
                contractions (1B w13 28.1 us 672 GB/s; wcls 51.9 us 728)
      deep-k    (nb >= 256, out < 4096): tn=1024 knb=64 (8B w2 47.5 us
                695 GB/s; the r5.0 (2048, 8) choice measured ~14.6 us at
                the 1B w2 shape but loses at 8B scale)
      square    (else):                  tn=1024 knb=32 (wqkv 1.27x)
    Lane tiles come from `_lane_tile` so ragged outs (128256 vocab) keep
    wide tiles.
    """
    if out >= 4096:
        tile_n, tile_knb = 2048, (64 if nb >= 128 else 32)
    elif nb >= 256:
        tile_n, tile_knb = 1024, 64
    else:
        tile_n, tile_knb = 1024, 32
    tile_n = _lane_tile(out, tile_n)
    tile_knb = min(tile_knb, nb)
    while nb % tile_knb:
        tile_knb //= 2
    # VMEM: packed i32 block (dbl-buffered, 16*knb*tn bytes) + lo/hi int8
    # temps + the per-row blockdiag expansions [rows*knb, knb*16] x2
    while 4 * tile_knb * 16 * tile_n > 8 * 1024 * 1024 and tile_knb > 8:
        tile_knb //= 2
    while 2 * rows * tile_knb * tile_knb * HGRP > 4 * 1024 * 1024 and tile_knb > 8:
        tile_knb //= 2
    # Mosaic sublane rule for the [tile_knb, tile_n] scale block (multi-k
    # grids need tile_knb % 8 unless the block spans the whole leading dim)
    if tile_knb != nb and tile_knb % 8:
        tile_knb = nb
    return tile_n, tile_knb


def _kernel_fs_i8(
    x8a_ref, x8b_ref, xs_ref, bs_ref, mask_ref, qp_ref, dt_ref, out_ref
):
    """Packed-weight int8-MXU decode kernel: two i32 mask ops + pltpu.bitcast
    unpack the nibble planes straight into int8 MXU operands (module
    docstring). Per plane, the blockdiag trick gives every block's partial
    dot in ONE 2D int8 matmul; the two planes' partials add (they are
    disjoint halves of each block's features), the +8 offset leaves via the
    prologue-computed per-block sums, and per-block scales combine on the
    VPU at 1/32nd the element count. Bit-exact vs the reference's Q80xQ40
    integer dot (all-integer until the final f32 scale combine)."""
    k = pl.program_id(1)
    knb, tn = dt_ref.shape
    R = x8a_ref.shape[0]
    mask = mask_ref[...]  # [knb, knb*16]
    lo, hi = _fs_lo_hi(qp_ref[...])  # int8 [knb*16, tn] each
    partials = None
    for x_ref, w in ((x8a_ref, lo), (x8b_ref, hi)):
        x8 = x_ref[...]  # [R, knb*16] int8
        if R == 1:
            bd = jnp.where(mask != 0, jnp.broadcast_to(x8, mask.shape), jnp.int8(0))
        else:
            # strictly 2D per-row broadcast-select + sublane concat (3D int8
            # broadcasts fail Mosaic's shape-cast lowering on this platform)
            bd = jnp.concatenate(
                [
                    jnp.where(
                        mask != 0,
                        jnp.broadcast_to(x8[r : r + 1], mask.shape),
                        jnp.int8(0),
                    )
                    for r in range(R)
                ],
                axis=0,
            )  # [R*knb, knb*16]
        p = jax.lax.dot_general(
            bd, w, (((1,), (0,)), ((), ())), preferred_element_type=jnp.int32
        )  # [R*knb, tn]
        partials = p if partials is None else partials + p
    dtf = _scale_f32(dt_ref[...])  # [knb, tn]
    rows = []
    for r in range(R):
        pr = partials[r * knb : (r + 1) * knb].astype(jnp.float32)
        pr = pr - 8.0 * bs_ref[...][:, r * 128 : r * 128 + 1]
        scale = xs_ref[...][:, r * 128 : r * 128 + 1] * dtf
        rows.append(jnp.sum(pr * scale, axis=0)[None, :])
    acc = rows[0] if R == 1 else jnp.concatenate(rows, axis=0)  # [R, tn]

    @pl.when(k == 0)
    def _():
        out_ref[...] = acc

    @pl.when(k != 0)
    def _():
        out_ref[...] += acc


def _kernel_fs_stacked_i8(
    l_ref, x8a_ref, x8b_ref, xs_ref, bs_ref, mask_ref, qp_ref, dt_ref, out_ref
):
    # identical math to _kernel_fs_i8; the layer offset was folded into the
    # weight block index by the scalar-prefetch index_map
    _kernel_fs_i8(x8a_ref, x8b_ref, xs_ref, bs_ref, mask_ref, qp_ref, dt_ref, out_ref)


@partial(jax.jit, static_argnames=("interpret",))
def _i8_call(x8, xs, qt, dt, interpret: bool = False) -> jnp.ndarray:
    """LEGACY (probe support): the round-4 unpacked-int8 MXU pallas_call on
    pre-quantized activations — the A/B baseline the packed kernels are
    measured against (scripts/probe_int4*.py). x8 [R, in] int8, xs
    [nb, R*128] scales, dt already `_dt_operand`-shaped; qt UNPACKED
    [nb, 32, out] int8. Returns [R, out] f32."""
    nb, _, out = qt.shape
    R = x8.shape[0]
    tile_n, tile_knb = _i8_tiles(nb, out, rows=R)
    mask = _blockdiag_mask(tile_knb)
    grid = (out // tile_n, nb // tile_knb)
    return pl.pallas_call(
        _kernel_i8,
        grid=grid,
        in_specs=[
            pl.BlockSpec((R, tile_knb * Q_BLOCK), lambda j, k: (0, k)),
            pl.BlockSpec((tile_knb, R * 128), lambda j, k: (k, 0)),
            pl.BlockSpec((tile_knb, tile_knb * Q_BLOCK), lambda j, k: (0, 0)),
            pl.BlockSpec((tile_knb, Q_BLOCK, tile_n), lambda j, k: (k, 0, j)),
            pl.BlockSpec((tile_knb, tile_n), lambda j, k: (k, j)),
        ],
        out_specs=pl.BlockSpec((R, tile_n), lambda j, k: (0, j)),
        out_shape=jax.ShapeDtypeStruct((R, out), jnp.float32),
        interpret=interpret,
        **_i8_compiler_params(),
    )(x8, xs, mask, qt, dt)


@partial(jax.jit, static_argnames=("interpret",))
def q40_matmul_pallas_i8(x, qt, dt, interpret: bool = False) -> jnp.ndarray:
    """x @ w via the packed int8-MXU kernel for decode-sized batches. x:
    [..., in] with a small row count (quant_matmul gates rows <= 8); qt the
    PACKED [nb*4, out] int32 plane; returns [..., out] f32. Jitted so eager
    callers (compile checks) run prologue + kernel as one program; inlines
    when traced inside a larger jit."""
    rows4, out = qt.shape
    nb = rows4 // 4
    in_features = nb * Q_BLOCK
    lead = x.shape[:-1]
    R = 1
    for s in lead:
        R *= s
    x8a, x8b, xs, bs = _quantize_rows_q80_split(x.reshape(R, in_features), nb)
    dt = _dt_operand(dt)
    tile_n, tile_knb = _fs_tiles(nb, out, rows=R)
    mask = _halfmask(tile_knb)
    grid = (out // tile_n, nb // tile_knb)
    out2 = pl.pallas_call(
        _kernel_fs_i8,
        grid=grid,
        in_specs=[
            pl.BlockSpec((R, tile_knb * HGRP), lambda j, k: (0, k)),
            pl.BlockSpec((R, tile_knb * HGRP), lambda j, k: (0, k)),
            pl.BlockSpec((tile_knb, R * 128), lambda j, k: (k, 0)),
            pl.BlockSpec((tile_knb, R * 128), lambda j, k: (k, 0)),
            pl.BlockSpec((tile_knb, tile_knb * HGRP), lambda j, k: (0, 0)),
            pl.BlockSpec((tile_knb * 4, tile_n), lambda j, k: (k, j)),
            pl.BlockSpec((tile_knb, tile_n), lambda j, k: (k, j)),
        ],
        out_specs=pl.BlockSpec((R, tile_n), lambda j, k: (0, j)),
        out_shape=jax.ShapeDtypeStruct((R, out), jnp.float32),
        interpret=interpret,
        **_i8_compiler_params(),
    )(x8a, x8b, xs, bs, mask, qt, dt)
    return out2.reshape(*lead, out)


@partial(jax.jit, static_argnames=("interpret",))
def q40_matmul_pallas_stacked_i8(
    x, qt, dt, layer, interpret: bool = False
) -> jnp.ndarray:
    """x @ w[layer] for a stacked packed Q40 weight via the int8-MXU kernel
    at decode-sized batches; the layer index scalar-prefetches into the DMA
    offsets exactly like q40_matmul_pallas_stacked."""
    L, rows4, out = qt.shape
    nb = rows4 // 4
    in_features = nb * Q_BLOCK
    lead = x.shape[:-1]
    R = 1
    for s in lead:
        R *= s
    x8a, x8b, xs, bs = _quantize_rows_q80_split(x.reshape(R, in_features), nb)
    dt = _dt_operand(dt)
    tile_n, tile_knb = _fs_tiles(nb, out, rows=R)
    mask = _halfmask(tile_knb)
    k_steps = nb // tile_knb
    qt2 = qt.reshape(L * rows4, out)
    dt3 = dt.reshape(L * nb, out)
    grid = (out // tile_n, k_steps)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[
            pl.BlockSpec((R, tile_knb * HGRP), lambda j, k, l: (0, k)),
            pl.BlockSpec((R, tile_knb * HGRP), lambda j, k, l: (0, k)),
            pl.BlockSpec((tile_knb, R * 128), lambda j, k, l: (k, 0)),
            pl.BlockSpec((tile_knb, R * 128), lambda j, k, l: (k, 0)),
            pl.BlockSpec((tile_knb, tile_knb * HGRP), lambda j, k, l: (0, 0)),
            pl.BlockSpec(
                (tile_knb * 4, tile_n), lambda j, k, l: (l[0] * k_steps + k, j)
            ),
            pl.BlockSpec((tile_knb, tile_n), lambda j, k, l: (l[0] * k_steps + k, j)),
        ],
        out_specs=pl.BlockSpec((R, tile_n), lambda j, k, l: (0, j)),
    )
    out2 = pl.pallas_call(
        _kernel_fs_stacked_i8,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((R, out), jnp.float32),
        interpret=interpret,
        **_i8_compiler_params(),
    )(jnp.asarray(layer, jnp.int32).reshape(1), x8a, x8b, xs, bs, mask, qt2, dt3)
    return out2.reshape(*lead, out)


def _kernel_grouped(be_ref, x_ref, qt_ref, dt_ref, out_ref):
    # same dequant-matmul math as _kernel_stacked; the expert index comes
    # from the scalar-prefetched per-row-block map instead of a layer scalar
    _dequant_dot_accum(pl.program_id(2), x_ref, qt_ref, dt_ref, out_ref)


@partial(jax.jit, static_argnames=("block_r", "dtype", "interpret"))
def q40_matmul_pallas_grouped(
    xp: jnp.ndarray,  # [R_pad, in] — rows grouped by expert, groups padded
    # to block_r multiples (ops/moe.py _grouped_layout)
    qt: jnp.ndarray,  # [..., nb*4, out] int32 packed expert stack — leading axes
    # flatten to one group axis (e.g. [E, ...] or the full [L, E, ...] all-
    # layers stack; block_expert then carries FLAT indices layer*E + e, so
    # no per-layer slice of the stack is ever materialized)
    dt: jnp.ndarray,  # [..., nb, out] scale plane
    block_expert: jnp.ndarray,  # [R_pad // block_r] int32 — flat group
    # index of each row block
    block_r: int,
    dtype=jnp.bfloat16,
    interpret: bool = False,
) -> jnp.ndarray:
    """Grouped (ragged) quantized matmul: row block i is multiplied by
    group block_expert[i]'s weight, streamed from HBM as int8 — the MoE
    prefill path's replacement for dequantize-the-whole-expert-stack +
    `lax.ragged_dot` (which writes and re-reads a bf16 copy of every expert,
    and at 30B-A3B scale materializes GB-sized transients). The group index
    rides the scalar-prefetch channel into the BlockSpec index maps exactly
    like the stacked kernels' layer index. Upgrades the formulation of the
    reference's per-expert indexed matmul (src/nn/nn-cpu-ops.cpp:1166-1192).
    """
    *lead, rows4, out = qt.shape
    nb = rows4 // 4
    E = 1
    for s in lead:
        E *= s
    in_features = nb * Q_BLOCK
    R_pad = xp.shape[0]
    xp = xp.astype(dtype)
    dt = _dt_operand(dt)

    # Tiles start at the WHOLE expert and shrink only under VMEM pressure:
    # MoE experts are small (ff 512-768 at Qwen3-MoE scale), and the cost
    # at default 256x64 tiles was GRID-STEP overhead, not bandwidth — 72
    # steps per role per layer ran the kernel at ~70 GB/s effective (round-5
    # profile). Whole-expert tiles make one step per row block.
    def vmem_need(tn, knb):
        # packed block (dbl-buffered) + dequant bf16 w + cat int8 temp +
        # x block (dbl) + out block (dbl)
        return (
            2 * knb * HGRP * tn
            + knb * Q_BLOCK * tn * 2
            + knb * Q_BLOCK * tn
            + 2 * block_r * knb * Q_BLOCK * 2
            + 2 * block_r * tn * 4
        )

    tile_n = out
    tile_knb = nb
    cap = 10 * 1024 * 1024
    while vmem_need(tile_n, tile_knb) > cap and tile_n > 256 and tile_n % 2 == 0:
        tile_n //= 2
    while vmem_need(tile_n, tile_knb) > cap and tile_knb > 8:
        nxt = tile_knb // 2
        if nb % nxt:
            break
        tile_knb = nxt
    while out % tile_n:
        tile_n //= 2
    while nb % tile_knb:
        tile_knb //= 2
    if tile_knb != nb and tile_knb % 8:
        tile_knb = nb
    k_steps = nb // tile_knb

    qt2 = qt.reshape(E * rows4, out)
    dt3 = dt.reshape(E * nb, out)
    grid = (R_pad // block_r, out // tile_n, k_steps)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_r, tile_knb * Q_BLOCK), lambda i, j, k, be: (i, k)),
            pl.BlockSpec(
                (tile_knb * 4, tile_n),
                lambda i, j, k, be, ks=k_steps: (be[i] * ks + k, j),
            ),
            pl.BlockSpec(
                (tile_knb, tile_n), lambda i, j, k, be, ks=k_steps: (be[i] * ks + k, j)
            ),
        ],
        out_specs=pl.BlockSpec((block_r, tile_n), lambda i, j, k, be: (i, j)),
    )
    return pl.pallas_call(
        _kernel_grouped,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((R_pad, out), jnp.float32),
        interpret=interpret,
        # row blocks and out tiles are independent; only k accumulates.
        # Declaring that is a measured 10x on this kernel (62.7 vs 619 us
        # at the bench MoE w1 shape — without it Mosaic serializes the
        # whole (i, j, k) grid behind each scalar-prefetched block index)
        compiler_params=_CompilerParams(
            dimension_semantics=(pltpu.PARALLEL, pltpu.PARALLEL, pltpu.ARBITRARY)
        ),
    )(jnp.asarray(block_expert, jnp.int32), xp, qt2, dt3)


@partial(jax.jit, static_argnames=("dtype", "interpret"))
def q40_matmul_pallas(
    x: jnp.ndarray,  # [..., in_features]
    qt: jnp.ndarray,  # [nb*4, out] int32 packed
    dt: jnp.ndarray,  # [nb, out]
    dtype=jnp.bfloat16,
    interpret: bool = False,
) -> jnp.ndarray:
    """Returns x @ w (logical x @ w.T for the [out, in] weight), f32."""
    rows4, out = qt.shape
    nb = rows4 // 4
    in_features = nb * Q_BLOCK
    lead = x.shape[:-1]
    b = 1
    for s in lead:
        b *= s
    x2 = x.reshape(b, in_features).astype(dtype)
    dt = _dt_operand(dt)

    tile_n = min(DEFAULT_TILE_N, out)
    while out % tile_n:
        tile_n //= 2
    tile_knb = min(DEFAULT_TILE_KNB, nb)
    while nb % tile_knb:
        tile_knb //= 2
    # _bf16_tile_cap owns BOTH the VMEM cap and the Mosaic sublane rule
    # (ragged nb falls back to one whole-dim k step inside it)
    tile_n, tile_knb = _bf16_tile_cap(b, tile_n, tile_knb, nb)

    grid = (out // tile_n, nb // tile_knb)
    out2 = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (b, tile_knb * Q_BLOCK), lambda j, k: (0, k), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (tile_knb * 4, tile_n), lambda j, k: (k, j), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec((tile_knb, tile_n), lambda j, k: (k, j), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((b, tile_n), lambda j, k: (0, j), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, out), jnp.float32),
        interpret=interpret,
    )(x2, qt, dt)
    return out2.reshape(*lead, out)
