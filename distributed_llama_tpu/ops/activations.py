"""Activation functions (reference: src/nn/nn-cpu-ops.cpp OP_SILU / OP_GELU).

The reference's SiLU kernel computes ``x / (1 + exp(-x))`` and its GELU uses
the tanh approximation; both are elementwise and fuse into the surrounding
matmuls under XLA.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def silu(x: jnp.ndarray) -> jnp.ndarray:
    return x * jax.nn.sigmoid(x)


def gelu(x: jnp.ndarray) -> jnp.ndarray:
    # tanh approximation, matching the reference's geluForward
    return jax.nn.gelu(x, approximate=True)
