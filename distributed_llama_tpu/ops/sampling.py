"""On-device sampling (argmax / temperature / top-p).

The reference samples on the host per token (reference: Sampler::sample,
src/tokenizer.cpp:482-512) — fine over PCIe-attached CPUs, but on TPU every
device->host round trip costs tunnel/dispatch latency, so the decode loop
samples on-device and ships tokens back in chunks (runtime/decode.py).

Math matches the reference exactly (temperature scaling -> softmax -> top-p
truncation at the first cumulative-prob > topp, sampling within the kept
mass); only the RNG differs — the reference's xorshift* stream requires
sequential host state, here it's jax.random (counter-based, reproducible
under a fixed seed, but a different stream). The host Sampler remains the
bit-parity path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sample_logits(
    logits: jnp.ndarray,  # [b, vocab] f32
    key: jnp.ndarray,
    temperature: float,
    topp: float,
) -> jnp.ndarray:
    """Returns [b] int32 sampled tokens. `temperature`/`topp` are static."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    probs = jax.nn.softmax(logits / temperature, axis=-1)
    if topp <= 0.0 or topp >= 1.0:
        coin = jax.random.uniform(key, (logits.shape[0],))
        cdf = jnp.cumsum(probs, axis=-1)
        idx = jnp.sum(cdf < coin[:, None], axis=-1)
        return idx.astype(jnp.int32).clip(0, logits.shape[-1] - 1)
    return _sample_topp(probs, key, topp)


def _sample_topp(probs: jnp.ndarray, key: jnp.ndarray, topp: float) -> jnp.ndarray:
    b, n = probs.shape
    sorted_probs = jnp.sort(probs, axis=-1)[:, ::-1]
    order = jnp.argsort(-probs, axis=-1)
    csum = jnp.cumsum(sorted_probs, axis=-1)
    # keep everything up to and including the first element whose cumulative
    # probability exceeds topp (reference: sample_topp, tokenizer.cpp:426-447)
    over = csum > topp
    keep = jnp.logical_not(jnp.concatenate([jnp.zeros((b, 1), bool), over[:, :-1]], axis=-1))
    kept = jnp.where(keep, sorted_probs, 0.0)
    kept_sum = jnp.sum(kept, axis=-1, keepdims=True)
    coin = jax.random.uniform(key, (b, 1)) * kept_sum
    cdf = jnp.cumsum(kept, axis=-1)
    pick = jnp.sum(cdf < coin, axis=-1).clip(0, n - 1)
    return jnp.take_along_axis(order, pick[:, None], axis=-1)[:, 0].astype(jnp.int32)
