"""On-device sampling (argmax / temperature / top-p).

The reference samples on the host per token (reference: Sampler::sample,
src/tokenizer.cpp:482-512) — fine over PCIe-attached CPUs, but on TPU every
device->host round trip costs tunnel/dispatch latency, so the decode loop
samples on-device and ships tokens back in chunks (runtime/decode.py).

Math matches the reference exactly (temperature scaling -> softmax -> top-p
truncation at the first cumulative-prob > topp, sampling within the kept
mass); only the RNG differs — the reference's xorshift* stream requires
sequential host state, here it's jax.random (counter-based, reproducible
under a fixed seed, but a different stream). The host Sampler remains the
bit-parity path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

#: additive mask for grammar-illegal tokens: large enough that exp()
#: underflows to exactly 0 in every compute dtype, small enough to stay
#: finite in bfloat16
_MASKED = -1e30


def apply_grammar_mask(
    logits: jnp.ndarray,  # [..., vocab]
    grammar_table: jnp.ndarray | None,  # [S, vocab] int32; -1 = illegal
    grammar_state: jnp.ndarray | None,  # [...] int32 global DFA states
) -> jnp.ndarray:
    """Mask grammar-illegal tokens to -1e30 BEFORE any sampling branch.

    One gather keyed by the per-row grammar-state operand derives the
    boolean legality row (``table[state] >= 0``); unconstrained rows ride
    the arena's all-legal FREE state, so the masked program computes
    bit-identical logits for them and ONE warm program serves every
    constrained/unconstrained mix (runtime/grammar.py). With no grammar
    operands (grammar disabled at engine build) this is the identity —
    the traced program is unchanged."""
    if grammar_table is None or grammar_state is None:
        return logits
    legal = grammar_table[grammar_state] >= 0  # [..., vocab] bool
    return jnp.where(legal, logits, jnp.asarray(_MASKED, logits.dtype))


def sample_logits(
    logits: jnp.ndarray,  # [b, vocab] f32
    key: jnp.ndarray,
    temperature: float,
    topp: float,
) -> jnp.ndarray:
    """Returns [b] int32 sampled tokens. `temperature`/`topp` are static."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    probs = jax.nn.softmax(logits / temperature, axis=-1)
    if topp <= 0.0 or topp >= 1.0:
        coin = jax.random.uniform(key, (logits.shape[0],))
        cdf = jnp.cumsum(probs, axis=-1)
        idx = jnp.sum(cdf < coin[:, None], axis=-1)
        return idx.astype(jnp.int32).clip(0, logits.shape[-1] - 1)
    return _sample_topp(probs, key, topp)


def _sample_topp(
    probs: jnp.ndarray, key: jnp.ndarray | None, topp, coin=None
) -> jnp.ndarray:
    """Top-p pick over [b, vocab] probs: keep everything up to and
    including the first element whose cumulative probability exceeds topp
    (reference: sample_topp, tokenizer.cpp:426-447). `topp` may be a static
    float (the host-parity path) or a traced scalar (`sample_logits_traced`
    — which also passes its pre-drawn `coin` so both of its arms consume
    ONE uniform); with `coin=None` the draw happens here, bit-matching the
    original static program's stream."""
    b, n = probs.shape
    sorted_probs = jnp.sort(probs, axis=-1)[:, ::-1]
    order = jnp.argsort(-probs, axis=-1)
    csum = jnp.cumsum(sorted_probs, axis=-1)
    over = csum > topp
    keep = jnp.logical_not(jnp.concatenate([jnp.zeros((b, 1), bool), over[:, :-1]], axis=-1))
    kept = jnp.where(keep, sorted_probs, 0.0)
    kept_sum = jnp.sum(kept, axis=-1, keepdims=True)
    if coin is None:
        coin = jax.random.uniform(key, (b, 1))
    cdf = jnp.cumsum(kept, axis=-1)
    pick = jnp.sum(cdf < coin * kept_sum, axis=-1).clip(0, n - 1)
    return jnp.take_along_axis(order, pick[:, None], axis=-1)[:, 0].astype(jnp.int32)


def sample_logits_traced(
    logits: jnp.ndarray,  # [b, vocab] f32
    key: jnp.ndarray,
    temperature: jnp.ndarray,  # traced scalar; <= 0 = greedy
    topp: jnp.ndarray,  # traced scalar; outside (0, 1) = full distribution
    grammar_table: jnp.ndarray | None = None,  # [S, vocab] int32 arena
    grammar_state: jnp.ndarray | None = None,  # [b] int32 global DFA states
) -> jnp.ndarray:
    """`sample_logits` with TRACED temperature/top-p scalars: ONE compiled
    program serves every sampling setting, so a sampled request can never
    compile a new decode program after warmup (the recompile-sentinel
    contract — warmup only ever runs temperature 0). The greedy/sampled
    split is a `lax.cond` on the traced scalar: BOTH branches live in the
    one compiled program, but a greedy step executes only the argmax at
    runtime — the sampled branch's O(vocab log vocab) sorts would otherwise
    tax every step of the default greedy serving path. The greedy arm is
    the exact argmax chain (bit-identical to the old static program at
    temperature 0); the top-p arm draws the same single
    `uniform(key, (b, 1))` the static program's 0 < topp < 1 branch drew,
    so seeded top-p streams carry over too. Grammar operands (when the
    engine threads them) mask illegal tokens BEFORE the cond, so both arms
    sample from the constrained distribution."""
    logits = apply_grammar_mask(logits, grammar_table, grammar_state)

    def greedy_arm(logits, key, temperature, topp):
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def sampled_arm(logits, key, temperature, topp):
        b, n = logits.shape
        temp_safe = jnp.maximum(temperature, 1e-6)
        probs = jax.nn.softmax(logits / temp_safe, axis=-1)
        coin = jax.random.uniform(key, (b, 1))

        # full-distribution arm (topp outside (0, 1)): vocab-order CDF
        full_cdf = jnp.cumsum(probs, axis=-1)
        full_pick = (
            jnp.sum(full_cdf < coin, axis=-1).clip(0, n - 1).astype(jnp.int32)
        )

        # top-p arm: THE shared truncated-CDF pick, traced topp + the one
        # coin above (clamped to 1.0 outside (0,1) so both arms are finite)
        topp_safe = jnp.where((topp > 0.0) & (topp < 1.0), topp, 1.0)
        topp_pick = _sample_topp(probs, None, topp_safe, coin=coin)

        in_topp = (topp > 0.0) & (topp < 1.0)
        return jnp.where(in_topp, topp_pick, full_pick)

    return jax.lax.cond(
        temperature <= 0.0, greedy_arm, sampled_arm, logits, key, temperature,
        topp,
    )


def split_row_keys(keys_data: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Advance a [b, 2] uint32 array of per-row threefry key states one
    split: returns (new_states, subkeys_data). Each row's chain is
    independent — a row's sampled stream depends only on its own seed and
    its own step count, never on which rows it is co-batched with (the
    property that lets SEEDED requests share a continuous-batching round)."""

    def one(kd):
        k = jax.random.wrap_key_data(kd, impl="threefry2x32")
        nk, sub = jax.random.split(k)
        return jax.random.key_data(nk), jax.random.key_data(sub)

    return jax.vmap(one)(keys_data)


def sample_logits_per_row(
    logits: jnp.ndarray,  # [b, vocab] f32
    subkeys_data: jnp.ndarray,  # [b, 2] uint32 per-row key states
    temperature: jnp.ndarray,  # [b] f32; <= 0 means greedy for that row
    topp: jnp.ndarray,  # [b] f32; outside (0, 1) means full-distribution
    grammar_table: jnp.ndarray | None = None,  # [S, vocab] int32 arena
    grammar_state: jnp.ndarray | None = None,  # [b] int32 global DFA states
) -> jnp.ndarray:
    """Per-row sampling parameters as TRACED vectors: one compiled program
    serves any mix of greedy/temperature/top-p rows (continuous batching
    co-schedules requests with different sampling settings; the fixed-round
    design had to serialize them). Each row mirrors `sample_logits`' branch
    structure — greedy / full-distribution vocab-order CDF / top-p
    sorted-order CDF — but the RNG structure necessarily differs (per-row
    key chains vs one shared key), so streams only reproduce against other
    per-row-keyed runs with the same per-row key. Grammar operands mask
    illegal tokens up front, so every branch — greedy included — samples
    from the constrained distribution."""
    logits = apply_grammar_mask(logits, grammar_table, grammar_state)
    b, n = logits.shape
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    temp_safe = jnp.maximum(temperature, 1e-6)[:, None]
    probs = jax.nn.softmax(logits / temp_safe, axis=-1)

    def row_coin(kd):
        return jax.random.uniform(jax.random.wrap_key_data(kd, impl="threefry2x32"), ())

    coin = jax.vmap(row_coin)(subkeys_data)[:, None]  # [b, 1] in [0, 1)

    # full-distribution branch (topp outside (0,1)): vocab-order CDF, the
    # same structure as the scalar path's topp >= 1 branch
    full_cdf = jnp.cumsum(probs, axis=-1)
    full_pick = jnp.sum(full_cdf < coin, axis=-1).clip(0, n - 1).astype(jnp.int32)

    # top-p branch: sorted-order CDF truncated at the first cumulative
    # probability > topp (reference: sample_topp, tokenizer.cpp:426-447)
    topp_safe = jnp.where((topp > 0.0) & (topp < 1.0), topp, 1.0)
    sorted_probs = jnp.sort(probs, axis=-1)[:, ::-1]
    order = jnp.argsort(-probs, axis=-1)
    csum = jnp.cumsum(sorted_probs, axis=-1)
    over = csum > topp_safe[:, None]
    keep = jnp.logical_not(
        jnp.concatenate([jnp.zeros((b, 1), bool), over[:, :-1]], axis=-1)
    )
    kept = jnp.where(keep, sorted_probs, 0.0)
    kept_sum = jnp.sum(kept, axis=-1, keepdims=True)
    cdf = jnp.cumsum(kept, axis=-1)
    pick = jnp.sum(cdf < coin * kept_sum, axis=-1).clip(0, n - 1)
    topp_pick = jnp.take_along_axis(order, pick[:, None], axis=-1)[:, 0].astype(jnp.int32)

    in_topp = (topp > 0.0) & (topp < 1.0)
    sampled = jnp.where(in_topp, topp_pick, full_pick)
    return jnp.where(temperature <= 0.0, greedy, sampled)
