"""TPU-native compute ops.

Each op here replaces one of the reference engine's CPU/Vulkan kernels
(reference: src/nn/nn-cpu-ops.cpp, src/nn/vulkan/*.comp) with a functional JAX
equivalent designed for XLA fusion on TPU. There is no op registry or kernel
dispatch table — XLA is the executor, so ops are plain functions composed in
models/transformer.py.
"""

from .norm import rms_norm
from .activations import silu, gelu
from .rope import RopeTables, build_rope_tables, apply_rope_llama, apply_rope_falcon, apply_rope
from .attention import gqa_attention
from .quant import (
    QuantTensor,
    quant_tensor_from_q40,
    dequantize,
    quant_matmul,
    quantize_q80_activations,
)
from .moe import moe_router

__all__ = [
    "rms_norm",
    "silu",
    "gelu",
    "RopeTables",
    "build_rope_tables",
    "apply_rope_llama",
    "apply_rope_falcon",
    "apply_rope",
    "gqa_attention",
    "QuantTensor",
    "quant_tensor_from_q40",
    "dequantize",
    "quant_matmul",
    "quantize_q80_activations",
    "moe_router",
]
