"""Device-side quantized weights and the quantized matmul.

The reference's hot loop is `matmul_Q80_Q40_F32` — a Q80-quantized activation
row dotted against Q40 block-quantized weight rows with NEON/AVX intrinsics
(reference: src/nn/nn-cpu-ops.cpp:231-449). On TPU the same math maps to:

* weights stay resident in HBM as int8 values + per-block scales
  (`QuantTensor`) — 4.5 bits/weight of traffic instead of 16/32;
* the matmul dequantizes on the fly and accumulates in f32 on the MXU. Two
  implementations: a plain-XLA path (`quant_matmul`, dequant fuses into the
  matmul's operand load) and a fused Pallas kernel (ops/pallas_q40.py) that
  dequantizes per-tile in VMEM.

Activation quantization to Q80 exists only to *emulate the reference's
numerics* when bit-parity testing (`quantize_q80_activations`); the production
path feeds bf16/f32 activations straight in — on TPU there is no bandwidth
win from quantizing activations that are already on-chip.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..formats.quants import Q_BLOCK


@jax.tree_util.register_pytree_node_class
@dataclass
class QuantTensor:
    """A Q40 weight on device: int8 values in [-8,7] + per-block f32 scales.

    q: [out_features, in_features // 32, 32] int8
    d: [out_features, in_features // 32] f32 (converted from the file's f16)

    Logical value = q * d (per block). Layout matches `unpack_q40`
    (formats/quants.py) reshaped per row, i.e. exactly the reference's
    NnBlockQ40 stream (reference: src/nn/nn-quants.hpp:64-67).
    """

    q: jnp.ndarray
    d: jnp.ndarray

    @property
    def out_features(self) -> int:
        return self.q.shape[-3]

    @property
    def in_features(self) -> int:
        return self.q.shape[-2] * self.q.shape[-1]

    @property
    def shape(self) -> tuple:
        return (*self.q.shape[:-3], self.out_features, self.in_features)

    def tree_flatten(self):
        return (self.q, self.d), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


def quant_tensor_from_q40(q: np.ndarray, d: np.ndarray) -> QuantTensor:
    """From host-side unpack_q40 output reshaped to [out, in//32, 32]/[out, in//32]."""
    return QuantTensor(q=jnp.asarray(q, dtype=jnp.int8), d=jnp.asarray(d, dtype=jnp.float32))


def dequantize(w: QuantTensor, dtype=jnp.float32) -> jnp.ndarray:
    """Materialize [..., out_features, in_features] in `dtype`."""
    x = w.q.astype(dtype) * w.d[..., None].astype(dtype)
    return x.reshape(w.shape)


@partial(jax.jit, static_argnames=("dtype",))
def _quant_matmul_xla(x, q, d, dtype):
    w = (q.astype(dtype) * d[..., None].astype(dtype)).reshape(q.shape[-3], -1)
    # f32 operands get full-precision accumulation (parity tests); bf16
    # operands are the MXU-native fast path where precision is moot.
    precision = jax.lax.Precision.HIGHEST if dtype == jnp.float32 else None
    return jax.lax.dot_general(
        x.astype(dtype),
        w,
        (((x.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=precision,
    )


def quant_matmul(
    x: jnp.ndarray, w: QuantTensor, dtype=jnp.bfloat16, out_dtype=None
) -> jnp.ndarray:
    """``x @ w.T`` for a Q40 weight; x: [..., in_features] -> [..., out_features].

    `dtype` is the dequantized-operand dtype fed to the MXU (bf16 for speed,
    f32 for parity tests); accumulation is always f32.
    """
    out = _quant_matmul_xla(x, w.q, w.d, dtype)
    return out.astype(out_dtype if out_dtype is not None else x.dtype)


def quantize_q80_activations(x: jnp.ndarray) -> jnp.ndarray:
    """Round-trip x through Q80 (per-32-block int8 + f16 scale) numerics.

    Emulates the reference's `--buffer-float-type q80` activation path
    (reference: quantizeF32toQ80, src/nn/nn-quants.cpp:67-…) for parity
    testing: returns f32 values equal to dequantize(quantize(x)).
    """
    shape = x.shape
    xf = x.astype(jnp.float32).reshape(*shape[:-1], shape[-1] // Q_BLOCK, Q_BLOCK)
    amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    delta = amax / 127.0
    # int8 values are computed against the *unrounded* f32 scale, but dequant
    # uses the f16-rounded scale stored in the block — exactly the host codec
    # (formats/quants.py quantize_q80) and the reference converter.
    inv = jnp.where(delta != 0, 1.0 / delta, 0.0)
    qv = jnp.clip(jnp.round(xf * inv), -127, 127)
    delta16 = delta.astype(jnp.float16).astype(jnp.float32)
    return (qv * delta16).reshape(shape).astype(x.dtype)
