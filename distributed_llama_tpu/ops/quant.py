"""Device-side quantized weights and the quantized matmul.

The reference's hot loop is `matmul_Q80_Q40_F32` — a Q80-quantized activation
row dotted against Q40 block-quantized weight rows with NEON/AVX intrinsics
(reference: src/nn/nn-cpu-ops.cpp:231-449). On TPU the same math maps to:

* weights stay resident in HBM as int8 values + per-block scales
  (`QuantTensor`) — ~4.5 bits/weight of traffic instead of 16/32;
* the matmul dequantizes on the fly and accumulates in f32 on the MXU, via
  the fused Pallas kernel (ops/pallas_q40.py) on TPU or a plain-XLA
  dequant+dot fallback.

Device layout (the "T" layout, chosen for TPU tiling): a logical
[out_features, in_features] Q40 weight is stored *transposed, block-major
and nibble-packed*:

    q: [in_features // 8, out_features]   int32  (8 weights per word)
    d: [in_features // 32, out_features]  f16    (per-block scales — the
                                                  file's f16 bits verbatim)

so that the innermost axis (out_features, the matmul's N) sits on the
128-lane dimension and each int32 word carries 8 nibble-packed weights of
one output column — true 4-bit residency (4.5 bits/weight with scales, the
reference's defining Q40 trait, nn-quants.hpp:64-72) at HALF the round-4
int8 layout's HBM traffic and footprint.

The packing is the FEATURE-SPLIT codec the Pallas kernels unpack with two
i32 mask ops + a pltpu.bitcast (~0.4 VPU ops/weight — probed as the only
formulation that stays DMA-bound; plane-extraction unpacks are VPU-bound
and s4 arrays can't cross jit boundaries on this platform, see
scripts/probe_int4*.py): within block b, feature s in [0,16) shares a byte
with feature s+16 —

    byte[b, s, o]  = (v[b, s, o] + 8) | ((v[b, s + 16, o] + 8) << 4)
    word[b, g, o]  = bytes 4g..4g+3 little-endian, rows flattened to
                     [nb*4, out]

matching pltpu.bitcast's probed byte->sublane expansion (word row r ->
int8 sublanes 4r..4r+3), so the in-kernel unpack is layout-free.
``x @ w.T`` becomes ``x @ dequant(q, d)`` with no transpose.

Activation quantization to Q80 exists only to *emulate the reference's
numerics* when parity testing (`quantize_q80_activations`); the production
path feeds bf16/f32 activations straight in — there is no bandwidth win from
quantizing activations that are already on-chip.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..formats.quants import Q_BLOCK


@jax.tree_util.register_pytree_node_class
@dataclass
class QuantTensor:
    """A Q40 weight on device in the packed T layout (see module docstring).

    q: [..., in//8, out] int32 nibble-packed words;  d: [..., in//32, out]
    f16 (the file's scale bits verbatim; f32 also accepted for hand-built
    test tensors). `unpack_q(q)` recovers the logical [..., in//32, 32, out]
    int8 values.
    """

    q: jnp.ndarray
    d: jnp.ndarray

    @property
    def out_features(self) -> int:
        return self.q.shape[-1]

    @property
    def in_features(self) -> int:
        return self.q.shape[-2] * 8

    @property
    def shape(self) -> tuple:
        """Logical [..., out_features, in_features] shape."""
        return (*self.q.shape[:-2], self.out_features, self.in_features)

    def tree_flatten(self):
        return (self.q, self.d), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


HGRP = Q_BLOCK // 2  # features per nibble plane (feature s pairs with s+16)


def pack_q(qt: np.ndarray) -> np.ndarray:
    """Host-side nibble pack: [..., nb, 32, out] int8 in [-8, 7] ->
    [..., nb*4, out] int32 feature-split words (module docstring codec)."""
    *lead, nb, _, out = qt.shape
    u = (qt.astype(np.int16) + 8).astype(np.uint32)
    b8 = u[..., :HGRP, :] | (u[..., HGRP:, :] << 4)  # [..., nb, 16, out]
    b4 = b8.reshape(*lead, nb, 4, 4, out)  # [..., b, g, k, o]
    w = (
        b4[..., 0, :]
        | (b4[..., 1, :] << 8)
        | (b4[..., 2, :] << 16)
        | (b4[..., 3, :] << 24)
    )
    return w.reshape(*lead, nb * 4, out).astype(np.uint32).view(np.int32)


def unpack_q(qp: jnp.ndarray) -> jnp.ndarray:
    """[..., nb*4, out] int32 packed words -> [..., nb, 32, out] int8 values
    in [-8, 7]. Plain XLA ops — the fallback/parity dequant path and tests;
    the Pallas kernels unpack in-kernel with pltpu.bitcast instead."""
    *lead, rows, out = qp.shape
    nb = rows // 4
    planes = [
        (jnp.bitwise_and(jax.lax.shift_right_logical(qp, 4 * j), 0xF) - 8).astype(
            jnp.int8
        )
        for j in range(8)
    ]
    # plane j holds feature 16*(j%2) + 4*g + j//2 of word row (b*4+g)
    pj = jnp.stack(planes, axis=-3)  # [..., 8(j), nb*4, out]
    pj = pj.reshape(*lead, 4, 2, nb, 4, out)  # [..., k, h, b, g, o]
    v = jnp.transpose(
        pj, (*range(len(lead)), len(lead) + 2, len(lead) + 1, len(lead) + 3, len(lead), len(lead) + 4)
    )  # [..., b, h, g, k, o]
    return v.reshape(*lead, nb, Q_BLOCK, out)


def q40_to_t_layout(q: np.ndarray, d: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Host-side transform from the file layout ([out, in//32, 32] values +
    [out, in//32] scales, `unpack_q40`) to the packed device T layout. The
    single source of truth for the layout contract — used by both the param
    loader and `quant_tensor_from_q40`. The scale plane keeps the file's f16
    dtype (bit-exact, and half the HBM traffic/footprint of an f32 plane)."""
    qt = np.ascontiguousarray(np.transpose(q, (1, 2, 0)))
    dt = np.ascontiguousarray(np.transpose(d, (1, 0))).astype(np.float16)
    return pack_q(qt), dt


def quant_tensor_from_q40(q: np.ndarray, d: np.ndarray) -> QuantTensor:
    """From host-side `unpack_q40` output reshaped to [out, in//32, 32] /
    [out, in//32] (the file layout): transpose into the device T layout."""
    qt, dt = q40_to_t_layout(q, d)
    return QuantTensor(q=jnp.asarray(qt), d=jnp.asarray(dt))


def quant_tensor_from_t(qt: np.ndarray, dt: np.ndarray) -> QuantTensor:
    """From UNPACKED T-layout host values (qt [..., nb, 32, out] int8,
    dt [..., nb, out]): pack and wrap — the constructor tests and hand-built
    fixtures use."""
    return QuantTensor(q=jnp.asarray(pack_q(qt)), d=jnp.asarray(dt))


def dequantize_t(w: QuantTensor, dtype=jnp.float32) -> jnp.ndarray:
    """Materialize the [..., in_features, out_features] matmul-ready matrix
    (the T layout's natural orientation). Single owner of the dequant
    formula: value = q * d broadcast over the 32-sublane axis, scale multiply
    in f32, one cast at the end."""
    qv = unpack_q(w.q)
    x = (qv.astype(jnp.float32) * w.d[..., None, :].astype(jnp.float32)).astype(dtype)
    return x.reshape(*w.q.shape[:-2], w.in_features, w.out_features)


def dequantize(w: QuantTensor, dtype=jnp.float32) -> jnp.ndarray:
    """Materialize the logical [..., out_features, in_features] weight."""
    return jnp.swapaxes(dequantize_t(w, dtype), -1, -2)


def _use_pallas() -> bool:
    if os.environ.get("DLT_NO_PALLAS"):
        return False
    return jax.default_backend() == "tpu"


@partial(jax.jit, static_argnames=("dtype",))
def _quant_matmul_xla(x, q, d, dtype):
    # w [in, out] dequantized on the fly; dequant multiply in f32 (scale
    # precision — f16 scales upcast exactly), operands cast to `dtype`
    qv = unpack_q(q)
    w = (qv.astype(jnp.float32) * d[:, None, :].astype(jnp.float32)).astype(dtype)
    w = w.reshape(qv.shape[-3] * Q_BLOCK, qv.shape[-1])
    precision = jax.lax.Precision.HIGHEST if dtype == jnp.float32 else None
    return jax.lax.dot_general(
        x.astype(dtype),
        w,
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=precision,
    )


def slice_layer(w, i):
    """w[i] of an all-layers stacked weight (QuantTensor-aware); identity
    when i is None. Single owner of the stack-slicing idiom (the transformer
    and the MoE dispatch both use it)."""
    if i is None or w is None:
        return w
    if isinstance(w, QuantTensor):
        return QuantTensor(
            q=jax.lax.dynamic_index_in_dim(w.q, i, 0, keepdims=False),
            d=jax.lax.dynamic_index_in_dim(w.d, i, 0, keepdims=False),
        )
    return jax.lax.dynamic_index_in_dim(w, i, 0, keepdims=False)


def quant_matmul(
    x: jnp.ndarray,
    w: QuantTensor,
    dtype=jnp.bfloat16,
    out_dtype=None,
    pallas=None,
    layer=None,
) -> jnp.ndarray:
    """``x @ w.T`` (logical): x [..., in_features] -> [..., out_features].

    `w` is either an unstacked (2D packed q) QuantTensor, or — with `layer`
    given — an all-layers stack (3D q, [L, nb*4, out]): the matmul then uses
    ``w[layer]`` *without materializing the slice* (the Pallas kernel offsets
    its DMA by a scalar-prefetched layer index; the XLA fallback pays a
    dynamic-slice). This is how the transformer's `lax.scan` over layers
    avoids copying every layer's weights each step. Expert stacks go through
    models.transformer._expert_matmul.

    `dtype` is the MXU operand dtype (bf16 fast path, f32 parity path);
    accumulation is always f32. `pallas`: None = auto (fused Pallas kernel on
    TPU when tile-aligned), False = force the XLA dequant+dot path (required
    under GSPMD sharding — see ModelConfig.use_pallas), True = force-enable.
    """
    from .pallas_q40 import (
        q40_matmul_aligned,
        q40_matmul_pallas,
        q40_matmul_pallas_i8,
        q40_matmul_pallas_stacked,
        q40_matmul_pallas_stacked_i8,
        q40_stacked_aligned,
    )

    # "interpret" (cfg.pallas_arg): force-enabled kernels in interpret mode —
    # lets CPU tests drive the exact Pallas code path without TPU hardware.
    # The mode rides in the pallas argument (and thus the jit cache key via
    # cfg) rather than being read from the environment at trace time.
    interpret = pallas == "interpret"
    if interpret:
        pallas = True
    if pallas is None:
        # Auto mode never hands an f32 matmul to the Pallas kernels: their
        # in-kernel dots run at the MXU's default precision (~bf16 one-pass),
        # which silently degrades the f32 *parity* path to bf16-grade on real
        # TPUs (measured: 5e-3 abs error on a 256x384 matmul vs 2e-7 for the
        # XLA path with Precision.HIGHEST). The XLA fallback is exact and the
        # parity path is not performance-critical. Explicit pallas=True /
        # "interpret" still force the kernels (interpret mode executes them
        # exactly, so CPU kernel tests keep their f32 references).
        pallas = _use_pallas() and dtype != jnp.float32
    # decode-sized batches on the approximate bf16 path: the int8-MXU
    # kernel — weights hit the MXU as int8 with per-block scale combine,
    # removing the per-element VPU dequant (measured 17x on square shapes).
    # The kernel's block-diagonal lhs stacks rows on the sublane axis, so
    # any rows <= 8 qualify (beyond that, the bf16-dequant kernel's
    # per-element dequant amortizes over rows and wins). Activation
    # numerics = the reference's default `--buffer-float-type q80`; the
    # f32 parity paths never take this branch.
    rows = 1
    for s in x.shape[:-1]:
        rows *= s
    use_i8 = pallas and rows <= 8 and dtype == jnp.bfloat16
    if layer is not None and w.q.ndim == 3:
        stack_aligned = (
            x.shape[-1] == w.in_features
            and q40_stacked_aligned(w.in_features, w.out_features)
        )
        if pallas and stack_aligned:
            if use_i8:
                out = q40_matmul_pallas_stacked_i8(
                    x, w.q, w.d, layer, interpret=interpret
                )
            else:
                out = q40_matmul_pallas_stacked(
                    x, w.q, w.d, layer, dtype=dtype, interpret=interpret
                )
        else:
            q = jax.lax.dynamic_index_in_dim(w.q, layer, 0, keepdims=False)
            d = jax.lax.dynamic_index_in_dim(w.d, layer, 0, keepdims=False)
            out = _quant_matmul_xla(x, q, d, dtype)
        return out.astype(out_dtype if out_dtype is not None else x.dtype)
    assert w.q.ndim == 2, "quant_matmul handles unstacked weights only"
    if pallas and q40_matmul_aligned(x, w):
        if use_i8:
            out = q40_matmul_pallas_i8(x, w.q, w.d, interpret=interpret)
        else:
            out = q40_matmul_pallas(x, w.q, w.d, dtype=dtype, interpret=interpret)
    else:
        out = _quant_matmul_xla(x, w.q, w.d, dtype)
    return out.astype(out_dtype if out_dtype is not None else x.dtype)


def quantize_q80_activations(x: jnp.ndarray) -> jnp.ndarray:
    """Round-trip x through Q80 (per-32-block int8 + f16 scale) numerics.

    Emulates the reference's `--buffer-float-type q80` activation path
    (reference: quantizeF32toQ80, src/nn/nn-quants.cpp:67-…) for parity
    testing: returns values equal to dequantize(quantize(x)).
    """
    shape = x.shape
    xf = x.astype(jnp.float32).reshape(*shape[:-1], shape[-1] // Q_BLOCK, Q_BLOCK)
    amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    delta = amax / 127.0
    # int8 values are computed against the *unrounded* f32 scale, but dequant
    # uses the f16-rounded scale stored in the block — exactly the host codec
    # (formats/quants.py quantize_q80) and the reference converter.
    inv = jnp.where(delta != 0, 1.0 / delta, 0.0)
    qv = jnp.clip(jnp.round(xf * inv), -127, 127)
    delta16 = delta.astype(jnp.float16).astype(jnp.float32)
    return (qv * delta16).reshape(shape).astype(x.dtype)
