"""dlt-lint: the AST lint enforcing project rules the runtime can't.

Rules (ids in parentheses; suppress a line with ``# dlt: allow(<rule>)``,
comma-separate for several — the pragma documents WHY at the site):

* **bare-except** — ``except:`` catches SystemExit/KeyboardInterrupt and
  hides the watchdog's StallError; always name the exception;
* **swallowed-exception** — ``except Exception:`` (or BaseException) whose
  body is only ``pass``: a failure mode the operator can never see. Either
  narrow the type, handle it, or pragma it with the reason it is safe;
* **lock-with** — lock/condition ``.acquire()`` called explicitly: lock
  discipline in this codebase is ``with`` only (a raised exception between
  acquire and release leaks the lock and wedges the Batcher/gateway
  forever). Applies to receivers whose name looks lock-ish
  (lock/cond/mutex/sem);
* **thread-daemon** — ``threading.Thread(...)`` without an explicit
  ``daemon=``: a forgotten non-daemon thread turns every crash into a
  hang at interpreter exit (the watchdog/prober/writer threads must never
  outlive the process). Thread *subclasses* must pass ``daemon=`` in their
  ``super().__init__`` call;
* **float64** — ``float64`` dtype literals in device-side packages
  (ops/models/parallel/runtime): one f64 constant silently promotes a
  whole matmul chain (the graph auditor catches the traced result; this
  catches the source). Host-side precomputation (rope tables) carries a
  pragma;
* **host-sync** — ``np.asarray`` / ``np.array`` / ``jax.device_get`` /
  ``<device>.memory_stats()`` in the hot packages (runtime/parallel): each
  is a potential blocking device→host sync (or a runtime round trip) worth
  ~100 ms of tunnel latency. The sanctioned fetch sites carry pragmas —
  which doubles as the canonical list of blessed host syncs the
  host_sync_guard sanitizer allows (``memory_stats`` is blessed only at
  the cold-path HBM-ledger site, runtime/profiling.py);
* **trace-hot-emit** — ``trace.event(...)`` / ``TRACER.event(...)`` inside
  a ``for``/``while`` loop body in the emitting packages
  (runtime/parallel/server), or an emit call constructing a dict literal
  anywhere in them: per-iteration span emission must go through a
  pre-bound ``Trace.bind(...)`` / ``Tracer.bind_global(...)`` emitter
  (one tuple append per event — no name/keys re-tupling, no dict
  allocation in the decode/spec_step/Batcher inner loops;
  runtime/tracing.py Emitter). The server scope exists because the
  Batcher's step loop and the gateway's retry loop are exactly where the
  goodput-ledger and batch-timeline emits live; their sanctioned
  once-per-request/once-per-decision cold sites carry pragmas;
* **sentinel-release** — a class that subscribes a ``RecompileSentinel``
  (``self.x = RecompileSentinel(...).start()``) without a
  ``close``/``stop``/``__exit__`` method that calls ``self.x.stop()``:
  compile-event subscriptions are PROCESS-global (the jax registry has no
  unregister), so a teardown path that forgets the release leaks a
  sealed sentinel past its engine's lifetime — and a leaked SEALED FATAL
  sentinel kills every later engine build in the process (the
  cross-suite-pollution class the supervisor's rebuild path releases
  explicitly; runtime/engine.py ``close()`` is the reference shape);
* **env-surface** — an ``os.environ`` / ``getenv`` read of a ``DLT_*``
  variable whose name is missing from ``server/api.py``'s
  ``DLT_ENV_SURFACE`` registry (the ``/debug/config`` payload's declared
  knob surface) or from README/docs: every env knob the package reads
  must be debuggable from a running replica and documented, or it is
  config-surface drift — a flag operators cannot discover. The rule only
  fires when lint runs with repo-root context (``lint_paths``/CLI; plain
  ``lint_source`` has no cross-file registry to check against);
* **thread-release** — the sentinel-release rule's thread edition: a
  class holding a gateway-owned background loop (``FleetScraper``,
  ``Autoscaler``, ``HealthProber``, ``GatewayPeering`` — directly or via
  a local alias, ``x = FleetScraper(...); self.s = x``) without a
  ``close``/``stop``/``shutdown``/``server_close``/``__exit__`` method
  calling ``self.s.stop()``: these loops actuate against the fleet
  (scrape, drain, gossip), so one leaked past its server's teardown
  keeps scraping/draining from a gateway that no longer exists — and an
  in-process gateway restart (the crash-only tests instantiate the
  server twice) doubles every control loop.

The CLI lives at ``scripts/dlt_lint.py``; CI runs it over the tree.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path

ALL_RULES = (
    "bare-except",
    "swallowed-exception",
    "lock-with",
    "thread-daemon",
    "float64",
    "host-sync",
    "trace-hot-emit",
    "sentinel-release",
    "thread-release",
    "env-surface",
)

_PRAGMA_RE = re.compile(r"#\s*dlt:\s*allow\(([^)]*)\)")
_LOCKISH_RE = re.compile(r"(lock|cond|mutex|sem)", re.IGNORECASE)
_TRACEISH_RE = re.compile(r"^(tr|trace|tracer|TRACER)$")

#: packages where a float64 literal is device-side poison
FLOAT64_SCOPE = ("ops", "models", "parallel", "runtime", "formats")
#: packages whose np.asarray/np.array sites are potential host syncs
HOST_SYNC_SCOPE = ("runtime", "parallel")
#: packages whose loops must emit spans through pre-bound emitters: the
#: hot packages PLUS the server (Batcher step loop, gateway retry loop,
#: router decision path (server/router.py), disagg transfer path, and the
#: fleet control plane — scheduler admission/preemption loops
#: (server/scheduler.py), autoscaler ticks (server/autoscaler.py), the
#: load twin's stub decode loop (server/loadtwin.py) — the goodput-ledger
#: /batch-timeline/gw_route/kv_transfer/scheduler-decision emission
#: sites). The KV movement layer (runtime/kv_transport.py) rides the
#: `runtime` prefix: its transport fetch loops, the per-segment
#: insert/extract loops, AND the receipt-verification checksum loop
#: (verify_transfer's per-doubling-segment pass — emit-free by design:
#: the one `kv_integrity` event per fetch lands in DisaggClient.fetch
#: AFTER the peer loop) are in scope like every other hot path.
TRACE_EMIT_SCOPE = ("runtime", "parallel", "server")
#: packages whose classes must pair a sentinel subscription with a
#: teardown release (engine lifecycles live here)
SENTINEL_SCOPE = ("runtime", "server", "analysis")
#: class names whose instances are gateway-owned background loops: held
#: as a ``self.<attr>`` they must be released by a teardown method
#: (thread-release); all four expose ``.stop()``
THREAD_OWNER_CLASSES = (
    "FleetScraper", "Autoscaler", "HealthProber", "GatewayPeering",
)
#: method names that count as a teardown site for thread-release —
#: sentinel-release's set plus the http.server lifecycle pair the
#: gateway/api servers implement
RELEASE_METHODS = (
    "close", "stop", "shutdown", "server_close", "__exit__", "__del__",
)
#: packages whose DLT_* env reads must be declared + documented
#: (env-surface); scripts/ are operator-side and read what they document
#: themselves
ENV_SURFACE_SCOPE = ("distributed_llama_tpu",)
#: DLT_* names in markdown docs count as documented wherever they appear
_DOC_ENV_RE = re.compile(r"\bDLT_[A-Z0-9_]+\b")


def declared_env_surface(root) -> set | None:
    """The ``DLT_ENV_SURFACE`` registry tuple from server/api.py (the
    /debug/config declared knob surface), parsed statically; None when the
    file or registry is absent (rule degrades to docs-only)."""
    api = Path(root) / "distributed_llama_tpu" / "server" / "api.py"
    if not api.exists():
        return None
    try:
        tree = ast.parse(api.read_text())
    except SyntaxError:
        return None
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "DLT_ENV_SURFACE"
            for t in node.targets
        ):
            try:
                return set(ast.literal_eval(node.value))
            except (ValueError, SyntaxError):
                return None
    return None


def documented_env_vars(root) -> set | None:
    """Every DLT_* name mentioned anywhere in README.md / docs/*.md; None
    when no docs exist to check against."""
    root = Path(root)
    files = [root / "README.md"] + sorted((root / "docs").glob("*.md"))
    texts = [f.read_text() for f in files if f.exists()]
    if not texts:
        return None
    return set(_DOC_ENV_RE.findall("\n".join(texts)))


def _owner_ctor_name(call: ast.Call) -> str | None:
    """The THREAD_OWNER_CLASSES class name when ``call`` is its ctor (or
    a ``.start()`` chained onto one); None otherwise."""
    d = _dotted(call.func)
    for name in THREAD_OWNER_CLASSES:
        if d == name or d.endswith("." + name):
            return name
    if (
        isinstance(call.func, ast.Attribute)
        and call.func.attr == "start"
        and isinstance(call.func.value, ast.Call)
    ):
        return _owner_ctor_name(call.func.value)
    return None


def _is_sentinel_ctor(call: ast.Call) -> bool:
    """``RecompileSentinel(...)`` or a ``.start()`` chained onto one."""
    d = _dotted(call.func)
    if d.endswith("RecompileSentinel"):
        return True
    if (
        isinstance(call.func, ast.Attribute)
        and call.func.attr == "start"
        and isinstance(call.func.value, ast.Call)
    ):
        return _is_sentinel_ctor(call.func.value)
    return False


@dataclasses.dataclass(frozen=True)
class Violation:
    path: str
    line: int
    rule: str
    msg: str

    def __str__(self):
        return f"{self.path}:{self.line}: [{self.rule}] {self.msg}"


def _pragmas(source: str) -> dict:
    """line -> set of allowed rule ids (``*`` = all)."""
    out: dict = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = _PRAGMA_RE.search(line)
        if m:
            out[i] = {r.strip() for r in m.group(1).split(",") if r.strip()}
    return out


def _receiver_name(node: ast.AST) -> str:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _dotted(node: ast.AST) -> str:
    """Best-effort dotted name of a call target ('threading.Thread')."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


class _Linter(ast.NodeVisitor):
    def __init__(self, path: str, rel: str, source: str, env_surface=None):
        self.path = path
        self.rel = rel  # repo-relative path, for scope decisions
        self.pragmas = _pragmas(source)
        self.violations: list = []
        self._thread_classes: list = []  # ClassDef stack: is-Thread-subclass
        self._loop_depth = 0  # for/while nesting (trace-hot-emit)
        # (declared, documented) DLT_* name sets for env-surface, or None
        # when lint runs without repo-root context (rule off)
        self.env_surface = env_surface

    # -- plumbing -----------------------------------------------------------

    def _in_scope(self, packages) -> bool:
        parts = Path(self.rel).parts
        return any(p in parts for p in packages)

    def _allowed(self, rule: str, node: ast.AST) -> bool:
        start = getattr(node, "lineno", 0)
        end = getattr(node, "end_lineno", start) or start
        for line in range(start, end + 1):
            allowed = self.pragmas.get(line)
            if allowed and (rule in allowed or "*" in allowed):
                return True
        # a pragma-only line directly above the statement also applies
        allowed = self.pragmas.get(start - 1)
        return bool(allowed and (rule in allowed or "*" in allowed))

    def _flag(self, rule: str, node: ast.AST, msg: str):
        if not self._allowed(rule, node):
            self.violations.append(
                Violation(self.path, getattr(node, "lineno", 0), rule, msg)
            )

    # -- rules --------------------------------------------------------------

    def visit_ExceptHandler(self, node: ast.ExceptHandler):
        if node.type is None:
            self._flag(
                "bare-except", node,
                "bare `except:` — name the exception (it catches "
                "KeyboardInterrupt/SystemExit and hides StallError)",
            )
        else:
            names = []
            types = node.type.elts if isinstance(node.type, ast.Tuple) else [node.type]
            for t in types:
                names.append(_receiver_name(t))
            body_is_noop = all(
                isinstance(s, ast.Pass)
                or (
                    isinstance(s, ast.Expr)
                    and isinstance(s.value, ast.Constant)
                    and s.value.value is Ellipsis
                )
                for s in node.body
            )
            if body_is_noop and any(n in ("Exception", "BaseException") for n in names):
                self._flag(
                    "swallowed-exception", node,
                    "`except Exception: pass` swallows every failure mode — "
                    "narrow it, handle it, or pragma it with the reason",
                )
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        # lock-with: explicit .acquire() on lock-ish receivers
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "acquire"
            and _LOCKISH_RE.search(_receiver_name(node.func.value))
        ):
            self._flag(
                "lock-with", node,
                f"explicit {_dotted(node.func)}() — locks are taken via "
                "`with` only (exception safety)",
            )
        # thread-daemon: Thread(...) constructors
        dotted = _dotted(node.func)
        if dotted in ("threading.Thread", "Thread"):
            if not any(kw.arg == "daemon" for kw in node.keywords):
                self._flag(
                    "thread-daemon", node,
                    "Thread(...) without an explicit daemon= — an "
                    "undeclared non-daemon thread hangs process exit",
                )
        # thread-daemon: Thread subclass super().__init__ without daemon=
        if (
            dotted.endswith("__init__")
            and isinstance(node.func, ast.Attribute)
            and isinstance(node.func.value, ast.Call)
            and _dotted(node.func.value.func) == "super"
            and self._thread_classes
            and self._thread_classes[-1]
        ):
            if not any(kw.arg == "daemon" for kw in node.keywords):
                self._flag(
                    "thread-daemon", node,
                    "Thread subclass super().__init__ without daemon= — "
                    "declare the thread's lifetime explicitly",
                )
        # float64 dtype literal in device-side scope
        if self._in_scope(FLOAT64_SCOPE):
            for kw in node.keywords:
                if (
                    kw.arg == "dtype"
                    and isinstance(kw.value, ast.Constant)
                    and kw.value.value in ("float64", "f8", "double")
                ):
                    self._flag(
                        "float64", kw.value,
                        "float64 dtype literal in a device-side package",
                    )
        # host-sync: potential blocking fetches in hot packages
        if self._in_scope(HOST_SYNC_SCOPE):
            if dotted in ("np.asarray", "np.array", "numpy.asarray",
                          "numpy.array", "jax.device_get"):
                self._flag(
                    "host-sync", node,
                    f"{dotted}(...) in a hot package is a potential "
                    "blocking device->host sync — pragma the sanctioned "
                    "sites (see docs/ANALYSIS.md)",
                )
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "memory_stats"
            ):
                self._flag(
                    "host-sync", node,
                    ".memory_stats() in a hot package is a device-runtime "
                    "round trip — only the cold-path HBM-ledger site "
                    "(runtime/profiling.py) is sanctioned; pragma it",
                )
        # trace-hot-emit: span emission discipline in emitting packages —
        # per-iteration .event() calls re-tuple name/keys every time and
        # invite dict construction; loops must use a pre-bound
        # Trace.bind(...) / Tracer.bind_global(...) emitter (one tuple
        # append per event)
        if (
            self._in_scope(TRACE_EMIT_SCOPE)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "event"
            and _TRACEISH_RE.match(_receiver_name(node.func.value) or "")
        ):
            if self._loop_depth > 0:
                self._flag(
                    "trace-hot-emit", node,
                    ".event(...) inside a loop in a hot package — bind a "
                    "pre-bound emitter outside the loop (Trace.bind) and "
                    "call it per iteration",
                )
            has_dict = any(
                isinstance(a, (ast.Dict, ast.DictComp)) for a in node.args
            ) or any(
                isinstance(kw.value, (ast.Dict, ast.DictComp))
                for kw in node.keywords
            )
            if has_dict:
                self._flag(
                    "trace-hot-emit", node,
                    "dict construction in a span emit call — pass scalar "
                    "vals against pre-bound keys instead",
                )
        # env-surface: DLT_* env reads must be on the declared /debug/config
        # surface and documented
        if dotted in ("os.environ.get", "environ.get", "os.getenv", "getenv"):
            if (
                node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
                and node.args[0].value.startswith("DLT_")
            ):
                self._check_env_surface(node.args[0].value, node)
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript):
        # env-surface: os.environ["DLT_X"] subscript reads
        if (
            _dotted(node.value) in ("os.environ", "environ")
            and isinstance(node.slice, ast.Constant)
            and isinstance(node.slice.value, str)
            and node.slice.value.startswith("DLT_")
        ):
            self._check_env_surface(node.slice.value, node)
        self.generic_visit(node)

    def _check_env_surface(self, var: str, node: ast.AST):
        if self.env_surface is None or not self._in_scope(ENV_SURFACE_SCOPE):
            return
        declared, documented = self.env_surface
        missing = []
        if declared is not None and var not in declared:
            missing.append(
                "api.py's DLT_ENV_SURFACE registry (the /debug/config "
                "declared knob surface)"
            )
        if documented is not None and var not in documented:
            missing.append("README/docs")
        if missing:
            self._flag(
                "env-surface", node,
                f"{var} is read here but missing from "
                f"{' and from '.join(missing)} — every DLT_* knob must be "
                "discoverable from a running replica and documented",
            )

    def _visit_loop(self, node):
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    visit_For = _visit_loop
    visit_While = _visit_loop

    def visit_Attribute(self, node: ast.Attribute):
        if self._in_scope(FLOAT64_SCOPE) and node.attr == "float64":
            self._flag(
                "float64", node,
                "float64 literal in a device-side package (one f64 "
                "constant promotes the whole chain)",
            )
        self.generic_visit(node)

    def visit_ClassDef(self, node: ast.ClassDef):
        is_thread = any(
            _dotted(b) in ("threading.Thread", "Thread") for b in node.bases
        )
        self._thread_classes.append(is_thread)
        if self._in_scope(SENTINEL_SCOPE):
            self._check_sentinel_release(node)
            self._check_thread_release(node)
        self.generic_visit(node)
        self._thread_classes.pop()

    @staticmethod
    def _walk_own(node):
        """ast.walk, but skipping nested ClassDef subtrees — a nested
        class's sentinel belongs to the nested class (visit_ClassDef
        checks it on its own visit), not to the enclosing one."""
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                continue
            yield child
            yield from _Linter._walk_own(child)

    def _check_sentinel_release(self, cls: ast.ClassDef):
        """sentinel-release: every ``self.<attr> = RecompileSentinel(...)``
        in this class must have a teardown method (close/stop/__exit__)
        that calls ``self.<attr>.stop()`` — the subscription is process-
        global and a leaked sealed sentinel outlives its engine."""
        holders: list = []
        for sub in self._walk_own(cls):
            if not (
                isinstance(sub, ast.Assign)
                and isinstance(sub.value, ast.Call)
                and _is_sentinel_ctor(sub.value)
            ):
                continue
            for tgt in sub.targets:
                if (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                ):
                    holders.append((tgt.attr, sub))
        if not holders:
            return
        released: set = set()
        for sub in cls.body:
            if (
                isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef))
                and sub.name in ("close", "stop", "__exit__", "__del__")
            ):
                for c in ast.walk(sub):
                    if (
                        isinstance(c, ast.Call)
                        and isinstance(c.func, ast.Attribute)
                        and c.func.attr in ("stop", "close")
                        and isinstance(c.func.value, ast.Attribute)
                        and isinstance(c.func.value.value, ast.Name)
                        and c.func.value.value.id == "self"
                    ):
                        released.add(c.func.value.attr)
        for attr, node in holders:
            if attr not in released:
                self._flag(
                    "sentinel-release", node,
                    f"self.{attr} subscribes a RecompileSentinel but no "
                    "close/stop/__exit__ method calls "
                    f"self.{attr}.stop() — a leaked sealed sentinel "
                    "outlives the engine and kills later engine builds",
                )

    def _check_thread_release(self, cls: ast.ClassDef):
        """thread-release: every ``self.<attr>`` holding a gateway-owned
        background loop (THREAD_OWNER_CLASSES, directly or via a local
        alias) must be released — ``self.<attr>.stop()`` (or
        ``.close()``/``.join()``) from a RELEASE_METHODS teardown. A
        leaked scraper/autoscaler/prober/peer-sync loop keeps actuating
        against the fleet after its gateway is gone — and doubles on an
        in-process restart."""
        # local aliases: x = FleetScraper(...), possibly .start()-chained
        aliases: set = set()
        for sub in self._walk_own(cls):
            if (
                isinstance(sub, ast.Assign)
                and isinstance(sub.value, ast.Call)
                and _owner_ctor_name(sub.value)
            ):
                for tgt in sub.targets:
                    if isinstance(tgt, ast.Name):
                        aliases.add(tgt.id)
        holders: list = []
        for sub in self._walk_own(cls):
            if not isinstance(sub, ast.Assign):
                continue
            value = sub.value
            owner = None
            if isinstance(value, ast.Call):
                owner = _owner_ctor_name(value)
            elif isinstance(value, ast.Name) and value.id in aliases:
                owner = value.id
            if not owner:
                continue
            for tgt in sub.targets:
                if (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                ):
                    holders.append((tgt.attr, owner, sub))
        if not holders:
            return
        released: set = set()
        for sub in cls.body:
            if (
                isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef))
                and sub.name in RELEASE_METHODS
            ):
                for c in ast.walk(sub):
                    if (
                        isinstance(c, ast.Call)
                        and isinstance(c.func, ast.Attribute)
                        and c.func.attr in ("stop", "close", "join")
                        and isinstance(c.func.value, ast.Attribute)
                        and isinstance(c.func.value.value, ast.Name)
                        and c.func.value.value.id == "self"
                    ):
                        released.add(c.func.value.attr)
        for attr, owner, node in holders:
            if attr not in released:
                self._flag(
                    "thread-release", node,
                    f"self.{attr} holds a {owner} background loop but no "
                    "close/stop/shutdown/server_close method calls "
                    f"self.{attr}.stop() — a leaked control loop keeps "
                    "actuating against the fleet after its gateway dies",
                )


def lint_source(
    source: str, path: str, rel: str | None = None, env_surface=None
) -> list:
    tree = ast.parse(source, filename=path)
    linter = _Linter(
        path, rel if rel is not None else path, source, env_surface=env_surface
    )
    linter.visit(tree)
    return linter.violations


def lint_file(path, root=None, env_surface=None) -> list:
    p = Path(path)
    rel = str(p.relative_to(root)) if root else str(p)
    if env_surface is None and root is not None:
        env_surface = (declared_env_surface(root), documented_env_vars(root))
    return lint_source(p.read_text(), str(p), rel, env_surface=env_surface)


def lint_paths(paths, root=None, exclude=("tests", "__pycache__")) -> list:
    """Lint every .py under `paths` (files or directories). With a repo
    `root`, the cross-file env-surface context (DLT_ENV_SURFACE registry +
    docs) is resolved ONCE and shared across every file."""
    env_surface = None
    if root is not None:
        env_surface = (declared_env_surface(root), documented_env_vars(root))
    out: list = []
    for path in paths:
        p = Path(path)
        files = [p] if p.is_file() else sorted(p.rglob("*.py"))
        for f in files:
            if any(part in exclude for part in f.parts):
                continue
            out.extend(lint_file(f, root=root, env_surface=env_surface))
    return out


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="dlt-lint", description=__doc__)
    ap.add_argument("paths", nargs="*", help="files/dirs (default: the package + scripts)")
    ap.add_argument("--root", default=None, help="repo root for scope-relative paths")
    args = ap.parse_args(argv)

    root = Path(args.root) if args.root else Path(__file__).resolve().parents[2]
    paths = [Path(p) for p in args.paths] or [
        root / "distributed_llama_tpu",
        root / "scripts",
        root / "bench.py",
        root / "launch.py",
    ]
    violations = lint_paths([p for p in paths if p.exists()], root=root)
    for v in violations:
        print(v)
    if violations:
        print(f"dlt-lint: {len(violations)} violation(s)")
        return 1
    print("dlt-lint: clean")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
