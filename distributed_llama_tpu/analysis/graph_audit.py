"""Trace-time graph auditor: abstract-eval the engine's compiled step
programs and assert the contracts nothing else checks.

The engine's performance model rests on properties of the *traced graph*
that are invisible at the Python layer and silently violable:

* **dtype discipline** — no float64 anywhere (a stray literal promotes the
  whole matmul chain), and in bfloat16 engines the quantized (Q40/int8)
  projection matmuls must run in the compute dtype: the only sanctioned
  f32×f32 matmul is the attention probs·V product (ops/attention.py keeps
  it f32 for numerical stability). An accidental upcast of a projection
  shows up here as an extra f32 dot and fails the budget;
* **collective budget** — the explicit-collective pipeline path emits an
  exactly predictable set of psum/all_gather/ppermute per step
  (parallel/pipeline.py); a regression that inserts a surprise all-gather
  (or drops a psum) changes the count and fails loudly. Non-mesh and GSPMD
  programs must contain zero explicit collectives;
* **KV donation** — every decode/prefill entry point donates the cache; a
  lost `donate_argnames` doubles HBM traffic and peak memory without any
  functional symptom. The lowered MLIR carries `tf.aliasing_output` markers
  only when donation survived;
* **sharding consistency** — on pipeline meshes every per-layer weight
  stack must shard its layer axis over `pp` and the cache must match
  `pp_cache_sharding`, or stage handoff silently computes on replicated
  (wrong) slices.

Everything here is `jax.make_jaxpr` / `.lower()` only: no compilation, no
execution, no device transfers — cheap enough for CI on a tiny config and
for a preflight check on a real model.

Run standalone: ``python -m distributed_llama_tpu.analysis.graph_audit``
(builds a tiny synthetic model and audits its full warm-key ladder).
"""

from __future__ import annotations

import dataclasses
from collections import Counter

import jax
import jax.numpy as jnp
import numpy as np

try:  # jax >= 0.4.x keeps these importable from jax.core (newer: jax.extend)
    from jax.extend.core import ClosedJaxpr, Jaxpr  # type: ignore
except ImportError:
    from jax.core import ClosedJaxpr, Jaxpr  # type: ignore


from .jaxpr_tools import (  # noqa: F401  (re-exported: the walking layer
    COLLECTIVE_PRIMS,  # lived here before analysis/jaxpr_tools.py split out)
    _dtype_name,
    _sub_jaxprs,
    collective_counts,
    dot_input_census,
    dtype_census,
    iter_eqns,
    pool_gather_count,
)


class GraphAuditError(AssertionError):
    """One or more audited programs violated a graph contract."""


#: MLIR attributes jax emits on donated arguments: `tf.aliasing_output`
#: when the input/output aliasing is resolved at lowering (single-device),
#: `jax.buffer_donor` when it is deferred to compile (sharded programs)
DONATION_MARKERS = ("tf.aliasing_output", "jax.buffer_donor")


# -- warm-key ladder --------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class LadderEntry:
    """One compiled-program identity on the warm ladder.

    kind: "prefill" (whole-batch chunk), "decode" (solo chunked decode),
    "prefill_row" (BatchSession admission prefill), "batch_decode"
    (BatchSession per-row decode chunk), "verify" / "verify_row" (the
    speculative-decoding verify forwards — logits at every drafted
    position, scalar vs per-row positions; runtime/speculative.py),
    "prefix_extract" /"prefix_copy" / "prefix_copy_row" (the prefix
    cache's publish/splice copy programs — contiguous engines only),
    "page_copy" (the paged layout's copy-on-write page copy,
    runtime/paged_kv.py — paged engines share prefix pages host-side and
    carry no prefix copy programs). `size` is the token-chunk size,
    decode n_steps, draft bucket + 1, prefix bucket, or page size;
    `kv_len` the static KV read bucket (== size for prefix/page
    programs). On paged engines every forward-shaped program additionally
    takes the [b, slots] int32 page table as a small operand — the page
    count a bucket gathers is kv_len/page_size, so the same triples pin
    the paged shapes."""

    kind: str
    size: int
    kv_len: int


def warm_key_ladder(engine) -> list:
    """Every (kind, size, kv_bucket) program `InferenceEngine.warmup()`
    compiles. The enumeration itself lives on the engine
    (`InferenceEngine.warm_plan` — the full reachable cross product of
    chunk/decode sizes with kv buckets, plus the prefix-cache copy ladder);
    warmup executes from the same plan, so the auditor and the compiled set
    cannot drift. If they ever did, the recompile sentinel would fire in
    production — the two are tested against each other."""
    return [LadderEntry(kind, size, kv) for kind, size, kv in engine.warm_plan()]


# -- tracing one ladder entry ----------------------------------------------


def _sds(shape, dtype):
    return jax.ShapeDtypeStruct(shape, dtype)


def _paged_args(engine):
    """(page_table ShapeDtypeStruct, page_size) for a paged engine, or
    (None, None) — the extra operands every forward-shaped paged program
    carries (runtime/paged_kv.py)."""
    if not getattr(engine, "paged", False):
        return None, None
    return (
        _sds((engine.batch, engine.page_pool.max_slots), jnp.int32),
        engine.page_size,
    )


def _grammar_sds(engine):
    """The [S, V] int32 mask-table ShapeDtypeStruct a grammar-capable
    engine threads into every decode/verify dispatch (runtime/grammar.py,
    engine._gr_operand), or None when the arena is off. The per-row state
    operand's shape differs per kind ([b] for decode chunks, [b, t] for
    verify) — each tracing arm builds its own."""
    gr = getattr(engine, "grammar", None)
    if gr is None:
        return None
    return _sds(gr.table.shape, jnp.int32)


def trace_entry(engine, entry: LadderEntry):
    """`jax.make_jaxpr` of the program `entry` names, with abstract token /
    position inputs and the engine's real params/cache closed over (tracing
    reads shapes and shardings; nothing executes). On a grammar-capable
    engine the decode/batch_decode/verify arms carry the mask-table operand
    pair — the production dispatches always thread it there, so a
    fingerprint taken without it would hash a program serving never runs."""
    cfg, b = engine.cfg, engine.batch
    pt_sds, ps = _paged_args(engine)
    if entry.kind == "prefill":
        if engine.paged and not engine.use_pipeline:
            from ..models.transformer import forward

            fn = lambda toks, pos, pt: forward(
                cfg, engine.params, engine.rope, engine.cache, toks, pos,
                logits_mode="last", kv_len=entry.kv_len, page_table=pt,
                page_size=ps,
            )
            return jax.make_jaxpr(fn)(
                _sds((b, entry.size), jnp.int32), _sds((), jnp.int32), pt_sds
            )
        # pipeline engines (paged included — engine._forward threads the
        # page-table operand itself) and contiguous non-mesh engines
        fn = lambda toks, pos: engine._forward(
            toks, pos, logits_mode="last", kv_len=entry.kv_len
        )
        return jax.make_jaxpr(fn)(
            _sds((b, entry.size), jnp.int32), _sds((), jnp.int32)
        )
    if entry.kind == "decode":
        from ..runtime.engine import _greedy_prng_key

        key = _greedy_prng_key()
        if engine.use_pipeline:
            from ..parallel.pipeline import pipeline_decode_chunk

            fn = lambda tok, pos: pipeline_decode_chunk(
                cfg, engine.mesh, engine.params, engine.rope, engine.cache,
                tok, pos, key, n_steps=entry.size, temperature=0.0,
                topp=0.9, kv_len=entry.kv_len,
                page_table=engine._pt_operand() if engine.paged else None,
                page_size=ps,
            )
        else:
            from ..runtime.decode import decode_chunk

            # mirror engine._decode_chunk_any: paged engines add the page
            # table, grammar-capable engines ALWAYS add the (mask table,
            # [b] states) pair — both are part of the compiled shape
            gt_sds = _grammar_sds(engine)
            extra = [pt_sds] if engine.paged else []
            if gt_sds is not None:
                extra += [gt_sds, _sds((b,), jnp.int32)]

            def fn(tok, pos, *ops):
                it = iter(ops)
                pt = next(it) if engine.paged else None
                gtab = next(it) if gt_sds is not None else None
                gst = next(it) if gt_sds is not None else None
                return decode_chunk(
                    cfg, engine.params, engine.rope, engine.cache, tok, pos,
                    key, n_steps=entry.size, temperature=0.0, topp=0.9,
                    kv_len=entry.kv_len, page_table=pt, page_size=ps,
                    grammar_table=gtab, grammar_state=gst,
                )

            return jax.make_jaxpr(fn)(
                _sds((b,), jnp.int32), _sds((), jnp.int32), *extra
            )
        return jax.make_jaxpr(fn)(_sds((b,), jnp.int32), _sds((), jnp.int32))
    if entry.kind == "prefill_row":
        if engine.use_pipeline:
            from ..parallel.pipeline import pipeline_forward

            fn = lambda toks, pos_vec: pipeline_forward(
                cfg, engine.mesh, engine.params, engine.rope, engine.cache,
                toks, pos_vec, logits_mode="last", kv_len=entry.kv_len,
                page_table=engine._pt_operand() if engine.paged else None,
                page_size=ps,
            )
            return jax.make_jaxpr(fn)(
                _sds((b, entry.size), jnp.int32), _sds((b,), jnp.int32)
            )
        if engine.paged:
            # the paged admission prefill is the b=1 forward steered by the
            # row's one-row page-table slice (engine._dispatch_prefill_row)
            from ..models.transformer import forward

            fn = lambda toks, pos, pt: forward(
                cfg, engine.params, engine.rope, engine.cache, toks, pos,
                logits_mode="last", kv_len=entry.kv_len, page_table=pt,
                page_size=ps,
            )
            return jax.make_jaxpr(fn)(
                _sds((1, entry.size), jnp.int32), _sds((), jnp.int32),
                _sds((1, engine.page_pool.max_slots), jnp.int32),
            )
        from ..runtime.batch_session import prefill_row

        fn = lambda toks, pos, row: prefill_row(
            cfg, engine.params, engine.rope, engine.cache, toks, pos, row,
            kv_len=entry.kv_len,
        )
        return jax.make_jaxpr(fn)(
            _sds((1, entry.size), jnp.int32), _sds((), jnp.int32),
            _sds((), jnp.int32),
        )
    if entry.kind == "batch_decode":
        if engine.use_pipeline:
            from ..parallel.pipeline import pipeline_batch_decode_chunk as bdc

            fn = lambda tok, pos, keys, temp, topp: bdc(
                cfg, engine.mesh, engine.params, engine.rope, engine.cache,
                tok, pos, keys, temp, topp, n_steps=entry.size,
                kv_len=entry.kv_len,
                page_table=engine._pt_operand() if engine.paged else None,
                page_size=ps,
            )
        else:
            from ..runtime.batch_session import batch_decode_chunk

            # mirror the warm dispatch (engine._warm_batch_decode /
            # BatchSession.step): paged adds the page table, a grammar
            # arena adds the (mask table, [b] states) operand pair
            gt_sds = _grammar_sds(engine)
            extra = [pt_sds] if engine.paged else []
            if gt_sds is not None:
                extra += [gt_sds, _sds((b,), jnp.int32)]

            def fn(tok, pos, keys, temp, topp, *ops):
                it = iter(ops)
                pt = next(it) if engine.paged else None
                gtab = next(it) if gt_sds is not None else None
                gst = next(it) if gt_sds is not None else None
                return batch_decode_chunk(
                    cfg, engine.params, engine.rope, engine.cache, tok, pos,
                    keys, temp, topp, n_steps=entry.size, kv_len=entry.kv_len,
                    page_table=pt, page_size=ps,
                    grammar_table=gtab, grammar_state=gst,
                )

            return jax.make_jaxpr(fn)(
                _sds((b,), jnp.int32), _sds((b,), jnp.int32),
                _sds((b, 2), jnp.uint32), _sds((b,), jnp.float32),
                _sds((b,), jnp.float32), *extra,
            )
        return jax.make_jaxpr(fn)(
            _sds((b,), jnp.int32), _sds((b,), jnp.int32),
            _sds((b, 2), jnp.uint32), _sds((b,), jnp.float32),
            _sds((b,), jnp.float32),
        )
    if entry.kind == "page_copy":
        from ..runtime.paged_kv import copy_page

        fn = lambda src, dst: copy_page(
            engine.cache, src, dst, out_sharding=engine._cache_sharding
        )
        return jax.make_jaxpr(fn)(_sds((), jnp.int32), _sds((), jnp.int32))
    if entry.kind in ("page_extract", "page_insert"):
        # the KV movement layer's page-shipping programs (runtime/
        # kv_transport.py): pure gather/scatter between the pool and one
        # contiguous slice — zero collectives on every topology (the pool's
        # page axis is replicated; layer/head axes move shard-locally)
        from ..runtime.paged_kv import gather_pages, scatter_pages

        n = entry.size // engine.page_size
        if entry.kind == "page_extract":
            fn = lambda pages: gather_pages(
                engine.cache, pages,
                out_sharding=engine.prefix_cache.seg_sharding,
            )
            return jax.make_jaxpr(fn)(_sds((n,), jnp.int32))
        L, _, _, h, d = engine.cache.k.shape
        # wire segments are FLOAT even over int8 pools (gather_pages
        # dequantizes on extract; scatter_pages requantizes on insert)
        wire = jnp.float32 if engine.cfg.kv_quantized else engine.cache.k.dtype
        seg = _sds((L, entry.size, h, d), wire)
        fn = lambda k, v, pages: scatter_pages(
            engine.cache, k, v, pages, out_sharding=engine._cache_sharding
        )
        return jax.make_jaxpr(fn)(seg, seg, _sds((n,), jnp.int32))
    if entry.kind in ("verify", "verify_row"):
        # the speculative verify program: a prefill-shaped logits_mode="all"
        # forward (+ in-graph argmax on the fused non-mesh path). Mirrors
        # engine._dispatch_verify exactly: scalar-pos "verify" rides
        # engine._forward's microbatch rule, per-row "verify_row" rides the
        # admission-prefill shape (one microbatch).
        per_row = entry.kind == "verify_row"
        pos_sds = _sds((b,), jnp.int32) if per_row else _sds((), jnp.int32)
        if engine.use_pipeline:
            from ..parallel.pipeline import pipeline_forward

            pp = engine.mesh.shape["pp"]
            micro = 1 if per_row else (pp if entry.size % pp == 0 else 1)
            fn = lambda toks, pos: pipeline_forward(
                cfg, engine.mesh, engine.params, engine.rope, engine.cache,
                toks, pos, logits_mode="all", microbatches=micro,
                kv_len=entry.kv_len,
                page_table=engine._pt_operand() if engine.paged else None,
                page_size=ps,
            )
        else:
            from ..runtime.speculative import verify_chunk

            # mirror engine._dispatch_verify: on a grammar-capable engine
            # the verify program ALWAYS carries the mask-table pair, with
            # per-position [b, t] states (drafts advance the DFA in-graph)
            gt_sds = _grammar_sds(engine)
            extra = [pt_sds] if engine.paged else []
            if gt_sds is not None:
                extra += [gt_sds, _sds((b, entry.size), jnp.int32)]

            def fn(toks, pos, *ops):
                it = iter(ops)
                pt = next(it) if engine.paged else None
                gtab = next(it) if gt_sds is not None else None
                gst = next(it) if gt_sds is not None else None
                return verify_chunk(
                    cfg, engine.params, engine.rope, engine.cache, toks, pos,
                    kv_len=entry.kv_len, page_table=pt, page_size=ps,
                    grammar_table=gtab, grammar_state=gst,
                )

            return jax.make_jaxpr(fn)(
                _sds((b, entry.size), jnp.int32), pos_sds, *extra
            )
        return jax.make_jaxpr(fn)(_sds((b, entry.size), jnp.int32), pos_sds)
    if entry.kind in ("prefix_extract", "prefix_copy", "prefix_copy_row"):
        from ..runtime.prefix_cache import (
            copy_prefix_into_row,
            copy_prefix_into_rows,
            extract_prefix_from_row,
        )

        pc = engine.prefix_cache
        L, _, _, h, d = engine.cache.k.shape
        seg = _sds((L, entry.size, h, d), engine.cache.k.dtype)
        if entry.kind == "prefix_extract":
            fn = lambda row: extract_prefix_from_row(
                engine.cache, row, length=entry.size,
                out_sharding=pc.seg_sharding,
            )
            return jax.make_jaxpr(fn)(_sds((), jnp.int32))
        if entry.kind == "prefix_copy":
            fn = lambda k, v: copy_prefix_into_rows(
                engine.cache, k, v, out_sharding=pc.cache_sharding
            )
            return jax.make_jaxpr(fn)(seg, seg)
        fn = lambda k, v, row: copy_prefix_into_row(
            engine.cache, k, v, row, out_sharding=pc.cache_sharding
        )
        return jax.make_jaxpr(fn)(seg, seg, _sds((), jnp.int32))
    raise ValueError(f"unknown ladder kind {entry.kind!r}")


# -- expected manifests -----------------------------------------------------


def expected_collectives(engine, entry: LadderEntry):
    """The per-program collective budget for this engine's topology, or
    None when the topology has no exact manifest (MoE / sp / ep meshes —
    their collective structure is config-dependent; audit still enforces
    dtypes and donation there).

    Non-mesh and GSPMD programs contain ZERO explicit collectives (XLA
    inserts GSPMD collectives after partitioning, below the jaxpr). The
    shard_map pipeline path emits, per forward (parallel/pipeline.py):

    * 2 psum("tp") per pipeline round (attention + FFN output reductions,
      counted once per round's layer-scan body),
    * 1 psum("pp") broadcasting the final stage's activations,
    * 1 all_gather("tp") assembling the logits,
    * 1 ppermute per round (stage handoff).

    rounds = microbatches + pp - 1; decode runs 1 microbatch, prefill
    chunks microbatch to pp when the chunk length divides (engine._forward).

    Prefix-cache copy/extract programs are plain GSPMD slice/update
    programs on EVERY topology — zero explicit collectives always: a
    surprise collective there would mean a splice is reshuffling cached KV
    across stages on every hit.
    """
    if entry.kind.startswith(("prefix_", "page_")):
        # prefix copies AND the paged layer's page programs (page_copy /
        # page_extract / page_insert) are plain slice/gather/scatter
        # programs on EVERY topology — zero explicit collectives always: a
        # surprise collective there would mean page movement is reshuffling
        # KV across stages on every COW / ship / insert
        return {}
    if not engine.use_pipeline:
        return {}
    mesh = engine.mesh
    if engine.cfg.is_moe or mesh.shape["sp"] > 1 or mesh.shape.get("ep", 1) > 1:
        return None
    rounds = pipeline_rounds(engine, entry)
    return {"psum": 2 * rounds + 1, "all_gather": 1, "ppermute": rounds}


def pipeline_rounds(engine, entry: LadderEntry) -> int:
    """GPipe rounds this program's jaxpr contains: microbatches + pp - 1,
    with the microbatch rule mirroring engine._forward (prefill chunks
    microbatch to pp when the chunk length divides; decode runs 1;
    prefill_row rides pipeline_forward's default of 1). The ONE owner of
    this derivation — both the collective budget and the f32-dot budget
    are per-round quantities and must move together."""
    pp = engine.mesh.shape["pp"]
    if entry.kind in ("prefill", "verify"):
        # verify rides engine._forward like a whole-batch prefill chunk:
        # same microbatch rule, hence the ISSUE contract "collective budget
        # identical to prefill of the same size"
        micro = pp if entry.size % pp == 0 else 1
    else:  # decode / batch_decode / prefill_row / verify_row: one microbatch
        micro = 1
    return micro + pp - 1


def attention_sites(engine, entry: LadderEntry) -> int:
    """Structural count of attention bodies in this program's jaxpr: one
    per layer-scan body for non-mesh programs, one per pipeline round on
    shard_map meshes (the rounds loop is a Python loop, each round builds
    its own layer scan)."""
    if not engine.use_pipeline:
        return 1
    return pipeline_rounds(engine, entry)


def f32_dot_budget(engine, entry: LadderEntry) -> int:
    """Max sanctioned f32-touching dot_generals for a bfloat16 engine.

    The deliberate f32 matmuls live in attention — the softmax-side
    products ops/attention.py keeps at f32 for numerics: measured, each
    attention body contributes exactly 2 dots with an f32 input (scores
    path + probs·V). Everything else — the quantized Q40/int8 projections,
    logits — must keep bf16 inputs, so any EXTRA f32-touching dot is an
    accidental upcast of a quantized matmul path."""
    return 2 * attention_sites(engine, entry)


# -- the declarative contract registry --------------------------------------
#
# Every warm-ladder program kind carries ONE declarative contract built
# from the engine's topology and KV configuration; `audit_engine` (and the
# graph-contract CI stage, analysis/graph_diff.py) enforce contracts — the
# former hardcoded per-check functions below are thin views over them, so
# a new program kind that lands on warm_plan() without a registry row
# fails the coverage gate instead of silently auditing nothing.

#: kind -> registry row. `copy_program`: a pure slice/gather/scatter
#: KV-movement program (zero explicit collectives on EVERY topology);
#: `fused_decode`: eligible for the fused page-table-aware int8 decode
#: kernel, whose contract pins pool gathers to zero (PR 17).
KIND_REGISTRY = {
    "prefill": dict(copy_program=False, fused_decode=False),
    "decode": dict(copy_program=False, fused_decode=True),
    "prefill_row": dict(copy_program=False, fused_decode=False),
    "batch_decode": dict(copy_program=False, fused_decode=True),
    "verify": dict(copy_program=False, fused_decode=False),
    "verify_row": dict(copy_program=False, fused_decode=False),
    "prefix_extract": dict(copy_program=True, fused_decode=False),
    "prefix_copy": dict(copy_program=True, fused_decode=False),
    "prefix_copy_row": dict(copy_program=True, fused_decode=False),
    "page_copy": dict(copy_program=True, fused_decode=False),
    "page_extract": dict(copy_program=True, fused_decode=False),
    "page_insert": dict(copy_program=True, fused_decode=False),
}


@dataclasses.dataclass(frozen=True)
class ProgramContract:
    """The declared graph invariants of ONE warm-ladder program.

    * `forbid_f64` — no float64 output or dot input anywhere (always on);
    * `f32_dot_budget` — max sanctioned f32-touching dot_generals (the
      attention softmax-side products); None = unbudgeted (f32 engines,
      where every dot legitimately touches f32);
    * `collectives` — the EXACT expected collective multiset for this
      topology, or None when the topology has no manifest (MoE/sp/ep);
    * `forbid_pool_gather` — the KV pool's shape when this program must
      not materialize pool gathers (the fused int8 paged decode pin);
      None = unpinned.
    """

    entry: LadderEntry
    forbid_f64: bool = True
    f32_dot_budget: int | None = None
    collectives: dict | None = None
    forbid_pool_gather: tuple | None = None


def contract_for(engine, entry: LadderEntry) -> ProgramContract:
    """Build `entry`'s declarative contract from the registry + the
    engine's topology/KV configuration. Raises GraphAuditError for a kind
    with no registry row — the coverage gate's teeth: warm_plan() growth
    without a declared contract is a failure, not a silent hole."""
    row = KIND_REGISTRY.get(entry.kind)
    if row is None:
        raise GraphAuditError(
            f"no declared contract for warm-ladder kind {entry.kind!r} — "
            "add a KIND_REGISTRY row (and a golden fingerprint) for it"
        )
    budget = (
        f32_dot_budget(engine, entry)
        if engine.cfg.dtype == jnp.bfloat16 and not row["copy_program"]
        else None
    )
    pool = None
    if (
        row["fused_decode"]
        and getattr(engine, "paged", False)
        and engine.cfg.kv_quantized
        and _fused_kernel_active(engine)
    ):
        pool = tuple(engine.cache.k.shape)
    return ProgramContract(
        entry=entry,
        f32_dot_budget=budget,
        collectives=expected_collectives(engine, entry),
        forbid_pool_gather=pool,
    )


def _fused_kernel_active(engine) -> bool:
    """True when the int8 paged decode programs trace the fused
    page-table-aware Pallas kernel (models/transformer.py
    _fused_paged_eligible at decode's t=1): pallas enabled for this config
    and uniform lane-aligned head grouping."""
    from ..models.transformer import _pallas_enabled

    cfg = engine.cfg
    return (
        _pallas_enabled(cfg)
        and cfg.n_heads % cfg.n_kv_heads == 0
        and cfg.head_dim % 8 == 0
    )


def contract_problems(engine, contract: ProgramContract, jaxpr) -> list:
    """Check one traced program against its declared contract; every
    problem line names the offending primitive."""
    problems = []
    entry = contract.entry
    if contract.forbid_f64:
        dtypes = dtype_census(jaxpr)
        if "float64" in dtypes:
            problems.append("float64 appears in the traced program")
        for (l, r), cnt in dot_input_census(jaxpr).items():
            if "float64" in (l, r):
                problems.append(
                    f"float64 dot_general inputs ({l} x {r}) x{cnt}"
                )
    if contract.f32_dot_budget is not None:
        dots = dot_input_census(jaxpr)
        f32_dots = sum(
            cnt for (l, r), cnt in dots.items() if "float32" in (l, r)
        )
        if f32_dots > contract.f32_dot_budget:
            problems.append(
                f"{f32_dots} f32-input dot_generals exceed the sanctioned "
                f"budget of {contract.f32_dot_budget} (attention "
                "softmax-side products) — an accidental f32 upcast in a "
                "quantized matmul path"
            )
    if contract.collectives is not None:
        got = collective_counts(jaxpr)
        for name in sorted(set(contract.collectives) | set(got)):
            e, g = contract.collectives.get(name, 0), got.get(name, 0)
            if e != g:
                problems.append(
                    f"collective budget violated: {name} x{g} traced, "
                    f"x{e} expected for this topology"
                )
    if contract.forbid_pool_gather is not None:
        n = pool_gather_count(jaxpr, contract.forbid_pool_gather)
        if n:
            problems.append(
                f"gather x{n} materializes the int8 KV pool in "
                f"{entry.kind} — the fused page-table-aware decode kernel "
                "contract requires ZERO pool gathers (page tables ride "
                "the kernel's scalar prefetch; ops/pallas_attention.py)"
            )
    return problems


# -- checks (contract views) -------------------------------------------------


def dtype_problems(engine, entry: LadderEntry, jaxpr) -> list:
    """The contract's dtype clauses alone (f64 ban + f32 dot budget)."""
    budget = (
        f32_dot_budget(engine, entry)
        if engine.cfg.dtype == jnp.bfloat16
        else None
    )
    return contract_problems(
        engine,
        ProgramContract(entry=entry, f32_dot_budget=budget, collectives=None),
        jaxpr,
    )


def collective_problems(engine, entry: LadderEntry, jaxpr) -> list:
    """The contract's collective-budget clause alone."""
    return contract_problems(
        engine,
        ProgramContract(
            entry=entry,
            forbid_f64=False,
            collectives=expected_collectives(engine, entry),
        ),
        jaxpr,
    )


def donation_check(name: str, lowered) -> list:
    """The one donation predicate: `lowered` (a jax Lowered or its MLIR
    text) must carry a buffer-alias marker, or the cache donation was lost
    — the clause the planted de-donation mutation test drives directly."""
    txt = lowered if isinstance(lowered, str) else lowered.as_text()
    if not any(m in txt for m in DONATION_MARKERS):
        return [
            f"{name}: KV cache donation lost (no "
            f"{'/'.join(DONATION_MARKERS)} marker in the lowered program)"
        ]
    return []


def donation_problems(engine) -> list:
    """Lower each decode/prefill jit entry point this engine uses and
    assert the KV cache donation survived into the MLIR (buffer-alias
    markers). One lowering per program CLASS — donation is declared on the
    function, not per shape."""
    cfg, b = engine.cfg, engine.batch
    kvb = engine._kv_bucket(1)
    from ..runtime.engine import _greedy_prng_key

    key = _greedy_prng_key()  # the typed key aval serving dispatches
    tok1 = jnp.zeros((b, 1), jnp.int32)
    tokb = jnp.zeros((b,), jnp.int32)
    pos = jnp.int32(0)
    problems = []

    def check(name, lowered):
        problems.extend(donation_check(name, lowered))

    if engine.use_pipeline:
        from ..parallel import pipeline as pl

        paged = engine.paged
        psz = engine.page_size
        fn = pl._cached_pipeline_fn(
            cfg, engine.mesh, engine.params, engine.cache,
            ("fwd", "last", 1, kvb, False, paged, psz),
            lambda ps, cs: pl._build_pipeline_fn(
                cfg, engine.mesh, ps, cs, "last", 1, kvb, per_row=False,
                page_size=psz if paged else None,
            ),
        )
        fwd_args = (engine.params, engine.rope, engine.cache, tok1, pos)
        if paged:
            fwd_args = fwd_args + (engine._pt_operand(),)
        check("pipeline_forward", fn.lower(*fwd_args))
        dfn = pl._cached_pipeline_fn(
            cfg, engine.mesh, engine.params, engine.cache,
            ("decode", 1, 0.0, 0.9, kvb, False, paged, psz),
            lambda ps, cs: pl._build_pipeline_decode_fn(
                cfg, engine.mesh, ps, cs, 1, 0.0, 0.9, kvb, per_row=False,
                page_size=psz if paged else None,
            ),
        )
        dec_args = (engine.params, engine.rope, engine.cache, tokb, pos, key)
        if paged:
            dec_args = dec_args + (engine._pt_operand(),)
        check("pipeline_decode_chunk", dfn.lower(*dec_args))
        if paged:
            # the mesh-paged COW page copy donates the sharded pool exactly
            # like the single-chip one
            from ..runtime.paged_kv import copy_page

            check(
                "copy_page",
                copy_page.lower(
                    engine.cache, jnp.int32(0), jnp.int32(1),
                    out_sharding=engine._cache_sharding,
                ),
            )
    else:
        from ..models.transformer import forward
        from ..runtime.decode import decode_chunk

        pt = (
            jnp.zeros((b, engine.page_pool.max_slots), jnp.int32)
            if engine.paged
            else None
        )
        ps = engine.page_size
        # grammar-capable engines serve the MASKED program class (the
        # operand pair is part of every decode/batch_decode dispatch) —
        # donation must be proven on that class, not the grammar-less twin
        gt = (
            jnp.zeros(engine.grammar.table.shape, jnp.int32)
            if getattr(engine, "grammar", None) is not None
            else None
        )
        gsb = jnp.zeros((b,), jnp.int32) if gt is not None else None
        check(
            "forward",
            forward.lower(
                cfg, engine.params, engine.rope, engine.cache, tok1, pos,
                logits_mode="last", kv_len=kvb, page_table=pt, page_size=ps,
            ),
        )
        check(
            "decode_chunk",
            decode_chunk.lower(
                cfg, engine.params, engine.rope, engine.cache, tokb, pos,
                key, n_steps=1, temperature=0.0, topp=0.9, kv_len=kvb,
                page_table=pt, page_size=ps,
                grammar_table=gt, grammar_state=gsb,
            ),
        )
        if engine.paged:
            # the copy-on-write page copy moves KV within the donated pool;
            # a lost donation would duplicate the whole pool per COW
            from ..runtime.paged_kv import copy_page

            check(
                "copy_page",
                copy_page.lower(engine.cache, jnp.int32(0), jnp.int32(1)),
            )
        if engine.batch > 1:
            from ..runtime.batch_session import batch_decode_chunk, prefill_row

            check(
                "batch_decode_chunk",
                batch_decode_chunk.lower(
                    cfg, engine.params, engine.rope, engine.cache, tokb,
                    jnp.zeros((b,), jnp.int32), jnp.zeros((b, 2), jnp.uint32),
                    jnp.zeros((b,), jnp.float32), jnp.full((b,), 0.9, jnp.float32),
                    n_steps=1, kv_len=kvb, page_table=pt, page_size=ps,
                    grammar_table=gt, grammar_state=gsb,
                ),
            )
            if not engine.paged:
                # paged admission prefill rides the b=1 `forward` (already
                # checked above); the row-slice program is contiguous-only
                check(
                    "prefill_row",
                    prefill_row.lower(
                        cfg, engine.params, engine.rope, engine.cache,
                        jnp.zeros((1, 1), jnp.int32), pos, jnp.int32(0), kv_len=kvb,
                    ),
                )
    if engine.spec_mode is not None and not engine.use_pipeline:
        # the fused verify program donates the cache exactly like a prefill
        # chunk; a lost donation would copy the whole KV stack every round
        from ..runtime.speculative import verify_chunk

        k0 = engine.spec_buckets[0]
        check(
            "verify_chunk",
            verify_chunk.lower(
                cfg, engine.params, engine.rope, engine.cache,
                jnp.zeros((b, k0 + 1), jnp.int32), pos, kv_len=kvb,
                page_table=(
                    jnp.zeros((b, engine.page_pool.max_slots), jnp.int32)
                    if engine.paged
                    else None
                ),
                page_size=engine.page_size,
            ),
        )
    if (
        engine.prefix_cache is not None
        and engine.prefix_cache.buckets
        and not getattr(engine.prefix_cache, "paged", False)
    ):
        # the prefix-cache splice programs donate the live cache too: a
        # lost donation would double the cache's HBM footprint on every hit
        from ..runtime.prefix_cache import (
            copy_prefix_into_row,
            copy_prefix_into_rows,
        )

        pc = engine.prefix_cache
        P = pc.buckets[0]
        L, _, _, h, d = engine.cache.k.shape
        seg = jnp.zeros((L, P, h, d), engine.cache.k.dtype)
        check(
            "copy_prefix_into_rows",
            copy_prefix_into_rows.lower(
                engine.cache, seg, seg, out_sharding=pc.cache_sharding
            ),
        )
        check(
            "copy_prefix_into_row",
            copy_prefix_into_row.lower(
                engine.cache, seg, seg, jnp.int32(0),
                out_sharding=pc.cache_sharding,
            ),
        )
    if (
        engine.paged
        and engine.prefix_cache is not None
        and engine.prefix_cache.buckets
    ):
        # the paged external-insert scatter donates the live pool like
        # every other pool-writing program (runtime/kv_transport.py)
        from ..runtime.paged_kv import scatter_pages

        P0 = next(
            (B for B in engine.prefix_cache.buckets if B >= engine.page_size),
            None,
        )
        if P0:
            n = P0 // engine.page_size
            L, _, _, h, d = engine.cache.k.shape
            wire = (
                jnp.float32 if engine.cfg.kv_quantized else engine.cache.k.dtype
            )
            seg = jnp.zeros((L, P0, h, d), wire)
            check(
                "scatter_pages",
                scatter_pages.lower(
                    engine.cache, seg, seg, jnp.zeros((n,), jnp.int32),
                    out_sharding=engine._cache_sharding,
                ),
            )
    return problems


def sharding_problems(engine) -> list:
    """Per-stage sharding consistency on pipeline meshes: every per-layer
    weight stack shards its leading (layer) axis over `pp`, and the cache
    matches `pp_cache_sharding` — the invariants the shard_map in_specs are
    *derived from* (pipeline.py reads specs off the concrete arrays, so a
    mis-sharded param silently reshapes the whole program)."""
    if engine.mesh is None or not engine.use_pipeline:
        return []
    from jax.sharding import NamedSharding

    from ..parallel.pipeline import pp_cache_sharding, pp_paged_pool_sharding

    problems = []
    expected_cache = (
        pp_paged_pool_sharding(engine.mesh)
        if engine.paged
        else pp_cache_sharding(engine.mesh)
    )

    def norm(spec):
        # trailing Nones are unsharded-dim noise: plain-jit programs (the
        # paged pool's page movement) trim them from output shardings
        t = tuple(spec)
        while t and t[-1] is None:
            t = t[:-1]
        return t

    for name, arr in (("cache.k", engine.cache.k), ("cache.v", engine.cache.v)):
        sh = getattr(arr, "sharding", None)
        if not isinstance(sh, NamedSharding) or norm(sh.spec) != norm(
            expected_cache.spec
        ):
            problems.append(
                f"{name} sharding {getattr(sh, 'spec', None)} != pipeline "
                f"cache spec {expected_cache.spec}"
            )
    for i, leaf in enumerate(jax.tree.leaves(engine.params.layers)):
        sh = getattr(leaf, "sharding", None)
        if not isinstance(sh, NamedSharding):
            problems.append(f"layer param leaf {i} has no NamedSharding")
            continue
        if sh.mesh.shape != engine.mesh.shape:
            problems.append(f"layer param leaf {i} lives on a different mesh")
        spec = sh.spec
        if len(spec) == 0 or spec[0] != "pp":
            problems.append(
                f"layer param leaf {i} layer-stack axis not sharded over pp "
                f"(spec {spec}) — stages would compute on replicated layers"
            )
    return problems


# -- driver -----------------------------------------------------------------


@dataclasses.dataclass
class AuditReport:
    entry: LadderEntry
    collectives: dict
    dtypes: set
    problems: list
    contract: ProgramContract | None = None

    @property
    def ok(self) -> bool:
        return not self.problems


def audit_engine(engine, ladder=None) -> list:
    """Audit every warm-ladder program against its DECLARED contract
    (contract_for — the registry is the single source of per-program
    invariants) plus the engine-wide donation and sharding contracts;
    returns one AuditReport per ladder entry (engine-wide problems ride
    the first report)."""
    ladder = warm_key_ladder(engine) if ladder is None else ladder
    reports = []
    for entry in ladder:
        contract = contract_for(engine, entry)
        jaxpr = trace_entry(engine, entry)
        reports.append(
            AuditReport(
                entry=entry,
                collectives=collective_counts(jaxpr),
                dtypes=dtype_census(jaxpr),
                problems=contract_problems(engine, contract, jaxpr),
                contract=contract,
            )
        )
    engine_wide = donation_problems(engine) + sharding_problems(engine)
    if engine_wide:
        if not reports:
            reports.append(
                AuditReport(LadderEntry("engine", 0, 0), {}, set(), [])
            )
        reports[0].problems.extend(engine_wide)
    return reports


def assert_clean(reports) -> None:
    bad = [r for r in reports if not r.ok]
    if bad:
        lines = []
        for r in bad:
            for p in r.problems:
                lines.append(f"{r.entry.kind}[{r.entry.size}|kv{r.entry.kv_len}]: {p}")
        raise GraphAuditError(
            "graph audit failed:\n  " + "\n  ".join(lines)
        )


def format_reports(reports) -> str:
    lines = ["🔎 graph audit:"]
    for r in reports:
        status = "ok" if r.ok else "FAIL"
        coll = (
            " ".join(f"{k}x{v}" for k, v in sorted(r.collectives.items()))
            or "none"
        )
        lines.append(
            f"  [{status}] {r.entry.kind}[{r.entry.size}|kv{r.entry.kv_len}] "
            f"collectives: {coll}"
        )
        for p in r.problems:
            lines.append(f"         ! {p}")
    return "\n".join(lines)


def add_engine_args(p) -> None:
    """The shared engine-config flags of the graph CLIs (this auditor and
    analysis/graph_diff.py): ONE flag surface so a blessed golden config
    and the audited config can never drift apart syntactically."""
    p.add_argument("--model", default=None, help=".m file (default: tiny synthetic)")
    p.add_argument("--compute-dtype", default="float32")
    p.add_argument("--batch", type=int, default=2)
    p.add_argument("--max-chunk", type=int, default=16)
    p.add_argument("--decode-chunk-size", type=int, default=8)
    p.add_argument(
        "--prefix-cache-mb", type=int, default=64,
        help="prefix-cache budget: audits the copy/extract ladder too (0 = off)",
    )
    p.add_argument(
        "--speculative", choices=["off", "ngram"], default="ngram",
        help="audit the speculative verify programs too (default on; the "
        "model draft source adds no programs of its own — its engine "
        "audits separately)",
    )
    p.add_argument(
        "--draft-k", type=int, default=8,
        help="draft budget for the audited verify ladder (8 = both buckets)",
    )
    p.add_argument(
        "--kv-layout", choices=["contiguous", "paged"], default="contiguous",
        help="audit the paged-KV program ladder (page-table gather/scatter "
        "forwards, the copy-on-write page copy, and the KV movement "
        "layer's page_extract/page_insert shipping programs) instead of "
        "the contiguous one (runtime/paged_kv.py, runtime/kv_transport.py)",
    )
    p.add_argument(
        "--kv-dtype", choices=["bfloat16", "float32", "int8"], default=None,
        help="audit the quantized-KV program ladder (int8 payload + f32 "
        "scale sidecars, ops/kv_quant.py): the paged arm must lower the "
        "fused page-table-aware decode kernel and the collective budgets "
        "must match the float twin's (default: the compute-dtype default)",
    )
    p.add_argument(
        "--grammar", action="store_true",
        help="audit the MASKED program ladder: build the grammar "
        "mask-table arena (runtime/grammar.py) so every decode/verify "
        "program carries the [S, V] table + per-row state operands — the "
        "class grammar-capable servers actually dispatch; the masked-vs-"
        "unmasked equivalence axis lives in analysis/graph_diff.py",
    )
    p.add_argument(
        "--pp", type=int, default=1,
        help="audit on a pipeline-parallel mesh of this extent (needs that "
        "many devices — CI uses xla_force_host_platform_device_count); "
        "with --kv-layout paged this is the MESH-PAGED ladder: collective "
        "budgets must match the contiguous twin's",
    )
    p.add_argument(
        "--tp", type=int, default=1,
        help="tensor-parallel mesh extent (composes with --pp)",
    )


def engine_from_args(args, workdir: str):
    """Build the engine the parsed `add_engine_args` flags describe
    (writing a tiny synthetic model into `workdir` when no --model)."""
    from ..runtime.engine import InferenceEngine

    mesh = None
    if args.pp > 1 or args.tp > 1:
        from ..parallel import make_mesh

        mesh = make_mesh(pp=args.pp, tp=args.tp)
    model = args.model
    if model is None:
        from ..testing import tiny_header, write_tiny_model

        model = workdir + "/tiny.m"
        if mesh is not None:
            # layer/head counts must divide over the mesh axes
            hdr = tiny_header(
                seq_len=128, dim=128, hidden_dim=128, n_layers=4,
                n_heads=4, n_kv_heads=4,
            )
        else:
            hdr = tiny_header(seq_len=128)
        write_tiny_model(model, hdr, seed=0)
    return InferenceEngine(
        model, compute_dtype=args.compute_dtype, batch=args.batch,
        max_chunk=args.max_chunk, decode_chunk_size=args.decode_chunk_size,
        prefix_cache_mb=args.prefix_cache_mb,
        speculative=args.speculative, draft_k=args.draft_k,
        kv_layout=args.kv_layout, mesh=mesh,
        cache_dtype=args.kv_dtype,
        # None keeps the library env-or-off default, so DLT_GRAMMAR=1
        # experiments still reach the engine; the goldens stay keyed by
        # the RESULT (config_key's _grS suffix), never the flag
        grammar=True if getattr(args, "grammar", False) else None,
    )


def main(argv=None) -> int:
    """CLI: audit a model file's engine, or (default) a tiny synthetic
    model — the CI smoke path."""
    import argparse
    import tempfile

    p = argparse.ArgumentParser(prog="dlt-graph-audit")
    add_engine_args(p)
    p.add_argument(
        "--costs", action="store_true",
        help="also build the warm-ladder cost/memory table "
        "(runtime/profiling.py) and FAIL if any warm_plan() program is "
        "missing an entry — the /debug/costs coverage contract",
    )
    args = p.parse_args(argv)

    with tempfile.TemporaryDirectory() as d:
        engine = engine_from_args(args, d)
        try:
            reports = audit_engine(engine)
            cost_issues: list = []
            if args.costs:
                # cost coverage is part of the audit when asked: a program
                # kind that lands on the warm ladder without a cost-model
                # entry (profiling.lower_entry can't build it) fails here,
                # so /debug/costs can never silently drift from warm_plan()
                from ..runtime.profiling import (
                    build_cost_table,
                    cost_problems,
                    format_cost_table,
                )

                table = build_cost_table(engine)
                print(format_cost_table(table))
                cost_issues = cost_problems(engine, table)
                for p_ in cost_issues:
                    print(f"  ! cost coverage: {p_}")
        finally:
            engine.close()
    print(format_reports(reports))
    return 0 if all(r.ok for r in reports) and not cost_issues else 1


if __name__ == "__main__":
    raise SystemExit(main())
