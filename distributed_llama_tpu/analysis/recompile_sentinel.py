"""Recompile sentinel: post-warmup XLA compiles are bugs, catch them live.

The serving design compiles a closed ladder of programs up front
(`InferenceEngine.warmup`: every prefill (size, kv-bucket) pair, the decode
ramp + full chunks, the BatchSession admission/step cycle) precisely so no
user request ever pays a compile. That contract is *invisible*: a shape
regression — a mis-bucketed kv_len, a chunk planner change, a new dtype on a
traced argument — silently re-introduces multi-second compiles inside user
requests, and the only production symptom is a p99 cliff.

The sentinel makes the contract observable: it subscribes to JAX's
monitoring events (``/jax/core/compile/backend_compile_duration`` fires once
per actual backend compile; cache hits are silent), counts compiles during
the warmup window, and after ``seal()`` turns every further compile into

* a ``sanitizer_recompiles`` counter bump in the engine's `StepStats`
  (surfaces in ``/stats`` and ``/health``), and
* optionally a raised :class:`RecompileError` (``DLT_SANITIZERS_FATAL=1``
  or ``fatal=True``) — the exception propagates out of the jit call that
  triggered the compile, so tests and canaries fail at the exact site.

Scope: compile events are PROCESS-wide (JAX has no per-function hook).
While any subscribed sentinel is still in its warm window, compiles are
attributed to the warming engine(s) — a sealed co-resident engine neither
counts them nor (fatal mode) aborts another engine's legitimate warmup.
Once EVERY subscriber is sealed, any compile is a breach and is reported
to all sentinels (it cannot be attributed further). That is the right
semantics for a serving process — after warmup *nothing* should compile.
Opt-in via ``DLT_SANITIZERS=1`` (the engine wires this automatically; see
runtime/engine.py).
"""

from __future__ import annotations

import contextlib
import threading

from . import sanitizers_fatal

#: substrings identifying a compile event across jax versions
_COMPILE_EVENT_MARKERS = ("backend_compile",)

_install_lock = threading.Lock()
_installed = False
_subscribers: set = set()


class RecompileError(RuntimeError):
    """A post-warmup (sealed) compile happened — the warm-key ladder has a
    hole or a caller dispatched an unwarmed shape."""


def _dispatch(event: str, *args, **kwargs):
    if not any(m in event for m in _COMPILE_EVENT_MARKERS):
        return
    # JAX's compile events carry no function identity, so attribution is a
    # heuristic: while ANY subscriber is still in its warm window, compiles
    # belong to the warming engine(s) — a sealed co-resident engine must
    # neither count them nor (fatal mode) abort another engine's warmup.
    # Only when every subscriber is sealed is a compile a genuine breach
    # (and then it is reported to all, since it cannot be attributed).
    subs = list(_subscribers)
    # compile events fire on the thread that triggered the compile, so a
    # sealed sentinel whose exempt() window covers THIS thread claims the
    # event exactly like an unsealed (warming) one — co-resident sealed
    # sentinels must not treat another engine's sanctioned build as a breach
    claimants = [s for s in subs if not s.sealed or s.exempts_current_thread()]
    # a FATAL sentinel raises out of _on_compile — deliver the event to
    # every subscriber first (a breach must be counted by all of them, not
    # just the ones that happened to iterate before the raiser), then let
    # the first error propagate to the compiling call site
    err = None
    for s in (claimants if claimants else subs):
        try:
            s._on_compile(event)
        except RecompileError as e:
            err = err if err is not None else e
    if err is not None:
        raise err


def _install_once():
    """Register the ONE process-wide monitoring listener (jax.monitoring has
    no unregister, so sentinels subscribe/unsubscribe against our own
    dispatcher instead of the jax registry)."""
    global _installed
    with _install_lock:
        if _installed:
            return
        import jax.monitoring

        jax.monitoring.register_event_duration_secs_listener(_dispatch)
        _installed = True


class RecompileSentinel:
    """Counts backend compiles; after `seal()` they are violations.

    Usable standalone::

        sentinel = RecompileSentinel(stats=engine.stats).start()
        engine.warmup()
        sentinel.seal()
        ... serve ...
        assert sentinel.post_seal_compiles == 0

    or as a context manager (auto start/stop). Thread-safe: compile events
    can arrive from any thread that triggers a jit compile.
    """

    def __init__(self, stats=None, fatal: bool | None = None, name: str = "engine"):
        self.stats = stats  # StepStats: violations become counters
        self.fatal = sanitizers_fatal() if fatal is None else fatal
        self.name = name
        self.sealed = False
        self.warm_compiles = 0
        self.post_seal_compiles = 0
        self._lock = threading.Lock()
        self._active = False
        self._exempt_threads: set = set()  # thread ids inside exempt()

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "RecompileSentinel":
        _install_once()
        _subscribers.add(self)
        self._active = True
        return self

    def stop(self):
        _subscribers.discard(self)
        self._active = False

    def __enter__(self) -> "RecompileSentinel":
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    def seal(self):
        """End the warmup window: every compile from here on is a breach."""
        with self._lock:
            self.sealed = True
        if self.stats is not None:
            self.stats.gauge("sanitizer_warm_compiles", self.warm_compiles)

    def unseal(self):
        """Re-open the warmup window (e.g. an intentional reconfiguration
        that legitimately compiles new shapes)."""
        with self._lock:
            self.sealed = False

    @contextlib.contextmanager
    def exempt(self):
        """Thread-scoped sanctioned-compile window: compiles triggered by
        the CURRENT thread count as warm (an intentional reconfiguration —
        e.g. the lazy cost-table build's AOT compiles, runtime/profiling)
        while the sentinel stays sealed and every OTHER thread keeps full
        breach detection. Compile events fire on the compiling thread, so
        attribution is exact — unlike unseal(), which forgives the whole
        process for the window."""
        tid = threading.get_ident()
        with self._lock:
            self._exempt_threads.add(tid)
        try:
            yield self
        finally:
            with self._lock:
                self._exempt_threads.discard(tid)

    def exempts_current_thread(self) -> bool:
        with self._lock:
            return threading.get_ident() in self._exempt_threads

    # -- event sink ---------------------------------------------------------

    def _on_compile(self, event: str):
        with self._lock:
            if (
                not self.sealed
                or threading.get_ident() in self._exempt_threads
            ):
                self.warm_compiles += 1
                return
            self.post_seal_compiles += 1
        if self.stats is not None:
            self.stats.incr("sanitizer_recompiles")
        if self.fatal:
            # post-mortem BEFORE the raise: the trace ring holds the spans
            # of whatever request dispatched the mis-bucketed shape
            from ..runtime.tracing import flight_record

            flight_record(
                f"sanitizer:recompile:{self.name}",
                counters=self.stats.counters_snapshot() if self.stats else None,
            )
            raise RecompileError(
                f"post-warmup XLA compile detected ({self.name}): the "
                "warm-key ladder does not cover a shape that just got "
                "dispatched — find the mis-bucketed caller "
                f"(event {event})"
            )
