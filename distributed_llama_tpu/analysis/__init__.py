"""dlt-audit: trace-time graph auditing, runtime sanitizers, thread
auditing, and the repo lint — the machine-enforced versions of the engine's
invisible invariants.

The JAX port replaced the reference engine's hand-rolled distributed
discipline (head-split slices, pipe byte-sizes, thread fences) with
*implicit* contracts: the (size, kv-bucket) warm-key compile ladder, donated
KV buffers, per-stage sharding specs, and the lock discipline across the
Batcher / gateway / prober / watchdog threads. Nothing used to check them —
a stray f32 upcast, a surprise all-gather, a post-warmup recompile, or an
unguarded counter mutation shipped silently and surfaced as a latency cliff
or a flaky 503. This subpackage makes each contract checkable:

* :mod:`.graph_audit`   — abstract-evals (``jax.make_jaxpr`` /
  ``.lower()``) the decode step and every prefill-chunk entry on the warm
  ladder and asserts dtypes, collective budgets, KV donation, and sharding
  consistency per compiled key;
* :mod:`.recompile_sentinel` — records compile events during warmup and
  turns any post-warmup recompile into a counted (optionally fatal) event;
* :mod:`.host_sync_guard`  — ``jax.transfer_guard`` scopes over the decode
  hot loop so implicit device→host transfers outside the sanctioned
  ``_fetch_pool`` sites raise, with counters in ``StepStats``;
* :mod:`.thread_audit`  — lock-order graph recorder + long-hold detector +
  guarded-mutation checks over the serving layer's locks;
* :mod:`.lint`          — the AST repo lint (``scripts/dlt_lint.py``).

The runtime sanitizers are opt-in via ``DLT_SANITIZERS=1`` (fatal variants
via ``DLT_SANITIZERS_FATAL=1``); the trace-time passes run in CI and
``pytest -m analysis``. See docs/ANALYSIS.md.
"""

from __future__ import annotations

import os

__all__ = ["sanitizers_enabled", "sanitizers_fatal"]


def sanitizers_enabled() -> bool:
    """True when the opt-in runtime sanitizers (recompile sentinel, host
    sync guard) should be active for this process."""
    return os.environ.get("DLT_SANITIZERS", "0") not in ("", "0")


def sanitizers_fatal() -> bool:
    """True when a sanitizer violation should raise instead of only
    counting (``DLT_SANITIZERS_FATAL=1``)."""
    return os.environ.get("DLT_SANITIZERS_FATAL", "0") not in ("", "0")
