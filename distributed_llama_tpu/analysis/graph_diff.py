"""Golden-ladder drift detection and the differential equivalence prover.

Two static gates on top of the canonical fingerprints
(analysis/jaxpr_tools.py) and the declared contracts
(analysis/graph_audit.py):

* **Golden fingerprints** — every warm-ladder program's canonical hash +
  primitive histograms are checked into ``analysis/golden/<config>.json``.
  ``--check`` re-traces the ladder and fails on ANY structural drift with
  a readable ±primitive diff; ``--bless`` re-writes the goldens after an
  *intentional* graph change (the diff goes in the PR for review). The
  coverage gate additionally proves every ``engine.warm_plan()`` entry
  carries both a declared contract and a golden fingerprint — a new
  program kind cannot land unaudited.

* **Differential equivalence prover** — the engine's variant axes are
  *declared transformations* of a baseline, and the prover asserts each
  variant's normalized diff is exactly the declared delta:

  - paged = contiguous + {page-table gather + remapped scatter writes}
    (runtime/paged_kv.py) — and NOTHING else: no new collective, no new
    dot, no undeclared primitive;
  - int8 = f32 + {convert_element_type, scale mul/div, the fused Pallas
    decode kernel} minus the HLO pool gathers (ops/kv_quant.py, PR 17) —
    with zero pool gathers when the fused kernel is active;
  - verify_k = prefill twin of the same shape + {argmax fusion}
    (runtime/speculative.py) — same collectives, same dot census;
  - masked = unmasked + {mask-table gathers + comparison/where selects}
    (runtime/grammar.py, PR 20) — grammar-constrained decoding may add
    ONLY the [S, V] table lookups and the select that pins illegal
    logits: no new dot, no new collective, and the prefill family
    (which never samples) must be bit-identical.

  Any undeclared primitive, extra collective, changed dot-dtype census,
  reintroduced pool gather, or lost cache donation fails with a diff
  naming the offending primitive.

Everything here is `jax.make_jaxpr` / `.lower()` only — no compilation,
no execution. CLI: ``python -m distributed_llama_tpu.analysis.graph_diff``
(or ``scripts/dlt_graph_diff.py``).
"""

from __future__ import annotations

import dataclasses
import json
import os

import jax

from . import graph_audit as ga
from .jaxpr_tools import (
    Fingerprint,
    diff_fingerprints,
    fingerprint,
    pool_gather_count,
    primitive_delta,
)

#: where blessed goldens live, keyed by config_key(engine)
GOLDEN_DIR = os.path.join(os.path.dirname(__file__), "golden")

GOLDEN_VERSION = 1


class GraphDiffError(AssertionError):
    """Golden drift, coverage hole, or a failed equivalence proof."""


# -- golden store -----------------------------------------------------------


def entry_key(entry) -> str:
    """The stable per-program key: ``kind[size|kvN]`` — same rendering the
    audit reports and the engine's watchdog labels use."""
    return f"{entry.kind}[{entry.size}|kv{entry.kv_len}]"


def config_key(engine) -> str:
    """One golden file per distinct program-shaping configuration: layout,
    stored-KV dtype, compute dtype, batch/chunk geometry, speculative and
    prefix ladders, and mesh topology all change the traced graphs."""
    import numpy as np

    cfg = engine.cfg
    layout = "paged" if getattr(engine, "paged", False) else "contig"
    kv = np.dtype(engine.cache.k.dtype).name
    compute = np.dtype(cfg.dtype).name
    spec = f"spec{engine.draft_k}" if engine.spec_mode else "nospec"
    pfx = (
        f"pfx{len(engine.prefix_cache.buckets)}"
        if engine.prefix_cache is not None and engine.prefix_cache.buckets
        else "nopfx"
    )
    mesh = "nomesh"
    if engine.mesh is not None:
        mesh = "-".join(
            f"{ax}{n}" for ax, n in engine.mesh.shape.items() if n > 1
        ) or "mesh1"
    # interpret-mode pallas changes WHICH kernels trace (the fused paged
    # decode kernel becomes CPU-eligible) — a different program family,
    # hence a different golden file
    pi = "_pi" if getattr(cfg, "pallas_interpret", False) else ""
    # a grammar arena threads the [S, V] mask-table + state operands into
    # every decode/verify program (runtime/grammar.py) — a different
    # program family, keyed by the arena's state capacity (the table
    # operand's shape, hence part of every masked fingerprint)
    gr = ""
    if getattr(engine, "grammar", None) is not None:
        gr = f"_gr{engine.grammar.n_states}"
    return (
        f"{layout}_{kv}_{compute}_b{engine.batch}"
        f"_c{engine.max_chunk}_d{engine.decode_chunk_size}"
        f"_{spec}_{pfx}_{mesh}{pi}{gr}"
    )


def golden_path(golden_dir: str, key: str) -> str:
    return os.path.join(golden_dir, key + ".json")


def fingerprint_ladder(engine, ladder=None) -> dict:
    """entry_key -> Fingerprint for every warm-ladder program."""
    ladder = ga.warm_key_ladder(engine) if ladder is None else ladder
    return {
        entry_key(e): fingerprint(ga.trace_entry(engine, e)) for e in ladder
    }


def load_golden(golden_dir: str, key: str) -> dict | None:
    """{entry_key: Fingerprint} from the blessed file, or None when this
    config was never blessed."""
    path = golden_path(golden_dir, key)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        doc = json.load(f)
    return {
        k: Fingerprint.from_dict(d) for k, d in doc["programs"].items()
    }


def bless(engine, golden_dir: str = GOLDEN_DIR) -> str:
    """Re-trace the full warm ladder and write its fingerprints as the new
    goldens for this config. The resulting file diff IS the reviewable
    artifact of an intentional graph change."""
    key = config_key(engine)
    prints = fingerprint_ladder(engine)
    doc = {
        "version": GOLDEN_VERSION,
        "config": key,
        "jax": jax.__version__,
        "programs": {k: fp.to_dict() for k, fp in sorted(prints.items())},
    }
    os.makedirs(golden_dir, exist_ok=True)
    path = golden_path(golden_dir, key)
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    return path


def check_fingerprints(engine, golden_dir: str = GOLDEN_DIR) -> list:
    """Re-trace the warm ladder and diff against the blessed goldens.
    Returns problem strings — empty means zero structural drift. Every
    drifted program reports a primitive-level diff, never just a hash."""
    key = config_key(engine)
    golden = load_golden(golden_dir, key)
    if golden is None:
        return [
            f"no golden fingerprints for config {key!r} — bless this "
            "config first (scripts/dlt_graph_diff.py --bless)"
        ]
    problems = []
    current = fingerprint_ladder(engine)
    for ek in sorted(current):
        fp = current[ek]
        want = golden.get(ek)
        if want is None:
            problems.append(
                f"{ek}: program is on warm_plan() but has no golden "
                "fingerprint — an unreviewed ladder growth; re-bless "
                "after review"
            )
            continue
        if fp.hash != want.hash:
            diff = diff_fingerprints(want, fp)
            problems.append(
                f"{ek}: structural drift from blessed golden "
                f"({want.hash[:12]} -> {fp.hash[:12]}):\n      "
                + "\n      ".join(diff)
            )
    for ek in sorted(set(golden) - set(current)):
        problems.append(
            f"{ek}: golden fingerprint is stale — program left "
            "warm_plan(); re-bless after review"
        )
    return problems


def coverage_problems(engine, golden_dir: str = GOLDEN_DIR) -> list:
    """The 100%-coverage gate: every warm_plan() entry must carry BOTH a
    declared contract (graph_audit.contract_for) and a blessed golden
    fingerprint. Cheap — no tracing, just registry + file lookups."""
    problems = []
    golden = load_golden(golden_dir, config_key(engine)) or {}
    for entry in ga.warm_key_ladder(engine):
        ek = entry_key(entry)
        try:
            ga.contract_for(engine, entry)
        except ga.GraphAuditError as e:
            problems.append(f"{ek}: no declared contract — {e}")
        if ek not in golden:
            problems.append(
                f"{ek}: no golden fingerprint for config "
                f"{config_key(engine)!r} — bless it "
                "(scripts/dlt_graph_diff.py --bless)"
            )
    return problems


# -- declared transformation specs ------------------------------------------


@dataclasses.dataclass(frozen=True)
class TransformSpec:
    """ONE declared graph transformation between a baseline and a variant
    program. The prover admits exactly the declared delta: every primitive
    the variant adds must be in `allowed_added`, every primitive it drops
    in `allowed_removed`, and (unless waived) the collective multiset and
    the dot-dtype census must be IDENTICAL — a variant axis is never
    allowed to change what runs on the MXU or crosses the interconnect.

    `pin_pool_gathers`: the int8 clause — the variant must trace ZERO
    gathers of its KV pool wherever its contract pins them (the fused
    page-table-aware decode kernel, PR 17), and must never trace more
    pool gathers than the baseline anywhere else.
    """

    name: str
    allowed_added: frozenset
    allowed_removed: frozenset
    require_equal_collectives: bool = True
    require_equal_dots: bool = True
    pin_pool_gathers: bool = False


#: paged = contiguous + the page-table indirection: gathers of K/V pages
#: steered by the [b, slots] table, scatter writes remapped through it,
#: and the slot arithmetic (div/rem by page_size, bounds selects) that
#: computes page ids — in exchange for the contiguous layout's
#: dynamic_slice/dynamic_update_slice window movement (and, on the
#: admission prefill, the row-slice masking add/select_n arithmetic the
#: page table obviates: paged admission rides the plain b=1 forward).
PAGED_VS_CONTIGUOUS = TransformSpec(
    name="paged-vs-contiguous",
    allowed_added=frozenset(
        {
            "gather", "scatter", "concatenate", "reshape", "iota",
            "broadcast_in_dim", "convert_element_type", "pjit",
            "add", "sub", "mul", "div", "rem", "sign",
            "lt", "le", "ge", "eq", "ne", "and", "or", "min", "max",
            "select_n",
        }
    ),
    allowed_removed=frozenset(
        {
            "dynamic_slice", "dynamic_update_slice", "squeeze", "slice",
            "add", "select_n",
        }
    ),
)

#: int8 = f32 + the quantization arithmetic (convert_element_type, scale
#: mul/div, abs/round/reduce_max for requantization) and the fused Pallas
#: decode kernel's machinery (pallas_call, program_id, get/swap/cond) —
#: MINUS the HLO pool gathers the kernel exists to eliminate. No new pool
#: gathers, ever; zero where the fused-decode contract pins them.
INT8_VS_F32 = TransformSpec(
    name="int8-vs-f32",
    allowed_added=frozenset(
        {
            "convert_element_type", "mul", "div", "add", "sub",
            "abs", "round", "reduce_max", "max", "min", "sign", "exp",
            "lt", "le", "eq", "ne", "and", "select_n",
            "reshape", "broadcast_in_dim", "iota", "concatenate",
            "slice", "squeeze", "rem", "scatter", "pjit",
            "pallas_call", "program_id", "get", "swap", "cond",
        }
    ),
    allowed_removed=frozenset({"gather", "stop_gradient"}),
    pin_pool_gathers=True,
)

#: verify_k = a prefill twin of the same (size, kv) shape + the in-graph
#: argmax fusion over every drafted position — minus the last-position
#: slice extraction the prefill-shaped program does instead. Collectives
#: and dot census identical (the ISSUE-5 "verify rides prefill" contract).
VERIFY_VS_PREFILL = TransformSpec(
    name="verify-vs-prefill",
    allowed_added=frozenset(
        {"argmax", "reshape", "broadcast_in_dim", "iota", "concatenate",
         "scatter"}
    ),
    allowed_removed=frozenset(
        {"add", "lt", "select_n", "dynamic_slice", "dynamic_update_slice",
         "squeeze"}
    ),
)

#: masked = unmasked + the grammar constraint machinery, per decode step:
#: the mask lookup (table[state] gather -> `>= 0` legality -> select_n
#: pinning illegal logits to -inf) and the in-graph DFA advance
#: (table[state, tok] gather -> `< 0` free-row guard -> select_n), plus
#: the scan-carry plumbing (broadcast/concatenate/pjit) threading the
#: state vector. NOTHING may be removed, and the dot census + collective
#: multiset are pinned — masking is pure logits post-processing; an MXU
#: or interconnect delta would mean the mask leaked into the forward.
MASKED_VS_UNMASKED = TransformSpec(
    name="masked-vs-unmasked",
    allowed_added=frozenset(
        {
            "gather", "ge", "lt", "add", "select_n",
            "broadcast_in_dim", "concatenate", "pjit",
        }
    ),
    allowed_removed=frozenset(),
)

DECLARED_SPECS = {
    "paged": PAGED_VS_CONTIGUOUS,
    "int8": INT8_VS_F32,
    "verify": VERIFY_VS_PREFILL,
    "masked": MASKED_VS_UNMASKED,
}


# -- the prover -------------------------------------------------------------


def prove_delta(
    spec: TransformSpec,
    base_fp: Fingerprint,
    variant_fp: Fingerprint,
    label: str = "",
) -> list:
    """Assert variant = base + exactly the declared delta. Every problem
    line names the offending primitive."""
    tag = f"{spec.name}{f' {label}' if label else ''}"
    problems = []
    added, removed = primitive_delta(base_fp, variant_fp)
    for name in sorted(added):
        if name not in spec.allowed_added:
            problems.append(
                f"{tag}: undeclared primitive +{name} x{added[name]} in "
                "variant — not part of the declared transformation"
            )
    for name in sorted(removed):
        if name not in spec.allowed_removed:
            problems.append(
                f"{tag}: undeclared primitive -{name} x{removed[name]} "
                "dropped by variant — not part of the declared "
                "transformation"
            )
    if spec.require_equal_collectives:
        keys = set(base_fp.collectives) | set(variant_fp.collectives)
        for name in sorted(keys):
            nb = base_fp.collectives.get(name, 0)
            nv = variant_fp.collectives.get(name, 0)
            if nb != nv:
                problems.append(
                    f"{tag}: collective {name} changed x{nb} -> x{nv} — a "
                    "variant axis must never change what crosses the "
                    "interconnect"
                )
    if spec.require_equal_dots:
        keys = set(base_fp.dots) | set(variant_fp.dots)
        for key in sorted(keys):
            nb = base_fp.dots.get(key, 0)
            nv = variant_fp.dots.get(key, 0)
            if nb != nv:
                problems.append(
                    f"{tag}: dot_general({key}) changed x{nb} -> x{nv} — a "
                    "variant axis must never change the matmul dtype census"
                )
    return problems


def _provable_entries(base_engine, variant_engine):
    """The (kind, size, kv) programs BOTH engines compile, excluding the
    layout-specific copy programs (prefix_* vs page_* — different kinds by
    construction, covered by their own contracts + goldens)."""
    keep = lambda e: not ga.KIND_REGISTRY[e.kind]["copy_program"]
    base = {entry_key(e): e for e in ga.warm_key_ladder(base_engine) if keep(e)}
    var = {entry_key(e): e for e in ga.warm_key_ladder(variant_engine) if keep(e)}
    shared = sorted(set(base) & set(var))
    only = sorted(set(base) ^ set(var))
    return [base[k] for k in shared], only


def prove_variant_pair(base_engine, variant_engine, spec: TransformSpec) -> list:
    """Prove every shared forward-shaped warm-ladder program of the
    variant engine equivalent to the baseline's modulo `spec`, plus the
    engine-wide clauses (cache donation survived, pool-gather pin)."""
    entries, unshared = _provable_entries(base_engine, variant_engine)
    problems = []
    if not entries:
        problems.append(
            f"{spec.name}: no shared warm-ladder programs to prove "
            f"(unshared: {unshared})"
        )
    for entry in entries:
        bj = ga.trace_entry(base_engine, entry)
        vj = ga.trace_entry(variant_engine, entry)
        problems += prove_delta(
            spec, fingerprint(bj), fingerprint(vj), entry_key(entry)
        )
        if spec.pin_pool_gathers:
            n_base = pool_gather_count(bj, base_engine.cache.k.shape)
            n_var = pool_gather_count(vj, variant_engine.cache.k.shape)
            contract = ga.contract_for(variant_engine, entry)
            if contract.forbid_pool_gather is not None and n_var:
                problems.append(
                    f"{spec.name} {entry_key(entry)}: gather x{n_var} "
                    "reintroduces the materialized KV-pool read the fused "
                    "page-table-aware decode kernel eliminated"
                )
            elif n_var > n_base:
                problems.append(
                    f"{spec.name} {entry_key(entry)}: gather of the KV pool "
                    f"x{n_base} -> x{n_var} — the int8 transformation must "
                    "never ADD pool-materializing gathers"
                )
    # the transformation must preserve donation: a variant that silently
    # un-donates the cache doubles HBM traffic with no functional symptom
    for p in ga.donation_problems(variant_engine):
        problems.append(f"{spec.name}: {p}")
    return problems


def prove_masked_twin(base_engine, masked_engine) -> list:
    """Prove the grammar-capable engine's warm ladder equivalent to the
    grammar-less twin's modulo MASKED_VS_UNMASKED. Two clauses sharpen the
    generic variant proof: the ladder itself must be identical (masking
    adds operands to existing programs, never new programs), and the
    prefill family — which never samples — must be BIT-identical, not
    merely delta-clean."""
    spec = MASKED_VS_UNMASKED
    if getattr(masked_engine, "grammar", None) is None:
        return [
            f"{spec.name}: variant engine built no grammar arena "
            "(grammar-constrained decoding is single-chip device-decode "
            "only) — nothing to prove"
        ]
    entries, unshared = _provable_entries(base_engine, masked_engine)
    problems = []
    if unshared:
        problems.append(
            f"{spec.name}: masking changed the warm ladder itself "
            f"(unshared programs: {unshared}) — the arena must only add "
            "operands to existing programs"
        )
    for entry in entries:
        bf = fingerprint(ga.trace_entry(base_engine, entry))
        vf = fingerprint(ga.trace_entry(masked_engine, entry))
        if entry.kind in ("prefill", "prefill_row"):
            if bf.hash != vf.hash:
                problems.append(
                    f"{spec.name} {entry_key(entry)}: prefill program "
                    "changed under masking — prefill never samples, the "
                    "mask operands must not reach it:\n      "
                    + "\n      ".join(diff_fingerprints(bf, vf))
                )
            continue
        problems += prove_delta(spec, bf, vf, entry_key(entry))
    # masking must not cost the cache donation either (the masked scan
    # carries the state vector through the same donated-cache loop)
    for p in ga.donation_problems(masked_engine):
        problems.append(f"{spec.name}: {p}")
    return problems


def prove_verify_twin(engine) -> list:
    """Prove every speculative verify program equivalent to a prefill twin
    of the same (size, kv) shape, modulo VERIFY_VS_PREFILL. The twin is
    traced off-ladder — trace_entry works for any (kind, size, kv)."""
    spec = VERIFY_VS_PREFILL
    ladder = ga.warm_key_ladder(engine)
    targets = [e for e in ladder if e.kind in ("verify", "verify_row")]
    if not targets:
        return [
            f"{spec.name}: engine has no verify programs to prove "
            "(speculative off?)"
        ]
    problems = []
    for entry in targets:
        twin_kind = "prefill" if entry.kind == "verify" else "prefill_row"
        twin = ga.LadderEntry(twin_kind, entry.size, entry.kv_len)
        bj = ga.trace_entry(engine, twin)
        vj = ga.trace_entry(engine, entry)
        problems += prove_delta(
            spec, fingerprint(bj), fingerprint(vj),
            f"{entry_key(entry)} vs {entry_key(twin)}",
        )
    return problems


# -- CLI --------------------------------------------------------------------


def _clone_args(args, **overrides):
    import argparse

    d = dict(vars(args))
    d.update(overrides)
    return argparse.Namespace(**d)


def main(argv=None) -> int:
    import argparse
    import tempfile

    p = argparse.ArgumentParser(
        prog="dlt-graph-diff",
        description="golden jaxpr fingerprints + differential equivalence "
        "prover over the warm-key ladder",
    )
    ga.add_engine_args(p)
    p.add_argument(
        "--golden-dir", default=GOLDEN_DIR,
        help=f"golden fingerprint directory (default: {GOLDEN_DIR})",
    )
    p.add_argument(
        "--bless", action="store_true",
        help="re-trace the ladder and overwrite this config's goldens "
        "(the file diff is the reviewable artifact)",
    )
    p.add_argument(
        "--check", action="store_true",
        help="diff the traced ladder against the blessed goldens "
        "(default action when nothing else is asked)",
    )
    p.add_argument(
        "--coverage", action="store_true",
        help="the 100%% gate: every warm_plan() entry has a contract AND "
        "a golden",
    )
    p.add_argument(
        "--prove",
        choices=["paged", "int8", "verify", "masked", "all"], default=None,
        help="differential equivalence proof: paged-vs-contiguous, "
        "int8-vs-f32 (paged), verify-vs-prefill twins, "
        "masked-vs-unmasked (grammar), or all of them",
    )
    args = p.parse_args(argv)
    if not (args.bless or args.coverage or args.prove):
        args.check = True

    problems = []
    with tempfile.TemporaryDirectory() as d:
        engine = ga.engine_from_args(args, d)
        try:
            if args.bless:
                path = bless(engine, args.golden_dir)
                n = len(ga.warm_key_ladder(engine))
                print(f"🖋  blessed {n} program fingerprints -> {path}")
            if args.check:
                drift = check_fingerprints(engine, args.golden_dir)
                problems += drift
                print(
                    f"🔎 golden check [{config_key(engine)}]: "
                    + ("ok" if not drift else f"{len(drift)} problem(s)")
                )
            if args.coverage:
                cov = coverage_problems(engine, args.golden_dir)
                problems += cov
                print(
                    "🔎 coverage gate: "
                    + ("ok" if not cov else f"{len(cov)} hole(s)")
                )
        finally:
            engine.close()

        proofs = []
        if args.prove:
            proofs = (
                list(DECLARED_SPECS) if args.prove == "all" else [args.prove]
            )
        for mode in proofs:
            if mode == "masked" and (args.pp > 1 or args.tp > 1):
                # grammar-constrained decoding is single-chip only — on a
                # mesh config there is no masked ladder to prove
                print("🔎 prove masked-vs-unmasked: skipped (mesh config; "
                      "grammar is single-chip device-decode)")
                continue
            if mode == "masked":
                base = ga.engine_from_args(
                    _clone_args(args, grammar=False), d
                )
                var = ga.engine_from_args(_clone_args(args, grammar=True), d)
                try:
                    got = prove_masked_twin(base, var)
                finally:
                    base.close()
                    var.close()
            elif mode == "verify":
                e = ga.engine_from_args(
                    _clone_args(args, speculative="ngram"), d
                )
                try:
                    got = prove_verify_twin(e)
                finally:
                    e.close()
            elif mode == "paged":
                base = ga.engine_from_args(
                    _clone_args(args, kv_layout="contiguous"), d
                )
                var = ga.engine_from_args(
                    _clone_args(args, kv_layout="paged"), d
                )
                try:
                    got = prove_variant_pair(base, var, PAGED_VS_CONTIGUOUS)
                finally:
                    base.close()
                    var.close()
            else:  # int8: both engines paged, variant quantized
                base = ga.engine_from_args(
                    _clone_args(args, kv_layout="paged", kv_dtype=None), d
                )
                var = ga.engine_from_args(
                    _clone_args(args, kv_layout="paged", kv_dtype="int8"), d
                )
                try:
                    got = prove_variant_pair(base, var, INT8_VS_F32)
                finally:
                    base.close()
                    var.close()
            problems += got
            print(
                f"🔎 prove {DECLARED_SPECS[mode].name}: "
                + ("ok" if not got else f"{len(got)} problem(s)")
            )

    for prob in problems:
        print(f"  ! {prob}")
    return 1 if problems else 0


if __name__ == "__main__":
    raise SystemExit(main())
