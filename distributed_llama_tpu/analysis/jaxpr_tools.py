"""Canonical jaxpr normalization and structural fingerprints.

The graph-contract layer (analysis/graph_diff.py) needs to answer two
questions about a traced program without compiling or running it:

* "is this the SAME program we blessed last time?" — drift detection
  against golden fingerprints checked into ``analysis/golden/``;
* "how does variant B differ from baseline A, primitive by primitive?" —
  the differential equivalence prover's raw material.

Both reduce to a *canonical form* of the jaxpr: variables alpha-renamed in
first-use order (trace-time ``Var.count`` values are process-global and
differ run to run), equations rendered in their (deterministic) trace
order, sub-jaxprs (pjit/scan/while/cond/custom_*/pallas_call bodies)
inlined depth-first each with a fresh naming scope, and equation params
reduced to a stable value rendering that never leaks object identities
(function addresses, mesh device ids). The canonical form hashes to the
**structural fingerprint**; alongside the hash ride the primitive /
dot-dtype / collective / gather-scatter histograms, so a fingerprint
mismatch can always be explained as a readable ±primitive diff instead of
just "hash changed".

This module also owns the jaxpr *walking* helpers the rest of the analysis
layer builds on (``iter_eqns`` and friends) — ``analysis/graph_audit.py``
re-exports them for its callers.

Everything here is pure structure inspection: no compilation, no
execution, no device transfers.
"""

from __future__ import annotations

import dataclasses
import hashlib
from collections import Counter

import numpy as np

try:  # jax >= 0.4.x keeps these importable from jax.core (newer: jax.extend)
    from jax.extend.core import ClosedJaxpr, Jaxpr  # type: ignore
except ImportError:
    from jax.core import ClosedJaxpr, Jaxpr  # type: ignore


#: primitive names that are explicit cross-device collectives
COLLECTIVE_PRIMS = frozenset(
    {
        "psum",
        "pmax",
        "pmin",
        "all_gather",
        "all_to_all",
        "ppermute",
        "pshuffle",
        "reduce_scatter",
        "psum_scatter",
    }
)

#: primitive names that materialize indexed reads/writes — the "did the
#: paged layout add exactly the declared page-table movement?" census
GATHER_SCATTER_PRIMS = frozenset(
    {
        "gather",
        "scatter",
        "scatter-add",
        "scatter_add",
        "dynamic_slice",
        "dynamic_update_slice",
    }
)


# -- jaxpr walking ----------------------------------------------------------


def _sub_jaxprs(eqn):
    """Yield every jaxpr nested in an eqn's params (pjit/scan/while/cond/
    custom_* / pallas_call bodies), each exactly once."""
    for v in eqn.params.values():
        vals = v if isinstance(v, (list, tuple)) else (v,)
        for x in vals:
            if isinstance(x, ClosedJaxpr):
                yield x.jaxpr
            elif isinstance(x, Jaxpr):
                yield x


def iter_eqns(jaxpr):
    """Depth-first walk over every equation, descending into sub-jaxprs.

    Each sub-jaxpr is visited ONCE regardless of how many times it executes
    (a `lax.scan` body counts once) — the resulting census is a *structural
    fingerprint* of the program, which is exactly what a regression check
    wants: inserting one collective into a scan body changes the count by
    one, not by n_steps."""
    if isinstance(jaxpr, ClosedJaxpr):
        jaxpr = jaxpr.jaxpr
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in _sub_jaxprs(eqn):
            yield from iter_eqns(sub)


def _dtype_name(dtype) -> str:
    try:
        return np.dtype(dtype).name
    except TypeError:
        return str(dtype)  # extended dtypes (PRNG keys) have no numpy twin


def collective_counts(jaxpr) -> dict:
    """Structural count of explicit collective primitives."""
    c: Counter = Counter()
    for eqn in iter_eqns(jaxpr):
        name = eqn.primitive.name
        if name in COLLECTIVE_PRIMS:
            c[name] += 1
    return dict(c)


def dtype_census(jaxpr) -> set:
    """Set of dtypes appearing on any equation output."""
    out = set()
    for eqn in iter_eqns(jaxpr):
        for var in eqn.outvars:
            aval = getattr(var, "aval", None)
            if aval is not None and hasattr(aval, "dtype"):
                out.add(_dtype_name(aval.dtype))
    return out


def dot_input_census(jaxpr) -> Counter:
    """Counter of (lhs_dtype, rhs_dtype) pairs over every dot_general."""
    c: Counter = Counter()
    for eqn in iter_eqns(jaxpr):
        if eqn.primitive.name != "dot_general":
            continue
        lhs, rhs = eqn.invars[0].aval, eqn.invars[1].aval
        c[(_dtype_name(lhs.dtype), _dtype_name(rhs.dtype))] += 1
    return c


def primitive_counts(jaxpr) -> dict:
    """Structural count of EVERY primitive (the full histogram)."""
    c: Counter = Counter()
    for eqn in iter_eqns(jaxpr):
        c[eqn.primitive.name] += 1
    return dict(c)


def pool_gather_count(jaxpr, pool_shape) -> int:
    """Count of `gather` equations whose operand IS the KV pool (an invar
    of exactly `pool_shape`) — the materialized-page-view reads the fused
    int8 decode kernel exists to eliminate (scalar-prefetch page tables,
    ops/pallas_attention.py). The float paged twin legitimately carries
    them; the int8 decode contract pins them to zero."""
    shape = tuple(pool_shape)
    n = 0
    for eqn in iter_eqns(jaxpr):
        if eqn.primitive.name != "gather":
            continue
        if any(
            tuple(getattr(v.aval, "shape", ())) == shape for v in eqn.invars
        ):
            n += 1
    return n


# -- canonical form ---------------------------------------------------------


def _aval_str(aval) -> str:
    dt = getattr(aval, "dtype", None)
    shape = getattr(aval, "shape", None)
    if dt is None:
        return type(aval).__name__
    dims = "" if shape is None else ",".join(str(d) for d in shape)
    return f"{_dtype_name(dt)}[{dims}]"


def _canon_param(v) -> str:
    """Stable rendering of one equation param value: literals verbatim,
    containers recursively, dtypes by name, jaxprs as a placeholder (their
    bodies are normalized inline by `normalize`), everything else by TYPE
    name only — a function object, a sharding carrying mesh device ids, or
    any repr with a memory address must never reach the hash."""
    if isinstance(v, (ClosedJaxpr, Jaxpr)):
        return "<jaxpr>"
    if v is None or isinstance(v, (bool, int, float, complex, str)):
        return repr(v)
    if isinstance(v, (list, tuple)):
        return "(" + ",".join(_canon_param(x) for x in v) + ")"
    if isinstance(v, dict):
        return (
            "{"
            + ",".join(
                f"{k}:{_canon_param(v[k])}" for k in sorted(v, key=str)
            )
            + "}"
        )
    if isinstance(v, np.ndarray):
        return f"ndarray:{_dtype_name(v.dtype)}{tuple(v.shape)}"
    try:
        return f"dtype:{np.dtype(v).name}"
    except TypeError:
        pass
    if callable(v):
        return f"fn:{getattr(v, '__name__', type(v).__name__)}"
    return type(v).__name__


def normalize(jaxpr) -> list:
    """The canonical (alpha-renamed, stably-ordered) line rendering of a
    jaxpr: one line per equation, `o0:f32[2,8] = prim[k=v] i1 i2`, with
    sub-jaxprs inlined depth-first (indented, fresh variable scope per
    sub-jaxpr, visited in sorted-param-key order so the walk itself is
    deterministic). Two traces of the same program normalize identically
    regardless of trace-time Var counters; any structural change — an
    extra primitive, a changed dtype, a reordered operand — changes at
    least one line."""
    lines: list = []

    def render(jx, indent):
        if isinstance(jx, ClosedJaxpr):
            jx = jx.jaxpr
        names: dict = {}

        def name(v):
            val = getattr(v, "val", None)
            if val is not None or type(v).__name__ == "Literal":
                # literal operand: the value is part of the structure (a
                # changed constant IS graph drift); arrays render by shape
                if isinstance(val, np.ndarray) and val.size > 8:
                    return f"lit:{_dtype_name(val.dtype)}{tuple(val.shape)}"
                return f"lit:{val!r}"
            if v not in names:
                names[v] = f"v{len(names)}"
            return names[v]

        pad = "  " * indent
        for v in list(jx.constvars) + list(jx.invars):
            name(v)
        lines.append(
            pad
            + "in: "
            + " ".join(
                f"{name(v)}:{_aval_str(v.aval)}"
                for v in list(jx.constvars) + list(jx.invars)
            )
        )
        for eqn in jx.eqns:
            params = ",".join(
                f"{k}={_canon_param(eqn.params[k])}"
                for k in sorted(eqn.params, key=str)
            )
            outs = " ".join(
                f"{name(v)}:{_aval_str(v.aval)}" for v in eqn.outvars
            )
            ins = " ".join(name(v) for v in eqn.invars)
            lines.append(
                f"{pad}{outs} = {eqn.primitive.name}[{params}] {ins}"
            )
            for k in sorted(eqn.params, key=str):
                v = eqn.params[k]
                vals = v if isinstance(v, (list, tuple)) else (v,)
                for x in vals:
                    if isinstance(x, (ClosedJaxpr, Jaxpr)):
                        render(x, indent + 1)
        lines.append(pad + "out: " + " ".join(name(v) for v in jx.outvars))

    render(jaxpr, 0)
    return lines


def structural_hash(jaxpr) -> str:
    """sha256 of the canonical form — THE program identity the golden
    ladder pins."""
    text = "\n".join(normalize(jaxpr))
    return hashlib.sha256(text.encode()).hexdigest()


# -- fingerprints -----------------------------------------------------------


@dataclasses.dataclass
class Fingerprint:
    """One program's structural identity: the canonical-form hash plus the
    histograms that make a mismatch explainable (and diffable) at the
    primitive level."""

    hash: str
    n_eqns: int
    primitives: dict  # primitive name -> count
    dots: dict  # "lhs_dtype x rhs_dtype" -> count
    collectives: dict  # collective primitive -> count
    gathers: dict  # gather/scatter-family primitive -> count

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "Fingerprint":
        return cls(
            hash=d["hash"],
            n_eqns=d["n_eqns"],
            primitives=dict(d.get("primitives", {})),
            dots=dict(d.get("dots", {})),
            collectives=dict(d.get("collectives", {})),
            gathers=dict(d.get("gathers", {})),
        )


def fingerprint(jaxpr) -> Fingerprint:
    prims = primitive_counts(jaxpr)
    return Fingerprint(
        hash=structural_hash(jaxpr),
        n_eqns=sum(prims.values()),
        primitives=prims,
        dots={
            f"{l} x {r}": n for (l, r), n in sorted(dot_input_census(jaxpr).items())
        },
        collectives=collective_counts(jaxpr),
        gathers={
            k: v
            for k, v in sorted(prims.items())
            if k in GATHER_SCATTER_PRIMS
        },
    )


def primitive_delta(a: Fingerprint, b: Fingerprint):
    """(added, removed) primitive Counters going a -> b: what the variant
    introduced and what it dropped, structurally."""
    ca, cb = Counter(a.primitives), Counter(b.primitives)
    added = Counter({k: v for k, v in (cb - ca).items() if v})
    removed = Counter({k: v for k, v in (ca - cb).items() if v})
    return added, removed


def diff_fingerprints(a: Fingerprint, b: Fingerprint) -> list:
    """Readable primitive-level diff between two fingerprints (empty when
    the structural hashes match). Lines name each drifted primitive with
    its count delta — the artifact a CI failure prints."""
    if a.hash == b.hash:
        return []
    lines = []
    added, removed = primitive_delta(a, b)
    for name in sorted(added):
        lines.append(f"+{name} x{added[name]}")
    for name in sorted(removed):
        lines.append(f"-{name} x{removed[name]}")
    for key in sorted(set(a.dots) | set(b.dots)):
        na, nb = a.dots.get(key, 0), b.dots.get(key, 0)
        if na != nb:
            lines.append(f"dot_general({key}): {na} -> {nb}")
    for key in sorted(set(a.collectives) | set(b.collectives)):
        na, nb = a.collectives.get(key, 0), b.collectives.get(key, 0)
        if na != nb:
            lines.append(f"collective {key}: {na} -> {nb}")
    if not lines:
        lines.append(
            "identical primitive census — structural reordering, a shape/"
            "dtype change, or an equation-param change (same op multiset)"
        )
    return lines
