"""Host-sync guard: implicit device→host transfers in the hot loop raise.

The decode/prefill loops are engineered so the ONLY device→host transfer
per chunk is the explicit token fetch on the engine's `_fetch_pool` worker
thread (overlapping the next dispatch round trip). Anything else — an
accidental ``np.asarray`` on a device array, a ``float(x)`` on a traced
scalar result, an implicit `__array__` conversion inside a logging call —
serializes the pipeline on a tunnel round trip and silently puts a
~100 ms floor under every step. Nothing checked this; now:

* :func:`host_sync_guard` wraps a hot loop in
  ``jax.transfer_guard_device_to_host("disallow")`` — a *thread-local*
  scope, so the worker thread's sanctioned fetches are untouched while any
  same-thread implicit transfer raises (on backends with real transfers;
  the CPU test backend has no device boundary, so there the scope is
  bookkeeping-only and the contract is exercised structurally);
* :func:`sanctioned_fetch` re-allows transfers for the few blessed
  same-thread sites (BatchSession.step's token fetch) and counts them
  (``sanitizer_d2h_sanctioned`` in StepStats → ``/stats``);
* violations that raise inside a guarded scope are counted
  (``sanitizer_d2h_violations``) and re-raised.

Opt-in via ``DLT_SANITIZERS=1`` (the engine wires its loops; see
runtime/engine.py `_sanitizer_scope`).
"""

from __future__ import annotations

import contextlib
import threading

import jax

_tls = threading.local()


def guard_active() -> bool:
    """True while the calling thread is inside a `host_sync_guard` scope."""
    return getattr(_tls, "depth", 0) > 0


def is_transfer_guard_error(e: BaseException) -> bool:
    """Does this exception come from a tripped jax transfer guard?"""
    return isinstance(e, RuntimeError) and "isallow" in str(e) and "transfer" in str(e)


def default_mode() -> str:
    """The guard level the sanitizer tier implies: ``DLT_SANITIZERS=1``
    alone runs at ``"log"`` — violations show in the backend log, user
    requests are untouched (safe on a production canary);
    ``DLT_SANITIZERS_FATAL=1`` upgrades to ``"disallow"`` — the transfer
    raises at its site and is counted (CI / canary-with-teeth mode)."""
    from . import sanitizers_fatal

    return "disallow" if sanitizers_fatal() else "log"


@contextlib.contextmanager
def host_sync_guard(stats=None, mode: str | None = None):
    """Guard the calling thread against implicit device→host transfers.

    `mode` defaults to :func:`default_mode` (log unless fatal). In
    ``"disallow"`` mode `stats` (a StepStats) receives a
    ``sanitizer_d2h_violations`` bump when a transfer trips the guard
    inside the scope; the error still propagates (a hot loop that silently
    ate a 100 ms sync would be lying about its latency model)."""
    if mode is None:
        mode = default_mode()
    _tls.depth = getattr(_tls, "depth", 0) + 1
    try:
        with jax.transfer_guard_device_to_host(mode):
            yield
    except Exception as e:
        # guard scopes nest (BatchSession.step around verify_row_round's
        # engine scope, generate around its dispatch scopes): one breach
        # unwinds through every level, so count and flight-record only at
        # the OUTERMOST scope — depth == 1 here because every inner
        # scope's finally already ran
        if is_transfer_guard_error(e) and getattr(_tls, "depth", 0) == 1:
            if stats is not None:
                stats.incr("sanitizer_d2h_violations")
            # fatal sanitizer breach: snapshot the trace ring before the
            # error unwinds the serving loop — the violating request's
            # spans are the post-mortem
            from ..runtime.tracing import flight_record

            flight_record(
                "sanitizer:d2h-violation",
                counters=stats.counters_snapshot() if stats else None,
            )
        raise
    finally:
        _tls.depth -= 1


@contextlib.contextmanager
def sanctioned_fetch(stats=None):
    """A blessed device→host fetch site inside (or outside) a guarded
    scope: re-allows transfers for the block and counts the fetch, so
    `/stats` shows exactly how many host syncs the serving loop performs."""
    if stats is not None:
        stats.incr("sanitizer_d2h_sanctioned")
    with jax.transfer_guard_device_to_host("allow"):
        yield
