"""Thread auditor: lock-order recording, long-hold detection, and
guarded-mutation checks for the serving layer's thread soup.

The serving stack runs at least five thread families against shared state:
request handler threads (emit-queue writers), the Batcher step loop, the
gateway's per-connection proxies + `Balancer.cond` waiters, the health
prober, the stall watchdog, and the chaos proxy's accept loop. Their
discipline — a strict lock order, short hold times, counters only mutated
under `_counter_lock` — is enforced by convention only; a violation
deadlocks or corrupts silently and reproduces never.

This module turns the convention into a recorded, checkable artifact:

* :class:`AuditedLock` — a drop-in lock proxy recording every
  acquire/release with owner, wait time, and hold time;
* :class:`ThreadAuditor` — aggregates the proxies into a **lock-order
  graph** (edge A→B = "B acquired while holding A"); `cycles()` finds
  order inversions (potential deadlocks) even when the schedule never
  actually deadlocked in the run; a hold longer than `long_hold_ms` is a
  recorded violation (a lock held across a device call or socket write
  starves every co-batched request);
* :class:`GuardedDict` — a dict whose mutations must happen while the
  owning lock is held by the mutating thread; anything else is recorded.
  `instrument_stepstats` wires it under `StepStats.counters/gauges`, so a
  counter bumped outside `_counter_lock` fails tests instead of dropping
  increments under load.

The auditor is a TEST/diagnosis harness (pure Python, no jax): tests wrap
the real locks via the `instrument_*` helpers, drive real traffic, then
`check()`.
"""

from __future__ import annotations

import threading
import time


class ThreadAuditError(AssertionError):
    """Lock-order cycle, long hold, or unguarded mutation detected."""


class AuditedLock:
    """Proxy over a `threading.Lock`/`RLock` recording order + hold times.

    Also usable as the lock of a `threading.Condition` (it exposes
    `_is_owned`, which Condition prefers over its probe-acquire fallback).
    Reentrant acquires are tracked with a depth count so RLock wrapping
    works; a plain Lock simply never re-enters."""

    def __init__(self, auditor: "ThreadAuditor", lock, name: str):
        self._auditor = auditor
        self._lock = lock
        self.name = name
        self.owner: int | None = None  # thread ident while held
        self._depth = 0
        self._acquired_at = 0.0

    # -- lock protocol ------------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1):
        t0 = time.perf_counter()
        if timeout is None or timeout < 0:
            ok = self._lock.acquire(blocking)  # dlt: allow(lock-with) — the proxy IS the lock implementation
        else:
            ok = self._lock.acquire(blocking, timeout)  # dlt: allow(lock-with) — see above
        if ok:
            self._auditor._on_acquire(self, time.perf_counter() - t0)
        return ok

    def release(self):
        self._auditor._on_release(self)
        self._lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self) -> bool:
        return self._lock.locked() if hasattr(self._lock, "locked") else self.owner is not None

    def _is_owned(self) -> bool:  # Condition protocol
        return self.held_by_current_thread()

    def held_by_current_thread(self) -> bool:
        return self.owner == threading.get_ident()


class GuardedDict(dict):
    """A dict whose MUTATIONS require `lock.held_by_current_thread()`.

    Reads stay unguarded (snapshot methods copy under the lock already;
    racy reads are the documented contract). A mutation without the lock is
    recorded as a violation — not raised inline, so the auditor reports
    every offender instead of dying on the first."""

    def __init__(self, auditor: "ThreadAuditor", lock: AuditedLock, name: str, init=()):
        super().__init__(init)
        self._auditor = auditor
        self._lock = lock
        self._name = name

    def _check(self, op: str):
        if not self._lock.held_by_current_thread():
            self._auditor.record_violation(
                "unguarded-mutation",
                f"{self._name}.{op} without holding {self._lock.name} "
                f"(thread {threading.current_thread().name})",
            )

    def __setitem__(self, k, v):
        self._check(f"__setitem__[{k!r}]")
        super().__setitem__(k, v)

    def __delitem__(self, k):
        self._check(f"__delitem__[{k!r}]")
        super().__delitem__(k)

    def pop(self, *a, **kw):
        self._check("pop")
        return super().pop(*a, **kw)

    def popitem(self):
        self._check("popitem")
        return super().popitem()

    def clear(self):
        self._check("clear")
        super().clear()

    def update(self, *a, **kw):
        self._check("update")
        super().update(*a, **kw)

    def setdefault(self, *a, **kw):
        self._check("setdefault")
        return super().setdefault(*a, **kw)


class ThreadAuditor:
    """Aggregates AuditedLock events into order edges + violations."""

    def __init__(self, long_hold_ms: float = 500.0):
        self.long_hold_ms = long_hold_ms
        self._mu = threading.Lock()  # guards edges/violations/hold stats
        self._tls = threading.local()
        self.edges: dict = {}  # (held_name, acquired_name) -> count
        self.violations: list = []  # (kind, message)
        self.hold_counts: dict = {}  # name -> n releases
        self.max_hold_ms: dict = {}  # name -> worst hold

    # -- wiring -------------------------------------------------------------

    def wrap(self, lock, name: str) -> AuditedLock:
        return AuditedLock(self, lock, name)

    def record_violation(self, kind: str, msg: str):
        with self._mu:
            self.violations.append((kind, msg))

    # -- lock event sinks ---------------------------------------------------

    def _held_stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _on_acquire(self, lock: AuditedLock, wait_s: float):
        ident = threading.get_ident()
        stack = self._held_stack()
        if lock.owner == ident:
            lock._depth += 1  # reentrant (RLock under the proxy)
            return
        with self._mu:
            for held in stack:
                if held is not lock:
                    key = (held.name, lock.name)
                    self.edges[key] = self.edges.get(key, 0) + 1
        lock.owner = ident
        lock._depth = 1
        lock._acquired_at = time.perf_counter()
        stack.append(lock)

    def _on_release(self, lock: AuditedLock):
        ident = threading.get_ident()
        if lock.owner != ident:
            self.record_violation(
                "foreign-release",
                f"{lock.name} released by thread "
                f"{threading.current_thread().name} which does not own it",
            )
            return
        lock._depth -= 1
        if lock._depth > 0:
            return
        hold_ms = (time.perf_counter() - lock._acquired_at) * 1000.0
        lock.owner = None
        stack = self._held_stack()
        if lock in stack:
            stack.remove(lock)
        with self._mu:
            self.hold_counts[lock.name] = self.hold_counts.get(lock.name, 0) + 1
            self.max_hold_ms[lock.name] = max(
                self.max_hold_ms.get(lock.name, 0.0), hold_ms
            )
        if hold_ms > self.long_hold_ms:
            self.record_violation(
                "long-hold",
                f"{lock.name} held {hold_ms:.1f} ms "
                f"(> {self.long_hold_ms:.0f} ms) by "
                f"{threading.current_thread().name}",
            )

    # -- analysis -----------------------------------------------------------

    def cycles(self) -> list:
        """Cycles in the recorded lock-order graph (each as a name list).
        Any cycle is a potential deadlock: two threads interleaving those
        acquire chains can block forever, whether or not this run did."""
        with self._mu:
            adj: dict = {}
            for a, b in self.edges:
                adj.setdefault(a, set()).add(b)
        out, done = [], set()
        for start in adj:
            if start in done:
                continue
            path, on_path = [], set()

            def dfs(node):
                if node in on_path:
                    out.append(path[path.index(node):] + [node])
                    return
                if node in done:
                    return
                done.add(node)
                path.append(node)
                on_path.add(node)
                for nxt in adj.get(node, ()):
                    dfs(nxt)
                path.pop()
                on_path.remove(node)

            dfs(start)
        return out

    def check(self):
        """Raise ThreadAuditError on any recorded violation or order cycle."""
        problems = [f"{k}: {m}" for k, m in self.violations]
        problems += [
            "lock-order cycle: " + " -> ".join(c) for c in self.cycles()
        ]
        if problems:
            raise ThreadAuditError(
                "thread audit failed:\n  " + "\n  ".join(problems)
            )

    def report(self) -> str:
        with self._mu:
            lines = ["🔒 thread audit:"]
            for (a, b), n in sorted(self.edges.items()):
                lines.append(f"  order {a} -> {b} x{n}")
            for name in sorted(self.hold_counts):
                lines.append(
                    f"  hold  {name}: n={self.hold_counts[name]} "
                    f"max={self.max_hold_ms[name]:.2f} ms"
                )
            for k, m in self.violations:
                lines.append(f"  ! {k}: {m}")
        for c in self.cycles():
            lines.append("  ! cycle: " + " -> ".join(c))
        return "\n".join(lines)


# -- instrumentation helpers -------------------------------------------------


def instrument_stepstats(stats, auditor: ThreadAuditor, name: str = "stepstats"):
    """Swap StepStats' counter lock for an audited one and guard its
    counter/gauge dicts: a mutation outside `_counter_lock` is recorded."""
    lock = auditor.wrap(stats._counter_lock, f"{name}._counter_lock")
    stats._counter_lock = lock
    stats.counters = GuardedDict(auditor, lock, f"{name}.counters", stats.counters)
    stats.gauges = GuardedDict(auditor, lock, f"{name}.gauges", stats.gauges)
    return lock


def instrument_balancer(balancer, auditor: ThreadAuditor, name: str = "balancer"):
    """Audit the gateway Balancer's lock/condition (they share one mutex:
    `cond` is rebuilt around the audited proxy so both entry styles —
    `with self.lock` and `with self.cond` — are recorded)."""
    lock = auditor.wrap(balancer.lock, f"{name}.lock")
    balancer.lock = lock
    balancer.cond = threading.Condition(lock)
    return lock


def instrument_chaos(proxy, auditor: ThreadAuditor, name: str = "chaos"):
    """Audit a ChaosProxy's accept-counter lock."""
    lock = auditor.wrap(proxy._lock, f"{name}._lock")
    proxy._lock = lock
    return lock
