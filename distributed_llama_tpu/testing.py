"""Synthetic tiny-model generators for tests, demos, and benchmarks.

No real checkpoints ship with the repo, so tests build miniature but fully
structurally-faithful `.m` / `.t` files (same header keys, walk order, quant
formats as the reference converter emits) and run the whole stack on them.
"""

from __future__ import annotations

import numpy as np

from .formats import mfile
from .formats.mfile import ArchType, HiddenAct, MFileWriter, ModelHeader, RopeType, tensor_walk
from .formats.quants import FloatType
from .formats.tfile import TokenizerData, write_tfile


def tiny_header(
    arch: int = ArchType.LLAMA,
    dim: int = 64,
    hidden_dim: int = 160,
    n_layers: int = 3,
    n_heads: int = 4,
    n_kv_heads: int = 2,
    vocab_size: int = 256,
    seq_len: int = 128,
    head_dim: int = 0,
    n_experts: int = 0,
    n_active_experts: int = 0,
    moe_hidden_dim: int = 0,
    rope_type: int = RopeType.LLAMA,
    rope_theta: float = 10000.0,
    weight_type: int = FloatType.Q40,
    rope_scaling_factor: float = 1.0,
    # llama-3.1 wavelength-dependent scaling knobs (only written to the
    # header when rope_scaling_factor != 1.0, matching the converter; the
    # .m header stores them as int32, so integral values only). Defaults
    # are the llama-3.1 release values (factor 8 / low 1 / high 4 / 8192).
    rope_scaling_low_freq_factor: float = 1.0,
    rope_scaling_high_freq_factor: float = 4.0,
    rope_scaling_orig_max_seq_len: int = 8192,
) -> ModelHeader:
    h = ModelHeader(
        version=1,
        arch_type=arch,
        dim=dim,
        hidden_dim=hidden_dim,
        moe_hidden_dim=moe_hidden_dim,
        n_layers=n_layers,
        n_heads=n_heads,
        n_kv_heads=n_kv_heads,
        n_experts=n_experts,
        n_active_experts=n_active_experts,
        vocab_size=vocab_size,
        seq_len=seq_len,
        hidden_act=HiddenAct.SILU,
        rope_theta=rope_theta,
        rope_type=rope_type,
        rope_scaling_factor=rope_scaling_factor,
        rope_scaling_low_freq_factor=rope_scaling_low_freq_factor,
        rope_scaling_high_freq_factor=rope_scaling_high_freq_factor,
        rope_scaling_orig_max_seq_len=rope_scaling_orig_max_seq_len,
        norm_epsilon=1e-5,
        weight_type=weight_type,
        head_dim=head_dim,
    )
    return h.finalize()


def header_kv(h: ModelHeader) -> dict[int, int]:
    """Header key/value pairs as the converter would emit them (all int32)."""
    kv = {
        mfile.K_VERSION: 1,
        mfile.K_ARCH_TYPE: h.arch_type,
        mfile.K_DIM: h.dim,
        mfile.K_HIDDEN_DIM: h.hidden_dim,
        mfile.K_N_LAYERS: h.n_layers,
        mfile.K_N_HEADS: h.n_heads,
        mfile.K_N_KV_HEADS: h.n_kv_heads,
        mfile.K_N_EXPERTS: h.n_experts,
        mfile.K_N_ACTIVE_EXPERTS: h.n_active_experts,
        mfile.K_VOCAB_SIZE: h.vocab_size,
        mfile.K_SEQ_LEN: h.orig_seq_len or h.seq_len,
        mfile.K_HIDDEN_ACT: h.hidden_act,
        mfile.K_ROPE_THETA: int(h.rope_theta),
        mfile.K_WEIGHT_FLOAT_TYPE: h.weight_type,
        mfile.K_ROPE_TYPE: h.rope_type,
        mfile.K_HEAD_DIM: h.head_dim,
        mfile.K_NORM_EPSILON: 5 if abs(h.norm_epsilon - 1e-5) < 1e-9 else 6,
    }
    if h.rope_scaling_factor != 1.0:
        kv[mfile.K_ROPE_SCALING_FACTOR] = int(h.rope_scaling_factor)
        kv[mfile.K_ROPE_SCALING_LOW_FREQ_FACTOR] = int(h.rope_scaling_low_freq_factor)
        kv[mfile.K_ROPE_SCALING_HIGH_FREQ_FACTORY] = int(h.rope_scaling_high_freq_factor)
        kv[mfile.K_ROPE_SCALING_ORIG_MAX_SEQ_LEN] = h.rope_scaling_orig_max_seq_len
    if h.moe_hidden_dim:
        kv[mfile.K_MOE_HIDDEN_DIM] = h.moe_hidden_dim
    return kv


def write_tiny_model(path: str, h: ModelHeader, seed: int = 0, scale: float = 0.05) -> ModelHeader:
    """Write a random-weight .m file for ``h``; returns the header re-read back."""
    rng = np.random.default_rng(seed)
    # Recompute the walk against a header whose header_bytes matches what the
    # writer will emit, so offsets line up.
    kv = header_kv(h)
    h.header_bytes = 8 + len(kv) * 8
    with MFileWriter(path, kv) as w:
        for spec in tensor_walk(h):
            if spec.role in ("norm0", "norm1", "final_norm", "q_norm", "k_norm"):
                x = 1.0 + rng.standard_normal(spec.shape).astype(np.float32) * 0.01
            else:
                x = rng.standard_normal(spec.shape).astype(np.float32) * scale
            w.write_tensor(x, spec.float_type)
    return h


def _vocab_tokenizer(
    base_vocab: list[bytes],
    n_special: int = 3,
    chat_template: str | None = None,
    pad_to: int = 0,
    filler: str = "<pad{}>",
) -> TokenizerData:
    """Shared BPE fixture scaffolding: `base_vocab` single-unit tokens, a few
    merged words (so BPE has something to do), bos + specials after the
    regular vocab (mirroring the reference's layout assumption that ``bos_id``
    splits regular from special vocab), then filler tokens up to ``pad_to`` so
    any sampled id stays decodable."""
    vocab = list(base_vocab)
    scores = [0.0] * len(vocab)
    for word, sc in ((b"he", 1.0), (b"ll", 1.1), (b"hell", 2.0), (b"hello", 3.0), (b" wo", 1.2), (b"world", 3.0)):
        vocab.append(word)
        scores.append(sc)
    bos_id = len(vocab)
    specials = [b"<s>", b"</s>", b"<|eot|>"] + [f"<sp{i}>".encode() for i in range(max(0, n_special - 3))]
    vocab += specials
    scores += [0.0] * len(specials)
    while pad_to > len(vocab):
        vocab.append(filler.format(len(vocab)).encode())
        scores.append(0.0)
    return TokenizerData(
        vocab=vocab,
        scores=scores,
        bos_id=bos_id,
        eos_token_ids=[bos_id + 1, bos_id + 2],
        add_bos=True,
        chat_template=chat_template,
        max_token_length=max(len(v) for v in vocab),
    )


def byte_vocab_tokenizer(
    n_special: int = 8, chat_template: str | None = None, pad_to: int = 0
) -> TokenizerData:
    """A 256-byte-vocabulary tokenizer plus a few special tokens — any byte
    string encodes; decoding may produce raw/invalid UTF-8."""
    return _vocab_tokenizer(
        [bytes([i]) for i in range(256)], n_special, chat_template, pad_to
    )


def ascii_vocab_tokenizer(pad_to: int = 0, chat_template: str | None = None) -> TokenizerData:
    """A printable-ASCII vocabulary: every token decodes to a unique printable
    piece with no raw bytes, so a decoded stream (e.g. the reference CLI's
    per-token output, reference dllama.cpp:95-121) maps back to token ids
    unambiguously — the tool for cross-engine token-parity tests."""
    return _vocab_tokenizer(
        [bytes([i]) for i in range(32, 127)], 3, chat_template, pad_to,
        filler="<f{:04d}>",
    )


def write_tiny_tokenizer(path: str, **kw) -> TokenizerData:
    t = byte_vocab_tokenizer(**kw)
    write_tfile(path, t)
    return t
