"""Command-line entry point — the `dllama` analogue.

Modes (reference: src/dllama.cpp:325-359):
  inference   benchmark generation with eval/pred tok/s, TTFT, wall times
  chat        interactive REPL using the tokenizer's chat template
  perplexity  next-token probability evaluation over the prompt

The reference's `worker` mode does not exist here: there are no TCP workers —
multi-chip execution is a `jax.sharding.Mesh` given via --tp/--pp
(parallel/), with XLA collectives where the reference ran socket all-reduce.

Usage:
  python -m distributed_llama_tpu.cli inference --model m.m --tokenizer t.t \
      --prompt "Hello" --steps 64
"""

from __future__ import annotations

import argparse
import sys

from .runtime.engine import InferenceEngine
from .tokenizer import (
    ChatItem,
    ChatTemplateGenerator,
    EOS_FOUND,
    EOS_MAYBE,
    EosDetector,
    Sampler,
    TEMPLATE_UNKNOWN,
    Tokenizer,
)


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="distributed_llama_tpu")
    p.add_argument("mode", choices=["inference", "chat", "perplexity", "worker"])
    p.add_argument("--model", required=False, default=None)
    p.add_argument("--tokenizer", required=False, default=None)
    p.add_argument("--prompt", default=None)
    p.add_argument("--steps", type=int, default=0)
    p.add_argument("--max-seq-len", type=int, default=0)
    p.add_argument("--temperature", type=float, default=0.8)
    p.add_argument("--topp", type=float, default=0.9)
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--chat-template", default=None)
    # TPU-native knobs (replace --nthreads/--workers/--gpu-index):
    p.add_argument("--compute-dtype", choices=["bfloat16", "float32"], default="bfloat16")
    p.add_argument(
        "--cache-dtype", "--kv-dtype", dest="cache_dtype",
        choices=["bfloat16", "float32", "int8"], default=None,
        help="KV cache storage dtype (default DLT_KV_DTYPE env, else the "
        "compute-dtype default): 'int8' stores quantized KV with f32 "
        "per-(token, head) scale sidecars — half the decode KV traffic "
        "(ops/kv_quant.py; single-chip only, meshes fall back to float; "
        "docs/SERVING.md 'Quantized KV cache')",
    )
    p.add_argument("--tp", type=int, default=1, help="tensor-parallel mesh size")
    p.add_argument("--pp", type=int, default=1, help="pipeline-parallel mesh size")
    p.add_argument("--sp", type=int, default=1, help="sequence-parallel mesh size (long context)")
    p.add_argument("--ep", type=int, default=1, help="expert-parallel mesh size (MoE)")
    p.add_argument("--dp", type=int, default=1, help="data-parallel mesh size (batch)")
    p.add_argument(
        "--batch", type=int, default=1,
        help="engine batch rows (independent per-row sequences; the API "
        "server batches concurrent requests into them)",
    )
    # multi-host (pod) launch — the reference's `--workers host:port ...`
    # analogue. Every host runs the SAME command (multi-controller SPMD);
    # these wire jax.distributed.initialize, after which the mesh axes
    # below span ALL hosts' chips. On TPU pod slices with the platform's
    # metadata available, a bare --distributed suffices (docs/DISTRIBUTED.md).
    p.add_argument(
        "--distributed", action="store_true",
        help="initialize the multi-controller runtime (TPU pod metadata "
        "supplies coordinator/process ids; otherwise pass the flags below)",
    )
    p.add_argument("--coordinator", default=None, metavar="HOST:PORT",
                   help="coordinator address (process 0's reachable address)")
    p.add_argument("--num-processes", type=int, default=None)
    p.add_argument("--process-id", type=int, default=None)
    p.add_argument(
        "--host-decode", action="store_true",
        help="per-token host decode loop (bit-parity RNG with the reference; "
        "slower than the chunked on-device decode)",
    )
    # accepted-for-compat knobs from the reference CLI (no-ops or remapped):
    p.add_argument("--nthreads", type=int, default=None, help=argparse.SUPPRESS)
    p.add_argument("--buffer-float-type", default=None, help=argparse.SUPPRESS)
    p.add_argument("--net-turbo", default=None, help=argparse.SUPPRESS)
    p.add_argument("--max-batch-size", "--nbatches", dest="max_chunk", type=int, default=32)
    p.add_argument("--prefill-chunk-size", type=int, default=0)
    p.add_argument("--prefill-chunk-threshold", type=int, default=128)
    p.add_argument(
        "--prefix-cache-mb", type=int, default=-1,
        help="HBM budget for the radix prefix cache (cross-request KV reuse "
        "over shared prompts; runtime/prefix_cache.py). -1 = "
        "DLT_PREFIX_CACHE_MB env, defaulting to 512; 0 disables",
    )
    p.add_argument(
        "--speculative", choices=["off", "ngram", "model"], default=None,
        help="speculative decoding draft source for greedy requests "
        "(runtime/speculative.py): ngram = prompt-lookup over the live "
        "context (model-free), model = a second engine from --draft-model. "
        "Default: DLT_SPECULATIVE env, else ngram for the CLI/server",
    )
    p.add_argument(
        "--draft-k", type=int, default=0,
        help="max drafted tokens per verify round, bucketed at {4, 8} "
        "(default: DLT_DRAFT_K env, else 4)",
    )
    p.add_argument(
        "--draft-model", default=None,
        help=".m file for the --speculative model draft engine (a smaller "
        "model drafting autoregressively)",
    )
    p.add_argument(
        "--kv-layout", choices=["contiguous", "paged"], default=None,
        help="KV cache layout (runtime/paged_kv.py): 'paged' = fixed-size "
        "KV pages + per-row page tables with zero-copy prefix sharing and "
        "copy-on-write (the batch-scale layout; single-chip AND pure "
        "pp x tp pipeline meshes); 'contiguous' = per-row seq_len slabs "
        "(the bit-identity A/B arm). Default: DLT_KV_LAYOUT env, else "
        "PAGED for the CLI/server entry points (library engines default "
        "contiguous)",
    )
    p.add_argument(
        "--kv-page-size", type=int, default=0,
        help="tokens per KV page (power of two; default DLT_KV_PAGE env, "
        "else 16 — aligned with the prefix cache's bucket floor so hits "
        "share whole pages)",
    )
    p.add_argument(
        "--kv-pool-mb", type=int, default=0,
        help="paged KV pool HBM budget in MB (default DLT_KV_POOL_MB env, "
        "else contiguous parity: batch x seq_len worth of pages). Smaller "
        "pools serve MORE rows per HBM byte when rows are shorter than "
        "seq_len; exhaustion parks admissions and sheds with 503",
    )
    p.add_argument(
        "--role", choices=["unified", "prefill", "decode"], default=None,
        help="disaggregated serving role (server/disagg.py): 'prefill' "
        "workers answer POST /v1/prefill (run the prompt, ship bucket-"
        "aligned KV); 'decode' workers fetch shipped KV from --prefill-peer "
        "before admission and stream tokens; 'unified' (default, or "
        "DLT_ROLE env) serves everything locally. Both roles serve both "
        "KV layouts; DLT_KV_TRANSPORT={auto,device,http} picks the "
        "transfer path per peer (runtime/kv_transport.py)",
    )
    p.add_argument(
        "--prefill-peer", action="append", default=None, metavar="HOST:PORT",
        help="prefill worker a --role decode replica fetches KV from "
        "(repeatable; round-robin with in-request failover; default "
        "DLT_PREFILL_PEER env, comma-separated). A dead peer degrades the "
        "request to local prefill, never fails it",
    )
    return p


def make_engine(args) -> InferenceEngine:
    from .runtime.prefix_cache import resolve_budget_mb
    from .runtime.speculative import ModelDraft, resolve_draft_k, resolve_spec_mode

    max_chunk = args.prefill_chunk_size if args.prefill_chunk_size > 0 else args.max_chunk
    # radix prefix cache: ON by default for the CLI/server entry points
    # (serving workloads are where shared prefixes live); library engines
    # constructed directly keep the env-or-off default. One shared resolver
    # owns the env parsing — only the intended default differs.
    flag = getattr(args, "prefix_cache_mb", -1)
    prefix_mb = resolve_budget_mb(
        None if flag is None or flag < 0 else flag, default_mb=512
    )
    # speculative decoding: ngram (prompt-lookup) by default for the
    # CLI/server entry points — greedy requests only, zero extra FLOPs,
    # bit-identical output; library engines keep the env-or-off default
    spec_mode = resolve_spec_mode(getattr(args, "speculative", None), default="ngram")
    draft_k = resolve_draft_k(getattr(args, "draft_k", 0) or None)
    draft_source = None
    if spec_mode == "model" and not getattr(args, "draft_model", None):
        raise ValueError("--speculative model requires --draft-model")
    batch = getattr(args, "batch", 1) or 1
    dp_axis = getattr(args, "dp", 1)
    # an explicit batch must be compatible with the dp mesh, not silently
    # overridden: every dp shard holds batch/dp rows
    if batch % dp_axis != 0 and batch != 1:
        raise ValueError(
            f"--batch {batch} must be a multiple of --dp {dp_axis} "
            f"(each dp shard holds batch/dp rows)"
        )
    batch = max(batch, dp_axis)
    mesh = None
    sp = getattr(args, "sp", 1)
    ep = getattr(args, "ep", 1)
    dp = getattr(args, "dp", 1)
    distributed = getattr(args, "distributed", False) or getattr(args, "coordinator", None)
    if distributed:
        # must run before anything initializes the local backend; after it,
        # jax.devices() is the GLOBAL device set and the mesh spans hosts
        from .parallel.multihost import initialize_distributed, make_multihost_mesh

        initialize_distributed(
            coordinator_address=getattr(args, "coordinator", None),
            num_processes=getattr(args, "num_processes", None),
            process_id=getattr(args, "process_id", None),
        )
        # bare --distributed with no axis flags = TP over every chip in the
        # pod (tp=0 means "all remaining devices" to make_multihost_mesh)
        tp = 0 if (args.tp == 1 and args.pp == 1 and sp == ep == dp == 1) else args.tp
        mesh = make_multihost_mesh(tp=tp, pp=args.pp, sp=sp, ep=ep, dp=dp)
    elif args.tp > 1 or args.pp > 1 or sp > 1 or ep > 1 or dp > 1:
        from .parallel import make_mesh

        mesh = make_mesh(tp=args.tp, pp=args.pp, sp=sp, ep=ep, dp=dp)
    if spec_mode == "model":
        # the draft engine: batch=1 greedy chain, its own warm ladder
        # (warmed from the main engine's warmup()); speculation and the
        # prefix cache are pinned OFF on it — explicit args, so an ambient
        # DLT_SPECULATIVE=model cannot recurse into draft-of-draft engines.
        # Built AFTER the arg validation above so a bad --batch/--dp combo
        # never loads draft weights; torn down if the main engine fails.
        draft_source = ModelDraft(
            InferenceEngine(
                args.draft_model, compute_dtype=args.compute_dtype, batch=1,
                device_decode=True, prefix_cache_mb=0, speculative="off",
            ),
            owns=True,
        )
    from .runtime.paged_kv import resolve_kv_layout

    # paged is the serving DEFAULT for the CLI/server entry points (library
    # engines constructed directly keep the contiguous default): it went
    # through its soak — mesh twins token-identical to contiguous, zero
    # post-warmup recompiles under sanitizers, disagg roles on both
    # transports — and the default pool sizes at contiguous parity, so it
    # never fits fewer tokens. One shared resolver owns the env parsing.
    kv_layout = resolve_kv_layout(getattr(args, "kv_layout", None), default="paged")
    if kv_layout == "paged" and mesh is not None:
        # the mesh-paged path (runtime/kv_transport.py's mesh plumbing)
        # covers the reference's PPxTP topology: the pure pp x tp shard_map
        # pipeline. Other extents keep contiguous — say so instead of
        # failing the launch (sp shards the very axis paging replaces).
        pure_pptp = mesh.shape.get("dp", 1) == 1 and sp == 1 and ep == 1 and (
            mesh.shape["pp"] > 1 or mesh.shape["tp"] > 1
        )
        if not pure_pptp:
            print(
                "⚠️  --kv-layout paged covers single-chip and pure pp x tp "
                "pipeline meshes: this topology keeps the contiguous KV layout"
            )
            kv_layout = "contiguous"
    from .runtime.grammar import resolve_grammar_enabled

    # grammar-constrained decoding (runtime/grammar.py): ON by default for
    # the CLI/server entry points wherever it can actually serve — single-
    # chip device-decode, like speculation and the prefix cache the arena
    # composes with. Other topologies default off (an explicit DLT_GRAMMAR=1
    # still reaches the engine, which warns and serves unconstrained);
    # library engines constructed directly keep the env-or-off default.
    gr_capable = mesh is None and not getattr(args, "host_decode", False)
    grammar = resolve_grammar_enabled(None, default="1" if gr_capable else "0")
    try:
        engine = InferenceEngine(
            args.model,
            compute_dtype=args.compute_dtype,
            cache_dtype=args.cache_dtype,
            max_seq_len=args.max_seq_len,
            max_chunk=max_chunk,
            mesh=mesh,
            batch=batch,
            device_decode=not getattr(args, "host_decode", False),
            verbose=True,
            prefix_cache_mb=prefix_mb,
            speculative=spec_mode or "off",
            draft_k=draft_k,
            draft_source=draft_source,
            kv_layout=kv_layout,
            kv_page_size=getattr(args, "kv_page_size", 0) or None,
            kv_pool_mb=getattr(args, "kv_pool_mb", 0) or None,
            grammar=grammar,
        )
    except BaseException:
        # the main engine failed to build: release the draft engine's
        # fetch-pool thread + weights instead of leaking them
        if draft_source is not None:
            draft_source.close()
        raise
    if prefix_mb > 0 and engine.prefix_cache is None:
        # a requested prefix cache that cannot be built (sp>1 shards the
        # cache's seq axis; or the context is too small to publish) means
        # ZERO KV reuse across requests — every chat turn re-prefills its
        # whole history. Say so at startup instead of degrading silently.
        print(
            "⚠️  prefix cache unavailable on this topology (sp>1 mesh or "
            "tiny context): cross-request KV reuse is OFF; multi-turn "
            "chats re-prefill their full history each turn"
        )
    return engine


def make_sampler(args, vocab_size: int) -> Sampler:
    seed = args.seed if args.seed is not None else 12345
    return Sampler(vocab_size, args.temperature, args.topp, seed)


def run_inference(args) -> int:
    if not args.prompt:
        print("Prompt is required", file=sys.stderr)
        return 1
    if args.steps == 0:
        print("Number of steps is required", file=sys.stderr)
        return 1
    engine = make_engine(args)
    tok = Tokenizer(args.tokenizer)
    sampler = make_sampler(args, engine.cfg.vocab_size)
    ids = tok.encode(args.prompt)

    print(args.prompt)
    pieces: list[str] = []

    def on_token(t):
        piece = tok.decode(t)
        pieces.append(piece or "")

    res = engine.generate(ids, args.steps, sampler=sampler, on_token=on_token)

    # one line per measured step (a chunk on the device-decode path, a token
    # on the host-loop path); no Sync column — under XLA compute and
    # collectives are one fused device program, a split is not observable
    for s in res.eval_steps:
        print(f"🔷️ Eval{s.eval_us // 1000:5d} ms | ({s.n_tokens} tokens)")
    pi = 0
    for s in res.pred_steps:
        text = "".join(pieces[pi : pi + s.n_tokens]) or "~"
        label = f"({s.n_tokens} tokens) " if s.n_tokens > 1 else ""
        print(f"🔶 Pred{s.eval_us // 1000:5d} ms | {label}{text}")
        pi += s.n_tokens

    n_eval = res.n_prompt_tokens - 1
    n_pred = res.n_pred_tokens
    eval_ms = sum(s.eval_us for s in res.eval_steps) / 1000.0
    pred_ms = sum(s.eval_us for s in res.pred_steps) / 1000.0
    print()
    print("Evaluation")
    print(f"   nBatches: {engine.max_chunk}")
    print(f"    nTokens: {n_eval}")
    if eval_ms > 0 and n_eval > 0:
        print(f"   tokens/s: {n_eval * 1000 / eval_ms:3.2f} ({eval_ms / n_eval:3.2f} ms/tok)")
    print("Prediction")
    print(f"    nTokens: {n_pred}")
    if pred_ms > 0 and n_pred > 0:
        print(f"   tokens/s: {n_pred * 1000 / pred_ms:3.2f} ({pred_ms / n_pred:3.2f} ms/tok)")
    print("Timing")
    print(f"  prefillMs: {res.prefill_us / 1000.0:3.2f}")
    print(f"     ttftMs: {(res.ttft_us or res.prefill_us) / 1000.0:3.2f}")
    print(f"   decodeMs: {res.decode_us / 1000.0:3.2f}")
    print(f"    totalMs: {res.total_us / 1000.0:3.2f}")
    print()
    print(engine.stats.report())
    return 0


def run_perplexity(args) -> int:
    """Reference: dllama.cpp:167-207 — sequential next-token probabilities.

    TPU upgrade: one batched logits_mode="all" pass per chunk instead of a
    per-token loop.
    """
    import numpy as np

    if not args.prompt:
        print("Prompt is required", file=sys.stderr)
        return 1
    engine = make_engine(args)
    tok = Tokenizer(args.tokenizer)
    ids = tok.encode(args.prompt)
    n = len(ids)
    print(f"Evaluating {n} tokens...")

    total_log_prob = 0.0
    pos = 0
    # chunked teacher-forced pass; logits for every position
    chunk = engine.max_chunk
    for i in range(0, n - 1, chunk):
        part = ids[i : i + chunk]
        arr_logits = engine.forward_tokens(part, i, logits_mode="all")[0]
        probs = _softmax_np(arr_logits)
        for j in range(len(part)):
            if i + j + 1 >= n:
                break
            p = max(float(probs[j, ids[i + j + 1]]), 1e-30)
            total_log_prob += float(np.log(p))
            pos += 1
            print(f"{pos:5d} / {n - 1}, prob={p:f}")

    avg = total_log_prob / (n - 1)
    print()
    print("Results")
    print(f"   perplexity: {float(np.exp(-avg)):f} (lower = better)")
    print(f"   avgLogProb: {avg:f}")
    print(f"   bitPerToken: {-avg / float(np.log(2.0)):f}")
    return 0


def _softmax_np(x):
    import numpy as np

    m = x.max(axis=-1, keepdims=True)
    e = np.exp(x - m)
    return e / e.sum(axis=-1, keepdims=True)


def run_chat(args) -> int:
    """Interactive chat REPL (reference: dllama.cpp:209-305)."""
    engine = make_engine(args)
    tok = Tokenizer(args.tokenizer)
    sampler = make_sampler(args, engine.cfg.vocab_size)

    template_type = (
        ChatTemplateGenerator.parse_type(args.chat_template)
        if args.chat_template
        else TEMPLATE_UNKNOWN
    )
    stops = [tok.piece(t).decode("utf-8", errors="replace") for t in tok.eos_token_ids]
    gen = ChatTemplateGenerator(template_type, tok.chat_template, stops[0] if stops else "")
    max_stop = max((len(s) for s in stops), default=0)

    try:
        sys_prompt = input("💻 System prompt (optional): ")
    except (EOFError, KeyboardInterrupt):
        print()
        return 0
    delta_items: list[ChatItem] = []
    if sys_prompt:
        delta_items.append(ChatItem("system", sys_prompt))

    pos = 0
    seq_len = engine.cfg.seq_len
    while pos < seq_len:
        user = ""
        try:
            while not user:
                user = input("\n👱 User\n> ")
        except (EOFError, KeyboardInterrupt):
            print()
            return 0
        delta_items.append(ChatItem("user", user))
        prompt = gen.generate(delta_items, True)
        ids = tok.encode(prompt.content, is_start=(pos == 0))
        if pos + len(ids) - 1 >= seq_len:
            break

        tok.reset_decoder()
        detector = EosDetector(tok.eos_token_ids, stops, max_stop, max_stop)
        print("\n🤖 Assistant")
        if prompt.public_prompt:
            print(prompt.public_prompt, end="")

        # chunked on-device decode with host-side stop scanning: the engine
        # never appends tokens past the stop (overrun cache writes are
        # overwritten by the next turn's prefill — engine.generate contract)
        state = {"stop": False}

        def on_token(t):
            piece = tok.decode(t)
            eos_type = detector.append(t, piece)
            if eos_type != EOS_MAYBE:
                delta = detector.get_delta()
                if delta:
                    print(delta, end="", flush=True)
                detector.reset()
            if eos_type == EOS_FOUND:
                state["stop"] = True

        res = engine.generate(
            ids, seq_len, sampler=sampler, pos_start=pos,
            on_token=on_token, stop_fn=lambda t: state["stop"],
        )
        pos = pos + len(ids) - 1 + res.n_pred_tokens
        delta_items.clear()
    print("(end of context)")
    return 0


def _worker_migration_message() -> int:
    # the reference's cluster model (root + `dllama worker --port N`
    # processes, src/app.cpp:425-489) has no analogue here:
    # multi-controller SPMD runs the SAME command on every host. Greet
    # migrating scripts with the mapping instead of an argparse error.
    print(
        "this framework has no worker processes: multi-chip/multi-host "
        "execution runs the SAME command on every host.\n"
        "  reference:  dllama inference --workers h1:port h2:port ...\n"
        "  here:       <same inference command> --tp N      (one host)\n"
        "              <same inference command> --distributed "
        "--coordinator h0:port --num-processes P --process-id i  (pod)\n"
        "see docs/DISTRIBUTED.md",
        file=sys.stderr,
    )
    return 2


def main(argv=None) -> int:
    raw = list(argv) if argv is not None else sys.argv[1:]
    if raw[:1] == ["worker"]:
        # short-circuited before parsing so the reference's worker flags
        # don't get in the way
        return _worker_migration_message()
    args = build_arg_parser().parse_args(raw)
    if args.mode == "worker":
        # `worker` anywhere else in argv (e.g. after --model/--tokenizer)
        # parses fine — it is in the mode choices — and must get the same
        # migration message, not a silent exit
        return _worker_migration_message()
    if args.model is None or args.tokenizer is None:
        print("--model and --tokenizer are required", file=sys.stderr)
        return 2
    if args.mode == "inference":
        return run_inference(args)
    if args.mode == "perplexity":
        return run_perplexity(args)
    if args.mode == "chat":
        return run_chat(args)
    return 1


if __name__ == "__main__":
    sys.exit(main())
