"""Parameter pytrees and the `.m` weight loader.

Weights for all layers are *stacked* along a leading n_layers axis so the
forward pass can `lax.scan` over layers — one compiled layer body instead of
n_layers unrolled copies (compile time and HBM-code-size win; no reference
analogue, the reference builds n_layers explicit segments).

Q40 tensors stay quantized on device as `QuantTensor` (int8 + per-block
scales); F32/F16 tensors load as dense arrays. The loader replaces the
reference's root-mmap + TCP weight streaming (reference: loadLlmNetWeight,
src/llm.cpp:658-713 and NnRootWeightLoader, src/nn/nn-network.cpp:1818-1943):
on TPU each stacked tensor is handed to `jax.device_put` with an optional
`NamedSharding`, and JAX ships every chip exactly its shard.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..formats.mfile import MFileReader, TensorSpec
from ..formats.quants import FloatType
from ..ops.quant import QuantTensor
from .config import ModelConfig

# A weight is either a dense jnp array [out, in] or a QuantTensor.
Weight = Any


def _register(cls, fields):
    def flatten(s):
        return tuple(getattr(s, f) for f in fields), None

    def unflatten(aux, children):
        return cls(*children)

    jax.tree_util.register_pytree_node(cls, flatten, unflatten)
    return cls


@dataclass
class LayerParams:
    """Per-layer weights, each stacked with a leading [n_layers] axis.

    Decode makes one kernel dispatch per matmul, so the loader FUSES the
    row-split projections that share an input: q/k/v -> `wqkv` (always) and
    dense w1/w3 -> `w13` — 7 weight matmuls per layer become 4, with larger
    (better-streaming) shapes. The fused out axis is per-TP-shard
    interleaved (see _fuse_rows) so a plain out-axis sharding gives every
    shard exactly its own q|k|v (or w1|w3) slices. When fused, the separate
    fields are None; MoE expert stacks stay separate (the dispatch
    formulations index experts individually).
    """

    q: Optional[Weight]  # [L, q_dim, dim] — None when fused into wqkv
    k: Optional[Weight]  # [L, kv_dim, dim]
    v: Optional[Weight]  # [L, kv_dim, dim]
    wo: Weight  # [L, dim, q_dim]
    w1: Optional[Weight]  # [L, ff, dim] dense (None when fused) | [L, E, ff, dim] moe
    w2: Weight  # [L, dim, ff] dense | [L, E, dim, ff] moe
    w3: Optional[Weight]  # [L, ff, dim] dense (None when fused) | [L, E, ff, dim] moe
    norm0: jnp.ndarray  # [L, dim]
    norm1: jnp.ndarray  # [L, dim]
    q_norm: Optional[jnp.ndarray] = None  # [L, head_dim] (qwen3)
    k_norm: Optional[jnp.ndarray] = None  # [L, head_dim] (qwen3)
    moe_gate: Optional[jnp.ndarray] = None  # [L, E, dim] f32 (moe)
    wqkv: Optional[Weight] = None  # [L, q_dim+2*kv_dim, dim] fused projection
    w13: Optional[Weight] = None  # [L, 2*ff, dim] fused dense ffn in-proj


_register(
    LayerParams,
    ["q", "k", "v", "wo", "w1", "w2", "w3", "norm0", "norm1", "q_norm", "k_norm",
     "moe_gate", "wqkv", "w13"],
)


@dataclass
class ModelParams:
    embedding: jnp.ndarray  # [vocab, dim] (always dense; reference keeps F32)
    layers: LayerParams
    final_norm: jnp.ndarray  # [dim]
    wcls: Weight  # [vocab, dim]


_register(ModelParams, ["embedding", "layers", "final_norm", "wcls"])


@dataclass
class KVCache:
    """[n_layers, batch, seq_len, n_kv_heads, head_dim] key/value tensors.

    Functional replacement for the reference's per-layer key/value cache
    buffers updated by OP_SHIFT (reference: shiftForward,
    src/nn/nn-cpu-ops.cpp:1419-1441); under jit the dynamic-update-slice
    happens in place thanks to buffer donation.
    """

    k: jnp.ndarray
    v: jnp.ndarray
    # int8 KV arm (cache_dtype="int8"): per-(token, head) f32 dequant scales,
    # shaped like k/v minus the trailing head_dim axis. None on bf16/f32
    # engines — None children flatten away, so the float arms' leaf set (and
    # every donation/sharding contract over it) is unchanged.
    k_scale: Optional[jnp.ndarray] = None
    v_scale: Optional[jnp.ndarray] = None

    @property
    def batch(self) -> int:
        return self.k.shape[1]

    @property
    def seq_len(self) -> int:
        return self.k.shape[2]

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None


_register(KVCache, ["k", "v", "k_scale", "v_scale"])


def init_kv_cache(cfg: ModelConfig, batch: int, seq_len: int | None = None) -> KVCache:
    shape = (
        cfg.n_layers,
        batch,
        seq_len if seq_len is not None else cfg.seq_len,
        cfg.n_kv_heads,
        cfg.head_dim,
    )
    k = jnp.zeros(shape, dtype=cfg.kv_dtype)
    v = jnp.zeros(shape, dtype=cfg.kv_dtype)
    if cfg.kv_quantized:
        return KVCache(
            k=k, v=v,
            k_scale=jnp.zeros(shape[:-1], jnp.float32),
            v_scale=jnp.zeros(shape[:-1], jnp.float32),
        )
    return KVCache(k=k, v=v)


# ---------------------------------------------------------------------------
# Loading
# ---------------------------------------------------------------------------


def _load_one(reader: MFileReader, spec: TensorSpec, dense_dtype) -> Any:
    """Host-side load of a single tensor: QuantTensor parts (in the device T
    layout, ops/quant.py) or a dense ndarray."""
    if spec.float_type == FloatType.Q40 and len(spec.shape) == 2:
        out_f, in_f = spec.shape
        # fast path: the native codec unpacks + transposes in one
        # multithreaded C++ pass (native/q40_codec.cpp)
        from ..formats.native import q40_unpack_t_native

        nat = q40_unpack_t_native(reader.raw(spec), out_f, in_f)
        if nat is not None:
            from ..ops.quant import pack_q

            qt, dt = nat
            return pack_q(qt), dt
        from ..ops.quant import q40_to_t_layout

        q, d = reader.tensor_q40(spec)  # [out, in//32, 32], [out, in//32]
        return q40_to_t_layout(q, d)
    x = reader.tensor_f32(spec)
    return x.astype(dense_dtype) if len(spec.shape) == 2 else x


def _stack(parts: list) -> Any:
    """Stack host-side per-layer tensors; quant pairs stack componentwise."""
    if isinstance(parts[0], tuple):
        q = np.stack([p[0] for p in parts])
        d = np.stack([p[1] for p in parts])
        return (q, d)
    return np.stack(parts)


def _interleave(arrs: list, tp: int, axis: int) -> np.ndarray:
    """Concat host arrays along `axis`, permuted so TP shard s's slice of
    the result is the concat of shard s's slices of each input — a plain
    out-axis NamedSharding then gives every shard its own parts, at any tp."""
    if tp == 1:
        return np.concatenate(arrs, axis=axis)
    chunks = []
    for s in range(tp):
        for a in arrs:
            n = a.shape[axis]
            assert n % tp == 0, f"fused out dim {n} not divisible by tp={tp}"
            chunks.append(np.take(a, range(s * (n // tp), (s + 1) * (n // tp)), axis=axis))
    return np.concatenate(chunks, axis=axis)


def _fuse_rows(parts: list, tp: int) -> Any:
    """Fuse same-input row-split weights (one layer's host values) along the
    out axis: T-layout quant pairs (qt [nb,32,out], dt [nb,out]) concat on
    the last axis; dense [out, in] on axis 0."""
    if isinstance(parts[0], tuple):
        return (
            _interleave([p[0] for p in parts], tp, axis=-1),
            _interleave([p[1] for p in parts], tp, axis=-1),
        )
    return _interleave(parts, tp, axis=0)


def _put(x: Any, sharding=None) -> Weight:
    """Host tensor (or quant pair) -> device array(s), optionally sharded.

    `sharding` is one entry of parallel.sharding.param_shardings:
    {"quant": (q_sharding, d_sharding), "dense": sharding} — or None.
    """
    if isinstance(x, tuple):
        q, d = x
        if sharding is not None:
            q_sh, d_sh = sharding["quant"]
            return QuantTensor(q=jax.device_put(q, q_sh), d=jax.device_put(d, d_sh))
        return QuantTensor(q=jax.device_put(jnp.asarray(q)), d=jax.device_put(jnp.asarray(d)))
    if sharding is not None:
        return jax.device_put(x, sharding["dense"])
    return jax.device_put(jnp.asarray(x))


def load_params(
    reader: MFileReader,
    cfg: ModelConfig,
    shardings: Optional[dict] = None,
    tp: int = 1,
) -> ModelParams:
    """Read all weights, stack per-layer, move to device.

    `shardings` maps role name ("q", "w1", "embedding", ...) to either a
    `NamedSharding` (dense weights) or a pair of shardings (QuantTensor's q/d
    components) — provided by parallel/sharding.py; None loads replicated on
    the default device.

    `tp` is the TP degree the fused projections (LayerParams.wqkv / .w13)
    are interleaved for — it must match the mesh the shardings come from.
    """
    dense = np.dtype(cfg.compute_dtype)
    sh = shardings or {}

    def put(role: str, x):
        return _put(x, sh.get(role))

    roles = ["q", "k", "v", "wo", "w1", "w2", "w3", "norm0", "norm1"]
    if cfg.is_qwen3:
        roles += ["q_norm", "k_norm"]
    if cfg.is_moe:
        roles += ["moe_gate"]

    # the embedding and the MoE router gate stay f32 regardless of the
    # compute dtype (the reference keeps both f32 — gate is loadAll F32,
    # src/llm.cpp:680; bf16 router logits can flip expert selection on
    # near-ties)
    f32_roles = {"moe_gate"}

    per_role: dict[str, list] = {r: [] for r in roles}
    for l in range(cfg.n_layers):
        for r in roles:
            role_dtype = np.float32 if r in f32_roles else dense
            if r in ("w1", "w2", "w3") and cfg.is_moe:
                experts = [
                    _load_one(reader, reader.by_name[f"{r}.l{l}.e{e}"], role_dtype)
                    for e in range(cfg.n_experts)
                ]
                per_role[r].append(_stack(experts))
            else:
                per_role[r].append(_load_one(reader, reader.by_name[f"{r}.l{l}"], role_dtype))

    # fuse same-input row-split projections (see LayerParams docstring):
    # q/k/v always; dense w1/w3 (MoE expert stacks stay separate)
    per_role["wqkv"] = [
        _fuse_rows([per_role["q"][l], per_role["k"][l], per_role["v"][l]], tp)
        for l in range(cfg.n_layers)
    ]
    del per_role["q"], per_role["k"], per_role["v"]
    if not cfg.is_moe:
        per_role["w13"] = [
            _fuse_rows([per_role["w1"][l], per_role["w3"][l]], tp)
            for l in range(cfg.n_layers)
        ]
        del per_role["w1"], per_role["w3"]

    layer_kw = {r: put(r, _stack(parts)) for r, parts in per_role.items()}
    for r in ("q", "k", "v", "w1", "w3"):  # consumed by the fused forms
        layer_kw.setdefault(r, None)
    layers = LayerParams(**layer_kw)

    embedding = put("embedding", _load_one(reader, reader.by_name["embedding"], np.float32))
    final_norm = put("final_norm", _load_one(reader, reader.by_name["final_norm"], dense))
    wcls = put("wcls", _load_one(reader, reader.by_name["wcls"], dense))
    return ModelParams(embedding=embedding, layers=layers, final_norm=final_norm, wcls=wcls)
