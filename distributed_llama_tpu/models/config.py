"""Static model configuration.

Derived from the `.m` header (formats/mfile.py, reference: src/llm.hpp:45-77)
but hashable/frozen so it can be a static argument to jit-compiled functions.
"""

from __future__ import annotations

from dataclasses import dataclass, replace

import jax.numpy as jnp

from ..formats.mfile import ArchType, HiddenAct, ModelHeader, RopeType


@dataclass(frozen=True)
class ModelConfig:
    arch_type: int
    dim: int
    hidden_dim: int  # dense FFN width, or per-expert width for MoE
    n_layers: int
    n_heads: int
    n_kv_heads: int
    head_dim: int
    vocab_size: int
    seq_len: int
    n_experts: int
    n_active_experts: int
    hidden_act: int
    rope_type: int
    norm_epsilon: float
    # compute_dtype: operand dtype for matmuls/attention. "bfloat16" is the
    # TPU fast path (MXU-native); "float32" is the parity/testing path.
    compute_dtype: str = "bfloat16"
    # cache_dtype: KV cache storage dtype (the reference caches f32;
    # bf16 halves HBM traffic at negligible quality cost).
    cache_dtype: str = "bfloat16"
    # use_pallas: None = auto (on when running on TPU). The GSPMD engine path
    # forces False — XLA cannot partition a pallas_call over NamedSharding-ed
    # operands, so sharded-jit execution must use the XLA dequant path; the
    # shard_map pipeline path re-enables it (kernels see local shards there).
    use_pallas: bool | None = None
    # q80_activations: parity mode emulating the reference's
    # `--buffer-float-type q80` numerics — every Q40 matmul input is
    # round-tripped through Q80 quantization (the reference casts activations
    # into q80 buffers before each Q40 matmul, src/llm.cpp:221-255; pipes and
    # everything else stay f32). Off in production: activations already live
    # on-chip, quantizing them buys no bandwidth.
    q80_activations: bool = False
    # pallas_interpret: run Pallas kernels in interpret mode (CPU testing of
    # the kernel code paths). Captured into the config — a static jit
    # argument — at construction (from DLT_PALLAS_INTERPRET) so a program
    # traced in one mode can never be replayed in the other.
    pallas_interpret: bool = False

    @property
    def q_dim(self) -> int:
        return self.head_dim * self.n_heads

    @property
    def kv_dim(self) -> int:
        return self.head_dim * self.n_kv_heads

    @property
    def is_qwen3(self) -> bool:
        return self.arch_type in (ArchType.QWEN3, ArchType.QWEN3_MOE)

    @property
    def is_moe(self) -> bool:
        return self.n_experts > 0

    @property
    def pallas_arg(self):
        """The `pallas` argument for quant_matmul/linear: use_pallas, or the
        "interpret" sentinel (force-enabled interpret-mode kernels) when
        pallas_interpret is set."""
        if self.pallas_interpret:
            return "interpret"
        return self.use_pallas

    @property
    def dtype(self):
        return jnp.dtype(self.compute_dtype)

    @property
    def kv_dtype(self):
        return jnp.dtype(self.cache_dtype)

    @property
    def kv_quantized(self) -> bool:
        """True when the KV cache stores int8 with a f32 scale sidecar
        (ops/kv_quant.py). bf16/f32 caches store raw values and keep the
        pre-quantization program graphs bit-identical."""
        return self.cache_dtype == "int8"

    def with_(self, **kw) -> "ModelConfig":
        return replace(self, **kw)


def config_from_header(
    h: ModelHeader, compute_dtype: str = "bfloat16", cache_dtype: str | None = None
) -> ModelConfig:
    import os

    if cache_dtype is None:
        cache_dtype = "float32" if compute_dtype == "float32" else "bfloat16"
    return ModelConfig(
        pallas_interpret=bool(os.environ.get("DLT_PALLAS_INTERPRET")),
        arch_type=h.arch_type,
        dim=h.dim,
        hidden_dim=h.ff_dim,
        n_layers=h.n_layers,
        n_heads=h.n_heads,
        n_kv_heads=h.n_kv_heads,
        head_dim=h.head_dim,
        vocab_size=h.vocab_size,
        seq_len=h.seq_len,
        n_experts=h.n_experts,
        n_active_experts=h.n_active_experts,
        hidden_act=h.hidden_act,
        rope_type=h.rope_type,
        norm_epsilon=h.norm_epsilon,
        compute_dtype=compute_dtype,
        cache_dtype=cache_dtype,
    )
