"""Model layer: configs, weight loading, and the transformer forward pass.

Replaces the reference's graph builder (reference: buildLlmNet,
src/llm.cpp:152-649): instead of emitting a per-node op graph that a
hand-written executor walks, the forward pass is a jit-compiled JAX function
scanned over stacked per-layer weights; XLA is the executor and scheduler.
"""

from .config import ModelConfig, config_from_header
from .params import KVCache, LayerParams, ModelParams, init_kv_cache, load_params
from .transformer import forward

__all__ = [
    "ModelConfig",
    "config_from_header",
    "ModelParams",
    "LayerParams",
    "KVCache",
    "init_kv_cache",
    "load_params",
    "forward",
]
