"""The unified transformer forward pass (Llama / Qwen3 / Qwen3-MoE).

Functional re-design of the reference's per-node op graph (reference:
buildLlmNet, src/llm.cpp:152-649). One layer body is `lax.scan`ned over
stacked weights; XLA fuses norm->matmul->rope->attention chains and inserts
collectives when the arrays carry shardings (parallel/sharding.py).

Math per layer (reference att segment src/llm.cpp:278-418, ff segment
src/llm.cpp:421-569):

    y  = rms_norm(x, norm0);  q,k,v = y @ Wq,Wk,Wv
    [qwen3: per-head rms_norm of q,k]          (src/llm.cpp:337-361)
    q,k = rope(q,k); cache[pos] = k,v          (shiftForward)
    a  = gqa_attention(q, cache);  x += a @ Wo (+ TP psum in reference)
    y  = rms_norm(x, norm1)
    dense: x += (silu(y@W1) * (y@W3)) @ W2
    moe:   route -> top-k experts' swiglu, weighted sum (src/llm.cpp:440-514)

Final: rms_norm(x, final_norm) @ Wcls -> logits   (src/llm.cpp:593-636)
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp

from ..formats.mfile import HiddenAct
from ..ops import gqa_attention, moe_router, rms_norm
from ..ops.activations import gelu, silu
from ..ops.quant import QuantTensor, dequantize_t, quant_matmul, quantize_q80_activations
from ..ops.rope import RopeTables, apply_rope
from .config import ModelConfig
from .params import KVCache, LayerParams, ModelParams


def linear(
    x: jnp.ndarray, w: Any, dtype, pallas=None, q80: bool = False, layer=None
) -> jnp.ndarray:
    """x @ w.T for a dense or Q40 weight; returns x.dtype. `q80` is the
    reference-parity mode: the Q40 matmul input is round-tripped through Q80
    (ModelConfig.q80_activations). `layer`: use w[layer] of an all-layers
    stacked weight — the Q40/Pallas path selects the layer inside the kernel
    without materializing the slice (ops/quant.py)."""
    if isinstance(w, QuantTensor):
        if q80:
            x = quantize_q80_activations(x)
        return quant_matmul(
            x, w, dtype=dtype, pallas=pallas, layer=layer if w.q.ndim == 3 else None
        )
    if layer is not None and w.ndim == 3:
        w = jax.lax.dynamic_index_in_dim(w, layer, 0, keepdims=False)
    precision = jax.lax.Precision.HIGHEST if dtype == jnp.float32 else None
    y = jax.lax.dot_general(
        x.astype(dtype),
        w.astype(dtype),
        (((x.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=precision,
    )
    return y.astype(x.dtype)


def _activation(cfg: ModelConfig, x: jnp.ndarray) -> jnp.ndarray:
    return silu(x) if cfg.hidden_act == HiddenAct.SILU else gelu(x)


def _sel_layer(w: Any, i) -> Any:
    """w[i] for a stacked per-layer weight (QuantTensor-aware); identity when
    i is None (w already belongs to one layer). Delegates to the single
    stack-slicing owner in ops/quant.py."""
    from ..ops.quant import slice_layer

    return slice_layer(w, i)


def _dense_ffn(cfg: ModelConfig, y: jnp.ndarray, lp: LayerParams, layer=None) -> jnp.ndarray:
    q80 = cfg.q80_activations
    if lp.w13 is not None:
        # fused in-projection: one kernel reads w1|w3 (per-shard interleaved
        # halves, models/params.py) — identical math to two matmuls, half
        # the dispatches, one activation quantize
        h13 = linear(y, lp.w13, cfg.dtype, cfg.pallas_arg, q80, layer)
        ff = h13.shape[-1] // 2
        h = _activation(cfg, h13[..., :ff]) * h13[..., ff:]
    else:
        h = _activation(cfg, linear(y, lp.w1, cfg.dtype, cfg.pallas_arg, q80, layer)) * linear(y, lp.w3, cfg.dtype, cfg.pallas_arg, q80, layer)
    return linear(h, lp.w2, cfg.dtype, cfg.pallas_arg, q80, layer)


def _gather_expert(w: Any, idx: jnp.ndarray) -> Any:
    """Select per-token expert weights: w [E, ...] + idx [b, t, k].

    Callers pass a `_sel_layer`-sliced stack. Measured on-chip: XLA fuses
    that slice into this gather, while a single combined (layer, idx)
    advanced-index lowers to a generalized gather that ran 4x SLOWER at
    decode — keep the two-step form."""
    if isinstance(w, QuantTensor):
        return QuantTensor(q=w.q[idx], d=w.d[idx])
    return w[idx]


def _expert_matmul(x: jnp.ndarray, w: Any, dtype, q80: bool = False) -> jnp.ndarray:
    """Per-token expert matmul: x [b,t,k,in] with per-token gathered expert
    weights — QuantTensor in the packed T layout ([...,nb*4,out]) or dense
    [...,out,in]."""
    precision = jax.lax.Precision.HIGHEST if dtype == jnp.float32 else None
    if isinstance(w, QuantTensor):
        if q80:
            x = quantize_q80_activations(x)
        wd = dequantize_t(w, dtype)
        eq = "btki,btkio->btko"
    else:
        wd = w.astype(dtype)
        eq = "btki,btkoi->btko"
    y = jnp.einsum(
        eq, x.astype(dtype), wd, preferred_element_type=jnp.float32, precision=precision
    )
    return y.astype(x.dtype)


def _pallas_enabled(cfg) -> bool:
    """Single owner of the pallas-enable resolution for trace-time path
    choices: cfg.use_pallas, auto-resolved by backend when None, with
    interpret mode forcing on (it exists to exercise the kernel paths)."""
    from ..ops.quant import _use_pallas

    if cfg.pallas_interpret:
        return True
    return cfg.use_pallas if cfg.use_pallas is not None else _use_pallas()


def _attention_auto(cfg, q, k_view, v_view, positions, pos_start):
    """Pick the attention implementation for this (static) shape:

    * prefill-sized q on a bf16 cache with the Pallas path enabled -> blocked
      flash kernel (ops/pallas_attention.py) — no O(t*S) score tensor;
    * otherwise (decode t=1, f32 parity path, unaligned shapes) -> the XLA
      whole-cache einsum (ops/attention.py), whose reads the engine already
      bounds with the kv_len position bucket.
    """
    from ..ops.pallas_attention import flash_attention, flash_attention_aligned

    t = q.shape[1]
    # interpret mode rides in the (static, hashable) config, so the jit
    # cache can never replay a program traced in the other mode. Per-row
    # pos_start (vector) only occurs at decode t=1, which takes the einsum
    # path anyway — the flash kernel's causal math assumes one scalar chunk
    # start, so it is gated to scalar pos_start.
    if (
        _pallas_enabled(cfg)
        and jnp.ndim(pos_start) == 0
        and k_view.dtype == jnp.bfloat16
        and flash_attention_aligned(q, k_view, t)
    ):
        return flash_attention(
            q, k_view, v_view, pos_start, interpret=cfg.pallas_interpret
        )
    return gqa_attention(q, k_view, v_view, positions)


def _fused_paged_eligible(cfg, q, t: int, ps: int) -> bool:
    """Gate for the fused page-table-aware int8 decode kernel: Pallas
    enabled, decode-sized q blocks (one page of queries at most — solo
    decode t=1, batch decode t=1, speculative verify t=k+1 all qualify;
    prefill chunks take the gather+dequant view, which stays
    flash-eligible), and uniform lane-aligned head grouping."""
    n_heads, head_dim = q.shape[2], q.shape[3]
    return (
        _pallas_enabled(cfg)
        and t <= ps
        and n_heads % cfg.n_kv_heads == 0
        and head_dim % 8 == 0
    )


def _n_local_experts(w: Any, stacked: bool = False) -> int:
    """Expert count of an expert weight — `stacked`: w carries a leading
    all-layers axis ([L, E, ...] rather than [E, ...])."""
    axis = 1 if stacked else 0
    return w.q.shape[axis] if isinstance(w, QuantTensor) else w.shape[axis]


def _moe_ffn(
    cfg: ModelConfig, y: jnp.ndarray, lp: LayerParams, layer=None, ep_axis=None
) -> jnp.ndarray:
    """Top-k expert SwiGLU, matching the reference MoE graph
    (src/llm.cpp:440-514): router on the *normed* activation, top-k expert
    selection, weighted merge-sum.

    Two formulations, chosen at trace time (token count is static under jit)
    by comparing weight traffic: the ragged path streams ALL n_experts'
    weights once, the gather path reads (and materializes) one expert weight
    set per (token, slot) row — so ragged wins iff rows >= n_experts:
    * rows >= E (prefill chunks): sort-based ragged dispatch (ops/moe.py
      moe_ffn_ragged) — `lax.ragged_dot` against the HBM-resident expert
      stacks; flat O(rows) activation memory at any chunk size.
    * rows < E (decode, tiny tail chunks): gather the active experts'
      weights per token — reads only the weights the math needs, the
      bandwidth-optimal decode shape (the reference's per-expert indexed
      matmul, src/nn/nn-cpu-ops.cpp:1166-1192).

    `ep_axis`: shard_map expert parallelism — the expert axis of w1/w2/w3 is
    sharded over that mesh axis (gate stays replicated, so routing is
    global); each shard computes its resident experts' contributions and the
    results combine with one psum.
    """
    idx, wts = moe_router(y, _sel_layer(lp.moe_gate, layer), cfg.n_active_experts)  # [b,t,k]
    q80 = cfg.q80_activations

    rows = y.shape[0] * y.shape[1] * cfg.n_active_experts
    if rows >= cfg.n_experts:
        from ..ops.moe import moe_ffn_ragged

        # full stacks + layer index: the grouped kernel selects this layer's
        # experts via flat scalar-prefetched group indices — a dynamic-slice
        # of the stack here would MATERIALIZE every expert's weights per
        # layer per chunk (~50 MB/layer at the bench MoE shape; a
        # pallas_call cannot fuse the slice)
        return moe_ffn_ragged(
            y, idx, wts, lp.w1, lp.w3, lp.w2,
            partial(_activation, cfg), cfg.dtype, q80=q80, ep_axis=ep_axis,
            pallas=cfg.pallas_arg, layer=layer,
        )

    if ep_axis is not None:
        # small-chunk under EP: gather against the LOCAL expert slice — slots
        # routed to another shard's experts are clamped and zero-weighted,
        # and the shards' partials psum-combine
        n_local = _n_local_experts(lp.w1, stacked=layer is not None)
        e0 = jax.lax.axis_index(ep_axis) * n_local
        idx_local = idx - e0
        valid = (idx_local >= 0) & (idx_local < n_local)
        idx = jnp.clip(idx_local, 0, n_local - 1)
        wts = wts * valid.astype(wts.dtype)

    if _moe_decode_i8_eligible(cfg, y, lp):
        out = _moe_decode_i8(cfg, y, lp, layer, idx, wts)
    else:
        w1 = _gather_expert(_sel_layer(lp.w1, layer), idx)
        w3 = _gather_expert(_sel_layer(lp.w3, layer), idx)
        w2 = _gather_expert(_sel_layer(lp.w2, layer), idx)
        xk = jnp.broadcast_to(y[:, :, None, :], (*y.shape[:2], cfg.n_active_experts, y.shape[-1]))
        h = _activation(cfg, _expert_matmul(xk, w1, cfg.dtype, q80)) * _expert_matmul(xk, w3, cfg.dtype, q80)
        out = _expert_matmul(h, w2, cfg.dtype, q80)  # [b,t,k,dim]
        out = jnp.einsum("btko,btk->bto", out.astype(jnp.float32), wts)
    if ep_axis is not None:
        out = jax.lax.psum(out, ep_axis)
    return out.astype(y.dtype)


def _moe_decode_i8_eligible(cfg, y, lp) -> bool:
    """Single-token decode on the bf16 Pallas path with aligned Q40 expert
    stacks -> per-slot int8-MXU kernel calls (reads ONLY the k active
    experts' int8 weights; the gather path materializes dequantized copies)."""
    from ..ops.pallas_q40 import q40_stacked_aligned

    return (
        _pallas_enabled(cfg)
        and cfg.dtype == jnp.bfloat16
        and y.shape[0] * y.shape[1] == 1
        and all(isinstance(w, QuantTensor) for w in (lp.w1, lp.w2, lp.w3))
        and q40_stacked_aligned(lp.w1.in_features, lp.w1.out_features)
        and q40_stacked_aligned(lp.w2.in_features, lp.w2.out_features)
    )


def _moe_decode_i8(cfg, y, lp, layer, idx, wts):
    """One token's top-k expert SwiGLU via the scalar-prefetched stacked
    int8-MXU kernel (ops/pallas_q40.py): each (slot, role) matmul indexes the
    [L*E]-flattened expert stack directly, so HBM traffic is exactly the k
    active experts' int8 weights — the decode-optimal read set, at the same
    effective bandwidth as the dense decode path."""
    from ..ops.pallas_q40 import q40_matmul_pallas_stacked_i8

    def flat(w):
        # [L, E, nb*4, out] -> [L*E, nb*4, out] (free reshape); a
        # layer-sliced [E, ...] stack (pipeline path) passes through as-is
        if w.q.ndim == 4:
            return (
                w.q.reshape(-1, *w.q.shape[2:]),
                w.d.reshape(-1, *w.d.shape[2:]),
            )
        return w.q, w.d

    w1q, w1d = flat(lp.w1)
    w3q, w3d = flat(lp.w3)
    w2q, w2d = flat(lp.w2)
    n_e = _n_local_experts(lp.w1, stacked=lp.w1.q.ndim == 4)
    base = (layer * n_e) if layer is not None else 0
    interp = cfg.pallas_interpret

    x = y.reshape(1, y.shape[-1])
    k = idx.shape[-1]
    out = jnp.zeros((1, cfg.dim), jnp.float32)
    for slot in range(k):
        fi = base + idx.reshape(k)[slot]
        h = _activation(
            cfg, q40_matmul_pallas_stacked_i8(x, w1q, w1d, fi, interpret=interp)
        ) * q40_matmul_pallas_stacked_i8(x, w3q, w3d, fi, interpret=interp)
        o = q40_matmul_pallas_stacked_i8(
            h.astype(y.dtype), w2q, w2d, fi, interpret=interp
        )
        out = out + wts.reshape(k)[slot] * o
    return out.reshape(*y.shape[:2], cfg.dim)


def _layer(
    cfg: ModelConfig,
    rope: RopeTables,
    x: jnp.ndarray,  # [b, t, dim] residual stream (f32)
    positions: jnp.ndarray,  # [b, t] int32
    pos_start: jnp.ndarray,  # scalar int32 — cache write offset
    lp: LayerParams,
    k_cache: jnp.ndarray,  # [b, seq, n_kv, head_dim]
    v_cache: jnp.ndarray,
    reduce_fn=None,  # TP partial-sum reduction (shard_map path): applied to
    # the attention and ffn output projections. None under GSPMD — XLA
    # inserts the psum itself from the shardings (the reference's explicit
    # SYNC_NODE_SLICES after att/ff, src/llm.cpp:418,569).
    sp_ctx=None,  # (axis_name, shard_offset) when the cache's seq axis is
    # sharded under shard_map (long-context sequence parallelism): cache
    # writes become boundary-safe scatters and attention combines partial
    # online-softmax stats across the axis (ops/attention.py gqa_attention_sp)
    ep_axis=None,  # mesh axis name when the MoE expert stacks are sharded
    # under shard_map (expert parallelism — see _moe_ffn); attention weights
    # are replicated over this axis and the MoE output psums over it
    layer_idx=None,  # scalar int32 when `lp` holds ALL layers stacked: the
    # big matmuls select the layer inside the Pallas kernel (no weight-slice
    # copy — see quant_matmul) and the small per-layer tensors are sliced
    # here. None = `lp` is already a single layer's weights.
    kv_len=None,  # static int: attention reads only cache[:, :kv_len] (a
    # static slice that fuses into the attention ops). The engine picks the
    # power-of-two bucket covering pos_start + t, so decode reads scale with
    # the position, not the allocated cache (full-cache reads made 32k-seq
    # decode pay for the whole cache every token). None = full cache.
    stacked_cache=False,  # True: k_cache/v_cache are the FULL [L, b, S, h,
    # d] stacks riding the layer scan's CARRY, and this layer's rows are
    # updated in place at index `cache_layer` (XLA keeps loop-carried
    # buffers in place under a dynamic-update). False (the legacy
    # threading): the per-layer slices arrive via the scan's xs and leave
    # via its stacked ys — which REWRITES the whole allocation every call
    # (measured: the scan ys stacking cost ~0.64 ms/token on a 134 MB
    # cache, the round-3 small-model/32k per-token floor).
    cache_layer=None,  # stacked_cache index; defaults to layer_idx (the
    # pipeline path passes per-layer weight slices — layer_idx None — but
    # still carries a stacked LOCAL cache, so the two indices differ there)
    page_table=None,  # [b, max_slots] int32 traced array (paged KV layout,
    # runtime/paged_kv.py): k_cache/v_cache are then the [L, n_pages,
    # page_size, h, d] page POOLS, writes scatter through the table and
    # attention reads gather the first kv_len/page_size pages per row. -1
    # entries are unmapped: their writes DROP, their reads clamp to page 0
    # and are causally masked. None = contiguous layout (unchanged).
    page_size=None,  # static page length in tokens (paged layout only)
    k_scale=None,  # int8 KV arm (cfg.kv_quantized): the f32 per-(token,
    # head) scale sidecars riding the scan carry next to k_cache/v_cache
    # ([L, P, ps, h] paged / [L, b, S, h] contiguous). None on float caches
    # — every branch below is then BYTE-IDENTICAL to the pre-quantization
    # graph (the bf16 A/B bit-identity contract). When present, writes
    # quantize (ops/kv_quant.py) and the return grows to a 5-tuple.
    v_scale=None,
):
    if reduce_fn is None:
        reduce_fn = lambda z: z
    if cache_layer is None:
        cache_layer = layer_idx
    if k_scale is not None and (sp_ctx is not None or not (stacked_cache or page_table is not None)):
        raise NotImplementedError(
            "int8 KV is supported on the stacked-contiguous and paged arms "
            "only (the engine forces a float cache on sp/pipeline meshes)"
        )
    b, t, _ = x.shape
    q80 = cfg.q80_activations

    # --- attention block ---
    y = rms_norm(x, _sel_layer(lp.norm0, layer_idx), cfg.norm_epsilon)
    # head counts come from the weight shapes, not cfg: under shard_map the
    # local shard holds n_heads/tp heads (the reference's sliceMultiHeadAtt,
    # src/nn/nn-core.cpp:280-287)
    if lp.wqkv is not None:
        # fused projection: one kernel reads q|k|v. Local split sizes follow
        # from the global q:k:v ratio — every part shrinks by the same tp
        # factor under the interleaved row sharding (models/params.py)
        qkv = linear(y, lp.wqkv, cfg.dtype, cfg.pallas_arg, q80, layer_idx)
        fused_out = qkv.shape[-1]
        g_q = cfg.n_heads * cfg.head_dim
        g_kv = cfg.n_kv_heads * cfg.head_dim
        local_q = fused_out * g_q // (g_q + 2 * g_kv)
        local_kv = fused_out * g_kv // (g_q + 2 * g_kv)
        q = qkv[..., :local_q]
        k = qkv[..., local_q : local_q + local_kv]
        v = qkv[..., local_q + local_kv :]
    else:
        q = linear(y, lp.q, cfg.dtype, cfg.pallas_arg, q80, layer_idx)
        k = linear(y, lp.k, cfg.dtype, cfg.pallas_arg, q80, layer_idx)
        v = linear(y, lp.v, cfg.dtype, cfg.pallas_arg, q80, layer_idx)
    q = q.reshape(b, t, q.shape[-1] // cfg.head_dim, cfg.head_dim)
    k = k.reshape(b, t, k.shape[-1] // cfg.head_dim, cfg.head_dim)
    v = v.reshape(b, t, v.shape[-1] // cfg.head_dim, cfg.head_dim)

    if cfg.is_qwen3:
        q = rms_norm(q, _sel_layer(lp.q_norm, layer_idx), cfg.norm_epsilon)
        k = rms_norm(k, _sel_layer(lp.k_norm, layer_idx), cfg.norm_epsilon)

    q = apply_rope(q, rope, positions, cfg.rope_type)
    k = apply_rope(k, rope, positions, cfg.rope_type)

    if page_table is not None:
        # -- paged KV layout (runtime/paged_kv.py): the cache stacks are
        # page POOLS [L, P, ps, h, d]; logical positions map through the
        # per-row page table. Same write-before-read/causal-mask invariants
        # as contiguous — outputs are token-identical by construction.
        li = cache_layer
        ps = page_size
        n_pool = k_cache.shape[1]
        max_slots = page_table.shape[1]
        # write: scatter each new row to (table[pos // ps], pos % ps).
        # Invalid writes — parked rows at/past seq_len, or an unmapped
        # (-1) table entry — remap to pairwise-distinct page indices past
        # the pool and DROP (colliding dropped indices would be undefined
        # scatter behavior, the same discipline as scatter_cache_update_sp)
        slot = positions // ps
        offset = positions % ps
        safe_slot = jnp.clip(slot, 0, max_slots - 1)
        phys = jnp.take_along_axis(page_table, safe_slot, axis=1)  # [b, t]
        invalid = (positions >= cfg.seq_len) | (slot >= max_slots) | (phys < 0)
        b_idx = jnp.arange(b, dtype=jnp.int32)[:, None]
        col = jnp.arange(t, dtype=jnp.int32)[None, :]
        phys = jnp.where(invalid, n_pool + b_idx * t + col, phys)
        if k_scale is not None:
            # int8 pool: QUANTIZE-ON-WRITE, fused into the same scatter —
            # the scale sidecars take the identical (phys, offset) indices
            # and drop with their payloads
            from ..ops.kv_quant import quantize_kv

            kq, ks = quantize_kv(k)
            vq, vs = quantize_kv(v)
            k_cache = k_cache.at[li, phys, offset].set(
                kq, mode="drop", unique_indices=True
            )
            v_cache = v_cache.at[li, phys, offset].set(
                vq, mode="drop", unique_indices=True
            )
            k_scale = k_scale.at[li, phys, offset].set(
                ks, mode="drop", unique_indices=True
            )
            v_scale = v_scale.at[li, phys, offset].set(
                vs, mode="drop", unique_indices=True
            )
        else:
            k_cache = k_cache.at[li, phys, offset].set(
                k.astype(k_cache.dtype), mode="drop", unique_indices=True
            )
            v_cache = v_cache.at[li, phys, offset].set(
                v.astype(v_cache.dtype), mode="drop", unique_indices=True
            )
        # read: gather the first kv_len/ps page entries per row into the
        # contiguous [b, n*ps, h, d] view the attention math consumes —
        # this gather is the layout's whole read cost (the cost model
        # counts it; analysis/profiling.py). Unmapped entries clamp to
        # page 0: garbage, causally masked like any junk past a row's pos.
        n_read = max_slots if kv_len is None else min(-(-kv_len // ps), max_slots)
        if k_scale is not None and _fused_paged_eligible(cfg, q, t, ps):
            # int8 decode: the FUSED kernel reads the pool through the page
            # table (scalar-prefetch operand) and dequantizes in VMEM — no
            # materialized page gather, no dequantized KV view in HBM
            # (ops/pallas_attention.paged_flash_attention)
            from ..ops.pallas_attention import paged_flash_attention

            a = paged_flash_attention(
                q, k_cache, v_cache, k_scale, v_scale,
                jnp.asarray(li, jnp.int32), positions[:, 0], page_table,
                n_read=n_read, page_size=ps,
                interpret=cfg.pallas_interpret,
            )
        else:
            pages = jnp.maximum(
                jax.lax.slice_in_dim(page_table, 0, n_read, axis=1), 0
            )  # [b, n_read]
            k_view = k_cache[li, pages]
            v_view = v_cache[li, pages]
            if k_scale is not None:
                # int8 prefill / no-Pallas fallback: dequantize the gathered
                # view to the compute dtype (prefill stays flash-eligible)
                from ..ops.kv_quant import dequantize_kv

                k_view = dequantize_kv(k_view, k_scale[li, pages], cfg.dtype)
                v_view = dequantize_kv(v_view, v_scale[li, pages], cfg.dtype)
            k_view = k_view.reshape(b, n_read * ps, -1, cfg.head_dim)
            v_view = v_view.reshape(b, n_read * ps, -1, cfg.head_dim)
            a = _attention_auto(cfg, q, k_view, v_view, positions, pos_start)
    elif sp_ctx is None:
        if stacked_cache:
            # in-place update of this layer's rows inside the full carried
            # stack; attention then reads a bucketed dynamic-slice view. The
            # slice is the only cache traffic besides the row write — the
            # legacy xs/ys threading instead re-stacked the WHOLE allocation
            # per call.
            li = cache_layer
            S = k_cache.shape[2]
            nh, hd = k_cache.shape[3], k_cache.shape[4]
            if k_scale is not None:
                # int8 contiguous arm: quantize-on-write into the stacked
                # slab, scale sidecars at the same (layer, row, pos) indices
                from ..ops.kv_quant import quantize_kv

                kw, ks = quantize_kv(k)
                vw, vs = quantize_kv(v)
            else:
                kw, vw = k.astype(k_cache.dtype), v.astype(v_cache.dtype)
                ks = vs = None
            if jnp.ndim(pos_start) == 0:
                start = (li, 0, pos_start, 0, 0)
                k_cache = jax.lax.dynamic_update_slice(k_cache, kw[None], start)
                v_cache = jax.lax.dynamic_update_slice(v_cache, vw[None], start)
                if k_scale is not None:
                    sstart = (li, 0, pos_start, 0)
                    k_scale = jax.lax.dynamic_update_slice(k_scale, ks[None], sstart)
                    v_scale = jax.lax.dynamic_update_slice(v_scale, vs[None], sstart)
            else:
                # per-row positions: OOB-DROP scatter (see the unstacked
                # branch below for why drop is load-bearing)
                b_idx = jnp.arange(b, dtype=jnp.int32)[:, None]
                k_cache = k_cache.at[li, b_idx, positions].set(
                    kw, mode="drop", unique_indices=True
                )
                v_cache = v_cache.at[li, b_idx, positions].set(
                    vw, mode="drop", unique_indices=True
                )
                if k_scale is not None:
                    k_scale = k_scale.at[li, b_idx, positions].set(
                        ks, mode="drop", unique_indices=True
                    )
                    v_scale = v_scale.at[li, b_idx, positions].set(
                        vs, mode="drop", unique_indices=True
                    )
            view_len = min(kv_len, S) if kv_len is not None else S
            k_view = jax.lax.dynamic_slice(
                k_cache, (li, 0, 0, 0, 0), (1, b, view_len, nh, hd)
            )[0]
            v_view = jax.lax.dynamic_slice(
                v_cache, (li, 0, 0, 0, 0), (1, b, view_len, nh, hd)
            )[0]
            if k_scale is not None:
                # dequantize the bucketed read view to the compute dtype
                # (flash stays eligible on the bf16 path)
                from ..ops.kv_quant import dequantize_kv

                ks_view = jax.lax.dynamic_slice(
                    k_scale, (li, 0, 0, 0), (1, b, view_len, nh)
                )[0]
                vs_view = jax.lax.dynamic_slice(
                    v_scale, (li, 0, 0, 0), (1, b, view_len, nh)
                )[0]
                k_view = dequantize_kv(k_view, ks_view, cfg.dtype)
                v_view = dequantize_kv(v_view, vs_view, cfg.dtype)
        else:
            if jnp.ndim(pos_start) == 0:
                k_cache = jax.lax.dynamic_update_slice_in_dim(
                    k_cache, k.astype(k_cache.dtype), pos_start, axis=1
                )
                v_cache = jax.lax.dynamic_update_slice_in_dim(
                    v_cache, v.astype(v_cache.dtype), pos_start, axis=1
                )
            else:
                # per-row sequences (independent prompts per batch row):
                # each row writes at its own positions — a scatter with
                # OOB-DROP semantics, not a clamping dynamic_update_slice.
                # The drop is load-bearing: a row whose positions reach
                # seq_len writes NOTHING, so finished rows can keep riding
                # decode chunks (generate_batch) and rolling admission can
                # "park" a row at pos_start = seq_len, both without
                # disturbing the row's live cache tail. Indices are
                # pos_start + arange per row — strictly increasing, hence
                # unique; all are >= 0 so none wrap before the drop applies.
                b_idx = jnp.arange(b, dtype=jnp.int32)[:, None]
                k_cache = k_cache.at[b_idx, positions].set(
                    k.astype(k_cache.dtype), mode="drop", unique_indices=True
                )
                v_cache = v_cache.at[b_idx, positions].set(
                    v.astype(v_cache.dtype), mode="drop", unique_indices=True
                )
            if kv_len is not None and kv_len < k_cache.shape[1]:
                k_view = jax.lax.slice_in_dim(k_cache, 0, kv_len, axis=1)
                v_view = jax.lax.slice_in_dim(v_cache, 0, kv_len, axis=1)
            else:
                k_view, v_view = k_cache, v_cache
        a = _attention_auto(cfg, q, k_view, v_view, positions, pos_start)
    else:
        from ..ops.attention import (
            flash_attention_sp,
            gqa_attention_sp,
            scatter_cache_update_sp,
        )
        from ..ops.pallas_attention import flash_attention_aligned

        axis_name, shard_offset = sp_ctx
        li = cache_layer if stacked_cache else None
        k_cache = scatter_cache_update_sp(k_cache, k, positions, shard_offset, layer=li)
        v_cache = scatter_cache_update_sp(v_cache, v, positions, shard_offset, layer=li)
        # per-shard KV read bound: kv_len is the GLOBAL position bucket; a
        # static local bound of min(kv_len, local_seq) is EXACT for every
        # shard — rows past it are either beyond the bucket (shard 0) or at
        # global positions >= kv_len (later shards), i.e. future and fully
        # masked either way. SPMD forbids per-shard static shapes, so this
        # uniform bound is the tightest static slice available; it caps the
        # worst case at sp * min(kv_len, local_seq) reads instead of the
        # full allocation every token (the round-2 behavior).
        local_seq = k_cache.shape[2] if stacked_cache else k_cache.shape[1]
        local_kv = min(kv_len, local_seq) if kv_len is not None else local_seq
        if stacked_cache:
            nh, hd = k_cache.shape[3], k_cache.shape[4]
            k_view = jax.lax.dynamic_slice(
                k_cache, (li, 0, 0, 0, 0), (1, b, local_kv, nh, hd)
            )[0]
            v_view = jax.lax.dynamic_slice(
                v_cache, (li, 0, 0, 0, 0), (1, b, local_kv, nh, hd)
            )[0]
        elif local_kv < local_seq:
            k_view = jax.lax.slice_in_dim(k_cache, 0, local_kv, axis=1)
            v_view = jax.lax.slice_in_dim(v_cache, 0, local_kv, axis=1)
        else:
            k_view, v_view = k_cache, v_cache
        if (
            _pallas_enabled(cfg)
            and jnp.ndim(pos_start) == 0  # flash's causal math assumes one
            # scalar chunk start (same gate as _attention_auto); per-row
            # prefill chunks take the masked einsum below
            and k_view.dtype == jnp.bfloat16
            and flash_attention_aligned(q, k_view, t)
        ):
            # prefill-sized chunks: blocked flash over the local shard with
            # cross-shard online-softmax combine — the long-context sp path
            # finally runs the same kernel as the single-chip path
            a = flash_attention_sp(
                q, k_view, v_view, pos_start, shard_offset, axis_name,
                interpret=cfg.pallas_interpret,
            )
        else:
            a = gqa_attention_sp(q, k_view, v_view, positions, shard_offset, axis_name)
    n_local_heads = q.shape[2]  # == cfg.n_heads unless sharded under shard_map
    att_out = linear(a.reshape(b, t, n_local_heads * cfg.head_dim), lp.wo, cfg.dtype, cfg.pallas_arg, q80, layer_idx)
    x = x + reduce_fn(att_out).astype(x.dtype)

    # --- ffn block ---
    y = rms_norm(x, _sel_layer(lp.norm1, layer_idx), cfg.norm_epsilon)
    ff = (
        _moe_ffn(cfg, y, lp, layer_idx, ep_axis=ep_axis)
        if cfg.is_moe
        else _dense_ffn(cfg, y, lp, layer_idx)
    )
    x = x + reduce_fn(ff).astype(x.dtype)
    if k_scale is not None:
        return x, k_cache, v_cache, k_scale, v_scale
    return x, k_cache, v_cache


def forward_uncompiled(
    cfg: ModelConfig,
    params: ModelParams,
    rope: RopeTables,
    cache: KVCache,
    tokens: jnp.ndarray,  # [b, t] int32
    pos_start: jnp.ndarray,  # int32 absolute position of tokens[:, 0] —
    # scalar (all rows aligned) or [b] (independent per-row sequences;
    # batch decode / DP serving)
    logits_mode: str = "last",  # "last" | "all"
    kv_len: int | None = None,  # static KV read bound (see _layer)
    page_table: jnp.ndarray | None = None,  # [b, max_slots] int32 — paged
    # KV layout (cache = page pools; see _layer's paged branch)
    page_size: int | None = None,  # static page length (paged layout only)
) -> tuple[jnp.ndarray, KVCache]:
    """One forward step (prefill chunk or decode token).

    Returns (logits, updated cache). logits: [b, vocab] for "last",
    [b, t, vocab] for "all" (perplexity path, reference dllama.cpp:167-207).
    The cache is donated: under jit the update is in-place in HBM.
    """
    b, t = tokens.shape
    ps = jnp.asarray(pos_start, jnp.int32)
    positions = ps[..., None] + jnp.arange(t, dtype=jnp.int32)[None, :]
    positions = jnp.broadcast_to(positions, (b, t))

    x = params.embedding[tokens].astype(jnp.float32)

    # the scan's xs carry only the layer index; the stacked weights ride in
    # via closure and each matmul selects its layer inside the kernel
    # (scanning over sliced weights instead would copy every layer's weights
    # out of the stack on every step — a dynamic-slice cannot fuse into a
    # pallas_call). The FULL cache stack rides the CARRY and each layer
    # updates its rows in place (stacked_cache): threading per-layer slices
    # through xs/ys instead re-stacked the whole allocation every call —
    # measured at ~0.64 ms/token on a 134 MB cache, the dominant term of the
    # round-3 small-model and 32k-context decode floors.
    quantized = cache.k_scale is not None

    def body(carry, li):
        if quantized:
            # int8 arm: the f32 scale sidecars ride the carry beside their
            # pools and update in place exactly like them
            x, k_c, v_c, ks_c, vs_c = carry
            x, k_c, v_c, ks_c, vs_c = _layer(
                cfg, rope, x, positions, pos_start, params.layers, k_c, v_c,
                layer_idx=li, kv_len=kv_len, stacked_cache=True,
                page_table=page_table, page_size=page_size,
                k_scale=ks_c, v_scale=vs_c,
            )
            return (x, k_c, v_c, ks_c, vs_c), None
        x, k_c, v_c = carry
        x, k_c, v_c = _layer(
            cfg, rope, x, positions, pos_start, params.layers, k_c, v_c,
            layer_idx=li, kv_len=kv_len, stacked_cache=True,
            page_table=page_table, page_size=page_size,
        )
        return (x, k_c, v_c), None

    layer_ids = jnp.arange(cfg.n_layers, dtype=jnp.int32)
    if quantized:
        (x, new_k, new_v, new_ks, new_vs), _ = jax.lax.scan(
            body, (x, cache.k, cache.v, cache.k_scale, cache.v_scale), layer_ids
        )
        new_cache = KVCache(k=new_k, v=new_v, k_scale=new_ks, v_scale=new_vs)
    else:
        (x, new_k, new_v), _ = jax.lax.scan(body, (x, cache.k, cache.v), layer_ids)
        new_cache = KVCache(k=new_k, v=new_v)

    x = rms_norm(x, params.final_norm, cfg.norm_epsilon)
    if logits_mode == "last":
        x = x[:, -1, :]
    logits = linear(x, params.wcls, cfg.dtype, cfg.pallas_arg, cfg.q80_activations)
    return logits.astype(jnp.float32), new_cache


# The jit entry point: cache is donated (updated in place in HBM); one
# compiled program per (cfg, token-shape, logits_mode, kv_len bucket,
# page_size arm). The page table (paged layout) rides as a small non-donated
# operand.
forward = partial(
    jax.jit,
    static_argnames=("cfg", "logits_mode", "kv_len", "page_size"),
    donate_argnames=("cache",),
)(forward_uncompiled)
