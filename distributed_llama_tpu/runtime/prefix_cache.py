"""Radix prefix cache: device-resident cross-request KV reuse.

Real serving fleets are dominated by *shared prefixes* — system prompts,
few-shot templates, multi-turn chat histories — yet every admission used to
re-prefill from token 0; the only reuse was the serialized path's
`NaiveCache`, which remembered exactly one conversation and thrashed the
moment two users interleaved. This module is the engine-wide replacement:
a radix tree (RadixAttention, SGLang / Zheng et al. 2023) over *token
chains* whose published nodes own **device-resident KV slices** — per-layer
k/v copied out of the live cache at bucket-aligned lengths — refcounted and
LRU-evicted under an HBM byte budget (PagedAttention's refcounted-sharing
memory discipline at slice granularity rather than per-block).

A new request longest-prefix-matches the trie; the match is rounded *down*
to a chunk-bucket boundary B; one jitted donate-safe copy program splices
the cached slice into the request's row(s); chunked prefill resumes from B.
Completed prefills publish their prompt KV back into the trie (one extract
copy), and completed generations publish the whole conversation, so the
next turn of a chat hits near-zero-TTFT regardless of which other users
interleaved in between.

Correctness invariants (the reasons this is bit-identical to a cold run):

* a published slice of length P holds, at position p < P, exactly the KV a
  cold prefill writes for that position — it was *extracted from* a
  completed prefill/decode, never recomputed;
* splicing writes the WHOLE stored slice [0, P); positions in [B, P) may
  belong to a diverged sibling request, but the resumed prefill (and then
  decode) rewrites every position >= B before any query at position >= B
  reads it — the same write-before-read invariant padded prefill tails and
  parked rows already rely on (models/transformer.py OOB-scatter notes);
* the copy/extract programs are plain jitted slice/update programs on the
  engine's warm-key ladder: one `(bucket, bucket)` entry per prefix bucket,
  warmed by `InferenceEngine.warmup()`, ZERO collectives (the graph
  auditor enforces this), cache donated so the splice is in-place in HBM.

Sharding: on shard_map pipeline meshes a cached slice carries
`parallel.pipeline.pp_prefix_sharding` — the live cache's per-stage layout
minus the batch axis — enforced with an in-program sharding constraint so
extraction and splice never reshuffle KV across stages. Sequence-parallel
(`sp > 1`) meshes shard the seq axis itself and are not supported; the
cache disables itself there.

Thread-safety: all trie/LRU/refcount state is guarded by one lock. The
device programs are dispatched by whichever thread owns the engine (the
Batcher worker, or the caller of `generate`); `/stats` readers only take
snapshots.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..models.params import KVCache
from .tracing import global_event

#: prefixes shorter than this are not worth a splice dispatch (~a tunnel
#: round trip); also the smallest published bucket
PREFIX_MIN_TOKENS = 16


def prefix_buckets(seq_len: int) -> list:
    """Power-of-two published-slice lengths: PREFIX_MIN_TOKENS up to
    seq_len // 2 (a prefix past half the context leaves no room to decode,
    and the cap keeps the copy-program ladder O(log seq_len))."""
    out = []
    b = PREFIX_MIN_TOKENS
    while b <= seq_len // 2:
        out.append(b)
        b *= 2
    return out


def bucket_down(n: int, seq_len: int) -> int:
    """Largest prefix bucket <= n (0 = below the publishable floor)."""
    best = 0
    for b in prefix_buckets(seq_len):
        if b <= n:
            best = b
    return best


def resolve_budget_mb(explicit, default_mb: int) -> int:
    """THE one resolver of the prefix-cache budget: an explicit value wins;
    otherwise DLT_PREFIX_CACHE_MB; an unset OR unparsable env value means
    `default_mb` (library engines pass 0 = off, the CLI/server entry points
    pass their serving default — same parsing everywhere, only the intended
    default differs)."""
    if explicit is not None:
        return int(explicit)
    raw = os.environ.get("DLT_PREFIX_CACHE_MB")
    if raw is None or raw == "":
        return default_mb
    try:
        return int(raw)
    except ValueError:
        return default_mb


# -- the jitted device programs ---------------------------------------------
#
# One compiled program per (prefix bucket, cache shape) — the new entries on
# the warm-key ladder. All three are pure slice/update programs: no matmuls,
# no collectives (GSPMD may partition them, but the traced jaxpr is
# collective-free — analysis/graph_audit.py asserts it). `out_sharding` is a
# STATIC NamedSharding (hashable) so pipeline engines pin the per-stage
# layout inside the program instead of hoping XLA propagates it.


@partial(
    jax.jit,
    static_argnames=("out_sharding",),
    donate_argnames=("cache",),
)
def copy_prefix_into_rows(cache, k_seg, v_seg, out_sharding=None):
    """Splice a cached slice [L, P, h, d] into positions [0, P) of EVERY
    batch row (the solo `generate`/`generate_batch` paths treat rows as one
    aligned front). Donated cache: in-place in HBM."""
    L, b = cache.k.shape[0], cache.k.shape[1]
    P = k_seg.shape[1]
    kb = jnp.broadcast_to(k_seg[:, None], (L, b, P) + k_seg.shape[2:])
    vb = jnp.broadcast_to(v_seg[:, None], (L, b, P) + v_seg.shape[2:])
    k = jax.lax.dynamic_update_slice(cache.k, kb.astype(cache.k.dtype), (0, 0, 0, 0, 0))
    v = jax.lax.dynamic_update_slice(cache.v, vb.astype(cache.v.dtype), (0, 0, 0, 0, 0))
    if out_sharding is not None:
        k = jax.lax.with_sharding_constraint(k, out_sharding)
        v = jax.lax.with_sharding_constraint(v, out_sharding)
    return KVCache(k=k, v=v)


@partial(
    jax.jit,
    static_argnames=("out_sharding",),
    donate_argnames=("cache",),
)
def copy_prefix_into_row(cache, k_seg, v_seg, row, out_sharding=None):
    """Splice a cached slice [L, P, h, d] into positions [0, P) of ONE batch
    row (the BatchSession admission path; `row` is traced so every row
    shares one compiled program per bucket). Donated cache."""
    k = jax.lax.dynamic_update_slice(
        cache.k, k_seg[:, None].astype(cache.k.dtype), (0, row, 0, 0, 0)
    )
    v = jax.lax.dynamic_update_slice(
        cache.v, v_seg[:, None].astype(cache.v.dtype), (0, row, 0, 0, 0)
    )
    if out_sharding is not None:
        k = jax.lax.with_sharding_constraint(k, out_sharding)
        v = jax.lax.with_sharding_constraint(v, out_sharding)
    return KVCache(k=k, v=v)


@partial(jax.jit, static_argnames=("length", "out_sharding"))
def extract_prefix_from_row(cache, row, length, out_sharding=None):
    """Copy positions [0, length) of one row OUT of the live cache into a
    standalone [L, length, h, d] pair (the publish path). NOT donated — the
    live cache must survive; the result is the published entry's storage."""
    L, h, d = cache.k.shape[0], cache.k.shape[3], cache.k.shape[4]
    k = jax.lax.dynamic_slice(cache.k, (0, row, 0, 0, 0), (L, 1, length, h, d))[:, 0]
    v = jax.lax.dynamic_slice(cache.v, (0, row, 0, 0, 0), (L, 1, length, h, d))[:, 0]
    if out_sharding is not None:
        k = jax.lax.with_sharding_constraint(k, out_sharding)
        v = jax.lax.with_sharding_constraint(v, out_sharding)
    return k, v


# -- host-side structure ----------------------------------------------------


@dataclass
class PrefixEntry:
    """One published slice: `tokens` (a bucket-length tuple) is the trie
    key; `refs` pins the entry against eviction while an admission is
    between match and splice-dispatch. Contiguous engines store extracted
    device arrays in k/v; PAGED engines store `pages` instead — the
    physical page ids of the publishing row, refcount-retained in the
    engine's PagePool (runtime/paged_kv.py), so publishing moves ZERO
    device bytes and a hit maps the pages into the new row's table."""

    tokens: tuple
    k: object
    v: object
    nbytes: int
    refs: int = 0
    last_used: int = 0
    pages: tuple = ()  # paged engines: physical page ids covering tokens

    @property
    def length(self) -> int:
        return len(self.tokens)


class _Node:
    """Radix node: `edge` is the token run from the parent (path
    compression), children keyed by first token, `entry` set when a
    published slice ends exactly at this node."""

    __slots__ = ("edge", "children", "entry")

    def __init__(self, edge=()):
        self.edge = tuple(edge)
        self.children: dict = {}
        self.entry = None


class PrefixCache:
    """The engine-wide radix prefix cache (see module docstring)."""

    def __init__(
        self,
        budget_bytes: int,
        seq_len: int,
        max_chunk: int,
        stats=None,
        seg_sharding=None,
        cache_sharding=None,
        page_pool=None,  # runtime/paged_kv.PagePool: the cache then shares
        # refcounted pages instead of extracting/splicing copies (zero
        # device work on publish AND on hit)
    ):
        self.budget_bytes = int(budget_bytes)
        self.seq_len = seq_len
        self.max_chunk = max_chunk
        self.stats = stats  # StepStats: counters surface in /stats, /health
        self.seg_sharding = seg_sharding  # published-slice layout (pipeline)
        self.cache_sharding = cache_sharding  # live-cache layout to preserve
        self.page_pool = page_pool
        self.paged = page_pool is not None
        self.buckets = prefix_buckets(seq_len)
        self._root = _Node()
        self._entries: dict = {}  # token tuple -> PrefixEntry
        self._bytes = 0
        self._clock = 0
        self._lock = threading.Lock()
        # runtime/kv_tiering.TieredKvStore (or None): when set, eviction
        # DEMOTES the victim down the host/disk tier ladder instead of
        # simply deleting it — wired by the server after engine build
        self.tier = None

    # -- construction -------------------------------------------------------

    @classmethod
    def build(cls, engine, prefix_cache_mb=None):
        """The engine's factory: resolves the budget (constructor arg >
        DLT_PREFIX_CACHE_MB env > 0/off) and the topology gates. Returns
        None when the cache is disabled — `sp > 1` meshes shard the cache's
        seq axis itself, which a replicated slice cannot splice into."""
        prefix_cache_mb = resolve_budget_mb(prefix_cache_mb, default_mb=0)
        if prefix_cache_mb <= 0:
            return None
        if engine.mesh is not None and engine.mesh.shape.get("sp", 1) > 1:
            return None
        if engine.cfg.kv_quantized and not engine.paged:
            # contiguous int8: the extract/splice copy programs would need
            # scale-sidecar twins for marginal benefit — the paged layout is
            # the int8 serving shape (zero-copy page sharing needs no dtype
            # awareness at all), so the contiguous arm disables itself here
            return None
        if not prefix_buckets(engine.cfg.seq_len):
            return None  # context too small for a publishable prefix
        seg_sh = None
        cache_sh = engine._cache_sharding
        if engine.use_pipeline:
            from ..parallel.pipeline import pp_prefix_sharding

            seg_sh = pp_prefix_sharding(engine.mesh)
        return cls(
            prefix_cache_mb * 1024 * 1024,
            seq_len=engine.cfg.seq_len,
            max_chunk=engine.max_chunk,
            stats=engine.stats,
            seg_sharding=seg_sh,
            cache_sharding=cache_sh,
            page_pool=engine.page_pool if engine.paged else None,
        )

    # -- observability ------------------------------------------------------

    def _incr(self, name, n=1):
        if self.stats is not None:
            self.stats.incr(name, n)

    def _gauges(self):
        if self.stats is not None:
            self.stats.gauge("prefix_cache_bytes", self._bytes)
            self.stats.gauge("prefix_cache_entries", len(self._entries))

    @property
    def n_entries(self) -> int:
        return len(self._entries)

    @property
    def total_bytes(self) -> int:
        """Device bytes the published entries hold — also the HBM ledger's
        ``prefix_cache`` component (runtime/profiling.py hbm_ledger)."""
        return self._bytes

    def stats_snapshot(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": self._bytes,
                "budget_bytes": self.budget_bytes,
                "buckets": list(self.buckets),
                "pinned": sum(1 for e in self._entries.values() if e.refs > 0),
            }

    # -- matching -----------------------------------------------------------

    def resume_boundary(self, m: int) -> int:
        """Round a matched length DOWN to a chunk-bucket boundary: a
        multiple of max_chunk, or (below one chunk) the largest power-of-two
        chunk bucket — so the resumed prefill's chunk plan stays on the same
        (size, kv-bucket) warm ladder a cold prefill walks."""
        if m >= self.max_chunk:
            return (m // self.max_chunk) * self.max_chunk
        b = 0
        p = 1
        while p <= m:
            b = p
            p *= 2
        return b

    def _walk(self, tokens):
        """(m, subtree_node, best_on_path): m = longest shared prefix with
        any published chain; subtree_node roots the entries sharing exactly
        m tokens; best_on_path = deepest entry whose WHOLE chain matched."""
        node = self._root
        t = tuple(tokens)
        m = 0
        best = None
        while True:
            if node.entry is not None:
                best = node.entry
            if m == len(t):
                return m, node, best
            child = node.children.get(t[m])
            if child is None:
                return m, None, best
            e = child.edge
            lim = min(len(e), len(t) - m)
            lcp = 0
            while lcp < lim and e[lcp] == t[m + lcp]:
                lcp += 1
            m += lcp
            if lcp == len(e):
                node = child
                continue
            # diverged (or ran out of tokens) mid-edge: everything below
            # `child` still shares exactly the first m tokens
            return m, child, best

    @staticmethod
    def _first_entry(node):
        if node is None:
            return None
        stack = [node]
        while stack:
            n = stack.pop()
            if n.entry is not None:
                return n.entry
            stack.extend(n.children.values())
        return None

    def match(self, tokens):
        """Longest-prefix match: (covered, entry). `covered` is the number
        of leading tokens of `tokens` the entry's slice holds CORRECT KV
        for; entry None on a miss. An entry deeper than the divergence point
        is still usable — its positions past `covered` get rewritten by the
        resumed prefill before any query reads them (module docstring)."""
        with self._lock:
            m, subtree, best = self._walk(tokens)
            entry = self._first_entry(subtree)
            if entry is not None:
                return m, entry
            if best is not None:
                return min(m, best.length), best
            return 0, None

    def match_for_splice(self, tokens):
        """The admission-path lookup: returns (resume_boundary, entry) with
        the entry PINNED (refs+1) so eviction cannot drop it between match
        and splice dispatch — the caller must `entry_release` it after the
        copy is dispatched (or abandoned). A miss (including a match whose
        boundary rounds below the publishable floor) is counted here; a HIT
        is counted by `record_hit` at splice-dispatch time, so an admission
        abandoned before its splice never inflates prefix_hit_tokens (the
        metric is "prefill compute actually skipped")."""
        covered, entry = self.match(tokens)
        B = self.resume_boundary(min(covered, len(tokens)))
        if self.paged and entry is not None:
            # page sharing maps WHOLE pages read-only: floor the boundary
            # to a page multiple and cap it at the entry's own coverage
            # (the contiguous splice copies positions past the divergence
            # too — rewritten later; shared pages must never be written)
            ps = self.page_pool.page_size
            B = (min(B, entry.length) // ps) * ps
        if entry is None or B < PREFIX_MIN_TOKENS:
            self._incr("prefix_misses")
            return 0, None
        with self._lock:
            entry.refs += 1
            self._clock += 1
            entry.last_used = self._clock
        return B, entry

    def pin_entry(self, entry) -> None:
        """Pin an entry (refs+1) so eviction cannot drop it while a
        disaggregated fetch uses it as the merge base
        (runtime/kv_transport.py); release with `entry_release`. Prefer
        :meth:`match_pinned` — pinning an entry obtained from a bare
        `match` leaves an eviction window between the two calls."""
        with self._lock:
            entry.refs += 1
            self._clock += 1
            entry.last_used = self._clock

    def match_pinned(self, tokens):
        """Longest-prefix match with the entry PINNED under the SAME lock
        hold that found it — the disaggregated fetch's lookup: between a
        bare `match` and a later pin, pool pressure could evict the entry
        and RECYCLE its pages, so a merge base must never be obtained
        unpinned. Returns ``(covered, entry|None)``; the caller must
        `entry_release` a non-None entry exactly once."""
        with self._lock:
            m, subtree, best = self._walk(tokens)
            entry = self._first_entry(subtree)
            covered = m
            if entry is None and best is not None:
                covered, entry = min(m, best.length), best
            if entry is None:
                return 0, None
            entry.refs += 1
            self._clock += 1
            entry.last_used = self._clock
            return covered, entry

    def record_hit(self, resume: int) -> None:
        """Count one splice that actually dispatched (`resume` = the
        bucket-aligned prefill tokens it skipped)."""
        self._incr("prefix_hits")
        self._incr("prefix_hit_tokens", resume)
        # engine-level trace event (flight-recorder context; the request's
        # own prefix_match/prefix_splice spans carry the per-request view)
        global_event("prefix_hit", keys=("tokens",), vals=(resume,))

    def entry_release(self, entry) -> None:
        with self._lock:
            entry.refs = max(0, entry.refs - 1)

    # -- splicing -----------------------------------------------------------

    def splice_rows(self, engine, entry):
        """Dispatch the all-rows copy program; returns the new (donated)
        cache. Dispatch-only: nothing here blocks on the device."""
        return copy_prefix_into_rows(
            engine.cache, entry.k, entry.v, out_sharding=self.cache_sharding
        )

    def splice_row(self, engine, entry, row: int):
        """Dispatch the one-row copy program (BatchSession admissions)."""
        return copy_prefix_into_row(
            engine.cache, entry.k, entry.v, jnp.asarray(row, jnp.int32),
            out_sharding=self.cache_sharding,
        )

    def share_row(self, engine, entry, row: int, resume: int) -> None:
        """The PAGED splice: map the entry's pages covering [0, resume)
        into `row`'s page table with refcounts bumped — ZERO device
        dispatches, zero KV bytes moved. `resume` is the page-aligned
        boundary `match_for_splice` returned."""
        n = resume // self.page_pool.page_size
        self.page_pool.share(row, entry.pages[:n])
        engine._pt_cache = None  # table changed: refresh the operand

    def share_rows(self, engine, entry, resume: int) -> None:
        """Paged splice into EVERY row (the solo generate / generate_batch
        aligned-front paths): each row maps the same shared pages."""
        for row in range(engine.batch):
            self.share_row(engine, entry, row, resume)

    # -- publishing ---------------------------------------------------------

    def publish_from_row(self, engine, row: int, tokens, max_len=None) -> bool:
        """Publish the first `bucket_down(max_len)` tokens' KV of `row` into
        the trie: one extract copy out of the live cache, then a host-side
        radix insert. Every position < max_len must already hold final KV
        (callers cap at the last *fed* token). Dedupes by token key; evicts
        LRU unpinned entries to fit the budget; skips (with a counter) when
        pinned entries leave no room. Returns True when an entry was
        inserted or refreshed."""
        n = len(tokens) if max_len is None else min(max_len, len(tokens))
        P = bucket_down(n, self.seq_len)
        if self.paged:
            # only whole pages can be shared read-only
            P = (P // self.page_pool.page_size) * self.page_pool.page_size
        if P < PREFIX_MIN_TOKENS:
            return False
        key = tuple(int(t) for t in tokens[:P])
        with self._lock:
            existing = self._entries.get(key)
            if existing is not None:
                self._clock += 1
                existing.last_used = self._clock
                return True
            need = self._slice_nbytes(engine, P)
            if need > self.budget_bytes:
                self._incr("prefix_publish_skipped")
                return False
            if not self._evict_until(self.budget_bytes - need):
                self._incr("prefix_publish_skipped")
                return False
        if self.paged:
            # PAGED publish: retain the publisher row's own pages — no
            # extract program, no device bytes moved. Positions < P are
            # final for the row (the callers' max_len contract), and the
            # row's future writes land past P in other pages (or trigger
            # copy-on-write if it ever rewinds), so the shared pages are
            # immutable from here on.
            try:
                pages = self.page_pool.row_pages(
                    row, P // self.page_pool.page_size
                )
            except ValueError:
                # unmapped slots below P: the row never actually held this
                # span (shouldn't happen — defensive, counted)
                self._incr("prefix_publish_skipped")
                return False
            self.page_pool.retain(pages)
            k = v = None
            nbytes = self._slice_nbytes(engine, P)
        else:
            # dispatch OUTSIDE the lock: /stats readers must not wait on a
            # device dispatch. The extract is async; the arrays become the
            # entry's storage and are only consumed by later splice
            # dispatches, which XLA orders after the producing program.
            pages = ()
            with engine._guard(f"prefix_extract[{P}]", ("prefix_extract", P, P)):
                k, v = extract_prefix_from_row(
                    engine.cache, jnp.asarray(row, jnp.int32), length=P,
                    out_sharding=self.seg_sharding,
                )
            nbytes = k.nbytes + v.nbytes
        with self._lock:
            if key in self._entries:  # raced with another publisher
                if pages:
                    self.page_pool.release(pages)
                return True
            self._clock += 1
            entry = PrefixEntry(
                tokens=key, k=k, v=v, nbytes=nbytes,
                last_used=self._clock, pages=pages,
            )
            self._insert(entry)
            self._entries[key] = entry
            self._bytes += entry.nbytes
            self._gauges()
        self._incr("prefix_inserts")
        global_event("prefix_publish", keys=("tokens", "row"), vals=(P, int(row)))
        return True

    def insert_external(
        self, engine, tokens, k_np, v_np, start: int = 0, base_entry=None
    ) -> bool:
        """Insert a slice computed OUTSIDE this process — the disaggregated
        serving path (runtime/kv_transport.py, server/disagg.py): a prefill
        worker ran the prompt, extracted k/v covering tokens ``[start, P)``
        at bucket boundaries, and shipped them here. The result is inserted
        exactly like a local publish, so the very next admission's
        ``match_for_splice`` hits and splices it through the SAME warmed
        programs a local hit uses — which is what makes the disaggregated
        path bit-identical to unified serving.

        ``k_np``/``v_np``: one array covering ``[start, P)``, or a list of
        per-segment arrays along the binary doubling ladder
        (:func:`~.kv_transport.doubling_segments` of ``(start, P)`` — every
        segment a prefix-bucket length, which is what keeps the paged
        scatter on the warm program ladder). ``start > 0`` is a partial
        send: the content-addressed skip determined this process already
        holds the leading pages in ``base_entry`` (PINNED by the caller;
        its tokens must equal ``tokens[:start]``), and the merged entry
        reuses them — CONTIGUOUS engines splice the base's device slice
        with the shipped arrays host-side (a cold-path bounce, never a
        compile), PAGED engines retain the base's physical pages and
        scatter the shipped segments into freshly allocated ones.

        MUST run on the engine's dispatch thread for paged engines (the
        scatter donates the live pool — server/disagg.py defers the apply
        to the Batcher loop / the serialized lock for exactly this reason).
        Returns False — never raises to the serving path — when the slice
        is unusable (off-bucket length, misaligned start, budget/pool
        unreachable): the caller then simply prefills locally, the
        degradation contract."""
        from .kv_transport import doubling_segments

        P = len(tokens)
        if P < PREFIX_MIN_TOKENS or P != bucket_down(P, self.seq_len):
            return False
        if start < 0 or start >= P:
            return False
        if start > 0:
            if base_entry is None or start != bucket_down(start, self.seq_len):
                return False
            if tuple(base_entry.tokens[:start]) != tuple(
                int(t) for t in tokens[:start]
            ):
                return False
        key = tuple(int(t) for t in tokens)
        with self._lock:
            existing = self._entries.get(key)
            if existing is not None:
                self._clock += 1
                existing.last_used = self._clock
                return True
        # normalize the shipped arrays to (seg_start, k, v) doubling
        # segments; a single array is host-sliced (numpy views / one
        # bounded copy off a device array — a cold path, no compiles)
        segs = doubling_segments(start, P)
        if isinstance(k_np, (list, tuple)):
            if len(k_np) != len(segs) or len(v_np) != len(segs):
                return False
            parts = [(a, k_np[i], v_np[i]) for i, (a, _b) in enumerate(segs)]
        else:
            k_host = np.asarray(k_np)  # dlt: allow(host-sync) — cold external-insert path, never the serving loop
            v_host = np.asarray(v_np)
            if k_host.shape[1] != P - start or v_host.shape[1] != P - start:
                return False
            parts = [
                (a, k_host[:, a - start : b - start], v_host[:, a - start : b - start])
                for a, b in segs
            ]
        L, _, _, h, d = engine.cache.k.shape
        for a, kp, vp in parts:
            b = a + kp.shape[1]
            if tuple(kp.shape) != (L, b - a, h, d) or tuple(vp.shape) != (
                L, b - a, h, d,
            ):
                return False
        need = self._slice_nbytes(engine, P)
        with self._lock:
            if need > self.budget_bytes or not self._evict_until(
                self.budget_bytes - need
            ):
                self._incr("prefix_publish_skipped")
                return False
        if self.paged:
            ok, k, v, pages = self._materialize_paged(
                engine, parts, start, P, base_entry
            )
        else:
            ok, k, v, pages = self._materialize_contiguous(
                engine, parts, start, P, base_entry
            )
        if not ok:
            return False
        with self._lock:
            if key in self._entries:  # raced with another inserter
                if pages:
                    self.page_pool.release(pages)
                return True
            if not self._evict_until(self.budget_bytes - need):
                if pages:
                    self.page_pool.release(pages)
                self._incr("prefix_publish_skipped")
                return False
            self._clock += 1
            entry = PrefixEntry(
                tokens=key, k=k, v=v, nbytes=need, last_used=self._clock,
                pages=pages,
            )
            self._insert(entry)
            self._entries[key] = entry
            self._bytes += entry.nbytes
            self._gauges()
        self._incr("prefix_inserts")
        global_event(
            "prefix_insert_external", keys=("tokens", "start"), vals=(P, start)
        )
        return True

    def _materialize_contiguous(self, engine, parts, start, P, base_entry):
        """Build one [L, P, h, d] device pair from the base entry's leading
        slice plus the shipped segments. Host-side concat + ONE device_put:
        no eager device ops, so nothing here can trip the recompile
        sentinel post-seal."""
        dt = engine.cache.k.dtype
        pieces_k, pieces_v = [], []
        if start > 0:
            # the base entry's arrays may be longer than `start` (a deeper
            # entry matched); only its verified leading span merges
            base_k = np.asarray(base_entry.k)[:, :start]  # dlt: allow(host-sync) — cold external-insert path
            base_v = np.asarray(base_entry.v)[:, :start]  # dlt: allow(host-sync) — cold external-insert path
            pieces_k.append(base_k)
            pieces_v.append(base_v)
        for _a, kp, vp in parts:
            pieces_k.append(np.asarray(kp))  # dlt: allow(host-sync) — cold external-insert path
            pieces_v.append(np.asarray(vp))  # dlt: allow(host-sync) — cold external-insert path
        k_full = np.concatenate(pieces_k, axis=1) if len(pieces_k) > 1 else pieces_k[0]
        v_full = np.concatenate(pieces_v, axis=1) if len(pieces_v) > 1 else pieces_v[0]
        k_full = k_full.astype(dt)
        v_full = v_full.astype(dt)
        if self.seg_sharding is not None:
            k = jax.device_put(k_full, self.seg_sharding)
            v = jax.device_put(v_full, self.seg_sharding)
        else:
            k = jax.device_put(k_full)
            v = jax.device_put(v_full)
        return True, k, v, ()

    def _materialize_paged(self, engine, parts, start, P, base_entry):
        """Land the shipped segments in freshly allocated pool pages (one
        warmed ``page_insert`` scatter per doubling segment) and retain the
        base entry's leading pages — the merged entry's storage is then
        location-independent page content under process-local page ids.
        Allocation runs OUTSIDE the trie lock (the pool's reclaim hook
        takes it). Engine-thread only: the scatter donates the live pool."""
        from .paged_kv import PagePoolExhausted, scatter_pages

        pool = self.page_pool
        ps = pool.page_size
        if start % ps != 0 or P % ps != 0:
            return False, None, None, ()
        if any((a % ps or kp.shape[1] % ps) for a, kp, _v in parts):
            return False, None, None, ()
        base_pages = ()
        if start > 0:
            base_pages = tuple(base_entry.pages[: start // ps])
            if len(base_pages) != start // ps:
                return False, None, None, ()
        new_pages: list = []
        try:
            for a, kp, vp in parts:
                # numpy operands on purpose: the warm page_insert programs
                # compiled against host arrays (engine._warmup_fill), and a
                # committed device operand's sharding would key a different
                # lowering. Host fetch of a device segment is sanctioned —
                # one cold external-insert per transfer, never serving-loop.
                kp = np.asarray(kp)  # dlt: allow(host-sync) — cold external-insert path
                vp = np.asarray(vp)  # dlt: allow(host-sync) — cold external-insert path
                n = kp.shape[1] // ps
                seg_pages = pool.allocate_pages(n)
                new_pages.extend(seg_pages)
                pages_np = np.asarray(seg_pages, np.int32)  # dlt: allow(host-sync) — host page-id list, no device source
                B = kp.shape[1]
                with engine._guard(
                    f"page_insert[{B}]", ("page_insert", B, B)
                ):
                    engine.cache = scatter_pages(
                        engine.cache, kp, vp, pages_np,
                        out_sharding=self.cache_sharding,
                    )
        except PagePoolExhausted:
            if new_pages:
                pool.release(new_pages)
            self._incr("prefix_publish_skipped")
            return False, None, None, ()
        pool.retain(base_pages)
        return True, None, None, base_pages + tuple(new_pages)

    def _slice_nbytes(self, engine, P: int) -> int:
        if self.paged:
            from .paged_kv import page_pool_bytes

            ps = self.page_pool.page_size
            return page_pool_bytes(engine.cfg, P // ps, ps)
        L, _, _, h, d = engine.cache.k.shape
        return 2 * L * P * h * d * engine.cache.k.dtype.itemsize

    # -- trie maintenance (callers hold the lock) ---------------------------

    def _insert(self, entry) -> None:
        t = entry.tokens
        node = self._root
        i = 0
        while True:
            if i == len(t):
                node.entry = entry
                return
            child = node.children.get(t[i])
            if child is None:
                leaf = _Node(t[i:])
                leaf.entry = entry
                node.children[t[i]] = leaf
                return
            e = child.edge
            lim = min(len(e), len(t) - i)
            lcp = 0
            while lcp < lim and e[lcp] == t[i + lcp]:
                lcp += 1
            if lcp == len(e):
                node = child
                i += lcp
                continue
            # split the edge at the divergence point
            mid = _Node(e[:lcp])
            child.edge = e[lcp:]
            mid.children[child.edge[0]] = child
            node.children[t[i]] = mid
            i += lcp
            if i == len(t):
                mid.entry = entry
            else:
                leaf = _Node(t[i:])
                leaf.entry = entry
                mid.children[t[i]] = leaf
            return

    def _detach(self, entry) -> None:
        """Remove `entry` from the trie, pruning now-empty nodes."""
        t = entry.tokens
        path = []  # (parent, first_token, node)
        node = self._root
        i = 0
        while i < len(t):
            child = node.children.get(t[i])
            if child is None:
                return  # not present (already detached)
            path.append((node, t[i], child))
            i += len(child.edge)
            node = child
        if node.entry is not entry:
            return
        node.entry = None
        for parent, first, n in reversed(path):
            if n.entry is None and not n.children:
                del parent.children[first]
            else:
                break

    def _evict_until(self, target_bytes: int) -> bool:
        """Evict LRU UNPINNED entries until total <= target; False when
        pinned entries make the target unreachable."""
        while self._bytes > target_bytes:
            victims = [e for e in self._entries.values() if e.refs == 0]
            if not victims:
                return False
            victim = min(victims, key=lambda e: e.last_used)
            self._remove(victim)
            self._incr("prefix_evictions")
        return True

    def _remove(self, entry) -> None:
        self._detach(entry)
        self._entries.pop(entry.tokens, None)
        self._bytes -= entry.nbytes
        if self.tier is not None:
            # demote-not-delete: capture the victim BEFORE its pages go
            # back to the pool — the capture's gather dispatches on this
            # same thread, so it is ordered ahead of any scatter that
            # recycles them. `clear()` (engine recovery) bypasses this on
            # purpose: a possibly-corrupt cache must not seed a tier.
            self.tier.capture_demotion(entry)
        if entry.pages:
            self.page_pool.release(entry.pages)
        self._gauges()

    def evict_one(self) -> bool:
        """Evict the LRU UNPINNED entry (the page pool's reclaim hook:
        allocation pressure trades cached prefixes for live-row pages).
        False when everything is pinned or the cache is empty."""
        with self._lock:
            victims = [e for e in self._entries.values() if e.refs == 0]
            if not victims:
                return False
            self._remove(min(victims, key=lambda e: e.last_used))
            self._incr("prefix_evictions")
            return True

    def clear(self) -> None:
        """Drop every entry (engine recovery: after an engine failure the
        in-flight extracts may descend from the failed computation).
        Paged entries release their page refs back to the pool."""
        with self._lock:
            for entry in self._entries.values():
                if entry.pages:
                    self.page_pool.release(entry.pages)
            self._root = _Node()
            self._entries.clear()
            self._bytes = 0
            self._gauges()
