"""Radix prefix cache: device-resident cross-request KV reuse.

Real serving fleets are dominated by *shared prefixes* — system prompts,
few-shot templates, multi-turn chat histories — yet every admission used to
re-prefill from token 0; the only reuse was the serialized path's
`NaiveCache`, which remembered exactly one conversation and thrashed the
moment two users interleaved. This module is the engine-wide replacement:
a radix tree (RadixAttention, SGLang / Zheng et al. 2023) over *token
chains* whose published nodes own **device-resident KV slices** — per-layer
k/v copied out of the live cache at bucket-aligned lengths — refcounted and
LRU-evicted under an HBM byte budget (PagedAttention's refcounted-sharing
memory discipline at slice granularity rather than per-block).

A new request longest-prefix-matches the trie; the match is rounded *down*
to a chunk-bucket boundary B; one jitted donate-safe copy program splices
the cached slice into the request's row(s); chunked prefill resumes from B.
Completed prefills publish their prompt KV back into the trie (one extract
copy), and completed generations publish the whole conversation, so the
next turn of a chat hits near-zero-TTFT regardless of which other users
interleaved in between.

Correctness invariants (the reasons this is bit-identical to a cold run):

* a published slice of length P holds, at position p < P, exactly the KV a
  cold prefill writes for that position — it was *extracted from* a
  completed prefill/decode, never recomputed;
* splicing writes the WHOLE stored slice [0, P); positions in [B, P) may
  belong to a diverged sibling request, but the resumed prefill (and then
  decode) rewrites every position >= B before any query at position >= B
  reads it — the same write-before-read invariant padded prefill tails and
  parked rows already rely on (models/transformer.py OOB-scatter notes);
* the copy/extract programs are plain jitted slice/update programs on the
  engine's warm-key ladder: one `(bucket, bucket)` entry per prefix bucket,
  warmed by `InferenceEngine.warmup()`, ZERO collectives (the graph
  auditor enforces this), cache donated so the splice is in-place in HBM.

Sharding: on shard_map pipeline meshes a cached slice carries
`parallel.pipeline.pp_prefix_sharding` — the live cache's per-stage layout
minus the batch axis — enforced with an in-program sharding constraint so
extraction and splice never reshuffle KV across stages. Sequence-parallel
(`sp > 1`) meshes shard the seq axis itself and are not supported; the
cache disables itself there.

Thread-safety: all trie/LRU/refcount state is guarded by one lock. The
device programs are dispatched by whichever thread owns the engine (the
Batcher worker, or the caller of `generate`); `/stats` readers only take
snapshots.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp

from ..models.params import KVCache
from .tracing import global_event

#: prefixes shorter than this are not worth a splice dispatch (~a tunnel
#: round trip); also the smallest published bucket
PREFIX_MIN_TOKENS = 16


def prefix_buckets(seq_len: int) -> list:
    """Power-of-two published-slice lengths: PREFIX_MIN_TOKENS up to
    seq_len // 2 (a prefix past half the context leaves no room to decode,
    and the cap keeps the copy-program ladder O(log seq_len))."""
    out = []
    b = PREFIX_MIN_TOKENS
    while b <= seq_len // 2:
        out.append(b)
        b *= 2
    return out


def bucket_down(n: int, seq_len: int) -> int:
    """Largest prefix bucket <= n (0 = below the publishable floor)."""
    best = 0
    for b in prefix_buckets(seq_len):
        if b <= n:
            best = b
    return best


def resolve_budget_mb(explicit, default_mb: int) -> int:
    """THE one resolver of the prefix-cache budget: an explicit value wins;
    otherwise DLT_PREFIX_CACHE_MB; an unset OR unparsable env value means
    `default_mb` (library engines pass 0 = off, the CLI/server entry points
    pass their serving default — same parsing everywhere, only the intended
    default differs)."""
    if explicit is not None:
        return int(explicit)
    raw = os.environ.get("DLT_PREFIX_CACHE_MB")
    if raw is None or raw == "":
        return default_mb
    try:
        return int(raw)
    except ValueError:
        return default_mb


# -- the jitted device programs ---------------------------------------------
#
# One compiled program per (prefix bucket, cache shape) — the new entries on
# the warm-key ladder. All three are pure slice/update programs: no matmuls,
# no collectives (GSPMD may partition them, but the traced jaxpr is
# collective-free — analysis/graph_audit.py asserts it). `out_sharding` is a
# STATIC NamedSharding (hashable) so pipeline engines pin the per-stage
# layout inside the program instead of hoping XLA propagates it.


@partial(
    jax.jit,
    static_argnames=("out_sharding",),
    donate_argnames=("cache",),
)
def copy_prefix_into_rows(cache, k_seg, v_seg, out_sharding=None):
    """Splice a cached slice [L, P, h, d] into positions [0, P) of EVERY
    batch row (the solo `generate`/`generate_batch` paths treat rows as one
    aligned front). Donated cache: in-place in HBM."""
    L, b = cache.k.shape[0], cache.k.shape[1]
    P = k_seg.shape[1]
    kb = jnp.broadcast_to(k_seg[:, None], (L, b, P) + k_seg.shape[2:])
    vb = jnp.broadcast_to(v_seg[:, None], (L, b, P) + v_seg.shape[2:])
    k = jax.lax.dynamic_update_slice(cache.k, kb.astype(cache.k.dtype), (0, 0, 0, 0, 0))
    v = jax.lax.dynamic_update_slice(cache.v, vb.astype(cache.v.dtype), (0, 0, 0, 0, 0))
    if out_sharding is not None:
        k = jax.lax.with_sharding_constraint(k, out_sharding)
        v = jax.lax.with_sharding_constraint(v, out_sharding)
    return KVCache(k=k, v=v)


@partial(
    jax.jit,
    static_argnames=("out_sharding",),
    donate_argnames=("cache",),
)
def copy_prefix_into_row(cache, k_seg, v_seg, row, out_sharding=None):
    """Splice a cached slice [L, P, h, d] into positions [0, P) of ONE batch
    row (the BatchSession admission path; `row` is traced so every row
    shares one compiled program per bucket). Donated cache."""
    k = jax.lax.dynamic_update_slice(
        cache.k, k_seg[:, None].astype(cache.k.dtype), (0, row, 0, 0, 0)
    )
    v = jax.lax.dynamic_update_slice(
        cache.v, v_seg[:, None].astype(cache.v.dtype), (0, row, 0, 0, 0)
    )
    if out_sharding is not None:
        k = jax.lax.with_sharding_constraint(k, out_sharding)
        v = jax.lax.with_sharding_constraint(v, out_sharding)
    return KVCache(k=k, v=v)


@partial(jax.jit, static_argnames=("length", "out_sharding"))
def extract_prefix_from_row(cache, row, length, out_sharding=None):
    """Copy positions [0, length) of one row OUT of the live cache into a
    standalone [L, length, h, d] pair (the publish path). NOT donated — the
    live cache must survive; the result is the published entry's storage."""
    L, h, d = cache.k.shape[0], cache.k.shape[3], cache.k.shape[4]
    k = jax.lax.dynamic_slice(cache.k, (0, row, 0, 0, 0), (L, 1, length, h, d))[:, 0]
    v = jax.lax.dynamic_slice(cache.v, (0, row, 0, 0, 0), (L, 1, length, h, d))[:, 0]
    if out_sharding is not None:
        k = jax.lax.with_sharding_constraint(k, out_sharding)
        v = jax.lax.with_sharding_constraint(v, out_sharding)
    return k, v


# -- host-side structure ----------------------------------------------------


@dataclass
class PrefixEntry:
    """One published slice: `tokens` (a bucket-length tuple) is the trie
    key; `refs` pins the entry against eviction while an admission is
    between match and splice-dispatch. Contiguous engines store extracted
    device arrays in k/v; PAGED engines store `pages` instead — the
    physical page ids of the publishing row, refcount-retained in the
    engine's PagePool (runtime/paged_kv.py), so publishing moves ZERO
    device bytes and a hit maps the pages into the new row's table."""

    tokens: tuple
    k: object
    v: object
    nbytes: int
    refs: int = 0
    last_used: int = 0
    pages: tuple = ()  # paged engines: physical page ids covering tokens

    @property
    def length(self) -> int:
        return len(self.tokens)


class _Node:
    """Radix node: `edge` is the token run from the parent (path
    compression), children keyed by first token, `entry` set when a
    published slice ends exactly at this node."""

    __slots__ = ("edge", "children", "entry")

    def __init__(self, edge=()):
        self.edge = tuple(edge)
        self.children: dict = {}
        self.entry = None


class PrefixCache:
    """The engine-wide radix prefix cache (see module docstring)."""

    def __init__(
        self,
        budget_bytes: int,
        seq_len: int,
        max_chunk: int,
        stats=None,
        seg_sharding=None,
        cache_sharding=None,
        page_pool=None,  # runtime/paged_kv.PagePool: the cache then shares
        # refcounted pages instead of extracting/splicing copies (zero
        # device work on publish AND on hit)
    ):
        self.budget_bytes = int(budget_bytes)
        self.seq_len = seq_len
        self.max_chunk = max_chunk
        self.stats = stats  # StepStats: counters surface in /stats, /health
        self.seg_sharding = seg_sharding  # published-slice layout (pipeline)
        self.cache_sharding = cache_sharding  # live-cache layout to preserve
        self.page_pool = page_pool
        self.paged = page_pool is not None
        self.buckets = prefix_buckets(seq_len)
        self._root = _Node()
        self._entries: dict = {}  # token tuple -> PrefixEntry
        self._bytes = 0
        self._clock = 0
        self._lock = threading.Lock()

    # -- construction -------------------------------------------------------

    @classmethod
    def build(cls, engine, prefix_cache_mb=None):
        """The engine's factory: resolves the budget (constructor arg >
        DLT_PREFIX_CACHE_MB env > 0/off) and the topology gates. Returns
        None when the cache is disabled — `sp > 1` meshes shard the cache's
        seq axis itself, which a replicated slice cannot splice into."""
        prefix_cache_mb = resolve_budget_mb(prefix_cache_mb, default_mb=0)
        if prefix_cache_mb <= 0:
            return None
        if engine.mesh is not None and engine.mesh.shape.get("sp", 1) > 1:
            return None
        if not prefix_buckets(engine.cfg.seq_len):
            return None  # context too small for a publishable prefix
        seg_sh = None
        cache_sh = engine._cache_sharding
        if engine.use_pipeline:
            from ..parallel.pipeline import pp_prefix_sharding

            seg_sh = pp_prefix_sharding(engine.mesh)
        return cls(
            prefix_cache_mb * 1024 * 1024,
            seq_len=engine.cfg.seq_len,
            max_chunk=engine.max_chunk,
            stats=engine.stats,
            seg_sharding=seg_sh,
            cache_sharding=cache_sh,
            page_pool=engine.page_pool if engine.paged else None,
        )

    # -- observability ------------------------------------------------------

    def _incr(self, name, n=1):
        if self.stats is not None:
            self.stats.incr(name, n)

    def _gauges(self):
        if self.stats is not None:
            self.stats.gauge("prefix_cache_bytes", self._bytes)
            self.stats.gauge("prefix_cache_entries", len(self._entries))

    @property
    def n_entries(self) -> int:
        return len(self._entries)

    @property
    def total_bytes(self) -> int:
        """Device bytes the published entries hold — also the HBM ledger's
        ``prefix_cache`` component (runtime/profiling.py hbm_ledger)."""
        return self._bytes

    def stats_snapshot(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": self._bytes,
                "budget_bytes": self.budget_bytes,
                "buckets": list(self.buckets),
                "pinned": sum(1 for e in self._entries.values() if e.refs > 0),
            }

    # -- matching -----------------------------------------------------------

    def resume_boundary(self, m: int) -> int:
        """Round a matched length DOWN to a chunk-bucket boundary: a
        multiple of max_chunk, or (below one chunk) the largest power-of-two
        chunk bucket — so the resumed prefill's chunk plan stays on the same
        (size, kv-bucket) warm ladder a cold prefill walks."""
        if m >= self.max_chunk:
            return (m // self.max_chunk) * self.max_chunk
        b = 0
        p = 1
        while p <= m:
            b = p
            p *= 2
        return b

    def _walk(self, tokens):
        """(m, subtree_node, best_on_path): m = longest shared prefix with
        any published chain; subtree_node roots the entries sharing exactly
        m tokens; best_on_path = deepest entry whose WHOLE chain matched."""
        node = self._root
        t = tuple(tokens)
        m = 0
        best = None
        while True:
            if node.entry is not None:
                best = node.entry
            if m == len(t):
                return m, node, best
            child = node.children.get(t[m])
            if child is None:
                return m, None, best
            e = child.edge
            lim = min(len(e), len(t) - m)
            lcp = 0
            while lcp < lim and e[lcp] == t[m + lcp]:
                lcp += 1
            m += lcp
            if lcp == len(e):
                node = child
                continue
            # diverged (or ran out of tokens) mid-edge: everything below
            # `child` still shares exactly the first m tokens
            return m, child, best

    @staticmethod
    def _first_entry(node):
        if node is None:
            return None
        stack = [node]
        while stack:
            n = stack.pop()
            if n.entry is not None:
                return n.entry
            stack.extend(n.children.values())
        return None

    def match(self, tokens):
        """Longest-prefix match: (covered, entry). `covered` is the number
        of leading tokens of `tokens` the entry's slice holds CORRECT KV
        for; entry None on a miss. An entry deeper than the divergence point
        is still usable — its positions past `covered` get rewritten by the
        resumed prefill before any query reads them (module docstring)."""
        with self._lock:
            m, subtree, best = self._walk(tokens)
            entry = self._first_entry(subtree)
            if entry is not None:
                return m, entry
            if best is not None:
                return min(m, best.length), best
            return 0, None

    def match_for_splice(self, tokens):
        """The admission-path lookup: returns (resume_boundary, entry) with
        the entry PINNED (refs+1) so eviction cannot drop it between match
        and splice dispatch — the caller must `entry_release` it after the
        copy is dispatched (or abandoned). A miss (including a match whose
        boundary rounds below the publishable floor) is counted here; a HIT
        is counted by `record_hit` at splice-dispatch time, so an admission
        abandoned before its splice never inflates prefix_hit_tokens (the
        metric is "prefill compute actually skipped")."""
        covered, entry = self.match(tokens)
        B = self.resume_boundary(min(covered, len(tokens)))
        if self.paged and entry is not None:
            # page sharing maps WHOLE pages read-only: floor the boundary
            # to a page multiple and cap it at the entry's own coverage
            # (the contiguous splice copies positions past the divergence
            # too — rewritten later; shared pages must never be written)
            ps = self.page_pool.page_size
            B = (min(B, entry.length) // ps) * ps
        if entry is None or B < PREFIX_MIN_TOKENS:
            self._incr("prefix_misses")
            return 0, None
        with self._lock:
            entry.refs += 1
            self._clock += 1
            entry.last_used = self._clock
        return B, entry

    def record_hit(self, resume: int) -> None:
        """Count one splice that actually dispatched (`resume` = the
        bucket-aligned prefill tokens it skipped)."""
        self._incr("prefix_hits")
        self._incr("prefix_hit_tokens", resume)
        # engine-level trace event (flight-recorder context; the request's
        # own prefix_match/prefix_splice spans carry the per-request view)
        global_event("prefix_hit", keys=("tokens",), vals=(resume,))

    def entry_release(self, entry) -> None:
        with self._lock:
            entry.refs = max(0, entry.refs - 1)

    # -- splicing -----------------------------------------------------------

    def splice_rows(self, engine, entry):
        """Dispatch the all-rows copy program; returns the new (donated)
        cache. Dispatch-only: nothing here blocks on the device."""
        return copy_prefix_into_rows(
            engine.cache, entry.k, entry.v, out_sharding=self.cache_sharding
        )

    def splice_row(self, engine, entry, row: int):
        """Dispatch the one-row copy program (BatchSession admissions)."""
        return copy_prefix_into_row(
            engine.cache, entry.k, entry.v, jnp.asarray(row, jnp.int32),
            out_sharding=self.cache_sharding,
        )

    def share_row(self, engine, entry, row: int, resume: int) -> None:
        """The PAGED splice: map the entry's pages covering [0, resume)
        into `row`'s page table with refcounts bumped — ZERO device
        dispatches, zero KV bytes moved. `resume` is the page-aligned
        boundary `match_for_splice` returned."""
        n = resume // self.page_pool.page_size
        self.page_pool.share(row, entry.pages[:n])
        engine._pt_cache = None  # table changed: refresh the operand

    def share_rows(self, engine, entry, resume: int) -> None:
        """Paged splice into EVERY row (the solo generate / generate_batch
        aligned-front paths): each row maps the same shared pages."""
        for row in range(engine.batch):
            self.share_row(engine, entry, row, resume)

    # -- publishing ---------------------------------------------------------

    def publish_from_row(self, engine, row: int, tokens, max_len=None) -> bool:
        """Publish the first `bucket_down(max_len)` tokens' KV of `row` into
        the trie: one extract copy out of the live cache, then a host-side
        radix insert. Every position < max_len must already hold final KV
        (callers cap at the last *fed* token). Dedupes by token key; evicts
        LRU unpinned entries to fit the budget; skips (with a counter) when
        pinned entries leave no room. Returns True when an entry was
        inserted or refreshed."""
        n = len(tokens) if max_len is None else min(max_len, len(tokens))
        P = bucket_down(n, self.seq_len)
        if self.paged:
            # only whole pages can be shared read-only
            P = (P // self.page_pool.page_size) * self.page_pool.page_size
        if P < PREFIX_MIN_TOKENS:
            return False
        key = tuple(int(t) for t in tokens[:P])
        with self._lock:
            existing = self._entries.get(key)
            if existing is not None:
                self._clock += 1
                existing.last_used = self._clock
                return True
            need = self._slice_nbytes(engine, P)
            if need > self.budget_bytes:
                self._incr("prefix_publish_skipped")
                return False
            if not self._evict_until(self.budget_bytes - need):
                self._incr("prefix_publish_skipped")
                return False
        if self.paged:
            # PAGED publish: retain the publisher row's own pages — no
            # extract program, no device bytes moved. Positions < P are
            # final for the row (the callers' max_len contract), and the
            # row's future writes land past P in other pages (or trigger
            # copy-on-write if it ever rewinds), so the shared pages are
            # immutable from here on.
            try:
                pages = self.page_pool.row_pages(
                    row, P // self.page_pool.page_size
                )
            except ValueError:
                # unmapped slots below P: the row never actually held this
                # span (shouldn't happen — defensive, counted)
                self._incr("prefix_publish_skipped")
                return False
            self.page_pool.retain(pages)
            k = v = None
            nbytes = self._slice_nbytes(engine, P)
        else:
            # dispatch OUTSIDE the lock: /stats readers must not wait on a
            # device dispatch. The extract is async; the arrays become the
            # entry's storage and are only consumed by later splice
            # dispatches, which XLA orders after the producing program.
            pages = ()
            with engine._guard(f"prefix_extract[{P}]", ("prefix_extract", P, P)):
                k, v = extract_prefix_from_row(
                    engine.cache, jnp.asarray(row, jnp.int32), length=P,
                    out_sharding=self.seg_sharding,
                )
            nbytes = k.nbytes + v.nbytes
        with self._lock:
            if key in self._entries:  # raced with another publisher
                if pages:
                    self.page_pool.release(pages)
                return True
            self._clock += 1
            entry = PrefixEntry(
                tokens=key, k=k, v=v, nbytes=nbytes,
                last_used=self._clock, pages=pages,
            )
            self._insert(entry)
            self._entries[key] = entry
            self._bytes += entry.nbytes
            self._gauges()
        self._incr("prefix_inserts")
        global_event("prefix_publish", keys=("tokens", "row"), vals=(P, int(row)))
        return True

    def insert_external(self, engine, tokens, k_np, v_np) -> bool:
        """Insert a slice computed OUTSIDE this process — the disaggregated
        serving path (server/disagg.py): a prefill worker ran the prompt,
        extracted ``[L, P, h, d]`` k/v at a bucket boundary, and shipped the
        host arrays here. They are device_put (cast to the live cache's
        dtype, pinned to the pipeline slice sharding where one exists) and
        inserted exactly like a local publish, so the very next admission's
        ``match_for_splice`` hits and splices them through the SAME warmed
        copy programs a local hit uses — which is what makes the
        disaggregated path bit-identical to unified serving.

        Contiguous engines only: a PAGED entry's storage is physical page
        ids in this process's pool, which have no host representation (the
        serve() role gate forces contiguous on disaggregated workers).
        Returns False — never raises — when the slice is unusable (paged
        engine, off-bucket length, budget unreachable): the caller then
        simply prefills locally, the degradation contract."""
        if self.paged:
            return False
        P = len(tokens)
        if P < PREFIX_MIN_TOKENS or P != bucket_down(P, self.seq_len):
            return False
        key = tuple(int(t) for t in tokens)
        with self._lock:
            existing = self._entries.get(key)
            if existing is not None:
                self._clock += 1
                existing.last_used = self._clock
                return True
        dt = engine.cache.k.dtype
        L, _, _, h, d = engine.cache.k.shape
        if tuple(k_np.shape) != (L, P, h, d) or tuple(v_np.shape) != (L, P, h, d):
            return False
        if self.seg_sharding is not None:
            k = jax.device_put(k_np.astype(dt), self.seg_sharding)
            v = jax.device_put(v_np.astype(dt), self.seg_sharding)
        else:
            k = jax.device_put(k_np.astype(dt))
            v = jax.device_put(v_np.astype(dt))
        nbytes = k.nbytes + v.nbytes
        with self._lock:
            if key in self._entries:  # raced with another inserter
                return True
            if nbytes > self.budget_bytes or not self._evict_until(
                self.budget_bytes - nbytes
            ):
                self._incr("prefix_publish_skipped")
                return False
            self._clock += 1
            entry = PrefixEntry(
                tokens=key, k=k, v=v, nbytes=nbytes, last_used=self._clock
            )
            self._insert(entry)
            self._entries[key] = entry
            self._bytes += entry.nbytes
            self._gauges()
        self._incr("prefix_inserts")
        global_event("prefix_insert_external", keys=("tokens",), vals=(P,))
        return True

    def _slice_nbytes(self, engine, P: int) -> int:
        if self.paged:
            from .paged_kv import page_pool_bytes

            ps = self.page_pool.page_size
            return page_pool_bytes(engine.cfg, P // ps, ps)
        L, _, _, h, d = engine.cache.k.shape
        return 2 * L * P * h * d * engine.cache.k.dtype.itemsize

    # -- trie maintenance (callers hold the lock) ---------------------------

    def _insert(self, entry) -> None:
        t = entry.tokens
        node = self._root
        i = 0
        while True:
            if i == len(t):
                node.entry = entry
                return
            child = node.children.get(t[i])
            if child is None:
                leaf = _Node(t[i:])
                leaf.entry = entry
                node.children[t[i]] = leaf
                return
            e = child.edge
            lim = min(len(e), len(t) - i)
            lcp = 0
            while lcp < lim and e[lcp] == t[i + lcp]:
                lcp += 1
            if lcp == len(e):
                node = child
                i += lcp
                continue
            # split the edge at the divergence point
            mid = _Node(e[:lcp])
            child.edge = e[lcp:]
            mid.children[child.edge[0]] = child
            node.children[t[i]] = mid
            i += lcp
            if i == len(t):
                mid.entry = entry
            else:
                leaf = _Node(t[i:])
                leaf.entry = entry
                mid.children[t[i]] = leaf
            return

    def _detach(self, entry) -> None:
        """Remove `entry` from the trie, pruning now-empty nodes."""
        t = entry.tokens
        path = []  # (parent, first_token, node)
        node = self._root
        i = 0
        while i < len(t):
            child = node.children.get(t[i])
            if child is None:
                return  # not present (already detached)
            path.append((node, t[i], child))
            i += len(child.edge)
            node = child
        if node.entry is not entry:
            return
        node.entry = None
        for parent, first, n in reversed(path):
            if n.entry is None and not n.children:
                del parent.children[first]
            else:
                break

    def _evict_until(self, target_bytes: int) -> bool:
        """Evict LRU UNPINNED entries until total <= target; False when
        pinned entries make the target unreachable."""
        while self._bytes > target_bytes:
            victims = [e for e in self._entries.values() if e.refs == 0]
            if not victims:
                return False
            victim = min(victims, key=lambda e: e.last_used)
            self._remove(victim)
            self._incr("prefix_evictions")
        return True

    def _remove(self, entry) -> None:
        self._detach(entry)
        self._entries.pop(entry.tokens, None)
        self._bytes -= entry.nbytes
        if entry.pages:
            self.page_pool.release(entry.pages)
        self._gauges()

    def evict_one(self) -> bool:
        """Evict the LRU UNPINNED entry (the page pool's reclaim hook:
        allocation pressure trades cached prefixes for live-row pages).
        False when everything is pinned or the cache is empty."""
        with self._lock:
            victims = [e for e in self._entries.values() if e.refs == 0]
            if not victims:
                return False
            self._remove(min(victims, key=lambda e: e.last_used))
            self._incr("prefix_evictions")
            return True

    def clear(self) -> None:
        """Drop every entry (engine recovery: after an engine failure the
        in-flight extracts may descend from the failed computation).
        Paged entries release their page refs back to the pool."""
        with self._lock:
            for entry in self._entries.values():
                if entry.pages:
                    self.page_pool.release(entry.pages)
            self._root = _Node()
            self._entries.clear()
            self._bytes = 0
            self._gauges()
