"""Tiered KV store: HBM -> host RAM -> disk -> peer fleet cache.

The radix prefix cache (runtime/prefix_cache.py) is an HBM-budgeted LRU:
under pressure it simply deletes the victim, and the next request sharing
that prefix pays a full cold prefill. At fleet scale the warm-prefix
working set (system prompts, RAG corpora, conversation histories) vastly
exceeds HBM, so deletion throws away exactly the KV the next minute of
traffic wants. This module turns eviction into DEMOTION down a ladder of
cheaper tiers, and admission misses into PROMOTION back up it:

* **tier 0 — HBM**: the prefix cache itself (unchanged; this module never
  touches its hit path).
* **tier 1 — host RAM**: evicted entries are captured at `_remove` time
  (a warmed ``page_extract`` gather for paged engines — dispatched BEFORE
  the pool recycles the victim's pages, so same-thread dispatch order
  guarantees the bytes are read first; a zero-work device-ref retain for
  contiguous engines) and drained to pinned host arrays by a background
  thread, byte-budgeted via ``DLT_KV_HOST_TIER_MB``.
* **tier 2 — disk**: host-tier eviction spills the entry as ONE file in
  the PR 10/16 wire format (length-prefixed JSON header + raw k + raw v,
  WITH per-doubling-segment checksums), budgeted via
  ``DLT_KV_DISK_TIER_MB`` under ``DLT_KV_DISK_TIER_DIR``. Reads re-verify
  through :func:`~.kv_transport.verify_transfer` — a flipped bit on disk
  is rejected, unlinked, and counted, never inserted.
* **tier 3 — peer fleet**: any replica can fetch a named page set from
  whichever peer holds it (``DLT_KV_TIER_PEERS``) over
  ``POST /v1/kv_fetch`` — the disagg ``have``/skip protocol generalized
  from "ask the prefill tier to compute" to "ask whoever already holds
  these page_keys". The response rides the SAME verified wire codec, so
  the PR 16 integrity/quarantine semantics (checksum verify before the
  cache is touched, per-peer strikes with TTL redemption, degrade to
  local prefill token-identically) apply unchanged.

Promotion lands through :meth:`PrefixCache.insert_external` — the SAME
warmed ``page_insert``/``device_put`` path a disaggregated transfer uses —
so a promoted prefix splices through the engine's existing warm ladder:
zero post-warmup recompiles, token-identical to a cold prefill. Paged
int8 entries compose: the gather dequantizes on extract, so host/disk
budgets charge the bytes actually stored at that tier.

The router already knows the request's prefix chain before the replica
has parsed the body: the gateway stamps it as ``X-DLT-Prefetch-Chain``,
and :meth:`TieredKvStore.prefetch_hint` starts lifting matching disk/peer
entries into the host tier while the request is still being tokenized.
"""

from __future__ import annotations

import json
import os
import queue
import tempfile
import threading
import time
from collections import OrderedDict

import numpy as np

from .kv_transport import (
    KEY_PAGE_TOKENS,
    WIRE_VERSION,
    KvCodecError,
    KvVersionError,
    TransferResult,
    device_peer,
    doubling_segments,
    kv_payload,
    matching_pages,
    page_keys,
    parse_kv_payload,
    segment_checksum,
    verify_transfer,
)
from .prefix_cache import PREFIX_MIN_TOKENS, bucket_down

DEFAULT_TIMEOUT_S = 5.0

#: test hook: one-shot payload corruption on the SERVING side — the next
#: ``serve_fetch`` flips a byte inside the k region after checksumming, so
#: the chaos twin proves the requester's verify gate rejects it and the
#: request degrades to local prefill (tests/test_kv_tiering.py)
_serve_chaos: list = []


def set_serve_chaos(enabled: bool = True) -> None:
    """Arm (or clear) the one-shot corrupt-serve fault."""
    _serve_chaos.clear()
    if enabled:
        _serve_chaos.append("flip")


def _prefill_boundary(n_prompt_tokens: int, seq_len: int) -> int:
    # mirrors server/disagg.prefill_boundary without a runtime->server import
    P = bucket_down(max(n_prompt_tokens - 1, 0), seq_len)
    return P if P >= PREFIX_MIN_TOKENS else 0


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def resolve_tier_peers(explicit=None) -> list:
    """``DLT_KV_TIER_PEERS``: comma-separated host:port fleet-cache peers."""
    raw = list(explicit) if explicit else [
        s for s in os.environ.get("DLT_KV_TIER_PEERS", "").split(",")
        if s.strip()
    ]
    peers = []
    for s in raw:
        if isinstance(s, (tuple, list)) and len(s) == 2:
            peers.append((str(s[0]), int(s[1])))
            continue
        host, _, port = str(s).strip().rpartition(":")
        peers.append((host or "127.0.0.1", int(port)))
    return peers


class _HostEntry:
    """One host-tier (tier 1) resident: full [0, P) coverage as pinned
    host arrays plus the READY wire header (page_keys + per-segment
    checksums computed once at demotion-drain time), so a disk spill or a
    peer serve is a slice + serialize, never a re-hash of the whole
    entry."""

    __slots__ = ("tokens", "k", "v", "header", "nbytes")

    def __init__(self, tokens, k, v, header, nbytes):
        self.tokens = tokens
        self.k = k
        self.v = v
        self.header = header
        self.nbytes = int(nbytes)


def _build_header(tokens, k_np, v_np) -> dict:
    P = len(tokens)
    spans = doubling_segments(0, P)
    return {
        "v": WIRE_VERSION,
        "tokens": [int(t) for t in tokens],
        "p": P,
        "start": 0,
        "page_tokens": KEY_PAGE_TOKENS,
        "page_keys": [format(h, "x") for h in page_keys(tokens)],
        "prefill_us": 0,
        "k_shape": list(k_np.shape),
        "v_shape": list(v_np.shape),
        "dtype": str(k_np.dtype),
        "k_sums": [
            format(segment_checksum(k_np[:, a:b].tobytes()), "x")
            for a, b in spans
        ],
        "v_sums": [
            format(segment_checksum(v_np[:, a:b].tobytes()), "x")
            for a, b in spans
        ],
    }


def _slice_payload(tokens, k_np, v_np, start: int) -> bytes:
    """Serialize tokens ``[start, P)`` of a held entry as the v2 wire
    payload — the ``/v1/kv_fetch`` response body (and, with start=0, the
    disk-tier file format). Checksums cover the doubling ladder of the
    SLICE, exactly like server/disagg.run_prefill."""
    P = len(tokens)
    k_s = k_np[:, start:] if start else k_np
    v_s = v_np[:, start:] if start else v_np
    spans = doubling_segments(start, P)
    header = {
        "v": WIRE_VERSION,
        "tokens": [int(t) for t in tokens],
        "p": P,
        "start": start,
        "page_tokens": KEY_PAGE_TOKENS,
        "page_keys": [format(h, "x") for h in page_keys(tokens)],
        "prefill_us": 0,
        "k_shape": list(k_s.shape),
        "v_shape": list(v_s.shape),
        "dtype": str(k_s.dtype),
        "k_sums": [
            format(segment_checksum(k_s[:, a - start : b - start].tobytes()), "x")
            for a, b in spans
        ],
        "v_sums": [
            format(segment_checksum(v_s[:, a - start : b - start].tobytes()), "x")
            for a, b in spans
        ],
    }
    return kv_payload(header, np.ascontiguousarray(k_s), np.ascontiguousarray(v_s))


class PendingPromotion:
    """A tier hit fetched-but-not-yet-inserted — the promotion half of the
    PR 2 double-buffer idiom: the host/disk/peer fetch ran on the handler
    thread (overlapping admission), and the device insert defers here so
    it runs on the ENGINE's dispatch thread (a paged insert donates the
    live pool). Duck-types server/disagg.PendingExternalKv — the Batcher
    and the serialized path apply either without knowing which subsystem
    produced it. ``base_entry`` (a peer fetch's content-addressed skip
    base) stays PINNED until applied or abandoned."""

    def __init__(self, store, tokens, k, v, tier: str, start: int = 0,
                 base_entry=None):
        self.store = store
        self.tokens = tokens
        self.k = k
        self.v = v
        self.tier = tier
        self.start = start
        self.base_entry = base_entry
        self._applied = False

    def apply(self, state) -> bool:
        if self._applied:
            return True
        self._applied = True
        engine = self.store.engine
        pc = engine.prefix_cache
        t0 = time.perf_counter()
        try:
            ok = pc.insert_external(
                engine, self.tokens, self.k, self.v, start=self.start,
                base_entry=self.base_entry,
            )
        finally:
            if self.base_entry is not None:
                pc.entry_release(self.base_entry)
            self.base_entry = None
        engine.stats.record(
            "promotion_insert_us", int((time.perf_counter() - t0) * 1e6)
        )
        if ok:
            engine.stats.incr("kv_tier_promotions")
            engine.stats.incr(
                "kv_tier_promoted_tokens", len(self.tokens) - self.start
            )
        else:
            engine.stats.incr("kv_tier_insert_failed")
            if self.store.goodput is not None:
                self.store.goodput.add_waste(
                    "transfer_retry", len(self.tokens) - self.start
                )
        return ok

    def abandon(self):
        """Release the pinned base without inserting (failed request path
        between fetch and admission)."""
        if self.base_entry is not None:
            self.store.engine.prefix_cache.entry_release(self.base_entry)
            self.base_entry = None
        self._applied = True


class TieredKvStore:
    """The tier 1-3 ladder behind one engine's prefix cache. Thread
    model: `capture_demotion` runs on the engine thread inside the trie
    lock (dispatch-only); a drain thread moves captured device arrays to
    host; a prefetch thread lifts disk/peer entries toward the host tier;
    `fetch`/`serve_fetch` run on handler threads and touch host memory
    and sockets only (the device insert defers to
    :class:`PendingPromotion`)."""

    def __init__(self, engine, goodput=None, host_mb=None, disk_mb=None,
                 disk_dir=None, peers=None, timeout_s=None,
                 backoff_s=None, integrity_strikes=None, strike_ttl_s=None):
        self.engine = engine
        self.goodput = goodput
        self.host_budget = (
            _env_int("DLT_KV_HOST_TIER_MB", 0) if host_mb is None else host_mb
        ) * 1024 * 1024
        self.disk_budget = (
            _env_int("DLT_KV_DISK_TIER_MB", 0) if disk_mb is None else disk_mb
        ) * 1024 * 1024
        if disk_dir is None:
            disk_dir = os.environ.get("DLT_KV_DISK_TIER_DIR", "")
        self.disk_dir = disk_dir or os.path.join(
            tempfile.gettempdir(), "dlt_kv_tier"
        )
        self.peers = resolve_tier_peers(peers)
        self.timeout_s = (
            _env_float("DLT_DISAGG_TIMEOUT_S", DEFAULT_TIMEOUT_S)
            if timeout_s is None else timeout_s
        )
        self.backoff_s = (
            _env_float("DLT_DISAGG_PEER_BACKOFF_S", 10.0)
            if backoff_s is None else backoff_s
        )
        self.integrity_strikes = max(
            _env_int("DLT_KV_INTEGRITY_STRIKES", 3)
            if integrity_strikes is None else integrity_strikes, 1,
        )
        self.strike_ttl_s = (
            _env_float("DLT_KV_INTEGRITY_TTL_S", 300.0)
            if strike_ttl_s is None else strike_ttl_s
        )
        self._lock = threading.Lock()  # host/disk indexes + peer ledgers
        self._host: OrderedDict = OrderedDict()  # token tuple -> _HostEntry
        self._host_bytes = 0
        self._disk: OrderedDict = OrderedDict()  # token tuple -> (path, nbytes)
        self._disk_bytes = 0
        self._file_seq = 0
        self._rr = 0
        self._backoff_until: dict = {}
        self._strikes: dict = {}
        # the prefetch-hint index: chain key (router FNV-1a text-block
        # hash) -> known token prefix tuple. Bounded: a hint is a hint.
        self._hints: OrderedDict = OrderedDict()
        self._hints_cap = 1024
        self._demote_q: queue.Queue = queue.Queue(maxsize=64)
        self._prefetch_q: queue.Queue = queue.Queue(maxsize=64)
        self._closed = False
        self._drain_thread = threading.Thread(
            target=self._drain_loop, name="kv-tier-drain", daemon=True
        )
        self._prefetch_thread = threading.Thread(
            target=self._prefetch_loop, name="kv-tier-prefetch", daemon=True
        )
        self._drain_thread.start()
        self._prefetch_thread.start()

    # -- construction -------------------------------------------------------

    @classmethod
    def build(cls, engine, goodput=None):
        """None unless some tier is configured (host or disk budget > 0,
        or fleet-cache peers named) AND the engine runs a prefix cache —
        without tier 0 there is nothing to demote from or promote into."""
        if engine.prefix_cache is None:
            return None
        host_mb = _env_int("DLT_KV_HOST_TIER_MB", 0)
        disk_mb = _env_int("DLT_KV_DISK_TIER_MB", 0)
        peers = resolve_tier_peers()
        if host_mb <= 0 and disk_mb <= 0 and not peers:
            return None
        return cls(engine, goodput=goodput)

    def close(self):
        """Stop the drain/prefetch loops (sentinel per queue; daemon
        threads, so a missed join can never hang exit)."""
        self._closed = True
        for q in (self._demote_q, self._prefetch_q):
            try:
                q.put_nowait(None)
            except queue.Full:
                pass  # dlt: allow(swallowed-exception) — the loop also polls self._closed
        self._drain_thread.join(timeout=2.0)
        self._prefetch_thread.join(timeout=2.0)

    def _incr(self, name, n=1):
        self.engine.stats.incr(name, n)

    def _gauges(self):
        # callers hold self._lock
        self.engine.stats.gauge("kv_tier_host_bytes", self._host_bytes)
        self.engine.stats.gauge("kv_tier_host_entries", len(self._host))
        self.engine.stats.gauge("kv_tier_host_budget_bytes", self.host_budget)
        self.engine.stats.gauge("kv_tier_disk_bytes", self._disk_bytes)
        self.engine.stats.gauge("kv_tier_disk_entries", len(self._disk))

    # -- demotion (tier 0 -> 1 -> 2) ----------------------------------------

    def capture_demotion(self, entry) -> None:
        """Called by PrefixCache._remove UNDER the trie lock, on the
        engine thread, BEFORE the victim's pages return to the pool.
        Paged: dispatch ONE warmed ``page_extract`` gather over the
        victim's pages — dispatch order on the engine thread serializes
        it ahead of any scatter that later recycles them, so the capture
        reads the victim's bytes, never a successor's. Contiguous: the
        entry owns standalone device arrays; retaining the refs is the
        whole capture. Never blocks: a full drain queue drops the
        demotion (counted) rather than stall an eviction."""
        if self._closed or (self.host_budget <= 0 and self.disk_budget <= 0):
            return
        engine = self.engine
        pc = engine.prefix_cache
        P = entry.length
        if P != bucket_down(P, pc.seq_len):
            return
        if entry.pages:
            from .paged_kv import gather_pages

            # host page-index tuple -> numpy operand (no device involved)
            seg_pages = np.asarray(entry.pages, np.int32)  # dlt: allow(host-sync) — host-only page indices, not a device array
            with engine._guard(f"page_extract[{P}]", ("page_extract", P, P)):
                k, v = gather_pages(
                    engine.cache, seg_pages, out_sharding=pc.seg_sharding
                )
        else:
            k, v = entry.k, entry.v
        try:
            self._demote_q.put_nowait((tuple(entry.tokens), k, v))
        except queue.Full:
            self._incr("kv_tier_demote_dropped")

    def _drain_loop(self):
        while True:
            try:
                item = self._demote_q.get(timeout=0.5)
            except queue.Empty:
                if self._closed:
                    return
                continue
            if item is None:
                return
            tokens, k, v = item
            try:
                # the ONLY d2h in the subsystem: a background drain of a
                # cold eviction, under no transfer guard (the hot-loop
                # guard scope is thread-local by design)
                k_np = np.ascontiguousarray(np.asarray(k))  # dlt: allow(host-sync) — cold demotion drain, never the serving loop
                v_np = np.ascontiguousarray(np.asarray(v))  # dlt: allow(host-sync) — cold demotion drain, never the serving loop
                header = _build_header(tokens, k_np, v_np)
                nbytes = int(k_np.nbytes) + int(v_np.nbytes)
                self._host_put(
                    _HostEntry(tokens, k_np, v_np, header, nbytes)
                )
                self._incr("kv_tier_demoted_host")
                self._incr("kv_tier_demoted_bytes", nbytes)
            except Exception:  # dlt: allow(swallowed-exception) — counted; a failed demotion is a cache miss later, never an error now
                self._incr("kv_tier_demote_dropped")

    def _host_put(self, entry: _HostEntry) -> None:
        if self.host_budget <= 0:
            self._spill_to_disk(entry)
            return
        with self._lock:
            old = self._host.pop(entry.tokens, None)
            if old is not None:
                self._host_bytes -= old.nbytes
            self._host[entry.tokens] = entry
            self._host_bytes += entry.nbytes
            spill = []
            while self._host_bytes > self.host_budget and len(self._host) > 1:
                _key, victim = self._host.popitem(last=False)
                self._host_bytes -= victim.nbytes
                spill.append(victim)
            if self._host_bytes > self.host_budget:
                _key, victim = self._host.popitem(last=False)
                self._host_bytes -= victim.nbytes
                spill.append(victim)
            self._gauges()
        for victim in spill:
            self._spill_to_disk(victim)

    def _spill_to_disk(self, entry: _HostEntry) -> None:
        if self.disk_budget <= 0:
            return
        try:
            payload = _slice_payload(entry.tokens, entry.k, entry.v, 0)
            os.makedirs(self.disk_dir, exist_ok=True)
            with self._lock:
                self._file_seq += 1
                seq = self._file_seq
            name = format(page_keys(entry.tokens)[-1], "016x")
            path = os.path.join(
                self.disk_dir, f"{name}_{len(entry.tokens)}_{seq}.kv"
            )
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(payload)
            os.replace(tmp, path)
        except OSError:
            self._incr("kv_tier_disk_errors")
            return
        unlink = []
        with self._lock:
            old = self._disk.pop(entry.tokens, None)
            if old is not None:
                self._disk_bytes -= old[1]
                unlink.append(old[0])
            self._disk[entry.tokens] = (path, len(payload))
            self._disk_bytes += len(payload)
            while self._disk_bytes > self.disk_budget and len(self._disk) > 1:
                _key, (vpath, vbytes) = self._disk.popitem(last=False)
                self._disk_bytes -= vbytes
                unlink.append(vpath)
            self._gauges()
        for vpath in unlink:
            try:
                os.unlink(vpath)
            except OSError:
                pass  # dlt: allow(swallowed-exception) — already gone; the index no longer names it
        self._incr("kv_tier_demoted_disk")

    # -- lookup (tiers 1/2 local, handler-thread safe) ----------------------

    def _held_buckets(self, ids, P: int) -> list:
        """Candidate bucket lengths, longest first, capped at P."""
        pc = self.engine.prefix_cache
        return [B for B in reversed(pc.buckets) if PREFIX_MIN_TOKENS <= B <= P]

    def _host_get(self, key):
        with self._lock:
            entry = self._host.get(key)
            if entry is not None:
                self._host.move_to_end(key)
            return entry

    def _disk_get(self, key):
        """Load + VERIFY one disk-tier entry; a corrupt or unreadable file
        is unlinked and counted — disk rot degrades to a miss, exactly
        like a corrupt peer degrades to local prefill."""
        with self._lock:
            hit = self._disk.get(key)
        if hit is None:
            return None
        path, nbytes = hit
        try:
            with open(path, "rb") as f:
                raw = f.read()
            header, k, v = parse_kv_payload(raw)
            result = TransferResult(header, k, v, "http", len(raw))
            verify_transfer(result, list(key), len(key))
        except (OSError, KvCodecError):
            self._incr("kv_tier_disk_corrupt")
            with self._lock:
                if self._disk.pop(key, None) is not None:
                    self._disk_bytes -= nbytes
                self._gauges()
            try:
                os.unlink(path)
            except OSError:
                pass  # dlt: allow(swallowed-exception) — already gone
            return None
        return _HostEntry(key, k, v, header, int(k.nbytes) + int(v.nbytes))

    def _truncated(self, entry: _HostEntry, B: int) -> _HostEntry:
        """A bucket-B view of a longer resident — valid because causal KV
        at positions < B depends only on tokens[:B] (the same property the
        HBM radix trie exploits). Checksums are recomputed for the slice,
        so the wire/verify contract is unchanged."""
        k = np.ascontiguousarray(entry.k[:, :B])
        v = np.ascontiguousarray(entry.v[:, :B])
        tokens = tuple(entry.tokens[:B])
        return _HostEntry(
            tokens, k, v, _build_header(tokens, k, v),
            int(k.nbytes) + int(v.nbytes),
        )

    def _lookup_local(self, ids, P: int, promote_host: bool):
        """(tier_name, _HostEntry) for the longest held bucket <= P, or
        (None, None). A disk hit optionally re-lands in the host tier."""
        for B in self._held_buckets(ids, P):
            key = tuple(int(t) for t in ids[:B])
            entry = self._host_get(key)
            if entry is not None:
                return "host", entry
            entry = self._disk_get(key)
            if entry is not None:
                if promote_host:
                    self._host_put(entry)
                return "disk", entry
        # exact-length keys missed: a LONGER resident whose leading tokens
        # match still covers the request — a prompt ending exactly on a
        # bucket boundary (publish at bucket_down(n), fetch at
        # bucket_down(n-1)), or a shorter sibling sharing the prefix
        for B in self._held_buckets(ids, P):
            prefix = tuple(int(t) for t in ids[:B])
            with self._lock:
                host_key = next(
                    (
                        k for k in reversed(self._host)
                        if len(k) > B and k[:B] == prefix
                    ),
                    None,
                )
            if host_key is not None:
                entry = self._host_get(host_key)
                if entry is not None:
                    return "host", self._truncated(entry, B)
            with self._lock:
                disk_key = next(
                    (
                        k for k in self._disk
                        if len(k) > B and k[:B] == prefix
                    ),
                    None,
                )
            if disk_key is not None:
                entry = self._disk_get(disk_key)
                if entry is not None:
                    entry = self._truncated(entry, B)
                    if promote_host:
                        self._host_put(entry)
                    return "disk", entry
        return None, None

    # -- peer tier (tier 3) -------------------------------------------------

    def _peer_usable(self, peer) -> bool:
        now = time.monotonic()
        with self._lock:
            if self._backoff_until.get(peer, 0.0) > now:
                return False
            entry = self._strikes.get(peer)
            if entry is None:
                return True
            count, ttl = entry
            if ttl <= now:
                del self._strikes[peer]
                return True
            return count < self.integrity_strikes

    def _peer_failed(self, peer):
        with self._lock:
            self._backoff_until[peer] = time.monotonic() + self.backoff_s

    def _peer_strike(self, peer) -> int:
        now = time.monotonic()
        with self._lock:
            count, ttl = self._strikes.get(peer, (0, 0.0))
            if ttl <= now:
                count = 0
            count += 1
            self._strikes[peer] = (count, now + self.strike_ttl_s)
            return count

    def _peer_ok(self, peer):
        with self._lock:
            self._backoff_until.pop(peer, None)

    def _peer_fetch_raw(self, peer, ids, have) -> bytes:
        """One peer round trip: the same-process registry short-circuits
        the socket (still through the SERIALIZED payload, so the verify
        gate sees real bytes either way); otherwise POST /v1/kv_fetch."""
        host, port = peer
        provider = device_peer(port)
        if provider is not None and hasattr(provider, "kv_tier_payload"):
            raw = provider.kv_tier_payload(list(ids), have_keys=tuple(have))
            if raw is None:
                raise OSError(f"peer {host}:{port} holds no matching pages")
            return raw
        import http.client

        conn = http.client.HTTPConnection(host, port, timeout=self.timeout_s)
        try:
            body = {"ids": [int(t) for t in ids]}
            if have:
                body["have"] = [format(int(h), "x") for h in have]
            conn.request(
                "POST", "/v1/kv_fetch", body=json.dumps(body),
                headers={"Content-Type": "application/json",
                         "Connection": "close"},
            )
            resp = conn.getresponse()
            raw = resp.read()
            if resp.status != 200:
                raise OSError(f"/v1/kv_fetch returned {resp.status}")
        finally:
            conn.close()
        return raw

    def _skip_base(self, ids, covered, entry):
        """(start, base_entry STILL PINNED or None, have_keys) — the
        content-addressed skip claim from a `match_pinned` result
        (server/disagg.DisaggClient._skip_base, tier edition). Releases
        the pin itself (returning None) when nothing local is usable as
        a peer-fetch merge base."""
        engine = self.engine
        pc = engine.prefix_cache
        if entry is None:
            return 0, None, ()
        S = bucket_down(min(covered, entry.length), engine.cfg.seq_len)
        if engine.paged and engine.page_size and S % engine.page_size != 0:
            S = 0
        if S < KEY_PAGE_TOKENS or tuple(entry.tokens[:S]) != tuple(
            int(t) for t in ids[:S]
        ):
            pc.entry_release(entry)
            return 0, None, ()
        return S, entry, page_keys(ids[:S])

    def _fetch_peer(self, ids, P: int, have, S: int = 0):
        """Round-robin the fleet-cache peers for the longest bucket any
        of them holds. Returns (entry, B, r_start, peer_key, err,
        rejected_peer, rejected_err); entry None when every usable peer
        missed/failed. ``S`` is the have/skip claim: a peer may answer
        with ``start == S`` (ships only ``[S, B)`` — the caller merges
        onto its pinned base) or ``start == 0`` (full coverage); any
        other slicing is rejected as corrupt. Integrity semantics are
        the PR 16 contract verbatim: verify BEFORE anything is kept,
        corrupt peers take strikes, version skew skips without a
        strike."""
        usable = [p for p in self.peers if self._peer_usable(p)]
        err = None
        rejected_peer = None
        rejected_err = ""
        if not usable:
            return None, 0, 0, None, err, rejected_peer, rejected_err
        with self._lock:
            start = self._rr
            self._rr = (self._rr + 1) % len(usable)
        for i in range(len(usable)):
            peer = usable[(start + i) % len(usable)]
            host, port = peer
            try:
                raw = self._peer_fetch_raw(peer, ids[: P + 1], have)
                header, k, v = parse_kv_payload(raw)
                B = int(header.get("p", 0))
                if (
                    B < PREFIX_MIN_TOKENS
                    or B > P
                    or B != bucket_down(B, self.engine.cfg.seq_len)
                ):
                    raise KvCodecError(
                        f"peer names off-ladder boundary p={B}"
                    )
                r_start = int(header.get("start", 0))
                if r_start not in (0, S) or r_start >= B:
                    raise KvCodecError(
                        f"peer shipped start={r_start}, asked {S}"
                    )
                result = TransferResult(header, k, v, "http", len(raw))
                verify_transfer(result, ids, B)
                self._peer_ok(peer)
                self._incr("kv_integrity_verified")
                entry = _HostEntry(
                    tuple(int(t) for t in ids[:B]), k, v, header,
                    int(k.nbytes) + int(v.nbytes),
                )
                return (
                    entry, B, r_start, f"{host}:{port}", None,
                    rejected_peer, rejected_err,
                )
            except KvVersionError as e:
                err = e
                self._incr("kv_tier_peer_version_mismatch")
            except KvCodecError as e:
                err = e
                self._incr("kv_integrity_rejected")
                rejected_peer = f"{host}:{port}"
                rejected_err = f"{type(e).__name__}: {e}"
                self._peer_strike(peer)
            except Exception as e:
                err = e
                self._incr("kv_tier_peer_errors")
                self._peer_failed(peer)
        return None, 0, 0, None, err, rejected_peer, rejected_err

    # -- the admission-path fetch -------------------------------------------

    def fetch(self, ids: list, trace=None) -> dict:
        """Try to land ``ids``' leading bucket from a lower tier ahead of
        admission. Returns ``{promotion_us, tier_path, promoted_tokens,
        pending_kv}`` — ``pending_kv`` (a :class:`PendingPromotion`) is
        the deferred device insert the engine thread applies, exactly
        like the disagg client's pending. Zeros whenever the request
        proceeds on plain local prefill. Never raises."""
        out = {
            "promotion_us": 0, "tier_path": "", "promoted_tokens": 0,
            "pending_kv": None,
        }
        engine = self.engine
        pc = engine.prefix_cache
        if pc is None or self._closed:
            return out
        P = _prefill_boundary(len(ids), engine.cfg.seq_len)
        if P <= 0:
            return out
        # ONE trie walk, the entry pinned under the match's own lock hold
        # — it doubles as the peer fetch's merge base, and pool pressure
        # must never recycle a base's pages between lookup and insert
        covered, matched = pc.match_pinned(ids[:P])
        if matched is not None and covered >= P:
            pc.entry_release(matched)
            self._incr("kv_tier_local_hits")
            return out
        S, base_entry, have = self._skip_base(ids, covered, matched)
        t0 = time.perf_counter()
        tier, entry = self._lookup_local(ids, P, promote_host=True)
        B = len(entry.tokens) if entry is not None else 0
        r_start = 0
        peer_key = None
        err = None
        rejected_peer = None
        rejected_err = ""
        if (entry is None or B <= S) and self.peers:
            p_entry, p_B, r_start, peer_key, err, rejected_peer, rejected_err = (
                self._fetch_peer(ids, P, have, S)
            )
            if p_entry is not None and p_B > max(B, S):
                tier, entry, B = "peer", p_entry, p_B
                if r_start == 0:
                    # a verified FULL peer fetch also lands in the host
                    # tier, so the next replica asking this one can be
                    # served and a re-eviction re-promotes without
                    # another network trip (partial sends can't: the
                    # host tier stores full coverage only)
                    self._host_put(p_entry)
                self._incr("kv_tier_peer_bytes", p_entry.nbytes)
            else:
                r_start = 0
        wall_us = int((time.perf_counter() - t0) * 1e6)
        from .tracing import to_us

        if rejected_peer is not None and trace is not None:
            # ONE event per fetch, outside the peer loop — landed even
            # unsampled and even when failover saved the request
            trace.event(
                "kv_integrity", to_us(t0), wall_us,
                ("peer", "outcome", "error"),
                (rejected_peer, "rejected", rejected_err),
                always=True,
            )
        if entry is None or B <= S:
            if base_entry is not None:
                pc.entry_release(base_entry)
            if tier is None and peer_key is None and err is None:
                self._incr("kv_tier_misses")
            elif err is not None:
                # a peer round trip failed or was rejected AND no lower
                # tier could cover: degrade to local prefill (token-
                # identical). Integrity rejections ledger as integrity
                # waste so a corrupting peer is visible in goodput.
                self._incr("kv_tier_degraded")
                if self.goodput is not None:
                    reason = (
                        "integrity"
                        if isinstance(err, KvCodecError)
                        and not isinstance(err, KvVersionError)
                        else "transfer_retry"
                    )
                    self.goodput.add_waste(reason, P)
                if trace is not None:
                    trace.event(
                        "kv_tier_fetch", to_us(t0), wall_us,
                        ("tier", "tokens", "failed", "error"),
                        (
                            "peer", P, 1,
                            f"{type(err).__name__}: {err}",
                        ),
                        always=True,
                    )
            return out
        if tier != "peer" or r_start == 0:
            # host/disk hits (and full peer sends) ship full coverage:
            # the base pin is no longer a merge base
            if base_entry is not None:
                pc.entry_release(base_entry)
            base_entry = None
            r_start = 0
        self._incr(f"kv_tier_hits_{tier}")
        self.engine.stats.record("promotion_us", wall_us)
        out["promotion_us"] = wall_us
        out["tier_path"] = tier
        out["promoted_tokens"] = B - r_start
        out["pending_kv"] = PendingPromotion(
            self, list(entry.tokens), entry.k, entry.v, tier,
            start=r_start, base_entry=base_entry,
        )
        if trace is not None:
            trace.event(
                "kv_tier_fetch", to_us(t0), wall_us,
                ("tier", "tokens", "failed", "peer"),
                (tier, B - r_start, 0, peer_key or ""),
            )
        return out

    # -- the serving side of tier 3 -----------------------------------------

    def serve_fetch(self, ids: list, have_keys=()) -> bytes | None:
        """Build the ``POST /v1/kv_fetch`` response: the longest held
        bucket covering a prefix of ``ids`` (host tier first, then a disk
        load — VERIFIED before serving), minus the leading pages the
        requester's ``have`` names prove it already holds. Host memory
        and disk only — zero device work, so ANY role can serve its
        tiers from a handler thread. None when nothing is held."""
        if self._closed:
            return None
        P = _prefill_boundary(len(ids), self.engine.cfg.seq_len)
        if P <= 0:
            return None
        tier, entry = self._lookup_local(ids, P, promote_host=False)
        if entry is None:
            return None
        B = len(entry.tokens)
        S = matching_pages(page_keys(entry.tokens), have_keys) * KEY_PAGE_TOKENS
        S = bucket_down(S, self.engine.cfg.seq_len) if S else 0
        if S >= B:
            S = 0  # the requester claims full coverage; ship everything anyway
        payload = _slice_payload(entry.tokens, entry.k, entry.v, S)
        if _serve_chaos:
            _serve_chaos.pop()
            # flip one byte INSIDE the k region (past the length-prefixed
            # header) the way bad hardware would — the checksums upstream
            # already cover it, so the requester's verify gate must reject
            buf = bytearray(payload)
            hdr_len = 4 + int.from_bytes(buf[:4], "big")
            if len(buf) > hdr_len:
                buf[hdr_len] ^= 0xFF
            payload = bytes(buf)
        self._incr("kv_tier_peer_served")
        self._incr("kv_tier_peer_served_bytes", len(payload))
        return payload

    # -- prefetch hints ------------------------------------------------------

    def note_chain(self, chain, ids) -> None:
        """Teach the hint index what token prefix each router chain key
        resolves to (called once per admitted request — the replica side
        of the ``X-DLT-Prefetch-Chain`` contract)."""
        if not chain:
            return
        P = _prefill_boundary(len(ids), self.engine.cfg.seq_len)
        if P <= 0:
            return
        # P+1 tokens, not P: the boundary is bucket_down(n-1), so replaying
        # the hint through the same math must land on the SAME bucket the
        # original request promoted
        prefix = tuple(int(t) for t in ids[: P + 1])
        with self._lock:
            for ck in chain:
                self._hints[int(ck)] = prefix
                self._hints.move_to_end(int(ck))
            while len(self._hints) > self._hints_cap:
                self._hints.popitem(last=False)

    def prefetch_hint(self, chain) -> None:
        """The gateway's ``X-DLT-Prefetch-Chain`` landed: start lifting
        the named prefix toward the host tier NOW, while the request body
        is still being parsed/tokenized. Deepest key first — the longest
        known prefix wins. Non-blocking; the hint is advisory."""
        if self._closed or not chain:
            return
        prefix = None
        with self._lock:
            for ck in reversed(list(chain)):
                prefix = self._hints.get(int(ck))
                if prefix is not None:
                    break
        if prefix is None:
            return
        self._incr("kv_tier_prefetch_hints")
        try:
            self._prefetch_q.put_nowait(prefix)
        except queue.Full:
            pass  # dlt: allow(swallowed-exception) — a dropped hint is just a slower first hit

    def _prefetch_loop(self):
        while True:
            try:
                prefix = self._prefetch_q.get(timeout=0.5)
            except queue.Empty:
                if self._closed:
                    return
                continue
            if prefix is None:
                return
            try:
                ids = list(prefix)
                P = _prefill_boundary(len(ids), self.engine.cfg.seq_len)
                if P <= 0:
                    continue
                if self._host_get(tuple(ids[:P])) is not None:
                    continue  # already tier 1: the admission fetch will hit
                tier, entry = self._lookup_local(
                    ids, P, promote_host=True
                )
                if entry is None and self.peers:
                    entry, B, _rs, _pk, _err, _rp, _re = self._fetch_peer(
                        ids, P, ()
                    )
                    if entry is not None:
                        self._host_put(entry)
                if entry is not None:
                    self._incr("kv_tier_prefetched")
            except Exception:  # dlt: allow(swallowed-exception) — counted at the tiers; a failed prefetch is a slower hit, never an error
                self._incr("kv_tier_peer_errors")

    # -- observability -------------------------------------------------------

    def snapshot(self) -> dict:
        now = time.monotonic()
        with self._lock:
            backing_off = [
                f"{h}:{p}" for (h, p), t in self._backoff_until.items()
                if t > now
            ]
            peer_strikes = {
                f"{h}:{p}": c
                for (h, p), (c, ttl) in self._strikes.items() if ttl > now
            }
            struck_out = [
                f"{h}:{p}"
                for (h, p), (c, ttl) in self._strikes.items()
                if ttl > now and c >= self.integrity_strikes
            ]
            return {
                "host": {
                    "entries": len(self._host),
                    "bytes": self._host_bytes,
                    "budget_bytes": self.host_budget,
                },
                "disk": {
                    "entries": len(self._disk),
                    "bytes": self._disk_bytes,
                    "budget_bytes": self.disk_budget,
                    "dir": self.disk_dir,
                },
                "peers": [f"{h}:{p}" for h, p in self.peers],
                "peers_backing_off": backing_off,
                "hints_tracked": len(self._hints),
                "integrity": {
                    "strikes_limit": self.integrity_strikes,
                    "strike_ttl_s": self.strike_ttl_s,
                    "peer_strikes": peer_strikes,
                    "peers_struck_out": struck_out,
                },
            }

    def memory_snapshot(self) -> dict:
        """The hbm_ledger's host-tier section: host RAM held by tier 1
        (NOT an HBM component — it reconciles against process RSS, not
        device memory_stats)."""
        with self._lock:
            return {
                "host_bytes": self._host_bytes,
                "host_budget_bytes": self.host_budget,
                "disk_bytes": self._disk_bytes,
                "disk_budget_bytes": self.disk_budget,
            }
