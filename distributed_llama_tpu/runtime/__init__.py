"""Runtime: the inference engine driving jit-compiled forward steps.

Replaces the reference's executor/thread-pool/socket runtime (reference:
src/nn/nn-executor.cpp, src/app.cpp): XLA replaces the step list and thread
pool, buffer donation replaces pipe memory management, and the host-side
engine here only orchestrates prefill chunking, sampling, and timing.
"""

from .engine import GenerationResult, InferenceEngine, StepTiming
from .speculative import DraftSource, ModelDraft, NGramDraft

__all__ = [
    "InferenceEngine",
    "GenerationResult",
    "StepTiming",
    "DraftSource",
    "NGramDraft",
    "ModelDraft",
]
