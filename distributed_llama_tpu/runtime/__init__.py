"""Runtime: the inference engine driving jit-compiled forward steps.

Replaces the reference's executor/thread-pool/socket runtime (reference:
src/nn/nn-executor.cpp, src/app.cpp): XLA replaces the step list and thread
pool, buffer donation replaces pipe memory management, and the host-side
engine here only orchestrates prefill chunking, sampling, and timing.

The engine exports are LAZY (module ``__getattr__``): importing a jax-free
sibling — ``runtime.tracing``, which the gateway shares for trace IDs and
Prometheus exposition — must not drag jax into a process that never
dispatches device work.
"""

from __future__ import annotations

import importlib

_EXPORTS = {
    "InferenceEngine": ".engine",
    "GenerationResult": ".engine",
    "StepTiming": ".engine",
    "DraftSource": ".speculative",
    "NGramDraft": ".speculative",
    "ModelDraft": ".speculative",
    "PagePool": ".paged_kv",
    "PagePoolExhausted": ".paged_kv",
}

__all__ = list(_EXPORTS)


def __getattr__(name: str):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    return getattr(importlib.import_module(mod, __name__), name)
