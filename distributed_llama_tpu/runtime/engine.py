"""The inference engine: model loading, prefill/decode orchestration, timing.

Host-side equivalent of the reference's `RootLlmInference` + `inference()`
driver (reference: src/app.cpp:223-303, src/dllama.cpp:13-151), minus
everything XLA now owns (thread pool, step list, collectives).

TPU-specific design:
* the forward step is jit-compiled once per (batch, chunk) shape; prompt
  chunks are padded to power-of-two buckets so the number of compiled
  programs is O(log max_chunk), not O(prompt length);
* the KV cache is donated through every step — it lives in HBM and is
  updated in place, never shipped to the host;
* cross-request KV reuse rides the radix prefix cache (prefix_cache.py):
  admissions splice cached shared-prompt KV and resume prefill at a
  chunk-bucket boundary, bit-identical to the cold path;
* sampling runs on the host over the final logits row (f32), byte-matching
  the reference Sampler's numerics (tokenizer.py); a device-side argmax fast
  path covers the temperature=0 benchmark case.
* padded tail positions write garbage into cache slots past the true length;
  those slots are either masked (attention masks t <= pos) or overwritten by
  the next real token before they are ever visible — same invariant the
  reference maintains by only advancing `pos` over real tokens.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from ..formats.mfile import MFileReader
from ..models import KVCache, config_from_header, forward, init_kv_cache, load_params
from ..ops import build_rope_tables
from ..tokenizer import Sampler
from .telemetry import StepStats, memory_report, watchdog
from .tracing import to_us


@dataclass
class StepTiming:
    """Per-step wall time over `n_tokens` tokens (analogue of the
    reference's Eval/Pred ms columns, reference dllama.cpp:76-83,111-118).
    There is deliberately no Sync column: under XLA, compute and collectives
    fuse into one device program and cannot be told apart from the host —
    printing a split would be fabricating a measurement. One StepTiming
    covers one real host-observable unit (a prefill chunk, a decode chunk,
    or one host-loop decode step) — per-token numbers are only reported
    where a token is actually a measurement boundary."""

    eval_us: int = 0
    n_tokens: int = 0


@dataclass
class GenerationResult:
    tokens: list[int] = field(default_factory=list)
    n_prompt_tokens: int = 0
    prefill_us: int = 0
    ttft_us: int = 0
    decode_us: int = 0
    total_us: int = 0
    eval_steps: list[StepTiming] = field(default_factory=list)
    pred_steps: list[StepTiming] = field(default_factory=list)

    @property
    def n_pred_tokens(self) -> int:
        return len(self.tokens) - self.n_prompt_tokens

    @property
    def eval_tok_per_s(self) -> float:
        us = sum(s.eval_us for s in self.eval_steps) or 1
        n = sum(s.n_tokens for s in self.eval_steps)
        return n * 1e6 / us

    @property
    def pred_tok_per_s(self) -> float:
        us = sum(s.eval_us for s in self.pred_steps) or 1
        n = sum(s.n_tokens for s in self.pred_steps)
        return n * 1e6 / us


def maybe_enable_compilation_cache():
    """Enable JAX's persistent compilation cache when `DLT_COMPILE_CACHE`
    names a directory. First compiles of the big prefill graphs cost
    anywhere from ~30 s to many minutes depending on the backend's day; the
    cache makes them one-time per machine instead of per process (verified
    working through the axon tunnel: cross-process recompile 3.1 s -> 1.5 s
    on a probe graph). Opt-in via env so library users keep JAX's defaults."""
    path = os.environ.get("DLT_COMPILE_CACHE")
    if not path:
        return
    try:
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # dlt: allow(swallowed-exception) — older jax without the knobs; the cache is an optimization only
        pass


def _next_subkey(key, temperature: float):
    """(key, subkey) for one decode chunk. Greedy chunks never draw, so the
    per-chunk split — a device op, i.e. a tunnel round trip — is skipped."""
    if temperature == 0.0:
        return key, key
    return jax.random.split(key)


def _greedy_prng_key() -> jax.Array:
    """The throwaway key greedy chunks carry (they never draw). TYPED
    threefry key — the same aval `_sampler_prng_key` produces — so greedy
    warmup and sampled serving dispatch ONE compiled decode program per
    (n, kv-bucket): a legacy `PRNGKey(0)` operand here gave the sampled
    path a different key dtype and a post-warmup recompile (the recorded
    /v1/chat fatal-sanitizer hole)."""
    return jax.random.wrap_key_data(
        jnp.zeros((2,), dtype=jnp.uint32), impl="threefry2x32"
    )


def _sampler_prng_key(sampler) -> jax.Array:
    """Device PRNG key derived from the host sampler's xorshift* state.

    The state is an unsigned 64-bit value (seed 0 maps to the golden-ratio
    constant 0x9E3779B97F4A7C15 > 2^63-1, tokenizer.py Sampler.set_seed), so
    it must be split into 32-bit halves — `PRNGKey(int(state))` overflows
    int64 for half the state space."""
    state = getattr(sampler, "_state", None)
    if state is None:
        return _greedy_prng_key()
    s = int(state)
    return jax.random.wrap_key_data(
        jnp.asarray([(s >> 32) & 0xFFFFFFFF, s & 0xFFFFFFFF], dtype=jnp.uint32),
        impl="threefry2x32",
    )


def _chunk_buckets(max_chunk: int) -> list[int]:
    out = [1]
    while out[-1] < max_chunk:
        out.append(min(out[-1] * 2, max_chunk))
    return out


def chunk_plan(n_tokens: int, pos_start: int, max_chunk: int, seq_len: int):
    """The padded power-of-two prefill ladder — the ONE owner of the chunk
    arithmetic shared by `prefill`, `generate_batch`, and
    `BatchSession.admit`: yields (offset, size, n_real) triples covering
    `n_tokens` tokens whose first absolute position is `pos_start`. `size`
    is the padded bucket (keeps compiled programs O(log max_chunk)); the
    last chunk's tail past `n_real` is padding. Raises when a chunk would
    write past seq_len (dynamic_update_slice would CLAMP the start and
    silently overwrite earlier positions' KV — real corruption, not junk)."""
    buckets = _chunk_buckets(max_chunk)
    i = 0
    while i < n_tokens:
        remaining = n_tokens - i
        size = next(b for b in buckets if b >= min(remaining, max_chunk))
        size = min(size, seq_len - (pos_start + i))
        if size <= 0:
            raise ValueError(
                f"prefill would write past seq_len ({seq_len}): "
                f"{n_tokens} tokens starting at position {pos_start}"
            )
        n_real = min(size, remaining)
        yield i, size, n_real
        i += n_real


class InferenceEngine:
    """Owns params + cache + compiled steps for one model."""

    def __init__(
        self,
        model_path: str,
        compute_dtype: str = "bfloat16",
        max_seq_len: int = 0,
        batch: int = 1,
        max_chunk: int = 32,
        mesh=None,
        cache_dtype: str | None = None,
        device_decode: bool = True,
        decode_chunk_size: int = 64,  # 64 amortizes the ~70 ms host
        # dispatch round trip below 1.1 ms/token without hurting stop-token
        # overrun much (measured: chunk 32 -> 3.3 ms/tok, 64 -> 2.7)
        verbose: bool = False,
        q80_activations: bool = False,
        execution: str = "auto",
        prefill_pipelined: bool | None = None,  # None = env default (on);
        # False = strict serial dispatch->block->dispatch chunks (the
        # bit-parity reference path for the overlap smoke test)
        prefix_cache_mb: int | None = None,  # HBM budget for the radix
        # prefix cache (runtime/prefix_cache.py): cross-request KV reuse for
        # shared prompts. None = DLT_PREFIX_CACHE_MB env (default 0 = off
        # for library engines; the API server defaults it on — server/api.py)
        speculative: str | None = None,  # "off" | "ngram" | "model" draft
        # source for greedy speculative decode (runtime/speculative.py).
        # None = DLT_SPECULATIVE env (default off for library engines; the
        # CLI/server entry points default ngram — cli.make_engine)
        draft_k: int | None = None,  # max drafted tokens per verify round
        # (bucketed at {4, 8}). None = DLT_DRAFT_K env, default 4
        draft_source=None,  # DraftSource override; REQUIRED for "model"
        # (a speculative.ModelDraft wrapping the smaller draft engine)
        grammar: bool | None = None,  # build the grammar mask-table arena
        # (runtime/grammar.py) so /v1/chat response_format constrained
        # decoding runs as a traced operand pair on the ordinary warm
        # programs. None = DLT_GRAMMAR env (default off for library
        # engines; the server entry point defaults it on). Single-chip
        # device-decode only — mesh/host-decode engines warn-fallback to
        # unconstrained, like the int8-KV topology gate
        kv_layout: str | None = None,  # "contiguous" (per-row seq_len KV
        # slabs — the reference shape and the bit-identity A/B arm) or
        # "paged" (fixed-size KV pages + per-row page tables, zero-copy
        # prefix sharing, copy-on-write; runtime/paged_kv.py). None =
        # DLT_KV_LAYOUT env, default contiguous for library engines (the
        # CLI/server entry points default paged — cli.make_engine). Paged
        # runs single-chip AND on pure pp x tp shard_map pipeline meshes
        # (the reference's PPxTP topology): the pool buffer shards like
        # the contiguous cache (layers over pp, kv heads over tp) and the
        # page tables stay replicated host-side. dp/sp/ep extents keep
        # the contiguous layout (sp shards the seq axis paging replaces;
        # dp/ep paging is a follow-on).
        kv_page_size: int | None = None,  # tokens per KV page (power of
        # two). None = DLT_KV_PAGE env, default 16 — aligned with the
        # prefix cache's bucket floor so hits share whole pages
        kv_pool_mb: int | None = None,  # paged-pool HBM budget. None =
        # DLT_KV_POOL_MB env; 0/unset = contiguous parity (batch x seq_len
        # worth of pages), so default paged never fits fewer tokens
    ):
        maybe_enable_compilation_cache()
        self.reader = MFileReader(model_path, max_seq_len=max_seq_len)
        self.header = self.reader.header
        # KV storage dtype knob (--kv-dtype / DLT_KV_DTYPE): "int8" turns on
        # the quantized KV cache (ops/kv_quant.py — int8 payload + f32
        # per-(token, kv-head) scale sidecars). None keeps the compute-dtype
        # default; bf16/f32 caches stay byte-identical to pre-quantization.
        from .paged_kv import resolve_kv_dtype

        cache_dtype = resolve_kv_dtype(cache_dtype)
        if cache_dtype == "int8" and mesh is not None:
            # int8 KV is single-chip for now: the pipeline scan carries and
            # the GSPMD cache shardings don't thread the scale sidecars.
            # Fall back to the float default rather than fail — the knob is
            # a perf hint, not a topology contract (docs/SERVING.md).
            import warnings

            warnings.warn(
                "kv_dtype='int8' is single-chip only; mesh engine falls "
                "back to the default float KV cache",
                stacklevel=2,
            )
            cache_dtype = None
        self.cfg = config_from_header(
            self.header, compute_dtype=compute_dtype, cache_dtype=cache_dtype
        )
        if q80_activations:
            self.cfg = self.cfg.with_(q80_activations=True)
        self.mesh = mesh
        shardings = None
        self._cache_sharding = None
        # execution path for meshes: "pipeline" = explicit shard_map
        # (ppermute stage handoff, psum TP reduce; Pallas kernels see local
        # shards and stay enabled), "gspmd" = sharded jit with XLA-inserted
        # collectives (pp/sp/ep extents unsupported, and the Pallas fused
        # kernel is disabled — GSPMD cannot partition an opaque pallas_call).
        # "auto" picks pipeline for ANY model-parallel axis — including
        # tp-only meshes, precisely to keep the fused Q40 kernel in the
        # flagship TP configs — and gspmd only for dp-only meshes.
        needs_pipeline = mesh is not None and (
            mesh.shape["pp"] > 1
            or mesh.shape["sp"] > 1
            or mesh.shape.get("ep", 1) > 1
        )
        if execution not in ("auto", "gspmd", "pipeline"):
            raise ValueError(f"unknown execution mode {execution!r}")
        if execution == "gspmd" and needs_pipeline:
            raise ValueError("pp/sp/ep mesh axes require the pipeline path")
        if execution == "pipeline" and mesh is None:
            raise ValueError("execution='pipeline' requires a mesh")
        self.use_pipeline = mesh is not None and (
            needs_pipeline
            or execution == "pipeline"
            or (execution == "auto" and mesh.shape["tp"] > 1)
        )
        if mesh is not None and batch % mesh.shape["dp"] != 0:
            raise ValueError(
                f"batch ({batch}) must divide over the dp mesh axis "
                f"({mesh.shape['dp']})"
            )
        if self.use_pipeline:
            from ..parallel.pipeline import pp_cache_sharding, pp_param_shardings

            # shard_map kernels see local shards — the pallas path stays
            # available
            shardings = pp_param_shardings(mesh, moe=self.cfg.is_moe)
            self._cache_sharding = pp_cache_sharding(mesh)
        elif mesh is not None:
            from ..parallel import cache_shardings, param_shardings

            # GSPMD cannot partition a pallas_call over sharded operands —
            # force the XLA dequant path (ModelConfig.use_pallas docstring)
            self.cfg = self.cfg.with_(use_pallas=False)
            shardings = param_shardings(mesh, moe=self.cfg.is_moe)
            self._cache_sharding = cache_shardings(mesh)
        # fused-projection interleaving (load_params tp=) is a SHARD_MAP
        # concept: each shard must see its own q|k|v slices locally. Under
        # GSPMD the forward computes global math over the global arrays —
        # the fused axis must stay in plain concat order (tp=1) and XLA
        # partitions the matmul + split itself.
        self.params = load_params(
            self.reader, self.cfg, shardings=shardings,
            tp=mesh.shape["tp"] if self.use_pipeline else 1,
        )
        self.rope = build_rope_tables(self.header)
        self.batch = batch
        self.max_chunk = max(1, min(max_chunk, self.cfg.seq_len))
        # device_decode: run the decode loop on device in chunks (fast path);
        # False = per-token host loop with the reference's exact RNG stream.
        self.device_decode = device_decode
        self.decode_chunk_size = decode_chunk_size
        self.stats = StepStats()
        # KV layout (runtime/paged_kv.py): paged replaces the per-row
        # contiguous slabs with a page pool + per-row page tables. The
        # contiguous arm stays byte-for-byte what it was — it is the
        # bit-identity A/B reference for the paged programs.
        from .paged_kv import (
            PagePool,
            page_pool_bytes,
            resolve_kv_layout,
            resolve_page_size,
            resolve_pool_pages,
        )

        self.kv_layout = resolve_kv_layout(kv_layout)
        self.paged = self.kv_layout == "paged"
        self.page_size = resolve_page_size(kv_page_size) if self.paged else None
        self.page_pool = None
        self._pt_cache = None  # (pool.version, device tables) — the cached
        # page-table operand; invalidated by any pool mutation
        if self.paged:
            if mesh is not None and (
                not self.use_pipeline
                or mesh.shape["dp"] > 1
                or mesh.shape["sp"] > 1
                or mesh.shape.get("ep", 1) > 1
            ):
                raise ValueError(
                    "kv_layout='paged' on meshes requires the pure pp x tp "
                    "shard_map pipeline path (dp=sp=ep=1); other topologies "
                    "keep the contiguous layout"
                )
            if mesh is not None:
                # mesh-paged: the pool buffer rides the pipeline cache
                # shardings (layers over pp, kv heads over tp); the page
                # axis is replicated — page ids are global, so the host-side
                # pool/tables need no mesh awareness at all
                from ..parallel.pipeline import pp_paged_pool_sharding

                self._cache_sharding = pp_paged_pool_sharding(mesh)
            ps = self.page_size
            max_slots = -(-self.cfg.seq_len // ps)
            parity = self.batch * max_slots
            n_pages = resolve_pool_pages(
                kv_pool_mb, page_pool_bytes(self.cfg, 1, ps), parity
            )
            self.page_pool = PagePool(
                n_pages, ps, self.batch, self.cfg.seq_len, stats=self.stats,
                reclaim=self._reclaim_pages,
                page_bytes=page_pool_bytes(self.cfg, 1, ps),
                kv_dtype=self.cfg.cache_dtype,
            )
        self.cache = self._new_cache()
        if verbose:
            print(memory_report(self.params, self.cache))
        self._argmax_step = jax.jit(
            lambda logits: jnp.argmax(logits, axis=-1).astype(jnp.int32)
        )
        # one worker for the decode loop's token fetches and the prefill
        # pipeline's input prep (each overlaps a dispatch round trip on the
        # main thread — see _decode_device and prefill)
        self._fetch_pool = ThreadPoolExecutor(max_workers=1)
        if prefill_pipelined is None:
            prefill_pipelined = os.environ.get("DLT_PREFILL_PIPELINE", "1") != "0"
        self.prefill_pipelined = prefill_pipelined
        # dispatch-vs-compute overlap summary of the most recent prefill
        # (bench.py reads it; /stats exports the gauge twin)
        self.last_prefill_timing: dict | None = None
        # per-request tracing context (runtime/tracing.py Trace), set by the
        # serving layer around a request (the serialized API path; the
        # Batcher threads per-row traces through BatchSession instead).
        # None = untraced: every emission site guards on it, so library and
        # bench callers pay nothing.
        self.trace = None
        # shape keys this engine has executed at least once: a first-shape
        # call legitimately blocks on XLA compilation, so its watchdog runs
        # with the (much wider) compile threshold and a "compile" label
        # instead of crying EXEC_STALL (the BENCH_r04 false alarm)
        self._warm: set = set()
        # radix prefix cache: cross-request KV reuse over shared prompt
        # prefixes (None = disabled). Warmup suppresses it (_in_warmup) so
        # the ladder sweep's synthetic prompts neither publish junk entries
        # nor match each other.
        from .prefix_cache import PrefixCache

        self.prefix_cache = PrefixCache.build(self, prefix_cache_mb)
        self.last_prefix_hit_tokens = 0  # tokens the most recent prefill
        # skipped via a prefix-cache splice (0 = cold; /stats gauge twin)
        # speculative decoding (runtime/speculative.py): greedy requests
        # draft k tokens and verify them in ONE prefill-shaped forward; the
        # verify programs ride the warm ladder at (k+1, kv-bucket) keys
        from .speculative import (
            build_draft_source,
            resolve_draft_k,
            resolve_spec_mode,
            spec_buckets,
        )

        self.spec_mode = resolve_spec_mode(speculative, default="off")
        self.draft_k = resolve_draft_k(draft_k)
        self.spec_buckets = spec_buckets(self.draft_k) if self.spec_mode else ()
        self.draft_source = build_draft_source(self.spec_mode, draft_source)
        # draft/verify/acceptance summary of the most recent speculative
        # generate (bench.py reads it; mirrors last_prefill_timing)
        self.last_spec_timing: dict | None = None
        # grammar-constrained decoding (runtime/grammar.py): ONE device
        # mask-table arena serves every live grammar as a traced
        # (table, state) operand pair on the ordinary warm programs —
        # installing a grammar bumps arena.version (a re-upload), never
        # re-traces. Single-chip device-decode only for now: the pipeline
        # programs and the per-token host loop don't thread the operands,
        # so other topologies warn-fallback (a capability hint, not a
        # topology contract — same shape as the int8-KV gate above).
        from .grammar import GrammarArena, resolve_grammar_enabled

        self.grammar = None
        self._gr_cache = None  # (arena.version, device table) — the cached
        # grammar mask-table operand; invalidated by any arena mutation
        if resolve_grammar_enabled(grammar):
            if mesh is not None or not device_decode:
                import warnings

                warnings.warn(
                    "grammar-constrained decoding is single-chip "
                    "device-decode only; this engine serves unconstrained",
                    stacklevel=2,
                )
            else:
                self.grammar = GrammarArena(self.cfg.vocab_size)
        self._in_warmup = False
        # engine lifetime anchor: the device-duty-cycle gauge (profiling
        # .roofline_view) reports busy-time as a fraction of this span
        self._t_start = time.perf_counter()
        # warm-ladder cost table (runtime/profiling.py): per-program
        # FLOP/byte analysis built from the SAME warm_plan() — None until
        # warmup builds it (DLT_COST_TABLE=1), the server's post-warmup
        # build runs, or a cold endpoint (/debug/costs) asks for it
        self._cost_table = None
        # serializes the lazy cost-table build: concurrent /debug/costs
        # handler threads must not both pay the full-ladder AOT compile
        self._cost_table_lock = threading.Lock()
        # opt-in runtime sanitizers (DLT_SANITIZERS=1, docs/ANALYSIS.md):
        # the recompile sentinel counts XLA compiles and, once warmup()
        # seals it, flags any post-warmup recompile (a warm-key-ladder
        # hole) through StepStats counters; the host-sync guard wraps the
        # decode/prefill hot loops so implicit device->host transfers
        # outside the sanctioned _fetch_pool/_host_fetch sites raise.
        from ..analysis import sanitizers_enabled

        self._sanitize = sanitizers_enabled()
        self.sentinel = None
        if self._sanitize:
            from ..analysis.recompile_sentinel import RecompileSentinel

            self.sentinel = RecompileSentinel(stats=self.stats).start()

    def close(self):
        self._fetch_pool.shutdown(wait=False)
        if self.draft_source is not None:
            self.draft_source.close()
        if self.sentinel is not None:
            self.sentinel.stop()

    def __del__(self):
        try:
            self.close()
        except Exception:  # dlt: allow(swallowed-exception) — interpreter-teardown destructor; nothing to report to
            pass

    def _sanitizer_scope(self):
        """Transfer-guard scope for a hot loop (no-op unless
        DLT_SANITIZERS=1): implicit device->host transfers on THIS thread
        raise; the worker-thread fetches stay sanctioned by construction
        (the guard is thread-local)."""
        if self._sanitize:
            from ..analysis.host_sync_guard import host_sync_guard

            return host_sync_guard(self.stats)
        return contextlib.nullcontext()

    def _host_fetch(self, x) -> np.ndarray:
        """THE sanctioned blocking device->host fetch: `np.asarray` under
        the sanitizer's allow-scope, counted in /stats
        (`sanitizer_d2h_sanctioned`). Every hot-loop token fetch routes
        through here; any OTHER same-thread transfer inside a guarded loop
        is a host-sync violation."""
        if self._sanitize:
            from ..analysis.host_sync_guard import sanctioned_fetch

            with sanctioned_fetch(self.stats):
                return np.asarray(x)  # dlt: allow(host-sync) — the one blessed fetch site
        return np.asarray(x)  # dlt: allow(host-sync) — the one blessed fetch site

    # -- low-level steps ----------------------------------------------------

    def _kv_bucket(self, end_pos: int) -> int | None:
        """Static KV read bound: smallest power-of-two bucket covering
        `end_pos` (floored so tiny contexts don't multiply compiled
        programs). Attention then reads cache[:, :bucket] instead of the
        whole allocation — decode cost scales with position, not seq_len —
        at the price of O(log seq_len) compiled step variants."""
        floor = min(256, self.cfg.seq_len)
        b = floor
        while b < end_pos:
            b *= 2
        return min(b, self.cfg.seq_len)

    def _kv_buckets(self) -> list:
        """Every static KV read bound `_kv_bucket` can return: the floor
        bucket doubling up to seq_len."""
        out = [min(256, self.cfg.seq_len)]
        while out[-1] < self.cfg.seq_len:
            out.append(min(out[-1] * 2, self.cfg.seq_len))
        return out

    @staticmethod
    def _halving_sizes(top: int) -> list:
        """The sizes a dispatch shrink loop (`n //= 2` until it fits) can
        actually produce from `top`, ascending."""
        out = set()
        n = max(1, top)
        while n >= 1:
            out.add(n)
            n //= 2
        return sorted(out)

    def warm_plan(self) -> list:
        """THE warm-key ladder: every (kind, size, kv-bucket) program this
        engine may dispatch while serving, as `warmup()` compiles it and the
        graph auditor audits it (analysis/graph_audit.py delegates here —
        single ownership is what keeps the recompile sentinel's zero-post-
        warmup-compile contract honest).

        The ladder is the full cross product of chunk/decode sizes with the
        reachable kv buckets — not just the canonical warmup request's
        schedule — because real traffic reaches every combination: a prompt
        whose tail chunk lands in a deep bucket (the recorded 52-token-
        prompt repro: a max_chunk-sized chunk the canonical n-1-token
        warmup prompt never produced), a long conversation whose decode
        crosses bucket boundaries, a prefix-cache resume that starts
        mid-ladder. A (size, kvb) pair is reachable iff size <= kvb (the
        bucket must cover the chunk's own end). Prefix-cache copy/extract
        programs ride the same ladder at (bucket, bucket)."""
        plan = []
        kvbs = self._kv_buckets()
        prefill_sizes = _chunk_buckets(self.max_chunk)
        decode_sizes = sorted(
            set(
                self._halving_sizes(self.decode_chunk_size)
                + self._halving_sizes(min(8, self.decode_chunk_size))
            )
        )
        for kvb in kvbs:
            for s in prefill_sizes:
                if s <= kvb:
                    plan.append(("prefill", s, kvb))
            for n in decode_sizes:
                if n <= kvb:
                    plan.append(("decode", n, kvb))
        if self.batch > 1 and self.device_decode:
            for kvb in kvbs:
                for s in prefill_sizes:
                    if s <= kvb:
                        plan.append(("prefill_row", s, kvb))
                for n in decode_sizes:
                    if n <= kvb:
                        plan.append(("batch_decode", n, kvb))
        if self.spec_mode is not None and self.device_decode:
            # speculative verify programs: one prefill-shaped logits-at-
            # every-position forward per (draft bucket + 1, kv bucket) —
            # "verify" at scalar pos (solo generate: rows aligned),
            # "verify_row" at per-row positions (generate_batch /
            # BatchSession.spec_step), gated like the other per-row kinds
            for kvb in kvbs:
                for k in self.spec_buckets:
                    if k + 1 <= kvb:
                        plan.append(("verify", k + 1, kvb))
                        if self.batch > 1:
                            plan.append(("verify_row", k + 1, kvb))
        if self.prefix_cache is not None and not self.paged:
            for P in self.prefix_cache.buckets:
                # extract first: its (correctly sharded) outputs are the
                # operands the copy warms compile against, exactly like the
                # runtime publish -> splice flow
                plan.append(("prefix_extract", P, P))
                plan.append(("prefix_copy", P, P))
                if self.batch > 1 and self.device_decode:
                    plan.append(("prefix_copy_row", P, P))
        if self.paged:
            # the paged prefix cache shares pages host-side (zero copy
            # programs); its ONE device program is the copy-on-write page
            # copy. Keyed (page_copy, page_size, page_size): the page count
            # in the gather programs above is kv-bucket/page_size, so the
            # (kind, size, kv-bucket) triples already pin the paged shapes.
            plan.append(("page_copy", self.page_size, self.page_size))
            if self.prefix_cache is not None:
                # the KV movement layer's page-shipping programs
                # (runtime/kv_transport.py): gather pool pages into one
                # contiguous slice (the paged /v1/prefill extract) and
                # scatter a shipped slice into freshly allocated pages (the
                # paged external insert). One pair per prefix bucket —
                # doubling segments keep every runtime span on this ladder.
                for P in self.prefix_cache.buckets:
                    if P >= self.page_size:
                        plan.append(("page_extract", P, P))
                        plan.append(("page_insert", P, P))
        return plan

    def cost_table(self, build: bool = True):
        """The warm-ladder cost table (runtime/profiling.py CostTable), or
        None. ``build=True`` constructs the FULL-ladder table on first use
        (AOT lower+compile of every warm_plan program — compile work, no
        execution; a bench-built partial table is upgraded). The build runs
        inside the sentinel's THREAD-scoped `exempt()` window: this
        thread's compiles are sanctioned reconfiguration, never
        post-warmup-recompile breaches, while concurrent serving threads
        keep full breach detection — so a DLT_SANITIZERS_FATAL=1 server
        can serve /debug/costs lazily without a process-wide blind spot."""
        if build and (self._cost_table is None or self._cost_table.partial):
            import contextlib

            from .profiling import build_cost_table

            with self._cost_table_lock:
                if self._cost_table is None or self._cost_table.partial:
                    ctx = (
                        self.sentinel.exempt()
                        if self.sentinel is not None
                        else contextlib.nullcontext()
                    )
                    with ctx:
                        table = build_cost_table(self)
                    self._cost_table = table
        return self._cost_table

    def _forward(self, tokens_arr, pos_start, logits_mode="last", kv_len=None):
        """Dispatch one forward step to the GSPMD jit or the shard_map
        pipeline depending on the mesh shape."""
        if self.use_pipeline:
            from ..parallel.pipeline import pipeline_forward

            # GPipe microbatching: prefill chunks split into pp microbatches
            # so all stages stay busy (the reference's prefill chunking,
            # src/app.cpp:156-184); decode (t=1) necessarily runs 1
            pp = self.mesh.shape["pp"]
            t = tokens_arr.shape[-1]
            micro = pp if t % pp == 0 else 1
            return pipeline_forward(
                self.cfg, self.mesh, self.params, self.rope, self.cache,
                tokens_arr, pos_start, logits_mode=logits_mode,
                microbatches=micro, kv_len=kv_len,
                page_table=self._pt_operand() if self.paged else None,
                page_size=self.page_size,
            )
        if self.paged:
            return forward(
                self.cfg, self.params, self.rope, self.cache, tokens_arr,
                pos_start, logits_mode=logits_mode, kv_len=kv_len,
                page_table=self._pt_operand(), page_size=self.page_size,
            )
        return forward(
            self.cfg, self.params, self.rope, self.cache, tokens_arr,
            pos_start, logits_mode=logits_mode, kv_len=kv_len,
        )

    def _new_cache(self):
        if self.paged:
            from .paged_kv import init_kv_pool

            pool = init_kv_pool(self.cfg, self.page_pool.n_pages, self.page_size)
            if self._cache_sharding is not None:
                # int8 is single-chip (ctor gate), so mesh pools never carry
                # scale sidecars — sharding only the payload is exhaustive
                pool = KVCache(
                    k=jax.device_put(pool.k, self._cache_sharding),
                    v=jax.device_put(pool.v, self._cache_sharding),
                )
            return pool
        cache = init_kv_cache(self.cfg, self.batch)
        if self._cache_sharding is not None:
            import jax as _jax

            cache = KVCache(
                k=_jax.device_put(cache.k, self._cache_sharding),
                v=_jax.device_put(cache.v, self._cache_sharding),
            )
        return cache

    def reset(self):
        """Fresh independent sequence: contiguous zeros the cache; paged
        releases every row's page mappings IN PLACE (the pool arrays must
        survive — prefix-cache entries hold page indices into them; their
        pinned pages keep their refcounts and the next request's writes
        land in freshly allocated pages — write-before-read, as ever)."""
        if self.paged:
            self.page_pool.release_all_rows()
            self._pt_cache = None
            try:
                dead = self.cache.k.is_deleted()
            except Exception:  # dlt: allow(swallowed-exception) — treat an unreadable buffer as dead and rebuild
                dead = True
            if dead:
                # a failed dispatch donated the pool and died before
                # producing the output: the old buffer is gone. Rebuild —
                # recover() cleared the prefix cache (its page CONTENT
                # lived in the dead pool), so no entry can splice stale ids.
                self.cache = self._new_cache()
            return
        self.cache = self._new_cache()

    # -- paged-KV plumbing (runtime/paged_kv.py) -----------------------------

    def _reclaim_pages(self) -> bool:
        """Page-pool pressure valve: evict one LRU unpinned prefix-cache
        entry (releasing its page refs) so the allocation can retry. False
        = nothing to evict — the pool is truly exhausted."""
        pc = self.prefix_cache
        if pc is None:
            return False
        return pc.evict_one()

    def _pt_operand(self):
        """The device page-table operand, re-uploaded only when the pool's
        tables actually changed (one small host->device transfer per
        mutation, not per dispatch). On pipeline meshes the table is
        replicated (page ids are global — every stage reads the same
        row->page map; only the pool buffer itself is sharded)."""
        pool = self.page_pool
        if self._pt_cache is None or self._pt_cache[0] != pool.version:
            tables = pool.device_tables()
            if self.use_pipeline:
                from jax.sharding import NamedSharding, PartitionSpec

                dev = jax.device_put(
                    tables, NamedSharding(self.mesh, PartitionSpec())
                )
            else:
                dev = jax.device_put(tables)
            self._pt_cache = (pool.version, dev)
        return self._pt_cache[1]

    def _gr_operand(self):
        """The device grammar mask-table operand (the GrammarArena's one
        [S, V] int32 table), re-uploaded only when the arena's version
        moved — a grammar install/evict is one host->device transfer, a
        steady-state dispatch is zero (the `_pt_operand` discipline)."""
        ar = self.grammar
        if self._gr_cache is None or self._gr_cache[0] != ar.version:
            self._gr_cache = (ar.version, jax.device_put(ar.table))
        return self._gr_cache[1]

    def _ensure_pages(self, spans) -> None:
        """Make every (row, start, end) span privately writable before a
        dispatch writes it: allocates unmapped slots, replaces shared pages
        (copy-on-write), and dispatches the :func:`paged_kv.copy_page`
        program for the rare partial-page COW (a write starting mid-page
        over a shared page — the only case whose old content must move)."""
        from .paged_kv import copy_page

        pool = self.page_pool
        # per-span: each span's COW copies dispatch before the next span's
        # allocation can raise, so an exhaustion mid-spans leaves every
        # COMPLETED span consistent (pool.ensure itself is atomic per span)
        for row, start, end in spans:
            for src, dst in pool.ensure(row, start, end):
                src_dev, dst_dev = jax.device_put(
                    (np.int32(src), np.int32(dst))
                )
                with self._guard(
                    f"page_copy[{self.page_size}]",
                    ("page_copy", self.page_size, self.page_size),
                ):
                    self.cache = copy_page(
                        self.cache, src_dev, dst_dev,
                        out_sharding=self._cache_sharding,
                    )

    def _ensure_pages_all_rows(self, start: int, end: int) -> None:
        self._ensure_pages((r, start, end) for r in range(self.batch))

    def forward_tokens(
        self, tokens: list[int], pos_start: int, logits_mode: str = "last"
    ) -> np.ndarray:
        """Run one (unpadded, caller-shaped) forward over `tokens` for every
        batch row; returns host logits."""
        arr = jnp.asarray([tokens] * self.batch, dtype=jnp.int32)
        if self.paged:
            self._ensure_pages_all_rows(pos_start, pos_start + len(tokens))
        logits, self.cache = self._forward(arr, jnp.int32(pos_start), logits_mode)
        return np.asarray(logits)  # dlt: allow(host-sync) — deliberate blocking fetch; library entry, not the serving loop

    def warmup(self) -> None:
        """Compile the serving-critical program ladder before the first real
        request (cold-TTFT, VERDICT r4 #6), in two passes:

        1. the CANONICAL flow — a streaming generate (prefill ladder + TTFT
           ramp + full decode chunks) and, batch > 1, one BatchSession
           admit/step cycle — exercising the real driver paths end to end
           (argmax step, per-row key chains, the admission prefill ladder);
        2. the LADDER FILL (`warm_plan`) — every remaining (kind, size,
           kv-bucket) cross-product program the canonical request's shapes
           do not reach: prefill tail buckets below max_chunk, deep-kv-
           bucket decode/batch-decode chunks (the recorded 52-token-prompt
           sentinel repro), per-row admission chunks at depth, and the
           prefix-cache copy/extract programs.

        With DLT_COMPILE_CACHE set the artifacts persist, so the next
        process loads in seconds instead of compiling for minutes (the
        reference has no compile step to hide; this is the TPU tax paid
        once, up front, instead of inside the first user's request). The
        prefix cache is suppressed for the duration and cleared at the end:
        warmup's synthetic prompts must not publish junk entries."""
        self._in_warmup = True
        try:
            n = max(1, min(self.max_chunk, self.cfg.seq_len - self.decode_chunk_size - 2))
            prompt = [1] * n
            steps = min(n + self.decode_chunk_size + 8, self.cfg.seq_len)
            self.generate(prompt, steps, sampler=None, on_token=lambda t: None)
            self.reset()
            # sampled-request RNG plumbing: a seeded/sampled request derives
            # its device PRNG key through EAGER ops (wrap_key_data, the
            # per-chunk split, the Batcher's key_data round trip) that XLA
            # compiles on first use. The canonical pass above is greedy
            # (sampler=None -> PRNGKey(0)), so without this the FIRST
            # sampled /v1/chat request after seal tripped the recompile
            # sentinel (the recorded fatal-sanitizer chat hole; the decode
            # program itself is temperature-agnostic now — decode_chunk
            # takes temperature/topp as traced operands).
            warm_sampler = Sampler(self.cfg.vocab_size, 1.0, 0.9, 12345)
            wkey = _sampler_prng_key(warm_sampler)
            wkey, _ = _next_subkey(wkey, 1.0)
            np.asarray(jax.random.key_data(wkey))  # dlt: allow(host-sync) — warmup-only compile of the seed-derivation ops
            if self.batch > 1 and self.device_decode:
                from .batch_session import BatchSession

                s = BatchSession(self)
                # a max_chunk admission prompt compiles the per-row admission
                # prefill ladder (prefill_row is a DIFFERENT program from the
                # whole-batch _forward that generate() warms) — without it the
                # first real request still paid full compile inside the request.
                # Cap leaves exactly the room the step(8)+step(chunk) below need
                # so the max_chunk bucket itself gets warmed whenever it fits
                room = self.cfg.seq_len - self.decode_chunk_size - 10
                s.admit(0, [1] * max(2, min(self.max_chunk, room)))
                for chunk in (8, self.decode_chunk_size):
                    if s.pos[0] + 1 + chunk <= self.cfg.seq_len:
                        s.step(chunk)
                s.release(0)
                self.reset()
            self._warmup_fill()
            if self.draft_source is not None:
                # a model-backed draft source compiles its own ladder; it
                # must finish before THIS engine's sentinel seals, or its
                # first serving-time draft would count as a recompile
                self.draft_source.warmup()
            if self.prefix_cache is not None:
                self.prefix_cache.clear()
            self.reset()
            if os.environ.get("DLT_COST_TABLE") == "1":
                # opt-in at-warmup cost-table build: the compiles land in
                # the sentinel's warm window (it seals below) and dedupe
                # against the ladder's own via DLT_COMPILE_CACHE. Default
                # off — the table builds lazily on first /debug/costs (or
                # the server's post-warmup build), keeping library warmups
                # at their current cost.
                self.cost_table()
        finally:
            self._in_warmup = False
        if self.sentinel is not None:
            # the ladder is compiled: from here on, any XLA compile is a
            # ladder hole — counted (sanitizer_recompiles) and optionally
            # fatal (DLT_SANITIZERS_FATAL=1)
            self.sentinel.seal()

    def _warmup_fill(self) -> None:
        """Execute every `warm_plan` program the canonical warmup pass did
        not already dispatch. Cache contents become junk (chunks of zeros at
        synthetic positions) — warmup resets afterwards. Each entry runs the
        PRODUCTION dispatch path for its kind so the compiled shapes (and
        the `_warm` watchdog keys) are exactly what serving hits."""
        key = _greedy_prng_key()
        prefix_segs: dict = {}  # bucket -> (k_seg, v_seg) from the extract warm
        for kind, size, kvb in self.warm_plan():
            if self.paged:
                # bound the pool high-water during the ladder sweep: each
                # entry allocates only its own span, and a sub-parity pool
                # (the whole point of paging) must still warm the full
                # ladder. Reads below the span gather unmapped sentinels —
                # junk, same as the contiguous ladder's zero reads.
                self.page_pool.release_all_rows()
                self._pt_cache = None
            pos = kvb - size  # bucket(pos + size) == kvb by construction
            if kind == "prefill":
                if ("prefill", ((size, kvb),)) in self._warm:
                    continue
                self.prefill([1] * size, pos_start=pos)
            elif kind == "decode":
                if ("decode", size, kvb) in self._warm:
                    continue
                if self.paged:
                    self._ensure_pages_all_rows(pos, pos + size)
                with self._sanitizer_scope(), self._guard(
                    f"decode[{size}]", ("decode", size, kvb)
                ):
                    _, last, self.cache, _ = self._decode_chunk_any(
                        jnp.zeros((self.batch,), jnp.int32), jnp.int32(pos),
                        key, n_steps=size, temperature=0.0, topp=0.9,
                        kv_len=kvb,
                    )
                    if self.use_pipeline:
                        # committed-operand twin: serving's lookahead chunks
                        # feed the PREVIOUS chunk's on-device `last` token,
                        # whose output sharding is part of the mesh lowering
                        # key — warming only the fresh host operand left
                        # that signature cold (a post-seal recompile on the
                        # first mid-stream chunk of every new size)
                        _, _, self.cache, _ = self._decode_chunk_any(
                            last, jnp.int32(pos), key, n_steps=size,
                            temperature=0.0, topp=0.9, kv_len=kvb,
                        )
            elif kind == "prefill_row":
                if ("prefill_row", size, kvb) in self._warm:
                    continue
                with self._sanitizer_scope(), self._guard(
                    f"prefill_row[{size}]", ("prefill_row", size, kvb)
                ):
                    self._dispatch_prefill_row(0, [0] * size, pos, kvb)
            elif kind == "batch_decode":
                if ("batch_decode", size, kvb) in self._warm:
                    continue
                with self._sanitizer_scope(), self._guard(
                    f"batch_decode[{size}]", ("batch_decode", size, kvb)
                ):
                    self._dispatch_batch_decode_warm(size, kvb, pos)
            elif kind in ("verify", "verify_row"):
                if (kind, size, kvb) in self._warm:
                    continue
                toks = np.zeros((self.batch, size), np.int32)
                if kind == "verify":
                    vpos = pos
                else:
                    # per-row shape: one live row, the rest parked at
                    # seq_len (writes dropped) — exactly the serving shape
                    vpos = np.full((self.batch,), self.cfg.seq_len, np.int32)
                    vpos[0] = pos
                with self._sanitizer_scope(), self._guard(
                    f"{kind}[{size - 1}]", (kind, size, kvb)
                ):
                    self._dispatch_verify(toks, vpos, kvb)
            elif kind == "prefix_extract":
                from .prefix_cache import extract_prefix_from_row

                with self._sanitizer_scope(), self._guard(
                    f"prefix_extract[{size}]", ("prefix_extract", size, kvb)
                ):
                    prefix_segs[size] = extract_prefix_from_row(
                        self.cache, jnp.asarray(0, jnp.int32), length=size,
                        out_sharding=self.prefix_cache.seg_sharding,
                    )
            elif kind == "prefix_copy":
                from .prefix_cache import copy_prefix_into_rows

                k_seg, v_seg = prefix_segs[size]
                with self._sanitizer_scope(), self._guard(
                    f"prefix_copy[{size}]", ("prefix_copy", size, kvb)
                ):
                    self.cache = copy_prefix_into_rows(
                        self.cache, k_seg, v_seg,
                        out_sharding=self.prefix_cache.cache_sharding,
                    )
            elif kind == "prefix_copy_row":
                from .prefix_cache import copy_prefix_into_row

                k_seg, v_seg = prefix_segs[size]
                with self._sanitizer_scope(), self._guard(
                    f"prefix_copy_row[{size}]", ("prefix_copy_row", size, kvb)
                ):
                    self.cache = copy_prefix_into_row(
                        self.cache, k_seg, v_seg, jnp.asarray(0, jnp.int32),
                        out_sharding=self.prefix_cache.cache_sharding,
                    )
            elif kind == "page_copy":
                from .paged_kv import copy_page

                if self.page_pool.n_pages < 2:
                    continue  # degenerate pool: nothing to COW between
                src_dev, dst_dev = jax.device_put(
                    (np.int32(0), np.int32(self.page_pool.n_pages - 1))
                )
                with self._sanitizer_scope(), self._guard(
                    f"page_copy[{size}]", ("page_copy", size, kvb)
                ):
                    self.cache = copy_page(
                        self.cache, src_dev, dst_dev,
                        out_sharding=self._cache_sharding,
                    )
            elif kind == "page_extract":
                from .paged_kv import gather_pages

                n = size // self.page_size
                pages = np.zeros((n,), np.int32)  # page-0 junk reads, like
                # every other ladder entry's synthetic operands
                with self._sanitizer_scope(), self._guard(
                    f"page_extract[{size}]", ("page_extract", size, kvb)
                ):
                    gather_pages(
                        self.cache, pages,
                        out_sharding=self.prefix_cache.seg_sharding,
                    )
            elif kind == "page_insert":
                from .paged_kv import scatter_pages

                n = size // self.page_size
                L, _, _, h, d = self.cache.k.shape
                # numpy operands on purpose: the runtime insert path
                # (prefix_cache.insert_external) feeds host arrays, and the
                # jit cache keys committed shardings — warming with device
                # operands would leave the np-operand signature cold.
                # Wire segments are FLOAT even over int8 pools: gather_pages
                # dequantizes on extract and scatter_pages requantizes on
                # insert, so the transport dtype is f32, not the pool dtype
                wire = np.float32 if self.cfg.kv_quantized else self.cache.k.dtype
                seg = np.zeros((L, size, h, d), wire)
                # pairwise-distinct dropped indices past the pool (colliding
                # dropped indices would be undefined scatter behavior — the
                # same discipline the forward's paged write path uses)
                drop = self.page_pool.n_pages + np.arange(n, dtype=np.int32)
                with self._sanitizer_scope(), self._guard(
                    f"page_insert[{size}]", ("page_insert", size, kvb)
                ):
                    self.cache = scatter_pages(
                        self.cache, seg, seg, drop,
                        out_sharding=self._cache_sharding,
                    )

    def _dispatch_prefill_row(self, row: int, chunk: list, pos: int, kv_len: int):
        """One admission-prefill chunk dispatch for `row` — the SAME program
        `BatchSession.prefill_pending` dispatches (both execution paths);
        owned here so warmup's ladder fill and the session share it."""
        import numpy as _np

        if self.use_pipeline:
            from ..parallel.pipeline import pipeline_forward

            toks = _np.zeros((self.batch, len(chunk)), _np.int32)
            toks[row, :] = chunk
            pos_vec = _np.full((self.batch,), self.cfg.seq_len, _np.int32)
            pos_vec[row] = pos
            if self.paged:
                # mesh-paged admission prefill: the full-batch program with
                # every other row parked at seq_len — their writes DROP via
                # the paged scatter, so no per-row table slice is needed
                self._ensure_pages([(row, pos, pos + len(chunk))])
            toks_dev, pos_dev = jax.device_put((toks, pos_vec))
            _, self.cache = pipeline_forward(
                self.cfg, self.mesh, self.params, self.rope, self.cache,
                toks_dev, pos_dev, logits_mode="last", kv_len=kv_len,
                page_table=self._pt_operand() if self.paged else None,
                page_size=self.page_size,
            )
        elif self.paged:
            # paged admission prefill: the b=1 forward against the SHARED
            # pool, steered to the row purely by its page-table slice — no
            # row slice/unslice copies at all (the contiguous prefill_row
            # moves one whole cache row in and out per chunk)
            self._ensure_pages([(row, pos, pos + len(chunk))])
            pt_row = jax.device_put(
                self.page_pool.device_tables()[row : row + 1]
            )
            toks_dev, pos_dev = jax.device_put(
                (_np.asarray([chunk], _np.int32), _np.int32(pos))  # dlt: allow(host-sync) — host token list -> device operand prep
            )
            _, self.cache = forward(
                self.cfg, self.params, self.rope, self.cache, toks_dev,
                pos_dev, logits_mode="last", kv_len=kv_len,
                page_table=pt_row, page_size=self.page_size,
            )
        else:
            from .batch_session import prefill_row

            toks_dev, pos_dev, row_dev = jax.device_put(
                (
                    _np.asarray([chunk], _np.int32),  # dlt: allow(host-sync) — host token list -> device operand prep
                    _np.int32(pos),
                    _np.int32(row),
                )
            )
            self.cache = prefill_row(
                self.cfg, self.params, self.rope, self.cache,
                toks_dev, pos_dev, row_dev, kv_len=kv_len,
            )

    def _dispatch_batch_decode_warm(self, n_steps: int, kv_len: int, pos: int):
        """Dispatch one BatchSession-shaped decode chunk with throwaway
        operands (positions at `pos` so the kv bucket matches; tokens/keys
        zero) — compiles exactly the program `BatchSession.step` runs."""
        b = self.batch
        if self.paged:
            self._ensure_pages_all_rows(pos, pos + n_steps)
        token = jnp.zeros((b,), jnp.int32)
        pos_vec = jnp.full((b,), pos, jnp.int32)
        keys = jnp.zeros((b, 2), jnp.uint32)
        temp = jnp.zeros((b,), jnp.float32)
        topp = jnp.full((b,), 0.9, jnp.float32)
        if self.use_pipeline:
            from ..parallel.pipeline import pipeline_batch_decode_chunk

            _, self.cache, _ = pipeline_batch_decode_chunk(
                self.cfg, self.mesh, self.params, self.rope, self.cache,
                token, pos_vec, keys, temp, topp, n_steps=n_steps,
                kv_len=kv_len,
                page_table=self._pt_operand() if self.paged else None,
                page_size=self.page_size,
            )
        elif self.grammar is not None:
            from .batch_session import batch_decode_chunk

            # the grammar operands are part of the compiled shape too
            # (same rule as the paged operands below): BatchSession.step
            # always threads them on a grammar-capable engine, so the warm
            # program must carry them
            _, self.cache, _, _ = batch_decode_chunk(
                self.cfg, self.params, self.rope, self.cache,
                token, pos_vec, keys, temp, topp, n_steps=n_steps,
                kv_len=kv_len,
                page_table=self._pt_operand() if self.paged else None,
                page_size=self.page_size,
                grammar_table=self._gr_operand(),
                grammar_state=jnp.zeros((b,), jnp.int32),
            )
        else:
            from .batch_session import batch_decode_chunk

            _, self.cache, _ = batch_decode_chunk(
                self.cfg, self.params, self.rope, self.cache,
                token, pos_vec, keys, temp, topp, n_steps=n_steps,
                kv_len=kv_len,
                # the paged operands are part of the compiled shape: warming
                # without them compiled a contiguous-signature program the
                # serving path never dispatches (a post-seal recompile at
                # every deep kv bucket — caught by the deep-bucket test)
                page_table=self._pt_operand() if self.paged else None,
                page_size=self.page_size,
            )

    def _guard(self, label: str, key) -> watchdog:
        """Watchdog for a blocking device call; `key` identifies the
        compiled shape so first-time calls get the compile threshold."""
        first = key not in self._warm
        self._warm.add(key)
        return watchdog(label, compiling=first, stats=self.stats)

    def _pipelined_chunks(self, n_chunks: int, prep, dispatch):
        """The ONE owner of the double-buffered prep/dispatch loop shared by
        `prefill` and `generate_batch`: while chunk k's dispatch round trip
        is in flight on this thread, the worker thread runs `prep(k+1)`
        (token slicing + the chunk's single combined device_put). Honors
        `prefill_pipelined` — the strict serial arm preps inline and blocks
        on the cache after every dispatch (the dispatch->block->dispatch
        reference path). `dispatch(idx, operands)` returns the chunk's
        output; the last one is returned."""
        out = None
        if self.prefill_pipelined:
            fut = self._fetch_pool.submit(prep, 0)
            for idx in range(n_chunks):
                operands = fut.result()
                if idx + 1 < n_chunks:
                    fut = self._fetch_pool.submit(prep, idx + 1)
                out = dispatch(idx, operands)
        else:
            for idx in range(n_chunks):
                out = dispatch(idx, prep(idx))
                jax.block_until_ready(self.cache.k)
        return out

    def prefill(
        self,
        tokens: list[int],
        pos_start: int = 0,
        on_chunk=None,
        sync: bool = True,
        publish: bool = True,
    ) -> None:
        """Feed `tokens` through the model in padded power-of-two chunks,
        with the whole pipeline asynchronous end to end.

        Only the KV cache matters here: logits for the first generated token
        come from the subsequent decode step feeding the final prompt token
        (the reference's shape: prefill covers nInputTokens-1 tokens,
        dllama.cpp:44-85), so chunks run with logits_mode="last" (one wcls
        row) and nothing is fetched to the host until the final sync.

        Through the driver tunnel every host-blocking device call is a
        ~75-100 ms round trip, so the chunk loop is double-buffered: while
        chunk k's dispatch round trip is in flight on this thread, the worker
        thread slices chunk k+1's tokens and `device_put`s its operands
        (tokens + pos scalar in ONE transfer) — the same two-concurrent-RPCs
        pattern the decode loop's dispatch/fetch overlap relies on. The final
        sync is a bare ready-wait on the last chunk's logits
        (`jax.block_until_ready`) instead of the old `np.asarray(jnp.sum(out))`,
        which enqueued one EXTRA dispatch round trip per prefill and then
        fetched its scalar (`sync=False` skips the wait entirely, letting
        decode dispatch chain straight on). Per-chunk dispatch walls land in
        StepStats
        (`prefill_dispatch[size]`), the sync wait in `prefill_sync`, and
        `last_prefill_timing` carries the dispatch-vs-compute overlap summary
        the bench and `/stats` export. `DLT_PREFILL_PIPELINE=0` (or
        engine `prefill_pipelined=False`) forces the strict serial
        dispatch->block->dispatch path — the bit-parity reference for the
        overlap smoke test, and a probe mode for tunnel triage.
        """
        self.last_prefix_hit_tokens = 0  # reset even for empty/cold calls:
        # "the most recent prefill's skip" must never carry a stale hit
        n = len(tokens)
        if n == 0:
            return
        t0 = time.perf_counter()
        # prefix-cache splice: longest-prefix-match the radix trie, round
        # the match DOWN to a chunk-bucket boundary, copy the cached KV into
        # every row with ONE donate-safe program, and resume the chunk plan
        # from the boundary. Only fresh sequences (pos_start == 0) can hit:
        # a continuation's absolute positions don't start at the trie root.
        pc = self.prefix_cache
        tr = self.trace
        resume = 0
        if pc is not None and pos_start == 0 and not self._in_warmup:
            t_match = time.perf_counter()
            resume, entry = pc.match_for_splice(tokens)
            if tr is not None:
                tr.event(
                    "prefix_match", to_us(t_match),
                    int((time.perf_counter() - t_match) * 1e6),
                    ("resume_tokens",), (resume,),
                )
            if entry is not None:
                t_splice = time.perf_counter()
                try:
                    if self.paged:
                        # zero-copy splice: the entry's pages map into every
                        # row's table host-side — no device dispatch at all
                        # (the prefix_copy series stays untouched)
                        pc.share_rows(self, entry, resume)
                    else:
                        with self._sanitizer_scope(), self._guard(
                            f"prefix_copy[{entry.length}]",
                            ("prefix_copy", entry.length, entry.length),
                        ):
                            self.cache = pc.splice_rows(self, entry)
                finally:
                    # ALWAYS unpin — a watchdog StallError out of the guard
                    # must not leave the entry unevictable forever
                    pc.entry_release(entry)
                pc.record_hit(resume)
                if tr is not None:
                    tr.event(
                        "prefix_splice", to_us(t_splice),
                        int((time.perf_counter() - t_splice) * 1e6),
                        ("tokens",), (resume,),
                    )
        self.last_prefix_hit_tokens = resume
        rem = tokens[resume:]
        base = pos_start + resume
        plan = (
            list(chunk_plan(len(rem), base, self.max_chunk, self.cfg.seq_len))
            if rem
            else []
        )
        chunk_shapes = [
            (size, self._kv_bucket(base + i + size)) for i, size, _ in plan
        ]
        if self.paged and plan:
            # allocate the whole prefill span (padded tail included — its
            # junk writes need real pages like the contiguous slab's tail)
            # up front so the chunk loop stays dispatch-only
            i_last, size_last, _ = plan[-1]
            self._ensure_pages_all_rows(base, base + i_last + size_last)

        def prep(idx):
            """Host-side work for one chunk: token slicing + ONE combined
            host->device transfer of its operands. Runs on the worker thread
            so it overlaps the previous chunk's dispatch round trip."""
            i, size, n_real = plan[idx]
            chunk = rem[i : i + n_real] + [0] * (size - n_real)
            arr = np.asarray([chunk] * self.batch, dtype=np.int32)  # dlt: allow(host-sync) — host token list -> device operand prep
            return jax.device_put((arr, np.int32(base + i)))

        timing = {"dispatch_us": 0}
        sync_us = 0
        sync_t0 = 0.0
        chunk_log: list = []  # (t_dispatch_perf, dispatch_us, size) per chunk

        def dispatch(idx, operands):
            arr, pos_dev = operands
            size, kvb = chunk_shapes[idx]
            td = time.perf_counter()
            out, self.cache = self._forward(arr, pos_dev, kv_len=kvb)
            dus = int((time.perf_counter() - td) * 1e6)
            timing["dispatch_us"] += dus
            self.stats.record(f"prefill_dispatch[{size}]", dus)
            chunk_log.append((td, dus, size))
            return out

        # the guard now covers the dispatch loop too (not just the sync): a
        # first-shape chunk's dispatch can block on XLA compilation, and an
        # in-flight-but-uncompiled chunk must run under the compile-aware
        # threshold, not the narrow stall one. The sanitizer scope
        # (DLT_SANITIZERS=1) additionally forbids implicit device->host
        # transfers on this thread for the whole chunk loop — the pipeline
        # is only async end-to-end if nothing in here blocks on a fetch.
        if plan:
            with self._sanitizer_scope(), self._guard(
                f"prefill[{len(rem)}]",
                # the kv bucket matters to the compiled shape: a prefix-cache
                # continuation at a deeper position is a NEW compile even
                # with a seen chunk ladder. Key on EVERY chunk's (size,
                # kv_bucket) pair — the exact shapes the forward calls
                # compile with. Keying only the last bucket aliased ladders
                # whose intermediate buckets differ (different pos_start),
                # mis-tagging a genuine first compile as warm and running it
                # under the narrow stall threshold (false EXEC_STALL)
                ("prefill", tuple(chunk_shapes)),
            ):
                out = self._pipelined_chunks(len(plan), prep, dispatch)
                if sync:
                    ts = sync_t0 = time.perf_counter()
                    # block on the last chunk's logits — the ONE host round trip
                    # of a pipelined prefill: a ready-wait, no extra device op
                    # enqueued (jnp.sum was a dispatch round trip) and no buffer
                    # payload transferred (np.asarray would ship the logits row)
                    jax.block_until_ready(out)
                    sync_us = int((time.perf_counter() - ts) * 1e6)
                    self.stats.record("prefill_sync", sync_us)
        elif sync and resume:
            # full-prefix hit: no chunks to run — the only in-flight device
            # work is the splice; wait for it so the caller's timing (and
            # error surfacing) semantics match the cold path
            ts = sync_t0 = time.perf_counter()
            jax.block_until_ready(self.cache.k)
            sync_us = int((time.perf_counter() - ts) * 1e6)
            self.stats.record("prefill_sync", sync_us)
        total_us = int((time.perf_counter() - t0) * 1e6)
        # dispatch-vs-compute overlap: the fraction of the prefill wall spent
        # inside dispatch calls, during which the device concurrently runs
        # previously-dispatched chunks. 100% = the final sync found all
        # compute already done (fully hidden); low = the sync wait re-paid
        # compute the dispatches failed to hide.
        dispatch_us = timing["dispatch_us"]
        self.last_prefill_timing = {
            "n_tokens": n,
            "n_chunks": len(plan),
            "prefix_hit_tokens": resume,
            "total_us": total_us,
            "dispatch_us": dispatch_us,
            "sync_us": sync_us,
            "overlap_pct": round(100.0 * dispatch_us / max(total_us, 1), 1),
        }
        self.stats.gauge(
            "prefill_dispatch_overlap_pct", self.last_prefill_timing["overlap_pct"]
        )
        if tr is not None:
            # span per chunk from the dispatch walls recorded above (the
            # emitter is pre-bound; None when this trace is unsampled).
            # Each span is the chunk's DISPATCH wall — compute overlaps the
            # next dispatch, which is exactly what last_prefill_timing's
            # overlap_pct summarizes.
            em = tr.bind("prefill_chunk", ("size",))
            if em is not None:
                for td, dus, size in chunk_log:
                    em(to_us(td), dus, size)
            if sync_us:
                tr.event("prefill_sync", to_us(sync_t0), sync_us)
        for _, size, n_real in plan:
            dt = total_us * n_real // max(len(rem), 1)
            self.stats.record(f"prefill[{size}]", dt)
            if on_chunk is not None:
                on_chunk(StepTiming(eval_us=dt, n_tokens=n_real))
        if (
            publish
            and pc is not None
            and pos_start == 0
            and sync
            and not self._in_warmup
        ):
            # publish this prompt's KV back into the trie (one extract copy
            # from row 0 — every row holds the same sequence on this path).
            # The sync above already proved the prefill ran clean, so the
            # extracted slice can't descend from a failed computation.
            with self._sanitizer_scope():
                pc.publish_from_row(self, 0, tokens)

    def _decode_chunk_any(
        self, token, pos, key, n_steps, temperature, topp, kv_len=None,
        gr_state=None,
    ):
        """One on-device decode chunk on whichever execution path this
        engine uses; returns (tokens [b, n], last_token [b], cache,
        gr_out). `pos` may be a scalar or a [b] per-row position vector
        (independent sequences); both paths accept either.

        This is the ONE choke point for the grammar operand pair: a
        grammar-capable engine threads (mask table, [b] states) into EVERY
        decode dispatch — `gr_state=None` rides the all-legal FREE zeros,
        so unconstrained traffic shares the same warm program — and
        `gr_out` is the chunk's final device state vector for lookahead
        callers to chain, like `last_token` (None on grammar-less engines
        and the pipeline path, where the arena is gated off)."""
        if self.use_pipeline:
            from ..parallel.pipeline import pipeline_decode_chunk

            toks, last, cache = pipeline_decode_chunk(
                self.cfg, self.mesh, self.params, self.rope, self.cache,
                token, pos, key, n_steps=n_steps, temperature=temperature,
                topp=topp, kv_len=kv_len,
                page_table=self._pt_operand() if self.paged else None,
                page_size=self.page_size,
            )
            return toks, last, cache, None
        from .decode import decode_chunk

        if self.grammar is None:
            toks, last, cache = decode_chunk(
                self.cfg, self.params, self.rope, self.cache, token, pos,
                key, n_steps=n_steps, temperature=temperature, topp=topp,
                kv_len=kv_len,
                page_table=self._pt_operand() if self.paged else None,
                page_size=self.page_size,
            )
            return toks, last, cache, None
        if gr_state is None:
            gr_state = np.zeros((self.batch,), np.int32)
        return decode_chunk(
            self.cfg, self.params, self.rope, self.cache, token, pos, key,
            n_steps=n_steps, temperature=temperature, topp=topp, kv_len=kv_len,
            page_table=self._pt_operand() if self.paged else None,
            page_size=self.page_size,
            grammar_table=self._gr_operand(), grammar_state=gr_state,
        )

    def _dispatch_verify(self, tokens_np, pos, kv_len: int, gr_states=None):
        """Dispatch one speculative verify forward (runtime/speculative.py):
        a prefill-shaped pass over [last_token, drafts...] returning logits
        at EVERY position plus their greedy argmax. `pos` is a host scalar
        (solo: rows aligned — the ("verify", size, kvb) program) or a [b]
        vector (per-row positions, parked rows at seq_len — the
        ("verify_row", ...) program). Dispatch-only: the caller fetches the
        ids. Returns (ids_dev [b, t], logits_dev [b, t, vocab]).

        On a grammar-capable engine the verify program ALWAYS carries the
        mask-table operand pair: `gr_states` is [b, t] int32 per-position
        global DFA states (None rides all-FREE zeros), and the returned
        argmax chain is over MASKED logits — greedy acceptance can never
        admit a grammar-illegal token, bonus position included."""
        per_row = np.ndim(pos) != 0
        if self.paged:
            # the verify feed writes positions [pos, pos + t) per live row
            # (parked rows sit at seq_len and their writes drop)
            t = np.shape(tokens_np)[1]
            if per_row:
                self._ensure_pages(
                    (r, int(p), int(p) + t)
                    for r, p in enumerate(pos)
                    if int(p) < self.cfg.seq_len
                )
            else:
                self._ensure_pages_all_rows(int(pos), int(pos) + t)
        toks_dev, pos_dev = jax.device_put(
            (
                np.asarray(tokens_np, np.int32),  # dlt: allow(host-sync) — host token rows -> device operand prep
                np.asarray(pos, np.int32) if per_row else np.int32(pos),
            )
        )
        if self.use_pipeline:
            if per_row:
                # mirror the admission-prefill mesh path: per-row positions
                # run one microbatch (prefill_row's collective budget)
                from ..parallel.pipeline import pipeline_forward

                logits, self.cache = pipeline_forward(
                    self.cfg, self.mesh, self.params, self.rope, self.cache,
                    toks_dev, pos_dev, logits_mode="all", kv_len=kv_len,
                    page_table=self._pt_operand() if self.paged else None,
                    page_size=self.page_size,
                )
            else:
                # _forward applies the same microbatch rule a prefill chunk
                # of this size gets — identical collective budget by
                # construction (graph_audit mirrors the rule)
                logits, self.cache = self._forward(
                    toks_dev, pos_dev, logits_mode="all", kv_len=kv_len
                )
            ids = self._argmax_step(logits)
            return ids, logits
        from .speculative import verify_chunk

        gr_table = gr_dev = None
        if self.grammar is not None:
            if gr_states is None:
                gr_states = np.zeros(np.shape(tokens_np), np.int32)
            gr_table = self._gr_operand()
            # callers hand int32 ndarrays (verify_row_round / the solo
            # verify path build them that way) — upload as-is, no cast
            gr_dev = jax.device_put(gr_states)
        ids, logits, self.cache = verify_chunk(
            self.cfg, self.params, self.rope, self.cache, toks_dev, pos_dev,
            kv_len=kv_len,
            page_table=self._pt_operand() if self.paged else None,
            page_size=self.page_size,
            grammar_table=gr_table, grammar_state=gr_dev,
        )
        return ids, logits

    def decode_one(self, token: int, pos: int) -> np.ndarray:
        """One decode step; returns host logits [batch, vocab]."""
        arr = jnp.full((self.batch, 1), token, dtype=jnp.int32)
        if self.paged:
            self._ensure_pages_all_rows(pos, pos + 1)
        logits, self.cache = self._forward(
            arr, jnp.int32(pos), kv_len=self._kv_bucket(pos + 1)
        )
        return np.asarray(logits)  # dlt: allow(host-sync) — per-token host loop / library entry; the chunked path is the hot loop

    # -- generation driver --------------------------------------------------

    def generate(
        self,
        prompt_tokens: list[int],
        steps: int,
        sampler: Sampler | None = None,
        on_token=None,
        stop_fn=None,
        pos_start: int = 0,
        grammar=None,  # runtime/grammar.py GrammarSession: constrain this
        # generation to the session's DFA (masked sampling + masked
        # speculative verify); the session is advanced host-side from every
        # emitted token and a terminal state stops like EOS
    ) -> GenerationResult:
        """The reference `inference()` loop (dllama.cpp:13-151): prefill all
        but the last prompt token, then decode until position `steps` or
        `stop_fn(token)` says stop. `pos_start` > 0 continues an existing
        cache (the API server's naive-prefix-cache path).
        """
        if not prompt_tokens:
            raise ValueError("prompt tokens required")
        if grammar is not None and self.grammar is None:
            raise ValueError(
                "this engine was built without a grammar arena "
                "(grammar=True / DLT_GRAMMAR=1, single-chip device-decode)"
            )
        if pos_start + len(prompt_tokens) > self.cfg.seq_len:
            raise ValueError("prompt is longer than the sequence length")
        res = GenerationResult(tokens=list(prompt_tokens), n_prompt_tokens=len(prompt_tokens))
        wall0 = time.perf_counter()

        # prefill all but the last prompt token (its logits come from the
        # first decode step, reference dllama.cpp:44-85). publish=False: the
        # post-decode publish below covers the prompt AND the reply in one
        # extract, so the next chat turn hits the whole conversation.
        self.prefill(
            prompt_tokens[:-1], pos_start, on_chunk=res.eval_steps.append,
            publish=False,
        )
        res.prefill_us = int((time.perf_counter() - wall0) * 1e6)
        if self.trace is not None:
            self.trace.event(
                "prefill", to_us(wall0), res.prefill_us,
                ("n_tokens", "prefix_hit_tokens"),
                (len(prompt_tokens) - 1, self.last_prefix_hit_tokens),
            )

        pos = pos_start + len(prompt_tokens) - 1
        token = prompt_tokens[-1]
        max_pos = min(self.cfg.seq_len, steps)
        if self.device_decode:
            # speculative decode applies to GREEDY generations only: under a
            # sampler, accepting drafts would change the RNG stream (and the
            # acceptance test itself needs the deterministic argmax chain)
            use_spec = (
                self.spec_mode is not None
                and not self._in_warmup
                and (sampler is None or sampler.temperature == 0.0)
            )
            # sanitizer scope: the chunked decode loop must never block on
            # an implicit device->host transfer on this thread (the token
            # fetches ride the worker thread; DLT_SANITIZERS=1 enforces it)
            with self._sanitizer_scope():
                if use_spec:
                    self._decode_speculative(
                        res, token, pos, max_pos, on_token, stop_fn, wall0,
                        grammar=grammar,
                    )
                else:
                    self._decode_device(
                        res, token, pos, max_pos, sampler, on_token, stop_fn,
                        wall0, grammar=grammar,
                    )
        else:
            self._decode_host(res, token, pos, max_pos, sampler, on_token, stop_fn, wall0)
        res.total_us = int((time.perf_counter() - wall0) * 1e6)
        res.decode_us = res.total_us - res.prefill_us
        if (
            self.prefix_cache is not None
            and pos_start == 0
            and not self._in_warmup
            and len(res.tokens) > 1
        ):
            # conversation-level publish: prompt + generated tokens in one
            # entry, so the next turn of this chat longest-prefix-matches
            # the whole history. Capped at len-1: the final token was
            # sampled but may never have been FED (its KV slot is unwritten
            # when the stop landed on the last step of the last chunk).
            with self._sanitizer_scope():
                self.prefix_cache.publish_from_row(
                    self, 0, res.tokens, max_len=len(res.tokens) - 1
                )
        return res

    def generate_batch(
        self,
        prompts: list,
        max_new_tokens,  # int (shared) or list[int] (per row)
        sampler: Sampler | None = None,
        on_token=None,  # on_token(row, token) as tokens arrive
        stop_fn=None,  # stop_fn(row, token) -> bool, per row
        grammars=None,  # per-row GrammarSession list (None entries =
        # unconstrained rows riding the FREE state — mixed co-batching)
    ) -> list:
        """Generate independent continuations for `len(prompts)` different
        prompts in ONE batch — each batch row is its own sequence with its
        own positions (the reference is single-sequence: its batch axis is
        prefill positions; this is the beyond-reference batch-serving axis).

        Rows are right-padded to a common length for prefill (junk written
        past a row's true length is causally masked until decode overwrites
        it — the same invariant single-sequence padding relies on); decode
        then runs chunks with per-row positions. Returns a list of per-row
        generated-token lists (stop token included, as `generate` does).
        Requires len(prompts) == self.batch. Works on both execution paths:
        single-chip/GSPMD via runtime/decode.py and tp/pp/sp/ep meshes via
        the shard_map pipeline (per-row positions thread through
        parallel/pipeline.py's vector-pos path).

        `max_new_tokens` may be per-row: each row's budget is bounded by ITS
        OWN prompt length against seq_len, so a short prompt co-batched with
        a long one keeps its full budget (rows that finish keep riding the
        chunk loop; their cache writes past seq_len are DROPPED by the
        per-row scatter — the live cache tail stays intact — and their
        tokens are discarded host-side).
        """
        if len(prompts) != self.batch:
            raise ValueError(f"need exactly {self.batch} prompts, got {len(prompts)}")
        if any(len(p) == 0 for p in prompts):
            raise ValueError("empty prompt")
        if grammars is not None:
            if self.grammar is None and any(g is not None for g in grammars):
                raise ValueError(
                    "this engine was built without a grammar arena "
                    "(grammar=True / DLT_GRAMMAR=1, single-chip device-decode)"
                )
            if len(grammars) != self.batch:
                raise ValueError("per-row grammars must match the batch size")
        lens = [len(p) for p in prompts]
        if isinstance(max_new_tokens, int):
            budgets = [max_new_tokens] * self.batch
        else:
            budgets = list(max_new_tokens)
            if len(budgets) != self.batch:
                raise ValueError("per-row budgets must match the batch size")
        for r in range(self.batch):
            if lens[r] + budgets[r] > self.cfg.seq_len:
                raise ValueError(
                    f"row {r}: prompt ({lens[r]}) + budget ({budgets[r]}) "
                    f"exceeds the sequence length ({self.cfg.seq_len})"
                )

        # prefix-cache splice for the SHARED leading tokens (the shared-
        # system-prompt serving shape): longest-prefix-match the trie with
        # the prompts' common prefix, splice the cached KV into EVERY row
        # (rows agree on [0, resume) by construction), and prefill only the
        # remainder. Rows' divergent tails and the entry's positions past
        # the boundary are rewritten before any query reads them — the same
        # write-before-read invariant right-padding relies on.
        pre_t = max(lens) - 1
        pc = self.prefix_cache
        resume = 0
        if pc is not None and not self._in_warmup and pre_t > 0:
            common_len = 0
            p0 = prompts[0]
            while common_len < min(lens) and all(
                p[common_len] == p0[common_len] for p in prompts
            ):
                common_len += 1
            if common_len:
                resume, entry = pc.match_for_splice(
                    list(p0[: min(common_len, pre_t)])
                )
                if entry is not None:
                    try:
                        if self.paged:
                            pc.share_rows(self, entry, resume)
                        else:
                            with self._sanitizer_scope(), self._guard(
                                f"prefix_copy[{entry.length}]",
                                ("prefix_copy", entry.length, entry.length),
                            ):
                                self.cache = pc.splice_rows(self, entry)
                    finally:
                        pc.entry_release(entry)
                    pc.record_hit(resume)
        self.last_prefix_hit_tokens = resume

        # prefill all-but-last per row (from the resume boundary), rows
        # right-padded to a common length, through the shared double-buffered
        # chunk pipeline (worker-thread prep overlapping dispatch; honors
        # prefill_pipelined like `prefill`)
        if pre_t > resume:
            padded = [list(p[:-1]) + [0] * (pre_t - (len(p) - 1)) for p in prompts]
            plan = list(
                chunk_plan(pre_t - resume, resume, self.max_chunk, self.cfg.seq_len)
            )
            if self.paged and plan:
                i_last, size_last, _ = plan[-1]
                self._ensure_pages_all_rows(resume, resume + i_last + size_last)

            def prep(idx):
                i, size, _ = plan[idx]
                rows = [row[resume + i : resume + i + size] for row in padded]
                rows = [r + [0] * (size - len(r)) for r in rows]
                return jax.device_put(
                    (np.asarray(rows, dtype=np.int32), np.int32(resume + i))  # dlt: allow(host-sync) — host token rows -> device operand prep
                )

            def dispatch(idx, operands):
                arr, pos_dev = operands
                i, size, _ = plan[idx]
                out, self.cache = self._forward(
                    arr, pos_dev, kv_len=self._kv_bucket(resume + i + size),
                )
                return out

            self._pipelined_chunks(len(plan), prep, dispatch)

        temperature = 0.0 if sampler is None else sampler.temperature
        topp = sampler.topp if sampler is not None else 0.9
        key = _sampler_prng_key(sampler)

        out: list[list[int]] = [[] for _ in range(self.batch)]
        total_needed = max(budgets)
        if total_needed <= 0:
            return out
        if (
            self.spec_mode is not None
            and self.device_decode
            and not self._in_warmup
            and temperature == 0.0
        ):
            # greedy batches take the speculative path: per-row drafts, one
            # per-row-position verify dispatch per round
            # (runtime/speculative.py). Sampled batches keep the chunked
            # lookahead loop below — accepting drafts under a sampler would
            # change the RNG stream — and host-decode engines always do:
            # their warm plan (and the sentinel's sealed ladder) carries no
            # verify programs, the same gate every other spec entry has.
            self._decode_batch_speculative(
                prompts, lens, budgets, out, on_token, stop_fn,
                grammars=grammars,
            )
        else:
            self._decode_batch_chunked(
                prompts, lens, budgets, out, on_token, stop_fn, key,
                temperature, topp, grammars=grammars,
            )
        if pc is not None and not self._in_warmup and pre_t > 0 and resume == 0:
            # publish the rows' common prefix (row 0's copy, capped at its
            # prefilled extent) so the NEXT shared-prefix batch splices it.
            # After the decode loop on purpose: a failed batch must not
            # leave a half-written slice in the trie. A hit this call
            # (resume > 0) means the prefix is already published.
            with self._sanitizer_scope():
                pc.publish_from_row(
                    self, 0, list(prompts[0]), max_len=min(common_len, lens[0] - 1)
                )
        return out

    def _decode_batch_chunked(
        self, prompts, lens, budgets, out, on_token, stop_fn, key,
        temperature, topp, grammars=None,
    ):
        """generate_batch's chunked decode loop: one-chunk lookahead +
        worker-thread fetch, exactly like _decode_device — chunk i+1's
        dispatch (device-resident inputs) overlaps chunk i's ~100 ms tunnel
        fetch. Without this the round-4 batched loop paid a full synchronous
        round trip per chunk — the dominant share of the batched-serving
        per-stream tax (measured: the batched chunk program computes
        ~1.9 ms/step with the batch axis nearly free, but e2e ran at
        ~3.5 ms/step). Chunks are PLANNED against the max per-row budget
        (tokens aren't visible at dispatch time); rows cap at their own
        budgets at consume time, and a stop_fn early-exit wastes at most the
        lookahead chunk (same overrun tradeoff the solo path accepts)."""
        pos = jnp.asarray([l - 1 for l in lens], jnp.int32)  # [b]
        token = jnp.asarray([p[-1] for p in prompts], jnp.int32)
        done = [False] * self.batch
        total_needed = max(budgets)
        planned = 0
        key_box = [key]
        # grammar chain mirrors _decode_device's: lookahead chunks consume
        # the previous chunk's device final states (rows without a session
        # start at FREE 0 and stay there — the all-legal self-loop)
        gr0 = None
        if grammars is not None and any(g is not None for g in grammars):
            gr0 = np.fromiter(
                (g.row_state if g is not None else 0 for g in grammars),
                np.int32,
                count=len(grammars),
            )
        state = {"token": token, "pos": pos, "gr": gr0}

        def dispatch_chunk():
            nonlocal planned
            ramp = planned == 0 and on_token is not None
            n = min(8, self.decode_chunk_size) if ramp else self.decode_chunk_size
            while n > (total_needed - planned):
                n //= 2
            n = max(n, 1)
            key_box[0], sub = _next_subkey(key_box[0], temperature)
            # kv bucket covers the furthest position any not-yet-done row
            # reaches this chunk (finished rows still step, but their
            # output is discarded and their trailing writes never read)
            max_end = min(
                max(
                    lens[r] + planned
                    for r in range(self.batch)
                    if not done[r]
                )
                + n,
                self.cfg.seq_len,
            )
            kvb = self._kv_bucket(max_end)
            if self.paged:
                # LIVE rows need pages over their chunk span; DONE rows
                # keep stepping but their junk writes land on unmapped
                # slots and DROP (the phys < 0 guard) — allocating for
                # them would burn pool pages on output nobody reads
                self._ensure_pages(
                    (r, lens[r] - 1 + planned, lens[r] - 1 + planned + n)
                    for r in range(self.batch)
                    if not done[r] and lens[r] - 1 + planned < self.cfg.seq_len
                )
            toks, last, self.cache, gr_out = self._decode_chunk_any(
                state["token"], state["pos"], sub, n_steps=n,
                temperature=temperature, topp=topp, kv_len=kvb,
                gr_state=state["gr"],
            )
            state["token"] = last
            state["pos"] = state["pos"] + n
            if state["gr"] is not None:
                state["gr"] = gr_out
            planned += n
            return toks, n, kvb

        # same hot-loop sanitizer contract as _decode_device: fetches ride
        # the worker thread, this thread must never implicitly sync
        with self._sanitizer_scope():
            pending = dispatch_chunk()
            while pending is not None:
                toks, n, kvb = pending
                fut = self._fetch_pool.submit(self._host_fetch, toks)
                nxt = None
                if planned < total_needed:
                    nxt = dispatch_chunk()
                with self._guard(f"decode_batch[{n}]", ("decode_batch", n, kvb)):
                    host = fut.result()  # [b, n]
                for j in range(n):
                    for r in range(self.batch):
                        if done[r] or len(out[r]) >= budgets[r]:
                            done[r] = True
                            continue
                        tkn = int(host[r, j])
                        out[r].append(tkn)
                        g = grammars[r] if grammars is not None else None
                        if g is not None:
                            g.advance(tkn)
                        if on_token is not None:
                            on_token(r, tkn)
                        if stop_fn is not None and stop_fn(r, tkn):
                            done[r] = True
                        elif g is not None and (g.done or g.at_terminal):
                            # grammar completion stops the row like EOS:
                            # this token is delivered, the chunk tail is
                            # ordinary overrun
                            done[r] = True
                        elif len(out[r]) >= budgets[r]:
                            done[r] = True
                if all(done):
                    # a dispatched lookahead chunk past this point is
                    # discarded: its cache writes sit beyond every returned
                    # sequence, junk the same way padded prefill tails are
                    pending = None
                else:
                    pending = nxt

    def _decode_batch_speculative(
        self, prompts, lens, budgets, out, on_token, stop_fn, grammars=None,
    ):
        """generate_batch's speculative decode loop (greedy batches): every
        round drafts per row from the row's OWN context, then either one
        per-row-position verify dispatch (any row drafted; rows with no
        draft still advance by their one bonus token) or one plain batched
        decode chunk (nobody drafted — the draft-hostile fallback that keeps
        worst-case throughput at the chunked loop's rate). Per-row
        acceptance: each row keeps its longest draft prefix matching its own
        argmax chain. Finished rows park at seq_len — their writes drop via
        the per-row scatter and they skip drafting. Rows advance unevenly
        (speculation is per-row), so positions/tokens are host lists rather
        than the aligned device vectors of the chunked loop."""
        from .speculative import verify_row_round

        b = self.batch
        seq_len = self.cfg.seq_len
        ds = self.draft_source
        key = _greedy_prng_key()  # greedy chunks never draw
        pos = [l - 1 for l in lens]
        token = [int(p[-1]) for p in prompts]
        done = [budgets[r] <= 0 for r in range(b)]
        with self._sanitizer_scope():
            while not all(done):
                live = [r for r in range(b) if not done[r]]
                drafts = {}
                for r in live:
                    # cap: emitted <= drafts+1 <= remaining budget, which
                    # also bounds writes to pos + cap <= seq_len - 2 (the
                    # lens+budgets <= seq_len constructor check)
                    cap = min(self.spec_buckets[-1], budgets[r] - len(out[r]) - 1)
                    d = ds.draft(list(prompts[r]) + out[r], cap) if cap > 0 else []
                    drafts[r] = [int(t) for t in d[:max(cap, 0)]]
                if any(drafts.values()):
                    # the shared per-row verify round (speculative.py):
                    # one dispatch, per-row acceptance, rows advance by
                    # their own 1..K+1 emitted tokens
                    rounds = verify_row_round(
                        self, drafts, token, pos, seq_len, grammars=grammars,
                    )
                    for r, emitted in rounds.items():
                        g = grammars[r] if grammars is not None else None
                        pos[r] += len(emitted)
                        token[r] = emitted[-1]
                        for t in emitted:
                            out[r].append(t)
                            if g is not None:
                                g.advance(t)
                            if on_token is not None:
                                on_token(r, t)
                            if stop_fn is not None and stop_fn(r, t):
                                done[r] = True
                                break
                            if g is not None and (g.done or g.at_terminal):
                                done[r] = True
                                break
                            if len(out[r]) >= budgets[r]:
                                done[r] = True
                                break
                else:
                    # nobody drafted: one plain chunk at per-row positions
                    # (the generate_batch decode program) — surplus tokens
                    # past a row's budget/stop are discarded at consume time
                    needed = max(budgets[r] - len(out[r]) for r in live)
                    n = self.decode_chunk_size
                    while n > needed:
                        n //= 2
                    n = max(n, 1)
                    pv = np.full((b,), seq_len, np.int32)
                    tv = np.zeros((b,), np.int32)
                    for r in live:
                        pv[r] = pos[r]
                        tv[r] = token[r]
                    kvb = self._kv_bucket(
                        min(max(pos[r] for r in live) + 1 + n, seq_len)
                    )
                    if self.paged:
                        self._ensure_pages(
                            (r, pos[r], pos[r] + n) for r in live
                        )
                    gr_state = None
                    if grammars is not None and any(
                        g is not None for g in grammars
                    ):
                        gr_state = np.fromiter(
                            (
                                g.row_state if g is not None else 0
                                for g in grammars
                            ),
                            np.int32,
                            count=len(grammars),
                        )
                    tok_dev, pos_dev = jax.device_put((tv, pv))
                    with self._guard(f"decode_batch[{n}]", ("decode_batch", n, kvb)):
                        toks, _, self.cache, _ = self._decode_chunk_any(
                            tok_dev, pos_dev, key, n_steps=n, temperature=0.0,
                            topp=0.9, kv_len=kvb, gr_state=gr_state,
                        )
                        host = self._host_fetch(toks)
                    for r in live:
                        g = grammars[r] if grammars is not None else None
                        for j in range(n):
                            t = int(host[r, j])
                            out[r].append(t)
                            if g is not None:
                                g.advance(t)
                            if on_token is not None:
                                on_token(r, t)
                            if stop_fn is not None and stop_fn(r, t):
                                done[r] = True
                                break
                            if g is not None and (g.done or g.at_terminal):
                                done[r] = True
                                break
                            if len(out[r]) >= budgets[r]:
                                done[r] = True
                                break
                        pos[r] += n
                        token[r] = int(host[r, n - 1])

    def _decode_host(self, res, token, pos, max_pos, sampler, on_token, stop_fn, wall0):
        """Per-token host loop: one device round trip per token. Bit-parity
        path (host Sampler = the reference's xorshift* stream)."""
        greedy = sampler is None or sampler.temperature == 0.0
        first = True
        while pos < max_pos:
            t0 = time.perf_counter()
            if greedy:
                arr = jnp.full((self.batch, 1), token, dtype=jnp.int32)
                if self.paged:
                    self._ensure_pages_all_rows(pos, pos + 1)
                logits, self.cache = self._forward(
                    arr, jnp.int32(pos), kv_len=self._kv_bucket(pos + 1)
                )
                token = int(self._argmax_step(logits)[0])
            else:
                logits = self.decode_one(token, pos)
                token = sampler.sample(logits[0].copy())
            dt = int((time.perf_counter() - t0) * 1e6)
            res.pred_steps.append(StepTiming(eval_us=dt, n_tokens=1))
            if first:
                res.ttft_us = int((time.perf_counter() - wall0) * 1e6)
                first = False
            res.tokens.append(token)
            pos += 1
            if on_token is not None:
                on_token(token)
            if stop_fn is not None and stop_fn(token):
                return

    def _decode_device(
        self, res, token, pos, max_pos, sampler, on_token, stop_fn, wall0,
        grammar=None,
    ):
        """Chunked on-device decode: K forward+sample steps per host call
        (runtime/decode.py), one token-array fetch per chunk."""
        import jax

        temperature = 0.0 if sampler is None else sampler.temperature
        topp = sampler.topp if sampler is not None else 0.9
        key = [_sampler_prng_key(sampler)]
        # grammar chain: the lookahead chunk dispatches BEFORE this chunk's
        # tokens reach the host, so its initial grammar states must be the
        # previous chunk's on-device final states (gr_out), chained exactly
        # like `last`. The host session stays authoritative between
        # generations; inside the loop it only consumes (advance + stop).
        gr_box = [
            np.full((self.batch,), grammar.row_state, np.int32)
            if grammar is not None
            else None
        ]

        def dispatch(at_pos, tok_arr, chunk=None):
            """Queue one device chunk (async); returns (tokens_device,
            last_token_device, n)."""
            limit = min(max_pos, self.cfg.seq_len) - at_pos
            n = chunk if chunk is not None else self.decode_chunk_size
            # largest power-of-two chunk that fits the remaining budget —
            # O(log chunk) compiled programs, no per-token tail round trips
            while n > limit:
                n //= 2
            n = max(n, 1)
            key[0], sub = _next_subkey(key[0], temperature)
            kvb = self._kv_bucket(at_pos + n)
            if self.paged:
                self._ensure_pages_all_rows(at_pos, at_pos + n)
            toks, last, self.cache, gr_out = self._decode_chunk_any(
                tok_arr, jnp.int32(at_pos), sub, n_steps=n,
                temperature=temperature, topp=topp, kv_len=kvb,
                gr_state=gr_box[0],
            )
            if grammar is not None:
                gr_box[0] = gr_out
            return toks, last, n, kvb

        if pos >= max_pos:
            return  # no decode budget (steps <= prompt length)
        # one-chunk lookahead: chunk i+1 is dispatched (its inputs are all
        # device-resident) before chunk i's tokens are fetched, so the
        # ~tens-of-ms device->host transfer overlaps the next chunk's compute.
        # The fetch ALSO runs on the engine's worker thread: through the
        # driver tunnel, dispatch and fetch are each a ~75 ms host-blocking
        # round trip, and they are independent (the next dispatch consumes
        # the DEVICE tokens array, not the host copy) — serializing them put
        # a ~150 ms/chunk host floor under small-model decode (the round-3
        # per-token floor's other half, beside the cache re-stack).
        # pre-bound span emitter (one tuple append per CHUNK, not per token;
        # None = untraced or unsampled — the same guard covers both)
        em_chunk = (
            self.trace.bind("decode_chunk", ("n",)) if self.trace is not None else None
        )
        first = True
        t_prev = time.perf_counter()
        # TTFT ramp — only when a consumer is streaming (on_token): the first
        # chunk is small (8) so the first tokens reach the host after ~8
        # decode steps instead of a full chunk (~100 ms of TTFT on the 1B,
        # ~800 ms on 8B). The ramp is NOT free: it de-aligns the remaining
        # budget from the power-of-two chunk ladder, so a fixed budget decays
        # into a fragmented tail (8+64+32+16+8 instead of 64+64) and every
        # extra chunk pays a ~70-90 ms tunnel dispatch — a 2x throughput hit
        # on short fixed-budget runs (caught by the round-3 bench). Without a
        # streaming consumer, TTFT is unobservable; keep full chunks.
        first_chunk = min(8, self.decode_chunk_size) if on_token is not None else None
        pending = dispatch(
            pos, jnp.full((self.batch,), token, dtype=jnp.int32), chunk=first_chunk
        )
        dispatched = pos + pending[2]
        while pending is not None:
            toks, last, n, kvb = pending
            # start the host fetch on the worker thread, then dispatch the
            # lookahead chunk from this thread — the two tunnel round trips
            # overlap. np.asarray(toks) transfers without enqueueing any
            # device op (indexing toks[0] here would create a device slice
            # op ordered *behind* the in-flight chunk and serialize; `last`
            # comes back from the chunk program itself for the same reason).
            fut = self._fetch_pool.submit(self._host_fetch, toks)
            nxt = None
            if dispatched < max_pos:
                nxt = dispatch(dispatched, last)
                dispatched += nxt[2]
            with self._guard(f"decode[{n}]", ("decode", n, kvb)):
                host_toks = fut.result()[0].tolist()
            now = time.perf_counter()
            dt = int((now - t_prev) * 1e6)
            if em_chunk is not None:
                em_chunk(to_us(t_prev), dt, n)
            t_prev = now
            self.stats.record(f"decode[{n}]", dt)
            if first:
                res.ttft_us = int((now - wall0) * 1e6)
                first = False
            # one timing record per CHUNK — the chunk boundary is the only
            # host-observable measurement point on the device decode path
            res.pred_steps.append(StepTiming(eval_us=dt, n_tokens=n))
            for t in host_toks:
                res.tokens.append(t)
                pos += 1
                if grammar is not None:
                    grammar.advance(t)
                if on_token is not None:
                    on_token(t)
                if stop_fn is not None and stop_fn(t):
                    # tokens past the stop are never appended; the cache
                    # overran by up to 2*chunk positions (this chunk's tail
                    # plus the in-flight lookahead), which is harmless — a
                    # continuation re-writes those slots before reading them
                    return
                if grammar is not None and (grammar.done or grammar.at_terminal):
                    # grammar completion stops like EOS: the emitted token
                    # is delivered; the chunk tail is ordinary overrun
                    return
            pending = nxt

    def _decode_speculative(
        self, res, token, pos, max_pos, on_token, stop_fn, wall0, grammar=None,
    ):
        """Greedy speculative decode (runtime/speculative.py): per round,
        the draft source proposes up to k tokens from the live context, ONE
        verify dispatch scores [token, drafts...] at every position, and
        the longest draft prefix matching the model's own argmax chain is
        accepted plus the bonus token at the first mismatch — 1..k+1 tokens
        of the exact plain-decode chain per dispatch. Rounds with no draft
        fall back to one ordinary decode chunk (the plain program off the
        same warm ladder), so draft-hostile traffic pays only the failed
        lookup, not per-token dispatches. Rejected drafts need no KV
        rollback: positions past the accepted boundary are rewritten by a
        later round's feed before any query reads them (write-before-read).
        Unlike the chunked loop there is no lookahead dispatch — each
        round's draft depends on the previous round's outcome."""
        from .speculative import accept_greedy, note_round

        ds = self.draft_source
        seq_len = self.cfg.seq_len
        key = _greedy_prng_key()  # greedy chunks never draw
        t0 = time.perf_counter()
        rounds = fallback_chunks = drafted = accepted = emitted_total = 0
        draft_us = verify_us = 0
        first = True
        # pre-bound per-round emitters (one tuple append per verify round /
        # fallback chunk; None = untraced or unsampled)
        tr = self.trace
        em_round = tr.bind("spec_round", ("drafted", "accepted")) if tr else None
        em_chunk = tr.bind("decode_chunk", ("n",)) if tr else None
        while pos < max_pos:
            # the verify feed writes positions pos..pos+k; at scalar pos the
            # cache update is a dynamic_update_slice whose start CLAMPS at
            # seq_len - size (silently corrupting earlier KV), so a bucket
            # only qualifies when it fits entirely
            kmax = 0
            for b in self.spec_buckets:
                if pos + b + 1 <= seq_len:
                    kmax = b
            td = time.perf_counter()
            drafts = ds.draft(list(res.tokens), kmax) if kmax else []
            if grammar is not None and drafts:
                # grammar-hostile drafts collapse to their legal prefix
                # BEFORE the round is shaped: acceptance can then never
                # reach an illegal proposal (the verify mask guards the
                # argmax chain, this guards the match test's inputs)
                drafts = drafts[: grammar.legal_prefix(drafts)]
            draft_us += int((time.perf_counter() - td) * 1e6)
            tv = time.perf_counter()
            if drafts:
                drafts = [int(t) for t in drafts[:kmax]]
                K = next(b for b in self.spec_buckets if b >= len(drafts))
                size = K + 1
                feed = [int(token)] + drafts + [0] * (K - len(drafts))
                kvb = self._kv_bucket(pos + size)
                gr_states = None
                if grammar is not None:
                    row = np.zeros((size,), np.int32)
                    vs = grammar.verify_states(drafts)
                    row[: len(vs)] = vs
                    gr_states = np.repeat(row[None, :], self.batch, axis=0)
                with self._guard(f"verify[{K}]", ("verify", size, kvb)):
                    ids_dev, _ = self._dispatch_verify(
                        np.asarray([feed] * self.batch, np.int32), pos, kvb,  # dlt: allow(host-sync) — host token list -> device operand prep
                        gr_states=gr_states,
                    )
                    ids = self._host_fetch(ids_dev)[0]
                a = accept_greedy(drafts, ids)
                emitted = drafts[:a] + [int(ids[a])]
                dt = int((time.perf_counter() - tv) * 1e6)
                verify_us += dt
                rounds += 1
                drafted += len(drafts)
                accepted += a
                note_round(self.stats, len(drafts), a)
                self.stats.record(f"spec_verify[{K}]", dt)
                if em_round is not None:
                    em_round(to_us(tv), dt, len(drafts), a)
            else:
                # no draft: one plain decode chunk (largest power-of-two
                # that fits the remaining budget — the ordinary ladder).
                # First-chunk TTFT ramp exactly like _decode_device: a
                # streaming consumer gets tokens after ~8 steps, not a
                # full chunk
                limit = min(max_pos, seq_len) - pos
                n = (
                    min(8, self.decode_chunk_size)
                    if first and on_token is not None
                    else self.decode_chunk_size
                )
                while n > limit:
                    n //= 2
                n = max(n, 1)
                kvb = self._kv_bucket(pos + n)
                if self.paged:
                    self._ensure_pages_all_rows(pos, pos + n)
                with self._guard(f"decode[{n}]", ("decode", n, kvb)):
                    toks, _, self.cache, _ = self._decode_chunk_any(
                        jnp.full((self.batch,), int(token), jnp.int32),
                        jnp.int32(pos), key, n_steps=n, temperature=0.0,
                        topp=0.9, kv_len=kvb,
                        gr_state=(
                            np.full((self.batch,), grammar.row_state, np.int32)
                            if grammar is not None
                            else None
                        ),
                    )
                    emitted = [int(t) for t in self._host_fetch(toks)[0]]
                dt = int((time.perf_counter() - tv) * 1e6)
                fallback_chunks += 1
                self.stats.record(f"decode[{n}]", dt)
                if em_chunk is not None:
                    em_chunk(to_us(tv), dt, n)
            if first:
                res.ttft_us = int((time.perf_counter() - wall0) * 1e6)
                first = False
            res.pred_steps.append(
                StepTiming(eval_us=dt, n_tokens=min(len(emitted), max_pos - pos))
            )
            stopped = False
            for t in emitted:
                if pos >= max_pos:
                    break  # a round may overshoot the budget; surplus
                    # tokens are discarded like a chunk's post-stop tail
                res.tokens.append(t)
                pos += 1
                emitted_total += 1
                if grammar is not None:
                    grammar.advance(t)
                if on_token is not None:
                    on_token(t)
                if stop_fn is not None and stop_fn(t):
                    stopped = True
                    break
                if grammar is not None and (grammar.done or grammar.at_terminal):
                    stopped = True
                    break
            token = res.tokens[-1]
            if stopped:
                break
        total_us = int((time.perf_counter() - t0) * 1e6)
        self.last_spec_timing = {
            "rounds": rounds,
            "fallback_chunks": fallback_chunks,
            "draft_tokens": drafted,
            "accepted_tokens": accepted,
            "emitted_tokens": emitted_total,
            "acceptance_rate": round(accepted / drafted, 4) if drafted else None,
            "draft_us": draft_us,
            "verify_us": verify_us,
            "total_us": total_us,
        }
