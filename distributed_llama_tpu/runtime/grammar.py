"""Grammar-constrained structured decoding (`response_format` on /v1/chat).

Compiles a regex (or JSON-schema, lowered to a canonical regex) spec into a
DFA over BYTES, then lifts it to a DFA over the tokenizer's VOCABULARY:

    token_table[s, t] = the byte-DFA state reached by walking token t's
                        piece bytes from state s, or -1 when any byte dies.

The in-graph contract derives everything from that one int32 table:

* legality mask — ``table[state] >= 0`` is the ISSUE's "states x vocab
  boolean mask" row; `ops/sampling.py` applies it as a
  ``where(legal, logits, -BIG)`` BEFORE the greedy/top-p branch, so no
  sampled token is ever outside the DFA's legal set;
* state advance — ``table[state, tok]`` moves the per-row grammar state
  inside a multi-step decode scan without any host round-trip.

All live grammars co-tenant ONE device arena (`GrammarArena`): each grammar
occupies a contiguous span of global states (its local table shifted by a
base offset), row/state 0 is the reserved FREE state (all tokens legal,
self-loop) that unconstrained rows ride, and unallocated rows are all -1.
The arena is ONE traced operand of a fixed [S, V] shape, so one warm
program serves every grammar and every constrained/unconstrained mix with
zero post-warmup recompiles — grammar installs only bump `arena.version`,
which re-uploads the table (engine._gr_operand), never re-traces.

EOS semantics: at ACCEPTING byte-DFA states every eos token is legal as a
self-loop; everywhere else eos is illegal. A "terminal" state (accepting
with ONLY eos legal) therefore forces the model to emit EOS next — grammar
completion ends the stream through the ordinary EOS stop machinery and
lands in the goodput ledger as delivered, not overrun. The host-side
`GrammarSession` detects the terminal state one step earlier and lets the
server stop without spending that step.

Compile-time budgets are grammar-bomb defenses, not tuning knobs: a spec
whose DFA exceeds ``DLT_GRAMMAR_MAX_STATES`` (or whose body exceeds
``DLT_GRAMMAR_MAX_SPEC_KB``) raises `GrammarError` — a 400 client error,
never an engine failure (server/quarantine.py must NOT strike it).
"""

from __future__ import annotations

import dataclasses
import json
import os
from collections import OrderedDict

import numpy as np

__all__ = [
    "GrammarError",
    "CompiledGrammar",
    "GrammarCompiler",
    "GrammarArena",
    "GrammarSession",
    "schema_to_regex",
    "parse_response_format",
    "resolve_grammar_enabled",
]

#: the reserved all-legal self-loop state unconstrained rows ride
FREE_STATE = 0

#: env knobs (registered in server/api.py DLT_ENV_SURFACE + docs/SERVING.md)
ENV_GRAMMAR = "DLT_GRAMMAR"
ENV_CACHE_MB = "DLT_GRAMMAR_CACHE_MB"
ENV_MAX_STATES = "DLT_GRAMMAR_MAX_STATES"
ENV_ARENA_MB = "DLT_GRAMMAR_ARENA_MB"
ENV_MAX_SPEC_KB = "DLT_GRAMMAR_MAX_SPEC_KB"


def resolve_grammar_enabled(explicit: bool | None = None, default: str = "0") -> bool:
    """THE one resolver of the grammar-arena build flag: an explicit engine
    kwarg wins; otherwise ``DLT_GRAMMAR``; unset means `default` (library
    engines pass "0", the server entry point passes "1" — same pattern as
    the speculative/prefix-cache defaults). The arena is a build-time
    choice because its operands are part of every warm decode program."""
    if explicit is not None:
        return bool(explicit)
    raw = (os.environ.get(ENV_GRAMMAR) or "").strip().lower() or default
    return raw in ("1", "on", "true", "yes")


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def grammar_cache_mb() -> int:
    """Host compile-cache budget (MB) for `GrammarCompiler`."""
    return _env_int(ENV_CACHE_MB, 64)


def grammar_max_states() -> int:
    """Per-grammar byte-DFA state cap — the grammar-bomb defense."""
    return _env_int(ENV_MAX_STATES, 256)


def grammar_arena_mb() -> int:
    """Device mask-table budget (MB): arena rows = budget / (4 * vocab)."""
    return _env_int(ENV_ARENA_MB, 32)


def grammar_max_spec_kb() -> int:
    """`response_format` body size cap (KB)."""
    return _env_int(ENV_MAX_SPEC_KB, 64)


class GrammarError(ValueError):
    """A client-supplied grammar is malformed or over budget (HTTP 400)."""


def _fnv1a(data: bytes) -> int:
    h = 0xCBF29CE484222325
    for b in data:
        h ^= b
        h = (h * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h


# ---------------------------------------------------------------------------
# Regex subset -> AST
# ---------------------------------------------------------------------------
#
# Byte-level semantics: the pattern's UTF-8 bytes are the alphabet, `.`
# matches any byte except newline, classes hold single bytes (ranges and
# the \d \w \s escapes included), and matching is fully anchored —
# generation must produce a complete match, there is no unanchored search.

_DIGITS = frozenset(range(0x30, 0x3A))
_WORD = _DIGITS | frozenset(range(0x41, 0x5B)) | frozenset(range(0x61, 0x7B)) | {0x5F}
_SPACE = frozenset(b" \t\r\n\x0b\x0c")
_ANY = frozenset(range(256)) - {0x0A}
_ALL = frozenset(range(256))

#: hard caps on quantifier bounds and expanded AST size — a {1000}{1000}
#: nesting must die in the parser, not in subset construction
_MAX_REPEAT = 512
_MAX_ATOMS = 65536


class _Parser:
    def __init__(self, pattern: bytes):
        self.p = pattern
        self.i = 0
        self.atoms = 0

    def _atom_budget(self, n: int = 1):
        self.atoms += n
        if self.atoms > _MAX_ATOMS:
            raise GrammarError("regex expands past the atom budget")

    def error(self, msg: str) -> GrammarError:
        return GrammarError(f"regex: {msg} at byte {self.i}")

    def peek(self):
        return self.p[self.i] if self.i < len(self.p) else None

    def take(self):
        b = self.peek()
        if b is None:
            raise self.error("unexpected end of pattern")
        self.i += 1
        return b

    def parse(self):
        node = self.alt()
        if self.i != len(self.p):
            raise self.error("unbalanced ')'")
        return node

    def alt(self):
        branches = [self.seq()]
        while self.peek() == 0x7C:  # |
            self.i += 1
            branches.append(self.seq())
        return branches[0] if len(branches) == 1 else ("alt", branches)

    def seq(self):
        items = []
        while True:
            b = self.peek()
            if b is None or b in (0x7C, 0x29):  # | )
                break
            items.append(self.repeat())
        if not items:
            return ("seq", [])
        return items[0] if len(items) == 1 else ("seq", items)

    def repeat(self):
        node = self.atom()
        while True:
            b = self.peek()
            if b == 0x2A:  # *
                self.i += 1
                node = ("rep", node, 0, None)
            elif b == 0x2B:  # +
                self.i += 1
                node = ("rep", node, 1, None)
            elif b == 0x3F:  # ?
                self.i += 1
                node = ("rep", node, 0, 1)
            elif b == 0x7B:  # {
                save = self.i
                self.i += 1
                m, n = self._bounds()
                if m is None:  # a literal '{'
                    self.i = save
                    break
                node = ("rep", node, m, n)
            else:
                break
        return node

    def _bounds(self):
        num = b""
        while self.peek() is not None and 0x30 <= self.peek() <= 0x39:
            num += bytes([self.take()])
        if not num:
            return None, None
        m = int(num)
        n = m
        if self.peek() == 0x2C:  # ,
            self.i += 1
            num = b""
            while self.peek() is not None and 0x30 <= self.peek() <= 0x39:
                num += bytes([self.take()])
            n = int(num) if num else None
        if self.peek() != 0x7D:  # }
            return None, None
        self.i += 1
        if m > _MAX_REPEAT or (n is not None and (n > _MAX_REPEAT or n < m)):
            raise self.error(f"repeat bounds over the {_MAX_REPEAT} cap")
        return m, n

    def atom(self):
        b = self.take()
        if b == 0x28:  # (
            # non-capturing group syntax is accepted and ignored
            if self.p[self.i : self.i + 2] == b"?:":
                self.i += 2
            node = self.alt()
            if self.peek() != 0x29:
                raise self.error("unbalanced '('")
            self.i += 1
            return node
        if b == 0x5B:  # [
            return ("lit", self._cls())
        if b == 0x2E:  # .
            self._atom_budget()
            return ("lit", _ANY)
        if b == 0x5C:  # backslash
            self._atom_budget()
            return ("lit", self._escape(in_class=False))
        if b in (0x2A, 0x2B, 0x3F, 0x29):
            raise self.error(f"dangling {chr(b)!r}")
        self._atom_budget()
        return ("lit", frozenset({b}))

    def _escape(self, in_class: bool):
        b = self.take()
        if b == 0x64:  # d
            return _DIGITS
        if b == 0x44:  # D
            return _ALL - _DIGITS
        if b == 0x77:  # w
            return _WORD
        if b == 0x57:  # W
            return _ALL - _WORD
        if b == 0x73:  # s
            return _SPACE
        if b == 0x53:  # S
            return _ALL - _SPACE
        if b == 0x6E:  # n
            return frozenset({0x0A})
        if b == 0x74:  # t
            return frozenset({0x09})
        if b == 0x72:  # r
            return frozenset({0x0D})
        if b == 0x78:  # xHH
            hx = bytes([self.take(), self.take()])
            try:
                return frozenset({int(hx, 16)})
            except ValueError:
                raise self.error(f"bad \\x escape {hx!r}") from None
        return frozenset({b})  # any other byte: itself, escaped

    def _cls(self):
        negate = False
        if self.peek() == 0x5E:  # ^
            negate = True
            self.i += 1
        members: set[int] = set()
        first = True
        while True:
            b = self.peek()
            if b is None:
                raise self.error("unbalanced '['")
            if b == 0x5D and not first:  # ]
                self.i += 1
                break
            first = False
            self.i += 1
            if b == 0x5C:
                sub = self._escape(in_class=True)
                if len(sub) > 1 or self.peek() != 0x2D:
                    members |= sub
                    continue
                b = next(iter(sub))
            if self.peek() == 0x2D and self.p[self.i + 1 : self.i + 2] not in (b"", b"]"):
                self.i += 1
                hi = self.take()
                if hi == 0x5C:
                    sub = self._escape(in_class=True)
                    if len(sub) != 1:
                        raise self.error("multi-byte escape as range bound")
                    hi = next(iter(sub))
                if hi < b:
                    raise self.error("reversed class range")
                members |= set(range(b, hi + 1))
            else:
                members.add(b)
        self._atom_budget()
        return frozenset(_ALL - members if negate else members)


_REGEX_META = b"\\.[]{}()|*+?^$"


def regex_escape(text: bytes | str) -> str:
    """Escape a literal for embedding in the regex subset above."""
    if isinstance(text, str):
        text = text.encode("utf-8")
    out = []
    for b in text:
        if b in _REGEX_META:
            out.append("\\")
        out.append(chr(b) if 0x20 <= b < 0x7F else f"\\x{b:02x}")
    return "".join(out)


# ---------------------------------------------------------------------------
# AST -> Thompson NFA -> byte DFA
# ---------------------------------------------------------------------------


class _Nfa:
    def __init__(self, cap: int):
        self.eps: list[set[int]] = []
        self.edges: list[list[tuple[frozenset, int]]] = []
        self.cap = cap

    def state(self) -> int:
        if len(self.eps) >= self.cap:
            raise GrammarError("regex NFA exceeds the state budget")
        self.eps.append(set())
        self.edges.append([])
        return len(self.eps) - 1

    def build(self, node) -> tuple[int, int]:
        kind = node[0]
        if kind == "lit":
            s, a = self.state(), self.state()
            self.edges[s].append((node[1], a))
            return s, a
        if kind == "seq":
            s = a = self.state()
            for child in node[1]:
                cs, ca = self.build(child)
                self.eps[a].add(cs)
                a = ca
            return s, a
        if kind == "alt":
            s, a = self.state(), self.state()
            for child in node[1]:
                cs, ca = self.build(child)
                self.eps[s].add(cs)
                self.eps[ca].add(a)
            return s, a
        if kind == "rep":
            _, child, m, n = node
            s = a = self.state()
            for _i in range(m):
                cs, ca = self.build(child)
                self.eps[a].add(cs)
                a = ca
            if n is None:  # star tail
                cs, ca = self.build(child)
                self.eps[a].add(cs)
                self.eps[ca].add(cs)
                end = self.state()
                self.eps[a].add(end)
                self.eps[ca].add(end)
                return s, end
            for _i in range(n - m):  # bounded optional tail
                cs, ca = self.build(child)
                end = self.state()
                self.eps[a].add(cs)
                self.eps[a].add(end)
                self.eps[ca].add(end)
                a = end
            return s, a
        raise GrammarError(f"internal: unknown AST node {kind!r}")


def _compile_byte_dfa(pattern: str, max_states: int):
    """(trans_byte [S,256] int32 with -1 dead, accepting [S] bool); state 0
    is the start. Subset construction aborts past `max_states` — the
    grammar-bomb defense the request path relies on."""
    ast = _Parser(pattern.encode("utf-8")).parse()
    nfa = _Nfa(cap=max(4 * _MAX_ATOMS, 1024))
    start, accept = nfa.build(ast)

    def closure(seed):
        seen = set(seed)
        stack = list(seed)
        while stack:
            s = stack.pop()
            for t in nfa.eps[s]:
                if t not in seen:
                    seen.add(t)
                    stack.append(t)
        return frozenset(seen)

    start_c = closure({start})
    index = {start_c: 0}
    order = [start_c]
    rows, accepting = [], []
    i = 0
    while i < len(order):
        cur = order[i]
        row = np.full(256, -1, np.int32)
        move: dict[int, set] = {}
        for s in cur:
            for byteset, t in nfa.edges[s]:
                for b in byteset:
                    move.setdefault(b, set()).add(t)
        for b, tgts in move.items():
            c = closure(tgts)
            j = index.get(c)
            if j is None:
                if len(order) >= max_states:
                    raise GrammarError(
                        f"grammar DFA exceeds {max_states} states "
                        f"({ENV_MAX_STATES}) — simplify the pattern"
                    )
                j = len(order)
                index[c] = j
                order.append(c)
            row[b] = j
        rows.append(row)
        accepting.append(accept in cur)
        i += 1
    return np.stack(rows), np.fromiter(accepting, bool, count=len(accepting))


# ---------------------------------------------------------------------------
# JSON schema subset -> canonical regex
# ---------------------------------------------------------------------------

#: JSON string body: any non-quote/backslash byte (control bytes excluded —
#: json.loads rejects raw <0x20 in strings) or a backslash escape
_STR_BODY = '(?:[^"\\\\\\x00-\\x1f]|\\\\.)'
_INT = "-?(?:0|[1-9][0-9]*)"
_NUM = _INT + "(?:\\.[0-9]+)?(?:[eE][-+]?[0-9]+)?"

_MAX_SCHEMA_DEPTH = 8
#: explicit min/max bounds COUNT, and counting costs DFA states linearly —
#: these caps keep a bounded schema inside the state budget
_MAX_BOUND = 256


def schema_to_regex(schema, depth: int = 0) -> str:
    """Lower the supported JSON-schema subset to a canonical (no-whitespace)
    regex. Objects emit properties in DECLARED order, all required —
    a documented limit of the subset. Strings and arrays are UNBOUNDED by
    default (a star costs no DFA states); explicit min/max bounds count,
    and counting costs states linearly, so bounds are capped."""
    if depth > _MAX_SCHEMA_DEPTH:
        raise GrammarError(f"schema nests deeper than {_MAX_SCHEMA_DEPTH}")
    if not isinstance(schema, dict):
        raise GrammarError("schema must be a JSON object")
    if "enum" in schema:
        opts = schema["enum"]
        if not isinstance(opts, list) or not opts:
            raise GrammarError("enum must be a non-empty list")
        return (
            "(?:"
            + "|".join(
                regex_escape(json.dumps(v, separators=(",", ":"))) for v in opts
            )
            + ")"
        )
    if "const" in schema:
        return regex_escape(json.dumps(schema["const"], separators=(",", ":")))
    t = schema.get("type")
    if t == "string":
        if "minLength" not in schema and "maxLength" not in schema:
            return f'"{_STR_BODY}*"'
        lo = int(schema.get("minLength", 0))
        hi = schema.get("maxLength")
        if lo < 0 or lo > _MAX_BOUND or (
            hi is not None and (int(hi) < lo or int(hi) > _MAX_BOUND)
        ):
            raise GrammarError("string length bounds out of range")
        tail = f"{{{lo},{int(hi)}}}" if hi is not None else f"{{{lo},}}"
        return f'"{_STR_BODY}{tail}"'
    if t == "integer":
        return _INT
    if t == "number":
        return _NUM
    if t == "boolean":
        return "(?:true|false)"
    if t == "null":
        return "null"
    if t == "object":
        props = schema.get("properties", {})
        if not isinstance(props, dict):
            raise GrammarError("properties must be an object")
        parts = []
        for name, sub in props.items():
            parts.append(
                '"' + regex_escape(str(name)) + '":' + schema_to_regex(sub, depth + 1)
            )
        if not parts:
            return "\\{\\}"
        return "\\{" + ",".join(parts) + "\\}"
    if t == "array":
        item = schema_to_regex(schema.get("items", {"type": "integer"}), depth + 1)
        lo = int(schema.get("minItems", 0))
        hi = schema.get("maxItems")
        if lo < 0 or lo > _MAX_BOUND or (
            hi is not None and (int(hi) < lo or int(hi) > _MAX_BOUND)
        ):
            raise GrammarError("array item bounds out of range")
        if hi is not None and int(hi) == 0:
            return "\\[\\]"
        tail = (
            f"{{{max(lo - 1, 0)},{int(hi) - 1}}}" if hi is not None
            else (f"{{{lo - 1},}}" if lo > 1 else "*")
        )
        body = f"(?:{item}(?:,{item}){tail})"
        if lo == 0:
            body = body + "?"
        return "\\[" + body + "\\]"
    raise GrammarError(f"unsupported schema type {t!r}")


def parse_response_format(rf) -> tuple[str, str]:
    """Validate a request's `response_format` body -> ("regex"|"json_schema",
    canonical pattern). Raises GrammarError on anything malformed — the
    quarantine classifier treats that as a 400 client error, never a
    poison strike."""
    if not isinstance(rf, dict):
        raise GrammarError("response_format must be an object")
    body = json.dumps(rf, sort_keys=True)
    if len(body) > grammar_max_spec_kb() * 1024:
        raise GrammarError(
            f"response_format exceeds {ENV_MAX_SPEC_KB} "
            f"({grammar_max_spec_kb()} KB)"
        )
    kind = rf.get("type")
    if kind == "regex":
        pat = rf.get("regex", rf.get("pattern"))
        if not isinstance(pat, str) or not pat:
            raise GrammarError("response_format.regex must be a pattern string")
        return "regex", pat
    if kind == "json_schema":
        schema = rf.get("json_schema", rf.get("schema"))
        if isinstance(schema, dict) and "schema" in schema:
            schema = schema["schema"]  # OpenAI-style nesting
        if not isinstance(schema, dict):
            raise GrammarError("response_format.json_schema must carry a schema")
        return "json_schema", schema_to_regex(schema)
    raise GrammarError(
        f"unsupported response_format type {kind!r} (regex | json_schema)"
    )


# ---------------------------------------------------------------------------
# Token-level DFA
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class CompiledGrammar:
    """One grammar lowered to the tokenizer's vocabulary.

    `table` is the [n_states, vocab] int32 token DFA (-1 = illegal; the
    boolean mask row is `table[s] >= 0`), `trans_byte`/`accepting` the
    underlying byte DFA (kept for host-side full-match validation),
    `terminal` marks accepting states whose ONLY legal tokens are eos."""

    key: int
    kind: str
    pattern: str
    table: np.ndarray
    trans_byte: np.ndarray
    accepting: np.ndarray
    terminal: np.ndarray
    eos_ids: frozenset

    @property
    def n_states(self) -> int:
        return self.table.shape[0]

    @property
    def nbytes(self) -> int:
        return self.table.nbytes + self.trans_byte.nbytes

    def fullmatch(self, data: bytes) -> bool:
        """Host-side byte-DFA walk — the test/bench validity oracle."""
        s = 0
        for b in data:
            s = int(self.trans_byte[s, b])
            if s < 0:
                return False
        return bool(self.accepting[s])


class GrammarCompiler:
    """regex/JSON-schema -> CompiledGrammar over one tokenizer's vocab,
    with an FNV-keyed LRU compile cache budgeted by DLT_GRAMMAR_CACHE_MB.

    The vocab piece matrix is precomputed once; each compile is then a
    vectorized numpy walk (L steps of [S, V] advanced indexing, L = the
    longest piece) — no per-(state, token) Python loop."""

    def __init__(self, tokenizer, vocab_size: int | None = None):
        pieces = list(tokenizer.vocab)
        self.vocab_size = int(vocab_size or len(pieces))
        self.eos_ids = frozenset(
            int(e) for e in tokenizer.eos_token_ids if 0 <= int(e) < self.vocab_size
        )
        self.bos_id = int(getattr(tokenizer, "bos_id", -1))
        V = self.vocab_size
        L = max((len(p) for p in pieces[:V]), default=1) or 1
        self._piece_mat = np.zeros((V, L), np.int32)
        self._piece_len = np.zeros(V, np.int64)
        for t, p in enumerate(pieces[:V]):
            self._piece_len[t] = len(p)
            if p:
                self._piece_mat[t, : len(p)] = np.frombuffer(p, np.uint8)
        self._cache: OrderedDict[int, CompiledGrammar] = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    # -- cache -------------------------------------------------------------

    def compile_request(self, response_format) -> CompiledGrammar:
        kind, pattern = parse_response_format(response_format)
        return self.compile(kind, pattern)

    def compile(self, kind: str, pattern: str) -> CompiledGrammar:
        key = _fnv1a(f"{kind}:{pattern}".encode("utf-8"))
        hit = self._cache.get(key)
        if hit is not None:
            self._cache.move_to_end(key)
            self.hits += 1
            return hit
        self.misses += 1
        g = self._compile(key, kind, pattern)
        self._cache[key] = g
        self._bytes += g.nbytes
        budget = grammar_cache_mb() * (1 << 20)
        while self._bytes > budget and len(self._cache) > 1:
            _, old = self._cache.popitem(last=False)
            self._bytes -= old.nbytes
            self.evictions += 1
        return g

    def cache_stats(self) -> dict:
        return {
            "entries": len(self._cache),
            "bytes": self._bytes,
            "hits": self.hits,
            "misses": self.misses,
            "evictions": self.evictions,
        }

    # -- the lift ----------------------------------------------------------

    def _compile(self, key: int, kind: str, pattern: str) -> CompiledGrammar:
        trans_byte, accepting = _compile_byte_dfa(pattern, grammar_max_states())
        S, V = trans_byte.shape[0], self.vocab_size
        L = self._piece_mat.shape[1]
        st = np.broadcast_to(np.arange(S, dtype=np.int32)[:, None], (S, V)).copy()
        for step in range(L):
            live = (self._piece_len > step)[None, :] & (st >= 0)
            nxt = trans_byte[np.clip(st, 0, None), self._piece_mat[:, step][None, :]]
            st = np.where(live, np.where(st >= 0, nxt, -1), st)
        st[:, self._piece_len == 0] = -1  # model-vocab padding ids
        if 0 <= self.bos_id < V:
            st[:, self.bos_id] = -1  # bos never appears mid-stream
        ids = np.arange(S, dtype=np.int32)
        for e in self.eos_ids:
            st[:, e] = np.where(accepting, ids, -1)
        # every token-reachable state must keep >= 1 legal token, or a
        # constrained row would mask the entire vocabulary mid-generation
        legal_any = (st >= 0).any(axis=1)
        reach = np.zeros(S, bool)
        stack = [0]
        reach[0] = True
        while stack:
            s = stack.pop()
            if not legal_any[s]:
                raise GrammarError(
                    "grammar dead-ends: a reachable state has no legal "
                    "token under this vocabulary"
                )
            for t in np.unique(st[s][st[s] >= 0]):
                if not reach[t]:
                    reach[t] = True
                    stack.append(int(t))
        non_eos = st.copy()
        for e in self.eos_ids:
            non_eos[:, e] = -1
        terminal = accepting & ~(non_eos >= 0).any(axis=1)
        return CompiledGrammar(
            key=key, kind=kind, pattern=pattern, table=st,
            trans_byte=trans_byte, accepting=accepting, terminal=terminal,
            eos_ids=self.eos_ids,
        )


# ---------------------------------------------------------------------------
# Device arena
# ---------------------------------------------------------------------------


class GrammarArena:
    """All live grammars as ONE [S, V] int32 host table (uploaded to the
    device by engine._gr_operand when `version` moves). Row 0 is FREE
    (all-legal self-loop); grammars occupy contiguous spans of global
    states, their local tables shifted by the span base. Zero-ref spans
    stay installed (a warm reuse hit is free) until space is needed."""

    def __init__(self, vocab_size: int, n_states: int | None = None):
        if n_states is None:
            per_state = 4 * max(vocab_size, 1)
            n_states = (grammar_arena_mb() * (1 << 20)) // per_state
        self.n_states = int(max(64, min(65536, n_states)))
        self.vocab_size = int(vocab_size)
        self.table = np.full((self.n_states, self.vocab_size), -1, np.int32)
        self.table[FREE_STATE, :] = FREE_STATE
        self.version = 1
        #: key -> [base, size, refs]; insertion order is the LRU order
        self._spans: OrderedDict[int, list] = OrderedDict()

    def _gap(self, need: int) -> int | None:
        used = sorted((s[0], s[1]) for s in self._spans.values())
        prev_end = 1  # row 0 reserved for FREE
        for base, size in used:
            if base - prev_end >= need:
                return prev_end
            prev_end = base + size
        return prev_end if self.n_states - prev_end >= need else None

    def install(self, g: CompiledGrammar) -> int:
        span = self._spans.get(g.key)
        if span is not None:
            span[2] += 1
            self._spans.move_to_end(g.key)
            return span[0]
        need = g.n_states
        if need > self.n_states - 1:
            raise GrammarError(
                f"grammar needs {need} states; the device mask-table "
                f"arena holds {self.n_states - 1} ({ENV_ARENA_MB})"
            )
        base = self._gap(need)
        while base is None:
            evicted = False
            for key, (b, size, refs) in list(self._spans.items()):
                if refs == 0:
                    self.table[b : b + size, :] = -1
                    del self._spans[key]
                    evicted = True
                    break
            if not evicted:
                raise GrammarError(
                    "device mask-table arena exhausted by live grammars "
                    f"({ENV_ARENA_MB}) — retry later or raise the budget"
                )
            base = self._gap(need)
        self.table[base : base + need, :] = np.where(g.table >= 0, g.table + base, -1)
        self._spans[g.key] = [base, need, 1]
        self.version += 1
        return base

    def release(self, key: int):
        span = self._spans.get(key)
        if span is not None and span[2] > 0:
            span[2] -= 1

    def snapshot(self) -> dict:
        return {
            "n_states": self.n_states,
            "vocab": self.vocab_size,
            "bytes": int(self.table.nbytes),
            "version": self.version,
            "spans": len(self._spans),
            "live": sum(1 for s in self._spans.values() if s[2] > 0),
        }


# ---------------------------------------------------------------------------
# Host-side per-row tracking
# ---------------------------------------------------------------------------


class GrammarSession:
    """One request's authoritative grammar state, advanced from ACCEPTED
    tokens host-side (the in-graph carry is its traced mirror). Owns an
    arena span ref: close() releases it."""

    def __init__(self, arena: GrammarArena, grammar: CompiledGrammar):
        self.arena = arena
        self.grammar = grammar
        self.base = arena.install(grammar)
        self.state = 0
        self.done = False
        self.n_illegal = 0
        self._closed = False

    @property
    def row_state(self) -> int:
        """The global-state operand for this row (FREE once finished)."""
        return FREE_STATE if self.done else self.base + self.state

    @property
    def at_terminal(self) -> bool:
        """Only eos is legal here — the server may stop the stream now and
        count the last emitted token as delivered (EOS-equivalent stop)."""
        return (not self.done) and bool(self.grammar.terminal[self.state])

    def is_legal(self, tok: int) -> bool:
        if self.done:
            return True
        if not 0 <= tok < self.grammar.table.shape[1]:
            return False
        return int(self.grammar.table[self.state, tok]) >= 0

    def advance(self, tok: int) -> str:
        """'ok' | 'terminal' | 'eos' | 'done' | 'illegal'."""
        if self.done:
            return "done"
        g = self.grammar
        nxt = (
            int(g.table[self.state, tok])
            if 0 <= tok < g.table.shape[1]
            else -1
        )
        if nxt < 0:
            self.n_illegal += 1
            return "illegal"
        if tok in g.eos_ids:
            self.done = True
            return "eos"
        self.state = nxt
        return "terminal" if g.terminal[self.state] else "ok"

    def legal_prefix(self, tokens) -> int:
        """Length of the longest draft prefix every token of which is legal
        (stopping before any eos) — speculative drafts are pre-truncated to
        this so greedy longest-prefix acceptance can never admit an illegal
        token."""
        if self.done:
            return 0
        g = self.grammar
        s, n = self.state, 0
        for t in tokens:
            t = int(t)
            if t in g.eos_ids or not 0 <= t < g.table.shape[1]:
                break
            nxt = int(g.table[s, t])
            if nxt < 0:
                break
            s = nxt
            n += 1
        return n

    def verify_states(self, tokens) -> np.ndarray:
        """Global grammar states for the verify operand: position j is the
        state BEFORE feeding tokens[j] emits its logits — i.e. the state
        after walking tokens[:j]. Positions past an illegal/eos token ride
        FREE (their logits are beyond the acceptance horizon)."""
        out = np.zeros(len(tokens) + 1, np.int32)
        if self.done:
            return out
        g = self.grammar
        s = self.state
        out[0] = self.base + s
        for j, t in enumerate(tokens):
            t = int(t)
            if t in g.eos_ids or not 0 <= t < g.table.shape[1]:
                break
            nxt = int(g.table[s, t])
            if nxt < 0:
                break
            s = nxt
            out[j + 1] = self.base + s
        return out

    def close(self):
        if not self._closed:
            self._closed = True
            self.arena.release(self.grammar.key)
