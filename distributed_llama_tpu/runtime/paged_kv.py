"""Paged KV cache: fixed-size KV pages, per-row page tables, refcounted
zero-copy sharing, and copy-on-write — the vLLM/PagedAttention memory
discipline (Kwon et al. 2023) on top of the engine's trace-once programs.

The contiguous layout binds every batch row to a full ``seq_len`` KV slab:
a 64-token co-tenant pays the same HBM as a 32k-token one, and the radix
prefix cache (prefix_cache.py) can only reuse KV by *copying* bucket-length
slices in and out of that slab. This module replaces the slab with a
device-resident **page pool** — ``[L, n_pages, page_size, n_kv, head_dim]``
key/value tensors — plus a host-managed **page table** per batch row
(``int32 [max_slots]``, slot ``s`` naming the physical page holding logical
positions ``[s*page_size, (s+1)*page_size)``).

Device side, the forward pass changes in exactly two places
(models/transformer.py ``_layer``):

* **write**: new KV rows scatter to ``(page_table[row, pos // ps],
  pos % ps)`` — out-of-range positions (parked rows) remap to page indices
  past the pool and drop, the same OOB-scatter semantics the contiguous
  per-row path uses;
* **read**: attention gathers the first ``kv_len / ps`` page entries per
  row and reshapes them into the ``[b, kv_len, h, d]`` view the unchanged
  attention math consumes. Garbage in unallocated/foreign slots is causally
  masked exactly like contiguous junk past a row's length — which is why
  paged decode is token-identical to the contiguous arm.

Host side, :class:`PagePool` owns allocation: a free list, per-page
refcounts, and the page tables. Sharing is refcounting — a prefix-cache hit
maps the entry's pages into the new row's table (refs bumped, ZERO device
copies) — and writes demand exclusivity: before a dispatch writes span
``[a, b)`` of a row, :meth:`PagePool.ensure` replaces every overlapping
page whose refcount > 1 with a fresh page (**copy-on-write**). The old
page's content is device-copied (:func:`copy_page`, one jitted program)
only when the row still needs positions below ``a`` from it — a write
starting on the page boundary fully overwrites the page, so the copy is
skipped (allocate-on-write).

Exhaustion is a first-class signal: :class:`PagePoolExhausted` from an
allocation that found no free page (after the reclaim hook — prefix-cache
LRU eviction — made no progress). The Batcher parks admissions and sheds
load on it; library callers see the typed error.

The layout is no longer single-chip: on pure ``pp x tp`` shard_map
pipeline meshes the pool buffer shards like the contiguous cache (layers
over ``pp``, kv heads over ``tp`` — ``parallel.pipeline
.pp_paged_pool_sharding``) with the page axis REPLICATED, so page ids are
global and everything host-side here — free list, refcounts, tables,
prefix sharing — runs unchanged. Cross-boundary page movement (the
``gather_pages``/``scatter_pages`` shipping programs below) belongs to
the KV movement layer (runtime/kv_transport.py).

Every page-count mutation is under one lock (allocation decisions happen on
the engine's dispatch thread, but ``/stats`` snapshots and prefix-cache
retain/release may arrive from handler threads).
"""

from __future__ import annotations

import os
import threading
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..models.params import KVCache

#: default page size in token positions. 16 == prefix_cache.PREFIX_MIN_TOKENS:
#: every accepted prefix-cache resume boundary (a multiple of max_chunk, or a
#: power of two >= 16) is then page-aligned, so a hit shares WHOLE pages and
#: needs no partial-page copy.
DEFAULT_PAGE_SIZE = 16

KV_LAYOUTS = ("contiguous", "paged")


def resolve_kv_layout(explicit: str | None, default: str = "contiguous") -> str:
    """THE one resolver of the KV layout: an explicit value wins; otherwise
    ``DLT_KV_LAYOUT``; unset/unrecognized env means `default` (same parsing
    everywhere — engine constructor, CLI, server)."""
    layout = explicit
    if layout is None:
        raw = (os.environ.get("DLT_KV_LAYOUT") or "").strip().lower()
        layout = raw if raw in KV_LAYOUTS else default
    layout = layout.strip().lower()
    if layout not in KV_LAYOUTS:
        raise ValueError(f"unknown kv layout {layout!r} (choose from {KV_LAYOUTS})")
    return layout


def resolve_page_size(explicit: int | None = None) -> int:
    """Page size in tokens: explicit > ``DLT_KV_PAGE`` env > 16. Must be a
    power of two (bucket/boundary arithmetic relies on it)."""
    v = explicit
    if v is None:
        raw = os.environ.get("DLT_KV_PAGE")
        try:
            v = int(raw) if raw else 0
        except ValueError:
            v = 0
    v = int(v) if v else DEFAULT_PAGE_SIZE
    if v <= 0 or (v & (v - 1)) != 0:
        raise ValueError(f"kv page size must be a positive power of two, got {v}")
    return v


KV_DTYPES = ("bfloat16", "float32", "int8")


def resolve_kv_dtype(explicit: str | None = None) -> str | None:
    """THE one resolver of the KV storage dtype: an explicit value wins;
    otherwise ``DLT_KV_DTYPE``; unset means None — the engine then keeps
    its compute-dtype default (bf16 cache for bf16 compute, f32 for f32,
    models/config.config_from_header). ``"int8"`` selects the quantized
    arm (ops/kv_quant.py: int8 payload + f32 per-(token, head) scale
    sidecar); the float dtypes keep the pre-quantization programs
    bit-identical."""
    v = explicit
    if v is None:
        raw = (os.environ.get("DLT_KV_DTYPE") or "").strip()
        v = raw or None
    if v is None:
        return None
    v = v.strip().lower()
    if v == "bf16":
        v = "bfloat16"
    if v not in KV_DTYPES:
        raise ValueError(f"unknown kv dtype {v!r} (choose from {KV_DTYPES})")
    return v


def resolve_pool_pages(
    explicit_mb: int | None, page_bytes: int, parity_pages: int
) -> int:
    """Pool size in pages: an explicit MB budget (constructor arg >
    ``DLT_KV_POOL_MB`` env) wins; 0/unset means CONTIGUOUS PARITY — exactly
    the pages a ``batch x seq_len`` slab holds, so the default paged engine
    can never fit fewer tokens than the contiguous one."""
    mb = explicit_mb
    if mb is None:
        raw = os.environ.get("DLT_KV_POOL_MB")
        try:
            mb = int(raw) if raw else 0
        except ValueError:
            mb = 0
    if mb and mb > 0:
        return max(1, (int(mb) * 1024 * 1024) // max(page_bytes, 1))
    return parity_pages


def page_pool_bytes(cfg, n_pages: int, page_size: int) -> int:
    """Device bytes of a pool's k+v tensors (+ the f32 scale sidecars on the
    int8 arm — capacity math, /stats, and the cost model must all price the
    STORED width, including the 4 scale bytes per head_dim payload bytes)."""
    per_vector = cfg.head_dim * jnp.dtype(cfg.kv_dtype).itemsize
    if cfg.kv_quantized:
        per_vector += 4  # one f32 scale per (token, kv-head) vector
    return 2 * cfg.n_layers * n_pages * page_size * cfg.n_kv_heads * per_vector


def init_kv_pool(cfg, n_pages: int, page_size: int) -> KVCache:
    """The device page pool, riding the existing :class:`KVCache` pytree so
    every jit entry point's ``donate_argnames=("cache",)`` keeps working:
    ``k``/``v`` are ``[L, n_pages, page_size, n_kv, head_dim]``; the int8
    arm adds ``[L, n_pages, page_size, n_kv]`` f32 scale sidecars that page
    ops move with the SAME page indices as their payloads."""
    shape = (cfg.n_layers, n_pages, page_size, cfg.n_kv_heads, cfg.head_dim)
    k = jnp.zeros(shape, dtype=cfg.kv_dtype)
    v = jnp.zeros(shape, dtype=cfg.kv_dtype)
    if cfg.kv_quantized:
        return KVCache(
            k=k, v=v,
            k_scale=jnp.zeros(shape[:-1], jnp.float32),
            v_scale=jnp.zeros(shape[:-1], jnp.float32),
        )
    return KVCache(k=k, v=v)


# -- the jitted copy-on-write program ----------------------------------------


@partial(jax.jit, donate_argnames=("cache",), static_argnames=("out_sharding",))
def copy_page(cache: KVCache, src, dst, out_sharding=None) -> KVCache:
    """Copy one physical page's k/v (every layer) to another page — THE
    copy-on-write device program, one compiled shape per engine regardless
    of which pages move (`src`/`dst` are traced scalars). Donated cache:
    in-place in HBM; the host guarantees ``src != dst``. `out_sharding`:
    mesh-paged engines pin the pool's pp/tp layout in-program (the page
    moves within every shard locally — the slice keeps the layer and head
    axes whole, so no collective is traced; graph_audit asserts it)."""
    L, _, ps, h, d = cache.k.shape
    k_seg = jax.lax.dynamic_slice(cache.k, (0, src, 0, 0, 0), (L, 1, ps, h, d))
    v_seg = jax.lax.dynamic_slice(cache.v, (0, src, 0, 0, 0), (L, 1, ps, h, d))
    k = jax.lax.dynamic_update_slice(cache.k, k_seg, (0, dst, 0, 0, 0))
    v = jax.lax.dynamic_update_slice(cache.v, v_seg, (0, dst, 0, 0, 0))
    if out_sharding is not None:
        k = jax.lax.with_sharding_constraint(k, out_sharding)
        v = jax.lax.with_sharding_constraint(v, out_sharding)
    if cache.k_scale is None:
        return KVCache(k=k, v=v)
    # int8 arm: the scale sidecars move with the SAME page indices — a COW
    # copy that left scales behind would dequantize the moved payload with
    # the destination page's stale scales (int8 is single-chip, no sharding)
    ks_seg = jax.lax.dynamic_slice(cache.k_scale, (0, src, 0, 0), (L, 1, ps, h))
    vs_seg = jax.lax.dynamic_slice(cache.v_scale, (0, src, 0, 0), (L, 1, ps, h))
    return KVCache(
        k=k, v=v,
        k_scale=jax.lax.dynamic_update_slice(cache.k_scale, ks_seg, (0, dst, 0, 0)),
        v_scale=jax.lax.dynamic_update_slice(cache.v_scale, vs_seg, (0, dst, 0, 0)),
    )


# -- page movement programs (the KV movement layer, runtime/kv_transport.py) --
#
# Two bucketed programs move KV between the pool and a contiguous
# [L, n*ps, h, d] slice — the shape the prefix-extract programs, the disagg
# wire codec, and the device transport all share. Page-count operands are
# PADDED to the prefix-bucket ladder so the compiled-program count stays
# O(log seq_len): a gather pads with clamped page 0 (junk the caller slices
# off host-side), a scatter pads with indices past the pool (mode="drop" —
# the same OOB discipline the forward's paged write path uses). Both are
# collective-free slice/gather programs on every topology (audited).


@partial(jax.jit, static_argnames=("out_sharding",))
def gather_pages(cache: KVCache, pages, out_sharding=None):
    """Read the named pool pages into one contiguous [L, n*ps, h, d] k/v
    pair (the paged publish/ship path). `pages` is a traced int32 [n]
    vector — one compiled program per padded page count; entries past the
    real span are clamped to 0 and the caller discards their rows. NOT
    donated: the pool must survive."""
    pages = jnp.maximum(pages, 0)
    k = cache.k[:, pages]  # [L, n, ps, h, d]
    v = cache.v[:, pages]
    L, n, ps, h, d = k.shape
    if cache.k_scale is not None:
        # int8 pool: DEQUANT-ON-EXTRACT — the contiguous [L, n*ps, h, d]
        # slice every consumer of this shape shares (prefix segments, the
        # disagg wire codec, the device transport) stays a float tensor, so
        # cross-dtype peers interoperate for free; the insert path
        # (scatter_pages) re-quantizes, which is lossless after the first
        # quantization (ops/kv_quant.py idempotence note)
        from ..ops.kv_quant import dequantize_kv

        k = dequantize_kv(k, cache.k_scale[:, pages], jnp.float32)
        v = dequantize_kv(v, cache.v_scale[:, pages], jnp.float32)
    k = k.reshape(L, n * ps, h, d)
    v = v.reshape(L, n * ps, h, d)
    if out_sharding is not None:
        k = jax.lax.with_sharding_constraint(k, out_sharding)
        v = jax.lax.with_sharding_constraint(v, out_sharding)
    return k, v


@partial(jax.jit, donate_argnames=("cache",), static_argnames=("out_sharding",))
def scatter_pages(cache: KVCache, k_seg, v_seg, pages, out_sharding=None) -> KVCache:
    """Write a contiguous [L, n*ps, h, d] slice into the named pool pages
    (the paged external-insert path — KV computed in ANOTHER process lands
    in freshly allocated local pages). Pad entries carry indices past the
    pool and DROP; real indices are pairwise distinct by allocation.
    Donated cache: in-place in HBM."""
    L, n = cache.k.shape[0], pages.shape[0]
    ps, h, d = cache.k.shape[2], cache.k.shape[3], cache.k.shape[4]
    k_seg = k_seg.reshape(L, n, ps, h, d)
    v_seg = v_seg.reshape(L, n, ps, h, d)
    if cache.k_scale is not None:
        # int8 pool: QUANTIZE the float segment here — a bare .astype would
        # silently truncate bf16/f32 values into int8 garbage. The scale
        # sidecars scatter with the same indices (and the same drop mode:
        # a padded write that drops its payload must drop its scale too).
        from ..ops.kv_quant import quantize_kv

        k_seg, ks_seg = quantize_kv(k_seg)
        v_seg, vs_seg = quantize_kv(v_seg)
        k_scale = cache.k_scale.at[:, pages].set(
            ks_seg, mode="drop", unique_indices=True
        )
        v_scale = cache.v_scale.at[:, pages].set(
            vs_seg, mode="drop", unique_indices=True
        )
    else:
        k_seg = k_seg.astype(cache.k.dtype)
        v_seg = v_seg.astype(cache.v.dtype)
        k_scale = v_scale = None
    k = cache.k.at[:, pages].set(k_seg, mode="drop", unique_indices=True)
    v = cache.v.at[:, pages].set(v_seg, mode="drop", unique_indices=True)
    if out_sharding is not None:
        k = jax.lax.with_sharding_constraint(k, out_sharding)
        v = jax.lax.with_sharding_constraint(v, out_sharding)
    return KVCache(k=k, v=v, k_scale=k_scale, v_scale=v_scale)


# -- host-side pool ----------------------------------------------------------


class PagePoolExhausted(RuntimeError):
    """No free page and the reclaim hook made no progress. The Batcher
    parks/sheds on this; library callers size the pool or free rows."""


class PagePool:
    """Host-side page allocator + per-row page tables (module docstring).

    ``tables[row, slot]`` is the physical page holding the row's logical
    positions ``[slot*ps, (slot+1)*ps)``, or -1 (unmapped). ``version``
    bumps on every table mutation so the engine can cache the device copy
    of the tables between dispatches."""

    def __init__(
        self,
        n_pages: int,
        page_size: int,
        n_rows: int,
        seq_len: int,
        stats=None,
        reclaim=None,  # () -> bool: try to free pages (prefix-cache LRU
        # eviction); True = progress was made, retry the allocation
        page_bytes: int = 0,  # device bytes per page incl. scale sidecars
        # (page_pool_bytes(cfg, 1, ps)) — /stats capacity truthing
        kv_dtype: str | None = None,  # storage dtype label for /stats
    ):
        if n_pages <= 0:
            raise ValueError("page pool needs at least one page")
        self.n_pages = int(n_pages)
        self.page_size = int(page_size)
        self.page_bytes = int(page_bytes)
        self.kv_dtype = kv_dtype
        self.n_rows = int(n_rows)
        self.seq_len = int(seq_len)
        self.max_slots = -(-seq_len // page_size)  # ceil
        self.stats = stats
        self.reclaim = reclaim
        self.refs = np.zeros(self.n_pages, np.int32)
        self._free: list = list(range(self.n_pages - 1, -1, -1))
        self.tables = np.full((self.n_rows, self.max_slots), -1, np.int32)
        self.version = 0
        self._lock = threading.Lock()

    # -- observability -------------------------------------------------------

    def _incr(self, name: str, n: int = 1):
        if self.stats is not None:
            self.stats.incr(name, n)

    def _gauges(self):
        if self.stats is not None:
            self.stats.gauge("kv_pool_pages_used", self.n_pages - len(self._free))
            self.stats.gauge("kv_pool_pages_free", len(self._free))

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def used_pages(self) -> int:
        return self.n_pages - len(self._free)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "n_pages": self.n_pages,
                "page_size": self.page_size,
                "used_pages": self.used_pages,
                "free_pages": self.free_pages,
                "max_slots": self.max_slots,
                "shared_pages": int(np.sum(self.refs > 1)),
                # capacity truthing: STORED bytes (int8 payload + f32 scale
                # sidecars on the quantized arm), so equal-MB budgets show
                # their real token capacity — ~2x pages under int8
                "kv_dtype": self.kv_dtype,
                "page_bytes": self.page_bytes,
                "pool_bytes": self.page_bytes * self.n_pages,
                "used_bytes": self.page_bytes * self.used_pages,
                "tokens_capacity": self.n_pages * self.page_size,
            }

    # -- allocation ----------------------------------------------------------

    def ensure(self, row: int, start: int, end: int) -> list:
        """Make span ``[start, end)`` of `row` privately writable: allocate
        unmapped slots, copy-on-write shared ones. Returns the
        ``[(src_page, dst_page), ...]`` device copies the caller must
        dispatch (:func:`copy_page`) BEFORE the write — non-empty only when
        a shared page holds positions below `start` the row still needs.

        ATOMIC per span: the whole plan is applied under one lock hold only
        when every needed page is available, otherwise nothing mutates and
        :class:`PagePoolExhausted` raises (after the reclaim hook stops
        making progress). A partial application would be a real corruption:
        slot remapped, refcount dropped, but the COW copy never dispatched
        because the caller saw the exception — the retry would then see a
        private page and silently skip the copy."""
        if end <= start:
            return []
        end = min(end, self.seq_len)
        ps = self.page_size
        while True:
            with self._lock:
                plan = []  # (slot, cur_page_or_-1)
                for slot in range(start // ps, -(-end // ps)):
                    cur = int(self.tables[row, slot])
                    if cur < 0 or int(self.refs[cur]) > 1:
                        plan.append((slot, cur))
                if not plan:
                    return []
                if len(self._free) >= len(plan):
                    cow: list = []
                    for slot, cur in plan:
                        page = self._free.pop()
                        self.refs[page] = 1
                        if cur >= 0:
                            # copy-on-write: this row loses its claim on
                            # the shared page; content is copied only when
                            # the write starts mid-page (positions below
                            # `start` must survive). A shared page keeps
                            # refs >= 1 here, so it can't join the free
                            # list mid-plan.
                            self.refs[cur] -= 1
                            if self.refs[cur] == 0:
                                self._free.append(cur)
                            if slot * ps < start:
                                cow.append((cur, page))
                                self._incr("kv_cow_copies")
                            self._incr("kv_cow_pages")
                        self.tables[row, slot] = page
                    self.version += 1
                    self._gauges()
                    return cow
            # not enough pages for the WHOLE span: reclaim outside the
            # lock and re-plan (tables untouched so far)
            if self.reclaim is None or not self.reclaim():
                self._incr("kv_pool_exhausted")
                raise PagePoolExhausted(
                    f"kv page pool exhausted ({self.n_pages} pages of "
                    f"{self.page_size} tokens)"
                )
            self._incr("kv_pool_reclaims")

    def allocate_pages(self, n: int) -> tuple:
        """Take `n` free pages off the free list with refs=1, bound to NO
        row — the external-insert path (runtime/kv_transport.py): shipped
        KV scatters into them and a prefix-cache entry retains them, so
        they live exactly as long as the entry (release() frees them).
        Retries through the reclaim hook under pressure; raises
        :class:`PagePoolExhausted` when nothing frees."""
        if n <= 0:
            return ()
        while True:
            with self._lock:
                if len(self._free) >= n:
                    out = []
                    for _ in range(n):
                        page = self._free.pop()
                        self.refs[page] = 1
                        out.append(page)
                    self._gauges()
                    return tuple(out)
            if self.reclaim is None or not self.reclaim():
                self._incr("kv_pool_exhausted")
                raise PagePoolExhausted(
                    f"kv page pool exhausted ({self.n_pages} pages of "
                    f"{self.page_size} tokens)"
                )
            self._incr("kv_pool_reclaims")

    def share(self, row: int, pages) -> None:
        """Map `pages` (physical ids) into the row's leading slots with
        refcounts bumped — the ZERO-COPY prefix-cache splice. Existing
        mappings in those slots are released (retain-before-release so a
        self-share is safe)."""
        pages = list(pages)
        if len(pages) > self.max_slots:
            raise ValueError("shared prefix longer than the row's table")
        with self._lock:
            for p in pages:
                self.refs[p] += 1
            for slot, p in enumerate(pages):
                cur = int(self.tables[row, slot])
                if cur >= 0:
                    self.refs[cur] -= 1
                    if self.refs[cur] == 0:
                        self._free.append(cur)
                self.tables[row, slot] = p
            self.version += 1
            self._incr("kv_pages_shared", len(pages))
            self._gauges()

    def row_holds_pages(self, row: int) -> bool:
        """Whether any slot of `row` is mapped — the Batcher's park-vs-shed
        test: a parked admission only waits when SOMEONE ELSE holds pages
        that can eventually free (waiting on co-tenants that hold nothing
        is a livelock)."""
        with self._lock:
            return bool((self.tables[row] >= 0).any())

    def row_pages(self, row: int, n_slots: int):
        """The row's first `n_slots` physical pages (publish path). Raises
        when any slot is unmapped — the caller's length accounting is off."""
        with self._lock:
            pages = [int(p) for p in self.tables[row, :n_slots]]
        if any(p < 0 for p in pages):
            raise ValueError(
                f"row {row} has unmapped slots below {n_slots * self.page_size}"
            )
        return tuple(pages)

    def retain(self, pages) -> None:
        """Pin `pages` (prefix-cache entry publish): refs bumped, pages
        survive every row release until the entry releases them."""
        with self._lock:
            for p in pages:
                self.refs[p] += 1

    def release(self, pages) -> None:
        """Drop one reference per page (entry eviction / clear)."""
        with self._lock:
            for p in pages:
                self.refs[p] -= 1
                if self.refs[p] == 0:
                    self._free.append(p)
                elif self.refs[p] < 0:  # double release — keep it visible
                    self.refs[p] = 0
                    self._incr("kv_pool_double_release")
            self._gauges()

    def release_row(self, row: int) -> None:
        """Unmap the whole row (park/finish/reset): every mapped page loses
        the row's reference; shared pages survive via their other holders."""
        with self._lock:
            for slot in range(self.max_slots):
                cur = int(self.tables[row, slot])
                if cur >= 0:
                    self.refs[cur] -= 1
                    if self.refs[cur] == 0:
                        self._free.append(cur)
                    self.tables[row, slot] = -1
            self.version += 1
            self._gauges()

    def release_all_rows(self) -> None:
        for r in range(self.n_rows):
            self.release_row(r)

    def device_tables(self) -> np.ndarray:
        """The gather/scatter operand: raw tables with -1 sentinels for
        unmapped slots. The device write path DROPS writes whose entry is
        negative (so a padded tail or allocation bug can never land through
        a stale sentinel into someone else's page), and the read path clamps
        to 0 (the garbage it gathers is causally masked)."""
        with self._lock:
            return self.tables.copy()
