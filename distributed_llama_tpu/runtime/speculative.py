"""Speculative decoding: pluggable draft sources + bucketed greedy verify.

Plain decode pays one device dispatch per generated token (amortized by the
chunked decode loop, but still one forward per token of FLOPs *serialized on
the token chain*). Speculative decoding (Leviathan et al. 2023) breaks the
chain: a cheap DRAFT source proposes k tokens, one prefill-shaped VERIFY
forward scores all k+1 positions at once, and greedy acceptance keeps the
longest prefix of drafts matching the model's own argmax chain — so one
dispatch can land up to k+1 tokens, and a wrong draft costs only the
discarded tail of a forward that ran anyway.

Two draft sources:

* :class:`NGramDraft` — prompt-lookup decoding (Saxena 2023): the context's
  own longest suffix n-gram is matched against earlier context, and the
  tokens that followed the match are proposed. Zero extra FLOPs, no second
  model — and because greedy decode loves to fall into repetition (and real
  serving traffic loves to quote its own prompt: code edits, RAG answers,
  multi-turn chat), acceptance is high exactly where decode spends the most
  tokens. This is the default source; it also runs on the tiny CPU test
  configs, which is what makes the whole subsystem tier-1-testable.
* :class:`ModelDraft` — a second, smaller :class:`InferenceEngine` drafting
  autoregressively (the classic two-model split). The draft engine keeps
  its own KV cache loosely synced to the accepted context (common-prefix
  resync, then one greedy decode chunk of exactly k steps).

Correctness (why greedy outputs are bit-identical to plain decode):

* the verify forward feeds ``[last_token, d1..dk]`` at positions
  ``pos..pos+k`` with ``logits_mode="all"`` — position j's logits are
  computed from exactly the same (written-this-forward) KV a plain decode
  step at position j would see, so its argmax IS the plain-decode token;
* acceptance only ever emits tokens that equal that argmax chain: the
  accepted drafts by the match test, and the bonus token (the first
  mismatch position's argmax) by construction. Rejected drafts' KV needs no
  rollback: positions past the accepted boundary are rewritten by a later
  round's feed before any query reads them — the same write-before-read
  invariant padded prefill tails and parked batch rows already rely on
  (models/transformer.py OOB-scatter notes);
* speculation applies to GREEDY requests only (temperature 0). Sampled
  rows keep the plain chunked path — accepting drafts under a sampler
  would change the RNG stream, and the per-row threefry chains' stream
  stability is a documented serving contract.

Programs: draft lengths are bucketed at k ∈ {4, 8} (``spec_buckets``), so
the verify ladder adds O(|buckets| · log seq_len) compiled programs — the
``("verify"/"verify_row", k+1, kv_bucket)`` entries of
``InferenceEngine.warm_plan()``. The verify program is donate-safe, carries
the same per-topology collective budget as a prefill chunk of the same
size (analysis/graph_audit.py enforces both), and fuses the greedy argmax
in-graph so one round costs one dispatch plus one [b, k+1] int fetch.

Configuration: ``DLT_SPECULATIVE`` ∈ {off, ngram, model} /
``--speculative`` with ``--draft-k`` (and ``--draft-model`` for the model
source). Server + CLI default to ngram/k=4; library engines default off.
Observability: ``spec_rounds`` / ``spec_draft_tokens`` /
``spec_accepted_tokens`` / ``spec_rejected_tokens`` counters and the
``spec_acceptance_rate`` gauge in StepStats (the `/stats` ``speculative``
section; counters ride `/health` too), plus ``engine.last_spec_timing``.
"""

from __future__ import annotations

import os
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..models.transformer import forward_uncompiled
from ..ops.sampling import apply_grammar_mask
from .tracing import TRACER, to_us

SPEC_MODES = ("off", "ngram", "model")

#: power-of-two draft-length buckets: every verify program's draft capacity
#: is one of these, so the compiled-program count stays O(|buckets|), not
#: O(draft lengths seen)
DRAFT_BUCKETS = (4, 8)


def resolve_spec_mode(explicit: str | None, default: str = "off") -> str | None:
    """THE one resolver of the speculative mode: an explicit value wins;
    otherwise ``DLT_SPECULATIVE``; an unset or unrecognized env value means
    `default` (library engines pass "off", the CLI/server entry points pass
    "ngram" — same parsing everywhere, only the intended default differs).
    Returns None for "off" so callers can truth-test the mode."""
    mode = explicit
    if mode is None:
        raw = (os.environ.get("DLT_SPECULATIVE") or "").strip().lower()
        mode = raw if raw in SPEC_MODES else default
    mode = mode.strip().lower()
    if mode not in SPEC_MODES:
        raise ValueError(
            f"unknown speculative mode {mode!r} (choose from {SPEC_MODES})"
        )
    return None if mode == "off" else mode


def resolve_draft_k(explicit: int | None = None) -> int:
    """Max drafted tokens per verify round: explicit > ``DLT_DRAFT_K`` env >
    4. Snapped to the available buckets by :func:`spec_buckets`."""
    if explicit is not None and explicit > 0:
        return int(explicit)
    raw = os.environ.get("DLT_DRAFT_K")
    try:
        v = int(raw) if raw else 0
    except ValueError:
        v = 0
    return v if v > 0 else 4


def spec_buckets(draft_k: int) -> tuple:
    """The draft buckets a ``draft_k`` budget enables, ascending — always at
    least the smallest bucket (a draft budget below 4 still buys one)."""
    out = tuple(b for b in DRAFT_BUCKETS if b <= draft_k)
    return out or (DRAFT_BUCKETS[0],)


# -- the verify program ------------------------------------------------------


@partial(
    jax.jit,
    static_argnames=("cfg", "kv_len", "page_size"),
    donate_argnames=("cache",),
)
def verify_chunk(
    cfg, params, rope, cache, tokens, pos_start, kv_len=None,
    page_table=None, page_size=None, grammar_table=None, grammar_state=None,
):
    """One verify forward: a prefill-shaped pass over ``[last_token,
    d1..dk]`` returning logits at EVERY position (``logits_mode="all"``)
    plus their in-graph greedy argmax, so a verify round costs one dispatch
    and one small int fetch (through the driver tunnel every extra
    host-issued device op is a round trip). ``pos_start`` may be a scalar
    (solo: all rows aligned) or a [b] vector (per-row positions — the
    generate_batch / BatchSession verify). The cache is donated: the k+1
    KV writes land in place, exactly like a prefill chunk's.

    Grammar operands (a grammar-capable engine ALWAYS threads them so the
    warm program is shared): ``grammar_state`` is [b, t] int32 — position
    j's global DFA state after walking the accepted feed prefix — and the
    argmax chain is taken over the MASKED logits, so greedy acceptance can
    never admit a token outside the grammar's legal set (the bonus token
    included). Free/parked rows and positions past the acceptance horizon
    ride the all-legal FREE state, where masking is the identity.

    Returns (greedy_ids [b, t] int32, logits [b, t, vocab] f32, cache)."""
    logits, cache = forward_uncompiled(
        cfg, params, rope, cache, tokens, pos_start, logits_mode="all",
        kv_len=kv_len, page_table=page_table, page_size=page_size,
    )
    logits = apply_grammar_mask(logits, grammar_table, grammar_state)
    return jnp.argmax(logits, axis=-1).astype(jnp.int32), logits, cache


def accept_greedy(drafts, greedy_ids) -> int:
    """Longest-prefix greedy acceptance: the number of leading drafts that
    match the verify forward's own argmax chain. ``greedy_ids[a]`` for the
    returned ``a`` is then the bonus token (the model's actual next token at
    the first mismatch — or after the whole accepted draft), so every round
    emits ``a + 1`` tokens of the exact plain-decode chain."""
    a = 0
    for d in drafts:
        if int(greedy_ids[a]) != int(d):
            break
        a += 1
    return a


def note_round(stats, n_drafted: int, n_accepted: int) -> None:
    """Record one verify round's acceptance telemetry: the four spec_*
    counters plus the cumulative ``spec_acceptance_rate`` gauge (accepted /
    drafted over the engine's lifetime — the number the bench and `/stats`
    report)."""
    stats.incr("spec_rounds")
    stats.incr("spec_draft_tokens", n_drafted)
    stats.incr("spec_accepted_tokens", n_accepted)
    stats.incr("spec_rejected_tokens", n_drafted - n_accepted)
    c = stats.counters_snapshot()
    drafted = c.get("spec_draft_tokens", 0)
    if drafted:
        stats.gauge(
            "spec_acceptance_rate",
            round(c.get("spec_accepted_tokens", 0) / drafted, 4),
        )


def choose_bucket(buckets, dmax: int) -> int:
    """Smallest draft bucket covering `dmax` drafted tokens (the largest
    bucket when none does — callers have already truncated)."""
    return next((k for k in buckets if k >= dmax), buckets[-1])


def verify_row_round(
    engine, drafts: dict, token, pos, seq_len: int, grammars=None
) -> dict:
    """ONE per-row verify round — the shared core of
    `BatchSession.spec_step` and `InferenceEngine._decode_batch_speculative`
    (a fix to feed assembly, bucketing, guard keys, or acceptance must land
    exactly once). `drafts` maps row -> proposed tokens (empty list =
    bonus-token-only row); `token`/`pos` are row-indexable current
    token/position state; rows absent from `drafts` are parked at
    `seq_len` (writes dropped, no progress).

    `grammars` (row-indexable, entries None or GrammarSession) composes
    structured decoding with speculation: each constrained row's drafts are
    pre-truncated to their grammar-legal prefix, and the verify dispatch
    carries a [b, K+1] per-position state operand so the argmax chain —
    bonus token included — is taken over MASKED logits. A grammar-hostile
    draft stream thus shows acceptance collapse, never an illegal emission.

    Assembles the [b, K+1] feed, dispatches the ("verify_row", K+1,
    kv-bucket) program under the sanitizer scope + watchdog, fetches the
    greedy ids, and returns {row: emitted tokens} after per-row
    longest-prefix acceptance (telemetry recorded here: note_round +
    the spec_verify[K] latency series). Callers advance their own
    position/token state from the returned rows."""
    rows = sorted(drafts)

    def _sess(r):
        return grammars[r] if grammars is not None else None

    clean = {r: [int(t) for t in drafts[r]] for r in rows}
    for r in rows:
        g = _sess(r)
        if g is not None:
            clean[r] = clean[r][: g.legal_prefix(clean[r])]
    dmax = max(len(clean[r]) for r in rows)
    K = choose_bucket(engine.spec_buckets, dmax)
    clean = {r: clean[r][:K] for r in rows}
    size = K + 1
    toks = np.zeros((engine.batch, size), np.int32)
    pv = np.full((engine.batch,), seq_len, np.int32)
    for r in rows:
        toks[r, 0] = int(token[r])
        dr = clean[r]
        toks[r, 1 : 1 + len(dr)] = dr
        pv[r] = int(pos[r])
    gr_states = None
    if getattr(engine, "grammar", None) is not None and any(
        _sess(r) is not None for r in rows
    ):
        gr_states = np.zeros((engine.batch, size), np.int32)
        for r in rows:
            g = _sess(r)
            if g is not None:
                vs = g.verify_states(clean[r])
                gr_states[r, : len(vs)] = vs
    kvb = engine._kv_bucket(min(int(max(pv[r] for r in rows)) + size, seq_len))
    t0 = time.perf_counter()
    with engine._sanitizer_scope():
        with engine._guard(f"verify_row[{K}]", ("verify_row", size, kvb)):
            ids_dev, _ = engine._dispatch_verify(toks, pv, kvb, gr_states=gr_states)
            ids = engine._host_fetch(ids_dev)
    engine.stats.record(f"spec_verify[{K}]", (time.perf_counter() - t0) * 1e6)
    # one engine-level event per verify round (per-row acceptance spans are
    # emitted by the caller, which owns the row -> request mapping)
    TRACER.event(
        "verify_row", to_us(t0), int((time.perf_counter() - t0) * 1e6),
        ("rows", "bucket"), (len(rows), K),
    )
    out = {}
    for r in rows:
        a = accept_greedy(clean[r], ids[r])
        note_round(engine.stats, len(clean[r]), a)
        out[r] = clean[r][:a] + [int(ids[r, a])]
    return out


def spec_snapshot(engine) -> dict | None:
    """The `/stats` ``speculative`` section: configuration plus the
    acceptance counters, one self-contained dict (None when speculation is
    off)."""
    if engine.spec_mode is None:
        return None
    c = engine.stats.counters_snapshot()
    drafted = c.get("spec_draft_tokens", 0)
    accepted = c.get("spec_accepted_tokens", 0)
    return {
        "mode": engine.spec_mode,
        "draft_k": engine.draft_k,
        "buckets": list(engine.spec_buckets),
        "rounds": c.get("spec_rounds", 0),
        "draft_tokens": drafted,
        "accepted_tokens": accepted,
        "rejected_tokens": c.get("spec_rejected_tokens", 0),
        "acceptance_rate": round(accepted / drafted, 4) if drafted else None,
    }


# -- draft sources -----------------------------------------------------------


class DraftSource:
    """A proposer of likely next tokens. ``draft(ctx, k)`` returns up to
    ``k`` tokens it expects the model to emit after ``ctx`` (the live
    accepted context: prompt + generated so far); an empty list means "no
    idea", and the caller falls back to a plain decode chunk for the round.
    Implementations must be cheap relative to a verify forward and must
    never dispatch work that blocks the caller beyond their own fetches.

    Stateless sources (NGramDraft) are trivially safe to share across the
    per-row calls of a batched verify round; stateful ones (ModelDraft
    keeps a synced KV cache) document their own granularity."""

    name = "base"

    def draft(self, ctx: list, k: int) -> list:
        raise NotImplementedError

    def warmup(self) -> None:
        """Compile anything the source will dispatch while serving (called
        from ``InferenceEngine.warmup()`` BEFORE the recompile sentinel
        seals, so a model-backed source's programs count as warm)."""

    def close(self) -> None:
        pass


class NGramDraft(DraftSource):
    """Prompt-lookup drafting (Saxena 2023): match the context's own suffix
    n-gram against earlier context and propose the tokens that followed the
    most recent match. Longest n wins (``max_n`` down to ``min_n``); a
    match whose continuation runs into the context edge proposes however
    many tokens remain (< k is fine — the verify bucket pads). Pure host
    arithmetic over the token list: zero device work, zero FLOPs."""

    name = "ngram"

    def __init__(self, max_n: int = 4, min_n: int = 1):
        if min_n < 1 or max_n < min_n:
            raise ValueError(f"bad n-gram range [{min_n}, {max_n}]")
        self.max_n = max_n
        self.min_n = min_n

    def draft(self, ctx: list, k: int) -> list:
        L = len(ctx)
        if k <= 0 or L < self.min_n + 1:
            return []
        arr = np.asarray(ctx, dtype=np.int64)  # dlt: allow(host-sync) — host token list, no device source
        for n in range(min(self.max_n, L - 1), self.min_n - 1, -1):
            pat = arr[L - n :]
            # windows start at 0..L-n; the last one IS the suffix — exclude
            windows = np.lib.stride_tricks.sliding_window_view(arr, n)[:-1]
            hits = np.nonzero((windows == pat).all(axis=1))[0]
            if hits.size:
                i = int(hits[-1])  # most recent earlier occurrence
                cont = ctx[i + n : i + n + k]
                if cont:
                    return [int(t) for t in cont]
        return []


class ModelDraft(DraftSource):
    """A second (smaller) engine drafting autoregressively. The draft
    engine's KV cache tracks the accepted context by common-prefix resync:
    each ``draft`` call prefills whatever suffix of ``ctx[:-1]`` the draft
    cache does not already hold (rejected speculation shows up as a
    shortened common prefix and is simply re-fed — the draft cache rides
    the same write-before-read invariant as the main one), then runs ONE
    greedy decode chunk of exactly ``k`` steps and returns its tokens.

    Sized for the solo path: per-row calls from a batched verify round are
    correct but resync-thrash the single draft cache — batched serving
    should prefer the ngram source. The draft engine is warmed (its own
    full warm ladder) from ``warmup()`` so the recompile sentinel's
    zero-post-warmup-compile contract covers its programs too."""

    name = "model"

    def __init__(self, engine, owns: bool = True):
        if engine.batch != 1:
            raise ValueError("draft engines run batch=1 (one drafted chain)")
        self.engine = engine
        self._owns = owns
        self._synced: list = []  # tokens whose KV the draft cache holds

    def draft(self, ctx: list, k: int) -> list:
        eng = self.engine
        L = len(ctx)
        if k <= 0 or L == 0:
            return []
        # snap the chunk to the draft engine's warm decode ladder (powers
        # of two up to decode_chunk_size): batched callers cap k at odd
        # budget remainders, and dispatching a raw n_steps=3 would compile
        # an off-ladder program mid-serving (a post-warmup recompile)
        n = 1
        while n < k:
            n *= 2
        n = min(n, eng.decode_chunk_size)
        # the chunk writes draft KV at positions L-1 .. L-2+n — all must
        # stay inside the DRAFT model's context window
        if L + n > eng.cfg.seq_len:
            return []
        pre = [int(t) for t in ctx[:-1]]
        cp = 0
        lim = min(len(self._synced), len(pre))
        while cp < lim and self._synced[cp] == pre[cp]:
            cp += 1
        if len(pre) > cp:
            eng.prefill(pre[cp:], pos_start=cp, publish=False)
        pos = L - 1
        kvb = eng._kv_bucket(pos + n)
        # typed greedy key: the draft engine's warm ladder compiles decode
        # with _greedy_prng_key's aval — a legacy PRNGKey(0) operand here
        # would be a different key dtype and a post-seal recompile on the
        # first model-draft round
        from .engine import _greedy_prng_key

        if eng.paged:
            # a paged draft engine (ambient DLT_KV_LAYOUT=paged) must map
            # pages for the chunk's KV writes like any other decode site —
            # unmapped slots DROP writes silently, which here would mean
            # drafting against a cache missing the very tokens _synced
            # claims it holds
            eng._ensure_pages_all_rows(pos, pos + n)
        with eng._sanitizer_scope(), eng._guard(
            f"draft_decode[{n}]", ("decode", n, kvb)
        ):
            toks, _, eng.cache, _ = eng._decode_chunk_any(
                jnp.full((1,), int(ctx[-1]), jnp.int32), jnp.int32(pos),
                _greedy_prng_key(), n_steps=n, temperature=0.0, topp=0.9,
                kv_len=kvb,
            )
            out = [int(t) for t in eng._host_fetch(toks)[0]]
        # the chunk fed ctx[-1] and the first n-1 drafts: their KV is in
        # the draft cache now; the n-th draft was sampled but never fed.
        # Return only what the caller asked for — the surplus still synced.
        self._synced = [int(t) for t in ctx] + out[:-1]
        return out[:k]

    def warmup(self) -> None:
        self.engine.warmup()
        self._synced = []

    def close(self) -> None:
        if self._owns:
            self.engine.close()


def build_draft_source(mode: str | None, draft_source=None) -> DraftSource | None:
    """Engine-side factory: an explicit source wins (any mode); otherwise
    ngram builds its default and model REQUIRES one (a second engine cannot
    be conjured from thin air — the CLI builds it from ``--draft-model``)."""
    if mode is None:
        return None
    if draft_source is not None:
        return draft_source
    if mode == "ngram":
        return NGramDraft()
    raise ValueError(
        "speculative='model' requires a draft_source (a ModelDraft wrapping "
        "the smaller engine; the CLI builds one from --draft-model)"
    )
