"""Continuous batching: rolling admission over parkable batch rows.

The fixed-round Batcher design admits requests only at round boundaries: a
request arriving mid-round waits for the whole in-flight round, and a short
request co-batched with a long one holds its slot idle until the round
drains. The reference has no analogue at all — its API serves strictly
serially (reference: src/dllama-api.cpp:571-576) and its only concurrency is
gateway replica-DP (src/dllama-gateway.cpp:266-301).

This module is the engine-side machinery that makes admission a per-chunk
decision instead:

* every batch row is an independent SLOT with its own position, last token,
  sampling settings, and RNG chain;
* a free slot can be (re)filled between decode chunks: the newcomer's prompt
  is prefilled into its row — on the single-chip path via a row-sliced
  single-sequence forward (full speed: flash attention, scalar positions; the
  other rows' cache is untouched), on mesh paths via the per-row-position
  pipeline forward with every other row parked at pos seq_len (their cache
  writes are dropped by the OOB scatter, models/transformer.py);
* admission can be INTERLEAVED: `begin_admit` stages the prompt and
  `prefill_pending(row, budget)` advances it a bounded number of tokens at a
  time, so a long prompt's prefill slots between decode chunks instead of
  stalling every co-batched stream for the whole prompt (Sarathi-Serve's
  chunked-prefill piggyback; the server's Batcher drives this);
* admission consults the engine's radix PREFIX CACHE
  (runtime/prefix_cache.py): `begin_admit` longest-prefix-matches the
  staged prompt and pins the entry; the first `prefill_pending` splices the
  cached KV into the row with one donate-safe copy and resumes chunked
  prefill from the bucket boundary; arming (and row retirement, via
  `publish_row`) publishes the row's KV back for the next request;
* `step(n)` decodes n tokens for ALL slots in one on-device chunk with
  per-row positions, per-row threefry key chains, and per-row
  temperature/top-p vectors (ops/sampling.py sample_logits_per_row) — so
  requests with different sampling settings, including explicitly seeded
  ones, share a chunk; a row's sampled stream depends only on its own seed
  and step count, never on its co-tenants;
* a finished row is parked (pos = seq_len): it keeps riding the chunk for
  shape stability, its writes drop, its tokens are discarded host-side.
"""

from __future__ import annotations

import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..models.params import KVCache
from ..models.transformer import forward_uncompiled
from ..ops.sampling import sample_logits_per_row, split_row_keys
from .tracing import to_us


@partial(
    jax.jit,
    static_argnames=("cfg", "n_steps", "kv_len", "page_size"),
    donate_argnames=("cache",),
)
def batch_decode_chunk(
    cfg,
    params,
    rope,
    cache,
    token: jnp.ndarray,  # [b] int32
    pos: jnp.ndarray,  # [b] int32 per-row positions (seq_len = parked)
    keys: jnp.ndarray,  # [b, 2] uint32 per-row threefry key states
    temperature: jnp.ndarray,  # [b] f32 (<= 0 = greedy row)
    topp: jnp.ndarray,  # [b] f32
    n_steps: int = 16,
    kv_len: int | None = None,
    page_table: jnp.ndarray | None = None,  # paged KV layout (paged_kv.py)
    page_size: int | None = None,
    grammar_table: jnp.ndarray | None = None,  # [S, vocab] int32 grammar
    # arena (runtime/grammar.py); constrained rows carry their global DFA
    # state, unconstrained rows ride the all-legal FREE state 0
    grammar_state: jnp.ndarray | None = None,  # [b] int32
):
    """n_steps decode iterations with everything per-row and TRACED — one
    compiled program per (batch, n_steps, kv_len) serves any mix of
    greedy/sampled/seeded rows (and, with grammar operands, any mix of
    constrained/unconstrained rows). Returns (tokens [b, n_steps], cache,
    keys) — plus the final grammar states when the operands are threaded."""

    def step(carry, _):
        token, pos, cache, keys, gstate = carry
        logits, cache = forward_uncompiled(
            cfg, params, rope, cache, token[:, None], pos,
            logits_mode="last", kv_len=kv_len,
            page_table=page_table, page_size=page_size,
        )
        keys, subs = split_row_keys(keys)
        nxt = sample_logits_per_row(
            logits, subs, temperature, topp,
            grammar_table=grammar_table, grammar_state=gstate,
        )
        if gstate is not None:
            adv = grammar_table[gstate, nxt]
            gstate = jnp.where(adv < 0, gstate, adv)
        return (nxt, pos + 1, cache, keys, gstate), nxt

    (_, _, cache, keys, gout), toks = jax.lax.scan(
        step, (token, pos, cache, keys, grammar_state), None, length=n_steps
    )
    toks = jnp.transpose(toks, (1, 0))
    if grammar_state is not None:
        return toks, cache, keys, gout
    return toks, cache, keys


@partial(jax.jit, static_argnames=("cfg", "kv_len"), donate_argnames=("cache",))
def prefill_row(
    cfg,
    params,
    rope,
    cache,
    tokens: jnp.ndarray,  # [1, t] int32 — one prompt chunk
    pos_start,  # scalar int32
    row,  # scalar int32 — which batch row receives the chunk
    kv_len: int | None = None,
):
    """Prefill one row of a batched cache through the ordinary
    single-sequence forward: slice the row's cache (batch axis 1 of the
    [L, b, S, h, d] stack), run the b=1 forward at SCALAR positions (the
    fast path — flash attention, bucketed reads), write the row back. The
    slice+unslice moves one cache row (~tens of MB), negligible next to the
    prefill itself; the alternative — pushing the whole batch through with
    b-1 parked rows — multiplies the prefill matmul FLOPs by the batch."""
    k_row = jax.lax.dynamic_slice_in_dim(cache.k, row, 1, axis=1)
    v_row = jax.lax.dynamic_slice_in_dim(cache.v, row, 1, axis=1)
    row_cache = KVCache(k=k_row, v=v_row)
    if cache.k_scale is not None:
        # int8 arm: the row's scale sidecars slice/unslice with the payload
        row_cache = KVCache(
            k=k_row, v=v_row,
            k_scale=jax.lax.dynamic_slice_in_dim(cache.k_scale, row, 1, axis=1),
            v_scale=jax.lax.dynamic_slice_in_dim(cache.v_scale, row, 1, axis=1),
        )
    _, rc = forward_uncompiled(
        cfg, params, rope, row_cache, tokens, pos_start,
        logits_mode="last", kv_len=kv_len,
    )
    k = jax.lax.dynamic_update_slice_in_dim(cache.k, rc.k, row, axis=1)
    v = jax.lax.dynamic_update_slice_in_dim(cache.v, rc.v, row, axis=1)
    if cache.k_scale is None:
        return KVCache(k=k, v=v)
    return KVCache(
        k=k, v=v,
        k_scale=jax.lax.dynamic_update_slice_in_dim(
            cache.k_scale, rc.k_scale, row, axis=1
        ),
        v_scale=jax.lax.dynamic_update_slice_in_dim(
            cache.v_scale, rc.v_scale, row, axis=1
        ),
    )


class BatchSession:
    """Host-side slot state for one continuously-batched engine.

    Not thread-safe — the server's Batcher worker owns it. All device work
    happens in `admit` (prefill) and `step` (decode chunk); between calls
    the device is idle and admission decisions are free.
    """

    def __init__(self, engine):
        self.engine = engine
        b = engine.batch
        self.seq_len = engine.cfg.seq_len
        self.pos = np.full((b,), self.seq_len, np.int32)  # parked
        self.token = np.zeros((b,), np.int32)
        self.active = np.zeros((b,), bool)
        self.temp = np.zeros((b,), np.float32)
        self.topp = np.full((b,), 0.9, np.float32)
        self.keys = np.zeros((b, 2), np.uint32)
        # per-row GrammarSession (runtime/grammar.py) or None; the session
        # object is SHARED with the request owner (the Batcher advances it
        # per accepted token), this list only feeds the device state operand
        self.grammars: list = [None] * b
        self._admits = 0  # distinguishes unseeded admissions' default keys
        # rows mid-admission: prompt + prefill progress, armed on completion
        # (begin_admit / prefill_pending — the Batcher's interleaved path)
        self._pending: dict[int, dict] = {}
        engine.reset()

    def free_rows(self) -> list[int]:
        return [
            r
            for r in range(len(self.active))
            if not self.active[r] and r not in self._pending
        ]

    def active_rows(self) -> list[int]:
        return [r for r in range(len(self.active)) if self.active[r]]

    def pending_rows(self) -> list[int]:
        """Rows whose admission prefill is staged/in progress (begin_admit
        called, not yet armed), in STAGING order — the Batcher advances the
        earliest-staged admission first, so a later arrival can't preempt an
        in-flight prefill and grow its TTFT."""
        return list(self._pending)

    def pending_resume(self, row: int) -> int:
        """Prefix-cache resume boundary of `row`'s staged admission (tokens
        the splice will cover; 0 = cold). The Batcher reads this into the
        request's goodput ledger at admission time."""
        st = self._pending.get(row)
        return 0 if st is None else int(st["resume"])

    def admit(
        self,
        row: int,
        prompt_tokens: list[int],
        temperature: float = 0.0,
        topp: float = 0.9,
        key_data=None,  # (hi, lo) uint32 pair; None derives from the row+pos
        trace=None,
        grammar=None,
    ) -> None:
        """Prefill `prompt_tokens[:-1]` into `row` and arm the slot in one
        call (begin_admit + an unbounded prefill_pending). The row starts
        decoding on the next `step` call — admission latency is one prefill
        plus at most one in-flight chunk boundary."""
        self.begin_admit(
            row, prompt_tokens, temperature, topp, key_data, trace,
            grammar=grammar,
        )
        self.prefill_pending(row)

    def begin_admit(
        self,
        row: int,
        prompt_tokens: list[int],
        temperature: float = 0.0,
        topp: float = 0.9,
        key_data=None,
        trace=None,  # runtime/tracing.py Trace for this request (None = untraced):
        # admission-prefill chunks and the splice emit span events into it
        grammar=None,  # GrammarSession constraining this row (None = free)
    ) -> None:
        """Stage an admission without running its prefill: the prompt then
        advances in bounded chunks via `prefill_pending`, scheduled by the
        caller BETWEEN decode chunks (the Batcher interleaves one prefill
        chunk per chunk boundary, so co-batched decode streams see a bounded
        per-token latency bump instead of a whole-prompt stall — the
        Sarathi-style chunked-prefill piggyback). The row stays parked
        (inactive, junk-stepping) until its prefill completes and the slot
        arms itself."""
        n = len(prompt_tokens)
        if n == 0:
            raise ValueError("empty prompt")
        if n >= self.seq_len:
            raise ValueError(
                f"prompt ({n} tokens) exceeds the context window ({self.seq_len})"
            )
        if self.active[row]:
            raise ValueError(f"row {row} is still active")
        if row in self._pending:
            raise ValueError(f"row {row} already has a pending admission")
        if key_data is None:
            # unseeded: a fresh chain per admission (deterministic within a
            # session, distinct across re-used rows, numbered in ARRIVAL
            # order so interleaved and stall-free admissions draw the same
            # streams). Seeded callers pass key_data derived from the seed
            # alone, so the stream reproduces regardless of which row/chunks
            # it lands in.
            self._admits += 1
            key_data = (
                np.uint32(0x9E3779B9),
                np.uint32((self._admits * 2654435761) & 0xFFFFFFFF),
            )
        # prefix-cache lookup at STAGING time (host-only): the matched entry
        # is PINNED (refcounted) so LRU eviction cannot drop it before the
        # splice dispatches — prefill_pending runs the copy at the first
        # chunk boundary this row gets (device work stays out of
        # begin_admit, per the class contract).
        resume, entry = 0, None
        eng = self.engine
        if eng.prefix_cache is not None and not eng._in_warmup:
            t_match = time.perf_counter()
            resume, entry = eng.prefix_cache.match_for_splice(prompt_tokens[:-1])
            if trace is not None:
                trace.event(
                    "prefix_match", to_us(t_match),
                    int((time.perf_counter() - t_match) * 1e6),
                    ("resume_tokens", "row"), (resume, row),
                )
        if grammar is not None and self.engine.grammar is None:
            raise ValueError("this engine was built without a grammar arena")
        self._pending[row] = {
            "tokens": list(prompt_tokens),
            "done": 0,  # prefilled prefix length within tokens[:-1]
            "temperature": temperature,
            "topp": topp,
            "key_data": key_data,
            "grammar": grammar,
            "resume": resume,  # chunk-bucket-aligned prefix-cache boundary
            "entry": entry,  # pinned PrefixEntry to splice, or None
            "trace": trace,
            # pre-bound per-chunk emitter: admission prefill advances one
            # chunk per call below — a tuple append each, nothing more
            "em_chunk": None if trace is None else trace.bind(
                "prefill_chunk", ("size", "row")
            ),
        }

    def prefill_pending(self, row: int, max_tokens: int | None = None) -> int:
        """Advance `row`'s staged prompt prefill by up to `max_tokens` tokens
        (None = to completion); returns the prefill tokens still remaining.
        Chunks follow the same padded power-of-two ladder as `admit` (same
        compiled shapes — an interleaved admission warms nothing new), each
        dispatched with its operands in ONE host->device transfer. When the
        last chunk lands the slot arms exactly as `admit` would have."""
        eng = self.engine
        st = self._pending[row]
        pre = st["tokens"][:-1]
        budget = len(pre) if max_tokens is None else max_tokens
        from .engine import chunk_plan

        # admission prefill is part of the Batcher's hot path too: the
        # chunk loop is dispatch-only (completion is observed by the next
        # step fetch), so under DLT_SANITIZERS=1 nothing in here may
        # implicitly sync device->host
        with eng._sanitizer_scope():
            entry = st.pop("entry", None)
            if entry is not None:
                # prefix-cache splice: ONE donate-safe copy writes the
                # cached KV into this row at positions [0, entry.length);
                # chunked prefill then resumes from the bucket boundary.
                # Positions in [resume, entry.length) may belong to a
                # diverged sibling prompt — the chunks below rewrite every
                # position >= resume before any query reads it (the parked-
                # row write-before-read invariant).
                t_splice = time.perf_counter()
                try:
                    if eng.paged:
                        # zero-copy: the entry's pages map into this row's
                        # table host-side (no device dispatch, no guard)
                        eng.prefix_cache.share_row(eng, entry, row, st["resume"])
                    else:
                        with eng._guard(
                            f"prefix_copy_row[{entry.length}]",
                            ("prefix_copy_row", entry.length, entry.length),
                        ):
                            eng.cache = eng.prefix_cache.splice_row(eng, entry, row)
                finally:
                    # ALWAYS unpin — a watchdog StallError out of the guard
                    # must not leave the entry pinned (unevictable) forever
                    eng.prefix_cache.entry_release(entry)
                eng.prefix_cache.record_hit(st["resume"])
                if st["trace"] is not None:
                    st["trace"].event(
                        "prefix_splice", to_us(t_splice),
                        int((time.perf_counter() - t_splice) * 1e6),
                        ("tokens", "row"), (st["resume"], row),
                    )
                st["done"] = min(st["resume"], len(pre))
            em_chunk = st["em_chunk"]
            while st["done"] < len(pre) and budget > 0:
                done = st["done"]
                t_chunk = time.perf_counter()
                # plan against the REMAINING BUDGET too, so a budget below
                # max_chunk is honored exactly (the chunk's bucket may pad
                # past an odd budget, but its real tokens never exceed it)
                # instead of overshooting by up to a whole max_chunk chunk
                _, size, n_real = next(
                    iter(
                        chunk_plan(
                            min(len(pre) - done, budget), done, eng.max_chunk,
                            self.seq_len,
                        )
                    )
                )
                chunk = pre[done : done + n_real] + [0] * (size - n_real)
                kv_len = eng._kv_bucket(done + size)
                # dispatch through the ONE owner of the admission-prefill
                # chunk program (engine._dispatch_prefill_row: pipeline /
                # paged / contiguous-row arms — warmup's ladder fill and
                # the session must compile the same shapes), under a
                # watchdog keyed on THIS chunk's full (size, kv_bucket)
                # pair — the same keys warmup's ladder fill seeds. A
                # prefix-cache resume at a deeper position can make an
                # intermediate bucket a genuine first compile; keying
                # anything coarser would run it under the narrow stall
                # threshold and trip a false EXEC_STALL
                with eng._guard(
                    f"prefill_row[{size}|kv{kv_len}]",
                    ("prefill_row", size, kv_len),
                ):
                    eng._dispatch_prefill_row(row, chunk, done, kv_len)
                if em_chunk is not None:
                    # dispatch wall of this admission-prefill chunk (the
                    # dispatch is async; completion is observed by the next
                    # step fetch, same semantics as the solo prefill spans)
                    em_chunk(
                        to_us(t_chunk),
                        int((time.perf_counter() - t_chunk) * 1e6), n_real, row,
                    )
                st["done"] = done + n_real
                budget -= n_real

        remaining = len(pre) - st["done"]
        if remaining <= 0:
            tokens = st["tokens"]
            self.pos[row] = len(tokens) - 1
            self.token[row] = tokens[-1]
            self.temp[row] = st["temperature"]
            self.topp[row] = st["topp"]
            self.keys[row] = np.asarray(st["key_data"], np.uint32)  # dlt: allow(host-sync) — host tuple, no device source
            self.grammars[row] = st["grammar"]
            self.active[row] = True
            del self._pending[row]
            if eng.prefix_cache is not None and not eng._in_warmup:
                # publish this prompt's KV at arming (one extract copy): a
                # burst of shared-prefix admissions then hits from the
                # SECOND request on, without waiting for the first to finish
                with eng._sanitizer_scope():
                    eng.prefix_cache.publish_from_row(eng, row, pre)
            return 0
        return remaining

    def release(self, row: int) -> None:
        """Park the row: its cache writes drop from the next chunk on, so
        the slot can be re-admitted later without disturbing anyone. Also
        drops any staged admission mid-prefill (its partial KV is junk past
        every live row's view, same as any parked interval) — unpinning the
        prefix-cache entry a never-spliced admission still holds. Paged
        engines release the row's page mappings here: pages shared with
        prefix-cache entries survive via the entry's own refs, everything
        else returns to the pool (the refcount-release-on-finish contract)."""
        self.active[row] = False
        self.pos[row] = self.seq_len
        self.temp[row] = 0.0  # greedy is the cheap sampling path for junk
        self.grammars[row] = None  # the session's OWNER closes it
        st = self._pending.pop(row, None)
        if st is not None and st.get("entry") is not None:
            self.engine.prefix_cache.entry_release(st["entry"])
        if self.engine.paged:
            self.engine.page_pool.release_row(row)
            self.engine._pt_cache = None

    def publish_row(self, row: int, tokens: list) -> None:
        """Publish the first `len(tokens) - 1` tokens' KV of `row` into the
        engine's prefix cache (no-op when disabled). The Batcher calls this
        at row retirement with prompt + delivered tokens: every position
        below the cap was FED during a decode chunk, so its KV is final.
        The -1 cap drops the last token, whose slot is unwritten when it
        was the final sample of the row's final chunk."""
        eng = self.engine
        if eng.prefix_cache is None or eng._in_warmup or len(tokens) < 2:
            return
        with eng._sanitizer_scope():
            eng.prefix_cache.publish_from_row(
                eng, row, list(tokens), max_len=len(tokens) - 1
            )

    def spec_step(self, drafts: dict) -> dict:
        """One speculative verify round (runtime/speculative.py) for the
        rows named in `drafts` (row -> proposed tokens; an EMPTY list is
        valid — the row still advances by its one greedy bonus token).
        Rows absent from `drafts` — parked, prefilling, or sampled — are
        parked for the round: fed at pos seq_len, writes dropped, no
        progress. All named rows must be active and GREEDY (speculation
        never advances a sampled row: accepting drafts would change its
        stream, and this round does not consume the per-row key chains —
        greedy rows never draw from them).

        One verify dispatch + one [b, k+1] int fetch serves every row:
        per-row acceptance keeps each row's longest draft prefix matching
        its own argmax chain plus the bonus token, so rows advance
        UNEVENLY (1..k+1 positions). Returns {row: emitted tokens}.
        Rejected drafts' KV needs no rollback — positions past a row's
        accepted boundary are rewritten before any query reads them (the
        parked-row write-before-read invariant)."""
        eng = self.engine
        if eng.spec_mode is None or not eng.device_decode:
            raise ValueError("speculative decoding is not enabled on this engine")
        rows = sorted(drafts)
        if not rows:
            return {}
        for r in rows:
            if not self.active[r]:
                raise ValueError(f"row {r} is not active")
            if self.temp[r] > 0.0:
                raise ValueError(f"row {r} is sampled; speculation is greedy-only")
        from .speculative import choose_bucket, verify_row_round

        K = choose_bucket(eng.spec_buckets, max(len(drafts[r]) for r in rows))
        ends = [int(self.pos[r]) + K + 1 for r in rows]
        if max(ends) > self.seq_len:
            # mirror step()'s overrun guard: silently-dropped writes would
            # hand back junk tokens instead of an error. The Batcher only
            # takes the spec path when every decode row has K+1 headroom.
            raise ValueError(
                f"verify round would overrun seq_len={self.seq_len}: "
                f"max row end {max(ends)} (draft bucket {K})"
            )
        out = verify_row_round(
            eng, drafts, self.token, self.pos, self.seq_len,
            grammars=self.grammars,
        )
        for r, emitted in out.items():
            self.pos[r] += len(emitted)
            self.token[r] = emitted[-1]
        return out

    def step(self, n_steps: int) -> np.ndarray:
        """One decode chunk for every slot; returns host tokens [b, n_steps]
        (junk in parked rows). Advances every row's position by n_steps."""
        eng = self.engine
        ends = [int(self.pos[r]) + 1 + n_steps for r in self.active_rows()]
        if ends and max(ends) > self.seq_len:
            # without this, an overrunning caller would get silently-dropped
            # cache writes (the parked-row OOB-scatter semantics) and junk
            # tokens instead of an error — the Batcher clamps its chunks to
            # seq_len headroom, but a direct API caller must hear about it
            raise ValueError(
                f"decode chunk would overrun seq_len={self.seq_len}: "
                f"max row end {max(ends)} (step n_steps={n_steps})"
            )
        kv_len = eng._kv_bucket(min(max(ends, default=1), self.seq_len))
        t_chunk = time.perf_counter()
        if eng.paged:
            # paged layout: every live row needs private pages over its
            # chunk span BEFORE the dispatch (PagePoolExhausted surfaces
            # here — the Batcher's park/shed path; parked rows write
            # nothing and need nothing)
            eng._ensure_pages(
                (r, int(self.pos[r]), int(self.pos[r]) + n_steps)
                for r in self.active_rows()
            )
        # the sanitizer scope covers the Batcher's production decode path
        # exactly like the solo loops: the ONLY device->host syncs allowed
        # in here are the two _host_fetch calls below (DLT_SANITIZERS=1)
        with eng._sanitizer_scope():
            token = jnp.asarray(self.token)
            pos = jnp.asarray(self.pos)
            keys = jnp.asarray(self.keys)
            temp = jnp.asarray(self.temp)
            topp = jnp.asarray(self.topp)
            if eng.use_pipeline:
                from ..parallel.pipeline import pipeline_batch_decode_chunk

                toks, eng.cache, keys = pipeline_batch_decode_chunk(
                    eng.cfg, eng.mesh, eng.params, eng.rope, eng.cache,
                    token, pos, keys, temp, topp, n_steps=n_steps, kv_len=kv_len,
                    page_table=eng._pt_operand() if eng.paged else None,
                    page_size=eng.page_size,
                )
            elif eng.grammar is not None:
                # grammar-capable engine: the SAME warm program serves
                # constrained and free rows — the state vector (FREE 0 for
                # unconstrained rows) is just another small operand. The
                # in-graph final states are discarded: the host sessions
                # are authoritative and re-advance from the fetched tokens
                # before the next step is dispatched.
                gr_state = jnp.asarray(
                    np.fromiter(
                        (g.row_state if g is not None else 0 for g in self.grammars),
                        np.int32,
                        count=len(self.grammars),
                    )
                )
                toks, eng.cache, keys, _ = batch_decode_chunk(
                    eng.cfg, eng.params, eng.rope, eng.cache,
                    token, pos, keys, temp, topp, n_steps=n_steps, kv_len=kv_len,
                    page_table=eng._pt_operand() if eng.paged else None,
                    page_size=eng.page_size,
                    grammar_table=eng._gr_operand(), grammar_state=gr_state,
                )
            else:
                toks, eng.cache, keys = batch_decode_chunk(
                    eng.cfg, eng.params, eng.rope, eng.cache,
                    token, pos, keys, temp, topp, n_steps=n_steps, kv_len=kv_len,
                    page_table=eng._pt_operand() if eng.paged else None,
                    page_size=eng.page_size,
                )
            # the fetch is the batch path's one blocking device call —
            # watchdog it like the solo decode path, so a wedged device
            # raises StallError into the Batcher loop (reset + bounded
            # client retry) instead of hanging every co-batched request
            with eng._guard(
                f"batch_decode[{n_steps}]", ("batch_decode", n_steps, kv_len)
            ):
                host = eng._host_fetch(toks)
            # .copy(): the fetched view of a device array is READ-ONLY, and
            # admit writes rows into these between chunks
            self.keys = eng._host_fetch(keys).copy()
        # whole-chunk wall (dispatch + fetch): the batched serving path's
        # per-program series — /stats latency numbers and the roofline join
        # (profiling.roofline_view) read it exactly like solo decode[n]
        eng.stats.record(
            f"batch_decode[{n_steps}]", (time.perf_counter() - t_chunk) * 1e6
        )
        self.pos += n_steps
        # parked rows stay pinned at seq_len (a long-lived session must not
        # creep their positions toward int32 range)
        self.pos[~self.active] = self.seq_len
        self.token = host[:, -1].copy()
        return host
