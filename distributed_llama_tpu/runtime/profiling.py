"""Device-performance observability: warm-ladder cost model, HBM ledger,
live roofline/MFU gauges, and on-demand profiler capture.

PR 6 made the *request* path observable (trace IDs, flight recorder,
Prometheus); this module makes the *device* observable. Four pieces:

* **Warm-ladder cost model** — every program `engine.warm_plan()` names is
  traced AND lowered+compiled AOT (abstract params/cache, so nothing is
  baked or duplicated): ``memory_analysis()`` supplies the per-dispatch
  argument/output/temp/alias bytes, XLA's ``cost_analysis()`` rides along
  raw, and the headline per-dispatch FLOPs / HBM bytes come from a
  trip-count-aware census of the traced jaxpr (XLA counts every scan body
  exactly once — measured — which would undercount a 64-step decode chunk
  64x; see the census block below). One per-(kind, size, kv-bucket) table,
  served at ``GET /debug/costs``, printed by ``graph_audit --costs``, and
  audited for 100% ladder coverage — a new program kind that lands on the
  warm ladder without a cost entry fails the audit, so the table can never
  silently drift from the ladder.
* **HBM ledger** — modeled per-component device-memory accounting (Q40
  weights, rope tables, KV cache, prefix-cache entries, draft engine),
  reconciled against ``device.memory_stats()`` where the backend provides
  it (TPU/GPU; XLA:CPU returns None and the measured side is skipped).
  Exported as ``dlt_hbm_bytes{component=...}`` gauges plus a headroom
  gauge; growth of the measured-minus-modeled residual beyond
  ``DLT_HBM_DRIFT_MB`` bumps the ``hbm_drift_events`` counter — a leak
  detector for anything the model doesn't know about.
* **Live roofline / MFU** — the cost table joined with the per-program
  chunk walls StepStats already records (``decode[n]``,
  ``batch_decode[n]``, ``spec_verify[k]``) yields achieved GB/s and
  FLOP/s per program and the aggregate ``dlt_mfu`` /
  ``dlt_bw_utilization`` / ``dlt_device_duty_cycle`` gauges on
  ``/metrics`` — the bench's roofline arithmetic as a first-class live
  metric. SLO attainment (``dlt_slo_ttft_attainment`` /
  ``dlt_slo_tpot_attainment``) is derived from the PR 6 cumulative
  TTFT/TPOT histograms against ``DLT_SLO_TTFT_MS`` / ``DLT_SLO_TPOT_MS``.
* **On-demand capture** — ``GET /debug/profile?ms=...`` wraps
  ``jax.profiler.trace`` around live serving for a bounded window
  (single-flight; concurrent captures get 409) and returns the trace
  directory + the perfetto ``.trace.json.gz`` path.

Measurement honesty notes:

* The joined walls are HOST chunk-boundary walls — the same numbers the
  bench's roofline headline uses. In steady state a decode chunk's wall is
  its device compute (the lookahead hides dispatch/fetch); when the tunnel
  round trip dominates (tiny models), achieved GB/s is honestly *lower*
  than the kernel rate, exactly as the bench reports it. Prefill
  *dispatch* walls are asynchronous (the device runs behind them) and are
  deliberately NOT joined.
* Per-series joins use the **p50 of the recent window**, so warmup's
  compile walls (which land in the same series) age out instead of
  poisoning a mean, and the **shallowest kv-bucket** cost variant, a
  conservative floor; the full per-bucket table is at ``/debug/costs``.
* Everything here is cold-path: table building compiles (at warmup, or
  lazily inside the sentinel's thread-scoped ``exempt()`` window), but scrapes
  (`metrics_view`) read host-side metadata only — no device dispatch, no
  device→host array transfer, so the sanitizer contract is untouched.

Peak knobs: ``DLT_PEAK_TFLOPS`` (default 197, the bench chip's bf16 MXU
peak) and ``DLT_PEAK_HBM_GBS`` (default 819) — set them to your part's
datasheet numbers for honest MFU/roofline percentages.
"""

from __future__ import annotations

import dataclasses
import glob
import os
import re
import tempfile
import threading
import time

import jax

from .telemetry import _tree_bytes


def peak_flops() -> float:
    """Device peak FLOP/s for MFU (``DLT_PEAK_TFLOPS``, bf16 MXU peak)."""
    try:
        return float(os.environ.get("DLT_PEAK_TFLOPS", 197.0)) * 1e12
    except ValueError:
        return 197.0e12


def peak_hbm_bytes_s() -> float:
    """Device peak HBM bandwidth for roofline (``DLT_PEAK_HBM_GBS``)."""
    try:
        return float(os.environ.get("DLT_PEAK_HBM_GBS", 819.0)) * 1e9
    except ValueError:
        return 819.0e9


# -- cost table --------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class CostEntry:
    """Cost/memory analysis of ONE warm-ladder program.

    ``flops`` / ``bytes_accessed`` are PER DISPATCH, from a trip-count-aware
    census of the traced jaxpr (scan lengths applied — XLA's own
    ``cost_analysis()`` counts every loop body exactly once, which would
    undercount a 64-step decode chunk 64x; those raw body-once numbers ride
    along as ``xla_body_*``). The byte census models HBM-RESIDENT traffic:
    reads of program inputs (packed weights at their STORED width, rope,
    the KV cache at its sliced kv-bucket read bound) and in-place cache
    update writes — intermediates are assumed on-chip, the same optimism a
    roofline model wants. ``arg/out/temp/alias`` come from XLA's
    ``memory_analysis()`` (loop-independent, so per-dispatch correct)."""

    kind: str
    size: int
    kv_len: int
    flops: float  # per dispatch (trip-count-aware jaxpr census)
    bytes_accessed: float  # per dispatch HBM-resident traffic (see above)
    xla_body_flops: float  # XLA cost_analysis raw (loop bodies once)
    xla_body_bytes: float
    arg_bytes: int
    out_bytes: int
    temp_bytes: int
    alias_bytes: int  # donated (in-place) bytes
    tokens: int  # token positions processed per dispatch (batch included)

    @property
    def flops_per_token(self) -> float:
        return self.flops / max(self.tokens, 1)

    @property
    def bytes_per_token(self) -> float:
        return self.bytes_accessed / max(self.tokens, 1)

    def as_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["flops_per_token"] = round(self.flops_per_token, 1)
        d["bytes_per_token"] = round(self.bytes_per_token, 1)
        return d


class CostTable:
    """Per-(kind, size, kv-bucket) cost entries over a warm plan, plus the
    per-entry build failures (a failure IS information: a new warm-plan
    kind the cost model can't lower fails the coverage audit loudly)."""

    def __init__(self, entries: dict, failures: dict, partial: bool = False):
        self.entries = entries  # (kind, size, kv_len) -> CostEntry
        self.failures = failures  # (kind, size, kv_len) -> error string
        self.partial = partial  # built over a sub-plan (bench), not the ladder

    def lookup(self, kind: str, size: int):
        """The (kind, size) entry at the SHALLOWEST kv bucket — the
        conservative per-program floor the roofline join uses."""
        best = None
        for (k, s, kv), e in self.entries.items():
            if k == kind and s == size and (best is None or kv < best.kv_len):
                best = e
        return best

    def coverage_problems(self, plan) -> list:
        """One message per warm-plan program missing from the table."""
        problems = []
        for key in plan:
            key = tuple(key)
            if key in self.entries:
                continue
            why = self.failures.get(key, "no cost entry built")
            problems.append(
                f"{key[0]}[{key[1]}|kv{key[2]}]: missing cost/memory entry "
                f"({why})"
            )
        return problems

    def snapshot(self, plan=None) -> dict:
        """The ``/debug/costs`` payload."""
        out = {
            "partial": self.partial,
            "n_entries": len(self.entries),
            "peak_tflops": peak_flops() / 1e12,
            "peak_hbm_gb_s": peak_hbm_bytes_s() / 1e9,
            "entries": [
                self.entries[k].as_dict() for k in sorted(self.entries)
            ],
        }
        if self.failures:
            out["failures"] = {
                f"{k[0]}[{k[1]}|kv{k[2]}]": v for k, v in self.failures.items()
            }
        if plan is not None:
            missing = self.coverage_problems(plan)
            out["coverage"] = {
                "plan_size": len(list(plan)),
                "complete": not missing,
                "missing": missing,
            }
        return out


def _abstract(tree):
    """ShapeDtypeStruct twin of a concrete pytree (shardings preserved) —
    lowering against it compiles the production program without baking the
    real weights in as constants (or duplicating them on device)."""

    def one(a):
        sh = getattr(a, "sharding", None)
        try:
            return jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=sh)
        except TypeError:  # older jax without the sharding kwarg
            return jax.ShapeDtypeStruct(a.shape, a.dtype)

    return jax.tree.map(one, tree)


def _sds(shape, dtype):
    return jax.ShapeDtypeStruct(shape, dtype)


def entry_tokens(engine, kind: str, size: int) -> int:
    """Token positions one dispatch of this program processes (the
    per-token normalization for ``/debug/costs``): whole-batch programs
    advance `batch * size` positions, the per-row admission prefill one
    row's `size`, prefix copies move `size` cached positions."""
    b = engine.batch
    if kind in ("prefill", "decode", "batch_decode", "verify", "verify_row"):
        return b * size
    # prefill_row / prefix_extract / prefix_copy(_row) / page_copy /
    # page_extract / page_insert: one row's chunk, one cached or shipped
    # slice, or one page worth of positions
    return size


def lower_entry(engine, key):
    """AOT-lower the program a warm-plan key names — the SAME jit entry
    points serving dispatches (`graph_audit.trace_entry`'s abstract-eval
    twin, but through `.lower()` so the result can `.compile()` for
    cost/memory analysis). Params/rope/cache ride as abstract trees."""
    import jax.numpy as jnp

    kind, size, kvb = key
    cfg, b = engine.cfg, engine.batch
    a_params = _abstract(engine.params)
    a_rope = _abstract(engine.rope)
    a_cache = _abstract(engine.cache)
    from .engine import _greedy_prng_key

    key0 = _greedy_prng_key()
    paged = getattr(engine, "paged", False)
    ps = engine.page_size
    pt_sds = (
        _sds((b, engine.page_pool.max_slots), jnp.int32) if paged else None
    )

    if kind == "page_copy":
        from .paged_kv import copy_page

        return copy_page.lower(
            a_cache, _sds((), jnp.int32), _sds((), jnp.int32),
            out_sharding=engine._cache_sharding,
        )
    if kind in ("page_extract", "page_insert"):
        # the KV movement layer's page-shipping programs
        # (runtime/kv_transport.py): pool <-> contiguous-slice gathers
        from .paged_kv import gather_pages, scatter_pages

        n = size // engine.page_size
        if kind == "page_extract":
            return gather_pages.lower(
                a_cache, _sds((n,), jnp.int32),
                out_sharding=engine.prefix_cache.seg_sharding,
            )
        L, _, _, h, d = engine.cache.k.shape
        # wire segments are FLOAT even over int8 pools (dequant-on-extract /
        # requant-on-insert, runtime/paged_kv.py)
        wire = jnp.float32 if cfg.kv_quantized else engine.cache.k.dtype
        seg = _sds((L, size, h, d), wire)
        return scatter_pages.lower(
            a_cache, seg, seg, _sds((n,), jnp.int32),
            out_sharding=engine._cache_sharding,
        )
    if kind in ("prefill", "verify", "verify_row"):
        mode = "last" if kind == "prefill" else "all"
        per_row = kind == "verify_row"
        pos_sds = _sds((b,), jnp.int32) if per_row else _sds((), jnp.int32)
        if engine.use_pipeline:
            from ..parallel.pipeline import pipeline_forward

            pp = engine.mesh.shape["pp"]
            micro = 1 if per_row else (pp if size % pp == 0 else 1)
            if paged:
                fn = lambda params, rope, cache, toks, pos, pt: pipeline_forward(
                    cfg, engine.mesh, params, rope, cache, toks, pos,
                    logits_mode=mode, microbatches=micro, kv_len=kvb,
                    page_table=pt, page_size=ps,
                )
                return jax.jit(fn).lower(
                    a_params, a_rope, a_cache, _sds((b, size), jnp.int32),
                    pos_sds, pt_sds,
                )
            fn = lambda params, rope, cache, toks, pos: pipeline_forward(
                cfg, engine.mesh, params, rope, cache, toks, pos,
                logits_mode=mode, microbatches=micro, kv_len=kvb,
            )
            return jax.jit(fn).lower(
                a_params, a_rope, a_cache, _sds((b, size), jnp.int32), pos_sds
            )
        if kind == "prefill":
            from ..models.transformer import forward

            return forward.lower(
                cfg, a_params, a_rope, a_cache, _sds((b, size), jnp.int32),
                pos_sds, logits_mode="last", kv_len=kvb,
                page_table=pt_sds, page_size=ps,
            )
        from .speculative import verify_chunk

        return verify_chunk.lower(
            cfg, a_params, a_rope, a_cache, _sds((b, size), jnp.int32),
            pos_sds, kv_len=kvb, page_table=pt_sds, page_size=ps,
        )
    if kind == "decode":
        if engine.use_pipeline:
            from ..parallel.pipeline import pipeline_decode_chunk

            if paged:
                fn = lambda params, rope, cache, tok, pos, pt: pipeline_decode_chunk(
                    cfg, engine.mesh, params, rope, cache, tok, pos, key0,
                    n_steps=size, temperature=0.0, topp=0.9, kv_len=kvb,
                    page_table=pt, page_size=ps,
                )
                return jax.jit(fn).lower(
                    a_params, a_rope, a_cache, _sds((b,), jnp.int32),
                    _sds((), jnp.int32), pt_sds,
                )
            fn = lambda params, rope, cache, tok, pos: pipeline_decode_chunk(
                cfg, engine.mesh, params, rope, cache, tok, pos, key0,
                n_steps=size, temperature=0.0, topp=0.9, kv_len=kvb,
            )
            return jax.jit(fn).lower(
                a_params, a_rope, a_cache, _sds((b,), jnp.int32),
                _sds((), jnp.int32),
            )
        from .decode import decode_chunk

        return decode_chunk.lower(
            cfg, a_params, a_rope, a_cache, _sds((b,), jnp.int32),
            _sds((), jnp.int32), key0, n_steps=size, temperature=0.0,
            topp=0.9, kv_len=kvb, page_table=pt_sds, page_size=ps,
        )
    if kind == "batch_decode":
        args = (
            _sds((b,), jnp.int32), _sds((b,), jnp.int32),
            _sds((b, 2), jnp.uint32), _sds((b,), jnp.float32),
            _sds((b,), jnp.float32),
        )
        if engine.use_pipeline:
            from ..parallel.pipeline import pipeline_batch_decode_chunk as bdc

            if paged:
                fn = lambda params, rope, cache, tok, pos, keys, temp, topp, pt: bdc(
                    cfg, engine.mesh, params, rope, cache, tok, pos, keys,
                    temp, topp, n_steps=size, kv_len=kvb, page_table=pt,
                    page_size=ps,
                )
                return jax.jit(fn).lower(a_params, a_rope, a_cache, *args, pt_sds)
            fn = lambda params, rope, cache, tok, pos, keys, temp, topp: bdc(
                cfg, engine.mesh, params, rope, cache, tok, pos, keys, temp,
                topp, n_steps=size, kv_len=kvb,
            )
            return jax.jit(fn).lower(a_params, a_rope, a_cache, *args)
        from .batch_session import batch_decode_chunk

        return batch_decode_chunk.lower(
            cfg, a_params, a_rope, a_cache, *args, n_steps=size, kv_len=kvb,
            page_table=pt_sds, page_size=ps,
        )
    if kind == "prefill_row":
        if engine.use_pipeline:
            from ..parallel.pipeline import pipeline_forward

            if paged:
                fn = lambda params, rope, cache, toks, pos_vec, pt: pipeline_forward(
                    cfg, engine.mesh, params, rope, cache, toks, pos_vec,
                    logits_mode="last", kv_len=kvb, page_table=pt,
                    page_size=ps,
                )
                return jax.jit(fn).lower(
                    a_params, a_rope, a_cache, _sds((b, size), jnp.int32),
                    _sds((b,), jnp.int32), pt_sds,
                )
            fn = lambda params, rope, cache, toks, pos_vec: pipeline_forward(
                cfg, engine.mesh, params, rope, cache, toks, pos_vec,
                logits_mode="last", kv_len=kvb,
            )
            return jax.jit(fn).lower(
                a_params, a_rope, a_cache, _sds((b, size), jnp.int32),
                _sds((b,), jnp.int32),
            )
        if paged:
            # the paged admission prefill is the b=1 forward steered by a
            # one-row page-table slice (engine._dispatch_prefill_row)
            from ..models.transformer import forward

            return forward.lower(
                cfg, a_params, a_rope, a_cache, _sds((1, size), jnp.int32),
                _sds((), jnp.int32), logits_mode="last", kv_len=kvb,
                page_table=_sds((1, engine.page_pool.max_slots), jnp.int32),
                page_size=ps,
            )
        from .batch_session import prefill_row

        return prefill_row.lower(
            cfg, a_params, a_rope, a_cache, _sds((1, size), jnp.int32),
            _sds((), jnp.int32), _sds((), jnp.int32), kv_len=kvb,
        )
    if kind in ("prefix_extract", "prefix_copy", "prefix_copy_row"):
        from .prefix_cache import (
            copy_prefix_into_row,
            copy_prefix_into_rows,
            extract_prefix_from_row,
        )

        pc = engine.prefix_cache
        L, _, _, h, d = engine.cache.k.shape
        seg = _sds((L, size, h, d), engine.cache.k.dtype)
        if kind == "prefix_extract":
            return extract_prefix_from_row.lower(
                a_cache, _sds((), jnp.int32), length=size,
                out_sharding=pc.seg_sharding,
            )
        if kind == "prefix_copy":
            return copy_prefix_into_rows.lower(
                a_cache, seg, seg, out_sharding=pc.cache_sharding
            )
        return copy_prefix_into_row.lower(
            a_cache, seg, seg, _sds((), jnp.int32),
            out_sharding=pc.cache_sharding,
        )
    raise ValueError(f"unknown warm-plan kind {kind!r}")


def _cost_from_compiled(compiled) -> tuple:
    """(flops, bytes_accessed, memory dict) from a compiled executable —
    normalizing across backends (XLA:CPU returns a one-element list from
    ``cost_analysis()``, TPU a dict)."""
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    flops = float(ca.get("flops", 0.0) or 0.0)
    bytes_accessed = float(ca.get("bytes accessed", 0.0) or 0.0)
    mem = {"arg": 0, "out": 0, "temp": 0, "alias": 0}
    ma = compiled.memory_analysis()
    if ma is not None:
        mem = {
            "arg": int(getattr(ma, "argument_size_in_bytes", 0) or 0),
            "out": int(getattr(ma, "output_size_in_bytes", 0) or 0),
            "temp": int(getattr(ma, "temp_size_in_bytes", 0) or 0),
            "alias": int(getattr(ma, "alias_size_in_bytes", 0) or 0),
        }
    return flops, bytes_accessed, mem


# -- trip-count-aware jaxpr census -------------------------------------------
#
# XLA's HloCostAnalysis counts every loop body exactly ONCE (measured: a
# lax.scan of length 1, 2, and 8 over the same matmul reports identical
# flops), so its aggregates describe one decode STEP, not the n-step chunk a
# dispatch runs. The census below walks the traced jaxpr with the scan
# lengths applied — exact for dot flops — and models HBM traffic by tagging
# which values are device-RESIDENT (the program's inputs: weights at their
# stored/packed width, rope, cache) and counting only their reads, at the
# sliced width where a slice is what's read (the kv-bucket bound), plus
# in-place cache-update writes. Intermediates are assumed on-chip — the
# optimistic-cache assumption a roofline denominator wants.

#: layout-only ops: an HBM-resident array stays resident through them, and
#: the op itself moves no bytes the consumer won't pay for
_LAYOUT_PRIMS = frozenset({"reshape", "transpose", "broadcast_in_dim", "squeeze"})
#: slice-like ops: reading FROM a resident array costs the slice taken,
#: not the whole allocation (this is exactly what kv_len bucketing buys)
_SLICE_PRIMS = frozenset({"slice", "dynamic_slice", "gather", "take"})


def _aval_bytes(aval) -> int:
    try:
        return int(aval.size) * aval.dtype.itemsize
    except Exception:  # tokens / extended dtypes (PRNG keys)
        return 0


def _aval_elems(aval) -> int:
    try:
        return int(aval.size)
    except Exception:
        return 0


def _dot_flops(eqn, mult: float) -> float:
    (lc, _), _ = eqn.params["dimension_numbers"]
    lhs = eqn.invars[0].aval
    out = eqn.outvars[0].aval
    k = 1
    for i in lc:
        k *= lhs.shape[i]
    return 2.0 * k * _aval_elems(out) * mult


def _paged_kernel_census(eqn, in_hbm):
    """Recognize the fused page-table-aware decode kernel
    (ops/pallas_attention.paged_flash_attention) by operand signature — the
    ONE pallas_call whose HBM reads happen *inside* the kernel (the HLO page
    gather the fusion removed) — and price them at STORED width: per grid
    cell one (page, kv-head) tile of int8 payload plus its f32 scale row,
    for K and V. Returns ``(bytes, body_grid_mult)`` or None (any other
    pallas_call keeps the generic sub-jaxpr handling). Without this the
    fused program's KV reads would census as ZERO bytes — the quantized
    roofline would flatter itself by exactly the traffic it claims to save."""
    import numpy as np

    pools = [
        v
        for v, res in zip(eqn.invars, in_hbm)
        if res
        and getattr(v.aval, "ndim", 0) == 5
        and v.aval.dtype == np.int8
    ]
    if len(pools) != 2:
        return None
    meta = next(
        (
            v
            for v in eqn.invars
            if getattr(v.aval, "ndim", 0) == 1 and v.aval.dtype == np.int32
        ),
        None,
    )
    q4 = next(
        (
            v
            for v in eqn.invars
            if getattr(v.aval, "ndim", 0) == 4 and v.aval.dtype.kind == "f"
        ),
        None,
    )
    if meta is None or q4 is None:
        return None
    _, _, ps, n_kv, hd = pools[0].aval.shape
    bn = q4.aval.shape[0]  # b * n_kv grid rows
    b = bn // n_kv
    n_read = (int(meta.aval.size) - 1 - b) // b
    # K + V: int8 payload (ps*hd) and the f32 scale sidecar (ps*4) per cell
    return 2 * bn * n_read * (ps * hd + ps * 4), bn * n_read


def _census_walk(jaxpr, mult: float, hbm: dict, acc: dict) -> None:
    from ..analysis.graph_audit import _sub_jaxprs

    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "scan":
            body = eqn.params["jaxpr"].jaxpr
            length = int(eqn.params.get("length") or 1)
            inner = {}
            # scan body invars align 1:1 with [consts..., carry..., xs...];
            # an xs slice inherits its stacked source's residency, so a
            # layer scan's per-iteration weight slice counts per iteration
            # — length iterations read the whole stack, as the device does
            for bv, ov in zip(body.invars, eqn.invars):
                inner[id(bv)] = hbm.get(id(ov), False)
            _census_walk(body, mult * length, inner, acc)
            continue
        if name == "pallas_call":
            in_hbm = [hbm.get(id(v), False) for v in eqn.invars]
            pk = _paged_kernel_census(eqn, in_hbm)
            if pk is not None:
                pool_bytes, grid = pk
                acc["bytes"] += pool_bytes * mult
                # kernel body flops run once per grid cell (refs carry no
                # residency — bytes are fully owned by the pricing above)
                for sub in _sub_jaxprs(eqn):
                    _census_walk(sub, mult * grid, {}, acc)
                continue
        subs = list(_sub_jaxprs(eqn))
        if subs:
            # pjit / cond / while / custom_* bodies: trip count unknown or 1
            # — count once, mapping residency through where arities align
            for sub in subs:
                sub_j = sub
                inner = {}
                if len(sub_j.invars) == len(eqn.invars):
                    for bv, ov in zip(sub_j.invars, eqn.invars):
                        inner[id(bv)] = hbm.get(id(ov), False)
                _census_walk(sub_j, mult, inner, acc)
            continue
        in_hbm = [hbm.get(id(v), False) for v in eqn.invars]
        # -- flops: dots exact, everything else one op per output element
        # (layout/slice ops move data, they don't compute)
        if name == "dot_general":
            acc["flops"] += _dot_flops(eqn, mult)
        elif (
            name not in _LAYOUT_PRIMS
            and name not in _SLICE_PRIMS
            and name != "dynamic_update_slice"
            and eqn.outvars
            and hasattr(eqn.outvars[0].aval, "dtype")
        ):
            try:
                is_float = eqn.outvars[0].aval.dtype.kind == "f"
            except Exception:
                is_float = False
            if is_float:
                acc["flops"] += _aval_elems(eqn.outvars[0].aval) * mult
        # -- bytes: reads of resident arrays + in-place update writes
        if name in _LAYOUT_PRIMS:
            # residency flows through; the consumer pays the bytes
            if any(in_hbm):
                for ov in eqn.outvars:
                    hbm[id(ov)] = True
            continue
        if name == "dynamic_update_slice":
            if in_hbm[0]:
                # in-place write of the update region (donated cache)
                acc["bytes"] += _aval_bytes(eqn.invars[1].aval) * mult
                hbm[id(eqn.outvars[0])] = True  # still the resident cache
            continue
        if name.startswith("scatter"):
            # in-place scatter into a resident array (the per-row cache
            # writes, and the paged layout's page-table writes —
            # runtime/paged_kv.py): traffic is the UPDATES region plus its
            # index rows, never the whole operand (counting the operand as
            # a read overstated a batch_decode step by the full cache)
            if in_hbm[0]:
                acc["bytes"] += _aval_bytes(eqn.invars[-1].aval) * mult
                hbm[id(eqn.outvars[0])] = True
            continue
        if name in _SLICE_PRIMS:
            if any(in_hbm):
                acc["bytes"] += _aval_bytes(eqn.outvars[0].aval) * mult
            continue
        for v, resident in zip(eqn.invars, in_hbm):
            if resident:
                acc["bytes"] += _aval_bytes(v.aval) * mult


def jaxpr_census(closed_jaxpr) -> dict:
    """{"flops", "bytes"} per dispatch of a traced program (see the block
    comment above for the counting model)."""
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    acc = {"flops": 0.0, "bytes": 0.0}
    # resident set = the program's inputs, whether traced as arguments or
    # closed over (make_jaxpr puts the engine's params/cache in constvars)
    hbm = {id(v): True for v in list(jaxpr.invars) + list(jaxpr.constvars)}
    _census_walk(jaxpr, 1.0, hbm, acc)
    return acc


def build_cost_table(engine, plan=None) -> CostTable:
    """Lower + compile every program in `plan` (default: the engine's full
    ``warm_plan()``) and collect XLA's cost/memory analyses. Compilation is
    AOT — nothing executes, no device arrays move — but it IS compile work:
    call it at warmup, lazily from a cold endpoint, or over a partial plan
    (the bench's per-leg tables). With ``DLT_COMPILE_CACHE`` set the
    persistent cache dedupes these against warmup's own compiles."""
    from ..analysis.graph_audit import LadderEntry, trace_entry

    entries: dict = {}
    failures: dict = {}
    partial = plan is not None
    plan = engine.warm_plan() if plan is None else list(plan)
    for key in plan:
        key = tuple(key)
        if key in entries or key in failures:
            continue
        kind, size, kvb = key
        try:
            census = jaxpr_census(
                trace_entry(engine, LadderEntry(kind, size, kvb))
            )
            xla_flops, xla_bytes, mem = _cost_from_compiled(
                lower_entry(engine, key).compile()
            )
            entries[key] = CostEntry(
                kind=kind, size=size, kv_len=kvb,
                flops=census["flops"], bytes_accessed=census["bytes"],
                xla_body_flops=xla_flops, xla_body_bytes=xla_bytes,
                arg_bytes=mem["arg"], out_bytes=mem["out"],
                temp_bytes=mem["temp"], alias_bytes=mem["alias"],
                tokens=entry_tokens(engine, kind, size),
            )
        except Exception as e:  # recorded, surfaced by the coverage audit
            failures[key] = f"{type(e).__name__}: {e}"
    return CostTable(entries, failures, partial=partial)


def cost_problems(engine, table=None) -> list:
    """The ``graph_audit --costs`` check: every warm-plan program must have
    a cost/memory entry (build failures count as missing). Returns problem
    strings; empty means the table fully covers the ladder. (There is no
    disabled state here: ``DLT_COST_TABLE=0`` only defers the serve-time
    build — ``engine.cost_table()`` always constructs on demand.)"""
    table = engine.cost_table() if table is None else table
    return table.coverage_problems(engine.warm_plan())


def format_cost_table(table: CostTable) -> str:
    lines = ["💰 warm-ladder cost table:"]
    for key in sorted(table.entries):
        e = table.entries[key]
        lines.append(
            f"  {e.kind}[{e.size}|kv{e.kv_len}]: "
            f"{e.flops / 1e6:.1f} MFLOP, {e.bytes_accessed / 1e6:.1f} MB "
            f"accessed, temp {e.temp_bytes / 1e6:.1f} MB "
            f"({e.bytes_per_token:.0f} B/token)"
        )
    for key, why in sorted(table.failures.items()):
        lines.append(f"  ! {key[0]}[{key[1]}|kv{key[2]}]: FAILED — {why}")
    return "\n".join(lines)


# -- HBM ledger --------------------------------------------------------------


def _device_memory_stats(engine) -> dict | None:
    """Aggregate ``memory_stats()`` over the devices holding this engine's
    cache; None when the backend doesn't report (XLA:CPU)."""
    try:
        devices = list(engine.cache.k.devices())
    except Exception:
        devices = jax.devices()[:1]
    in_use = limit = 0
    seen = False
    for d in devices:
        stats = d.memory_stats() if hasattr(d, "memory_stats") else None  # dlt: allow(host-sync) — cold-path runtime query, no array transfer
        if not stats:
            continue
        seen = True
        in_use += int(stats.get("bytes_in_use", 0) or 0)
        limit += int(stats.get("bytes_limit", 0) or 0)
    if not seen:
        return None
    return {"bytes_in_use": in_use, "bytes_limit": limit or None}


def hbm_ledger(engine) -> dict:
    """Modeled per-component device-byte accounting, reconciled against the
    backend's measured numbers where available. Reads only host-side array
    metadata (`.nbytes`) — no device work, safe on any scrape."""
    components = {
        "weights": _tree_bytes(engine.params),
        "rope": _tree_bytes(engine.rope),
        "kv_cache": _tree_bytes(engine.cache),
    }
    pc = engine.prefix_cache
    if pc is not None and not getattr(pc, "paged", False):
        # paged entries own no storage of their own — their bytes ARE pool
        # pages already counted under kv_cache; adding them double-counted
        # and made every eviction wave look like measured-vs-modeled drift
        components["prefix_cache"] = pc.total_bytes
    draft_eng = getattr(engine.draft_source, "engine", None)
    if draft_eng is not None:
        components["draft_engine"] = (
            _tree_bytes(draft_eng.params)
            + _tree_bytes(draft_eng.cache)
            + _tree_bytes(draft_eng.rope)
        )
    modeled = sum(components.values())
    out = {
        "components": components,
        "modeled_bytes": modeled,
        "measured_bytes": None,
        "limit_bytes": None,
        "headroom_bytes": None,
        "unattributed_bytes": None,
    }
    tier = getattr(engine, "kv_tier", None)
    if tier is not None:
        # the tiered-KV store's host/disk occupancy rides the SAME ledger
        # payload but as a SIBLING section, never a component: host RAM
        # is not HBM, and folding it into `modeled` would fake
        # measured-vs-modeled drift on every demotion wave
        out["host_tier"] = tier.memory_snapshot()
    measured = _device_memory_stats(engine)
    if measured is not None:
        out["measured_bytes"] = measured["bytes_in_use"]
        out["unattributed_bytes"] = measured["bytes_in_use"] - modeled
        if measured["bytes_limit"]:
            out["limit_bytes"] = measured["bytes_limit"]
            out["headroom_bytes"] = (
                measured["bytes_limit"] - measured["bytes_in_use"]
            )
    return out


def _drift_threshold_bytes() -> int:
    try:
        return int(float(os.environ.get("DLT_HBM_DRIFT_MB", 64))) * 1024 * 1024
    except ValueError:
        return 64 * 1024 * 1024


#: serializes the read-modify-write of engine._hbm_drift_base: concurrent
#: /metrics scrapes (threaded server, bench scraper thread) must count one
#: residual excursion exactly once
_DRIFT_LOCK = threading.Lock()


def reconcile_hbm(engine, ledger: dict | None = None) -> dict:
    """The leak detector: the first reconcile baselines the measured-minus-
    modeled residual (compiled executables, runtime scratch — legitimate
    bytes the model doesn't itemize); later reconciles count residual
    GROWTH beyond ``DLT_HBM_DRIFT_MB`` as a drift event
    (``hbm_drift_events`` counter + ``dlt_hbm_drift_bytes`` gauge).
    Shrinkage re-baselines — freed scratch must not bank headroom that
    masks a later leak. No-op (drift 0) where nothing is measured."""
    ledger = hbm_ledger(engine) if ledger is None else ledger
    un = ledger.get("unattributed_bytes")
    if un is None:
        return {"drift_bytes": 0, "tripped": False}
    with _DRIFT_LOCK:
        base = getattr(engine, "_hbm_drift_base", None)
        if base is None or un < base:
            engine._hbm_drift_base = base = un
        drift = un - base
        tripped = drift > _drift_threshold_bytes()
        if tripped:
            engine.stats.incr("hbm_drift_events")
            engine._hbm_drift_base = un  # re-arm: count each excursion once
    return {"drift_bytes": drift, "tripped": tripped}


# -- live roofline / MFU / SLO -----------------------------------------------

_SERIES_RE = re.compile(r"^([a-z_]+)\[(\d+)\]$")

#: StepStats series that are honest whole-chunk device walls, mapped to
#: their cost-table kind(s) and the size offset from the series' bracket
#: number (spec_verify[k] walls belong to the (k+1)-token verify program).
#: Prefill *dispatch* series are asynchronous walls and deliberately absent.
_SERIES_KINDS = {
    "decode": (("decode", 0),),
    "batch_decode": (("batch_decode", 0),),
    "spec_verify": (("verify", 1), ("verify_row", 1)),
}

#: series whose all-time totals count toward the duty-cycle gauge — device
#: time regardless of whether a cost entry joins: the decode-side chunk
#: walls above plus the prefill loop (dispatch walls + the final sync wait
#: together span the prefill wall, and the phases are disjoint)
_BUSY_RE = re.compile(
    r"^(?:decode|batch_decode|spec_verify|prefill_dispatch)\[\d+\]$"
    r"|^prefill_sync$"
)


def roofline_view(engine, table: CostTable):
    """(gauges, labeled_series) joining the cost table with the recorded
    per-program walls. Per-series numbers use the recent-window p50 wall
    (warmup's compile walls age out) and the shallowest-kv cost variant
    (a conservative floor)."""
    gauges: dict = {}
    series: dict = {}
    prog_gbs: list = []
    prog_tflops: list = []
    w_flops = w_bytes = w_us = 0.0
    busy_us = 0.0
    for name, s in sorted(list(engine.stats.series.items())):
        if s.count and _BUSY_RE.match(name):
            # duty cycle counts EVERY device wall, joined or not — a
            # prefill-heavy server must not read as idle just because
            # prefill walls have no cost entry
            busy_us += s.total_us
        m = _SERIES_RE.match(name)
        if not m or m.group(1) not in _SERIES_KINDS or s.count == 0:
            continue
        entry = None
        for kind, off in _SERIES_KINDS[m.group(1)]:
            entry = table.lookup(kind, int(m.group(2)) + off)
            if entry is not None:
                break
        if entry is None:
            continue
        p = engine.stats.percentiles(name)
        p50_us = p.get("p50", 0.0)
        if p50_us <= 0:
            continue
        sec = p50_us / 1e6
        prog_gbs.append(({"program": name}, round(entry.bytes_accessed / sec / 1e9, 2)))
        prog_tflops.append(({"program": name}, round(entry.flops / sec / 1e12, 4)))
        n = len(s.recent)
        w_flops += n * entry.flops
        w_bytes += n * entry.bytes_accessed
        w_us += n * p50_us
    if prog_gbs:
        series["program_gb_s"] = prog_gbs
        series["program_tflop_s"] = prog_tflops
    if w_us > 0:
        gauges["mfu"] = round((w_flops / (w_us / 1e6)) / peak_flops(), 4)
        gauges["bw_utilization"] = round(
            (w_bytes / (w_us / 1e6)) / peak_hbm_bytes_s(), 4
        )
    elapsed_us = (time.perf_counter() - engine._t_start) * 1e6
    if elapsed_us > 0 and busy_us > 0:
        # busy fraction over the engine's lifetime, from the all-time series
        # totals — warmup (compiles included) counts as busy, honestly so
        gauges["device_duty_cycle"] = round(min(busy_us / elapsed_us, 1.0), 4)
    return gauges, series


def _slo_ms(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def slo_gauges(stats) -> dict:
    """SLO attainment from the cumulative TTFT/TPOT histograms: the
    fraction of observations at or under the target, read at the largest
    histogram bound <= the target (a conservative floor — log buckets, so
    within one 2x bucket of exact)."""
    out: dict = {}
    hists = stats.hists_snapshot()
    for hname, env, default, gauge in (
        ("ttft_ms", "DLT_SLO_TTFT_MS", 1000.0, "slo_ttft_attainment"),
        ("tpot_ms", "DLT_SLO_TPOT_MS", 100.0, "slo_tpot_attainment"),
    ):
        snap = hists.get(hname)
        if not snap or not snap["count"]:
            continue
        slo = _slo_ms(env, default)
        cum = 0
        for bound, c in snap["buckets"]:
            if isinstance(bound, str) or bound > slo:
                break
            cum = c
        out[gauge] = round(cum / snap["count"], 4)
        out[gauge.replace("attainment", "target_ms")] = slo
    return out


def slo_class_series(stats) -> dict:
    """Per-SLO-class attainment rows derived from the labeled
    ``ttft_ms{slo_class=...}`` / ``tpot_ms{...}`` histograms the serving
    paths observe (runtime/telemetry.py StepStats.observe(labels=)) —
    rendered as ``dlt_slo_ttft_attainment{slo_class=...}`` rows, exactly
    the family the fleet scraper already lifts into
    ``slo_ttft_attainment_by_class`` and the autoscaler's per-class
    pressure check reads (server/fleet.py, server/autoscaler.py)."""
    from .tracing import split_labeled_key

    out: dict = {}
    hists = stats.hists_snapshot()
    for base_name, env, default, gauge in (
        ("ttft_ms", "DLT_SLO_TTFT_MS", 1000.0, "slo_ttft_attainment"),
        ("tpot_ms", "DLT_SLO_TPOT_MS", 100.0, "slo_tpot_attainment"),
    ):
        slo = _slo_ms(env, default)
        rows = []
        for key, snap in sorted(hists.items()):
            base, labels = split_labeled_key(key)
            if base != base_name or not labels or "slo_class" not in labels:
                continue
            if not snap["count"]:
                continue
            cum = 0
            for bound, c in snap["buckets"]:
                if isinstance(bound, str) or bound > slo:
                    break
                cum = c
            rows.append(
                (
                    {"slo_class": labels["slo_class"]},
                    round(cum / snap["count"], 4),
                )
            )
        if rows:
            out[gauge] = rows
    return out


def metrics_view(engine):
    """Everything `/metrics` adds on top of StepStats: (flat_gauges,
    labeled_series). One cold-path call per scrape — host metadata reads
    only; the roofline section appears once a cost table exists
    (``/debug/costs``, warmup with ``DLT_COST_TABLE=1``, or the server's
    post-warmup build)."""
    ledger = hbm_ledger(engine)
    rec = reconcile_hbm(engine, ledger)
    gauges = {"hbm_modeled_bytes": ledger["modeled_bytes"]}
    series = {
        "hbm_bytes": [
            ({"component": k}, v) for k, v in sorted(ledger["components"].items())
        ]
    }
    if ledger["unattributed_bytes"] is not None:
        series["hbm_bytes"].append(
            ({"component": "unattributed"}, ledger["unattributed_bytes"])
        )
        gauges["hbm_drift_bytes"] = rec["drift_bytes"]
    if ledger["headroom_bytes"] is not None:
        gauges["hbm_headroom_bytes"] = ledger["headroom_bytes"]
    table = engine.cost_table(build=False)
    if table is not None:
        rg, rs = roofline_view(engine, table)
        gauges.update(rg)
        series.update(rs)
    # SLO attainment: ONE gauge family per metric — the unlabeled total row
    # (the shape the fleet table has always lifted) plus the {slo_class}
    # breakdown rows the autoscaler's per-class pressure check reads (TYPE
    # declares once — the goodput family's precedent). Targets stay flat.
    slo_flat = slo_gauges(engine.stats)
    cls_rows = slo_class_series(engine.stats)
    for gauge in ("slo_ttft_attainment", "slo_tpot_attainment"):
        total = slo_flat.pop(gauge, None)
        rows = ([({}, total)] if total is not None else []) + cls_rows.get(
            gauge, []
        )
        if rows:
            series[gauge] = rows
    gauges.update(slo_flat)
    return gauges, series


# -- bench integration -------------------------------------------------------


def bench_profile(engine, final_pos: int | None = None) -> dict:
    """The bench's per-leg device profile: build a PARTIAL cost table over
    exactly the decode/verify programs the leg's series recorded (a handful
    of compiles, not the whole ladder — the full table is a serving-time
    concern) and return the ledger + roofline numbers for the BENCH json."""
    kvb = engine._kv_bucket(
        final_pos if final_pos is not None else engine.cfg.seq_len
    )
    plan = []
    for name in list(engine.stats.series):
        m = _SERIES_RE.match(name)
        if not m or m.group(1) not in _SERIES_KINDS:
            continue
        for kind, off in _SERIES_KINDS[m.group(1)]:
            size = int(m.group(2)) + off
            if kind in ("verify", "verify_row") and (
                engine.spec_mode is None or engine.batch <= 1
                and kind == "verify_row"
            ):
                continue
            plan.append((kind, size, max(kvb, size)))
    table = build_cost_table(engine, plan=plan)
    if engine._cost_table is None:
        engine._cost_table = table
    gauges, _ = roofline_view(engine, table)
    ledger = hbm_ledger(engine)
    out = {
        "dlt_mfu": gauges.get("mfu"),
        "dlt_bw_utilization": gauges.get("bw_utilization"),
        "hbm_modeled_gb": round(ledger["modeled_bytes"] / 1e9, 3),
        "hbm_components_gb": {
            k: round(v / 1e9, 3) for k, v in ledger["components"].items()
        },
    }
    dchunk = table.lookup("decode", engine.decode_chunk_size)
    if dchunk is not None:
        out["decode_bytes_per_token_modeled"] = round(dchunk.bytes_per_token, 1)
        out["decode_flops_per_token_modeled"] = round(dchunk.flops_per_token, 1)
    return out


# -- prefill overlap probe (scripts/profile_prefill.py rides this) -----------


def prefill_overlap_probe(
    model_path: str,
    prompt_tokens: int,
    reps: int = 3,
    max_chunk: int = 512,
    compute_dtype: str = "bfloat16",
) -> list:
    """Dispatch-vs-compute overlap of the pipelined prefill, pipelined vs
    the forced-serial arm — the ONE timing pathway: every number comes from
    ``engine.last_prefill_timing`` and the ``prefill_dispatch[size]``
    StepStats series, the same sources `/stats` and `/metrics` export, so
    the probe script can never drift from serving telemetry."""
    from .engine import InferenceEngine

    arms = []
    for pipelined in (True, False):
        eng = InferenceEngine(
            model_path, compute_dtype=compute_dtype, max_chunk=max_chunk,
            prefill_pipelined=pipelined,
            prefix_cache_mb=0,  # repeated-prompt probe: a splice would
            # replace the prefill being measured
        )
        try:
            prompt = [(i % 1000) + 1 for i in range(prompt_tokens)]
            eng.prefill(prompt)  # compile the ladder
            walls = []
            for _ in range(reps):
                eng.reset()
                t0 = time.perf_counter()
                eng.prefill(prompt)
                walls.append((time.perf_counter() - t0) * 1e3)
            t = dict(eng.last_prefill_timing or {})
            arms.append(
                {
                    "pipelined": pipelined,
                    "n_tokens": prompt_tokens,
                    "n_chunks": t.get("n_chunks", 0),
                    "best_wall_ms": round(min(walls), 1),
                    "tok_s": round(prompt_tokens / min(walls) * 1e3, 1),
                    "dispatch_ms": round(t.get("dispatch_us", 0) / 1e3, 1),
                    "sync_ms": round(t.get("sync_us", 0) / 1e3, 1),
                    "overlap_pct": t.get("overlap_pct"),
                    "dispatch_series": {
                        k: {
                            "count": s.count,
                            "avg_ms": round(s.total_us / s.count / 1e3, 2),
                        }
                        for k, s in sorted(eng.stats.series.items())
                        if k.startswith("prefill_dispatch") and s.count
                    },
                }
            )
        finally:
            eng.close()
    return arms


# -- on-demand profiler capture ----------------------------------------------


class ProfileBusy(RuntimeError):
    """A capture is already in flight — the profiler is process-wide, so
    overlapping windows would corrupt each other's traces."""


class ProfilerCapture:
    """Single-flight ``jax.profiler.trace`` window around live serving.
    The capture blocks only ITS caller (the ``/debug/profile`` handler
    thread); serving threads keep dispatching and their device work lands
    in the trace — that is the point."""

    MIN_MS, MAX_MS = 10, 30000

    def __init__(self):
        self._lock = threading.Lock()
        self.last: dict | None = None

    @staticmethod
    def _dir() -> str:
        return os.environ.get("DLT_PROFILE_DIR") or os.path.join(
            tempfile.gettempdir(), "dlt-profiles"
        )

    def capture(self, ms: int) -> dict:
        ms = max(self.MIN_MS, min(int(ms), self.MAX_MS))
        if not self._lock.acquire(blocking=False):  # dlt: allow(lock-with) — single-flight try-lock, released in the finally below
            raise ProfileBusy("a profile capture is already in flight")
        try:
            path = os.path.join(
                self._dir(), f"capture-{int(time.time() * 1000)}-{os.getpid()}"
            )
            os.makedirs(path, exist_ok=True)
            t0 = time.perf_counter()
            with jax.profiler.trace(path):
                time.sleep(ms / 1000.0)
            files = sorted(
                os.path.relpath(f, path)
                for f in glob.glob(os.path.join(path, "**", "*"), recursive=True)
                if os.path.isfile(f)
            )
            perfetto = [f for f in files if f.endswith(".trace.json.gz")]
            self.last = {
                "path": path,
                "requested_ms": ms,
                "wall_ms": round((time.perf_counter() - t0) * 1e3, 1),
                "files": files,
                "perfetto_trace": os.path.join(path, perfetto[0]) if perfetto else None,
            }
            return self.last
        finally:
            self._lock.release()


PROFILER = ProfilerCapture()


def capture_profile(ms: int) -> dict:
    """Run one bounded profiler window on the process singleton (the
    ``/debug/profile`` endpoint's backend). Raises :class:`ProfileBusy`
    when a window is already open."""
    return PROFILER.capture(ms)
