"""On-device multi-token decode loop.

The reference pays one socket broadcast + 2L+1 all-reduces per decoded token
and samples on the host (reference: app.cpp:251-303, SURVEY.md §3.1). The
TPU analogue of that per-token cost is the host->device dispatch and
device->host logits fetch — tens of ms through the driver tunnel, dwarfing
the ~1 ms of actual 1B-model compute.

So the decode loop itself is a `lax.scan` on device: K forward steps +
on-device sampling per host call, returning K tokens in one transfer — the
per-token host cost is amortized by K. EOS is checked between chunks; at
most K-1 tokens of overrun compute are discarded. (Planned: dispatch chunk
i+1 before fetching chunk i's tokens — both inputs are device-resident — to
overlap the fetch with compute entirely.)
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from ..models.config import ModelConfig
from ..models.params import KVCache, ModelParams
from ..models.transformer import forward_uncompiled
from ..ops.rope import RopeTables
from ..ops.sampling import sample_logits_traced


@partial(
    jax.jit,
    static_argnames=("cfg", "n_steps", "kv_len", "page_size"),
    donate_argnames=("cache",),
)
def decode_chunk(
    cfg: ModelConfig,
    params: ModelParams,
    rope: RopeTables,
    cache: KVCache,
    token: jnp.ndarray,  # [b] int32 — the token to feed first
    pos_start,  # scalar int32
    key: jnp.ndarray,  # PRNG key (ignored when temperature == 0)
    n_steps: int = 16,
    temperature=0.0,  # TRACED scalar: one compiled program per (n_steps,
    # kv_len) serves every temperature — a sampled request can no longer
    # compile a fresh program mid-serving (the /v1/chat post-warmup
    # recompile: warmup only ever ran temperature 0)
    topp=0.9,  # traced, same reason
    kv_len: int | None = None,  # static KV read bound covering
    # pos_start + n_steps (the engine's position bucket): attention reads
    # scale with the position, not the allocated cache
    page_table: jnp.ndarray | None = None,  # paged KV layout: [b, slots]
    # int32 (runtime/paged_kv.py); cache is then the page pools
    page_size: int | None = None,
    grammar_table: jnp.ndarray | None = None,  # [S, vocab] int32 grammar
    # arena (runtime/grammar.py): masks illegal tokens before sampling
    grammar_state: jnp.ndarray | None = None,  # [b] int32 global DFA states
):
    """Run n_steps feed-forward+sample iterations on device.

    Returns (tokens [b, n_steps], last_token [b], cache): `last_token`
    aliases tokens[:, -1] on device so the caller can feed the next chunk
    without issuing a separate slice op — through the driver tunnel every
    host-issued device op costs a round trip, and the decode loop's per-chunk
    op count is the serving overhead floor.

    With grammar operands the per-row DFA state rides the scan carry —
    advanced in-graph from each sampled token, so intra-chunk masking needs
    no host round trip — and the final states are returned as a 4th output
    for the engine's lookahead dispatch to chain (like `last_token`).
    """
    temperature = jnp.asarray(temperature, jnp.float32)
    topp = jnp.asarray(topp, jnp.float32)

    def step(carry, _):
        token, pos, cache, key, gstate = carry
        logits, cache = forward_uncompiled(
            cfg, params, rope, cache, token[:, None], pos, logits_mode="last",
            kv_len=kv_len, page_table=page_table, page_size=page_size,
        )
        key, sub = jax.random.split(key)
        nxt = sample_logits_traced(
            logits, sub, temperature, topp,
            grammar_table=grammar_table, grammar_state=gstate,
        )
        if gstate is not None:
            adv = grammar_table[gstate, nxt]
            gstate = jnp.where(adv < 0, gstate, adv)
        return (nxt, pos + 1, cache, key, gstate), nxt

    (last, _, cache, _, gout), toks = jax.lax.scan(
        step,
        (token, jnp.asarray(pos_start, jnp.int32), cache, key, grammar_state),
        None, length=n_steps,
    )
    toks = jnp.transpose(toks, (1, 0))
    if grammar_state is not None:
        return toks, last, cache, gout
    return toks, last, cache
