"""Request-lifecycle tracing: trace IDs, span ring buffer, flight recorder,
and Prometheus text exposition.

The aggregate views (`/stats` StepStats percentiles, `/gateway/stats`
counters) answer "how is the fleet doing" but not "why was THIS request's
TTFT 900 ms" or "what was the engine doing when the watchdog fired". This
module is the per-request layer under both servers:

* **Trace IDs** — minted at the first hop (gateway, or the backend for
  direct traffic), propagated via the ``X-DLT-Trace-Id`` header and echoed
  in responses, so one request is one joinable identity across
  gateway -> retry -> backend.
* **Span events** — every stage emits ``(trace_id, name, t_us, dur_us,
  keys, vals)`` tuples into a bounded ring (`TraceRing`): gateway
  routing/retry decisions, Batcher queue wait/admit, prefix-cache
  match/splice/publish, each prefill chunk's dispatch, decode chunks, and
  speculative draft/verify rounds. The hot-loop emit cost is ONE tuple
  append onto a pre-bound :class:`Emitter` (no dict construction, no name
  lookups, no locks — `deque.append` is atomic under the GIL); the repo
  lint's ``trace-hot-emit`` rule enforces the pre-bound discipline inside
  runtime loops.
* **Sampling** — ``DLT_TRACE_SAMPLE=N`` records detail spans for one in N
  requests (default 1 = all; 0 = off). Error/lifecycle events are emitted
  with ``always=True`` and land regardless, so a failed request is always
  reconstructable even at aggressive sampling.
* **Flight recorder** — on `StallError`, ``api.recover()``, or a fatal
  sanitizer breach, the last ``DLT_FLIGHTREC_EVENTS`` ring events are
  snapshotted to a post-mortem JSON: kept in memory for
  ``GET /debug/flightrecord`` and dumped on disk under
  ``DLT_FLIGHTREC_DIR`` (default: a ``dlt-flightrecords`` dir in the
  system tempdir; set the env to ``""``/``0`` to disable the disk copy).
* **Exposition** — ``GET /debug/trace?id=...`` renders one trace's span
  tree plus a Chrome ``trace_event`` export (load in chrome://tracing /
  Perfetto), and ``GET /metrics`` renders StepStats counters, gauges,
  latency-series quantiles, and the log-bucket histograms (TTFT,
  time-per-output-token) as Prometheus text exposition.

Tracing adds zero device work: every timestamp is host-side
(`perf_counter` anchored to the epoch once at import, so timestamps are
wall-aligned AND monotonic), so the sanitizer contract — no host syncs, no
post-warmup recompiles — is untouched by construction.

Deliberately stdlib-only (no jax, no numpy): the gateway imports this
module and must stay runnable on a box with no accelerator stack
(runtime/__init__ lazies its engine exports for the same reason).
"""

from __future__ import annotations

import bisect
import collections
import json
import os
import re
import tempfile
import threading
import time
import uuid

TRACE_HEADER = "X-DLT-Trace-Id"
#: carries the FIRST hop's sampling decision alongside the trace id, so a
#: gateway-sampled request gets its detail spans recorded at the backend
#: too (the two processes' 1-in-N counters are not in phase otherwise)
SAMPLED_HEADER = "X-DLT-Trace-Sampled"

# one epoch anchor at import: timestamps are perf_counter-monotonic but
# reported in wall-clock microseconds, so traces from two processes
# (gateway + backend) line up on one timeline
_T0_EPOCH = time.time()
_T0_PERF = time.perf_counter()


def now_us() -> int:
    """Current wall-aligned monotonic timestamp in microseconds."""
    return int((_T0_EPOCH + (time.perf_counter() - _T0_PERF)) * 1e6)


def to_us(perf_t: float) -> int:
    """Convert a `time.perf_counter()` reading to the event timebase —
    hot loops keep their existing perf_counter reads and convert only when
    emitting."""
    return int((_T0_EPOCH + (perf_t - _T0_PERF)) * 1e6)


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def mint_trace_id() -> str:
    return uuid.uuid4().hex[:16]


def parse_sampled(raw: str | None) -> bool | None:
    """Decode an ``X-DLT-Trace-Sampled`` header value: None (absent) means
    "decide locally"; ``"0"`` is the only falsy wire value."""
    if raw is None:
        return None
    return raw.strip() != "0"


# -- the ring ----------------------------------------------------------------


class TraceRing:
    """Bounded ring of span-event tuples ``(trace_id, name, t_us, dur_us,
    keys, vals)``. Appends are one `deque.append` — O(1), atomic under the
    GIL, no lock — and the `maxlen` bound means memory is capped no matter
    how many events flow through (the 100k-event bound test)."""

    def __init__(self, capacity: int | None = None):
        self.capacity = capacity or _env_int("DLT_TRACE_RING", 16384)
        self._events: collections.deque = collections.deque(maxlen=self.capacity)

    def append(self, ev: tuple) -> None:
        self._events.append(ev)

    def __len__(self) -> int:
        return len(self._events)

    def snapshot(self) -> list:
        # list() materializes a consistent-enough copy while emitters append
        return list(self._events)

    def for_trace(self, trace_id: str) -> list:
        return [e for e in self.snapshot() if e[0] == trace_id]


class Emitter:
    """A pre-bound span emitter: trace id, span name, and arg keys are
    fixed at bind time, so the per-event hot-loop cost is ONE tuple append.
    This is the only emission API the repo lint allows inside runtime
    loops (``trace-hot-emit``)."""

    __slots__ = ("_append", "_tid", "name", "keys")

    def __init__(self, ring: TraceRing, trace_id: str, name: str, keys=()):
        self._append = ring._events.append
        self._tid = trace_id
        self.name = name
        self.keys = tuple(keys)

    def __call__(self, t_us: int, dur_us: int, *vals) -> None:
        self._append((self._tid, self.name, t_us, dur_us, self.keys, vals))


class Trace:
    """One request's tracing context: the ID (propagated via
    ``X-DLT-Trace-Id``) plus the sampling decision made at request start."""

    __slots__ = ("id", "sampled", "_ring")

    def __init__(self, trace_id: str, sampled: bool, ring: TraceRing):
        self.id = trace_id
        self.sampled = sampled
        self._ring = ring

    def bind(self, name: str, keys=()) -> Emitter | None:
        """A pre-bound emitter for a hot loop — None when this trace is
        unsampled, so the loop's per-event guard (`if em is not None`)
        covers sampling too."""
        if not self.sampled:
            return None
        return Emitter(self._ring, self.id, name, keys)

    def event(
        self, name: str, t_us: int, dur_us: int = 0, keys=(), vals=(),
        always: bool = False,
    ) -> None:
        """One span event (cold path — request lifecycle, errors, cache
        decisions). `always=True` bypasses sampling: errors and terminal
        request events must land even at DLT_TRACE_SAMPLE=1000."""
        if self.sampled or always:
            self._ring.append((self.id, name, t_us, dur_us, tuple(keys), tuple(vals)))


class Tracer:
    """Process-wide trace registry: mints/records traces over one shared
    ring. The module singleton ``TRACER`` is what the servers and the
    engine share; tests may build private instances."""

    def __init__(self, capacity: int | None = None):
        self.ring = TraceRing(capacity)
        self._lock = threading.Lock()
        self._n = 0

    @staticmethod
    def sample_every() -> int:
        """The ``DLT_TRACE_SAMPLE`` knob: detail spans for 1 in N requests
        (1 = every request, the default; 0 = never)."""
        return _env_int("DLT_TRACE_SAMPLE", 1)

    def start(self, trace_id: str | None = None, sampled: bool | None = None) -> Trace:
        """Open a trace. `sampled=None` makes the local 1-in-N decision;
        a non-None value adopts an upstream hop's decision (propagated via
        ``X-DLT-Trace-Sampled``), so one request samples coherently across
        gateway and backend."""
        if sampled is None:
            every = self.sample_every()
            with self._lock:
                self._n += 1
                n = self._n
            sampled = every > 0 and (n % every == 0)
        return Trace(trace_id or mint_trace_id(), bool(sampled), self.ring)

    def event(self, name: str, t_us: int, dur_us: int = 0, keys=(), vals=()) -> None:
        """An engine-level event not owned by any one request (prefix-cache
        publish, watchdog stall) — trace_id ``""``; flight-recorder context."""
        self.ring.append(("", name, t_us, dur_us, tuple(keys), tuple(vals)))

    def bind_global(self, name: str, keys=()) -> Emitter:
        """A pre-bound emitter for engine-level events NOT owned by any one
        request (trace_id ``""``) — the hot-loop twin of :meth:`event`. The
        Batcher's per-step batch-composition timeline rides this: one tuple
        append per step, no dicts, no locks."""
        return Emitter(self.ring, "", name, keys)

    def for_trace(self, trace_id: str) -> list:
        return self.ring.for_trace(trace_id)

    def for_names(self, names) -> list:
        """Ring events whose NAME is in `names` (any trace id) — the
        batch-timeline view reads the ``batch_*`` families this way."""
        names = frozenset(names)
        return [e for e in self.ring.snapshot() if e[1] in names]


TRACER = Tracer()


def global_event(name: str, t_us: int | None = None, dur_us: int = 0, keys=(), vals=()):
    """Emit an engine-level event on the process tracer (see
    :meth:`Tracer.event`)."""
    TRACER.event(name, now_us() if t_us is None else t_us, dur_us, keys, vals)


# -- rendering ---------------------------------------------------------------


def render_event(ev: tuple) -> dict:
    tid, name, t_us, dur_us, keys, vals = ev
    out = {"trace_id": tid, "name": name, "t_us": int(t_us), "dur_us": int(dur_us)}
    if keys:
        out["args"] = dict(zip(keys, vals))
    elif vals:
        out["args"] = {"values": list(vals)}
    return out


def trace_tree(events: list) -> list:
    """Nest a trace's events into a span tree by interval containment:
    events sorted by (start, -duration); an event whose interval falls
    inside the nearest still-open span becomes its child."""
    evs = sorted(events, key=lambda e: (e[2], -e[3]))
    roots: list = []
    stack: list = []  # (end_us, node)
    for ev in evs:
        node = render_event(ev)
        node["children"] = []
        start = ev[2]
        while stack and stack[-1][0] <= start:
            stack.pop()
        (stack[-1][1]["children"] if stack else roots).append(node)
        stack.append((start + ev[3], node))
    return roots


def chrome_trace(events: list) -> list:
    """Chrome ``trace_event`` format (complete events, microsecond ts/dur)
    — paste into chrome://tracing or Perfetto."""
    out = []
    for ev in events:
        tid, name, t_us, dur_us, keys, vals = ev
        out.append(
            {
                "name": name,
                "cat": "dlt",
                "ph": "X",
                "ts": int(t_us),
                "dur": max(int(dur_us), 1),
                "pid": os.getpid(),
                "tid": 0,
                "args": dict(zip(keys, vals)) if keys else {},
            }
        )
    return out


def trace_payload(trace_id: str, events: list) -> dict:
    """The ``/debug/trace`` response body: raw events, span tree, and the
    chrome://tracing export, one self-contained JSON."""
    return {
        "trace_id": trace_id,
        "n_events": len(events),
        "events": [render_event(e) for e in events],
        "tree": trace_tree(events),
        "chrome_trace": chrome_trace(events),
    }


# -- batch-composition timeline ----------------------------------------------

#: the event families the Batcher's timeline emits (server/api.py): one
#: sampled ``batch_step`` snapshot per step (slot composition + pool
#: occupancy) plus always-landed ``batch_park``/``batch_shed`` marks at the
#: pool-pressure decisions — the post-hoc view of batching pathologies
#: (admission stalls, park livelocks, pool thrash).
BATCH_TIMELINE_NAMES = ("batch_step", "batch_park", "batch_shed")


def batch_timeline_chrome(events: list) -> list:
    """Chrome ``trace_event`` view of a batch timeline: each ``batch_step``
    becomes an ``X`` slice (the chunk wall) PLUS counter (``C``) samples —
    ``batch_slots`` stacks decoding/prefilling/free rows, ``kv_pool`` plots
    pages used — so chrome://tracing / Perfetto render slot composition and
    pool pressure as stacked area charts over time; park/shed marks land as
    global instant events."""
    out: list = []
    pid = os.getpid()
    for ev in events:
        _, name, t_us, dur_us, keys, vals = ev
        args = dict(zip(keys, vals))
        if name == "batch_step":
            out.append(
                {
                    "name": "chunk", "cat": "dlt_batch", "ph": "X",
                    "ts": int(t_us), "dur": max(int(dur_us), 1),
                    "pid": pid, "tid": 0, "args": args,
                }
            )
            slots = {
                k: args[k] for k in ("decoding", "prefilling", "free")
                if k in args
            }
            if slots:
                out.append(
                    {
                        "name": "batch_slots", "cat": "dlt_batch", "ph": "C",
                        "ts": int(t_us), "pid": pid, "args": slots,
                    }
                )
            if "pool_pages_used" in args:
                out.append(
                    {
                        "name": "kv_pool", "cat": "dlt_batch", "ph": "C",
                        "ts": int(t_us), "pid": pid,
                        "args": {"pages_used": args["pool_pages_used"]},
                    }
                )
            if "queue_depth" in args:
                out.append(
                    {
                        "name": "backlog", "cat": "dlt_batch", "ph": "C",
                        "ts": int(t_us), "pid": pid,
                        "args": {"queue_depth": args["queue_depth"]},
                    }
                )
        else:  # batch_park / batch_shed: instant marks, global scope
            out.append(
                {
                    "name": name, "cat": "dlt_batch", "ph": "i", "s": "g",
                    "ts": int(t_us), "pid": pid, "tid": 0, "args": args,
                }
            )
    return out


def batch_timeline_payload(events: list) -> dict:
    """The ``/debug/batch_timeline`` response body: raw step snapshots plus
    the chrome://tracing export, one self-contained JSON."""
    return {
        "n_events": len(events),
        "n_steps": sum(1 for e in events if e[1] == "batch_step"),
        "parks": sum(1 for e in events if e[1] == "batch_park"),
        "sheds": sum(1 for e in events if e[1] == "batch_shed"),
        "events": [render_event(e) for e in events],
        "chrome_trace": batch_timeline_chrome(events),
    }


# -- histograms --------------------------------------------------------------

#: fixed log-scale (powers of two) millisecond buckets: cumulative counts
#: survive scrape-to-scrape (standard Prometheus histogram semantics) where
#: the StepStats recent-window percentiles cannot
DEFAULT_BUCKETS_MS = (
    1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0, 512.0,
    1024.0, 2048.0, 4096.0, 8192.0, 16384.0, 32768.0,
)


class Hist:
    """Fixed-bucket cumulative histogram (Prometheus ``le`` semantics:
    a bucket counts observations <= its bound; +Inf is the total)."""

    __slots__ = ("bounds", "_counts", "_sum", "_count", "_lock")

    def __init__(self, bounds=DEFAULT_BUCKETS_MS):
        self.bounds = tuple(float(b) for b in bounds)
        self._counts = [0] * (len(self.bounds) + 1)  # +Inf tail bucket
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        i = bisect.bisect_left(self.bounds, value)
        with self._lock:
            self._counts[i] += 1
            self._sum += value
            self._count += 1

    def snapshot(self) -> dict:
        with self._lock:
            counts = list(self._counts)
            total, count = self._sum, self._count
        buckets = []
        cum = 0
        for b, n in zip(self.bounds, counts):
            cum += n
            buckets.append([b, cum])
        buckets.append(["+Inf", count])
        return {"buckets": buckets, "sum": round(total, 3), "count": count}


# -- Prometheus text exposition ----------------------------------------------

_METRIC_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _metric(name: str) -> str:
    n = _METRIC_RE.sub("_", name)
    return ("_" + n) if n and n[0].isdigit() else n


def _esc(v) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def prom_line(name: str, labels: dict | None, value) -> str:
    lab = (
        ""
        if not labels
        else "{" + ",".join(f'{k}="{_esc(v)}"' for k, v in labels.items()) + "}"
    )
    return f"{name}{lab} {value}"


def render_counters(lines: list, counters: dict, prefix: str = "dlt") -> None:
    for k in sorted(counters):
        m = f"{prefix}_{_metric(k)}_total"
        lines.append(f"# TYPE {m} counter")
        lines.append(prom_line(m, None, counters[k]))


def render_gauges(lines: list, gauges: dict, prefix: str = "dlt") -> None:
    for k in sorted(gauges):
        m = f"{prefix}_{_metric(k)}"
        lines.append(f"# TYPE {m} gauge")
        lines.append(prom_line(m, None, gauges[k]))


def render_hist(lines: list, name: str, snap: dict, labels: dict | None = None,
                type_line: bool = True) -> None:
    """One histogram series; `labels` (e.g. ``{"slo_class": "batch"}``) ride
    every ``_bucket``/``_sum``/``_count`` row next to ``le`` — the per-class
    latency breakdown StepStats.observe(labels=...) produces.
    ``type_line=False`` skips the ``# TYPE`` header: a family with labeled
    breakdown series must declare its TYPE exactly once (the exposition
    format forbids a second TYPE line for the same metric)."""
    if type_line:
        lines.append(f"# TYPE {name} histogram")
    base = dict(labels) if labels else {}
    for le, cum in snap["buckets"]:
        lab = le if isinstance(le, str) else ("%g" % le)
        lines.append(prom_line(name + "_bucket", dict(base, le=lab), cum))
    lines.append(prom_line(name + "_sum", base or None, snap["sum"]))
    lines.append(prom_line(name + "_count", base or None, snap["count"]))


_LABELED_KEY_RE = re.compile(r'^([^{]+)\{(.*)\}$')
_LABEL_PAIR_RE = re.compile(r'(\w+)="([^"]*)"')


def split_labeled_key(key: str):
    """``'ttft_ms{slo_class="batch"}' -> ("ttft_ms", {"slo_class":
    "batch"})`` — the encoding StepStats uses to keep labeled histograms in
    its one flat dict (plain keys pass through with no labels)."""
    m = _LABELED_KEY_RE.match(key)
    if not m:
        return key, None
    labels = dict(_LABEL_PAIR_RE.findall(m.group(2)))
    return m.group(1), labels or None


def render_step_stats(
    stats, extra_gauges: dict | None = None, prefix: str = "dlt",
    extra_series: dict | None = None, extra_counter_series: dict | None = None,
) -> str:
    """Render a StepStats-shaped object (``snapshot()`` with reserved
    ``counters``/``gauges``/``histograms`` keys plus latency series) as
    Prometheus text: counters as ``_total``, gauges as-is, series as
    per-kind quantile gauges + cumulative step counts, histograms as
    cumulative ``_bucket`` series. `extra_series` adds LABELED gauge
    families — ``{name: [(labels_dict, value), ...]}`` — e.g. the HBM
    ledger's ``dlt_hbm_bytes{component=...}`` (runtime/profiling.py);
    `extra_counter_series` the same shape as LABELED counter families
    (``_total`` appended) — e.g. the goodput ledger's
    ``dlt_wasted_tokens_total{reason=...}`` (runtime/telemetry.py)."""
    snap = stats.snapshot()
    counters = snap.pop("counters", {})
    gauges = dict(snap.pop("gauges", {}))
    hists = snap.pop("histograms", {})
    if extra_gauges:
        gauges.update(extra_gauges)
    lines: list = []
    render_counters(lines, counters, prefix)
    render_gauges(lines, gauges, prefix)
    for name in sorted(extra_series or {}):
        m = f"{prefix}_{_metric(name)}"
        lines.append(f"# TYPE {m} gauge")
        for labels, value in extra_series[name]:
            lines.append(prom_line(m, labels, value))
    for name in sorted(extra_counter_series or {}):
        m = f"{prefix}_{_metric(name)}_total"
        lines.append(f"# TYPE {m} counter")
        for labels, value in extra_counter_series[name]:
            lines.append(prom_line(m, labels, value))
    if snap:
        m = f"{prefix}_step_latency_ms"
        lines.append(f"# TYPE {m} gauge")
        for kind in sorted(snap):
            s = snap[kind]
            for q in ("p50", "p95", "p99"):
                lines.append(prom_line(m, {"kind": kind, "quantile": q}, s[f"{q}_ms"]))
        mc = f"{prefix}_steps_total"
        lines.append(f"# TYPE {mc} counter")
        for kind in sorted(snap):
            lines.append(prom_line(mc, {"kind": kind}, snap[kind]["count"]))
    seen_hist_families: set = set()
    for hname in sorted(hists):
        base, labels = split_labeled_key(hname)
        fam = f"{prefix}_{_metric(base)}"
        render_hist(
            lines, fam, hists[hname], labels=labels,
            # ONE TYPE line per family: the unlabeled total and its
            # {slo_class} breakdown series share the declaration
            type_line=fam not in seen_hist_families,
        )
        seen_hist_families.add(fam)
    return "\n".join(lines) + "\n"


PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


# -- flight recorder ---------------------------------------------------------


class FlightRecorder:
    """Post-mortem snapshots of the trace ring. `record(reason)` captures
    the last ``DLT_FLIGHTREC_EVENTS`` events (default 2048) into a JSON
    payload, keeps it for ``/debug/flightrecord``, and best-effort dumps it
    on disk — a failure that takes the process down still leaves the dump
    behind."""

    def __init__(self, tracer: Tracer):
        self.tracer = tracer
        self._lock = threading.Lock()
        self.last: dict | None = None
        self._n = 0

    @staticmethod
    def _dir() -> str | None:
        raw = os.environ.get("DLT_FLIGHTREC_DIR")
        if raw is None:
            return os.path.join(tempfile.gettempdir(), "dlt-flightrecords")
        if raw in ("", "0"):
            return None
        return raw

    def record(self, reason: str, counters: dict | None = None) -> dict:
        keep = _env_int("DLT_FLIGHTREC_EVENTS", 2048)
        events = self.tracer.ring.snapshot()[-keep:]
        payload = {
            "reason": reason,
            "wall_time": time.time(),
            "t_us": now_us(),
            "pid": os.getpid(),
            "n_events": len(events),
            "events": [render_event(e) for e in events],
        }
        if counters:
            payload["counters"] = dict(counters)
        with self._lock:
            self._n += 1
            n = self._n
        d = self._dir()
        if d:
            try:
                os.makedirs(d, exist_ok=True)
                path = os.path.join(
                    d, f"flightrecord-{int(time.time() * 1000)}-{os.getpid()}-{n}.json"
                )
                with open(path, "w") as f:
                    json.dump(payload, f)
                payload["path"] = path
            except OSError:
                pass  # the dump is best-effort: a full disk must not turn
                # a recoverable stall into an unrecoverable crash
        with self._lock:
            self.last = payload
        return payload


FLIGHT = FlightRecorder(TRACER)


def flight_record(reason: str, counters: dict | None = None) -> dict:
    """Snapshot the process trace ring to a post-mortem record (see
    :class:`FlightRecorder`). Called on StallError, ``api.recover()``, and
    fatal sanitizer breaches."""
    return FLIGHT.record(reason, counters)


def last_flight_record() -> dict | None:
    return FLIGHT.last
