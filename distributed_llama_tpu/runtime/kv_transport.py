"""The KV movement layer: every cross-boundary KV transfer in one place.

Three subsystems used to move KV each in their own way — the paged pool
shares pages inside one chip (runtime/paged_kv.py), the prefix cache copies
bucket slices between rows (runtime/prefix_cache.py), and disaggregation
ships whole prefixes over HTTP through the host (server/disagg.py) — and
each exclusion (paged was single-chip, disagg forced contiguous) existed
because the transfers did not compose. This module is the composition
point; ROADMAP item 2:

* **content-addressed page naming** — :func:`page_keys` names each
  :data:`KEY_PAGE_TOKENS`-token span of a token chain by a *chained*
  FNV-1a hash (key ``i`` covers tokens ``[0, (i+1)*16)``), the token-level
  twin of the router's char-block chains (server/router.py
  ``prefix_chain``). Two processes that agree on the tokens agree on the
  names, so a page's identity is its *content*, never a pool-local
  physical page id — a decode worker can tell a prefill worker exactly
  which leading pages it already holds and receive only the rest
  (``disagg_pages_skipped``);
* **transport selection** — one :class:`KvTransport` interface per peer,
  resolved by :func:`resolve_transport` (``DLT_KV_TRANSPORT`` =
  ``auto`` | ``device`` | ``http``): :class:`DeviceKvTransport` moves KV
  as device arrays between same-process peers (the registry below; on
  multi-host deployments the same call shape covers jax-addressable
  devices) with zero host serialization, and :class:`HttpKvTransport`
  keeps the PR 10 length-prefixed binary codec as the portable fallback
  for peers the device path cannot reach. ``auto`` picks device whenever
  the peer is registered, http otherwise — per peer, per fetch;
* **the wire codec** — :func:`kv_payload` / :func:`parse_kv_payload`
  moved here from server/disagg.py (which re-exports them): the header
  grew ``start`` (the token offset of the shipped slice — partial sends
  ship only the pages the requester is missing) and ``page_keys`` (the
  content names of the covered span, so the receiver can verify the
  naming agreement instead of trusting it).

Every transfer is accounted per path: the ``kv_transfer_us[{path}]``
StepStats series (rendered as the labeled ``dlt_kv_transfer_us`` family)
and the ``kv_transfer_bytes_{path}`` counters (rendered as
``dlt_kv_transfer_bytes_total{path=...}``) — the goodput ledger's
``kv_transfer_us``/``kv_transfer_path`` fields carry the per-request view.

stdlib + numpy only at import time: the gateway-side tests and the codec
unit tests must not drag jax in.
"""

from __future__ import annotations

import json
import os
import struct
import threading

import numpy as np

#: tokens per content-addressed page name. Matches the paged pool's default
#: page size AND the prefix cache's publish floor (PREFIX_MIN_TOKENS), so
#: every bucket boundary both caches speak is a whole number of named pages.
KEY_PAGE_TOKENS = 16

KV_TRANSPORTS = ("auto", "device", "http")

_FNV64_OFFSET = 0xCBF29CE484222325
_FNV64_PRIME = 0x100000001B3


def resolve_transport(explicit: str | None = None) -> str:
    """THE one resolver of the KV transport mode: an explicit value wins;
    otherwise ``DLT_KV_TRANSPORT``; unset/unrecognized means ``auto``
    (device for registered same-process peers, http for everyone else)."""
    mode = explicit
    if mode is None:
        raw = (os.environ.get("DLT_KV_TRANSPORT") or "").strip().lower()
        mode = raw if raw in KV_TRANSPORTS else "auto"
    mode = mode.strip().lower()
    if mode not in KV_TRANSPORTS:
        raise ValueError(
            f"unknown kv transport {mode!r} (choose from {KV_TRANSPORTS})"
        )
    return mode


def _fnv1a_bytes(data: bytes, h: int = _FNV64_OFFSET) -> int:
    for b in data:
        h ^= b
        h = (h * _FNV64_PRIME) & 0xFFFFFFFFFFFFFFFF
    return h


def page_keys(tokens, page_tokens: int = KEY_PAGE_TOKENS) -> tuple:
    """Chained content names of a token chain's FULL pages: key ``i`` is
    the FNV-1a hash of page ``i``'s token ids (4-byte little-endian each)
    seeded with key ``i-1`` — so chains sharing a leading span share
    exactly the keys that span covers, and a one-token divergence renames
    every later page (the radix property, hashed). Only complete pages are
    named: a partial tail has no stable identity to ship."""
    out = []
    h = _FNV64_OFFSET
    n_full = len(tokens) // page_tokens
    for i in range(n_full):
        page = tokens[i * page_tokens : (i + 1) * page_tokens]
        h = _fnv1a_bytes(
            b"".join(struct.pack("<i", int(t)) for t in page), h
        )
        out.append(h)
    return tuple(out)


def doubling_segments(start: int, end: int) -> list:
    """Split ``[start, end)`` into segments along the binary doubling
    ladder: ``[s, 2s), [2s, 4s), ...`` — when `start` and `end` are prefix
    buckets (powers of two on the cache ladder), every segment length is
    itself a bucket, so paged scatter/gather dispatches stay on the warmed
    program ladder with no padding. ``start == 0`` is one full segment."""
    if start <= 0:
        return [(0, end)]
    out = []
    s = start
    while s < end:
        e = min(2 * s, end)
        out.append((s, e))
        s = e
    return out


def matching_pages(expected_keys, have_keys) -> int:
    """Longest leading run of ``have_keys`` matching ``expected_keys`` —
    the pages a transfer can skip. A mid-run mismatch stops the match
    (chained keys make any later agreement impossible anyway)."""
    n = 0
    for e, h in zip(expected_keys, have_keys):
        if int(e) != int(h):
            break
        n += 1
    return n


# -- the wire format ----------------------------------------------------------
#
# 4-byte big-endian header length | JSON header | raw k bytes | raw v bytes
# Header: tokens (ALL P token ids the boundary covers), p, start (token
# offset of the shipped slice — 0 for a full send, a page multiple when the
# requester already held the leading pages), page_keys (content names of the
# full span, hex strings), k_shape/v_shape (of the SHIPPED slice), dtype,
# prefill_us (the worker's wall — the decode side's ledger field). Raw bytes
# rather than base64-in-JSON: a 512-token 8B-class slice is tens of MB and
# the transfer wall is the metric under test.


def kv_payload(header: dict, k_np: np.ndarray, v_np: np.ndarray) -> bytes:
    hjson = json.dumps(header).encode()
    return struct.pack(">I", len(hjson)) + hjson + k_np.tobytes() + v_np.tobytes()


def _np_dtype(name: str):
    """Dtype-by-name incl. the ml_dtypes extended floats (``np.dtype``
    alone does not know ``bfloat16``)."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def parse_kv_payload(body: bytes):
    """``(header, k_np, v_np)`` from one payload; raises ValueError on any
    truncation or shape/dtype mismatch (the caller's degradation path)."""
    if len(body) < 4:
        raise ValueError("kv payload truncated before header length")
    (hlen,) = struct.unpack(">I", body[:4])
    if len(body) < 4 + hlen:
        raise ValueError("kv payload truncated inside header")
    header = json.loads(body[4 : 4 + hlen])
    dt = _np_dtype(header["dtype"])
    k_shape = tuple(header["k_shape"])
    v_shape = tuple(header["v_shape"])
    k_bytes = int(np.prod(k_shape)) * dt.itemsize
    v_bytes = int(np.prod(v_shape)) * dt.itemsize
    blob = body[4 + hlen :]
    if len(blob) != k_bytes + v_bytes:
        raise ValueError(
            f"kv payload truncated: body {len(blob)} B, "
            f"header names {k_bytes + v_bytes} B"
        )
    k = np.frombuffer(blob[:k_bytes], dtype=dt).reshape(k_shape)
    v = np.frombuffer(blob[k_bytes:], dtype=dt).reshape(v_shape)
    return header, k, v


# -- the same-process peer registry -------------------------------------------
#
# serve() registers each API server's state under its port; a decode
# worker whose --prefill-peer names a registered port reaches the prefill
# engine without touching a socket (the common test/colocated-roles shape,
# and the faithful single-host stand-in for jax-addressable-device
# transfer on a real pod). The provider contract is duck-typed — an object
# with `.role` and `.prefill_extract(ids, have_keys, trace=None) ->
# (header, k_arr, v_arr)` — so this module never imports the server.

_registry_lock = threading.Lock()
_device_peers: dict = {}  # port -> weakref.ref(provider)

#: test hook: when set, DeviceKvTransport.fetch raises it once per fetch —
#: the chaos twin proves a device-path failure degrades exactly like a
#: dead HTTP peer (see tests/test_kv_transport.py)
_device_chaos: list = []


def register_device_peer(port: int, provider) -> None:
    """Register a provider under its port. WEAK reference on purpose: the
    registry must never keep a torn-down server's engine (weights + KV
    pool) alive, and a dead ref heals `auto` back to the HTTP path for
    embedders that cycle servers on reused ports."""
    import weakref

    with _registry_lock:
        _device_peers[int(port)] = weakref.ref(provider)


def unregister_device_peer(port: int) -> None:
    with _registry_lock:
        _device_peers.pop(int(port), None)


def device_peer(port: int):
    with _registry_lock:
        ref = _device_peers.get(int(port))
        if ref is None:
            return None
        provider = ref()
        if provider is None:  # collected: prune the dead entry
            _device_peers.pop(int(port), None)
        return provider


def set_device_chaos(exc: BaseException | None) -> None:
    """Arm (or clear, with None) a one-shot device-path failure."""
    _device_chaos[:] = [exc] if exc is not None else []


# -- transports ---------------------------------------------------------------


class TransferResult:
    """One completed fetch: the worker's header, the KV arrays (numpy on
    the http path; device arrays — possibly per-doubling-segment LISTS —
    on the device path; the prefix cache's insert handles all three), the
    path taken, and the bytes that moved."""

    __slots__ = ("header", "k", "v", "path", "nbytes")

    def __init__(self, header, k, v, path, nbytes):
        self.header = header
        self.k = k
        self.v = v
        self.path = path
        self.nbytes = int(nbytes)


def _arrays_nbytes(x) -> int:
    if isinstance(x, (list, tuple)):
        return sum(int(getattr(a, "nbytes", 0)) for a in x)
    return int(getattr(x, "nbytes", 0))


class KvTransport:
    """One way of moving a prefix-KV slice from a prefill peer. `fetch`
    raises OSError/ValueError on any failure — the DisaggClient's
    degradation machinery (backoff, failover, local prefill) is
    transport-agnostic by construction."""

    path = "?"

    def fetch(self, peer, ids, have_keys=(), trace_id=None) -> TransferResult:
        raise NotImplementedError


class DeviceKvTransport(KvTransport):
    """Same-process (or jax-addressable) peer: call the registered
    provider directly and hand its device arrays straight to the local
    prefix cache — no socket, no host serialization, no byte copy of the
    KV payload. The bytes accounted are the slice's device bytes (what an
    ICI/DCN transfer would move on a real pod)."""

    path = "device"

    def fetch(self, peer, ids, have_keys=(), trace_id=None) -> TransferResult:
        if _device_chaos:
            exc = _device_chaos.pop()
            raise exc
        host, port = peer
        provider = device_peer(port)
        if provider is None:
            raise OSError(f"no same-process device peer at {host}:{port}")
        if getattr(provider, "role", None) != "prefill":
            # mirrors the HTTP path's 404 from a non-prefill replica
            raise OSError(f"device peer {host}:{port} does not serve prefill")
        header, k, v = provider.prefill_extract(
            list(ids), have_keys=tuple(have_keys), trace_id=trace_id
        )
        nbytes = _arrays_nbytes(k) + _arrays_nbytes(v)
        return TransferResult(header, k, v, self.path, nbytes)


class HttpKvTransport(KvTransport):
    """The portable fallback: POST /v1/prefill, length-prefixed binary
    payload back (the PR 10 codec). Works across any network boundary; a
    mid-body peer death surfaces as the same OSError/ValueError family
    the device path raises."""

    path = "http"

    def __init__(self, timeout_s: float):
        self.timeout_s = timeout_s

    def fetch(self, peer, ids, have_keys=(), trace_id=None) -> TransferResult:
        import http.client

        from .tracing import TRACE_HEADER

        host, port = peer
        conn = http.client.HTTPConnection(host, port, timeout=self.timeout_s)
        try:
            headers = {"Content-Type": "application/json", "Connection": "close"}
            if trace_id:
                headers[TRACE_HEADER] = trace_id
            body = {"ids": list(ids)}
            if have_keys:
                # content names of the pages this side already holds — the
                # worker ships only what the names don't cover
                body["have"] = [format(int(h), "x") for h in have_keys]
            conn.request(
                "POST", "/v1/prefill", body=json.dumps(body), headers=headers
            )
            resp = conn.getresponse()
            raw = resp.read()
            if resp.status != 200:
                raise OSError(f"/v1/prefill returned {resp.status}")
        finally:
            conn.close()
        header, k, v = parse_kv_payload(raw)
        return TransferResult(header, k, v, self.path, len(raw))


def build_transports(timeout_s: float) -> dict:
    """The per-process transport instances a DisaggClient selects from."""
    return {
        "device": DeviceKvTransport(),
        "http": HttpKvTransport(timeout_s),
    }


def transport_for(mode: str, peer, transports: dict) -> KvTransport:
    """Pick the transport for ONE peer under `mode`: explicit modes are
    absolute; ``auto`` takes the device path exactly when the peer is
    registered in this process."""
    if mode == "device":
        return transports["device"]
    if mode == "http":
        return transports["http"]
    _, port = peer
    return transports["device"] if device_peer(port) is not None else transports["http"]
