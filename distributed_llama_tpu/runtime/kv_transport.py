"""The KV movement layer: every cross-boundary KV transfer in one place.

Three subsystems used to move KV each in their own way — the paged pool
shares pages inside one chip (runtime/paged_kv.py), the prefix cache copies
bucket slices between rows (runtime/prefix_cache.py), and disaggregation
ships whole prefixes over HTTP through the host (server/disagg.py) — and
each exclusion (paged was single-chip, disagg forced contiguous) existed
because the transfers did not compose. This module is the composition
point; ROADMAP item 2:

* **content-addressed page naming** — :func:`page_keys` names each
  :data:`KEY_PAGE_TOKENS`-token span of a token chain by a *chained*
  FNV-1a hash (key ``i`` covers tokens ``[0, (i+1)*16)``), the token-level
  twin of the router's char-block chains (server/router.py
  ``prefix_chain``). Two processes that agree on the tokens agree on the
  names, so a page's identity is its *content*, never a pool-local
  physical page id — a decode worker can tell a prefill worker exactly
  which leading pages it already holds and receive only the rest
  (``disagg_pages_skipped``);
* **transport selection** — one :class:`KvTransport` interface per peer,
  resolved by :func:`resolve_transport` (``DLT_KV_TRANSPORT`` =
  ``auto`` | ``device`` | ``http``): :class:`DeviceKvTransport` moves KV
  as device arrays between same-process peers (the registry below; on
  multi-host deployments the same call shape covers jax-addressable
  devices) with zero host serialization, and :class:`HttpKvTransport`
  keeps the PR 10 length-prefixed binary codec as the portable fallback
  for peers the device path cannot reach. ``auto`` picks device whenever
  the peer is registered, http otherwise — per peer, per fetch;
* **the wire codec** — :func:`kv_payload` / :func:`parse_kv_payload`
  moved here from server/disagg.py (which re-exports them): the header
  grew ``start`` (the token offset of the shipped slice — partial sends
  ship only the pages the requester is missing) and ``page_keys`` (the
  content names of the covered span, so the receiver can verify the
  naming agreement instead of trusting it);
* **data-plane integrity** — wire version :data:`WIRE_VERSION` carries
  per-doubling-segment byte checksums (``k_sums``/``v_sums``) next to the
  token-derived ``page_keys`` echo, and :func:`verify_transfer` checks
  BOTH on receipt, before anything can touch the receiver's prefix cache:
  a flipped bit, a truncated-but-parseable body, or a stale page from a
  port-reused peer is a :class:`KvIntegrityError` — the caller's existing
  degrade path (reject, cold-prefill locally, token-identical output) —
  never a poisoned cache entry. The device path verifies the cheap
  metadata half (token chain, segment shapes, dtype, entry bounds): the
  bytes never leave the process. An unknown wire version is the separate
  :class:`KvVersionError` (skip the peer, never strike it — rolling
  deploys mix versions without quarantining innocents).

Every transfer is accounted per path: the ``kv_transfer_us[{path}]``
StepStats series (rendered as the labeled ``dlt_kv_transfer_us`` family)
and the ``kv_transfer_bytes_{path}`` counters (rendered as
``dlt_kv_transfer_bytes_total{path=...}``) — the goodput ledger's
``kv_transfer_us``/``kv_transfer_path`` fields carry the per-request view.

stdlib + numpy only at import time: the gateway-side tests and the codec
unit tests must not drag jax in.
"""

from __future__ import annotations

import json
import math
import os
import struct
import threading
import zlib

import numpy as np

#: tokens per content-addressed page name. Matches the paged pool's default
#: page size AND the prefix cache's publish floor (PREFIX_MIN_TOKENS), so
#: every bucket boundary both caches speak is a whole number of named pages.
KEY_PAGE_TOKENS = 16

KV_TRANSPORTS = ("auto", "device", "http")

#: wire codec version. v1 (PR 10) shipped bytes untagged and unchecked;
#: v2 adds the version field itself plus per-segment checksums and the
#: page_keys echo that verify_transfer checks before any cache insert.
WIRE_VERSION = 2

_FNV64_OFFSET = 0xCBF29CE484222325
_FNV64_PRIME = 0x100000001B3


class KvCodecError(ValueError):
    """A wire payload this build cannot use: truncation, garbage header,
    shape/dtype nonsense. A ValueError subclass so every pre-existing
    ``except ValueError`` degrade path keeps catching it — but the
    DisaggClient can now tell a complete-but-wrong response (this family:
    strike the peer) from a transport death (OSError: back off the peer)."""


class KvIntegrityError(KvCodecError):
    """The payload parsed but its content is wrong: checksum mismatch,
    page_keys echo disagreeing with the token chain, tokens that are not
    the ones asked for, shapes that do not cover the slice. The one
    corruption signal — the receiver rejects BEFORE the cache is touched
    and strikes the peer (corrupt-peer quarantine)."""


class KvVersionError(KvCodecError):
    """The peer speaks a different wire version. Rejected cleanly at the
    header — never mid-body as a generic parse error — and NEVER a strike:
    a mixed-version fleet mid-rolling-deploy is healthy, just incompatible;
    the client skips the peer (``disagg_peer_version_mismatch``)."""


def resolve_transport(explicit: str | None = None) -> str:
    """THE one resolver of the KV transport mode: an explicit value wins;
    otherwise ``DLT_KV_TRANSPORT``; unset/unrecognized means ``auto``
    (device for registered same-process peers, http for everyone else)."""
    mode = explicit
    if mode is None:
        raw = (os.environ.get("DLT_KV_TRANSPORT") or "").strip().lower()
        mode = raw if raw in KV_TRANSPORTS else "auto"
    mode = mode.strip().lower()
    if mode not in KV_TRANSPORTS:
        raise ValueError(
            f"unknown kv transport {mode!r} (choose from {KV_TRANSPORTS})"
        )
    return mode


def _fnv1a_bytes(data: bytes, h: int = _FNV64_OFFSET) -> int:
    for b in data:
        h ^= b
        h = (h * _FNV64_PRIME) & 0xFFFFFFFFFFFFFFFF
    return h


def page_keys(tokens, page_tokens: int = KEY_PAGE_TOKENS) -> tuple:
    """Chained content names of a token chain's FULL pages: key ``i`` is
    the FNV-1a hash of page ``i``'s token ids (4-byte little-endian each)
    seeded with key ``i-1`` — so chains sharing a leading span share
    exactly the keys that span covers, and a one-token divergence renames
    every later page (the radix property, hashed). Only complete pages are
    named: a partial tail has no stable identity to ship."""
    out = []
    h = _FNV64_OFFSET
    n_full = len(tokens) // page_tokens
    for i in range(n_full):
        page = tokens[i * page_tokens : (i + 1) * page_tokens]
        h = _fnv1a_bytes(
            b"".join(struct.pack("<i", int(t)) for t in page), h
        )
        out.append(h)
    return tuple(out)


def doubling_segments(start: int, end: int) -> list:
    """Split ``[start, end)`` into segments along the binary doubling
    ladder: ``[s, 2s), [2s, 4s), ...`` — when `start` and `end` are prefix
    buckets (powers of two on the cache ladder), every segment length is
    itself a bucket, so paged scatter/gather dispatches stay on the warmed
    program ladder with no padding. ``start == 0`` is one full segment."""
    if start <= 0:
        return [(0, end)]
    out = []
    s = start
    while s < end:
        e = min(2 * s, end)
        out.append((s, e))
        s = e
    return out


def matching_pages(expected_keys, have_keys) -> int:
    """Longest leading run of ``have_keys`` matching ``expected_keys`` —
    the pages a transfer can skip. A mid-run mismatch stops the match
    (chained keys make any later agreement impossible anyway)."""
    n = 0
    for e, h in zip(expected_keys, have_keys):
        if int(e) != int(h):
            break
        n += 1
    return n


# -- the wire format ----------------------------------------------------------
#
# 4-byte big-endian header length | JSON header | raw k bytes | raw v bytes
# Header: v (wire version), tokens (ALL P token ids the boundary covers),
# p, start (token offset of the shipped slice — 0 for a full send, a page
# multiple when the requester already held the leading pages), page_keys
# (content names of the full span, hex strings), k_shape/v_shape (of the
# SHIPPED slice), dtype, k_sums/v_sums (per-doubling-segment byte
# checksums, hex strings), prefill_us (the worker's wall — the decode
# side's ledger field). Raw bytes rather than base64-in-JSON: a 512-token
# 8B-class slice is tens of MB and the transfer wall is the metric under
# test — which is also why the checksum is crc32 (C speed, stdlib,
# xxhash-style cost) and not the pure-python FNV loop that names pages:
# page_keys hash a few hundred token ids, the sums hash the multi-MB body.


def segment_checksum(data: bytes) -> int:
    """Byte checksum of ONE doubling segment's raw k (or v) bytes."""
    return zlib.crc32(data) & 0xFFFFFFFF


def kv_payload(header: dict, k_np: np.ndarray, v_np: np.ndarray) -> bytes:
    # the codec owns the version field: every payload this build emits is
    # stamped, so a v3 receiver can reject it at the header
    if "v" not in header:
        header = dict(header, v=WIRE_VERSION)
    hjson = json.dumps(header).encode()
    return struct.pack(">I", len(hjson)) + hjson + k_np.tobytes() + v_np.tobytes()


def _np_dtype(name: str):
    """Dtype-by-name incl. the ml_dtypes extended floats (``np.dtype``
    alone does not know ``bfloat16``)."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def parse_kv_payload(body: bytes):
    """``(header, k_np, v_np)`` from one payload.

    Raises :class:`KvVersionError` on a wire-version mismatch (decided at
    the header, before any body work) and :class:`KvCodecError` — both
    ValueError subclasses — on EVERYTHING else a hostile or truncated body
    can be: fuzz-hardened, so a handler thread never sees a KeyError /
    TypeError / AttributeError escape from a garbage payload."""
    if not isinstance(body, (bytes, bytearray, memoryview)):
        raise KvCodecError(f"kv payload is {type(body).__name__}, not bytes")
    body = bytes(body)
    if len(body) < 4:
        raise KvCodecError("kv payload truncated before header length")
    (hlen,) = struct.unpack(">I", body[:4])
    if hlen > len(body) - 4:
        raise KvCodecError("kv payload truncated inside header")
    try:
        header = json.loads(body[4 : 4 + hlen])
    except ValueError as e:  # JSONDecodeError and bad-encoding both
        raise KvCodecError(f"kv header is not JSON: {e}") from None
    if not isinstance(header, dict):
        raise KvCodecError("kv header is not a JSON object")
    try:
        ver = int(header.get("v", 1))
    except (TypeError, ValueError):
        raise KvCodecError(f"unreadable kv wire version {header.get('v')!r}") from None
    if ver != WIRE_VERSION:
        raise KvVersionError(
            f"kv wire version {ver}; this build speaks {WIRE_VERSION}"
        )
    try:
        dt = _np_dtype(header["dtype"])
        k_shape = tuple(int(d) for d in header["k_shape"])
        v_shape = tuple(int(d) for d in header["v_shape"])
    except Exception as e:
        # KeyError (field missing), TypeError (np.dtype on garbage),
        # AttributeError (unknown ml_dtypes name), ValueError (non-int
        # dims) — all the same clean rejection
        raise KvCodecError(f"kv header unusable: {type(e).__name__}: {e}") from None
    if any(d < 0 for d in k_shape + v_shape):
        raise KvCodecError("negative dimension in kv header shapes")
    # math.prod, not np.prod: exact python ints — a garbage header naming
    # absurd dims must mismatch the body length, never wrap an int64
    k_bytes = math.prod(k_shape) * dt.itemsize
    v_bytes = math.prod(v_shape) * dt.itemsize
    blob = body[4 + hlen :]
    if len(blob) != k_bytes + v_bytes:
        raise KvCodecError(
            f"kv payload truncated: body {len(blob)} B, "
            f"header names {k_bytes + v_bytes} B"
        )
    try:
        k = np.frombuffer(blob[:k_bytes], dtype=dt).reshape(k_shape)
        v = np.frombuffer(blob[k_bytes:], dtype=dt).reshape(v_shape)
    except (ValueError, TypeError) as e:  # e.g. object dtype in the header
        raise KvCodecError(f"kv body unusable: {e}") from None
    return header, k, v


# -- receipt verification -----------------------------------------------------
#
# THE data-plane integrity gate: every fetched transfer passes through
# verify_transfer BEFORE insert_external/scatter_pages can touch the
# receiver's cache. Deliberately emit-free: this loop walks a multi-MB
# body (TRACE_EMIT_SCOPE's trace-hot-emit lint guards it) — the caller
# lands ONE kv_integrity event per rejection, outside any loop.


def verify_transfer(result, ids, P: int, page_tokens: int = KEY_PAGE_TOKENS):
    """Verify one fetched transfer against the tokens the CLIENT asked for.

    Checks, in cost order: wire version (:class:`KvVersionError` on
    mismatch), the token echo (the returned chain must be ``ids[:P]``
    exactly), slice bounds (``start`` aligned and inside ``[0, P)``), the
    ``page_keys`` echo against a local recomputation of the chained token
    hashes, and then the path-specific half: the HTTP path recomputes the
    per-doubling-segment byte checksums over the received k/v bytes; the
    device path — whose bytes never left the process — checks segment
    count, per-segment shapes, and k/v dtype agreement. Any content
    mismatch raises :class:`KvIntegrityError`; returns None on success."""
    header = result.header
    if not isinstance(header, dict):
        raise KvIntegrityError(f"kv header is {type(header).__name__}, not a dict")
    try:
        ver = int(header.get("v", 1))
    except (TypeError, ValueError):
        raise KvVersionError(f"unreadable kv wire version {header.get('v')!r}") from None
    if ver != WIRE_VERSION:
        raise KvVersionError(
            f"kv wire version {ver}; this build speaks {WIRE_VERSION}"
        )
    try:
        tokens = [int(t) for t in header["tokens"]]
        start = int(header.get("start", 0))
        p = int(header.get("p", len(tokens)))
        pt = int(header.get("page_tokens", page_tokens))
        echoed = tuple(int(h, 16) for h in header["page_keys"])
    except Exception as e:
        raise KvIntegrityError(
            f"kv header unusable: {type(e).__name__}: {e}"
        ) from None
    if tokens != [int(t) for t in ids[:P]]:
        raise KvIntegrityError("peer returned KV for different tokens")
    if p != P:
        raise KvIntegrityError(f"peer names boundary p={p}, asked {P}")
    if pt != page_tokens:
        raise KvIntegrityError(f"peer names page granularity {pt}, not {page_tokens}")
    if start < 0 or start >= P or start % page_tokens:
        raise KvIntegrityError(f"kv slice start {start} out of bounds for p={P}")
    if echoed != page_keys(tokens, page_tokens):
        raise KvIntegrityError("page_keys echo does not match the token chain")
    if result.path == "http":
        k, v = result.k, result.v
        spans = doubling_segments(start, P)
        try:
            k_sums = [int(s, 16) for s in header["k_sums"]]
            v_sums = [int(s, 16) for s in header["v_sums"]]
        except Exception as e:
            raise KvIntegrityError(
                f"v{ver} payload carries no usable checksums: "
                f"{type(e).__name__}: {e}"
            ) from None
        if len(k_sums) != len(spans) or len(v_sums) != len(spans):
            raise KvIntegrityError(
                f"{len(k_sums)}/{len(v_sums)} checksums do not cover "
                f"{len(spans)} doubling segments"
            )
        if k.ndim != 4 or k.shape[1] != P - start or v.shape != k.shape:
            raise KvIntegrityError(
                f"kv shapes {tuple(k.shape)}/{tuple(v.shape)} do not cover "
                f"tokens [{start}, {P})"
            )
        for i, (a, b) in enumerate(spans):
            ks = segment_checksum(k[:, a - start : b - start].tobytes())
            vs = segment_checksum(v[:, a - start : b - start].tobytes())
            if ks != k_sums[i] or vs != v_sums[i]:
                raise KvIntegrityError(
                    f"segment [{a}, {b}) checksum mismatch "
                    f"(k {ks:#x} vs {k_sums[i]:#x}, v {vs:#x} vs {v_sums[i]:#x})"
                )
    else:
        # device path: the arrays are the sender's own device buffers —
        # byte-hashing them would force a device->host sync for data that
        # never crossed a wire. Verify the metadata half instead.
        if isinstance(result.k, (list, tuple)):
            ks_list = list(result.k)
            vs_list = list(result.v) if isinstance(result.v, (list, tuple)) else []
            spans = doubling_segments(start, P)
            if len(ks_list) != len(spans) or len(vs_list) != len(spans):
                raise KvIntegrityError(
                    f"{len(ks_list)}/{len(vs_list)} device segments do not "
                    f"cover {len(spans)} doubling segments"
                )
        else:
            # contiguous extract ships tokens [start, P) as one segment
            ks_list, vs_list = [result.k], [result.v]
            spans = [(start, P)]
        for (a, b), ka, va in zip(spans, ks_list, vs_list):
            ksh = tuple(getattr(ka, "shape", ()))
            vsh = tuple(getattr(va, "shape", ()))
            if len(ksh) != 4 or ksh[1] != b - a or vsh != ksh:
                raise KvIntegrityError(
                    f"device segment [{a}, {b}) shapes {ksh}/{vsh} do not "
                    f"cover its {b - a} tokens"
                )
            if getattr(ka, "dtype", None) != getattr(va, "dtype", None):
                raise KvIntegrityError(
                    f"device segment [{a}, {b}) k/v dtype mismatch"
                )
    return None


# -- the same-process peer registry -------------------------------------------
#
# serve() registers each API server's state under its port; a decode
# worker whose --prefill-peer names a registered port reaches the prefill
# engine without touching a socket (the common test/colocated-roles shape,
# and the faithful single-host stand-in for jax-addressable-device
# transfer on a real pod). The provider contract is duck-typed — an object
# with `.role` and `.prefill_extract(ids, have_keys, trace=None) ->
# (header, k_arr, v_arr)` — so this module never imports the server.

_registry_lock = threading.Lock()
_device_peers: dict = {}  # port -> weakref.ref(provider)

#: test hook: one-shot device-path faults — ("raise", exc) makes the next
#: fetch die like a dead HTTP peer; ("corrupt", mode) lets the fetch
#: complete and then corrupts its result the way a buggy/stale provider
#: would, so the chaos twin proves the metadata verifier rejects it
#: (see tests/test_kv_transport.py and tests/test_kv_integrity.py)
_device_chaos: list = []

#: corrupt modes: the three metadata surfaces the device verifier covers
DEVICE_CORRUPT_MODES = ("page_keys", "tokens", "shape")


def register_device_peer(port: int, provider) -> None:
    """Register a provider under its port. WEAK reference on purpose: the
    registry must never keep a torn-down server's engine (weights + KV
    pool) alive, and a dead ref heals `auto` back to the HTTP path for
    embedders that cycle servers on reused ports."""
    import weakref

    with _registry_lock:
        _device_peers[int(port)] = weakref.ref(provider)


def unregister_device_peer(port: int) -> None:
    with _registry_lock:
        _device_peers.pop(int(port), None)


def device_peer(port: int):
    with _registry_lock:
        ref = _device_peers.get(int(port))
        if ref is None:
            return None
        provider = ref()
        if provider is None:  # collected: prune the dead entry
            _device_peers.pop(int(port), None)
        return provider


def set_device_chaos(exc: BaseException | None = None,
                     corrupt: str | None = None) -> None:
    """Arm a one-shot device-path fault: ``exc`` raises it mid-fetch
    (fail-stop twin), ``corrupt`` completes the fetch and then mangles one
    metadata surface of the result (:data:`DEVICE_CORRUPT_MODES` — the
    wrong-data twin). ``set_device_chaos(None)`` / no args clears."""
    if exc is not None:
        _device_chaos[:] = [("raise", exc)]
    elif corrupt is not None:
        if corrupt not in DEVICE_CORRUPT_MODES:
            raise ValueError(
                f"unknown corrupt mode {corrupt!r} (one of {DEVICE_CORRUPT_MODES})"
            )
        _device_chaos[:] = [("corrupt", corrupt)]
    else:
        _device_chaos[:] = []


def _corrupt_device_result(result: "TransferResult", mode: str) -> None:
    """Mutate a completed device fetch the way a wrong-data provider
    would: a stale page_keys chain, a token echo for someone else's
    prompt, or a slice that does not cover its claimed span."""
    header = result.header
    if mode == "page_keys" and header.get("page_keys"):
        keys = list(header["page_keys"])
        keys[-1] = format(int(keys[-1], 16) ^ 0x1, "x")
        header["page_keys"] = keys
    elif mode == "tokens" and header.get("tokens"):
        toks = list(header["tokens"])
        toks[-1] = int(toks[-1]) ^ 0x1
        header["tokens"] = toks
    elif mode == "shape":
        if isinstance(result.k, (list, tuple)):
            result.k = list(result.k)[:-1]  # one segment short
        else:
            result.k = result.k[:, :-1]  # one token short


# -- transports ---------------------------------------------------------------


class TransferResult:
    """One completed fetch: the worker's header, the KV arrays (numpy on
    the http path; device arrays — possibly per-doubling-segment LISTS —
    on the device path; the prefix cache's insert handles all three), the
    path taken, and the bytes that moved."""

    __slots__ = ("header", "k", "v", "path", "nbytes")

    def __init__(self, header, k, v, path, nbytes):
        self.header = header
        self.k = k
        self.v = v
        self.path = path
        self.nbytes = int(nbytes)


def _arrays_nbytes(x) -> int:
    if isinstance(x, (list, tuple)):
        return sum(int(getattr(a, "nbytes", 0)) for a in x)
    return int(getattr(x, "nbytes", 0))


class KvTransport:
    """One way of moving a prefix-KV slice from a prefill peer. `fetch`
    raises OSError/ValueError on any failure — the DisaggClient's
    degradation machinery (backoff, failover, local prefill) is
    transport-agnostic by construction."""

    path = "?"

    def fetch(self, peer, ids, have_keys=(), trace_id=None) -> TransferResult:
        raise NotImplementedError


class DeviceKvTransport(KvTransport):
    """Same-process (or jax-addressable) peer: call the registered
    provider directly and hand its device arrays straight to the local
    prefix cache — no socket, no host serialization, no byte copy of the
    KV payload. The bytes accounted are the slice's device bytes (what an
    ICI/DCN transfer would move on a real pod)."""

    path = "device"

    def fetch(self, peer, ids, have_keys=(), trace_id=None) -> TransferResult:
        chaos = _device_chaos.pop() if _device_chaos else None
        if chaos is not None and chaos[0] == "raise":
            raise chaos[1]
        host, port = peer
        provider = device_peer(port)
        if provider is None:
            raise OSError(f"no same-process device peer at {host}:{port}")
        if getattr(provider, "role", None) != "prefill":
            # mirrors the HTTP path's 404 from a non-prefill replica
            raise OSError(f"device peer {host}:{port} does not serve prefill")
        header, k, v = provider.prefill_extract(
            list(ids), have_keys=tuple(have_keys), trace_id=trace_id
        )
        nbytes = _arrays_nbytes(k) + _arrays_nbytes(v)
        result = TransferResult(header, k, v, self.path, nbytes)
        if chaos is not None and chaos[0] == "corrupt":
            _corrupt_device_result(result, chaos[1])
        return result


class HttpKvTransport(KvTransport):
    """The portable fallback: POST /v1/prefill, length-prefixed binary
    payload back (the PR 10 codec). Works across any network boundary; a
    mid-body peer death surfaces as the same OSError/ValueError family
    the device path raises."""

    path = "http"

    def __init__(self, timeout_s: float):
        self.timeout_s = timeout_s

    def fetch(self, peer, ids, have_keys=(), trace_id=None) -> TransferResult:
        import http.client

        from .tracing import TRACE_HEADER

        host, port = peer
        conn = http.client.HTTPConnection(host, port, timeout=self.timeout_s)
        try:
            headers = {"Content-Type": "application/json", "Connection": "close"}
            if trace_id:
                headers[TRACE_HEADER] = trace_id
            body = {"ids": list(ids)}
            if have_keys:
                # content names of the pages this side already holds — the
                # worker ships only what the names don't cover
                body["have"] = [format(int(h), "x") for h in have_keys]
            conn.request(
                "POST", "/v1/prefill", body=json.dumps(body), headers=headers
            )
            resp = conn.getresponse()
            raw = resp.read()
            if resp.status != 200:
                raise OSError(f"/v1/prefill returned {resp.status}")
        finally:
            conn.close()
        header, k, v = parse_kv_payload(raw)
        return TransferResult(header, k, v, self.path, len(raw))


def build_transports(timeout_s: float) -> dict:
    """The per-process transport instances a DisaggClient selects from."""
    return {
        "device": DeviceKvTransport(),
        "http": HttpKvTransport(timeout_s),
    }


def transport_for(mode: str, peer, transports: dict) -> KvTransport:
    """Pick the transport for ONE peer under `mode`: explicit modes are
    absolute; ``auto`` takes the device path exactly when the peer is
    registered in this process."""
    if mode == "device":
        return transports["device"]
    if mode == "http":
        return transports["http"]
    _, port = peer
    return transports["device"] if device_peer(port) is not None else transports["http"]
