"""Observability: stall watchdog, step-latency statistics, memory report.

TPU equivalents of the reference's aux subsystems (SURVEY.md §5):

* **Stall watchdog** — the reference's executor logs `[EXEC_STALL]` after a
  soft timeout and aborts after a hard one, both env-tunable
  (reference: src/nn/nn-executor.cpp:9-33,276-353, env
  `DLLAMA_EXEC_STALL_LOG_MS` / `DLLAMA_EXEC_STALL_TIMEOUT_MS`). Here the
  equivalent hazard is a device step that never completes (wedged runtime /
  dead tunnel): `watchdog()` wraps a blocking device call, logs after
  `DLT_STALL_LOG_MS` (default 60000) and raises `StallError` after
  `DLT_STALL_TIMEOUT_MS` (default 600000) — wider than the reference's
  2s/180s because a first call legitimately spends 20-40s compiling.
* **Step statistics** — the reference's network performance monitor keeps
  per-op latency min/avg/max and P50/P95/P99 with a recent-window
  (reference: src/nn/nn-network.cpp:883-1053). `StepStats` does the same for
  named step types (prefill/decode chunks), printable via `report()`.
* **Memory report** — the reference prints the per-node RAM requirement at
  graph build (reference: src/nn/nn-core.cpp:177-191); `memory_report`
  totals device bytes of params and cache pytrees.
"""

from __future__ import annotations

import os
import threading
import time
from collections import defaultdict
from dataclasses import dataclass, field

import jax
import numpy as np

from .tracing import Hist, global_event


class StallError(RuntimeError):
    pass


def _env_ms(name: str, default: int) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return float(default)


class watchdog:
    """Context manager guarding a blocking device call.

    >>> with watchdog("decode"):
    ...     out.block_until_ready()

    Logs `[EXEC_STALL]` after DLT_STALL_LOG_MS, raises StallError in the
    *watchdog thread's* place after DLT_STALL_TIMEOUT_MS by interrupting the
    main thread (the blocking jax call itself cannot be cancelled; the
    interrupt surfaces as soon as it returns — same semantics as the
    reference, which also only detects, not cancels).
    """

    def __init__(self, what: str, log_fn=None, compiling: bool = False, stats=None):
        self.what = ("compile " + what) if compiling else what
        self.stats = stats  # optional StepStats: stall events become counters
        if log_fn is None:
            import functools
            import sys

            # diagnostics go to STDERR: tools that contract to emit one
            # machine-readable stdout line (bench.py) must not get a stall
            # notice spliced into their output
            log_fn = functools.partial(print, file=sys.stderr)
        self.log_fn = log_fn
        # defaults are wider than the reference's 2s/180s because a first
        # call legitimately spends 20-40s in XLA compilation. `compiling`
        # marks a first-shape call (the engine tracks which shapes it has
        # run): the log threshold widens so an expected cold compile is not
        # reported as a stall (BENCH_r04 tripped EXEC_STALL on the 8B
        # prefill's first compile — a false alarm that cost the round's
        # measurement discipline a hole), and the label says what it is
        self.log_ms = _env_ms(
            "DLT_COMPILE_LOG_MS" if compiling else "DLT_STALL_LOG_MS",
            300000 if compiling else 60000,
        )
        self.timeout_ms = _env_ms("DLT_STALL_TIMEOUT_MS", 600000)
        self._done = threading.Event()
        self._timed_out = False
        self._thread = None

    def _watch(self, t0: float):
        logged = False
        while True:
            elapsed_ms = (time.perf_counter() - t0) * 1000.0
            # wake at whichever deadline comes first so a timeout shorter
            # than the log interval is still honored on time
            next_ms = min(
                self.log_ms if not logged else self.timeout_ms,
                max(self.timeout_ms - elapsed_ms, 1.0),
            )
            if self._done.wait(next_ms / 1000.0):
                return
            elapsed_ms = (time.perf_counter() - t0) * 1000.0
            if not logged and elapsed_ms >= self.log_ms:
                self.log_fn(
                    f"⏳ [EXEC_STALL] {self.what} exceeded {self.log_ms:.0f} ms "
                    f"(elapsed {elapsed_ms:.0f} ms)"
                )
                if self.stats is not None:
                    self.stats.incr("exec_stall_logged")
                # fires at most once per stall — a cold path, not a hot loop
                global_event("exec_stall_logged", keys=("what",), vals=(self.what,))  # dlt: allow(trace-hot-emit)
                logged = True
            if elapsed_ms >= self.timeout_ms:
                self._timed_out = True
                self.log_fn(
                    f"🚨 [EXEC_STALL] {self.what} exceeded hard timeout "
                    f"{self.timeout_ms:.0f} ms"
                )
                if self.stats is not None:
                    self.stats.incr("exec_stall_timeout")
                # ditto: one event per hard timeout, then the thread exits
                global_event("watchdog_stall", keys=("what",), vals=(self.what,))  # dlt: allow(trace-hot-emit)
                return

    def __enter__(self):
        self._thread = threading.Thread(
            target=self._watch, args=(time.perf_counter(),), daemon=True
        )
        self._thread.start()
        return self

    def __exit__(self, exc_type, *exc):
        self._done.set()
        self._thread.join(timeout=1)
        if self._timed_out and exc_type is None:
            # post-mortem BEFORE the raise: the ring still holds the stalled
            # request's spans (prefill chunks, decode chunks) and the
            # watchdog event the thread just emitted — exactly the context
            # an operator needs to reconstruct what wedged
            from .tracing import flight_record

            flight_record(
                f"stall:{self.what}",
                counters=self.stats.counters_snapshot() if self.stats else None,
            )
            raise StallError(f"{self.what} exceeded {self.timeout_ms:.0f} ms")
        return False


@dataclass
class _Series:
    count: int = 0
    total_us: float = 0.0
    min_us: float = float("inf")
    max_us: float = 0.0
    recent: list = field(default_factory=list)  # recent-window latencies
    window: int = 100


class StepStats:
    """Per-step-type latency aggregation with percentile report
    (the reference's NetworkPerfMonitor shape, applied to device steps),
    plus named event counters (stall resets/retries, shed requests) so the
    robustness layer is observable through the same snapshot `/health`,
    `/stats`, and `/gateway/stats` read."""

    def __init__(self, window: int = 100):
        self.series: dict[str, _Series] = defaultdict(lambda: _Series(window=window))
        self.counters: dict[str, int] = {}
        self.gauges: dict[str, float] = {}
        # fixed log-bucket histograms (runtime/tracing.py Hist): unlike the
        # recent-window percentiles above, their cumulative counts are
        # monotone across scrapes — the Prometheus `_bucket` series /metrics
        # exports (TTFT, time-per-output-token)
        self.hists: dict[str, Hist] = {}
        self._counter_lock = threading.Lock()

    def incr(self, name: str, n: int = 1):
        """Bump a named event counter (thread-safe; shows up in
        `snapshot()["counters"]`)."""
        with self._counter_lock:
            self.counters[name] = self.counters.get(name, 0) + n

    def gauge(self, name: str, value: float):
        """Set a last-value metric (e.g. the most recent prefill's
        dispatch-vs-compute overlap percentage) — exported in
        `snapshot()["gauges"]` next to the latency series, so `/stats`
        surfaces derived quantities the series alone can't express."""
        with self._counter_lock:
            self.gauges[name] = float(value)

    def observe(self, name: str, value_ms: float, bounds=None):
        """Record one observation into the named cumulative histogram
        (created on first use; fixed log-scale ms buckets). Thread-safe;
        exported under ``snapshot()["histograms"]`` and as Prometheus
        ``_bucket``/``_sum``/``_count`` series on `/metrics`."""
        with self._counter_lock:
            h = self.hists.get(name)
            if h is None:
                h = self.hists[name] = Hist(bounds) if bounds else Hist()
        h.observe(value_ms)

    def hists_snapshot(self) -> dict:
        with self._counter_lock:
            hists = dict(self.hists)
        return {k: h.snapshot() for k, h in hists.items()}

    def counters_snapshot(self) -> dict:
        with self._counter_lock:
            return dict(self.counters)

    def gauges_snapshot(self) -> dict:
        with self._counter_lock:
            return dict(self.gauges)

    def record(self, kind: str, us: float):
        s = self.series[kind]
        s.count += 1
        s.total_us += us
        s.min_us = min(s.min_us, us)
        s.max_us = max(s.max_us, us)
        s.recent.append(us)
        if len(s.recent) > s.window:
            s.recent.pop(0)

    def percentiles(self, kind: str) -> dict:
        s = self.series.get(kind)
        if not s or not s.recent:
            return {}
        # list() first: record() on another thread appends concurrently
        arr = np.sort(np.asarray(list(s.recent)))  # dlt: allow(host-sync) — host latency floats, no device source
        pick = lambda p: float(arr[min(len(arr) - 1, int(len(arr) * p))])
        return {"p50": pick(0.50), "p95": pick(0.95), "p99": pick(0.99)}

    def snapshot(self) -> dict:
        """JSON-able view of every series (the /stats endpoint's payload;
        same numbers `report()` prints) plus, under the reserved
        ``"counters"`` and ``"gauges"`` keys, the event counters and
        last-value gauges — the one source `/health` and the gateway's
        `/gateway/stats` both agree with."""
        out = {
            "counters": self.counters_snapshot(),
            "gauges": self.gauges_snapshot(),
            # reserved key like counters/gauges: existing /stats readers
            # (and their tests) key into what they know and keep working
            "histograms": self.hists_snapshot(),
        }
        # materialize the items: engine threads insert new kinds while the
        # /stats handler iterates
        for kind, s in sorted(list(self.series.items())):
            if s.count == 0:
                continue
            p = self.percentiles(kind)
            out[kind] = {
                "count": s.count,
                "avg_ms": round(s.total_us / s.count / 1000, 3),
                "min_ms": round(s.min_us / 1000, 3),
                "max_ms": round(s.max_us / 1000, 3),
                "p50_ms": round(p.get("p50", 0) / 1000, 3),
                "p95_ms": round(p.get("p95", 0) / 1000, 3),
                "p99_ms": round(p.get("p99", 0) / 1000, 3),
            }
        return out

    def report(self) -> str:
        lines = ["📊 Step performance report:"]
        for kind, s in sorted(self.series.items()):
            if s.count == 0:
                continue
            avg = s.total_us / s.count
            p = self.percentiles(kind)
            lines.append(
                f"  {kind:<16} n={s.count:<6} avg={avg/1000:8.2f}ms "
                f"min={s.min_us/1000:8.2f}ms max={s.max_us/1000:8.2f}ms "
                f"p50={p.get('p50', 0)/1000:8.2f}ms p95={p.get('p95', 0)/1000:8.2f}ms "
                f"p99={p.get('p99', 0)/1000:8.2f}ms"
            )
        return "\n".join(lines)


def _tree_bytes(tree) -> int:
    total = 0
    for leaf in jax.tree.leaves(tree):
        if hasattr(leaf, "nbytes"):
            total += leaf.nbytes
    return total


def memory_report(params, cache) -> str:
    """Device-memory footprint summary (reference: per-node RAM requirement
    print, src/nn/nn-core.cpp:177-191)."""
    pb = _tree_bytes(params)
    cb = _tree_bytes(cache)

    def fmt(n):
        return f"{n / 1e9:.2f} GB" if n >= 1e8 else f"{n / 1e6:.1f} MB"

    return (
        f"💿 Device memory: weights {fmt(pb)}, kv cache {fmt(cb)}, "
        f"total {fmt(pb + cb)}"
    )
