"""Observability: stall watchdog, step-latency statistics, memory report.

TPU equivalents of the reference's aux subsystems (SURVEY.md §5):

* **Stall watchdog** — the reference's executor logs `[EXEC_STALL]` after a
  soft timeout and aborts after a hard one, both env-tunable
  (reference: src/nn/nn-executor.cpp:9-33,276-353, env
  `DLLAMA_EXEC_STALL_LOG_MS` / `DLLAMA_EXEC_STALL_TIMEOUT_MS`). Here the
  equivalent hazard is a device step that never completes (wedged runtime /
  dead tunnel): `watchdog()` wraps a blocking device call, logs after
  `DLT_STALL_LOG_MS` (default 60000) and raises `StallError` after
  `DLT_STALL_TIMEOUT_MS` (default 600000) — wider than the reference's
  2s/180s because a first call legitimately spends 20-40s compiling.
* **Step statistics** — the reference's network performance monitor keeps
  per-op latency min/avg/max and P50/P95/P99 with a recent-window
  (reference: src/nn/nn-network.cpp:883-1053). `StepStats` does the same for
  named step types (prefill/decode chunks), printable via `report()`.
* **Memory report** — the reference prints the per-node RAM requirement at
  graph build (reference: src/nn/nn-core.cpp:177-191); `memory_report`
  totals device bytes of params and cache pytrees.
* **Goodput ledger** — per-request accounting of where wall time went
  (queue/prefill/decode/spec µs) and what every decoded token became
  (delivered / prefix-hit / spec-accepted / discarded), rolled up into a
  process `GoodputAggregator` whose delivered-token rate and per-reason
  waste counters ride `/metrics` (``dlt_goodput_tokens_per_s``,
  ``dlt_wasted_tokens_total{reason=...}``) — shed storms and
  draft-hostile traffic show up as goodput, not just event counters.
"""

from __future__ import annotations

import os
import threading
import time
from collections import defaultdict
from dataclasses import dataclass, field

import jax
import numpy as np

from .tracing import Hist, global_event


class StallError(RuntimeError):
    pass


def _env_ms(name: str, default: int) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return float(default)


class watchdog:
    """Context manager guarding a blocking device call.

    >>> with watchdog("decode"):
    ...     out.block_until_ready()

    Logs `[EXEC_STALL]` after DLT_STALL_LOG_MS, raises StallError in the
    *watchdog thread's* place after DLT_STALL_TIMEOUT_MS by interrupting the
    main thread (the blocking jax call itself cannot be cancelled; the
    interrupt surfaces as soon as it returns — same semantics as the
    reference, which also only detects, not cancels).
    """

    def __init__(self, what: str, log_fn=None, compiling: bool = False, stats=None):
        self.what = ("compile " + what) if compiling else what
        self.stats = stats  # optional StepStats: stall events become counters
        if log_fn is None:
            import functools
            import sys

            # diagnostics go to STDERR: tools that contract to emit one
            # machine-readable stdout line (bench.py) must not get a stall
            # notice spliced into their output
            log_fn = functools.partial(print, file=sys.stderr)
        self.log_fn = log_fn
        # defaults are wider than the reference's 2s/180s because a first
        # call legitimately spends 20-40s in XLA compilation. `compiling`
        # marks a first-shape call (the engine tracks which shapes it has
        # run): the log threshold widens so an expected cold compile is not
        # reported as a stall (BENCH_r04 tripped EXEC_STALL on the 8B
        # prefill's first compile — a false alarm that cost the round's
        # measurement discipline a hole), and the label says what it is
        self.log_ms = _env_ms(
            "DLT_COMPILE_LOG_MS" if compiling else "DLT_STALL_LOG_MS",
            300000 if compiling else 60000,
        )
        self.timeout_ms = _env_ms("DLT_STALL_TIMEOUT_MS", 600000)
        self._done = threading.Event()
        self._timed_out = False
        self._thread = None

    def _watch(self, t0: float):
        logged = False
        while True:
            elapsed_ms = (time.perf_counter() - t0) * 1000.0
            # wake at whichever deadline comes first so a timeout shorter
            # than the log interval is still honored on time
            next_ms = min(
                self.log_ms if not logged else self.timeout_ms,
                max(self.timeout_ms - elapsed_ms, 1.0),
            )
            if self._done.wait(next_ms / 1000.0):
                return
            elapsed_ms = (time.perf_counter() - t0) * 1000.0
            if not logged and elapsed_ms >= self.log_ms:
                self.log_fn(
                    f"⏳ [EXEC_STALL] {self.what} exceeded {self.log_ms:.0f} ms "
                    f"(elapsed {elapsed_ms:.0f} ms)"
                )
                if self.stats is not None:
                    self.stats.incr("exec_stall_logged")
                # fires at most once per stall — a cold path, not a hot loop
                global_event("exec_stall_logged", keys=("what",), vals=(self.what,))  # dlt: allow(trace-hot-emit)
                logged = True
            if elapsed_ms >= self.timeout_ms:
                self._timed_out = True
                self.log_fn(
                    f"🚨 [EXEC_STALL] {self.what} exceeded hard timeout "
                    f"{self.timeout_ms:.0f} ms"
                )
                if self.stats is not None:
                    self.stats.incr("exec_stall_timeout")
                # ditto: one event per hard timeout, then the thread exits
                global_event("watchdog_stall", keys=("what",), vals=(self.what,))  # dlt: allow(trace-hot-emit)
                return

    def __enter__(self):
        self._thread = threading.Thread(
            target=self._watch, args=(time.perf_counter(),), daemon=True
        )
        self._thread.start()
        return self

    def __exit__(self, exc_type, *exc):
        self._done.set()
        self._thread.join(timeout=1)
        if self._timed_out and exc_type is None:
            # post-mortem BEFORE the raise: the ring still holds the stalled
            # request's spans (prefill chunks, decode chunks) and the
            # watchdog event the thread just emitted — exactly the context
            # an operator needs to reconstruct what wedged
            from .tracing import flight_record

            flight_record(
                f"stall:{self.what}",
                counters=self.stats.counters_snapshot() if self.stats else None,
            )
            raise StallError(f"{self.what} exceeded {self.timeout_ms:.0f} ms")
        return False


@dataclass
class _Series:
    count: int = 0
    total_us: float = 0.0
    min_us: float = float("inf")
    max_us: float = 0.0
    recent: list = field(default_factory=list)  # recent-window latencies
    window: int = 100


class StepStats:
    """Per-step-type latency aggregation with percentile report
    (the reference's NetworkPerfMonitor shape, applied to device steps),
    plus named event counters (stall resets/retries, shed requests) so the
    robustness layer is observable through the same snapshot `/health`,
    `/stats`, and `/gateway/stats` read."""

    def __init__(self, window: int = 100):
        self.series: dict[str, _Series] = defaultdict(lambda: _Series(window=window))
        self.counters: dict[str, int] = {}
        self.gauges: dict[str, float] = {}
        # fixed log-bucket histograms (runtime/tracing.py Hist): unlike the
        # recent-window percentiles above, their cumulative counts are
        # monotone across scrapes — the Prometheus `_bucket` series /metrics
        # exports (TTFT, time-per-output-token)
        self.hists: dict[str, Hist] = {}
        self._counter_lock = threading.Lock()

    def incr(self, name: str, n: int = 1):
        """Bump a named event counter (thread-safe; shows up in
        `snapshot()["counters"]`)."""
        with self._counter_lock:
            self.counters[name] = self.counters.get(name, 0) + n

    def gauge(self, name: str, value: float):
        """Set a last-value metric (e.g. the most recent prefill's
        dispatch-vs-compute overlap percentage) — exported in
        `snapshot()["gauges"]` next to the latency series, so `/stats`
        surfaces derived quantities the series alone can't express."""
        with self._counter_lock:
            self.gauges[name] = float(value)

    def observe(self, name: str, value_ms: float, bounds=None, labels=None):
        """Record one observation into the named cumulative histogram
        (created on first use; fixed log-scale ms buckets). Thread-safe;
        exported under ``snapshot()["histograms"]`` and as Prometheus
        ``_bucket``/``_sum``/``_count`` series on `/metrics`. `labels`
        (e.g. ``{"slo_class": "interactive"}``) keys a SEPARATE labeled
        histogram rendered as extra rows of the same family — the
        per-class TTFT/TPOT breakdown (tracing.split_labeled_key is the
        decoding twin)."""
        if labels:
            name = (
                name
                + "{"
                + ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
                + "}"
            )
        with self._counter_lock:
            h = self.hists.get(name)
            if h is None:
                h = self.hists[name] = Hist(bounds) if bounds else Hist()
        h.observe(value_ms)

    def hists_snapshot(self) -> dict:
        with self._counter_lock:
            hists = dict(self.hists)
        return {k: h.snapshot() for k, h in hists.items()}

    def counters_snapshot(self) -> dict:
        with self._counter_lock:
            return dict(self.counters)

    def gauges_snapshot(self) -> dict:
        with self._counter_lock:
            return dict(self.gauges)

    def record(self, kind: str, us: float):
        s = self.series[kind]
        s.count += 1
        s.total_us += us
        s.min_us = min(s.min_us, us)
        s.max_us = max(s.max_us, us)
        s.recent.append(us)
        if len(s.recent) > s.window:
            s.recent.pop(0)

    def percentiles(self, kind: str) -> dict:
        s = self.series.get(kind)
        if not s or not s.recent:
            return {}
        # list() first: record() on another thread appends concurrently
        arr = np.sort(np.asarray(list(s.recent)))  # dlt: allow(host-sync) — host latency floats, no device source
        pick = lambda p: float(arr[min(len(arr) - 1, int(len(arr) * p))])
        return {"p50": pick(0.50), "p95": pick(0.95), "p99": pick(0.99)}

    def snapshot(self) -> dict:
        """JSON-able view of every series (the /stats endpoint's payload;
        same numbers `report()` prints) plus, under the reserved
        ``"counters"`` and ``"gauges"`` keys, the event counters and
        last-value gauges — the one source `/health` and the gateway's
        `/gateway/stats` both agree with."""
        out = {
            "counters": self.counters_snapshot(),
            "gauges": self.gauges_snapshot(),
            # reserved key like counters/gauges: existing /stats readers
            # (and their tests) key into what they know and keep working
            "histograms": self.hists_snapshot(),
        }
        # materialize the items: engine threads insert new kinds while the
        # /stats handler iterates
        for kind, s in sorted(list(self.series.items())):
            if s.count == 0:
                continue
            p = self.percentiles(kind)
            out[kind] = {
                "count": s.count,
                "avg_ms": round(s.total_us / s.count / 1000, 3),
                "min_ms": round(s.min_us / 1000, 3),
                "max_ms": round(s.max_us / 1000, 3),
                "p50_ms": round(p.get("p50", 0) / 1000, 3),
                "p95_ms": round(p.get("p95", 0) / 1000, 3),
                "p99_ms": round(p.get("p99", 0) / 1000, 3),
            }
        return out

    def report(self) -> str:
        lines = ["📊 Step performance report:"]
        for kind, s in sorted(self.series.items()):
            if s.count == 0:
                continue
            avg = s.total_us / s.count
            p = self.percentiles(kind)
            lines.append(
                f"  {kind:<16} n={s.count:<6} avg={avg/1000:8.2f}ms "
                f"min={s.min_us/1000:8.2f}ms max={s.max_us/1000:8.2f}ms "
                f"p50={p.get('p50', 0)/1000:8.2f}ms p95={p.get('p95', 0)/1000:8.2f}ms "
                f"p99={p.get('p99', 0)/1000:8.2f}ms"
            )
        return "\n".join(lines)


# -- per-request goodput ledger ----------------------------------------------

#: every waste reason the aggregator labels `dlt_wasted_tokens_total` with:
#: * ``overrun``     — decoded past the row's stop/budget before the step
#:                     loop noticed (discarded, never delivered);
#: * ``shed``        — decoded for a request later shed (pool-pressure
#:                     victim, overload 503);
#: * ``stall_retry`` — a failed attempt's tokens discarded before the
#:                     in-place retry re-decoded them;
#: * ``client_gone`` — decoded after the client dropped mid-stream;
#: * ``error``       — decoded before an engine failure killed the request;
#: * ``transfer_retry`` — prompt tokens a dead/failed disaggregated KV
#:                     transfer (server/disagg.py) forced the decode worker
#:                     to re-prefill locally (the prefill worker's compute
#:                     for them is lost fleet-wide);
#: * ``preempt``     — decoded for a lower-SLO-class row the scheduler
#:                     evicted so a waiting higher-class request could take
#:                     its slot (server/scheduler.py);
#: * ``deadline``    — decoded (or queued prompt tokens shed) for a request
#:                     whose end-to-end deadline (``X-DLT-Deadline-Ms``)
#:                     passed before delivery — an answer nobody was still
#:                     waiting for (server/scheduler.py resolve_deadline_ms);
#: * ``quarantined`` — prompt/decode work burned by a poison request before
#:                     its fingerprint crossed the quarantine strike limit
#:                     (server/quarantine.py);
#: * ``integrity``   — prompt tokens re-prefilled locally because the
#:                     fetched KV arrived complete but WRONG (checksum /
#:                     page_keys mismatch — runtime/kv_transport.py
#:                     verify_transfer rejected it before the cache was
#:                     touched); split from ``transfer_retry`` so corrupt
#:                     peers and dead peers are separate lines.
WASTE_REASONS = (
    "overrun", "shed", "stall_retry", "client_gone", "error",
    "transfer_retry", "preempt", "deadline", "quarantined", "integrity",
)

#: the SLO classes goodput breaks down by (server/scheduler.py is the
#: policy owner; this copy keeps telemetry jax-light and import-cycle-free
#: — a mismatch is pinned by tests)
SLO_CLASSES = ("interactive", "standard", "batch")

#: GoodputLedger fields attached to the request trace (one cold `ledger`
#: event per request) and returned in the `usage` extension — one list so
#: the trace, the HTTP payload, and the tests can never disagree on shape
LEDGER_FIELDS = (
    "queue_us", "prefill_us", "decode_us", "spec_us",
    "remote_prefill_us", "kv_transfer_us", "kv_transfer_path",
    "promotion_us", "prompt_tokens", "prefix_hit_tokens",
    "generated_tokens", "spec_accepted_tokens", "discarded_tokens",
    "retries",
)


@dataclass
class GoodputLedger:
    """One request's goodput accounting: where its wall time went and what
    every decoded token became. Accumulated along the serving path (queue
    wait at admission, prefill/decode/spec walls per chunk, token outcomes
    at retirement), attached to the request's trace, returned in the
    ``usage`` extension, and folded into the process aggregate — so a shed
    storm or draft-hostile traffic shows up as GOODPUT (delivered tokens/s
    net of waste), not just as counters.

    The accounting identity every request must satisfy (tested):
    ``generated_tokens + discarded_tokens == every token the engine decoded
    into this request's row(s)``."""

    queue_us: int = 0      # submit -> admission (batched; 0 serialized)
    prefill_us: int = 0    # prompt prefill wall (splice included)
    decode_us: int = 0     # plain decode-chunk walls
    spec_us: int = 0       # speculative draft+verify round walls
    remote_prefill_us: int = 0  # prefill-WORKER wall of a disaggregated
    # request (server/disagg.py; the worker reports it in its KV payload)
    kv_transfer_us: int = 0     # fetch wall of the shipped KV, net of the
    # worker's reported prefill (runtime/kv_transport.py)
    kv_transfer_path: str = ""  # transport the shipped KV took ("device" |
    # "http"; "" = no transfer) — the per-request twin of the labeled
    # dlt_kv_transfer_us series
    promotion_us: int = 0       # tiered-KV fetch wall: host/disk/peer tier
    # lookup + transfer for this request's prefix (runtime/kv_tiering.py;
    # 0 = no tier promotion)
    prompt_tokens: int = 0
    prefix_hit_tokens: int = 0   # prompt tokens resumed from the radix cache
    generated_tokens: int = 0    # delivered to the client (usage-visible)
    spec_accepted_tokens: int = 0
    discarded_tokens: int = 0    # decoded but never delivered
    retries: int = 0             # in-place stall retries this request took
    outcome: str = "ok"          # ok | shed | error | client_gone
    slo_class: str = "standard"  # interactive | standard | batch
    # (server/scheduler.py): labels the per-class goodput breakdown

    def as_dict(self) -> dict:
        out = {f: getattr(self, f) for f in LEDGER_FIELDS}
        out["outcome"] = self.outcome
        out["slo_class"] = self.slo_class
        return out

    def trace_vals(self) -> tuple:
        return tuple(getattr(self, f) for f in LEDGER_FIELDS) + (
            self.outcome, self.slo_class,
        )


#: trace-event keys for the per-request `ledger` event (pairs trace_vals)
LEDGER_TRACE_KEYS = LEDGER_FIELDS + ("outcome", "slo_class")


class GoodputAggregator:
    """Process-level rollup of request ledgers: cumulative delivered vs
    wasted tokens (by reason) plus a recent-window delivered-token rate —
    the ``dlt_goodput_tokens_per_s`` gauge and
    ``dlt_wasted_tokens_total{reason=...}`` counter family on /metrics.

    Thread-safe; `record()` is one lock hold per REQUEST (never per token),
    so the serving hot path is untouched."""

    def __init__(self, window_s: float = 60.0):
        self.window_s = window_s
        self._lock = threading.Lock()
        self.requests: dict[str, int] = {}   # outcome -> count
        self.delivered_tokens = 0
        self.prompt_tokens = 0
        self.prefix_hit_tokens = 0
        self.wasted: dict[str, int] = {}     # reason -> tokens
        # per-SLO-class breakdowns (server/scheduler.py): delivered/request
        # totals and (reason, class)-keyed waste — the slo_class-labeled
        # series on /metrics and the by_class section of /stats goodput
        self.delivered_by_class: dict[str, int] = {}
        self.requests_by_class: dict[str, int] = {}
        self.wasted_by_class: dict[tuple, int] = {}
        self._window: list = []              # (t, delivered, slo_class)

    def record(
        self,
        ledger: GoodputLedger,
        waste_reason: str | None = None,
        count_request: bool = True,
    ):
        """Fold one finished request (or failed attempt) in. `waste_reason`
        labels the ledger's discarded tokens; None derives it from the
        outcome (`ok` discards are chunk overrun). `count_request=False`
        folds the TOKEN accounting without bumping the request outcome
        counts — a stall-retried attempt's waste belongs to the ledger, but
        the request itself is counted once, by its final attempt."""
        if waste_reason is None:
            waste_reason = "overrun" if ledger.outcome == "ok" else ledger.outcome
        klass = ledger.slo_class if ledger.slo_class in SLO_CLASSES else "standard"
        now = time.monotonic()
        with self._lock:
            if count_request:
                self.requests[ledger.outcome] = (
                    self.requests.get(ledger.outcome, 0) + 1
                )
                self.requests_by_class[klass] = (
                    self.requests_by_class.get(klass, 0) + 1
                )
            self.delivered_tokens += ledger.generated_tokens
            self.delivered_by_class[klass] = (
                self.delivered_by_class.get(klass, 0) + ledger.generated_tokens
            )
            self.prompt_tokens += ledger.prompt_tokens
            self.prefix_hit_tokens += ledger.prefix_hit_tokens
            if ledger.discarded_tokens:
                self.wasted[waste_reason] = (
                    self.wasted.get(waste_reason, 0) + ledger.discarded_tokens
                )
                self.wasted_by_class[(waste_reason, klass)] = (
                    self.wasted_by_class.get((waste_reason, klass), 0)
                    + ledger.discarded_tokens
                )
            self._window.append((now, ledger.generated_tokens, klass))
            self._trim_locked(now)

    def add_waste(self, reason: str, tokens: int, slo_class: str = "standard"):
        """Count waste OUTSIDE any request ledger — tokens whose compute is
        lost without a failed request to pin them on (a degraded KV
        transfer's re-prefill: the REQUEST succeeds, the prefill worker's
        compute for those tokens is what was wasted)."""
        if tokens <= 0:
            return
        klass = slo_class if slo_class in SLO_CLASSES else "standard"
        with self._lock:
            self.wasted[reason] = self.wasted.get(reason, 0) + tokens
            self.wasted_by_class[(reason, klass)] = (
                self.wasted_by_class.get((reason, klass), 0) + tokens
            )

    def _trim_locked(self, now: float):
        cutoff = now - self.window_s
        w = self._window
        i = 0
        while i < len(w) and w[i][0] < cutoff:
            i += 1
        if i:
            del w[:i]

    def goodput_tokens_per_s(self) -> float:
        """Delivered tokens/s over the recent window — the headline gauge.
        The divisor is the observed span, floored at ONE second: a scrape
        landing milliseconds after a fresh replica's first completion must
        not extrapolate one request into a 50k tok/s routing signal (the
        fleet table lifts this gauge verbatim), and once the window has
        aged in the floor is inert."""
        now = time.monotonic()
        with self._lock:
            self._trim_locked(now)
            if not self._window:
                return 0.0
            span = max(now - self._window[0][0], 1.0)
            total = sum(n for _, n, _ in self._window)
        return round(total / span, 3)

    def goodput_series(self) -> list:
        """``[(labels, value), ...]`` for the ``dlt_goodput_tokens_per_s``
        gauge family: the unlabeled fleet-facing total (the signal the
        router/fleet table scores — unchanged shape) PLUS one
        ``slo_class``-labeled row per class over the same recent window,
        zero-valued classes included."""
        now = time.monotonic()
        with self._lock:
            self._trim_locked(now)
            window = list(self._window)
        if not window:
            return [({}, 0.0)] + [({"slo_class": c}, 0.0) for c in SLO_CLASSES]
        span = max(now - window[0][0], 1.0)
        per_class = {c: 0 for c in SLO_CLASSES}
        total = 0
        for _, n, klass in window:
            total += n
            per_class[klass] = per_class.get(klass, 0) + n
        return [({}, round(total / span, 3))] + [
            ({"slo_class": c}, round(per_class[c] / span, 3))
            for c in SLO_CLASSES
        ]

    def wasted_series(self) -> list:
        """``[(labels, value), ...]`` for the labeled counter family —
        every known reason present (zero-valued reasons included, so
        dashboards never see a series appear from nowhere mid-incident).
        These reason-only rows are the TOTALS; ``wasted_by_class_series``
        adds the per-class breakdown rows of the same family."""
        with self._lock:
            wasted = dict(self.wasted)
        return [({"reason": r}, wasted.get(r, 0)) for r in WASTE_REASONS]

    def wasted_by_class_series(self) -> list:
        """The ``{reason, slo_class}``-labeled breakdown rows of
        ``dlt_wasted_tokens_total``. Only (reason, class) pairs that have
        actually wasted tokens render — the zero-fill contract is carried
        by the reason-only totals; 21 always-zero breakdown rows would be
        noise. Summing the whole family double-counts: the reason-only
        rows are totals, the labeled rows their decomposition."""
        with self._lock:
            by_class = dict(self.wasted_by_class)
        return [
            ({"reason": r, "slo_class": c}, v)
            for (r, c), v in sorted(by_class.items())
        ]

    def by_class_snapshot(self) -> dict:
        """Per-SLO-class goodput view (the ``by_class`` section of the
        ``/stats`` goodput payload and ``/gateway/fleet`` rows)."""
        rates = {
            lab["slo_class"]: v
            for lab, v in self.goodput_series()
            if "slo_class" in lab
        }
        with self._lock:
            out = {}
            for c in SLO_CLASSES:
                wasted = {
                    r: v for (r, cc), v in self.wasted_by_class.items()
                    if cc == c
                }
                out[c] = {
                    "requests": self.requests_by_class.get(c, 0),
                    "delivered_tokens": self.delivered_by_class.get(c, 0),
                    "wasted_tokens": wasted,
                    "goodput_tokens_per_s": rates.get(c, 0.0),
                }
        return out

    def snapshot(self) -> dict:
        with self._lock:
            out = {
                "window_s": self.window_s,
                "requests": dict(self.requests),
                "delivered_tokens": self.delivered_tokens,
                "prompt_tokens": self.prompt_tokens,
                "prefix_hit_tokens": self.prefix_hit_tokens,
                "wasted_tokens": dict(self.wasted),
                "wasted_tokens_sum": sum(self.wasted.values()),
            }
        out["goodput_tokens_per_s"] = self.goodput_tokens_per_s()
        out["by_class"] = self.by_class_snapshot()
        return out


def _tree_bytes(tree) -> int:
    total = 0
    for leaf in jax.tree.leaves(tree):
        if hasattr(leaf, "nbytes"):
            total += leaf.nbytes
    return total


def memory_report(params, cache) -> str:
    """Device-memory footprint summary (reference: per-node RAM requirement
    print, src/nn/nn-core.cpp:177-191)."""
    pb = _tree_bytes(params)
    cb = _tree_bytes(cache)

    def fmt(n):
        return f"{n / 1e9:.2f} GB" if n >= 1e8 else f"{n / 1e6:.1f} MB"

    return (
        f"💿 Device memory: weights {fmt(pb)}, kv cache {fmt(cb)}, "
        f"total {fmt(pb + cb)}"
    )
