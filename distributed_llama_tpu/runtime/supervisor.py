"""Engine supervisor: crash-only replica recovery as a supervised lifecycle.

The serving tier's failure handling grew bottom-up — the watchdog's
`StallError` gets one in-place retry (PR 1), `api.recover()` resets the
engine and drops the prefix cache, and the gateway's breaker routes around
a dead replica — but every piece assumed the failure was *transient*: one
engine reset, then business as usual. At fleet scale the dangerous
failures are *sticky*: a wedged device runtime that stalls every
subsequent step, a sealed-sentinel breach that means the compiled ladder
no longer matches what serving dispatches, an engine exception that left
the KV pool or dispatch pipeline in an unknown state. Resetting a cache
does not fix any of those — only tearing the engine down and rebuilding it
from the weights up does (crash-only software: recovery IS restart).

This module is the state machine that decides *when* to rebuild and makes
the whole lifecycle observable:

* ``serving``    — the steady state;
* ``recovering`` — a rebuild is in progress: the replica's ``/health``
  reports it with a 503 (the gateway's active prober opens the breaker and
  routes away — the same signal path an operator drain uses), new chat
  requests shed immediately, and the engine-owning thread tears down the
  old engine (sentinel unsubscribed — a sealed fatal sentinel must never
  outlive its engine), builds a fresh one (fresh KV pool, fresh prefix
  cache), re-runs the warm ladder (``warmup()`` re-seals a FRESH recompile
  sentinel), and rejoins;
* ``failed``     — the restart budget is exhausted (``max_restarts``
  rebuilds within ``window_s``): the replica stops trying and reports
  unhealthy until an operator intervenes — a crash-looping replica
  rebuilding forever just burns the fleet's retry budget.

Escalation policy (:meth:`EngineSupervisor.classify`):

* ``StallError`` — the first ``stall_limit - 1`` stalls in the window take
  the cheap path (engine reset; the in-place retry machinery covers them);
  hitting ``stall_limit`` means the stall is sticky — rebuild;
* a fatal sanitizer breach (``RecompileError``, host-sync violation) —
  rebuild: the sealed ladder provably no longer covers what serving
  dispatches, and every further request would re-breach;
* any other unhandled engine exception — rebuild: the engine's device
  state is unknown, and "reset and hope" is how one poisoned replica
  serves corrupt KV for a week.

Every transition bumps ``dlt_supervisor_transitions_total{state=...}``,
dumps a flight record (the trace ring still holds the failing request's
spans), and lands a trace event. Rebuild attempts within the window pay
exponential backoff (``backoff_s`` doubling up to ``backoff_max_s``) so a
crash-looping build doesn't hot-spin the host.

The supervisor is deliberately engine-agnostic: the host (server/api.py
``ApiState``) supplies ``rebuild_fn``, and every decision method is a
host-side dict/clock touch — safe to call from the engine-owning thread
(the Batcher loop / the serialized handler), which is exactly where
rebuilds must run (the engine's dispatches are single-threaded by design).
"""

from __future__ import annotations

import os
import threading
import time

#: the supervisor states dlt_supervisor_transitions_total is labeled with
#: (zero-valued states always render — dashboards must exist before the
#: first incident)
SUPERVISOR_STATES = ("serving", "recovering", "failed")

SERVING = "serving"
RECOVERING = "recovering"
FAILED = "failed"


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


class SupervisorConfig:
    """Restart-budget knobs (``DLT_SUPERVISOR_*`` envs):

    * ``max_restarts`` — rebuilds allowed inside ``window_s`` before the
      replica gives up (state ``failed``); the budget is a sliding window,
      so a replica that crashed twice last week is not one strike from
      death forever;
    * ``stall_limit``  — StallErrors inside the window before a stall is
      treated as sticky (rebuild instead of reset); the default of 2
      matches the serving path's one-in-place-retry contract: the retry's
      second stall IS the exhaustion signal;
    * ``backoff_s`` / ``backoff_max_s`` — exponential pre-rebuild delay
      (the second rebuild in a window waits 2x, the third 4x, ...).
    """

    def __init__(
        self,
        max_restarts: int | None = None,
        window_s: float | None = None,
        stall_limit: int | None = None,
        backoff_s: float | None = None,
        backoff_max_s: float | None = None,
    ):
        self.max_restarts = (
            max_restarts
            if max_restarts is not None
            else _env_int("DLT_SUPERVISOR_RESTARTS", 3)
        )
        self.window_s = (
            window_s
            if window_s is not None
            else _env_float("DLT_SUPERVISOR_WINDOW_S", 600.0)
        )
        self.stall_limit = (
            stall_limit
            if stall_limit is not None
            else _env_int("DLT_SUPERVISOR_STALL_LIMIT", 2)
        )
        self.backoff_s = (
            backoff_s
            if backoff_s is not None
            else _env_float("DLT_SUPERVISOR_BACKOFF_S", 0.5)
        )
        self.backoff_max_s = (
            backoff_max_s
            if backoff_max_s is not None
            else _env_float("DLT_SUPERVISOR_BACKOFF_MAX_S", 30.0)
        )

    def snapshot(self) -> dict:
        return {
            "max_restarts": self.max_restarts,
            "window_s": self.window_s,
            "stall_limit": self.stall_limit,
            "backoff_s": self.backoff_s,
            "backoff_max_s": self.backoff_max_s,
        }


class EngineSupervisor:
    """The replica's engine-lifecycle state machine.

    ``rebuild_fn()`` is the host's teardown-and-rebuild (ApiState
    ``_rebuild_engine``: close the old engine — sentinel unsubscribed —
    build + warm a fresh one, swap it in). It runs on whichever thread
    calls :meth:`recover` — by contract the engine-owning thread, so the
    rebuild can never race a live dispatch.

    Thread-safety: state/budget mutations are under one lock; ``state`` is
    read lock-free by the health endpoint and admission checks (a stale
    read there costs one extra 503, never a race on the engine itself).
    """

    def __init__(self, rebuild_fn, config: SupervisorConfig | None = None,
                 sleep_fn=time.sleep):
        self.rebuild_fn = rebuild_fn
        self.config = config or SupervisorConfig()
        self._sleep = sleep_fn  # injectable: tests must not pay real backoff
        self._lock = threading.Lock()
        self.state = SERVING
        self.transitions = {s: 0 for s in SUPERVISOR_STATES}
        self.last_reason = ""
        self._restarts: list[float] = []   # rebuild timestamps (window)
        self._stalls: list[float] = []     # StallError timestamps (window)
        self.rebuilds_total = 0
        self.resets_total = 0

    # -- policy --------------------------------------------------------------

    def classify(self, exc: BaseException | None) -> str:
        """``"reset"`` or ``"rebuild"`` for one engine failure. StallError
        stays cheap until it proves sticky (``stall_limit`` in the
        window); everything else — fatal sanitizer breaches and unknown
        engine exceptions — rebuilds (the engine's state is unknown)."""
        from .telemetry import StallError

        if isinstance(exc, StallError):
            now = time.monotonic()
            with self._lock:
                self._stalls.append(now)
                self._trim_locked(self._stalls, now)
                if len(self._stalls) >= self.config.stall_limit:
                    self._stalls.clear()
                    return "rebuild"
            return "reset"
        return "rebuild"

    def _trim_locked(self, stamps: list, now: float):
        cutoff = now - self.config.window_s
        while stamps and stamps[0] < cutoff:
            stamps.pop(0)

    def budget_left(self) -> int:
        now = time.monotonic()
        with self._lock:
            self._trim_locked(self._restarts, now)
            return max(self.config.max_restarts - len(self._restarts), 0)

    # -- transitions ---------------------------------------------------------

    def _transition(self, state: str, reason: str):
        with self._lock:
            self.state = state
            self.transitions[state] = self.transitions.get(state, 0) + 1
            self.last_reason = reason
        # post-mortem + trace: the ring still holds the failing request's
        # spans; the flight record is the operator's reconstruction kit
        from .tracing import flight_record, global_event

        global_event(
            "supervisor_transition", keys=("state", "reason"),
            vals=(state, reason),
        )
        if state != SERVING:
            flight_record(f"supervisor:{state}:{reason}")

    def note_reset(self, reason: str):
        """A cheap in-place engine reset handled the failure (no state
        change — the replica never left serving)."""
        with self._lock:
            self.resets_total += 1
            self.last_reason = reason

    def note_ok(self):
        """A request completed successfully: the engine demonstrably
        recovered, so the stall strike window clears — "exhaustion" means
        stalls WITHOUT an intervening success (the in-place-retry
        contract), not N transient stalls spread over a quiet hour. The
        restart BUDGET does not clear: rebuilds are expensive however
        well the replica serves between them."""
        if not self._stalls:
            return  # lock-free fast path: the common case is no strikes
        with self._lock:
            self._stalls.clear()

    def enter_recovering(self, reason: str):
        """Pre-transition to ``recovering`` BEFORE the caller unblocks the
        failed requests' writers: by the time any 500 reaches a client,
        ``/health`` must already answer ``recovering`` — a client that
        polls after its 500 must never read a stale ``serving`` with the
        rebuild still ahead (then get shed by it moments later)."""
        if self.state != RECOVERING:
            self._transition(RECOVERING, reason)

    def recover(self, reason: str, stats=None) -> bool:
        """Run one supervised rebuild: transition to ``recovering`` (a
        no-op when :meth:`enter_recovering` already did), pay the backoff,
        call ``rebuild_fn``, rejoin (or ``failed`` when the budget is gone
        / the rebuild itself died). Returns True when the replica is
        serving again. MUST be called from the engine-owning thread."""
        now = time.monotonic()
        with self._lock:
            self._trim_locked(self._restarts, now)
            if len(self._restarts) >= self.config.max_restarts:
                exhausted = True
            else:
                exhausted = False
                n_recent = len(self._restarts)
                self._restarts.append(now)
        if exhausted:
            self._transition(FAILED, f"restart budget exhausted ({reason})")
            if stats is not None:
                stats.incr("supervisor_budget_exhausted")
            return False
        if self.state != RECOVERING:
            self._transition(RECOVERING, reason)
        if stats is not None:
            stats.incr("supervisor_rebuilds")
        # exponential backoff: the FIRST rebuild in a window is immediate
        # (the fleet is down a replica; don't dawdle), repeats wait
        if n_recent > 0:
            delay = min(
                self.config.backoff_s * (2 ** (n_recent - 1)),
                self.config.backoff_max_s,
            )
            self._sleep(delay)
        try:
            self.rebuild_fn()
        except Exception:
            # the rebuild itself died (bad weights path, OOM): the replica
            # cannot self-heal — report failed instead of crash-looping
            self._transition(FAILED, f"rebuild failed ({reason})")
            if stats is not None:
                stats.incr("supervisor_rebuild_failed")
            raise
        with self._lock:
            self.rebuilds_total += 1
        self._transition(SERVING, f"rejoined after {reason}")
        return True

    # -- views ---------------------------------------------------------------

    def transitions_series(self) -> list:
        """``[(labels, value), ...]`` for the labeled counter family —
        every state present, zeros included (the dashboard-exists-before-
        the-incident contract every counter family here keeps)."""
        with self._lock:
            t = dict(self.transitions)
        return [({"state": s}, t.get(s, 0)) for s in SUPERVISOR_STATES]

    def snapshot(self) -> dict:
        now = time.monotonic()
        with self._lock:
            self._trim_locked(self._restarts, now)
            return {
                "state": self.state,
                "last_reason": self.last_reason,
                "transitions": dict(self.transitions),
                "rebuilds_total": self.rebuilds_total,
                "resets_total": self.resets_total,
                "restarts_in_window": len(self._restarts),
                "config": self.config.snapshot(),
            }
