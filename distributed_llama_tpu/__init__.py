"""distributed_llama_tpu — a TPU-native distributed LLM inference framework.

A from-scratch JAX/XLA/Pallas re-design of the capability surface of
`inpyu/distributed-llama` (reference: /root/reference, a C++11 TCP-cluster
inference engine): same `.m` Q40 model files and `.t` tokenizers, same model
families (Llama 3.x, Qwen3, Qwen3-MoE), same CLI and OpenAI-compatible API —
but SPMD over a `jax.sharding.Mesh` with XLA/ICI collectives instead of
hand-rolled socket star/ring all-reduce, and Pallas kernels instead of
NEON/AVX2 intrinsics.
"""

__version__ = "0.1.0"
