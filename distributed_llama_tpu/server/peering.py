"""Active-active gateway peering: bounded control-plane gossip between
gateways serving the same fleet.

One gateway is a failure domain (PR 14 closed the replica domain; the
gateway itself was still a single process holding the locality map, the
quarantine ledger, and drain ownership in memory). This module lets two
or more gateways serve the SAME fleet with consistent routing affinity
and a fleet-wide poison budget:

* **bounded deltas on a gossip tick** (``DLT_GW_PEER_SYNC_S``): each
  gateway accumulates its own control-plane events — locality learns
  (chain key -> learned home), quarantine strikes, drain/undrain
  events — into a per-peer outbox (dict-merged, so the delta is bounded
  by DISTINCT keys, capped at ``DLT_GW_PEER_DELTA`` with an explicit
  dropped-entries counter — no silent truncation) and pushes it to every
  peer with one stdlib HTTP POST (``POST /gateway/peer/sync``);
* **last-writer-wins on monotonic event ids**: every locality/drain
  write is stamped with a Lamport clock + origin id; a received entry
  applies only when its ``(clock, origin)`` beats the stored version, so
  two gateways learning different homes for the same chain converge on
  the later write instead of ping-ponging. Strikes are ADDITIVE, not
  LWW: each strike is one implication event, delivered at most once
  (outbox entries clear only on a successful push), so a poison
  fingerprint's strike budget is fleet-wide — its retries burn
  ``DLT_QUARANTINE_STRIKES`` replicas total no matter how many gateways
  they land on;
* **exactly one autoscaler leader**: the gateway with the LOWEST live
  peer id (ids exchanged on every sync; live = heard from within
  ``DLT_GW_PEER_LIVE_S``) runs autoscaler ticks; followers hold
  (``dlt_autoscaler_decisions_total{action="follower_hold"}``), so two
  gateways never fight over drain decisions. A dead leader ages out of
  the live set and the next-lowest id takes over — counted on
  ``dlt_gw_peer_leadership_transitions_total``.

Peers are configured as a full mesh (every gateway lists every other via
repeatable ``--peer-gateway``); events are NOT relayed transitively — a
missing edge partitions state, visible as ``dlt_gw_peer_live 0`` for the
unreachable peer. Deliberately stdlib-only like the rest of the gateway.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import OrderedDict

from ..runtime.tracing import TRACER, now_us, prom_line
from .quarantine import parse_fp_hex

#: gossip cadence (seconds); <= 0 disables the background thread (tests
#: drive sync_round() explicitly)
DEFAULT_SYNC_S = 2.0
#: per-peer outbox cap per kind — past it the OLDEST pending entries drop
#: (counted on dlt_gw_peer_delta_dropped_total, never silently)
DEFAULT_DELTA_CAP = 2048
#: LWW version-map bound (locality keys + drain flags tracked)
VERSIONS_CAP = 8192

#: delta kinds every sync exchanges (the zero-filled metrics label set)
KINDS = ("locality", "strike", "drain")


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def parse_peer(s: str) -> tuple:
    host, port = s.rsplit(":", 1)
    return host, int(port)


class GatewayPeering:
    """One gateway's peering state: the Lamport clock, the per-peer
    outboxes, the LWW version map, peer liveness, and leader election.
    Construct and call :meth:`sync_round` / :meth:`apply` directly in
    tests; :meth:`start` runs the gossip loop."""

    def __init__(
        self,
        balancer,
        self_id: str,
        peers,
        interval_s: float | None = None,
        timeout_s: float | None = None,
        delta_cap: int | None = None,
        live_after_s: float | None = None,
    ):
        self.balancer = balancer
        self.self_id = self_id
        # peer ADDRESSES ("host:port" of the peer gateway's listen port);
        # peer IDS are learned from sync exchanges — election runs on ids
        self.peers = [p for p in dict.fromkeys(peers) if p]
        self.interval_s = (
            _env_float("DLT_GW_PEER_SYNC_S", DEFAULT_SYNC_S)
            if interval_s is None else interval_s
        )
        self.timeout_s = (
            _env_float("DLT_GW_PEER_TIMEOUT_S", 2.0)
            if timeout_s is None else timeout_s
        )
        self.delta_cap = (
            _env_int("DLT_GW_PEER_DELTA", DEFAULT_DELTA_CAP)
            if delta_cap is None else delta_cap
        )
        # a peer id is LIVE while heard from (either direction) within
        # this window; default 3 gossip ticks — one lost tick must not
        # flap leadership
        self.live_after_s = (
            _env_float(
                "DLT_GW_PEER_LIVE_S", 3.0 * max(self.interval_s, 0.1)
            )
            if live_after_s is None else live_after_s
        )
        self._lock = threading.Lock()
        self._clock = 0
        # LWW versions: ("loc", chain_key) / ("drain", backend) ->
        # (clock, origin_id); bounded LRU
        self._versions: "OrderedDict[tuple, tuple]" = OrderedDict()
        # per-peer outboxes: addr -> kind -> pending delta (dict-merged)
        self._out = {
            p: {
                "locality": OrderedDict(),  # key_hex -> (backend, c, origin)
                "strikes": OrderedDict(),   # fp_hex -> n (additive)
                "drains": OrderedDict(),    # backend -> (draining, by, c, o)
            }
            for p in self.peers
        }
        self._live_ids: dict = {}       # peer gateway id -> last-heard mono
        self._peer_id_by_addr: dict = {}
        self._last_leader: str | None = None
        self.counters = {
            "sync_ok": 0,
            "sync_failed": 0,
            "events_sent": 0,
            "applied_locality": 0,
            "applied_strike": 0,
            "applied_drain": 0,
            "stale_dropped": 0,      # LWW losers (older version arrived)
            "delta_dropped": 0,      # outbox-cap evictions (bounded delta)
            "leadership_transitions": 0,
        }
        self.sync_rounds = 0
        # chaos hook: peer addrs whose sync posts are dropped on the floor
        # (a network partition twin). The drop happens INSIDE _sync_peer's
        # failure path, so a partitioned push behaves exactly like a dead
        # network — delta restored, sync_failed counted, at-most-once kept.
        self._partitioned: set = set()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "GatewayPeering":
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="gateway-peer-sync"
        )
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _run(self):
        while not self._stop.wait(self.interval_s):
            self.sync_round()

    # -- chaos: network partition --------------------------------------------

    def partition(self, addrs=None) -> None:
        """Drop sync posts to ``addrs`` (default: every peer) — the
        split-brain chaos twin. Inbound applies are NOT blocked here; a
        symmetric partition partitions BOTH sides' peerings."""
        with self._lock:
            self._partitioned = set(self.peers if addrs is None else addrs)

    def heal(self) -> None:
        """End the partition: the next sync round delivers the backlog."""
        with self._lock:
            self._partitioned = set()

    # -- clock ---------------------------------------------------------------

    def _tick_locked(self, seen: int = 0) -> int:
        self._clock = max(self._clock, int(seen)) + 1
        return self._clock

    # -- local event capture (the gateway's hooks) ---------------------------

    def _bound_outbox_locked(self, kind: str):
        for box in self._out.values():
            d = box[kind]
            while len(d) > self.delta_cap:
                d.popitem(last=False)
                self.counters["delta_dropped"] += 1

    def note_locality(self, chain, backend: str) -> None:
        """A successful request taught the locality map ``chain -> this
        backend`` (gateway request loop, next to Router.learn) — version
        the write and queue it for every peer. One lock hold per REQUEST,
        never per token (dict merges, bounded)."""
        if not self.peers or not chain:
            return
        with self._lock:
            for ck in chain:
                c = self._tick_locked()
                ver = (c, self.self_id)
                self._versions[("loc", ck)] = ver
                self._versions.move_to_end(("loc", ck))
                hexkey = f"{ck:016x}"
                for box in self._out.values():
                    box["locality"][hexkey] = (backend, c, self.self_id)
            while len(self._versions) > VERSIONS_CAP:
                self._versions.popitem(last=False)
            self._bound_outbox_locked("locality")

    def note_strike(self, fp: int, n: int = 1) -> None:
        """The retry loop recorded ``n`` implication events for ``fp`` —
        queue the ADDITIVE delta for every peer (each event delivered at
        most once: cleared only on a successful push)."""
        if not self.peers or fp is None:
            return
        hexfp = f"{fp:016x}"
        with self._lock:
            self._tick_locked()
            for box in self._out.values():
                box["strikes"][hexfp] = box["strikes"].get(hexfp, 0) + n
                box["strikes"].move_to_end(hexfp)
            self._bound_outbox_locked("strikes")

    def note_drain(self, backend: str, draining: bool, by: str) -> None:
        """A local drain/undrain landed (operator endpoint, autoscaler, or
        recovery re-broadcast) — version the flag and queue it."""
        if not self.peers:
            return
        with self._lock:
            c = self._tick_locked()
            self._versions[("drain", backend)] = (c, self.self_id)
            self._versions.move_to_end(("drain", backend))
            for box in self._out.values():
                box["drains"][backend] = (draining, by, c, self.self_id)
            while len(self._versions) > VERSIONS_CAP:
                self._versions.popitem(last=False)

    # -- the gossip tick (sender side) ---------------------------------------

    def sync_round(self) -> dict:
        """Push each peer its pending delta; returns per-peer outcomes.
        A failed push re-merges the delta (LWW entries keep the newer
        version, strikes re-add) so nothing is lost to one dead peer."""
        out = {}
        for addr in self.peers:
            out[addr] = self._sync_peer(addr)
        self.sync_rounds += 1
        return out

    def _take_delta_locked(self, addr: str) -> dict:
        box = self._out[addr]
        delta = {
            "locality": {
                k: {"b": b, "c": c, "o": o}
                for k, (b, c, o) in box["locality"].items()
            },
            "strikes": dict(box["strikes"]),
            "drains": {
                k: {"draining": d, "by": by, "c": c, "o": o}
                for k, (d, by, c, o) in box["drains"].items()
            },
        }
        box["locality"] = OrderedDict()
        box["strikes"] = OrderedDict()
        box["drains"] = OrderedDict()
        return delta

    def _restore_delta_locked(self, addr: str, delta: dict):
        box = self._out[addr]
        for k, ent in delta["locality"].items():
            cur = box["locality"].get(k)
            if cur is None or (cur[1], cur[2]) < (ent["c"], ent["o"]):
                box["locality"][k] = (ent["b"], ent["c"], ent["o"])
        for k, n in delta["strikes"].items():
            box["strikes"][k] = box["strikes"].get(k, 0) + n
        for k, ent in delta["drains"].items():
            cur = box["drains"].get(k)
            if cur is None or (cur[2], cur[3]) < (ent["c"], ent["o"]):
                box["drains"][k] = (
                    ent["draining"], ent["by"], ent["c"], ent["o"]
                )
        self._bound_outbox_locked("locality")
        self._bound_outbox_locked("strikes")

    def _sync_peer(self, addr: str) -> dict:
        from .fleet import http_post_json

        with self._lock:
            delta = self._take_delta_locked(addr)
            clock = self._clock
        n_events = sum(len(delta[k]) for k in delta)
        payload = dict(delta, id=self.self_id, clock=clock)
        try:
            with self._lock:
                if addr in self._partitioned:
                    raise OSError("chaos: partitioned")
            host, port = addr.rsplit(":", 1)
            status, body = http_post_json(
                host, int(port), "/gateway/peer/sync", payload,
                self.timeout_s,
            )
            if status != 200:
                raise OSError(f"peer sync returned {status}")
            ack = json.loads(body)
        except Exception as e:
            with self._lock:
                self._restore_delta_locked(addr, delta)
                self.counters["sync_failed"] += 1
            TRACER.event(
                "gw_peer_sync_failed", now_us(), 0,
                ("peer", "error"), (addr, repr(e)),
            )
            return {"ok": False, "error": repr(e)}
        peer_id = ack.get("id")
        with self._lock:
            self._tick_locked(ack.get("clock", 0))
            self.counters["sync_ok"] += 1
            self.counters["events_sent"] += n_events
            if isinstance(peer_id, str) and peer_id:
                self._live_ids[peer_id] = time.monotonic()
                self._peer_id_by_addr[addr] = peer_id
        return {"ok": True, "peer_id": peer_id, "events": n_events}

    # -- the receive path (POST /gateway/peer/sync) --------------------------

    def apply(self, payload: dict) -> dict:
        """Apply one peer's delta; returns the ack body. LWW entries apply
        only when their version beats the stored one; strikes are additive
        into the gateway's own ledger (fleet-wide budget)."""
        origin = payload.get("id")
        applied = {"locality": 0, "strike": 0, "drain": 0}
        router = getattr(self.balancer, "router", None)
        with self._lock:
            self._tick_locked(payload.get("clock", 0))
            if isinstance(origin, str) and origin:
                self._live_ids[origin] = time.monotonic()
            loc_wins = []
            for hexkey, ent in (payload.get("locality") or {}).items():
                try:
                    ck = int(hexkey, 16)
                    ver = (int(ent["c"]), str(ent["o"]))
                    backend = str(ent["b"])
                except (TypeError, ValueError, KeyError):
                    continue
                cur = self._versions.get(("loc", ck))
                if cur is not None and cur >= ver:
                    self.counters["stale_dropped"] += 1
                    continue
                self._versions[("loc", ck)] = ver
                self._versions.move_to_end(("loc", ck))
                loc_wins.append((ck, backend))
            drain_wins = []
            for backend, ent in (payload.get("drains") or {}).items():
                try:
                    ver = (int(ent["c"]), str(ent["o"]))
                    draining = bool(ent["draining"])
                    by = str(ent.get("by", "operator"))
                except (TypeError, ValueError, KeyError):
                    continue
                cur = self._versions.get(("drain", backend))
                if cur is not None and cur >= ver:
                    self.counters["stale_dropped"] += 1
                    continue
                self._versions[("drain", backend)] = ver
                self._versions.move_to_end(("drain", backend))
                drain_wins.append((backend, draining, by))
            while len(self._versions) > VERSIONS_CAP:
                self._versions.popitem(last=False)
            clock = self._clock
        # writes land OUTSIDE our lock: the router/balancer/ledger own
        # their own locks (lock-order discipline — never nest theirs
        # under ours)
        if router is not None:
            for ck, backend in loc_wins:
                router.set_owner(ck, backend)
                applied["locality"] += 1
        ledger = getattr(self.balancer, "quarantine", None)
        if ledger is not None:
            for hexfp, n in (payload.get("strikes") or {}).items():
                fp = parse_fp_hex(hexfp)
                try:
                    n = int(n)
                except (TypeError, ValueError):
                    continue
                if fp is None or n <= 0:
                    continue
                ledger.strike(fp, n)
                applied["strike"] += n
        for backend, draining, by in drain_wins:
            # record=False: applying a peer's event must not re-broadcast
            # it (ping-pong); notify=False: the origin gateway already
            # hinted the replica
            if self.balancer.set_draining(
                backend, draining, by=by, record=False, notify=False
            ):
                applied["drain"] += 1
                if draining and by == "autoscaler":
                    a = getattr(self.balancer, "autoscaler", None)
                    if a is not None:
                        a.adopt_drain(backend)
        with self._lock:
            for k, n in applied.items():
                self.counters[f"applied_{k}"] += n
        return {"id": self.self_id, "clock": clock, "applied": applied}

    # -- leader election -----------------------------------------------------

    def _live_ids_now_locked(self, now: float) -> list:
        live = [self.self_id]
        for pid, seen in self._live_ids.items():
            if pid != self.self_id and now - seen <= self.live_after_s:
                live.append(pid)
        return sorted(live)

    def leader_id(self) -> str:
        """The current leader: LOWEST live gateway id (self always counts
        as live). Deterministic — every gateway with the same live set
        elects the same leader without a round of consensus."""
        now = time.monotonic()
        with self._lock:
            leader = self._live_ids_now_locked(now)[0]
            if leader != self._last_leader:
                if self._last_leader is not None:
                    self.counters["leadership_transitions"] += 1
                    TRACER.event(
                        "gw_peer_leadership", now_us(), 0,
                        ("from", "to", "self"),
                        (self._last_leader, leader, self.self_id),
                    )
                self._last_leader = leader
        return leader

    def is_leader(self) -> bool:
        return self.leader_id() == self.self_id

    # -- views ---------------------------------------------------------------

    def snapshot(self) -> dict:
        """The ``peering`` section of ``GET /gateway/fleet``."""
        leader = self.leader_id()
        now = time.monotonic()
        with self._lock:
            pending = {
                addr: {k: len(v) for k, v in box.items()}
                for addr, box in self._out.items()
            }
            live = self._live_ids_now_locked(now)
            return {
                "self_id": self.self_id,
                "peers": list(self.peers),
                "peer_ids": dict(self._peer_id_by_addr),
                "live_ids": live,
                "leader": leader,
                "is_leader": leader == self.self_id,
                "interval_s": self.interval_s,
                "clock": self._clock,
                "sync_rounds": self.sync_rounds,
                "pending": pending,
                "counters": dict(self.counters),
            }

    def metrics_lines(self) -> list:
        leader = self.leader_id()
        now = time.monotonic()
        with self._lock:
            c = dict(self.counters)
            live_by_addr = {
                addr: (
                    pid in self._live_ids
                    and now - self._live_ids[pid] <= self.live_after_s
                )
                for addr, pid in self._peer_id_by_addr.items()
            }
            for addr in self.peers:
                live_by_addr.setdefault(addr, False)
        lines = []
        for name, key in (
            ("dlt_gw_peer_sync_total", "sync_ok"),
            ("dlt_gw_peer_sync_failures_total", "sync_failed"),
            ("dlt_gw_peer_events_sent_total", "events_sent"),
            ("dlt_gw_peer_stale_dropped_total", "stale_dropped"),
            ("dlt_gw_peer_delta_dropped_total", "delta_dropped"),
            ("dlt_gw_peer_leadership_transitions_total",
             "leadership_transitions"),
        ):
            lines.append(f"# TYPE {name} counter")
            lines.append(prom_line(name, None, c.get(key, 0)))
        lines.append("# TYPE dlt_gw_peer_events_applied_total counter")
        for kind in KINDS:
            lines.append(
                prom_line(
                    "dlt_gw_peer_events_applied_total", {"kind": kind},
                    c.get(f"applied_{kind}", 0),
                )
            )
        lines.append("# TYPE dlt_gw_peer_live gauge")
        for addr, live in sorted(live_by_addr.items()):
            lines.append(
                prom_line("dlt_gw_peer_live", {"peer": addr}, int(live))
            )
        lines.append("# TYPE dlt_gw_peer_leader gauge")
        lines.append(
            prom_line("dlt_gw_peer_leader", None, int(leader == self.self_id))
        )
        return lines
